"""Invariant oracles: what must hold after EVERY chaos schedule.

Each oracle is a pure function over the run's durable evidence — the
journals (replayed post-hoc), the per-rank execution logs, the trips
files, the per-generation reports, the scratch directories, and the
supervisor's result dict.  Nothing here inspects live state: if an
invariant can't be proven from what survived the crashes, the runtime's
recovery story has a hole and the oracle should fail.

The suite (the table in design.md "Chaos engineering" mirrors this):

==================  ====================================================
oracle              invariant (owing subsystem)
==================  ====================================================
workload_completed  the supervised run converged within its restart
                    budget and every rank attested (supervisor)
no_lost_jobs        every accepted job reached a terminal state —
                    ``lost=0`` from the journal replay (scheduler /
                    federation journals + recovery)
replay_determinism  replaying a journal is a pure function of the file:
                    two independent replays agree, and the worker's
                    in-process summary equals the post-hoc one
exactly_once        a job journaled DONE never executes again in a later
                    generation, and every execution has a same-epoch
                    DISPATCHED record (scheduler ``_done_ids`` + replay)
counters_reconcile  ``offered = accepted + shed`` and the scheduler's
                    own ``counters_reconcile()`` held in every
                    generation's process (metrics plane)
trace_continuity    every record of one job carries one trace id across
                    requeues and generations (tracing)
mem_drained         zero live transient bytes at every clean exit — the
                    scratch dir is empty and the final beacon's
                    ``mem_live`` is 0 (memory ledger discipline)
blame               the run NAMES what was injected: lethal faults
                    appear in the supervisor's failure strings as the
                    victim rank in the victim generation (post-mortem
                    verdicts, when they name a rank, agree), and benign
                    faults left trip evidence at the armed site — a
                    survived-but-undiagnosed fault is a DIAGNOSIS
                    failure (postmortem / failure attribution)
==================  ====================================================
"""

from __future__ import annotations

import importlib.util
import json
import os
import re
import sys
from typing import Dict, List, Optional

__all__ = ["run_oracles", "failing", "ORACLES"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.normpath(os.path.join(_HERE, "..", ".."))

ORACLES = (
    "workload_completed",
    "no_lost_jobs",
    "replay_determinism",
    "exactly_once",
    "counters_reconcile",
    "trace_continuity",
    "mem_drained",
    "blame",
)


def _load(name: str, relpath: str):
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, relpath)
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _sched():
    for name in ("heat_tpu.parallel.scheduler", "heat_federation_scheduler"):
        if name in sys.modules:
            return sys.modules[name]
    if __package__:
        from ..parallel import scheduler as s
        return s
    return _load(
        "heat_federation_scheduler",
        os.path.join("heat_tpu", "parallel", "scheduler.py"),
    )


def _fed():
    if "heat_tpu.parallel.federation" in sys.modules:
        return sys.modules["heat_tpu.parallel.federation"]
    if __package__:
        from ..parallel import federation as f
        return f
    return _load(
        "heat_chaos_federation",
        os.path.join("heat_tpu", "parallel", "federation.py"),
    )


def _sup_mod():
    for name in ("heat_tpu.parallel.supervisor", "heat_chaos_supervisor"):
        if name in sys.modules:
            return sys.modules[name]
    if __package__:
        from ..parallel import supervisor as s
        return s
    return _load(
        "heat_chaos_supervisor",
        os.path.join("heat_tpu", "parallel", "supervisor.py"),
    )


def _pm():
    for name in ("heat_chaos_postmortem",):
        if name in sys.modules:
            return sys.modules[name]
    return _load(
        "heat_chaos_postmortem", os.path.join("scripts", "postmortem.py")
    )


# ---------------------------------------------------------------------- #
# evidence collection
# ---------------------------------------------------------------------- #
class Evidence:
    """Everything the oracles read, gathered once per run."""

    def __init__(self, run_dir: str, schedule: dict, sup: dict):
        self.dir = run_dir
        self.schedule = schedule
        self.sup = sup  # SupervisorResult.report() dict
        self.workload = schedule["workload"]
        self.ranks = int(schedule["ranks"])
        sched = _sched()
        self.journals: Dict[str, dict] = {}  # path -> replay
        self.summaries: Dict[str, dict] = {}
        if self.workload == "fed":
            fed = _fed()
            p = os.path.join(run_dir, "fed.jsonl")
            if os.path.exists(p):
                self.journals[p] = fed.replay_federation(p)
                self.summaries[p] = fed.fed_summary(self.journals[p])
            for w in ("w0", "w1"):
                wp = os.path.join(run_dir, f"fed_{w}.jsonl")
                if os.path.exists(wp):
                    self.journals[wp] = sched.replay_journal(wp)
                    self.summaries[wp] = sched.jobs_summary(self.journals[wp])
        else:
            for r in range(self.ranks):
                p = os.path.join(run_dir, f"journal_rank{r}.jsonl")
                if os.path.exists(p):
                    self.journals[p] = sched.replay_journal(p)
                    self.summaries[p] = sched.jobs_summary(self.journals[p])
        # executions: list of (epoch, job_id) per rank, journal-ordered
        self.execs: Dict[int, List] = {}
        for r in range(self.ranks):
            path = os.path.join(run_dir, f"exec_rank{r}.log")
            rows = []
            if os.path.exists(path):
                with open(path) as fh:
                    for line in fh:
                        parts = line.split()
                        if len(parts) == 2:
                            rows.append((int(parts[0]), parts[1]))
            self.execs[r] = rows
        # trips: {f"e{epoch}:{site}": count} per rank
        self.trips: Dict[int, dict] = {}
        for r in range(self.ranks):
            path = os.path.join(run_dir, f"trips_rank{r}.json")
            try:
                with open(path) as fh:
                    self.trips[r] = json.load(fh)
            except (OSError, ValueError):
                self.trips[r] = {}
        # per-generation reports (clean exits only — a killed generation
        # writes none, by design)
        self.reports: Dict[tuple, dict] = {}
        for name in sorted(os.listdir(run_dir)):
            m = re.match(r"report_rank(\d+)_epoch(\d+)\.json$", name)
            if m:
                try:
                    with open(os.path.join(run_dir, name)) as fh:
                        self.reports[(int(m.group(1)), int(m.group(2)))] = (
                            json.load(fh)
                        )
                except (OSError, ValueError):
                    pass


# ---------------------------------------------------------------------- #
# the oracles
# ---------------------------------------------------------------------- #
def _o_workload_completed(ev: Evidence) -> Optional[str]:
    if not ev.sup.get("ok"):
        return (
            f"supervisor gave up: restarts={ev.sup.get('restarts')} "
            f"failures={ev.sup.get('failures')}"
        )
    final = ev.sup.get("generations", 1) - 1
    for r in range(ev.ranks):
        if (r, final) not in ev.reports:
            return f"rank {r} wrote no final report for generation {final}"
    if not ev.journals:
        return "no journal found — nothing to audit"
    return None


def _o_no_lost_jobs(ev: Evidence) -> Optional[str]:
    sched = _sched()
    # in fed runs the FEDERATION journal is the ground truth for job
    # fates: a job left non-terminal in a world journal because the
    # restarted federator requeued it and reassigned it to the OTHER
    # world is accounted there, not lost.  Only a job non-terminal at
    # BOTH levels fell through the recovery story.
    fed_states = {}
    if ev.workload == "fed":
        fed_replay = ev.journals.get(os.path.join(ev.dir, "fed.jsonl"))
        if fed_replay:
            fed_states = {
                jid: v.get("state") for jid, v in fed_replay["jobs"].items()
            }
    terminal = (sched.DONE, sched.FAILED, sched.SHED)
    for path, summary in sorted(ev.summaries.items()):
        if summary.get("lost", 0) == 0:
            continue
        name = os.path.basename(path)
        if ev.workload == "fed" and name.startswith("fed_w"):
            replay = ev.journals[path]
            orphans = sorted(
                jid for jid, v in replay["jobs"].items()
                if v.get("state") not in terminal
                and fed_states.get(jid) not in terminal
            )
            if orphans:
                return (
                    f"{name}: {len(orphans)} job(s) non-terminal in the "
                    f"world journal AND unaccounted by the federation: "
                    f"{orphans[:5]}"
                )
            continue
        return f"{name}: lost={summary['lost']}"
    return None


def _o_replay_determinism(ev: Evidence) -> Optional[str]:
    sched = _sched()
    fed = _fed() if ev.workload == "fed" else None
    for path, replay in sorted(ev.journals.items()):
        # replay twice: identical views (pure function of the file)
        again = (
            fed.replay_federation(path)
            if fed is not None and os.path.basename(path) == "fed.jsonl"
            else sched.replay_journal(path)
        )
        if again["jobs"] != replay["jobs"] or again["torn"] != replay["torn"]:
            return f"{os.path.basename(path)}: two replays disagree"
    # the worker's in-process summary (written pre-exit) must equal the
    # post-hoc derivation — replay is the one source of truth
    final = ev.sup.get("generations", 1) - 1
    for r in range(ev.ranks):
        rep = ev.reports.get((r, final))
        if not rep or "summary" not in rep:
            continue
        if ev.workload == "fed":
            path = os.path.join(ev.dir, "fed.jsonl")
        else:
            path = os.path.join(ev.dir, f"journal_rank{r}.jsonl")
        post = ev.summaries.get(path)
        if post is not None and rep["summary"] != post:
            return (
                f"rank {r}: in-process summary {rep['summary']} != "
                f"post-hoc replay {post}"
            )
    return None


def _o_exactly_once(ev: Evidence) -> Optional[str]:
    sched = _sched()
    # merge each scheduler journal's execution-accountability view (the
    # fed meta-journal carries assignments, not dispatches — skip it)
    witness: Dict[str, dict] = {}
    for path, rep in sorted(ev.journals.items()):
        if os.path.basename(path) == "fed.jsonl":
            continue
        for jid, w in sched.execution_witness(rep).items():
            m = witness.setdefault(
                jid, {"dispatch_epochs": set(), "first_done_epoch": None}
            )
            m["dispatch_epochs"].update(w["dispatch_epochs"])
            d = w["first_done_epoch"]
            if d is not None and (
                m["first_done_epoch"] is None or d < m["first_done_epoch"]
            ):
                m["first_done_epoch"] = d
    for r, rows in sorted(ev.execs.items()):
        for epoch, jid in rows:
            w = witness.get(jid)
            if w is None or epoch not in w["dispatch_epochs"]:
                return (
                    f"rank {r} executed {jid} in generation {epoch} with no "
                    f"same-generation DISPATCHED record — an unjournaled "
                    f"execution"
                )
            first_done = w["first_done_epoch"]
            if first_done is not None and epoch > first_done:
                return (
                    f"{jid} was journaled DONE in generation {first_done} "
                    f"but executed again in generation {epoch}"
                )
    return None


def _o_counters_reconcile(ev: Evidence) -> Optional[str]:
    if not ev.reports:
        return "no per-generation report to audit"
    for (r, e), rep in sorted(ev.reports.items()):
        c = rep.get("counters", {})
        for prefix in (("sched",) if ev.workload != "fed" else ("sched", "fed")):
            offered = c.get(f"{prefix}.offered", 0)
            accepted = c.get(f"{prefix}.accepted", 0)
            shed = c.get(f"{prefix}.shed", 0)
            if offered != accepted + shed:
                return (
                    f"rank {r} gen {e}: {prefix}.offered={offered} != "
                    f"accepted={accepted} + shed={shed}"
                )
        if rep.get("reconciled") is False:
            return f"rank {r} gen {e}: scheduler counters_reconcile() was False"
    return None


def _o_trace_continuity(ev: Evidence) -> Optional[str]:
    sched = _sched()
    for path, replay in sorted(ev.journals.items()):
        audit = sched.trace_continuity(replay)
        if not audit.get("ok", True):
            return (
                f"{os.path.basename(path)}: trace chain severed — "
                f"{audit.get('violations')}"
            )
    return None


def _o_mem_drained(ev: Evidence) -> Optional[str]:
    for r in range(ev.ranks):
        scratch = os.path.join(ev.dir, f"scratch_rank{r}")
        leftovers = sorted(os.listdir(scratch)) if os.path.isdir(scratch) else []
        if leftovers:
            return f"rank {r} leaked transients at exit: {leftovers[:5]}"
        hb = os.path.join(ev.dir, "hb", f"rank{r}.json")
        try:
            with open(hb) as fh:
                beacon = json.load(fh)
            if beacon.get("mem_live"):
                return f"rank {r} final beacon mem_live={beacon['mem_live']}"
        except (OSError, ValueError):
            pass
    return None


def _o_blame(ev: Evidence) -> Optional[str]:
    sched_mod = _sched()
    sup_mod = _sup_mod()
    pm_mod = _pm()
    # the supervisor's failure strings, parsed structurally (the
    # supervisor module owns the string shapes AND the parser — the
    # oracle never regexes them itself)
    parsed = [
        p for p in (
            sup_mod.parse_failure(s) for s in ev.sup.get("failures", ())
        ) if p is not None
    ]
    lethal = [f for f in ev.schedule.get("faults", ())
              if f["mode"] in ("exit", "hang")]
    benign = [f for f in ev.schedule.get("faults", ())
              if f["mode"] not in ("exit", "hang")]
    for f in lethal:
        gen, rank = int(f["generation"]), int(f["rank"])
        want = "died" if f["mode"] == "exit" else "stale"
        named = any(
            p["epoch"] == gen and p["rank"] == rank and p["kind"] == want
            and (want != "died" or p.get("code") == -9)
            for p in parsed
        )
        if not named:
            return (
                f"injected {f['mode']} at {f['site']} "
                f"(rank {rank}, gen {gen}) but no supervisor failure names "
                f"it as kind={want}: {ev.sup.get('failures')}"
            )
        # diagnosis agreement: a post-mortem verdict that convicts a rank
        # for this generation must convict the victim
        for pm in ev.sup.get("postmortems", ()):
            if pm.get("epoch") != gen:
                continue
            convicted = pm_mod.verdict_rank(pm)
            if convicted is not None and convicted != rank:
                return (
                    f"post-mortem for gen {gen} blamed rank {convicted}, "
                    f"but the injected victim was rank {rank}"
                )
    for f in benign:
        gen, rank, site = int(f["generation"]), int(f["rank"]), f["site"]
        count = ev.trips.get(rank, {}).get(f"e{gen}:{site}", 0)
        if count < 1:
            return (
                f"armed {site}:{f['mode']}={f['value']} on rank {rank} "
                f"gen {gen} but the site never fired there — the schedule "
                f"tested nothing (runtime twin of HT113)"
            )
    # injected benign faults must also not have broken attribution: any
    # FAILED job's reason must be a NAMED outcome, never a bare crash
    for path, replay in sorted(ev.journals.items()):
        if os.path.basename(path) == "fed.jsonl":
            continue
        for jid, view in sorted(replay["jobs"].items()):
            if view.get("state") == sched_mod.FAILED and not view.get("reason"):
                return f"{jid} FAILED with no journaled reason"
    return None


_IMPL = {
    "workload_completed": _o_workload_completed,
    "no_lost_jobs": _o_no_lost_jobs,
    "replay_determinism": _o_replay_determinism,
    "exactly_once": _o_exactly_once,
    "counters_reconcile": _o_counters_reconcile,
    "trace_continuity": _o_trace_continuity,
    "mem_drained": _o_mem_drained,
    "blame": _o_blame,
}


def run_oracles(run_dir: str, schedule: dict, sup: dict) -> List[dict]:
    """Run the full suite over one finished run; returns one
    ``{"oracle", "ok", "detail"}`` row per invariant (detail '' when it
    held).  An oracle that cannot even gather its evidence reports that
    as its failure — a chaos engine must never crash on the wreckage it
    exists to audit."""
    ev = Evidence(run_dir, schedule, sup)
    out = []
    for name in ORACLES:
        try:
            detail = _IMPL[name](ev)
        except Exception as e:
            detail = f"oracle crashed on evidence: {type(e).__name__}: {e}"
        out.append({"oracle": name, "ok": detail is None,
                    "detail": detail or ""})
    return out


def failing(results: List[dict]) -> List[str]:
    return [r["oracle"] for r in results if not r["ok"]]
