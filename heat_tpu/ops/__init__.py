"""Custom TPU kernels (Pallas) for the framework's hot ops.

XLA's fusion covers most of the ops surface; these kernels target the spots
where manual control of the VMEM working set wins (SURVEY §2.7): the KMeans
assignment step (cdist+argmin fused so the (n, k) distance matrix never
touches HBM) and local softmax attention (flash-restructured so the (S, S)
score matrix never touches HBM).  Every kernel has a jnp fallback and is
selected automatically (`interpret=True` on CPU so the same code path is
testable on the dev mesh).
"""

from .flash_attention import flash_attention
from .kmeans_kernels import fused_assign, fused_em_stats

__all__ = ["flash_attention", "fused_assign", "fused_em_stats"]
