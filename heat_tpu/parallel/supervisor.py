"""Supervised restart-with-resume: the recovery half of the elastic runtime.

The detection half (``heat_tpu.utils.health``) gives every rank a heartbeat
beacon and every collective a deadline; this module is the process that
*acts* on those signals.  A :class:`Supervisor` owns a world of rank
subprocesses and drives the state machine::

    LAUNCH ──► MONITOR ──(all ranks exit 0)──► DONE(ok)
                  │
                  ├─ rank died (nonzero / signal)
                  ├─ heartbeat went stale (> heartbeat_timeout)
                  └─ generation overran its deadline
                  │
                  ▼
          TEARDOWN: SIGUSR1 every survivor (faulthandler stack dump into
          its log — the PR-2 wiring), grace, then SIGKILL
                  │
        restarts < budget? ──no──► DONE(failed, merged diagnostic report)
                  │ yes
                  ▼
          RELAUNCH: fresh coordinator port, HEAT_TPU_RESTART_EPOCH+1,
          back to MONITOR

Workers detect ``HEAT_TPU_RESTART_EPOCH > 0`` at bring-up
(``bootstrap.restart_epoch()``) and resume from the newest verified
checkpoint (``DASO.resume()`` / ``load_array_checkpoint``'s fallback
chain), so one ``kill -9`` costs at most ``checkpoint_every`` steps — not
the run.

Why a fresh port per generation: the coordination service lives inside
rank 0; when the world dies the listener dies with it, and rebinding the
old port races TIME_WAIT.  Why kill *everyone* on one failure: a dead
peer wedges every survivor's next collective forever (the MPI heritage
this layer exists to escape) — waiting for them is pure lost time.

Everything the watchdog does is counted (``watchdog.dumps``,
``watchdog.kills``, ``health.restarts``) and returned in the
:class:`SupervisorResult`, so a post-hoc report shows every silent kill.

Stdlib-only on purpose — no package-relative imports either, so launchers
may load this file standalone (``importlib.util.spec_from_file_location``)
without importing ``heat_tpu`` and hence without importing jax: the
supervisor is the process that outlives the runtime it supervises.  The
heartbeat *reader* here is deliberately just the file mtime — the writer
(``heat_tpu.utils.health.Heartbeat``) rewrites atomically, and mtime is
immune to payload-format drift between supervisor and worker versions.
"""

from __future__ import annotations

import importlib.util
import json
import os
import re
import signal
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

__all__ = [
    "Supervisor",
    "SupervisorResult",
    "free_port",
    "dump_stacks_then_kill",
]


def free_port() -> int:
    """An OS-assigned free TCP port (the next coordinator's address)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def dump_stacks_then_kill(procs, grace: float = 3.0) -> Dict[str, int]:
    """Watchdog teardown for wedged workers: SIGUSR1 each live process (the
    workers registered a faulthandler stack dump on it, so every thread's
    traceback lands in that rank's output), give them ``grace`` seconds to
    finish dumping, then kill.  Returns ``{"dumps": n, "kills": m}`` — the
    counts the callers fold into the merged telemetry report so silent
    kills stay visible post-hoc (``dumps`` = processes asked for a stack
    dump, ``kills`` = processes that had to be SIGKILLed after the
    grace)."""
    hung = [p for p in procs if p.poll() is None]
    if not hung:
        return {"dumps": 0, "kills": 0}
    print(
        f"watchdog: {len(hung)} process(es) still alive at the deadline; "
        "requesting stack dumps (SIGUSR1) before kill",
        flush=True,
    )
    for p in hung:
        try:
            p.send_signal(signal.SIGUSR1)
        except OSError:
            pass
    t0 = time.monotonic()
    while time.monotonic() - t0 < grace and any(p.poll() is None for p in hung):
        time.sleep(0.1)
    kills = 0
    for p in hung:
        if p.poll() is None:
            p.kill()
            kills += 1
    return {"dumps": len(hung), "kills": kills}


# the two failure shapes _check_failure emits, with the "epoch N:" prefix
# run() stamps on; kept as ONE module-level pattern so post-hoc consumers
# (the chaos blame oracle, log scrapers) parse failures structurally
# instead of each growing its own regex of these strings
_FAILURE_RE = re.compile(
    r"^epoch (?P<epoch>\d+): rank (?P<rank>\d+) "
    r"(?:died with exit code (?P<code>-?\d+)"
    r"|heartbeat stale \((?P<age>[\d.]+)s)"
)


def parse_failure(failure: str) -> Optional[dict]:
    """Parse one ``SupervisorResult.failures`` string into its facts:
    ``{"epoch", "rank", "kind": "died"|"stale", "code"|"age"}`` — None
    for shapes that name no rank (e.g. a generation-deadline overrun).
    This is the read-side contract of the failure strings: a wording
    change here must keep this parser (and its tests) honest."""
    m = _FAILURE_RE.match(failure)
    if not m:
        return None
    out = {"epoch": int(m.group("epoch")), "rank": int(m.group("rank"))}
    if m.group("code") is not None:
        out["kind"] = "died"
        out["code"] = int(m.group("code"))
    else:
        out["kind"] = "stale"
        out["age"] = float(m.group("age"))
    return out


@dataclass
class SupervisorResult:
    """What happened, for the caller and the post-hoc report."""

    ok: bool
    restarts: int
    generations: int
    returncodes: List[Optional[int]]
    counters: Dict[str, int]
    failures: List[str] = field(default_factory=list)
    postmortems: List[dict] = field(default_factory=list)
    jobs: Optional[dict] = None

    def report(self) -> dict:
        """Merged diagnostic structure (printed/JSON-dumped by launchers on
        give-up; the counters slot straight into a telemetry counters
        line).  ``postmortems`` carries one flight-recorder verdict per
        failed generation (``scripts/postmortem.py``): the report no
        longer just says "rank died / went stale", it names the first
        divergent collective sequence or the straggler rank.  ``jobs``
        (when a serving scheduler's journal was configured) accounts every
        accepted job per generation — accepted/completed/retried/shed/
        failed, plus ``lost``, the count the chaos lane pins at zero."""
        rep = {
            "ok": self.ok,
            "restarts": self.restarts,
            "generations": self.generations,
            "returncodes": self.returncodes,
            "counters": dict(self.counters),
            "failures": list(self.failures),
            "postmortems": list(self.postmortems),
        }
        if self.jobs is not None:
            rep["jobs"] = dict(self.jobs)
        return rep


class Supervisor:
    """Supervise ``n_ranks`` subprocesses with liveness + heartbeat
    monitoring and restart-with-resume.

    ``spawn(rank, epoch, port)`` launches one rank of generation ``epoch``
    against a coordinator at ``port`` and returns its ``subprocess.Popen``.
    The callback owns the environment; its contract with this class:

    - export ``HEAT_TPU_RESTART_EPOCH=<epoch>`` so the worker's resume
      path can branch on it;
    - if heartbeat monitoring is wanted, have rank ``r`` beat
      ``<heartbeat_dir>/rank<r>.json`` (``health.Heartbeat``);
    - route stdout/stderr somewhere durable (a log file) — SIGUSR1 stack
      dumps land there.

    Monitoring declares the generation failed when any rank exits nonzero
    (or by signal), any live rank's heartbeat goes staler than
    ``heartbeat_timeout`` (a rank that never beats is measured from the
    generation's start), or the generation exceeds
    ``generation_deadline``.  On failure the remaining world is torn down
    via :func:`dump_stacks_then_kill` and — while ``restart_budget``
    lasts — relaunched on a fresh port with the epoch incremented.
    """

    def __init__(
        self,
        spawn: Callable[[int, int, int], subprocess.Popen],
        n_ranks: int,
        *,
        heartbeat_dir: Optional[str] = None,
        heartbeat_timeout: float = 120.0,
        restart_budget: int = 1,
        generation_deadline: Optional[float] = None,
        poll_interval: float = 0.5,
        grace: float = 3.0,
        flightrec_dir: Optional[str] = None,
        telemetry_dir: Optional[str] = None,
        job_journal: Optional[str] = None,
        monitor_port: Optional[int] = None,
        resize: Optional[Callable[[int], Optional[int]]] = None,
    ):
        self.spawn = spawn
        self.n_ranks = int(n_ranks)
        # elastic capacity (ISSUE 17): `resize(current_n_ranks)` is
        # consulted at each RELAUNCH boundary — the one point where the
        # world is fully down and the checkpoint world-reshaping path
        # (resume validates topology via the sidecar) owns state across a
        # size change.  Returning a different positive rank count re-sizes
        # the next generation; None / same / nonpositive keeps it.  The
        # federation layer derives the target from journal-visible queue
        # depth (federation.resize_target).
        self.resize = resize
        self.heartbeat_dir = heartbeat_dir
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.restart_budget = int(restart_budget)
        self.generation_deadline = generation_deadline
        self.poll_interval = float(poll_interval)
        self.grace = float(grace)
        # post-mortem inputs: when `flightrec_dir` is set, every TEARDOWN
        # harvests the ranks' crash-durable rings, runs the analyzer
        # (scripts/postmortem.py, loaded standalone — still no jax) and
        # keeps the verdict; `telemetry_dir` additionally feeds the
        # comm.<name>.wait straggler evidence into it
        self.flightrec_dir = flightrec_dir
        self.telemetry_dir = telemetry_dir
        # serving integration: when the workers run a scheduler journaling
        # to `job_journal`, the final report carries a per-generation jobs
        # section (accepted/completed/retried/shed/failed + lost) merged
        # from that journal — scheduler.py is loaded standalone, so this
        # process still never imports jax
        self.job_journal = job_journal
        self.counters: Dict[str, int] = {
            "watchdog.dumps": 0,
            "watchdog.kills": 0,
            "health.restarts": 0,
        }
        # live observability plane (ISSUE 11): when monitor_port is given
        # (0 = OS-assigned), the SUPERVISOR hosts the /metrics + /healthz
        # endpoint over the whole world's heartbeat dir — the one process
        # guaranteed to outlive any generation, serving the worst-rank
        # staleness verdict + supervision counters without importing jax
        # (utils/monitor.py is stdlib-only and loaded standalone).
        self.monitor = None
        if monitor_port is not None:
            mon = self._load_tool("heat_monitor", self._MONITOR_PATH)
            if mon is not None:
                try:
                    self.monitor = mon.Monitor(
                        port=int(monitor_port),
                        heartbeat_dir=self.heartbeat_dir,
                        stale_after=self.heartbeat_timeout,
                    )
                except OSError:
                    self.monitor = None  # a busy port must not kill supervision
                else:
                    # weakly held, registered only once the server actually
                    # bound: a dead Supervisor is pruned at the next scrape
                    # instead of pinned alive by the module-global registry
                    import weakref

                    ref = weakref.ref(self)

                    def _sup_counters():
                        s = ref()
                        return dict(s.counters) if s is not None else None

                    mon.register_gauge_source("supervisor", _sup_counters)

    # ------------------------------------------------------------------ #
    def _heartbeat_path(self, rank: int) -> str:
        return os.path.join(self.heartbeat_dir, f"rank{rank}.json")

    def _heartbeat_payload(self, rank: int) -> dict:
        """Last heartbeat JSON of ``rank`` ({} on any problem — the
        monitor must never crash on a torn/missing beacon)."""
        try:
            with open(self._heartbeat_path(rank)) as fh:
                rec = json.load(fh)
            return rec if isinstance(rec, dict) else {}
        except (OSError, ValueError):
            return {}

    def _semantic_progress(self, stale_rank: int) -> str:
        """' (stuck at seq 417 Alltoall; peers at seq 423)' when the
        beacons carry the flight recorder's collective sequence — the
        live semantic-progress view the heartbeat ``seq`` field exists
        for; '' when no beacon has one."""
        mine = self._heartbeat_payload(stale_rank)
        peers = [
            self._heartbeat_payload(r).get("seq")
            for r in range(self.n_ranks)
            if r != stale_rank
        ]
        peers = [s for s in peers if isinstance(s, int)]
        if not isinstance(mine.get("seq"), int):
            return ""
        msg = f" (stuck at seq {mine['seq']} {mine.get('collective', '?')}"
        if peers:
            msg += f"; peers at seq {max(peers)}"
        if isinstance(mine.get("mem_live"), int):
            # memory rides the beacon too (the memory ledger's live bytes):
            # "stuck at seq 4 resplit, 1.9 GB live" tells an OOM-adjacent
            # wedge apart from a plain network stall at a glance
            msg += f"; {mine['mem_live']} B live"
        return msg + ")"

    def _clear_heartbeats(self) -> None:
        """Remove the previous generation's beacons so staleness is always
        measured against THIS generation (a stale leftover file would trip
        the monitor before the new rank's first beat)."""
        if not self.heartbeat_dir:
            return
        os.makedirs(self.heartbeat_dir, exist_ok=True)
        for r in range(self.n_ranks):
            try:
                os.unlink(self._heartbeat_path(r))
            except OSError:
                pass

    def _check_failure(
        self, procs: List[subprocess.Popen], gen_wall_start: float
    ) -> Optional[str]:
        codes = [p.poll() for p in procs]
        for r, c in enumerate(codes):
            if c is not None and c != 0:
                sig = f" (signal {-c})" if c < 0 else ""
                return f"rank {r} died with exit code {c}{sig}"
        if self.heartbeat_dir:
            now = time.time()
            for r, c in enumerate(codes):
                if c is not None:
                    continue  # exited 0: no longer expected to beat
                try:
                    age = now - os.path.getmtime(self._heartbeat_path(r))
                except OSError:
                    age = now - gen_wall_start  # never beat yet
                if age > self.heartbeat_timeout:
                    return (
                        f"rank {r} heartbeat stale ({age:.1f}s > "
                        f"{self.heartbeat_timeout:.1f}s) — hung or wedged"
                        + self._semantic_progress(r)
                    )
        return None

    # ------------------------------------------------------------------ #
    # flight-recorder harvest + post-mortem (TEARDOWN diagnostics)
    # ------------------------------------------------------------------ #
    _POSTMORTEM_PATH = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "..", "scripts",
        "postmortem.py",
    )
    _SCHEDULER_PATH = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scheduler.py"
    )
    _MONITOR_PATH = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "utils", "monitor.py"
    )
    _tool_mods: Dict[str, object] = {}

    @classmethod
    def _load_tool(cls, modname: str, path: str):
        """The ONE standalone-loader for the supervisor's stdlib-only
        diagnostic companions (postmortem analyzer, scheduler journal
        replayer) — this process must never import jax.  None when the
        file is missing (a stripped install): the report then degrades
        gracefully, it never loses the supervision result over a
        diagnostics module."""
        if modname not in cls._tool_mods:
            path = os.path.normpath(path)
            if not os.path.exists(path):
                return None
            spec = importlib.util.spec_from_file_location(modname, path)
            mod = importlib.util.module_from_spec(spec)
            sys.modules[spec.name] = mod
            spec.loader.exec_module(mod)
            cls._tool_mods[modname] = mod
        return cls._tool_mods[modname]

    def _jobs_section(self) -> Optional[dict]:
        """The per-generation job accounting merged from the scheduler
        journal; None when no journal was configured or nothing was
        written.  Diagnostics must never kill the supervisor: a corrupt /
        newer-schema journal — or a scheduler.py that fails to load —
        degrades to an ``error`` entry, not a crash."""
        if not self.job_journal or not os.path.exists(self.job_journal):
            return None
        try:
            sched = self._load_tool("heat_scheduler", self._SCHEDULER_PATH)
            if sched is None:
                return None
            return sched.jobs_summary(sched.replay_journal(self.job_journal))
        except Exception as e:
            return {"error": f"journal replay failed: {e!r}"}

    @classmethod
    def _load_postmortem(cls):
        """scripts/postmortem.py via :meth:`_load_tool` (kept as a named
        entry point — the run loop calls it at every teardown)."""
        return cls._load_tool("heat_postmortem", cls._POSTMORTEM_PATH)

    def _run_postmortem(self, epoch: int, failure: str) -> Optional[dict]:
        """Analyze the dead generation's rings, then HARVEST them (move
        into ``{flightrec_dir}/epoch{epoch}/``) so the relaunched world
        starts a clean black box and the evidence survives next to the
        logs.  Returns the verdict dict (with ``epoch``/``failure``
        attached), or None when no recorder was configured."""
        if not self.flightrec_dir:
            return None
        pm = self._load_postmortem()
        if pm is None:
            return None
        try:
            verdict = pm.analyze_dir(
                self.flightrec_dir,
                heartbeat_dir=self.heartbeat_dir,
                telemetry_dir=self.telemetry_dir,
                expected_ranks=list(range(self.n_ranks)),
            )
        except Exception as e:  # diagnostics must never kill the supervisor
            verdict = {"verdict": "inconclusive", "detail": f"analyzer failed: {e!r}"}
        verdict["epoch"] = epoch
        verdict["failure"] = failure
        harvest = os.path.join(self.flightrec_dir, f"epoch{epoch}")
        try:
            os.makedirs(harvest, exist_ok=True)
            for name in os.listdir(self.flightrec_dir):
                if name.startswith("flight_rank") and name.endswith(".ring"):
                    os.replace(
                        os.path.join(self.flightrec_dir, name),
                        os.path.join(harvest, name),
                    )
        except OSError:
            pass
        return verdict

    def run(self) -> SupervisorResult:
        failures: List[str] = []
        postmortems: List[dict] = []
        epoch = 0
        while True:
            port = free_port()
            self._clear_heartbeats()
            gen_wall_start = time.time()
            gen_t0 = time.monotonic()
            procs = [self.spawn(r, epoch, port) for r in range(self.n_ranks)]
            failure: Optional[str] = None
            while True:
                codes = [p.poll() for p in procs]
                if all(c == 0 for c in codes):
                    return SupervisorResult(
                        ok=True,
                        restarts=epoch,
                        generations=epoch + 1,
                        returncodes=codes,
                        counters=dict(self.counters),
                        failures=failures,
                        postmortems=postmortems,
                        jobs=self._jobs_section(),
                    )
                failure = self._check_failure(procs, gen_wall_start)
                if failure is not None:
                    break
                if (
                    self.generation_deadline is not None
                    and time.monotonic() - gen_t0 > self.generation_deadline
                ):
                    failure = (
                        f"generation {epoch} exceeded its "
                        f"{self.generation_deadline:.0f}s deadline"
                    )
                    break
                time.sleep(self.poll_interval)
            failures.append(f"epoch {epoch}: {failure}")
            print(f"supervisor: {failures[-1]}; tearing the world down", flush=True)
            d = dump_stacks_then_kill(procs, grace=self.grace)
            self.counters["watchdog.dumps"] += d["dumps"]
            self.counters["watchdog.kills"] += d["kills"]
            for p in procs:
                if p.poll() is None:
                    p.wait()
            # every dead rank has stopped moving its ring: analyze + harvest
            # NOW, before a relaunch overwrites the evidence
            pm = self._run_postmortem(epoch, failure)
            if pm is not None:
                postmortems.append(pm)
                mod = self._load_postmortem()
                if mod is not None:
                    print("supervisor: " + mod.summary_line(pm, epoch=epoch), flush=True)
            if epoch >= self.restart_budget:
                return SupervisorResult(
                    ok=False,
                    restarts=epoch,
                    generations=epoch + 1,
                    returncodes=[p.poll() for p in procs],
                    counters=dict(self.counters),
                    failures=failures,
                    postmortems=postmortems,
                    jobs=self._jobs_section(),
                )
            epoch += 1
            self.counters["health.restarts"] += 1
            if self.resize is not None:
                try:
                    want = self.resize(self.n_ranks)
                except Exception:
                    want = None  # a broken resize hook must not kill supervision
                if want is not None and int(want) > 0 and int(want) != self.n_ranks:
                    # beacons are cleared under the OLD count first: a
                    # shrink would otherwise leave high-rank beacons behind
                    # for the staleness monitor to convict
                    self._clear_heartbeats()
                    print(
                        f"supervisor: resizing world {self.n_ranks} -> "
                        f"{int(want)} rank(s) for epoch {epoch}",
                        flush=True,
                    )
                    self.n_ranks = int(want)
                    self.counters["health.resizes"] = (
                        self.counters.get("health.resizes", 0) + 1
                    )
            print(
                f"supervisor: restarting the world (epoch {epoch} of "
                f"<= {self.restart_budget}) on a fresh coordinator port",
                flush=True,
            )
