"""Learning-rate schedules (reference: ``heat/optim/lr_scheduler.py``).

The reference thin-wraps ``torch.optim.lr_scheduler`` with DASO-skip
awareness; here the schedules are optax-native factories with the same names.
"""

from __future__ import annotations

import optax

__all__ = [
    "ConstantLR",
    "CosineAnnealingLR",
    "CosineAnnealingWarmRestarts",
    "ExponentialLR",
    "LambdaLR",
    "LinearLR",
    "MultiStepLR",
    "OneCycleLR",
    "PolynomialLR",
    "StepLR",
]


def StepLR(lr: float, step_size: int, gamma: float = 0.1):
    """Decay lr by ``gamma`` every ``step_size`` steps."""
    return optax.exponential_decay(
        init_value=lr, transition_steps=step_size, decay_rate=gamma, staircase=True
    )


def ExponentialLR(lr: float, gamma: float):
    return optax.exponential_decay(init_value=lr, transition_steps=1, decay_rate=gamma)


def CosineAnnealingLR(lr: float, T_max: int, eta_min: float = 0.0):
    return optax.cosine_decay_schedule(init_value=lr, decay_steps=T_max, alpha=eta_min / lr if lr else 0.0)


def LambdaLR(lr: float, lr_lambda):
    def schedule(step):
        return lr * lr_lambda(step)

    return schedule


def MultiStepLR(lr: float, milestones, gamma: float = 0.1):
    """Decay lr by ``gamma`` at each milestone step (torch semantics)."""
    boundaries = {int(m): gamma for m in sorted(milestones)}
    return optax.piecewise_constant_schedule(init_value=lr, boundaries_and_scales=boundaries)


def ConstantLR(lr: float, factor: float = 1.0 / 3.0, total_iters: int = 5):
    """lr * factor for the first ``total_iters`` steps, then lr (torch semantics)."""
    return optax.join_schedules(
        [optax.constant_schedule(lr * factor), optax.constant_schedule(lr)],
        boundaries=[total_iters],
    )


def LinearLR(lr: float, start_factor: float = 1.0 / 3.0, end_factor: float = 1.0, total_iters: int = 5):
    """Linear ramp from ``lr*start_factor`` to ``lr*end_factor`` over
    ``total_iters`` steps, constant afterwards (torch semantics; optax's
    linear_schedule already holds the end value past the transition)."""
    return optax.linear_schedule(
        init_value=lr * start_factor, end_value=lr * end_factor, transition_steps=total_iters
    )


def PolynomialLR(lr: float, total_iters: int = 5, power: float = 1.0):
    """Polynomial decay to zero over ``total_iters`` steps (torch semantics)."""
    return optax.polynomial_schedule(
        init_value=lr, end_value=0.0, power=power, transition_steps=total_iters
    )


def CosineAnnealingWarmRestarts(lr: float, T_0: int, T_mult: int = 1, eta_min: float = 0.0):
    """SGDR cosine schedule restarting indefinitely (torch semantics).

    The restart position is computed analytically per step (jit-safe), so
    there is no finite horizon: ``T_mult == 1`` cycles forever with period
    ``T_0``; ``T_mult > 1`` grows the period geometrically.

    Boundary exactness: the restart index from the f32 log quotient is
    corrected against the exact (rounded-integer) cycle starts, so steps
    landing exactly on a restart return the restarted peak lr.  This is
    *stricter than torch*, whose float64 ``log(epoch*(Tm-1)/T0 + 1, Tm)``
    itself floors into the previous cycle for some boundaries (e.g.
    ``T_0=5, T_mult=3`` at step 605 torch returns ``eta_min``; we return
    the peak, which is the mathematically correct SGDR value)."""
    import jax.numpy as jnp

    def schedule(step):
        s = jnp.asarray(step, jnp.float32)
        if T_mult == 1:
            t_cur = jnp.mod(s, T_0)
            period = jnp.asarray(T_0, jnp.float32)
        else:
            # n = floor(log_Tm(step*(Tm-1)/T_0 + 1)) restarts so far.  The
            # f32 log quotient can land exactly-on-boundary steps at n∓eps
            # (flooring into the wrong cycle → eta_min instead of the
            # restarted peak), so correct n against the exact integer cycle
            # starts T_0·(Tm^m − 1)/(Tm − 1), which torch computes iteratively.
            n = jnp.floor(jnp.log(s * (T_mult - 1) / T_0 + 1.0) / jnp.log(float(T_mult)))

            def cycle_start(m):
                # integer by construction (T_0, T_mult ints) — round away the
                # exp/log error in jnp.power so the boundary compares are exact
                return jnp.round(T_0 * (jnp.power(float(T_mult), m) - 1.0) / (T_mult - 1.0))

            n = jnp.where(s >= cycle_start(n + 1.0), n + 1.0, n)
            n = jnp.where(s < cycle_start(n), n - 1.0, n)
            t_start = cycle_start(n)
            period = T_0 * (float(T_mult) ** n)
            t_cur = s - t_start
        cos = 0.5 * (1.0 + jnp.cos(jnp.pi * t_cur / period))
        return eta_min + (lr - eta_min) * cos

    return schedule


def OneCycleLR(lr: float, total_steps: int, pct_start: float = 0.3,
               div_factor: float = 25.0, final_div_factor: float = 1e4):
    """One-cycle policy, replicating torch's ``anneal_strategy='cos'``
    formula exactly (including FRACTIONAL phase boundaries: the peak step is
    the float ``pct_start·total_steps − 1``, not a rounded integer)."""
    import jax.numpy as jnp

    end1 = pct_start * total_steps - 1.0  # float, torch's phase-1 end step
    init_lr = lr / div_factor
    final_lr = init_lr / final_div_factor
    anneal_span = (total_steps - 1.0) - end1

    def _cos(frac, a, b):
        return b + (a - b) * 0.5 * (1.0 + jnp.cos(jnp.pi * frac))

    def schedule(step):
        s = jnp.asarray(step, jnp.float32)
        up = _cos(jnp.clip(s / jnp.maximum(end1, 1e-9), 0.0, 1.0), init_lr, lr)
        down = _cos(jnp.clip((s - end1) / jnp.maximum(anneal_span, 1e-9), 0.0, 1.0), lr, final_lr)
        return jnp.where(s <= end1, up, down)

    return schedule
