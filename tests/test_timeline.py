"""Cross-rank timeline export + critical-path attribution (ISSUE 18).

``heat_tpu/analysis/timeline.py`` merges per-rank telemetry JSONL,
flight-recorder rings and scheduler journals into one Chrome-trace /
Perfetto timeline; ``scripts/traceviz.py`` is the stdlib-only CLI.
Exercised here against synthetic artifacts:

- **clock alignment**: injected skew recovered from shared collective
  anchors within the asserted residual; a rank with telemetry but no
  ring is *named* unaligned, never silently merged;
- **exporter tolerance**: torn rings, empty dirs, single-rank dirs —
  the exporter degrades, it never dies;
- **trace schema**: the export passes the stdlib validator; the
  validator rejects garbage; flow events join both ranks' stamps for
  every shared collective seq;
- **critical path**: the short-stream straggler is the named gating
  rank at its last stamped ``(seq, op)`` — the same convention the
  post-mortem uses — and step windows blame the dominant comm wait;
- **CLI**: export + validate round trip, ``--validate-only``, empty
  and missing inputs exit 0/1 per contract.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACEVIZ = os.path.join(REPO, "scripts", "traceviz.py")


def _load(name, relpath):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, relpath)
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


tl = _load("timeline_under_test", "heat_tpu/analysis/timeline.py")
fr = tl._flightrec_mod()

BASE = 1000.0


def _mkring(d, rank, last_seq, skew=0.0, jitter=0.0, slots=64):
    """Collective stamps seq 1..last_seq at BASE + seq*0.01 + skew."""
    r = fr.FlightRecorder(
        os.path.join(d, f"flight_rank{rank}.ring"), slots=slots, rank=rank
    )
    for s in range(1, last_seq + 1):
        op = "resplit" if s % 3 == 0 else "Allreduce"
        r.record("coll", seq=s, op=op, wire=1024,
                 t=BASE + s * 0.01 + skew + jitter * (1 - s % 2))
    r.close()
    return os.path.join(d, f"flight_rank{rank}.ring")


def _span(name, ts, dur, rank=0, depth=0, attrs=None):
    rec = {"type": "span", "rank": rank, "name": name, "ts": ts,
           "dur_s": dur, "self_s": dur, "depth": depth}
    if attrs is not None:
        rec["attrs"] = attrs
    return rec


def _write_jsonl(d, rank, records, pid=None):
    with open(os.path.join(d, f"rank{rank}.jsonl"), "w") as fh:
        if pid is not None:
            fh.write(json.dumps(
                {"type": "meta", "rank": rank, "pid": pid,
                 "wall_time": BASE}) + "\n")
        for rec in records:
            fh.write(json.dumps(rec) + "\n")


def _standard_dir(d):
    """Two ring ranks (rank1 skewed +5s, straggling at seq 12), rank0
    telemetry with comm-dominated steps, ring-less rank2 telemetry, and
    a scheduler journal sharing rank0's pid."""
    _mkring(d, 0, 20)
    _mkring(d, 1, 12, skew=5.0)
    spans = []
    for i in range(3):
        t0 = BASE + i * 0.1
        spans.append(_span("daso.step", t0, 0.09,
                           attrs={"trace_id": "tr1"}))
        spans.append(_span("comm.allreduce.wait", t0 + 0.02, 0.05,
                           depth=1, attrs={"trace_id": "tr1"}))
    _write_jsonl(d, 0, spans, pid=1234)
    _write_jsonl(d, 2, [_span("io.load", BASE, 0.01, rank=2, attrs={})])
    with open(os.path.join(d, "sched_journal.jsonl"), "w") as fh:
        fh.write(json.dumps({"type": "meta", "pid": 1234, "epoch": 0,
                             "t": BASE}) + "\n")
        fh.write(json.dumps({"type": "submitted", "id": "j1", "tid": "tr1",
                             "t": BASE + 0.01}) + "\n")
        fh.write(json.dumps({"type": "done", "id": "j1", "tid": "tr1",
                             "t": BASE + 0.3}) + "\n")
    return d


# ---------------------------------------------------------------------- #
# clock alignment
# ---------------------------------------------------------------------- #
class TestClockAlignment:
    def test_injected_skew_recovered_within_residual(self, tmp_path):
        d = str(tmp_path)
        _mkring(d, 0, 16)
        _mkring(d, 1, 16, skew=5.0001)
        align = tl.estimate_clock_offsets(tl.load_rings([d]))
        assert align["ref"] == 0
        assert align["offsets"][0] == 0.0
        assert abs(align["offsets"][1] - 5.0001) < 1e-6
        assert align["per_rank"][1]["anchors"] == 16
        assert align["per_rank"][1]["max_residual_s"] < 1e-6

    def test_jittered_skew_uses_robust_median(self, tmp_path):
        # even seqs (10 of 21) land 3ms late on rank1: the median still
        # nails the bulk offset; the residual reports the jitter honestly
        d = str(tmp_path)
        _mkring(d, 0, 21)
        _mkring(d, 1, 21, skew=2.0, jitter=0.003)
        align = tl.estimate_clock_offsets(tl.load_rings([d]))
        off = align["offsets"][1]
        assert abs(off - 2.0) < 2e-3
        assert 1e-3 < align["per_rank"][1]["max_residual_s"] < 5e-3

    def test_rank_with_telemetry_but_no_ring_named_unaligned(self, tmp_path):
        d = _standard_dir(str(tmp_path))
        bundle = tl.assemble([d])
        un = {u["rank"]: u["reason"] for u in bundle["align"]["unaligned"]}
        assert un.get(2) == "no-ring"
        # and it is NOT silently given an offset
        assert 2 not in bundle["align"]["offsets"]

    def test_clock_report_lines(self, tmp_path):
        d = _standard_dir(str(tmp_path))
        rep = tl.clock_report(tl.assemble([d]))
        assert "CLOCK-ALIGN rank=1 offset_ms=+5000.0" in rep
        assert "anchors=12" in rep
        assert "CLOCK-ALIGN rank=2 UNALIGNED reason=no-ring" in rep

    def test_disjoint_seq_ranges_not_aligned(self, tmp_path):
        d = str(tmp_path)
        r0 = fr.FlightRecorder(
            os.path.join(d, "flight_rank0.ring"), slots=8, rank=0)
        r0.record("coll", seq=1, op="Allreduce", wire=8, t=BASE)
        r0.close()
        r1 = fr.FlightRecorder(
            os.path.join(d, "flight_rank1.ring"), slots=8, rank=1)
        r1.record("coll", seq=99, op="Allreduce", wire=8, t=BASE)
        r1.close()
        align = tl.estimate_clock_offsets(tl.load_rings([d]))
        assert any(u["rank"] == 1 and u["reason"] == "no-shared-anchors"
                   for u in align["unaligned"])


# ---------------------------------------------------------------------- #
# trace export + schema validation
# ---------------------------------------------------------------------- #
class TestChromeTrace:
    def test_export_is_schema_valid(self, tmp_path):
        d = _standard_dir(str(tmp_path))
        trace = tl.to_chrome_trace(tl.assemble([d]))
        assert tl.validate_chrome_trace(trace) == []

    def test_one_pid_per_rank_with_metadata(self, tmp_path):
        d = _standard_dir(str(tmp_path))
        evs = tl.to_chrome_trace(tl.assemble([d]))["traceEvents"]
        names = {e["pid"]: e["args"]["name"] for e in evs
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert names[0] == "rank0" and names[1] == "rank1"
        assert names[tl.SCHED_PID] == "scheduler (journal)"

    def test_flow_events_join_ranks_for_every_shared_seq(self, tmp_path):
        d = _standard_dir(str(tmp_path))
        evs = tl.to_chrome_trace(tl.assemble([d]))["traceEvents"]
        flows = [e for e in evs
                 if e["ph"] in "stf" and e.get("cat") == "collective"]
        # rank1 stamped seqs 1..12; every one of them has a start on one
        # rank and a finish on the other
        assert {e["id"] for e in flows} == set(range(1, 13))
        by_seq = {}
        for e in flows:
            by_seq.setdefault(e["id"], set()).add((e["ph"], e["pid"]))
        for seq, members in by_seq.items():
            phs = {ph for ph, _ in members}
            pids = {pid for _, pid in members}
            assert "s" in phs and "f" in phs, (seq, members)
            assert pids == {0, 1}, (seq, members)

    def test_trace_id_flows_cross_scheduler(self, tmp_path):
        d = _standard_dir(str(tmp_path))
        evs = tl.to_chrome_trace(tl.assemble([d]))["traceEvents"]
        tr = [e for e in evs if e.get("cat") == "trace"]
        assert tr and any(e["pid"] == tl.SCHED_PID for e in tr)
        assert all(e["id"] == "tr-tr1" for e in tr)

    def test_validator_rejects_garbage(self):
        assert tl.validate_chrome_trace([]) != []
        assert tl.validate_chrome_trace({"traceEvents": "nope"}) != []
        bad_ph = {"traceEvents": [
            {"ph": "Z", "pid": 0, "tid": 0, "ts": 0, "name": "x"}]}
        assert any("ph" in p for p in tl.validate_chrome_trace(bad_ph))
        no_dur = {"traceEvents": [
            {"ph": "X", "pid": 0, "tid": 0, "ts": 0, "name": "x"}]}
        assert any("dur" in p for p in tl.validate_chrome_trace(no_dur))
        neg = {"traceEvents": [
            {"ph": "X", "pid": 0, "tid": 0, "ts": 0, "dur": -1, "name": "x"}]}
        assert tl.validate_chrome_trace(neg) != []

    def test_torn_ring_still_exports_valid_trace(self, tmp_path):
        d = str(tmp_path)
        p0 = _mkring(d, 0, 6, slots=8)
        _mkring(d, 1, 6, slots=8)
        with open(p0, "r+b") as fh:
            fh.seek(fr._HEADER_SIZE + 2 * fr.DEFAULT_SLOT_SIZE + fr._LEN_SIZE)
            fh.write(b"\xff" * 16)
        bundle = tl.assemble([d])
        assert bundle["rings"][0]["slots_skipped"] == 1
        trace = tl.to_chrome_trace(bundle)
        assert tl.validate_chrome_trace(trace) == []
        # the surviving anchors still align the pair
        assert 1 in bundle["align"]["offsets"]

    def test_ring_only_ranks_get_reconstructed_slices(self, tmp_path):
        # chaos path: workers SIGKILLed before flushing telemetry — the
        # ring's span/span_end pairs become the lane slices
        d = str(tmp_path)
        r = fr.FlightRecorder(
            os.path.join(d, "flight_rank0.ring"), slots=16, rank=0)
        r.record("span", name="daso.step", t=BASE)
        r.record("coll", seq=1, op="Allreduce", wire=8, t=BASE + 0.01)
        r.record("span_end", name="daso.step", t=BASE + 0.05)
        r.close()
        evs = tl.to_chrome_trace(tl.assemble([d]))["traceEvents"]
        slices = [e for e in evs if e["ph"] == "X"
                  and e["name"] == "daso.step"]
        assert len(slices) == 1 and abs(slices[0]["dur"] - 50000) < 1


# ---------------------------------------------------------------------- #
# critical path
# ---------------------------------------------------------------------- #
class TestCriticalPath:
    def test_step_kind_blames_dominant_comm_wait(self, tmp_path):
        d = _standard_dir(str(tmp_path))
        cp = tl.critical_path(tl.assemble([d]))
        step_lines = [l for l in cp["lines"] if "kind=daso.step" in l]
        assert len(step_lines) == 1
        assert "rank=0 op=comm.allreduce.wait" in step_lines[0]
        assert "share=" in step_lines[0]

    def test_short_stream_straggler_is_the_gating_rank(self, tmp_path):
        # rank1 stops stamping at seq 12 (op=resplit) — the post-mortem
        # convention: blame lands at the straggler's LAST stamped (seq, op)
        d = _standard_dir(str(tmp_path))
        cp = tl.critical_path(tl.assemble([d]))
        coll = [l for l in cp["lines"] if "kind=collective" in l]
        assert any("rank=1 op=resplit seq=12 share=" in l for l in coll), coll

    def test_blame_table_shares_sum_to_one(self, tmp_path):
        d = _standard_dir(str(tmp_path))
        blame = tl.critical_path(tl.assemble([d]))["blame"]
        assert blame["total_s"] > 0
        assert abs(sum(v["share"] for v in blame["by_rank"].values())
                   - 1.0) < 1e-6
        assert abs(sum(v["share"] for v in blame["by_op"].values())
                   - 1.0) < 1e-6

    def test_greppable_line_format(self, tmp_path):
        import re
        d = _standard_dir(str(tmp_path))
        pat = re.compile(
            r"^CRITICAL-PATH kind=\S+ rank=\d+ op=\S+ seq=(\d+|-) "
            r"share=\d\.\d{3}$")
        for line in tl.critical_path(tl.assemble([d]))["lines"]:
            assert pat.match(line), line

    def test_no_artifacts_no_lines(self, tmp_path):
        bundle = tl.assemble([str(tmp_path)])
        assert tl.critical_path(bundle)["lines"] == []
        assert tl.critical_path_report(bundle) == ""


# ---------------------------------------------------------------------- #
# CLI
# ---------------------------------------------------------------------- #
class TestTracevizCLI:
    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, TRACEVIZ, *argv],
            capture_output=True, text=True, timeout=60,
        )

    def test_export_validate_round_trip(self, tmp_path):
        d = _standard_dir(str(tmp_path))
        out = os.path.join(d, "trace.json")
        r = self._run(d, "--out", out)
        assert r.returncode == 0, r.stderr
        assert "TRACE-EXPORT events=" in r.stdout
        assert "CLOCK-ALIGN rank=1" in r.stdout
        assert "CRITICAL-PATH kind=collective" in r.stdout
        r2 = self._run("--validate-only", out)
        assert r2.returncode == 0 and "TRACE-VALID events=" in r2.stdout

    def test_json_sidecar(self, tmp_path):
        d = _standard_dir(str(tmp_path))
        sidecar = os.path.join(d, "cp.json")
        r = self._run(d, "--out", os.path.join(d, "t.json"),
                      "--json", sidecar)
        assert r.returncode == 0, r.stderr
        payload = json.load(open(sidecar))
        assert payload["align"]["offsets"] and payload["critical_path"]

    def test_empty_dir_exits_0(self, tmp_path):
        r = self._run(str(tmp_path))
        assert r.returncode == 0, r.stderr

    def test_single_rank_dir_exits_0(self, tmp_path):
        d = str(tmp_path)
        _mkring(d, 0, 4)
        r = self._run(d, "--out", os.path.join(d, "t.json"))
        assert r.returncode == 0, r.stderr
        assert "TRACE-EXPORT events=" in r.stdout

    def test_no_targets_exits_1(self):
        assert self._run().returncode == 1

    def test_validate_only_rejects_garbage(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"traceEvents": [{"ph": "Z"}]}))
        r = self._run("--validate-only", str(bad))
        assert r.returncode == 1 and "INVALID" in r.stderr


# ---------------------------------------------------------------------- #
# report integration
# ---------------------------------------------------------------------- #
class TestReportIntegration:
    def test_critical_path_rides_telemetry_report(self, tmp_path, capsys):
        trep = _load("trep_for_timeline", "scripts/telemetry_report.py")
        d = _standard_dir(str(tmp_path))
        trace_out = os.path.join(d, "merged_trace.json")
        assert trep.main([d, "--timeline", "0",
                          "--trace-out", trace_out]) == 0
        out = capsys.readouterr().out
        assert "CLOCK-ALIGN rank=" in out
        assert "CRITICAL-PATH kind=" in out
        assert "TRACE-EXPORT events=" in out
        trace = json.load(open(trace_out))
        assert tl.validate_chrome_trace(trace) == []
