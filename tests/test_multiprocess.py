"""N-process SPMD tier (round-4 verdict #1, widened per r4 weak #6;
reference contract: the same suite passes under ``mpirun -n N``, SURVEY §4).

Two tiers, both launched as subprocess trees (the suite's own jax runtime
is single-process and cannot be re-initialized):

- the bespoke dryrun (``scripts/multiprocess_dryrun.py``) at BOTH mesh
  shapes — 2 processes × 4 devices and 4 processes × 2 devices — covering
  factories/reductions, ``resplit_``, token-ring hyperslab HDF5,
  cross-process ``numpy()``/``__repr__``, a DataParallel step, ring
  attention / MoE / pipeline seam crossings, and ``Communication.rank``
  semantics;
- the REAL suite's ``-m mp`` subset run SPMD across OS processes
  (``launch_pytest``): every rank executes the identical pytest selection
  with a shared per-test tmp dir, so IO round-trips and collectives cross
  the process seam inside ordinary suite tests.
"""

# assert_distributed exception (r4 #8): the checks run inside the worker
# subprocesses (is_fully_addressable assertions there are the multi-process
# equivalent of assert_distributed).

import importlib.util
import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "multiprocess_dryrun.py")

_spec = importlib.util.spec_from_file_location("multiprocess_dryrun", SCRIPT)
mpd = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(mpd)


@pytest.mark.heavy
@pytest.mark.parametrize(
    "n_proc,devs",
    [
        (2, 4),
        # the transposed shape sweeps the same seams at a different
        # process/device ratio — kept out of the quick (-m 'not slow')
        # lane for budget; the CI multiprocess job runs it unfiltered
        pytest.param(4, 2, marks=pytest.mark.slow),
    ],
    ids=["2x4", "4x2"],
)
def test_n_process_spmd_tier(n_proc, devs):
    proc = mpd.launch(timeout=700, n_proc=n_proc, devs_per_proc=devs)
    out = proc.stdout
    assert proc.returncode == 0, (proc.stderr or out)[-2000:]
    assert mpd.PASS_MARKER in out
    for pid in range(n_proc):
        assert f"[{pid}] {mpd.MARKER}" in out, out[-2000:]
        assert f"[{pid}] comm: size=8 rank={pid}/{n_proc}" in out
        # every rank exported a telemetry jsonl file...
        assert f"[{pid}] telemetry: rank file exported" in out, out[-2000:]
        # ...and ran the armed metadata sanitizer incl. the cross-rank
        # metadata-agreement digest (ISSUE 4: HEAT_TPU_CHECKS on a real
        # multi-process mesh)
        assert f"[{pid}] SANITIZER-OK" in out, out[-2000:]
        # ...and streamed a budgeted (tiled) resplit across the process
        # seam, bit-exact vs the monolithic oracle (ISSUE 6: the chunked
        # pipeline's per-tile SPMD programs over a real multi-process mesh)
        assert f"[{pid}] RESPLIT-BUDGETED tiles=3" in out, out[-2000:]
        # ...and seq-stamped every staged collective into its crash-durable
        # flight-recorder ring (ISSUE 7): lockstep SPMD means every rank
        # reports the IDENTICAL final sequence number
        assert re.search(rf"\[{pid}\] FLIGHTREC seq=\d+ op=", out), out[-2000:]
        # ...and the device-memory ledger (ISSUE 14, env-armed via
        # HEAT_TPU_MEMLEDGER=1) tracked every choke-point buffer: each rank
        # prints its greppable high-water line with a nonzero peak
        mm = re.search(rf"\[{pid}\] MEM-PEAK rank={pid} bytes=(\d+)", out)
        assert mm, out[-2000:]
        assert int(mm.group(1)) > 0
    seqs = set(re.findall(r"\] FLIGHTREC seq=(\d+) op=", out))
    assert len(seqs) == 1, f"ranks disagree on the collective seq: {seqs}"
    # ...and rank 0 armed the live /metrics + /healthz endpoint and scraped
    # its own server over a real localhost socket MID-RUN (ISSUE 11): a
    # non-empty Prometheus payload carrying the comm.* accounting, and a
    # fresh worst-rank /healthz verdict
    m = re.search(r"\[0\] MONITOR-SCRAPED metrics=(\d+) healthz=ok", out)
    assert m, out[-2000:]
    assert int(m.group(1)) > 10  # a real registry snapshot, not a stub
    # ...and the launcher merged them into ONE multi-rank report (ISSUE 3
    # acceptance: scripts/telemetry_report.py folds the mp lane's rank files)
    assert f"TELEMETRY-MERGED ranks={n_proc}" in out, out[-2000:]
    # ...and the green run's rings read CLEAN end to end (ISSUE 7: every
    # rank's stream identical AND terminated by a shutdown record)
    assert "POSTMORTEM verdict=clean" in out, out[-2000:]
    # ...and the cross-rank timeline exporter (ISSUE 18) aligned every
    # rank's clock from the shared collective stamps, named the gating
    # rank of the collective stream, and wrote a schema-valid Chrome
    # trace artifact (validated in-process before the PASS verdict)
    assert re.search(r"CLOCK-ALIGN rank=\d+ offset_ms=", out), out[-3000:]
    assert re.search(
        r"CRITICAL-PATH kind=collective rank=\d+ op=\S+ seq=\d+ share=", out
    ), out[-3000:]
    assert re.search(r"TRACE-EXPORT events=\d+ ranks=\d+ out=", out), out[-3000:]
    assert "trace INVALID" not in out, out[-3000:]


@pytest.mark.heavy
@pytest.mark.slow
@pytest.mark.chaos  # runs in the chaos CI lane too (-m chaos)
def test_postmortem_names_hung_rank_and_seq():
    """ISSUE 7 acceptance (a): one rank of a live 2-process gloo world hangs
    inside a staged collective (injected ``comm.collective`` hang at a known
    iteration) → the supervisor's heartbeat monitor tears the world down →
    the harvested flight-recorder rings name the hung rank AND the exact
    collective sequence it hung on (the stamp is written before the fault
    site fires, so the ring's last record IS the wedged collective).

    ISSUE 20: the whole contract — FAILED rc, semantic staleness line,
    the derived straggler verdict and critical-path attribution at the
    EXACT seq the victim announced — is the declarative
    ``hang-straggler-verdict`` spec replayed through the chaos engine."""
    from heat_tpu.chaos import scenarios

    proc = scenarios.run_scenario("hang-straggler-verdict")
    assert scenarios.check_scenario("hang-straggler-verdict", proc) == [], (
        (proc.stderr or proc.stdout)[-3000:]
    )


@pytest.mark.heavy
@pytest.mark.slow
@pytest.mark.chaos
def test_postmortem_names_first_divergent_seq():
    """ISSUE 7 acceptance (b): one rank of a 3-process world stages a
    rank-conditional EXTRA collective (the classic SPMD desync) → the
    analyzer reports the first divergent sequence and names the deviating
    rank by majority vote across the 3 fingerprint streams.

    ISSUE 20: declared as the ``desync-minority-verdict`` spec — the
    derived clause asserts the verdict names the EXACT seq the victim
    announced (``PM-DESYNC expect_seq=N`` → ``verdict=desync seq=N``)."""
    from heat_tpu.chaos import scenarios

    proc = scenarios.run_scenario("desync-minority-verdict")
    assert scenarios.check_scenario("desync-minority-verdict", proc) == [], (
        (proc.stderr or proc.stdout)[-3000:]
    )


@pytest.mark.heavy
@pytest.mark.slow
def test_serve_mode_green_all_jobs_accounted():
    """ISSUE 10: the elastic serving tier on a healthy 2-process world —
    20 mixed jobs against an 18-slot queue: 18 accepted and DONE, 2 shed
    with JobRejected{queue_full}, counters reconciled on every rank, the
    launcher's journal attestation and per-tenant SLO table printed, and
    the flight-recorder lockstep bracket reads clean."""
    proc = mpd.launch(timeout=700, n_proc=2, devs_per_proc=4, mode="serve")
    out = proc.stdout
    assert proc.returncode == 0, (proc.stderr or out)[-3000:]
    assert mpd.PASS_MARKER in out
    for pid in range(2):
        assert (
            f"[{pid}] {mpd.SERVE_MARKER} jobs=20 done=18 failed=0 shed=2 "
            "requeued=0 reconciled=True"
        ) in out, out[-3000:]
        # load shedding answered synchronously with a structured reason
        assert f"[{pid}] SCHED-SHED id=job018 reason=queue_full" in out
        assert f"[{pid}] SCHED-SHED id=job019 reason=queue_full" in out
    # the launcher's attestation comes from the JOURNAL, independently of
    # the workers' own accounting — and they agree
    assert "SCHED jobs=20 done=18 requeued=0 shed=2 failed=0 lost=0" in out, (
        out[-3000:]
    )
    # per-tenant SLO table rendered from the journal + sched.job spans
    assert "per-tenant serving SLO" in out, out[-3000:]
    for tenant in ("acme", "globex", "initech"):
        assert tenant in out
    # live endpoint (ISSUE 11): the mid-run /metrics scrape returned
    # reconciled sched_* counters straight off the Prometheus payload —
    # offered = accepted + shed (20 = 18 + 2)
    assert (
        "[0] MONITOR-SCRAPED" in out
        and "offered=20 accepted=18 shed=2 reconciled=True" in out
    ), out[-3000:]
    # trace propagation: every journaled record of a job carries its
    # submit-minted trace id, and the launcher assembled one job's causal
    # timeline across journal + telemetry + flight-ring sources
    assert "SCHED-TRACE-CONTINUITY jobs=20 ok=True" in out, out[-3000:]
    assert "causal timeline for trace" in out, out[-3000:]
    # step-time breakdown over the sched.job spans reports an overlap number
    assert re.search(r"STEP-OVERLAP kind=sched\.job steps=\d+", out), out[-3000:]
    assert "POSTMORTEM verdict=clean" in out, out[-3000:]
    # ISSUE 18: the timeline exporter attributes the serving lane's
    # critical path per step kind (sched.job windows) and per-step
    # latency distribution rides beside the pinned aggregate
    assert re.search(r"CRITICAL-PATH kind=sched\.job rank=\d+", out), out[-3000:]
    assert re.search(r"STEP-DIST kind=sched\.job n=\d+", out), out[-3000:]
    assert re.search(r"TRACE-EXPORT events=\d+ ranks=\d+ out=", out), out[-3000:]


@pytest.mark.heavy
@pytest.mark.slow
@pytest.mark.chaos  # the chaos CI lane's serve scenario (-m chaos)
def test_serve_sigkill_mid_queue_loses_zero_jobs():
    """ISSUE 10 acceptance: SIGKILL one serving rank mid-queue (the
    sched.dispatch fault's exit mode) → the supervisor tears down and
    relaunches → every rank replays rank 0's journal and requeues the
    accepted-but-unfinished jobs EXACTLY once → every accepted job ends
    DONE (zero lost, no duplicate execution), the shed jobs stay shed,
    and the launcher's journal-derived attestation proves it.

    ISSUE 20: the contract — zero-loss attestation, per-rank lockstep
    requeue equality (derived clauses), trace continuity across the
    restart — is the declarative ``serve-sigkill-mid-queue`` spec."""
    from heat_tpu.chaos import scenarios

    proc = scenarios.run_scenario("serve-sigkill-mid-queue")
    assert scenarios.check_scenario("serve-sigkill-mid-queue", proc) == [], (
        (proc.stderr or proc.stdout)[-3000:]
    )


@pytest.mark.heavy
@pytest.mark.slow  # ~2 min: 2 OS-process ranks each run the -m mp subset;
# the CI multiprocess lane runs this file unfiltered, so the quick
# (-m 'not slow') lane skipping it loses no coverage
def test_real_suite_subset_multiprocess():
    """>= 50 ordinary suite tests pass with 2 OS processes underneath
    (VERDICT r4 weak #6 'no real suite subset runs multi-process').

    Launched through the known-flake retry harness: the 2-proc gloo world
    is the other documented victim of the pre-existing
    ``op.preamble.length`` SIGABRT (bisected flaky at the SEED) — a rank
    failing WITH that signature retries the subset once; a failure
    without it, or a second signatured failure, is real."""
    results = mpd.launch_pytest_retrying_known_flake(
        timeout=2800, n_proc=2, devs_per_proc=4
    )
    assert len(results) == 2
    for rank, (rc, out) in enumerate(results):
        assert rc == 0, f"rank {rank}:\n{out[-3000:]}"
        m = re.search(r"(\d+) passed", out)
        assert m, out[-500:]
        assert int(m.group(1)) >= 50, f"rank {rank}: only {m.group(1)} passed"
