"""scripts/bench_compare.py — the perun-CB regression-comparator analogue
(SURVEY §2.6, VERDICT r4 item 7): payload loading (driver wrapper + direct
manual captures), direction inference, threshold flagging, and the
rows_expected/rows_captured manifest."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import bench_compare  # noqa: E402


def _payload(value, extra):
    return {"metric": "dist_matmul_16384_bf16_tflops_per_chip", "value": value,
            "unit": "TFLOPS/chip", "vs_baseline": None, "extra": extra}


class TestUnits:
    def test_flatten_recurses_and_skips_bools(self):
        rows = bench_compare.flatten(_payload(100.0, {
            "mfu_bf16": 0.8, "watchdog_timeout": True,
            "summa_vs_gspmd_cpu8dev": {"summa_over_gspmd": 0.7},
        }))
        assert rows["dist_matmul_16384_bf16_tflops_per_chip"] == 100.0
        assert rows["summa_vs_gspmd_cpu8dev.summa_over_gspmd"] == 0.7
        assert "watchdog_timeout" not in rows

    def test_direction(self):
        d = bench_compare.direction
        assert d("matmul_4096_bf16_tflops_per_chip") > 0
        assert d("lm_decode_b8_tok_per_s") > 0
        assert d("mfu_f32") > 0
        assert d("flash_attention_speedup") > 0  # "_s" substring must not win
        assert d("kmeans_kernel_speedup") > 0
        assert d("matmul_4096_dispatch_overhead_s") < 0
        assert d("qr_tsqr_1e6x256_f32_s") < 0
        assert d("summa_vs_gspmd_cpu8dev.summa_over_gspmd") < 0
        # bookkeeping rows are never flagged
        assert d("n_chips") == 0
        assert d("kmeans_rows") == 0
        assert d("bf16_peak_tflops_per_chip") == 0

    def test_wrapper_and_direct_forms_load(self, tmp_path):
        direct = tmp_path / "direct.json"
        direct.write_text(json.dumps(_payload(10.0, {})))
        wrapper = tmp_path / "wrapper.json"
        wrapper.write_text(json.dumps({"n": 5, "rc": 0, "tail": "…",
                                       "parsed": _payload(11.0, {})}))
        assert bench_compare.load(str(direct))["value"] == 10.0
        assert bench_compare.load(str(wrapper))["value"] == 11.0
        bogus = tmp_path / "bogus.json"
        bogus.write_text("{}")
        with pytest.raises(ValueError, match="metric"):
            bench_compare.load(str(bogus))


class TestEndToEnd:
    def _run(self, tmp_path, a, b, *flags):
        fa, fb = tmp_path / "a.json", tmp_path / "b.json"
        fa.write_text(json.dumps(a))
        fb.write_text(json.dumps(b))
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "bench_compare.py"),
             str(fa), str(fb), *flags],
            capture_output=True, text=True, timeout=120)

    def test_clean_pair_exits_zero(self, tmp_path):
        a = _payload(100.0, {"mfu_bf16": 0.80})
        b = _payload(98.0, {"mfu_bf16": 0.79})
        r = self._run(tmp_path, a, b)
        assert r.returncode == 0, r.stdout
        assert "no regressions" in r.stdout

    def test_regression_flagged_both_directions(self, tmp_path):
        a = _payload(100.0, {"step_wallclock_s": 1.0})
        b = _payload(80.0, {"step_wallclock_s": 1.5})  # ↓thr/chip AND ↑time
        r = self._run(tmp_path, a, b)
        assert r.returncode == 2
        assert r.stdout.count("REGRESSION") >= 2

    def test_threshold_flag(self, tmp_path):
        a = _payload(100.0, {})
        b = _payload(85.0, {})  # -15%: flagged at 10%, clean at 20%
        assert self._run(tmp_path, a, b).returncode == 2
        assert self._run(tmp_path, a, b, "--threshold", "0.20").returncode == 0

    def test_manifest_reported(self, tmp_path):
        a = _payload(100.0, {"rows_expected": ["headline", "flash_ab"],
                             "rows_captured": ["headline"],
                             "platform": "tpu", "watchdog_timeout": True})
        b = _payload(99.0, {})
        r = self._run(tmp_path, a, b)
        assert "1/2 expected rows captured" in r.stdout
        assert "MISSING: flash_ab" in r.stdout
        assert "WATCHDOG-CUT" in r.stdout

    def test_committed_round_payloads(self):
        """The real r4 artifacts load and compare (wrapper r03 vs manual
        r4b), and the comparator surfaces the f32 default-precision swing
        VERDICT r4 weak #2 is about (r4b vs r4d)."""
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "bench_compare.py"),
             os.path.join(REPO, "BENCH_r03.json"),
             os.path.join(REPO, "BENCH_r4b_manual.json")],
            capture_output=True, text=True, timeout=120)
        assert r.returncode in (0, 2)
        assert "dist_matmul_16384_bf16_tflops_per_chip" in r.stdout
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "bench_compare.py"),
             os.path.join(REPO, "BENCH_r4b_manual.json"),
             os.path.join(REPO, "BENCH_r4d_manual.json")],
            capture_output=True, text=True, timeout=120)
        assert r.returncode == 2
        assert "matmul_16384_f32_default_precision_tflops_per_chip" in r.stdout
