"""Lasso regression (reference: ``heat/regression/lasso.py``).

Coordinate descent with soft thresholding; all dots/means are distributed
through the array API exactly as in the reference (SURVEY §2.4) — and the
full sweep over features is one jitted ``fori_loop`` per iteration.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core import types
from ..core.base import BaseEstimator, RegressionMixin
from ..core.dndarray import DNDarray
from ..core.communication import Communication

__all__ = ["Lasso"]


class Lasso(RegressionMixin, BaseEstimator):
    """L1-regularized linear regression via cyclic coordinate descent.

    API mirrors the reference: ``lam`` (λ), ``max_iter``, ``tol``; fitted
    attrs ``coef_``, ``intercept_``, ``n_iter_``.
    """

    def __init__(self, lam: float = 0.1, max_iter: int = 100, tol: float = 1e-6):
        self.lam = lam
        self.max_iter = max_iter
        self.tol = tol
        self.__theta = None
        self.n_iter_ = None

    @property
    def coef_(self) -> Optional[DNDarray]:
        return None if self.__theta is None else self.__theta[1:]

    @property
    def intercept_(self) -> Optional[DNDarray]:
        return None if self.__theta is None else self.__theta[0]

    @property
    def theta(self):
        return self.__theta

    @staticmethod
    def soft_threshold(rho, lam):
        return jnp.sign(rho) * jnp.maximum(jnp.abs(rho) - lam, 0.0)

    def fit(self, x: DNDarray, y: DNDarray) -> "Lasso":
        if x.ndim != 2:
            raise ValueError("x needs to be 2-D (n_samples, n_features)")
        jX = x._jarray
        jy = y._jarray.reshape(-1)
        n, d = jX.shape
        # prepend intercept column
        A = jnp.concatenate([jnp.ones((n, 1), jX.dtype), jX], axis=1)
        m = d + 1
        lam_n = self.lam * n

        col_sq = jnp.sum(A * A, axis=0)

        @jax.jit
        def sweep(theta):
            def body(j, th):
                aj = A[:, j]
                resid = jy - A @ th + aj * th[j]
                rho = jnp.dot(aj, resid)
                new = jnp.where(
                    j == 0,
                    rho / jnp.maximum(col_sq[0], 1e-30),  # intercept: no penalty
                    Lasso.soft_threshold(rho, lam_n / 2.0) / jnp.maximum(col_sq[j], 1e-30),
                )
                return th.at[j].set(new)

            return jax.lax.fori_loop(0, m, body, theta)

        theta = jnp.zeros(m, jX.dtype)
        n_iter = 0
        for it in range(self.max_iter):
            new_theta = sweep(theta)
            diff = float(Communication.host_fetch(jnp.max(jnp.abs(new_theta - theta))))
            theta = new_theta
            n_iter = it + 1
            if diff < self.tol:
                break
        self.n_iter_ = n_iter
        th = x.comm.shard(theta.reshape(-1, 1), None)
        self.__theta = DNDarray(
            th, tuple(th.shape), types.canonical_heat_type(th.dtype), None, x.device, x.comm, True
        )
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        if self.__theta is None:
            raise RuntimeError("fit must be called before predict")
        jX = x._jarray
        th = self.__theta._jarray.reshape(-1)
        res = th[0] + jX @ th[1:]
        res = res.reshape(-1, 1)
        res = x.comm.shard(res, x.split)
        return DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), x.split, x.device, x.comm, True)
