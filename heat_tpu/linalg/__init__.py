"""Distributed linear algebra (reference: ``heat/core/linalg/``)."""

from .basics import *
from . import basics
from .qr import *
from . import qr as _qr_module
from .svdtools import *
from . import svdtools
from .solver import *
from . import solver
