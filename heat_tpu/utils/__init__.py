"""Utilities (reference: ``heat/utils/``)."""

from . import data
from . import faults
from . import health
from . import memledger
from . import monitor
from . import profiler
from . import telemetry
