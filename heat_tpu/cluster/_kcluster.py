"""Shared k-clustering skeleton (reference: ``heat/cluster/_kcluster.py``).

Init strategies and the E/M fit loop shell.  The per-iteration compute
(distances → assignment → masked aggregation) is one jitted XLA program; the
reference's two Allreduces per iteration (SURVEY §3.4) are implicit in the
sharded segment-sum.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import factories, types
from ..core.base import BaseEstimator, ClusteringMixin
from ..core.dndarray import DNDarray


class _KCluster(ClusteringMixin, BaseEstimator):
    """Base class for KMeans/KMedians/KMedoids."""

    def __init__(self, metric: Callable, n_clusters: int, init, max_iter: int, tol: float, random_state: Optional[int]):
        self.n_clusters = n_clusters
        self.init = init
        self.max_iter = max_iter
        self.tol = tol
        self.random_state = random_state
        self._metric = metric

        self._cluster_centers = None
        self._labels = None
        self._inertia = None
        self._n_iter = None

    @property
    def cluster_centers_(self) -> DNDarray:
        return self._cluster_centers

    @property
    def labels_(self) -> DNDarray:
        return self._labels

    @property
    def inertia_(self) -> float:
        return self._inertia

    @property
    def n_iter_(self) -> int:
        return self._n_iter

    @property
    def functional_value_(self) -> float:
        return self._inertia

    # ------------------------------------------------------------------ #
    def _initialize_cluster_centers(self, x: DNDarray, oversampling: float = 1.0, iter_multiplier: float = 1.0):
        """Center init: 'random', 'kmeans++' (distributed D² sampling), or
        a user-provided (k, d) DNDarray/array."""
        k = self.n_clusters
        jx = x._jarray
        n, d = x.shape
        key = jax.random.key(self.random_state if self.random_state is not None else 0)

        if isinstance(self.init, DNDarray) or isinstance(self.init, (np.ndarray, jnp.ndarray)):
            centers = self.init._jarray if isinstance(self.init, DNDarray) else jnp.asarray(self.init)
            if centers.shape != (k, d):
                raise ValueError(f"initial centers must have shape {(k, d)}, got {centers.shape}")
            self._cluster_centers = factories.array(centers, device=x.device, comm=x.comm)
            return

        if self.init == "random":
            idx = jax.random.choice(key, n, (k,), replace=False)
            centers = jx[idx]
        elif self.init in ("kmeans++", "probability_based"):
            # greedy D² sampling: draw several candidates ∝ D², keep the one
            # minimizing the resulting potential (the reference's Allreduce of
            # the D² mass is XLA's implicit psum over the sharded sample axis)
            n_trials = 2 + int(np.ceil(np.log2(max(k, 2))))

            def body(i, state):
                centers, d2, key = state
                key, sub = jax.random.split(key)
                probs = d2 / jnp.maximum(jnp.sum(d2), 1e-30)
                cand_idx = jax.random.choice(sub, n, (n_trials,), p=probs)
                cand = jx[cand_idx]  # (t, d)
                cd2 = jnp.sum((jx[:, None, :] - cand[None, :, :]) ** 2, axis=-1)  # (n, t)
                pots = jnp.sum(jnp.minimum(d2[:, None], cd2), axis=0)  # (t,)
                best = jnp.argmin(pots)
                nxt = cand[best]
                d2 = jnp.minimum(d2, cd2[:, best])
                return centers.at[i].set(nxt), d2, key

            key, sub = jax.random.split(key)
            first = jx[jax.random.randint(sub, (), 0, n)]
            centers0 = jnp.zeros((k, d), jx.dtype).at[0].set(first)
            d2_0 = jnp.sum((jx - first[None, :]) ** 2, axis=-1)
            centers, _, _ = jax.lax.fori_loop(1, k, body, (centers0, d2_0, key))
        elif self.init == "batchparallel":
            centers = jx[jax.random.choice(key, n, (k,), replace=False)]
        else:
            raise ValueError(f"Unknown init strategy {self.init!r}")
        centers = x.comm.shard(centers, None)
        self._cluster_centers = DNDarray(
            centers, (k, d), x.dtype, None, x.device, x.comm, True
        )

    # rows per E-step block: bounds the materialized (block, k) distance
    # tile so the fit scales to BASELINE's 1e8-row config without an n×k
    # buffer ever existing in HBM (the X matrix itself is the footprint)
    _ASSIGN_BLOCK = 1 << 20

    @staticmethod
    def _assign(jx, centers):
        """E-step: squared distances + argmin, fused on the MXU.

        For large n the rows are processed in fixed-size blocks read with
        ``dynamic_slice`` inside a ``fori_loop`` — X stays in its at-rest
        layout and only one (block, k) distance tile plus one (block, d) row
        tile exist at a time.  (A reshape/``lax.map`` formulation materializes
        a full lane-padded copy of X as an HLO temp — a 4× blowup for d=32
        that OOMs HBM at 2²⁵ rows; measured on v5e.)
        """
        cc = jnp.sum(centers * centers, axis=1)[None, :]

        def block_assign(xb):
            xx = jnp.sum(xb * xb, axis=1, keepdims=True)
            d2 = xx + cc - 2.0 * (xb @ centers.T)
            return jnp.argmin(d2, axis=1), jnp.min(jnp.maximum(d2, 0.0), axis=1)

        n = jx.shape[0]
        blk = _KCluster._ASSIGN_BLOCK
        if n <= blk:
            return block_assign(jx)
        # TRANSPOSED block loop: X at rest is {0,1}-laid-out (n, d), which IS
        # (d, n) row-major — jx.T is a free bitcast, and (d, blk) tiles have
        # their minor dim = blk, so nothing ever lane-pads (a (blk, d) slice
        # layout pads d→128 lanes: 4× HBM for d=32, measured OOM on v5e)
        xt = jx.T
        nblocks = -(-n // blk)

        def body(i, carry):
            labels, d2min = carry
            start = jnp.minimum(i * blk, n - blk)  # tail block overlaps; writes agree
            xb = jax.lax.dynamic_slice_in_dim(xt, start, blk, axis=1)  # (d, blk)
            xx = jnp.sum(xb * xb, axis=0)[None, :]
            d2 = cc.T + xx - 2.0 * (centers @ xb)  # (k, blk)
            lb = jnp.argmin(d2, axis=0)
            db = jnp.min(jnp.maximum(d2, 0.0), axis=0)
            labels = jax.lax.dynamic_update_slice(labels, lb, (start,))
            d2min = jax.lax.dynamic_update_slice(d2min, db, (start,))
            return labels, d2min

        labels0 = jnp.zeros((n,), dtype=jnp.int32)
        d2min0 = jnp.zeros((n,), dtype=jx.dtype)
        return jax.lax.fori_loop(0, nblocks, body, (labels0, d2min0))

    @staticmethod
    def _update(jx, labels, centers):
        raise NotImplementedError()

    @classmethod
    def _em_step(cls, jx, centers, use_kernel: bool = False):
        """One Lloyd iteration: new centers from current ones.  Default =
        assign then update (two passes over X); subclasses may fuse.
        ``use_kernel`` requests the Pallas E+M path where a subclass has
        one (base classes ignore it)."""
        labels, _ = cls._assign(jx, centers)
        return cls._update(jx, labels, centers)

    @classmethod
    def _fit_program(cls, use_kernel: bool = False):
        """The WHOLE Lloyd iteration as one compiled XLA program
        (lax.while_loop, SURVEY §3.4) — a single device dispatch per fit,
        no per-iteration host round-trips.  Cached per class so repeated
        fits (and new instances) skip retracing."""
        cache = cls.__dict__.get("_FIT_PROGRAM")
        if cache is None:
            cache = {}
            cls._FIT_PROGRAM = cache
        # the E/M block size is baked into the trace — key the cache on it
        prog = cache.get((_KCluster._ASSIGN_BLOCK, use_kernel))
        if prog is None:

            @jax.jit
            def prog(jx, centers0, max_iter, tol):
                def cond(state):
                    _, it, shift = state
                    return jnp.logical_and(it < max_iter, shift > tol)

                def body(state):
                    centers, it, _ = state
                    new = cls._em_step(jx, centers, use_kernel)
                    return new, it + 1, jnp.max(jnp.abs(new - centers))

                centers, n_iter, _ = jax.lax.while_loop(
                    cond, body, (centers0, jnp.asarray(0), jnp.asarray(jnp.inf, centers0.dtype))
                )
                labels, d2 = cls._assign(jx, centers)
                return centers, labels, jnp.sum(d2), n_iter

            cache[(_KCluster._ASSIGN_BLOCK, use_kernel)] = prog
        return prog

    def fit(self, x: DNDarray):
        """Lloyd iteration — one fused sharded XLA program per fit.

        Row-split inputs on a multi-device mesh take the shard_map path
        (per-shard blocked E+M + psum of the (k,d)/(k,) statistics — X never
        crosses chips); otherwise the global GSPMD program runs.
        """
        from ..core.sanitation import sanitize_in

        sanitize_in(x)
        self._initialize_cluster_centers(x)
        centers0 = self._cluster_centers._jarray
        n = x.shape[0]
        use_sharded = (
            getattr(self, "_supports_sharded_fit", False)
            and x.split == 0
            and x.comm.is_distributed()
        )
        use_kernel = bool(getattr(self, "_kernel_enabled", False))
        if use_sharded:
            prog = self._fit_program_sharded(x.comm, use_kernel)
            centers, labels_phys, inertia, n_iter = prog(
                x._masked(0),  # pads must be zero, not dead garbage
                centers0,
                jnp.asarray(n),
                jnp.asarray(self.max_iter),
                jnp.asarray(self.tol, centers0.dtype),
            )
            n_iter = int(n_iter)
            self._cluster_centers = DNDarray(
                x.comm.shard(centers, None), tuple(centers.shape), x.dtype, None,
                x.device, x.comm, True,
            )
            self._labels = DNDarray(
                labels_phys, (n,), types.canonical_heat_type(labels_phys.dtype),
                0, x.device, x.comm, True,
            )
            self._inertia = float(inertia)
            self._n_iter = n_iter
            return self

        jx = x._jarray
        if x.split is not None and x.comm.is_distributed():
            # global-path fits on a distributed non-row split: pallas_call
            # has no SPMD rule and would gather X — keep the jnp program
            use_kernel = False
        centers, labels, inertia, n_iter = self._fit_program(use_kernel)(
            jx, centers0, jnp.asarray(self.max_iter), jnp.asarray(self.tol, centers0.dtype)
        )
        n_iter = int(n_iter)

        self._cluster_centers = DNDarray(
            x.comm.shard(centers, None), tuple(centers.shape), x.dtype, None, x.device, x.comm, True
        )
        lab = x.comm.shard(labels, x.split)
        self._labels = DNDarray(
            lab, tuple(lab.shape), types.canonical_heat_type(lab.dtype), x.split, x.device, x.comm, True
        )
        self._inertia = float(inertia)
        self._n_iter = n_iter
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Nearest-center assignment for new data."""
        from ..core.sanitation import sanitize_in

        sanitize_in(x)
        use_kernel = getattr(self, "_kernel_enabled", False) and not (
            # pallas_call has no SPMD partitioning rule: on a distributed
            # split array it would gather X onto every device — the jnp
            # path stays GSPMD-partitioned
            x.split is not None and x.comm.is_distributed()
        )
        if use_kernel:
            from ..ops.kmeans_kernels import fused_assign

            labels, _ = fused_assign(x._jarray, self._cluster_centers._jarray)
        else:
            labels, _ = self._assign(x._jarray, self._cluster_centers._jarray)
        lab = x.comm.shard(labels, x.split)
        return DNDarray(
            lab, tuple(lab.shape), types.canonical_heat_type(lab.dtype), x.split, x.device, x.comm, True
        )
