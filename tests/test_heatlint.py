"""heatlint framework + rule tests (ISSUE 4 tentpole).

Every rule gets positive fixtures (known-bad snippet IS flagged) and
negative fixtures (the sanctioned idiom is NOT flagged), plus framework
tests for suppressions, the baseline workflow, and the CLI — and the
repo-wide gate itself: ``scripts/heatlint.py heat_tpu/`` must be clean
against the committed baseline.
"""

import importlib.util
import json
import os
import textwrap

import pytest

from heat_tpu.analysis import (
    LintContext,
    all_rules,
    lint_paths,
    load_baseline,
    split_by_baseline,
    write_baseline,
)
from heat_tpu.analysis.rules import (
    CollectiveAccountingRule,
    FederationJournaledMutationRule,
    HostSyncRule,
    MetadataMutationRule,
    NakedBlockingWaitRule,
    RankConditionalCollectiveRule,
    RawEntropyRule,
    SeqStampBypassRule,
    TraceIdentityRule,
    UnknownFaultSiteRule,
    UnledgeredDeviceBufferRule,
    UseAfterDonateRule,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "heatlint_cli", os.path.join(REPO, "scripts", "heatlint.py")
)
heatlint_cli = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(heatlint_cli)


def run_rule(rule, source, path="heat_tpu/somelib.py"):
    ctx = LintContext(path, textwrap.dedent(source))
    return list(rule.check(ctx))


# ---------------------------------------------------------------------- #
# HT101 — host sync in library code
# ---------------------------------------------------------------------- #
class TestHT101:
    def test_item_flagged(self):
        fs = run_rule(HostSyncRule(), """
            def f(x):
                return x.sum().item()
        """)
        assert [f.detail for f in fs] == ["item"]
        assert fs[0].rule == "HT101" and fs[0].qualname == "f"

    def test_device_get_flagged(self):
        fs = run_rule(HostSyncRule(), """
            import jax
            def f(x):
                return jax.device_get(x)
        """)
        assert [f.detail for f in fs] == ["device_get"]

    def test_float_cast_of_device_value_flagged(self):
        fs = run_rule(HostSyncRule(), """
            import jax.numpy as jnp
            def f(x):
                return float(jnp.sum(x._jarray))
        """)
        assert [f.detail for f in fs] == ["float-cast"]

    def test_np_asarray_of_device_value_flagged(self):
        fs = run_rule(HostSyncRule(), """
            import numpy as np
            def f(x):
                return np.asarray(x._jarray)
        """)
        assert [f.detail for f in fs] == ["np.asarray"]

    def test_np_asarray_of_host_data_not_flagged(self):
        fs = run_rule(HostSyncRule(), """
            import numpy as np
            def f(sections):
                return np.asarray(sections).ravel()
        """)
        assert fs == []

    def test_materialization_api_sanctioned(self):
        fs = run_rule(HostSyncRule(), """
            class DNDarray:
                def item(self):
                    return self._jarray.reshape(()).item()
                def numpy(self):
                    import jax
                    return jax.device_get(self._jarray)
                def __bool__(self):
                    return bool(self.item())
        """)
        assert fs == []

    def test_sanctioned_modules_skipped(self):
        src = """
            def render(x):
                return x.sum().item()
        """
        assert run_rule(HostSyncRule(), src, path="heat_tpu/core/printing.py") == []
        assert run_rule(HostSyncRule(), src, path="heat_tpu/core/io.py") == []
        assert len(run_rule(HostSyncRule(), src, path="heat_tpu/core/statistics.py")) == 1

    def test_inline_suppression(self):
        fs = run_rule(HostSyncRule(), """
            def f(x):
                return x.sum().item()  # heatlint: disable=HT101
        """)
        assert fs == []


# ---------------------------------------------------------------------- #
# HT102 — collective inside rank-conditional branch
# ---------------------------------------------------------------------- #
class TestHT102:
    def test_rank_conditional_collective_flagged(self):
        fs = run_rule(RankConditionalCollectiveRule(), """
            def f(comm, x):
                if comm.rank == 0:
                    comm.Bcast(x)
        """)
        assert [f.detail for f in fs] == ["Bcast"]
        assert fs[0].rule == "HT102"

    def test_process_index_conditional_flagged(self):
        fs = run_rule(RankConditionalCollectiveRule(), """
            import jax
            def f(comm, x):
                if jax.process_index() == 0:
                    return x.numpy()
        """)
        assert [f.detail for f in fs] == ["numpy"]

    def test_while_loop_flagged(self):
        fs = run_rule(RankConditionalCollectiveRule(), """
            def f(comm, x, n):
                while comm.rank < n:
                    comm.Allreduce(x)
        """)
        assert [f.detail for f in fs] == ["Allreduce"]

    def test_collective_in_both_arms_sanctioned(self):
        # the save_zarr idiom: every rank attends the collective fetch,
        # only the use of the result is rank-conditional
        fs = run_rule(RankConditionalCollectiveRule(), """
            def f(data, rank):
                if rank == 0:
                    arr = data.numpy()
                    arr.tofile("out")
                else:
                    data.numpy()  # the fetch is collective: every rank attends
        """)
        assert fs == []

    def test_local_work_in_rank_branch_not_flagged(self):
        fs = run_rule(RankConditionalCollectiveRule(), """
            import os
            def f(comm, path):
                if comm.rank == 0:
                    os.makedirs(path, exist_ok=True)
        """)
        assert fs == []

    def test_uniform_condition_not_flagged(self):
        fs = run_rule(RankConditionalCollectiveRule(), """
            def f(comm, x, n):
                if n > 2:
                    comm.Bcast(x)
        """)
        assert fs == []


# ---------------------------------------------------------------------- #
# HT103 — use after donate
# ---------------------------------------------------------------------- #
class TestHT103:
    def test_use_after_donate_kwarg_flagged(self):
        fs = run_rule(UseAfterDonateRule(), """
            import jax
            def f(x, sh):
                y = jax.device_put(x, sh, donate=True)
                return x + y
        """)
        assert [f.detail for f in fs] == ["x"]
        assert fs[0].rule == "HT103"

    def test_use_after_donate_argnums_flagged(self):
        fs = run_rule(UseAfterDonateRule(), """
            import jax
            def f(fn, a, b):
                prog = jax.jit(fn, donate_argnums=(0,))
                out = prog(a, b)
                return a, out
        """)
        assert [f.detail for f in fs] == ["a"]

    def test_rebind_clears_taint(self):
        fs = run_rule(UseAfterDonateRule(), """
            import jax
            def f(x, sh):
                x = jax.device_put(x, sh, donate=True)
                return x
        """)
        assert fs == []

    def test_donation_in_return_not_flagged(self):
        fs = run_rule(UseAfterDonateRule(), """
            import jax
            def f(x, sh, cond):
                if cond:
                    return jax.device_put(x, sh, donate=True)
                return x
        """)
        assert fs == []

    def test_exclusive_branches_not_flagged(self):
        # the Communication.resplit idiom: the donate attempt and its
        # TypeError fallback / the non-donate arm are mutually exclusive
        fs = run_rule(UseAfterDonateRule(), """
            import jax
            def f(self, array, sh, split, ok):
                if ok:
                    try:
                        out = jax.device_put(array, sh, donate=True)
                    except TypeError:
                        out = jax.device_put(array, sh)
                else:
                    out = self.shard(array, split)
                return out
        """)
        assert fs == []

    def test_second_positional_donate_position(self):
        fs = run_rule(UseAfterDonateRule(), """
            import jax
            def f(fn, a, b):
                prog = jax.jit(fn, donate_argnums=(1,))
                out = prog(a, b)
                return b
        """)
        assert [f.detail for f in fs] == ["b"]


# ---------------------------------------------------------------------- #
# HT104 — unaccounted public collective in communication.py
# ---------------------------------------------------------------------- #
class TestHT104:
    PATH = "heat_tpu/core/communication.py"

    def test_unaccounted_collective_flagged(self):
        fs = run_rule(CollectiveAccountingRule(), """
            from jax import lax
            class Communication:
                def Bcast(self, x, root=0):
                    return lax.psum(x, "x")
        """, path=self.PATH)
        assert [f.detail for f in fs] == ["Bcast"]
        assert fs[0].rule == "HT104"

    def test_accounted_collective_not_flagged(self):
        fs = run_rule(CollectiveAccountingRule(), """
            from jax import lax
            class Communication:
                def Bcast(self, x, root=0):
                    self._account("Bcast", x, 1.0)
                    return lax.psum(x, "x")
        """, path=self.PATH)
        assert fs == []

    def test_derived_collective_delegates(self):
        fs = run_rule(CollectiveAccountingRule(), """
            from jax import lax
            class Communication:
                def Allreduce(self, x, op="sum"):
                    self._account("Allreduce", x, 2.0)
                    return lax.psum(x, "x")
                def Reduce(self, x, root=0):
                    red = self.Allreduce(x)
                    return red
        """, path=self.PATH)
        assert fs == []

    def test_exempt_names(self):
        fs = run_rule(CollectiveAccountingRule(), """
            import jax
            class Communication:
                def Wait(self, x):
                    return jax.block_until_ready(x)
                def Barrier(self):
                    pass
        """, path=self.PATH)
        assert fs == []

    def test_other_files_not_in_scope(self):
        fs = run_rule(CollectiveAccountingRule(), """
            from jax import lax
            class Communication:
                def Bcast(self, x):
                    return lax.psum(x, "x")
        """, path="heat_tpu/parallel/ring.py")
        assert fs == []

    def test_tiled_entry_delegating_to_executor_not_flagged(self):
        # ISSUE 6: the tiled-resplit entry accounts PER TILE inside
        # core.redistribution.execute_plan (via _account_bytes) — delegating
        # to the executor IS accounting, not invisible traffic
        fs = run_rule(CollectiveAccountingRule(), """
            class Communication:
                def resplit_tiled(self, array, split, memory_budget=None):
                    from . import redistribution as _redist
                    plan = _redist.make_plan(self, array, split, memory_budget)
                    return _redist.execute_plan(self, array, plan)
        """, path=self.PATH)
        assert fs == []

    def test_tiled_entry_without_accounting_flagged(self):
        # a resplit* entry that neither accounts, delegates to an accounted
        # collective, nor routes through the executor IS flagged
        fs = run_rule(CollectiveAccountingRule(), """
            import jax
            class Communication:
                def resplit_tiled(self, array, split):
                    return jax.device_put(array, self.sharding(array.ndim, split))
        """, path=self.PATH)
        assert [f.detail for f in fs] == ["resplit_tiled"]

    def test_executor_delegation_scoped_to_resplit_entries(self):
        # the execute_plan exemption must NOT leak to other collectives: a
        # public collective delegating to something named execute_plan still
        # has invisible traffic unless it accounts its own
        fs = run_rule(CollectiveAccountingRule(), """
            class Communication:
                def Alltoallw(self, x):
                    from . import helper
                    return helper.execute_plan(self, x)
        """, path=self.PATH)
        assert [f.detail for f in fs] == ["Alltoallw"]

    def test_account_bytes_counts_as_accounting(self):
        fs = run_rule(CollectiveAccountingRule(), """
            from jax import lax
            class Communication:
                def Alltoall(self, x):
                    self._account_bytes("Alltoall", 128)
                    return lax.all_to_all(x, "x", 0, 0)
        """, path=self.PATH)
        assert fs == []

    def test_resplit_variant_delegating_to_resplit_not_flagged(self):
        # delegation among the resplit entries (resplit_tiled degenerates to
        # resplit for K=1 plans) carries the callee's accounting
        fs = run_rule(CollectiveAccountingRule(), """
            class Communication:
                def resplit(self, array, split):
                    self._account("resplit", array, 1.0)
                    return self.shard(array, split)
                def resplit_tiled(self, array, split):
                    return self.resplit(array, split)
        """, path=self.PATH)
        assert fs == []

    # -- ISSUE 16: the hierarchical/bucketed staging layer ------------- #
    COLL_PATH = "heat_tpu/core/collectives.py"

    def test_hierarchical_method_accounting_via_stage_accountant(self):
        # hierarchical_allreduce stages through _account_stages (which loops
        # comm._account_bytes) — delegation to the choke point IS accounting
        fs = run_rule(CollectiveAccountingRule(), """
            from jax import lax
            class Communication:
                def hierarchical_allreduce(self, x, op="sum"):
                    from . import collectives as _coll
                    _account_stages(self, _coll._Telescope(), 128, (0.5, 0.5), x=x)
                    return lax.psum(x, "x")
        """, path=self.PATH)
        assert fs == []

    def test_hierarchical_method_without_accounting_flagged(self):
        fs = run_rule(CollectiveAccountingRule(), """
            from jax import lax
            class Communication:
                def hierarchical_allreduce(self, x, op="sum"):
                    return lax.psum(x, "x")
        """, path=self.PATH)
        assert [f.detail for f in fs] == ["hierarchical_allreduce"]

    def test_staging_function_accounting_via_stage_accountant(self):
        fs = run_rule(CollectiveAccountingRule(), """
            def dispatch_bucket_averages(comm, leaves, plan, k, tele):
                _account_stages(comm, tele, 1024.0, (0.5, 0.5))
                return [leaves[j] for j in plan.buckets[k]]
        """, path=self.COLL_PATH)
        assert fs == []

    def test_staging_function_delegating_to_dispatch_half_not_flagged(self):
        # the lookahead pipelines account through their dispatch_* half
        fs = run_rule(CollectiveAccountingRule(), """
            def dispatch_bucket_averages(comm, leaves, plan, k, tele):
                comm._account_bytes("allreduce", tele.wire(128.0))
                return list(leaves)

            def bucketed_param_sync(comm, params, w, plan=None):
                avgs = dispatch_bucket_averages(comm, params, plan, 0, None)
                return avgs
        """, path=self.COLL_PATH)
        assert fs == []

    def test_staging_function_without_accounting_flagged(self):
        fs = run_rule(CollectiveAccountingRule(), """
            from jax import lax
            def bucketed_param_sync(comm, params, w):
                return lax.psum(params, "dcn")
        """, path=self.COLL_PATH)
        assert [f.detail for f in fs] == ["bucketed_param_sync"]

    def test_staging_helpers_not_in_scope(self):
        # underscore-private helpers (stage math, traced bodies) are not
        # staging entries — the traced body deliberately never accounts
        fs = run_rule(CollectiveAccountingRule(), """
            from jax import lax
            def _hierarchical_body(x, axis, p, d):
                return lax.psum(x, axis)
            def consume_bucket_averages(comm, leaves, avgs, plan, k, w):
                return leaves
        """, path=self.COLL_PATH)
        assert fs == []

    def test_repo_collectives_is_fully_accounted(self):
        # the live invariant: the real collectives.py has NO findings
        fs = lint_paths(
            [os.path.join(REPO, "heat_tpu", "core", "collectives.py")],
            select=["HT104"],
        )
        assert fs == []

    def test_repo_communication_is_fully_accounted(self):
        # the live invariant: the real communication.py has NO findings
        fs = lint_paths(
            [os.path.join(REPO, "heat_tpu", "core", "communication.py")],
            select=["HT104"],
        )
        assert fs == []


# ---------------------------------------------------------------------- #
# HT105 — raw process entropy
# ---------------------------------------------------------------------- #
class TestHT105:
    def test_np_random_flagged(self):
        fs = run_rule(RawEntropyRule(), """
            import numpy as np
            def f(n):
                return np.random.randint(0, n)
        """)
        assert [f.detail for f in fs] == ["np.random.randint"]
        assert fs[0].rule == "HT105"

    def test_stdlib_random_flagged(self):
        fs = run_rule(RawEntropyRule(), """
            import random
            def f():
                return random.random()
        """)
        assert [f.detail for f in fs] == ["random.random"]

    def test_os_urandom_flagged(self):
        fs = run_rule(RawEntropyRule(), """
            import os
            def f():
                return os.urandom(8)
        """)
        assert [f.detail for f in fs] == ["os.urandom"]

    def test_ht_random_module_sanctioned(self):
        fs = run_rule(RawEntropyRule(), """
            import numpy as np
            def seed(s=None):
                if s is None:
                    s = int(np.random.SeedSequence().entropy % (2**63))
                return s
        """, path="heat_tpu/core/random.py")
        assert fs == []

    def test_jax_random_not_flagged(self):
        fs = run_rule(RawEntropyRule(), """
            import jax
            def f(key, shape):
                return jax.random.normal(key, shape)
        """)
        assert fs == []

    def test_heat_own_random_module_not_confused_with_stdlib(self):
        # `from . import random` is heat's broadcast-state module, not the
        # stdlib: calls through it must NOT be flagged
        fs = run_rule(RawEntropyRule(), """
            from . import random
            def f(n):
                return random.randn(n)
        """)
        assert fs == []


# ---------------------------------------------------------------------- #
# HT106 — metadata mutation
# ---------------------------------------------------------------------- #
class TestHT106:
    def test_mangled_write_flagged(self):
        fs = run_rule(MetadataMutationRule(), """
            def f(x):
                x._DNDarray__split = 1
        """)
        assert [f.detail for f in fs] == ["_DNDarray__split"]
        assert fs[0].rule == "HT106"

    def test_unmangled_write_outside_class_flagged(self):
        fs = run_rule(MetadataMutationRule(), """
            def f(x, shape):
                x.__gshape = shape
        """)
        assert [f.detail for f in fs] == ["__gshape"]

    def test_foreign_class_own_private_not_flagged(self):
        # inside a class body the name mangles to the ENCLOSING class's
        # private (DCSR_matrix.__gshape), which is legal
        fs = run_rule(MetadataMutationRule(), """
            class DCSR_matrix:
                def __init__(self, shape):
                    self.__gshape = shape
        """)
        assert fs == []

    def test_dndarray_module_sanctioned(self):
        fs = run_rule(MetadataMutationRule(), """
            def f(x):
                x._DNDarray__split = 1
        """, path="heat_tpu/core/dndarray.py")
        assert fs == []

    def test_jarray_setter_not_flagged(self):
        fs = run_rule(MetadataMutationRule(), """
            def f(out, result):
                out._jarray = result
        """)
        assert fs == []


# ---------------------------------------------------------------------- #
# HT107 — naked blocking collective wait bypassing comm.deadline
# ---------------------------------------------------------------------- #
class TestHT107:
    def test_naked_barrier_flagged(self):
        fs = run_rule(NakedBlockingWaitRule(), """
            def f(comm):
                comm.Barrier()
        """)
        assert [f.detail for f in fs] == ["Barrier"]
        assert fs[0].rule == "HT107"

    def test_naked_wait_and_block_until_ready_flagged(self):
        fs = run_rule(NakedBlockingWaitRule(), """
            import jax
            def f(comm, x):
                comm.Wait(x)
                jax.block_until_ready(x)
        """)
        assert sorted(f.detail for f in fs) == ["Wait", "block_until_ready"]

    def test_sync_global_devices_flagged(self):
        fs = run_rule(NakedBlockingWaitRule(), """
            from jax.experimental import multihost_utils
            def f():
                multihost_utils.sync_global_devices("tag")
        """)
        assert [f.detail for f in fs] == ["sync_global_devices"]

    def test_under_deadline_not_flagged(self):
        fs = run_rule(NakedBlockingWaitRule(), """
            def f(comm, x):
                with comm.deadline(30.0):
                    comm.Wait(x)
                    comm.Barrier()
        """)
        assert fs == []

    def test_health_deadline_context_not_flagged(self):
        fs = run_rule(NakedBlockingWaitRule(), """
            from heat_tpu.utils import health
            def f(comm, x):
                with health.deadline(5.0) as dl:
                    comm.Wait(x)
        """)
        assert fs == []

    def test_wrapper_modules_sanctioned(self):
        src = """
            import jax
            def Wait(x):
                return jax.block_until_ready(x)
        """
        assert run_rule(
            NakedBlockingWaitRule(), src, path="heat_tpu/core/communication.py"
        ) == []
        assert run_rule(
            NakedBlockingWaitRule(), src, path="heat_tpu/utils/health.py"
        ) == []

    def test_foreign_barrier_api_not_flagged(self):
        # threading.Barrier(3) etc: Barrier WITH arguments is not the fence
        fs = run_rule(NakedBlockingWaitRule(), """
            import threading
            def f():
                b = threading.Barrier(3)
        """)
        assert fs == []

    def test_suppression_works(self):
        fs = run_rule(NakedBlockingWaitRule(), """
            def f(comm):
                comm.Barrier()  # heatlint: disable=HT107 teardown fence
        """)
        assert fs == []


# ---------------------------------------------------------------------- #
# HT108 — collective staging bypassing the seq-stamp choke point
# ---------------------------------------------------------------------- #
class TestHT108:
    def test_direct_execute_plan_flagged(self):
        fs = run_rule(SeqStampBypassRule(), """
            from heat_tpu.core import redistribution
            def f(comm, array, plan):
                return redistribution.execute_plan(comm, array, plan)
        """)
        assert [f.detail for f in fs] == ["execute_plan"]
        assert fs[0].rule == "HT108"

    def test_resharding_device_put_flagged(self):
        fs = run_rule(SeqStampBypassRule(), """
            import jax
            def f(comm, x):
                return jax.device_put(x._jarray, comm.sharding(x.ndim, 1))
        """)
        assert [f.detail for f in fs] == ["device_put"]

    def test_named_sharding_target_flagged(self):
        fs = run_rule(SeqStampBypassRule(), """
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            def f(mesh, x):
                return jax.device_put(x._parray, NamedSharding(mesh, P("dcn")))
        """)
        assert [f.detail for f in fs] == ["device_put"]

    def test_host_upload_not_flagged(self):
        # device_put of HOST data onto a sharding is placement (an upload
        # scatter), not collective traffic staged around the choke point
        fs = run_rule(SeqStampBypassRule(), """
            import jax
            import jax.numpy as jnp
            def f(comm, host, new, sh):
                a = jax.device_put(host, comm.sharding(2, 0))
                b = jax.device_put(jnp.asarray(new), sh)
                return a, b
        """)
        assert fs == []

    def test_single_device_put_not_flagged(self):
        fs = run_rule(SeqStampBypassRule(), """
            import jax
            def f(x, d):
                return jax.device_put(x._jarray, d)
        """)
        assert fs == []

    def test_accounting_layer_sanctioned(self):
        src = """
            import jax
            def resplit_tiled(self, array, split, plan):
                from . import redistribution
                return redistribution.execute_plan(self, array, plan)
        """
        assert run_rule(
            SeqStampBypassRule(), src, path="heat_tpu/core/communication.py"
        ) == []
        assert run_rule(
            SeqStampBypassRule(), src, path="heat_tpu/core/redistribution.py"
        ) == []

    def test_suppression_works(self):
        fs = run_rule(SeqStampBypassRule(), """
            from heat_tpu.core import redistribution
            def f(comm, array, plan):
                return redistribution.execute_plan(comm, array, plan)  # heatlint: disable=HT108 bench harness
        """)
        assert fs == []

    def test_collectives_staging_layer_sanctioned(self):
        # ISSUE 16: the hierarchical/bucketed staging layer routes every
        # stage through _account_stages → comm._account_bytes (HT104 proves
        # that), so its sharded program staging is the choke point, not a
        # bypass of it
        fs = run_rule(SeqStampBypassRule(), """
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            def dispatch_bucket_averages(comm, leaves, mesh):
                return jax.device_put(leaves[0]._jarray, NamedSharding(mesh, P("dcn")))
        """, path="heat_tpu/core/collectives.py")
        assert fs == []

    def test_same_staging_outside_sanctioned_layer_still_flagged(self):
        fs = run_rule(SeqStampBypassRule(), """
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            def dispatch_bucket_averages(comm, leaves, mesh):
                return jax.device_put(leaves[0]._jarray, NamedSharding(mesh, P("dcn")))
        """, path="heat_tpu/optim/helper.py")
        assert [f.detail for f in fs] == ["device_put"]


# ---------------------------------------------------------------------- #
# HT111 — device buffers minted around the memory-ledger choke points
# ---------------------------------------------------------------------- #
class TestHT111:
    def test_raw_make_array_from_callback_flagged(self):
        fs = run_rule(UnledgeredDeviceBufferRule(), """
            import jax
            def f(host, sh):
                return jax.make_array_from_callback(host.shape, sh, lambda i: host[i])
        """)
        assert [f.detail for f in fs] == ["make_array_from_callback"]
        assert fs[0].rule == "HT111"

    def test_sharded_device_put_flagged(self):
        fs = run_rule(UnledgeredDeviceBufferRule(), """
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            def f(mesh, p):
                return jax.device_put(p, NamedSharding(mesh, P("dcn")))
        """)
        assert [f.detail for f in fs] == ["device_put"]

    def test_comm_sharding_target_flagged(self):
        fs = run_rule(UnledgeredDeviceBufferRule(), """
            import jax
            def f(comm, host):
                return jax.device_put(host, comm.sharding(2, 0))
        """)
        assert [f.detail for f in fs] == ["device_put"]

    def test_device_kwarg_spelling_flagged(self):
        # device_put(x, device=NamedSharding(...)) mints the same buffer
        # as the positional form — the kwarg spelling must not slip through
        fs = run_rule(UnledgeredDeviceBufferRule(), """
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            def f(mesh, p):
                return jax.device_put(p, device=NamedSharding(mesh, P("dcn")))
        """)
        assert [f.detail for f in fs] == ["device_put"]

    def test_plain_device_placement_not_flagged(self):
        # device_put onto a DEVICE (the hosted-complex transport commit)
        # is placement, not a mesh buffer the ledger needs to see
        fs = run_rule(UnledgeredDeviceBufferRule(), """
            import jax
            def f(arr, dev):
                return jax.device_put(arr, dev)
        """)
        assert fs == []

    def test_registrar_function_exempt(self):
        # a function that registers its result with the ledger IS a
        # registration choke point (the DASO.init shape)
        fs = run_rule(UnledgeredDeviceBufferRule(), """
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            from heat_tpu.utils import memledger
            def f(mesh, p):
                placed = jax.device_put(p, NamedSharding(mesh, P("dcn")))
                memledger.register(placed, op="init", category="param")
                return placed
        """)
        assert fs == []

    def test_registration_layer_sanctioned(self):
        src = """
            import jax
            def _finalize(host, sh):
                return jax.make_array_from_callback(host.shape, sh, lambda i: host[i])
        """
        for path in (
            "heat_tpu/core/factories.py",
            "heat_tpu/core/communication.py",
            "heat_tpu/core/io.py",
        ):
            assert run_rule(UnledgeredDeviceBufferRule(), src, path=path) == []

    def test_suppression_works(self):
        fs = run_rule(UnledgeredDeviceBufferRule(), """
            import jax
            def f(host, sh):
                return jax.make_array_from_callback(host.shape, sh, lambda i: host[i])  # heatlint: disable=HT111 ingest shim
        """)
        assert fs == []


# ---------------------------------------------------------------------- #
# HT112 — federation mutations outside the journaled append path
# ---------------------------------------------------------------------- #
FED_PATH = "heat_tpu/parallel/federation.py"


class TestHT112:
    def test_foreign_scheduler_private_mutation_flagged(self):
        # reaching into another scheduler's _queue bypasses ITS journal —
        # flagged even from a function that journals federation-side
        fs = run_rule(FederationJournaledMutationRule(), """
            class Federation:
                def steal(self, sched, job):
                    self.journal.append({"type": "requeue"})
                    sched._queue.append(job)
        """, path=FED_PATH)
        assert [f.detail for f in fs] == ["_queue.append"]
        assert fs[0].rule == "HT112"

    def test_unjournaled_self_queue_mutation_flagged(self):
        fs = run_rule(FederationJournaledMutationRule(), """
            class Federation:
                def fast_path(self, job):
                    self._queue.append(job)
        """, path=FED_PATH)
        assert [f.detail for f in fs] == ["self._queue.append"]

    def test_unjournaled_subscript_store_flagged(self):
        fs = run_rule(FederationJournaledMutationRule(), """
            class Federation:
                def stash(self, job):
                    self._jobs[job.job_id] = job
        """, path=FED_PATH)
        assert [f.detail for f in fs] == ["self._jobs ="]

    def test_unjournaled_state_write_flagged(self):
        fs = run_rule(FederationJournaledMutationRule(), """
            def mark_failed(job):
                job.state = "failed"
        """, path=FED_PATH)
        assert [f.detail for f in fs] == ["state ="]

    def test_journaled_function_not_flagged(self):
        # the sanctioned shape: append the record FIRST, then mutate —
        # submit/_shed/_steal/_transition in federation.py all look like this
        fs = run_rule(FederationJournaledMutationRule(), """
            class Federation:
                def submit(self, job):
                    self.journal.append({"type": "submitted"})
                    self._jobs[job.job_id] = job
                    self._queue.append(job)
                    job.state = "submitted"
        """, path=FED_PATH)
        assert fs == []

    def test_init_constructing_fresh_state_not_flagged(self):
        fs = run_rule(FederationJournaledMutationRule(), """
            class Federation:
                def __init__(self):
                    self._jobs = {}
                    self._queue = []
        """, path=FED_PATH)
        assert fs == []

    def test_non_federation_module_not_flagged(self):
        # the rule scopes to federation code; the scheduler mutating its
        # OWN privates is governed by its journal-first convention, not HT112
        fs = run_rule(FederationJournaledMutationRule(), """
            def steal(sched, job):
                sched._queue.append(job)
        """, path="heat_tpu/parallel/scheduler.py")
        assert fs == []

    def test_suppression_works(self):
        fs = run_rule(FederationJournaledMutationRule(), """
            class Federation:
                def steal(self, sched, job):
                    sched._queue.append(job)  # heatlint: disable=HT112 recovery shim
        """, path=FED_PATH)
        assert fs == []

    def test_real_federation_module_clean(self):
        # the shipped federation layer must satisfy its own contract
        src = open(os.path.join(REPO, "heat_tpu", "parallel", "federation.py")).read()
        ctx = LintContext("heat_tpu/parallel/federation.py", src)
        assert list(FederationJournaledMutationRule().check(ctx)) == []


# ---------------------------------------------------------------------- #
# HT113 — fault-site literals must be catalog members
# ---------------------------------------------------------------------- #
class TestHT113:
    def test_misspelled_fire_site_flagged(self):
        fs = run_rule(UnknownFaultSiteRule(), """
            from heat_tpu.utils import faults
            def save(path):
                faults.fire("io.wrte", path=path)
        """)
        assert [f.detail for f in fs] == ["fire('io.wrte')"]
        assert fs[0].rule == "HT113"

    def test_unregistered_inject_site_flagged(self):
        fs = run_rule(UnknownFaultSiteRule(), """
            from heat_tpu.utils.faults import inject
            def test_x():
                with inject("bogus.site", fail=1):
                    pass
        """)
        assert [f.detail for f in fs] == ["inject('bogus.site')"]

    def test_trip_count_and_faultspec_literals_checked(self):
        fs = run_rule(UnknownFaultSiteRule(), """
            from heat_tpu.utils import faults
            def audit():
                spec = faults.FaultSpec("io.wrte", fail=1)
                return faults.trip_count("bogus.site"), spec
        """)
        assert sorted(f.detail for f in fs) == [
            "FaultSpec('io.wrte')", "trip_count('bogus.site')",
        ]

    def test_catalog_members_not_flagged(self):
        fs = run_rule(UnknownFaultSiteRule(), """
            from heat_tpu.utils import faults
            def save(path):
                faults.fire("io.write", path=path)
                with faults.inject("sched.dispatch", fail=1):
                    pass
                return faults.trip_count("mem.alloc")
        """)
        assert fs == []

    def test_variable_site_out_of_scope(self):
        # a variable site is someone's abstraction — only literals are
        # lexically checkable
        fs = run_rule(UnknownFaultSiteRule(), """
            from heat_tpu.utils import faults
            def fire_all(sites):
                for site in sites:
                    faults.fire(site)
        """)
        assert fs == []

    def test_call_with_retries_pseudo_site_exempt(self):
        # call_with_retries' site parameter names retry COUNTERS, not
        # armed fault sites — the chaos harness uses pseudo-sites there
        fs = run_rule(UnknownFaultSiteRule(), """
            from heat_tpu.utils import faults
            def submit(fn):
                return faults.call_with_retries(fn, "chaos.submit", retries=2)
        """)
        assert fs == []

    def test_suppression_works(self):
        fs = run_rule(UnknownFaultSiteRule(), """
            from heat_tpu.utils import faults
            def probe():
                faults.fire("io.wrte")  # heatlint: disable=HT113 negative fixture
        """)
        assert fs == []


# ---------------------------------------------------------------------- #
# HT109 — trace identity owned by the tracing choke points
# ---------------------------------------------------------------------- #
class TestHT109:
    def test_manual_trace_id_subscript_write_flagged(self):
        fs = run_rule(TraceIdentityRule(), """
            def f(attrs, job):
                attrs["trace_id"] = job.job_id + "-trace"
                return attrs
        """)
        assert [f.detail for f in fs] == ["trace_id"]
        assert fs[0].rule == "HT109"

    def test_parent_and_span_id_writes_flagged(self):
        fs = run_rule(TraceIdentityRule(), """
            def f(rec):
                rec["span_id"] = "s1"
                rec["parent_id"] = "s0"
        """)
        assert sorted(f.detail for f in fs) == ["parent_id", "span_id"]

    def test_trace_kwarg_smuggled_into_span_flagged(self):
        fs = run_rule(TraceIdentityRule(), """
            from heat_tpu.utils import telemetry
            def f(tid):
                with telemetry.span("work", trace_id=tid):
                    pass
        """)
        assert [f.detail for f in fs] == ["span:trace_id"]

    def test_record_event_trace_kwarg_flagged(self):
        fs = run_rule(TraceIdentityRule(), """
            from heat_tpu.utils import telemetry
            def f(tid):
                telemetry.record_event("e", 0.1, trace_id=tid)
        """)
        assert [f.detail for f in fs] == ["record_event:trace_id"]

    def test_direct_contextvar_set_flagged(self):
        fs = run_rule(TraceIdentityRule(), """
            from heat_tpu.utils.telemetry import _TRACE
            def f(tid):
                _TRACE.set((tid, None))
        """)
        assert len(fs) == 1 and "_TRACE" in fs[0].detail

    def test_tracing_helper_is_the_sanctioned_idiom(self):
        fs = run_rule(TraceIdentityRule(), """
            from heat_tpu.utils import telemetry
            def f(tid):
                with telemetry.tracing(trace_id=tid):
                    with telemetry.span("work"):
                        pass
        """)
        assert fs == []

    def test_reading_trace_identity_not_flagged(self):
        fs = run_rule(TraceIdentityRule(), """
            def f(attrs):
                tid = attrs.get("trace_id")
                other = {"unrelated": 1}
                other["tid"] = tid  # a foreign key name is not the triple
                return tid
        """)
        assert fs == []

    def test_owner_modules_sanctioned(self):
        src = """
            def submit(job, attrs):
                attrs["trace_id"] = "abc123"
        """
        assert run_rule(
            TraceIdentityRule(), src, path="heat_tpu/utils/telemetry.py"
        ) == []
        assert run_rule(
            TraceIdentityRule(), src, path="heat_tpu/parallel/scheduler.py"
        ) == []

    def test_suppression_works(self):
        fs = run_rule(TraceIdentityRule(), """
            def f(attrs):
                attrs["trace_id"] = "x"  # heatlint: disable=HT109 migration shim
        """)
        assert fs == []


# ---------------------------------------------------------------------- #
# framework: suppressions, baseline, discovery, CLI
# ---------------------------------------------------------------------- #
class TestFramework:
    BAD = "def f(x):\n    return x.sum().item()\n"

    def test_file_level_suppression(self):
        src = "# heatlint: disable-file=HT101\n" + self.BAD
        ctx = LintContext("heat_tpu/lib.py", src)
        assert list(HostSyncRule().check(ctx)) == []

    def test_suppression_with_trailing_reason(self):
        # a free-text reason after the code must not corrupt the code token
        src = "def f(x):\n    return x.sum().item()  # heatlint: disable=HT101 tolerated debug path\n"
        ctx = LintContext("heat_tpu/lib.py", src)
        assert list(HostSyncRule().check(ctx)) == []

    def test_multi_code_suppression_with_reason(self):
        src = "def f(x):\n    return x.sum().item()  # heatlint: disable=HT103, HT101 both fine here\n"
        ctx = LintContext("heat_tpu/lib.py", src)
        assert list(HostSyncRule().check(ctx)) == []

    def test_docstring_mentioning_syntax_does_not_suppress(self):
        # only REAL comments suppress — a docstring documenting the syntax
        # (like the framework's own module docstring) must not disable rules
        src = (
            '"""Docs: use ``# heatlint: disable-file=HT101`` for file scope\n'
            'or ``# heatlint: disable=HT101`` on a line."""\n'
            "def f(x):\n"
            "    return x.sum().item()\n"
        )
        ctx = LintContext("heat_tpu/lib.py", src)
        assert [f.detail for f in HostSyncRule().check(ctx)] == ["item"]

    def test_all_rules_registered(self):
        codes = [r.code for r in all_rules()]
        assert codes == [
            "HT101", "HT102", "HT103", "HT104", "HT105", "HT106", "HT107",
            "HT108", "HT109", "HT110", "HT111", "HT112", "HT113", "HT201",
            "HT202", "HT203", "HT204", "HT301", "HT302", "HT303", "HT304",
        ]

    def test_select_unknown_rule_raises(self):
        with pytest.raises(ValueError, match="unknown rule"):
            all_rules(select=["HT999"])

    def test_lint_paths_and_syntax_error(self, tmp_path):
        (tmp_path / "ok.py").write_text(self.BAD)
        (tmp_path / "broken.py").write_text("def f(:\n")
        fs = lint_paths([str(tmp_path)])
        rules = sorted({f.rule for f in fs})
        assert rules == ["HT000", "HT101"]

    def test_baseline_roundtrip_and_counts(self, tmp_path):
        src_dir = tmp_path / "pkg"
        src_dir.mkdir()
        (src_dir / "lib.py").write_text(self.BAD)
        findings = lint_paths([str(src_dir)])
        assert len(findings) == 1
        bl_path = str(tmp_path / "baseline.json")
        write_baseline(bl_path, findings)
        baseline = load_baseline(bl_path)
        new, old = split_by_baseline(findings, baseline)
        assert new == [] and len(old) == 1
        # a SECOND identical finding in the same function exceeds the
        # baselined count and is reported as new
        (src_dir / "lib.py").write_text(
            "def f(x):\n    a = x.sum().item()\n    return x.max().item() + a\n"
        )
        findings2 = lint_paths([str(src_dir)])
        assert len(findings2) == 2
        new2, old2 = split_by_baseline(findings2, baseline)
        assert len(new2) == 1 and len(old2) == 1

    def test_baseline_survives_line_drift(self, tmp_path):
        src_dir = tmp_path / "pkg"
        src_dir.mkdir()
        (src_dir / "lib.py").write_text(self.BAD)
        bl_path = str(tmp_path / "baseline.json")
        write_baseline(bl_path, lint_paths([str(src_dir)]))
        # unrelated edit shifts every line; the fingerprint still matches
        (src_dir / "lib.py").write_text("# comment\n\n\n" + self.BAD)
        new, old = split_by_baseline(lint_paths([str(src_dir)]), load_baseline(bl_path))
        assert new == [] and len(old) == 1

    def test_cli_exit_codes_and_json(self, tmp_path, capsys):
        src_dir = tmp_path / "pkg"
        src_dir.mkdir()
        (src_dir / "lib.py").write_text(self.BAD)
        bl = str(tmp_path / "bl.json")
        out_json = str(tmp_path / "out.json")
        # no baseline yet: the finding is new -> exit 1
        assert heatlint_cli.main([str(src_dir), "--baseline", bl, "--json", out_json]) == 1
        data = json.loads(open(out_json).read())
        assert data["counts"]["new"] == 1 and data["new"][0]["rule"] == "HT101"
        # write the baseline -> gate goes green
        assert heatlint_cli.main([str(src_dir), "--baseline", bl, "--write-baseline"]) == 0
        assert heatlint_cli.main([str(src_dir), "--baseline", bl]) == 0
        # --no-baseline reports it as new again
        assert heatlint_cli.main([str(src_dir), "--baseline", bl, "--no-baseline"]) == 1
        capsys.readouterr()

    def test_write_baseline_preserves_out_of_scope_entries(self, tmp_path, capsys):
        # grandfathered findings in files OUTSIDE the linted paths survive a
        # narrow --write-baseline run instead of being silently dropped
        a = tmp_path / "a"
        b = tmp_path / "b"
        a.mkdir(); b.mkdir()
        (a / "liba.py").write_text(self.BAD)
        (b / "libb.py").write_text(self.BAD)
        bl = str(tmp_path / "bl.json")
        assert heatlint_cli.main([str(a), str(b), "--baseline", bl, "--write-baseline"]) == 0
        assert heatlint_cli.main([str(a), str(b), "--baseline", bl]) == 0
        # re-write from only `a`: b's entry must be preserved
        assert heatlint_cli.main([str(a), "--baseline", bl, "--write-baseline"]) == 0
        assert heatlint_cli.main([str(a), str(b), "--baseline", bl]) == 0
        # fixing a's finding then re-writing from `a` drops a's entry only
        (a / "liba.py").write_text("def f(x):\n    return x\n")
        assert heatlint_cli.main([str(a), "--baseline", bl, "--write-baseline"]) == 0
        baseline = load_baseline(bl)
        assert len(baseline) == 1 and any("libb.py" in fp for fp in baseline)
        capsys.readouterr()

    def test_overlapping_paths_lint_once(self, tmp_path, capsys):
        # `heatlint pkg/ pkg/sub pkg/sub/lib.py` must not double-count
        # findings past the baseline's per-fingerprint budget
        sub = tmp_path / "pkg" / "sub"
        sub.mkdir(parents=True)
        (sub / "lib.py").write_text(self.BAD)
        fs = lint_paths([str(tmp_path / "pkg"), str(sub), str(sub / "lib.py")])
        assert len(fs) == 1
        bl = str(tmp_path / "bl.json")
        assert heatlint_cli.main([str(tmp_path / "pkg"), "--baseline", bl, "--write-baseline"]) == 0
        assert heatlint_cli.main(
            [str(tmp_path / "pkg"), str(sub), "--baseline", bl]
        ) == 0
        capsys.readouterr()

    def test_write_baseline_refuses_select(self, tmp_path, capsys):
        src_dir = tmp_path / "pkg"
        src_dir.mkdir()
        (src_dir / "lib.py").write_text(self.BAD)
        bl = str(tmp_path / "bl.json")
        rc = heatlint_cli.main(
            [str(src_dir), "--baseline", bl, "--select", "HT101", "--write-baseline"]
        )
        assert rc == 2
        assert not os.path.exists(bl)
        capsys.readouterr()

    def test_cli_list_rules(self, capsys):
        assert heatlint_cli.main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for code in ("HT101", "HT102", "HT103", "HT104", "HT105", "HT106"):
            assert code in out


# ---------------------------------------------------------------------- #
# the repo gate itself
# ---------------------------------------------------------------------- #
class TestRepoGate:
    def test_repo_clean_against_committed_baseline(self, capsys):
        """The acceptance criterion: scripts/heatlint.py heat_tpu/ exits 0."""
        rc = heatlint_cli.main([os.path.join(REPO, "heat_tpu")])
        capsys.readouterr()
        assert rc == 0

    def test_svdtools_host_sync_is_fixed(self):
        """ISSUE 4 satellite: the `.item()` at linalg/svdtools.py:74 is gone —
        HT101 finds nothing in svdtools (and the baseline carries no
        grandfathered entry for it either)."""
        fs = lint_paths(
            [os.path.join(REPO, "heat_tpu", "linalg", "svdtools.py")], select=["HT101"]
        )
        assert fs == []
        baseline = load_baseline(os.path.join(REPO, ".heatlint-baseline.json"))
        assert not any("svdtools" in fp for fp in baseline)

    def test_committed_baseline_loads(self):
        baseline = load_baseline(os.path.join(REPO, ".heatlint-baseline.json"))
        assert len(baseline) > 0
