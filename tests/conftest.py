"""Test bootstrap: run everything on a virtual 8-device CPU mesh.

The reference runs its suite under ``mpirun -n N`` for several N; the
TPU-native analogue (SURVEY §4) is a multi-device CPU mesh in ONE process via
``--xla_force_host_platform_device_count`` — same code paths as a real pod,
only the transport differs.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the suite is compile-bound on the 1-core
# CI host (measured 54 s -> 31 s for test_linalg.py on a warm cache), and the
# CI matrix re-runs the same programs across device-count/python lanes.
# Cache entries key on topology + HLO, so lanes coexist in one directory.
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get("HEAT_TPU_JAX_CACHE", "/tmp/heat_tpu_jax_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import numpy as np
import pytest


@pytest.fixture
def ht():
    import heat_tpu

    return heat_tpu


# split sweep used across op tests (the reference's distributed-coverage trick)
SPLITS_1D = [None, 0]
SPLITS_2D = [None, 0, 1]
