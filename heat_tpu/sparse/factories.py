"""Sparse factories (reference: ``heat/sparse/factories.py``)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core import devices as ht_devices
from ..core import types
from ..core.communication import sanitize_comm
from .dcsr_matrix import DCSR_matrix

__all__ = ["sparse_csr_matrix", "sparse_csc_matrix"]


def sparse_csr_matrix(obj, dtype=None, split: Optional[int] = None, is_split=None,
                      device=None, comm=None) -> DCSR_matrix:
    """Build a DCSR_matrix from scipy.sparse, dense arrays, or (data, indices,
    indptr) — mirrors the reference factory's accepted inputs."""
    from ..core.dndarray import DNDarray

    if isinstance(obj, DNDarray):
        # dense DNDarray in: sparsify on-device, inherit split/comm/device;
        # conflicting placement kwargs are rejected BEFORE conversion work
        from .manipulations import to_sparse

        want = split if split is not None else is_split
        if want is not None and want != obj.split:
            raise ValueError(
                "sparse_csr_matrix cannot re-split a DNDarray input "
                f"(array split={obj.split}, requested {want}); resplit the "
                "dense array first"
            )
        if comm is not None and comm != obj.comm:
            raise ValueError("sparse_csr_matrix cannot rebind a DNDarray to a different comm")
        if device is not None and ht_devices.sanitize_device(device) != obj.device:
            raise ValueError("sparse_csr_matrix cannot move a DNDarray to a different device")
        return to_sparse(obj if dtype is None else obj.astype(dtype))

    comm = sanitize_comm(comm)
    device = ht_devices.sanitize_device(device)
    if split is None and is_split is not None:
        split = is_split

    try:
        import scipy.sparse as sp

        if sp.issparse(obj):
            coo = obj.tocoo()
            dense_shape = coo.shape
            indices = jnp.stack(
                [jnp.asarray(coo.row, jnp.int32), jnp.asarray(coo.col, jnp.int32)], axis=1
            )
            data = jnp.asarray(coo.data)
            arr = jsparse.BCOO((data, indices), shape=dense_shape)
            dt = types.canonical_heat_type(dtype) if dtype else types.canonical_heat_type(data.dtype)
            if dtype:
                arr = jsparse.BCOO((data.astype(dt.jax_dtype()), indices), shape=dense_shape)
            return DCSR_matrix(arr, int(coo.nnz), dense_shape, dt, split, device, comm, True)
    except ImportError:
        pass

    dense = np.asarray(obj)
    if dense.ndim != 2:
        raise ValueError("sparse_csr_matrix requires a 2-D input")
    if dtype is not None:
        dense = dense.astype(types.canonical_heat_type(dtype).np_dtype())
    arr = jsparse.BCOO.fromdense(jnp.asarray(dense))
    dt = types.canonical_heat_type(arr.data.dtype)
    return DCSR_matrix(arr, int(arr.nse), dense.shape, dt, split, device, comm, True)


def sparse_csc_matrix(obj, dtype=None, split: Optional[int] = None, device=None, comm=None):
    raise NotImplementedError("CSC is not supported (reference supports CSR only)")
