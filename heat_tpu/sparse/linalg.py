"""Distributed sparse matmul (reference: ``heat/sparse/`` ``matmul`` —
SURVEY §2.2 sparse row).

``DCSR (split=0) × dense`` runs genuinely row-parallel: each mesh shard
holds only its row block's nonzeros (padded COO triplets, see
``DCSR_matrix._row_sharded_parts``) and emits its row block of the dense
result inside one shard_map'd program — the dense operand is the only
replicated input, and no collective touches the sparse data.  This is the
TPU translation of the reference's per-rank local CSR spmv: the row split
makes the output rows rank-private, so the reference needs no communication
either.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core import types
from ..core._cache import comm_cached
from ..core.dndarray import DNDarray
from .dcsr_matrix import DCSR_matrix

__all__ = ["matmul"]


@comm_cached
def _spmm_program(comm, m: int, rows_per_shard: int, ncols: int, k: int, dt_name: str):
    """One compiled row-parallel spmm per (comm, nnz-pad, shape) config."""
    from jax.sharding import PartitionSpec as P

    def shard_fn(data_blk, rows_blk, cols_blk, dense):
        # blocks arrive as (1, m) slices of the (p, m) triplet arrays
        idx = jnp.stack([rows_blk[0], cols_blk[0]], axis=1)
        mat = jsparse.BCOO((data_blk[0], idx), shape=(rows_per_shard, ncols))
        return jsparse.bcoo_dot_general(
            mat, dense, dimension_numbers=(((1,), (0,)), ((), ()))
        )

    mapped = comm.shard_map(
        shard_fn, in_splits=((2, 0), (2, 0), (2, 0), P()), out_splits=(2, 0)
    )
    return jax.jit(mapped)


def matmul(s: DCSR_matrix, other):
    """``s @ other`` for a distributed CSR left operand.

    - ``other`` dense (DNDarray, 1-D or 2-D): result is a dense DNDarray
      with ``split = s.split`` (row-parallel; the reference's case table).
      A split dense operand is resplit to None first — the spmm needs full
      columns on every shard, exactly as the reference gathers the dense
      operand rank-locally.
    - ``other`` sparse (DCSR_matrix): fully sparse product — BCOO×BCOO with
      duplicate summation, no dense intermediate (two 1e-5-density matrices
      multiply without materializing the (n, k) dense product); the result
      keeps the left operand's row split.
    """
    from ..core import manipulations as core_manip

    if isinstance(other, DCSR_matrix):
        if s.shape[1] != other.shape[0]:
            raise ValueError(f"shape mismatch: {s.shape} @ {other.shape}")
        res = (s.larray @ other.larray).sum_duplicates()
        return DCSR_matrix(
            res, int(res.nse), (s.shape[0], other.shape[1]),
            types.promote_types(s.dtype, other.dtype), s.split, s.device, s.comm, True,
        )
    if not isinstance(other, DNDarray):
        raise TypeError(f"unsupported matmul operand {type(other)}")
    if other.ndim not in (1, 2):
        raise ValueError(f"dense operand must be 1-D or 2-D, got {other.ndim}-D")
    if s.shape[1] != other.shape[0]:
        raise ValueError(f"shape mismatch: {s.shape} @ {other.shape}")
    vec = other.ndim == 1
    if vec:
        other = core_manip.expand_dims(other, 1)
    if other.split is not None:
        other = core_manip.resplit(other, None)
    out_dt = types.promote_types(s.dtype, other.dtype)
    jd = other._jarray.astype(out_dt.jax_dtype())
    if s.split == 0 and s.comm.is_distributed():
        data, rows, cols, m, rows_per_shard = s._row_sharded_parts()
        prog = _spmm_program(
            s.comm, m, rows_per_shard, s.shape[1], int(jd.shape[1]), out_dt.__name__
        )
        phys = prog(data.astype(out_dt.jax_dtype()), rows, cols, jd)
        res = DNDarray(
            phys, (s.shape[0], other.shape[1]), out_dt, 0, s.device, s.comm, True
        )
    else:
        dense = jsparse.bcoo_dot_general(
            s.larray, jd, dimension_numbers=(((1,), (0,)), ((), ()))
        )
        dense = s.comm.shard(dense, s.split)
        res = DNDarray(
            dense, (s.shape[0], other.shape[1]), out_dt, s.split, s.device, s.comm, True
        )
    if vec:
        return core_manip.squeeze(res, 1)
    return res
