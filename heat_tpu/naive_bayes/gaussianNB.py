"""Gaussian naive Bayes (reference: ``heat/naive_bayes/gaussianNB.py``).

Per-class distributed means/variances via masked global moments (the
reference's partial_fit moment merging is XLA's tree-reduce), joint
log-likelihood prediction.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core import types
from ..core.base import BaseEstimator, ClassificationMixin
from ..core.dndarray import DNDarray

__all__ = ["GaussianNB"]


class GaussianNB(ClassificationMixin, BaseEstimator):
    """Gaussian naive Bayes with sklearn/reference API
    (``priors``, ``var_smoothing``; fitted: ``theta_``, ``var_``,
    ``class_prior_``, ``class_count_``, ``classes_``)."""

    def __init__(self, priors=None, var_smoothing: float = 1e-9):
        self.priors = priors
        self.var_smoothing = var_smoothing
        self.theta_ = None
        self.var_ = None
        self.class_count_ = None
        self.class_prior_ = None
        self.classes_ = None
        self.epsilon_ = None

    def fit(self, x: DNDarray, y: DNDarray, sample_weight=None) -> "GaussianNB":
        if x.ndim != 2:
            raise ValueError("x must be 2-D (n_samples, n_features)")
        jX = x._jarray
        jy = y._jarray.reshape(-1)
        classes = jnp.unique(jy)  # eager: concrete sizes
        n_classes = int(classes.shape[0])
        n, d = jX.shape

        self.epsilon_ = self.var_smoothing * float(jnp.max(jnp.var(jX, axis=0)))

        onehot = (jy[:, None] == classes[None, :]).astype(jX.dtype)  # (n, c)
        counts = jnp.sum(onehot, axis=0)  # (c,)
        safe = jnp.maximum(counts, 1.0)[:, None]
        # shift by the global feature mean before the moment GEMMs so that
        # E[x²]−E[x]² cancellation is relative to the data spread, not its
        # offset (float32-safe)
        gmean = jnp.mean(jX, axis=0)
        xs = jX - gmean[None, :]
        sums_s = onehot.T @ xs  # (c, d) MXU GEMM + implicit Allreduce
        means_s = sums_s / safe
        sq_s = onehot.T @ (xs * xs)
        var = sq_s / safe - means_s**2
        var = jnp.maximum(var, 0.0) + self.epsilon_
        means = means_s + gmean[None, :]

        comm, device = x.comm, x.device

        def wrap(j):
            j = comm.shard(j, None)
            return DNDarray(j, tuple(j.shape), types.canonical_heat_type(j.dtype), None, device, comm, True)

        self.classes_ = wrap(classes)
        self.class_count_ = wrap(counts)
        if self.priors is not None:
            pr = jnp.asarray(self.priors, dtype=jX.dtype)
            if pr.shape[0] != n_classes:
                raise ValueError("Number of priors must match number of classes")
            if not np.isclose(float(jnp.sum(pr)), 1.0):
                raise ValueError("The sum of the priors should be 1")
            self.class_prior_ = wrap(pr)
        else:
            self.class_prior_ = wrap(counts / jnp.sum(counts))
        self.theta_ = wrap(means)
        self.var_ = wrap(var)
        return self

    def _joint_log_likelihood(self, jX):
        means = self.theta_._jarray
        var = self.var_._jarray
        prior = self.class_prior_._jarray
        # (n, c): log N(x | μ_c, σ_c²) summed over features + log prior
        log_det = -0.5 * jnp.sum(jnp.log(2.0 * jnp.pi * var), axis=1)  # (c,)
        diff = jX[:, None, :] - means[None, :, :]  # (n, c, d)
        quad = -0.5 * jnp.sum(diff * diff / var[None, :, :], axis=2)
        return jnp.log(jnp.maximum(prior, 1e-30))[None, :] + log_det[None, :] + quad

    def predict(self, x: DNDarray) -> DNDarray:
        if self.theta_ is None:
            raise RuntimeError("fit must be called before predict")
        jll = self._joint_log_likelihood(x._jarray)
        idx = jnp.argmax(jll, axis=1)
        labels = self.classes_._jarray[idx]
        lab = x.comm.shard(labels, x.split)
        return DNDarray(
            lab, tuple(lab.shape), types.canonical_heat_type(lab.dtype), x.split, x.device, x.comm, True
        )

    def predict_log_proba(self, x: DNDarray) -> DNDarray:
        jll = self._joint_log_likelihood(x._jarray)
        norm = jnp.log(jnp.sum(jnp.exp(jll - jnp.max(jll, axis=1, keepdims=True)), axis=1, keepdims=True)) + jnp.max(jll, axis=1, keepdims=True)
        res = jll - norm
        res = x.comm.shard(res, x.split)
        return DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), x.split, x.device, x.comm, True)

    def predict_proba(self, x: DNDarray) -> DNDarray:
        lp = self.predict_log_proba(x)
        res = jnp.exp(lp._jarray)
        return DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), x.split, x.device, x.comm, True)
