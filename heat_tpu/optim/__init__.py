"""Optimizers (reference: ``heat/optim/``)."""

from .dp_optimizer import DataParallelOptimizer, DASO, SGD, Adam, AdamW
from . import lr_scheduler
