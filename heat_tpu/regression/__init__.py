"""Regression estimators (reference: ``heat/regression/``)."""

from .lasso import Lasso
