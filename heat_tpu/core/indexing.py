"""Indexing ops (reference: ``heat/core/indexing.py``)."""

from __future__ import annotations

import jax.numpy as jnp

from . import types
from .dndarray import DNDarray

__all__ = ["flatnonzero", "nonzero", "tril_indices", "triu_indices", "where"]


def flatnonzero(x: DNDarray) -> DNDarray:
    """Global flat indices of non-zero elements (``nonzero`` on ``ravel``)."""
    from .manipulations import ravel

    return nonzero(ravel(x))


def _tri_indices(fn, n: int, k: int, m):
    from . import factories

    rows, cols = fn(n, k=k, m=n if m is None else m)
    return factories.array(rows, split=None), factories.array(cols, split=None)


def triu_indices(n: int, k: int = 0, m=None):
    """Row/col index arrays of the upper triangle of an (n, m) matrix
    (numpy keyword parity: the diagonal offset is ``k``, as in ``triu``)."""
    import numpy as np

    return _tri_indices(np.triu_indices, n, k, m)


def tril_indices(n: int, k: int = 0, m=None):
    """Row/col index arrays of the lower triangle of an (n, m) matrix
    (numpy keyword parity: the diagonal offset is ``k``, as in ``tril``)."""
    import numpy as np

    return _tri_indices(np.tril_indices, n, k, m)


def nonzero(x: DNDarray) -> DNDarray:
    """Global indices of non-zero elements, shape (nnz, ndim).

    The reference Allgathers rank-local indices + offsets; here the global
    array yields global indices directly.  Eager-only (data-dependent shape).
    """
    idx = jnp.nonzero(x._jarray)
    stacked = jnp.stack(idx, axis=1) if x.ndim > 1 else idx[0]
    out_split = 0 if x.split is not None else None
    stacked = x.comm.shard(stacked, out_split)
    return DNDarray(
        stacked,
        tuple(stacked.shape),
        types.canonical_heat_type(stacked.dtype),
        out_split,
        x.device,
        x.comm,
        True,
    )


def where(cond, x=None, y=None) -> DNDarray:
    """Ternary select; with one argument, alias of :func:`nonzero`."""
    if x is None and y is None:
        return nonzero(cond)
    if x is None or y is None:
        raise TypeError("either both or neither of x and y should be given")

    jx = x._jarray if isinstance(x, DNDarray) else x
    jy = y._jarray if isinstance(y, DNDarray) else y
    proto = cond if isinstance(cond, DNDarray) else (x if isinstance(x, DNDarray) else y)
    jc = cond._jarray if isinstance(cond, DNDarray) else jnp.asarray(cond)
    res = jnp.where(jc, jx, jy)
    split = None
    for a in (cond, x, y):
        if isinstance(a, DNDarray) and a.split is not None:
            split = a.split + (res.ndim - a.ndim)
            break
    if split is not None and split >= res.ndim:
        split = None
    res = proto.comm.shard(res, split)
    return DNDarray(
        res, tuple(res.shape), types.canonical_heat_type(res.dtype), split, proto.device, proto.comm, True
    )


DNDarray.nonzero = nonzero


def mask_indices(n: int, mask_func, k: int = 0):
    """Indices selected by a mask function over an (n, n) grid (numpy)."""
    import numpy as np

    rows, cols = np.mask_indices(n, mask_func, k)
    from . import factories

    return factories.array(rows, split=None), factories.array(cols, split=None)


__all__ += ["mask_indices"]
