"""First-class distributed communication skeletons (SURVEY §5.7).

The reference contains three reusable comm patterns buried inside ops:
the **ring pipeline** (``spatial.cdist``), the **halo exchange**
(``signal.convolve``) and the **all-to-all axis swap** (``resplit_``).
Here they are public, named utilities built on ``shard_map`` +
``lax.ppermute``/``lax.all_to_all`` — and they double as the building
blocks of sequence/context parallelism (ring attention's KV rotation is
exactly ``ring_map``) if transformer workloads are layered on top.
"""

from .ring import ring_map
from .halo import halo_exchange, with_halos

__all__ = ["ring_map", "halo_exchange", "with_halos"]
