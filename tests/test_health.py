"""Elastic-runtime health layer tests (ISSUE 5 tentpole).

Covers the three pieces end to end, all on CPU and all fast:

- **heartbeats**: atomic beacon writes, monotonic steps, staleness reads,
  the ``health.heartbeat.writes`` counter, the background beacon thread;
- **deadlines**: the ``Deadline`` helper, the ``comm.deadline`` context,
  the guarded blocking waits (``Wait``/``Barrier``/``host_fetch``) raising
  ``CollectiveTimeoutError`` on an injected hang instead of wedging the
  suite, and the staging-time check refusing to stage past an expired
  deadline;
- **supervisor**: the restart state machine against real subprocesses —
  clean run, crash-once-then-restart, budget exhaustion with a diagnostic
  report, heartbeat-stall detection, generation deadline — plus the
  ``watchdog.dumps``/``watchdog.kills``/``health.restarts`` accounting;
- the faults satellites: ``hang=``/``exit=`` modes, the ``proc.exit``
  SIGKILL site (in a subprocess), and ``call_with_retries``' total-time
  ``deadline=`` budget with ``retry.<site>.exhausted`` give-up counters.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.parallel import supervisor as sup
from heat_tpu.utils import faults, health, profiler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------- #
# Deadline helper
# ---------------------------------------------------------------------- #
class TestDeadline:
    def test_remaining_and_expired(self):
        dl = health.Deadline(30.0)
        assert 0 < dl.remaining() <= 30.0
        assert not dl.expired()
        gone = health.Deadline(0.0)
        assert gone.expired() and gone.remaining() <= 0

    def test_check_raises_and_counts(self):
        base = health.counters().get("health.deadline.trips", 0)
        dl = health.Deadline(0.0)
        with pytest.raises(health.CollectiveTimeoutError, match="deadline"):
            dl.check("comm.Allreduce")
        assert health.counters()["health.deadline.trips"] == base + 1

    def test_context_arms_and_disarms(self):
        assert health.active_deadline() is None
        with health.deadline(5.0) as dl:
            assert health.active_deadline() is dl
            # nested: the innermost governs
            with health.deadline(1.0) as inner:
                assert health.active_deadline() is inner
            assert health.active_deadline() is dl
        assert health.active_deadline() is None

    def test_counters_surface_in_profiler(self):
        # the health provider mirrors the module-local store into
        # profiler.counters() pre-prefixed (no double "health." prefix)
        health.counter_inc("health.deadline.trips", 0)  # force registration
        c = profiler.counters()
        assert "health.deadline.trips" in c
        assert not any(k.startswith("health.health.") for k in c)


# ---------------------------------------------------------------------- #
# heartbeats
# ---------------------------------------------------------------------- #
class TestHeartbeat:
    def test_write_and_read(self, tmp_path):
        p = str(tmp_path / "rank0.json")
        health.write_heartbeat(p, 7, status="training")
        rec = health.read_heartbeat(p)
        assert rec["step"] == 7 and rec["pid"] == os.getpid()
        assert rec["status"] == "training" and rec["restart_epoch"] == 0
        assert abs(rec["time"] - time.time()) < 5

    def test_age_and_missing(self, tmp_path):
        p = str(tmp_path / "hb.json")
        assert health.heartbeat_age(p) is None
        health.write_heartbeat(p, 1)
        assert health.heartbeat_age(p) < 5

    def test_torn_read_returns_none(self, tmp_path):
        p = str(tmp_path / "torn.json")
        with open(p, "w") as fh:
            fh.write('{"pid": 12')  # torn foreign write
        assert health.read_heartbeat(p) is None

    def test_beat_monotonic_and_counted(self, tmp_path):
        base = health.counters().get("health.heartbeat.writes", 0)
        hb = health.Heartbeat(str(tmp_path / "sub" / "rank1.json"))  # mkdirs
        hb.beat()
        hb.beat()
        hb.beat(step=10)
        rec = health.read_heartbeat(hb.path)
        assert rec["step"] == 10
        assert health.counters()["health.heartbeat.writes"] == base + 3
        assert profiler.counters()["health.heartbeat.writes"] >= base + 3

    def test_beacon_thread(self, tmp_path):
        hb = health.Heartbeat(str(tmp_path / "beacon.json"))
        hb.beat(step=3)
        with hb:
            hb.start_beacon(interval=0.05)
            time.sleep(0.25)
        rec = health.read_heartbeat(hb.path)
        assert rec["step"] == 3  # beacon re-beats the CURRENT step
        assert health.heartbeat_age(hb.path) < 5
        assert hb._thread is None  # context exit stopped the thread

    def test_beacon_and_beat_race_safely(self, tmp_path):
        """The beacon thread and the train loop's beat() write concurrently
        by design — per-thread tmp names keep every rewrite atomic (review
        finding: a shared tmp let one writer's rename consume the other's
        file, killing the beacon thread silently)."""
        hb = health.Heartbeat(str(tmp_path / "race.json"))
        with hb:
            hb.start_beacon(interval=0.001)
            for i in range(300):
                hb.beat(step=i)
            time.sleep(0.05)
            assert hb._thread.is_alive()  # the beacon survived the race
        rec = health.read_heartbeat(hb.path)
        assert rec is not None and rec["step"] == 299


# ---------------------------------------------------------------------- #
# guarded collectives (the comm.deadline watchdog)
# ---------------------------------------------------------------------- #
class TestGuardedCollectives:
    def test_wait_passthrough_without_deadline(self, ht):
        x = ht.arange(8, dtype=ht.float32, split=0)
        out = ht.communication.get_comm().Wait((x + 1.0)._jarray)
        np.testing.assert_allclose(np.asarray(out), np.arange(8) + 1.0)

    def test_injected_hang_on_wait_trips(self, ht):
        comm = ht.communication.get_comm()
        x = ht.arange(8, dtype=ht.float32, split=0)
        base = health.counters().get("health.deadline.trips", 0)
        t0 = time.monotonic()
        with faults.inject("comm.collective", hang=1):
            with comm.deadline(0.5):
                with pytest.raises(health.CollectiveTimeoutError, match="comm.Wait"):
                    comm.Wait(x._jarray)
        assert time.monotonic() - t0 < 10  # tripped, did not wedge the suite
        assert health.counters()["health.deadline.trips"] == base + 1

    def test_injected_hang_on_barrier_trips(self, ht):
        comm = ht.communication.get_comm()
        with faults.inject("comm.collective", hang=1):
            with comm.deadline(0.5):
                with pytest.raises(health.CollectiveTimeoutError, match="comm.Barrier"):
                    comm.Barrier()

    def test_injected_hang_on_host_fetch_trips(self, ht):
        comm = ht.communication.get_comm()
        x = ht.arange(8, dtype=ht.float32, split=0)
        with faults.inject("comm.host_fetch", hang=1):
            with comm.deadline(0.5):
                with pytest.raises(
                    health.CollectiveTimeoutError, match="comm.host_fetch"
                ):
                    comm.host_fetch(x._jarray)

    def test_injected_hang_at_staging_trips(self, ht):
        """A hang injected at the comm.collective STAGING site (inside
        _account) must be caught by the armed deadline like a hang in
        Wait — not wedge the caller's thread (review finding)."""
        import jax.numpy as jnp

        comm = ht.communication.get_comm()
        t0 = time.monotonic()
        with faults.inject("comm.collective", hang=1):
            with comm.deadline(0.5):
                with pytest.raises(health.CollectiveTimeoutError):
                    comm.shard_map(
                        lambda a: comm.Allreduce(a), ((1, 0),), (1, None)
                    )(jnp.arange(float(comm.size)) + 3.0)
        assert time.monotonic() - t0 < 10

    def test_host_fetch_all_batches(self, ht):
        comm = ht.communication.get_comm()
        xs = [ht.arange(8, dtype=ht.float32, split=0)._jarray,
              ht.ones(4, dtype=ht.float32)._jarray]
        assert comm.host_fetch_all([]) == []
        out = comm.host_fetch_all(xs)
        np.testing.assert_allclose(out[0], np.arange(8, dtype=np.float32))
        np.testing.assert_allclose(out[1], np.ones(4, dtype=np.float32))
        # one batched call fires the site ONCE however many leaves
        faults.reset_trips()
        with faults.inject("comm.host_fetch", fail=0):
            comm.host_fetch_all(xs)
        assert faults.trip_count("comm.host_fetch") == 1

    def test_expired_deadline_refuses_staging(self, ht):
        import jax.numpy as jnp

        comm = ht.communication.get_comm()
        with comm.deadline(0.0):
            time.sleep(0.01)
            with pytest.raises(health.CollectiveTimeoutError, match="comm.Allreduce"):
                comm.shard_map(
                    lambda a: comm.Allreduce(a), ((1, 0),), (1, None)
                )(jnp.arange(float(comm.size)))

    def test_guard_propagates_real_errors(self):
        with health.deadline(5.0):
            with pytest.raises(ZeroDivisionError):
                health.guard_blocking(lambda: 1 / 0, "test.op")

    def test_guard_returns_value_under_deadline(self):
        with health.deadline(5.0):
            assert health.guard_blocking(lambda: 42, "test.op") == 42

    def test_collective_inside_deadline_still_works(self, ht):
        # a deadline generous enough must not perturb results
        x = ht.arange(16, dtype=ht.float32, split=0)
        comm = ht.communication.get_comm()
        with comm.deadline(60.0):
            total = float(x.sum().numpy())
            comm.Barrier()
        assert total == float(np.arange(16).sum())


# ---------------------------------------------------------------------- #
# faults satellites: hang/exit modes, retry deadline budget
# ---------------------------------------------------------------------- #
class TestFaultModes:
    def test_parse_spec_hang_and_exit(self):
        specs = faults.parse_spec("comm.collective:hang=1,delay=0.5;proc.exit:exit=3")
        assert specs["comm.collective"].hang == 1
        assert specs["comm.collective"].delay == 0.5
        assert specs["proc.exit"].exit == 3
        with pytest.raises(ValueError):
            faults.parse_spec("proc.exit:explode=1")

    @pytest.mark.slow
    def test_proc_exit_sigkills_subprocess(self):
        # loads faults.py standalone: stdlib-only, no jax import in the victim
        code = (
            "import importlib.util, sys;"
            "spec = importlib.util.spec_from_file_location('f', sys.argv[1]);"
            "m = importlib.util.module_from_spec(spec);"
            "spec.loader.exec_module(m);"
            "m.fire('proc.exit');"
            "m.fire('proc.exit');"
            "print('SURVIVED FIRST');"
            "m.fire('proc.exit');"
            "print('NEVER')"
        )
        env = dict(os.environ, HEAT_TPU_FAULTS="proc.exit:exit=3")
        p = subprocess.run(
            [sys.executable, "-c", code,
             os.path.join(REPO, "heat_tpu", "utils", "faults.py")],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert p.returncode == -signal.SIGKILL
        assert "SURVIVED FIRST" in p.stdout and "NEVER" not in p.stdout


class TestRetryDeadlineBudget:
    def test_budget_caps_cumulative_backoff(self):
        clk = [0.0]
        slept = []

        def fake_sleep(d):
            slept.append(d)
            clk[0] += d

        base = profiler.counters().get("retry.io.write.exhausted", 0)
        with faults.inject("io.write", fail=-1):
            with pytest.raises(faults.TransientFault):
                faults.call_with_retries(
                    lambda: faults.fire("io.write"), "io.write",
                    retries=10, base_delay=1.0, factor=2.0, max_delay=10.0,
                    jitter=0.0, sleep=fake_sleep, rand=lambda: 0.0,
                    deadline=3.0, clock=lambda: clk[0],
                )
        # slept 1.0; the next 2.0 would overrun the 3.0 budget -> gave up
        assert slept == [1.0]
        assert profiler.counters()["retry.io.write.exhausted"] == base + 1

    def test_attempt_exhaustion_also_counts(self):
        base = profiler.counters().get("retry.io.read.exhausted", 0)
        with faults.inject("io.read", fail=-1):
            with pytest.raises(faults.TransientFault):
                faults.call_with_retries(
                    lambda: faults.fire("io.read"), "io.read",
                    retries=2, sleep=lambda _: None,
                )
        assert profiler.counters()["retry.io.read.exhausted"] == base + 1

    def test_success_within_budget_unchanged(self):
        with faults.inject("io.write", fail=2):
            out = faults.call_with_retries(
                lambda: faults.fire("io.write") or "done", "io.write",
                retries=4, sleep=lambda _: None, deadline=100.0,
            )
        assert out == "done"


# ---------------------------------------------------------------------- #
# supervisor: the restart state machine against real subprocesses
# ---------------------------------------------------------------------- #
def _spawn_code(code: str, hb_dir=None):
    """A spawn callback running ``python -c code`` with RANK/EPOCH/HB in
    the environment (the supervisor contract, minus jax)."""

    def spawn(rank, epoch, port):
        env = dict(os.environ)
        env["RANK"] = str(rank)
        env["HEAT_TPU_RESTART_EPOCH"] = str(epoch)
        if hb_dir:
            env["HB"] = hb_dir
        return subprocess.Popen(
            [sys.executable, "-c", code], env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

    return spawn


class TestSupervisor:
    def test_clean_world_no_restarts(self):
        s = sup.Supervisor(
            _spawn_code("pass"), 2, restart_budget=2, poll_interval=0.05
        )
        res = s.run()
        assert res.ok and res.restarts == 0 and res.generations == 1
        assert res.returncodes == [0, 0]
        assert res.counters["health.restarts"] == 0

    def test_crash_once_restarts_with_resume_epoch(self):
        code = (
            "import os, sys;"
            "sys.exit(3 if os.environ['RANK'] == '1' "
            "and os.environ['HEAT_TPU_RESTART_EPOCH'] == '0' else 0)"
        )
        s = sup.Supervisor(_spawn_code(code), 2, restart_budget=2, poll_interval=0.05)
        res = s.run()
        assert res.ok and res.restarts == 1 and res.generations == 2
        assert res.counters["health.restarts"] == 1
        assert "rank 1 died" in res.failures[0]

    def test_budget_exhaustion_reports(self):
        s = sup.Supervisor(
            _spawn_code("import sys; sys.exit(7)"), 2,
            restart_budget=1, poll_interval=0.05,
        )
        res = s.run()
        assert not res.ok and res.restarts == 1 and res.generations == 2
        assert len(res.failures) == 2
        rep = res.report()
        assert rep["ok"] is False and rep["failures"] == res.failures
        assert json.loads(json.dumps(rep)) == rep  # merged report is JSON-able

    def test_heartbeat_stall_detected_and_restarted(self, tmp_path):
        hb_dir = str(tmp_path / "hb")
        os.makedirs(hb_dir)
        # epoch 0: both ranks beat once, rank 1 then stalls forever;
        # epoch 1: everyone beats and exits 0
        code = (
            "import os, time;"
            "open(os.path.join(os.environ['HB'], 'rank%s.json' % os.environ['RANK']),"
            " 'w').write('{}');"
            "time.sleep(120) if os.environ['RANK'] == '1' "
            "and os.environ['HEAT_TPU_RESTART_EPOCH'] == '0' else None"
        )
        s = sup.Supervisor(
            _spawn_code(code, hb_dir=hb_dir), 2,
            heartbeat_dir=hb_dir, heartbeat_timeout=1.0,
            restart_budget=1, poll_interval=0.1,
        )
        t0 = time.monotonic()
        res = s.run()
        assert res.ok and res.restarts == 1
        assert "heartbeat stale" in res.failures[0]
        assert res.counters["watchdog.dumps"] >= 1  # the stalled rank was reaped
        assert time.monotonic() - t0 < 60

    def test_never_beats_measured_from_generation_start(self, tmp_path):
        hb_dir = str(tmp_path / "hb")
        code = (
            "import os, time;"
            "time.sleep(120) if os.environ['HEAT_TPU_RESTART_EPOCH'] == '0' else None"
        )
        s = sup.Supervisor(
            _spawn_code(code, hb_dir=hb_dir), 1,
            heartbeat_dir=hb_dir, heartbeat_timeout=1.0,
            restart_budget=1, poll_interval=0.1,
        )
        res = s.run()
        assert res.ok and res.restarts == 1
        assert "heartbeat stale" in res.failures[0]

    def test_generation_deadline_aborts(self):
        s = sup.Supervisor(
            _spawn_code("import time; time.sleep(120)"), 1,
            restart_budget=0, generation_deadline=1.0, poll_interval=0.1,
        )
        t0 = time.monotonic()
        res = s.run()
        assert not res.ok
        assert "deadline" in res.failures[0]
        assert time.monotonic() - t0 < 30

    def test_free_port_is_bindable(self):
        import socket

        port = sup.free_port()
        s = socket.socket()
        s.bind(("127.0.0.1", port))
        s.close()

    def test_dump_stacks_then_kill_counts(self):
        p = subprocess.Popen(
            [sys.executable, "-c",
             "import signal, time; signal.signal(signal.SIGUSR1, lambda *a: None);"
             "print('up', flush=True); time.sleep(120)"],
            stdout=subprocess.PIPE,
        )
        p.stdout.readline()  # SIGUSR1 handler installed
        d = sup.dump_stacks_then_kill([p], grace=0.5)
        p.wait()
        assert d == {"dumps": 1, "kills": 1}
        done = subprocess.Popen([sys.executable, "-c", "pass"])
        done.wait()
        assert sup.dump_stacks_then_kill([done]) == {"dumps": 0, "kills": 0}


# ---------------------------------------------------------------------- #
# launcher-side plumbing
# ---------------------------------------------------------------------- #
class TestLauncherStaysJaxFree:
    def test_standalone_telemetry_load_never_imports_jax(self):
        """The supervising launcher standalone-loads telemetry.py for
        write_counters_line; even with HEAT_TPU_TELEMETRY=1 in the
        environment the import-time env arming must NOT fire (it resolves
        jax.profiler) — the launcher process never imports jax (review
        finding)."""
        code = (
            "import importlib.util, sys, os;"
            "spec = importlib.util.spec_from_file_location('t', sys.argv[1]);"
            "m = importlib.util.module_from_spec(spec);"
            "sys.modules['t'] = m;"
            "spec.loader.exec_module(m);"
            "assert not m.enabled(), 'env arming fired on a standalone load';"
            "assert 'jax' not in sys.modules, 'launcher imported jax';"
            "p = m.write_counters_line(sys.argv[2], 2, {'watchdog.kills': 1});"
            "assert 'jax' not in sys.modules;"
            "print(open(p).read().strip())"
        )
        import tempfile

        tdir = tempfile.mkdtemp()
        env = dict(os.environ, HEAT_TPU_TELEMETRY="1",
                   HEAT_TPU_TELEMETRY_DIR=tdir)
        p = subprocess.run(
            [sys.executable, "-c", code,
             os.path.join(REPO, "heat_tpu", "utils", "telemetry.py"), tdir],
            env=env, capture_output=True, text=True, timeout=60,
        )
        assert p.returncode == 0, p.stderr[-2000:]
        rec = json.loads(p.stdout.strip())
        assert rec == {"type": "counters", "rank": 2,
                       "values": {"watchdog.kills": 1}}

    def test_write_counters_line_merges(self, tmp_path):
        """The launcher's counters line folds into the multi-rank merge as
        its own rank (never shadowing a real rank's last-wins counters)."""
        import importlib.util

        from heat_tpu.utils import telemetry

        telemetry.write_counters_line(str(tmp_path), 0, {"comm.x.calls": 5})
        telemetry.write_counters_line(str(tmp_path), 2, {"watchdog.kills": 1})
        spec = importlib.util.spec_from_file_location(
            "trep_health", os.path.join(REPO, "scripts", "telemetry_report.py")
        )
        trep = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(trep)
        merged = trep.merge_files(trep.find_rank_files(str(tmp_path)))
        assert merged["ranks"] == [0, 2]
        assert merged["counters"]["comm.x.calls"] == 5
        assert merged["counters"]["watchdog.kills"] == 1


# ---------------------------------------------------------------------- #
# bootstrap integration
# ---------------------------------------------------------------------- #
class TestRestartEpoch:
    def test_default_zero(self, monkeypatch):
        monkeypatch.delenv("HEAT_TPU_RESTART_EPOCH", raising=False)
        assert ht.core.bootstrap.restart_epoch() == 0

    def test_reads_env(self, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_RESTART_EPOCH", "3")
        assert ht.core.bootstrap.restart_epoch() == 3
        assert health.restart_epoch() == 3

    def test_garbage_env_is_zero(self, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_RESTART_EPOCH", "banana")
        assert ht.core.bootstrap.restart_epoch() == 0
