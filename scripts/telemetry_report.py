"""Merge per-rank telemetry JSONL exports into one timeline + summary.

    python scripts/telemetry_report.py DIR_OR_FILE... [--json OUT]
                                       [--top N] [--timeline N]

Each rank of a run writes ``rank<k>.jsonl`` (``heat_tpu.utils.telemetry
.flush``; the multiprocess lane and the CI telemetry job arm this via
``HEAT_TPU_TELEMETRY_DIR``).  This CLI reads any mix of directories
(``rank*.jsonl`` inside) and explicit files and prints:

- a cross-rank **span summary** aggregated by name, sorted by self-time —
  where the wall-clock went, per site, over all ranks;
- **counters** summed over ranks (``comm.*`` byte accounting, ``cache.*``
  hit/miss, ``retry.*``, ``io.*``, ``daso.*``) — the per-rank LAST counters
  record wins (counters are cumulative within a rank);
- merged **histograms** (log-spaced bins sum exactly across ranks; the
  percentiles are recomputed from the merged bins);
- a merged **timeline**: the first N spans of all ranks on one wall-clock
  axis (span timestamps are exported in epoch seconds for this reason);
- when a target directory also holds flight-recorder rings
  (``flight_rank*.ring``, written crash-durably by
  ``heat_tpu.utils.flightrec``), a per-rank **collective timeline** — the
  seq × rank fingerprint grid centered on the first divergence or the
  straggler's stuck sequence, plus the one-line post-mortem verdict
  (``scripts/postmortem.py`` does the merge; this CLI just folds its view
  into the report so one command reads a whole run's artifacts);
- when serving artifacts are present — ``sched.job`` telemetry spans in
  the rank files and/or a scheduler journal (``sched_journal*.jsonl``,
  ``heat_tpu.parallel.scheduler``) — a per-tenant **SLO table**: job
  counts by outcome plus p50/p99 queue wait and execution latency (span
  durations when exported; journal record timestamps otherwise, so a
  journal-only dir — all a SIGKILLed rank leaves behind — still yields
  the full table).

Deliberately stdlib-only (no jax, no heat_tpu import): it must run
instantly on a login node against artifacts scp'd from a pod.

Exit code: 0 on success, 1 when no rank files were found/readable.
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys
from typing import Dict, List, Optional


def find_rank_files(target: str) -> List[str]:
    """Rank files under a directory, or the file itself."""
    if os.path.isdir(target):
        return sorted(glob.glob(os.path.join(target, "rank*.jsonl")))
    return [target] if os.path.exists(target) else []


def _read_records(path: str) -> List[dict]:
    records = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue  # a torn tail line must not sink the whole report
    return records


def _merge_hist(agg: dict, rec: dict) -> None:
    """Histograms merge exactly: same fixed bin grid on every rank, so bin
    counts sum; min/max/total/count fold."""
    for i, c in rec.get("bins", {}).items():
        agg["bins"][i] = agg["bins"].get(i, 0) + int(c)
    agg["count"] += int(rec.get("count", 0))
    agg["total_s"] += float(rec.get("total_s", 0.0))
    agg["min_s"] = min(agg["min_s"], float(rec.get("min_s", math.inf) or math.inf))
    agg["max_s"] = max(agg["max_s"], float(rec.get("max_s", 0.0)))
    agg["lo"] = float(rec.get("lo", 1e-6))
    agg["per_decade"] = int(rec.get("per_decade", 5))


def _hist_quantile(agg: dict, q: float) -> float:
    if not agg["count"]:
        return 0.0
    target = q * agg["count"]
    seen = 0
    for i in sorted(agg["bins"], key=int):
        n = agg["bins"][i]
        seen += n
        if n and seen >= target:
            idx = int(i)
            if idx == 0:
                return 0.0 if agg["min_s"] is math.inf else agg["min_s"]
            return min(agg["lo"] * 10 ** (idx / agg["per_decade"]), agg["max_s"])
    return agg["max_s"]


def merge_files(paths: List[str]) -> dict:
    """Fold every rank file into one merged structure (see module docstring
    for the merge rules)."""
    spans: List[dict] = []
    counters_by_rank: Dict[int, dict] = {}
    hists_by_rank: Dict[int, Dict[str, dict]] = {}
    ranks = set()
    for path in paths:
        for rec in _read_records(path):
            kind = rec.get("type")
            rank = int(rec.get("rank", 0))
            ranks.add(rank)
            if kind == "span":
                spans.append(rec)
            elif kind == "counters":
                counters_by_rank[rank] = rec.get("values", {})  # last wins
            elif kind == "hist":
                # hist records are CUMULATIVE snapshots (like counters): a
                # rank that flushes twice writes the same observations twice,
                # so within a rank the LAST snapshot wins; only across ranks
                # do bins sum
                hists_by_rank.setdefault(rank, {})[rec["name"]] = rec
    spans.sort(key=lambda r: r.get("ts", 0.0))

    hists: Dict[str, dict] = {}
    for per_rank in hists_by_rank.values():
        for name, rec in per_rank.items():
            agg = hists.get(name)
            if agg is None:
                agg = hists[name] = {
                    "bins": {}, "count": 0, "total_s": 0.0,
                    "min_s": math.inf, "max_s": 0.0,
                    "lo": 1e-6, "per_decade": 5,
                }
            _merge_hist(agg, rec)

    by_name: Dict[str, list] = {}
    for s in spans:
        row = by_name.setdefault(s["name"], [0, 0.0, 0.0, 0.0, set()])
        row[0] += 1
        row[1] += float(s.get("dur_s", 0.0))
        row[2] += float(s.get("self_s", 0.0))
        row[3] = max(row[3], float(s.get("dur_s", 0.0)))
        row[4].add(int(s.get("rank", 0)))
    span_summary = sorted(
        (
            {
                "name": name,
                "count": c,
                "total_s": round(total, 6),
                "self_s": round(self_s, 6),
                "max_ms": round(mx * 1e3, 3),
                "ranks": sorted(rks),
            }
            for name, (c, total, self_s, mx, rks) in by_name.items()
        ),
        key=lambda r: -r["self_s"],
    )

    counters: Dict[str, int] = {}
    for vals in counters_by_rank.values():
        for k, v in vals.items():
            counters[k] = counters.get(k, 0) + int(v)

    hist_summary = {}
    for name, agg in sorted(hists.items()):
        if not agg["count"]:
            hist_summary[name] = {"count": 0}
            continue
        hist_summary[name] = {
            "count": agg["count"],
            "mean_s": round(agg["total_s"] / agg["count"], 9),
            "p50_s": round(_hist_quantile(agg, 0.50), 9),
            "p90_s": round(_hist_quantile(agg, 0.90), 9),
            "p99_s": round(_hist_quantile(agg, 0.99), 9),
            # bin-resolution caveat applies (telemetry.Histogram docstring):
            # the deep tail is exact about the BIN, upper-edge within it
            "p999_s": round(_hist_quantile(agg, 0.999), 9),
            "max_s": round(agg["max_s"], 9),
        }

    return {
        "ranks": sorted(ranks),
        "files": paths,
        "n_spans": len(spans),
        "span_summary": span_summary,
        "counters": dict(sorted(counters.items())),
        "counters_per_rank": {str(r): v for r, v in sorted(counters_by_rank.items())},
        "histograms": hist_summary,
        "timeline": spans,
    }


def _fmt_table(rows: List[List[str]], header: List[str]) -> str:
    widths = [max(len(str(r[i])) for r in [header] + rows) for i in range(len(header))]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*header), fmt.format(*("-" * w for w in widths))]
    lines += [fmt.format(*(str(c) for c in r)) for r in rows]
    return "\n".join(lines)


def render(merged: dict, top: int = 20, timeline: int = 25) -> str:
    out = []
    ranks = merged["ranks"]
    out.append(
        f"telemetry report: {len(merged['files'])} rank file(s), "
        f"ranks={ranks}, {merged['n_spans']} spans"
    )
    if merged["span_summary"]:
        out.append("\n-- span summary (by total self-time, all ranks) --")
        rows = [
            [r["name"], r["count"], f"{r['total_s'] * 1e3:.3f}",
             f"{r['self_s'] * 1e3:.3f}", f"{r['max_ms']:.3f}",
             ",".join(str(x) for x in r["ranks"])]
            for r in merged["span_summary"][:top]
        ]
        out.append(_fmt_table(rows, ["span", "calls", "total_ms", "self_ms", "max_ms", "ranks"]))
    if merged["counters"]:
        out.append("\n-- counters (summed over ranks) --")
        rows = [[k, v] for k, v in merged["counters"].items()]
        out.append(_fmt_table(rows, ["counter", "value"]))
    if merged["histograms"]:
        out.append("\n-- histograms (merged bins) --")
        rows = []
        for name, h in merged["histograms"].items():
            if not h["count"]:
                continue
            rows.append([
                name, h["count"], f"{h['mean_s'] * 1e3:.3f}",
                f"{h['p50_s'] * 1e3:.3f}", f"{h['p90_s'] * 1e3:.3f}",
                f"{h['p99_s'] * 1e3:.3f}",
                f"{h.get('p999_s', h['p99_s']) * 1e3:.3f}",
                f"{h['max_s'] * 1e3:.3f}",
            ])
        if rows:
            out.append(_fmt_table(
                rows, ["histogram", "n", "mean_ms", "p50_ms", "p90_ms",
                       "p99_ms", "p99.9_ms", "max_ms"]
            ))
    if merged["timeline"] and timeline > 0:
        out.append(f"\n-- timeline (first {min(timeline, len(merged['timeline']))} spans, all ranks) --")
        t0 = merged["timeline"][0].get("ts", 0.0)
        rows = []
        for s in merged["timeline"][:timeline]:
            rows.append([
                f"+{(s.get('ts', 0.0) - t0) * 1e3:.3f}ms",
                s.get("rank", 0),
                "  " * int(s.get("depth", 0)) + s["name"],
                f"{float(s.get('dur_s', 0.0)) * 1e3:.3f}",
            ])
        out.append(_fmt_table(rows, ["t", "rank", "span", "dur_ms"]))
    return "\n".join(out)


_postmortem = None


def _postmortem_mod():
    """``scripts/postmortem.py`` loaded standalone (it lives next to this
    file; both are stdlib-only) — the ONE implementation of ring loading,
    verdict analysis and the seq × rank grid.  None when the file is
    missing (a stripped install): the report then simply has no
    collective-timeline section."""
    global _postmortem
    if _postmortem is None:
        import importlib.util

        path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "postmortem.py")
        if not os.path.exists(path):
            return None
        spec = importlib.util.spec_from_file_location("telemetry_report_postmortem", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        _postmortem = mod
    return _postmortem


def _jsonl_ranks(d: str) -> List[int]:
    """Rank numbers of the ``rank<k>.jsonl`` files in ``d`` — every rank
    that exported telemetry there was part of the world, so they double as
    the analyzer's expected-ranks hint (a rank with telemetry but no ring
    lost its black box, and must not hide inside a clean verdict)."""
    out = []
    for path in find_rank_files(d):
        base = os.path.basename(path)
        try:
            out.append(int(base[len("rank") : -len(".jsonl")]))
        except ValueError:
            continue
    return sorted(set(out))


def flightrec_section(dirs: List[str], context: int = 5) -> str:
    """The collective-timeline section for every target directory holding
    ``flight_rank*.ring`` files; '' when none do (the common telemetry-only
    invocation prints nothing extra).  The verdict gets the same evidence
    the supervisor's analyzer gets: the dir's own telemetry jsonl for wait
    attribution, and its jsonl rank set as expected ranks."""
    pm = _postmortem_mod()
    if pm is None:
        return ""
    out = []
    for d in dirs:
        rings = pm.load_rings(d)
        if not rings:
            continue
        verdict = pm.analyze(
            rings,
            waits=pm.load_wait_hists(d),
            expected_ranks=_jsonl_ranks(d) or None,
        )
        around = verdict.get("first_divergent_seq")
        if around is None and verdict.get("straggler"):
            around = verdict["straggler"].get("seq")
        out.append(f"\n-- collective timeline (seq × rank) from {d} --")
        out.append(pm.summary_line(verdict))
        if verdict.get("missing_ranks"):
            out.append(
                "rank(s) with telemetry but NO ring file: "
                + ", ".join(str(r) for r in verdict["missing_ranks"])
            )
        out.append(pm.render_grid(rings, around=around, context=context))
    return "\n".join(out)


def memory_section(dirs: List[str], timeline: int = 12) -> str:
    """The device-memory section: per-rank watermark timeline + a
    top-buffers table, both read from the flight-ring ``mem``/``membuf``
    records the memory ledger writes (``heat_tpu/utils/memledger.py``);
    '' when no target dir holds rings with memory records.  An ``oom=1``
    record is called out explicitly with the failed request size — the
    same evidence ``scripts/postmortem.py`` turns into its ``oom``
    verdict."""
    pm = _postmortem_mod()
    if pm is None:
        return ""
    out: List[str] = []
    for d in dirs:
        rings = pm.load_rings(d)
        if not rings:
            continue
        per_rank: Dict[int, List[dict]] = {}
        bufs: List[dict] = []
        for r, ring in sorted(rings.items()):
            # a ring may hold several dumps (per-step attestations + an OOM
            # dump); keep only each rank's LAST membuf burst — the freshest
            # view — so stale rows from earlier dumps never interleave as
            # "top live buffers" (the same per-dump scoping postmortem.py's
            # collector applies)
            burst: List[dict] = []
            last_burst: List[dict] = []
            for rec in ring.get("records", []):
                if rec.get("k") == "mem":
                    per_rank.setdefault(r, []).append(rec)
                    if burst:
                        last_burst = burst
                    burst = []
                elif rec.get("k") == "membuf":
                    burst.append(dict(rec, rank=r))
            bufs.extend(burst or last_burst)
        if not per_rank and not bufs:
            continue
        out.append(f"\n-- device memory (ledger watermarks) from {d} --")
        for r, recs in sorted(per_rank.items()):
            peak = max((rec.get("peak") or 0) for rec in recs)
            out.append(f"MEM-PEAK rank={r} bytes={peak}")
            t0 = recs[0].get("t", 0.0)
            for rec in recs[-timeline:]:
                by = rec.get("by") or {}
                cats = " ".join(f"{c}={v}" for c, v in sorted(by.items()))
                flag = (
                    f"  OOM req={rec.get('req')} where={rec.get('where')}"
                    if rec.get("oom")
                    else ""
                )
                out.append(
                    f"  rank {r} t+{rec.get('t', 0.0) - t0:7.3f}s  "
                    f"live={rec.get('live', 0):>12}  "
                    f"peak={rec.get('peak', 0):>12}  {cats}{flag}"
                )
        if bufs:
            bufs.sort(key=lambda b: -(b.get("nb") or 0))
            rows = [
                [
                    str(b.get("rank")),
                    str(b.get("nb")),
                    str(b.get("op")),
                    str(b.get("cat")),
                    str(b.get("span") or "-"),
                    str(b.get("tid") or "-"),
                ]
                for b in bufs[:10]
            ]
            out.append("top live buffers (from ledger dumps):")
            out.append(
                _fmt_table(rows, ["rank", "bytes", "op", "category", "span",
                                  "trace"])
            )
    return "\n".join(out)


_stepprof = None


def _stepprof_mod():
    """``scripts/stepprof.py`` loaded standalone (next to this file,
    stdlib-only) — the ONE implementation of the step-time breakdown.
    None when missing (a stripped install): no overlap section."""
    global _stepprof
    if _stepprof is None:
        import importlib.util

        path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "stepprof.py")
        if not os.path.exists(path):
            return None
        spec = importlib.util.spec_from_file_location("telemetry_report_stepprof", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        _stepprof = mod
    return _stepprof


def overlap_section(spans: List[dict]) -> str:
    """The step-time compute/comm-overlap breakdown (``scripts/
    stepprof.py``) over the already-merged spans; '' when no step spans
    exist or stepprof is missing."""
    sp = _stepprof_mod()
    if sp is None or not spans:
        return ""
    return sp.overlap_section(spans)


_tl = None


def _timeline_mod():
    """``heat_tpu/analysis/timeline.py`` loaded standalone (stdlib-only)
    — the ONE implementation of clock alignment, Chrome-trace export and
    critical-path blame.  None when missing (a stripped install)."""
    mod = sys.modules.get("heat_tpu.analysis.timeline")
    if mod is not None:
        return mod
    global _tl
    if _tl is None:
        import importlib.util

        path = os.path.normpath(os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            os.pardir, "heat_tpu", "analysis", "timeline.py",
        ))
        if not os.path.exists(path):
            return None
        spec = importlib.util.spec_from_file_location("telemetry_report_timeline", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        _tl = mod
    return _tl


def critical_path_section(targets: List[str], trace_out: Optional[str] = None) -> str:
    """CLOCK-ALIGN + CRITICAL-PATH attribution over the target dirs'
    merged artifacts (``heat_tpu/analysis/timeline.py``); '' when nothing
    is attributable.  With ``trace_out``, additionally writes the
    schema-checked Chrome trace-event JSON there."""
    tl = _timeline_mod()
    if tl is None:
        return ""
    dirs = [t for t in targets if os.path.isdir(t)]
    if not dirs:
        return ""
    bundle = tl.assemble(dirs)
    if not bundle["ranks"]:
        return ""
    out = []
    clock = tl.clock_report(bundle)
    if clock:
        out.append(clock)
    report = tl.critical_path_report(bundle)
    if report:
        out.append(report)
    if trace_out:
        trace = tl.to_chrome_trace(bundle)
        problems = tl.validate_chrome_trace(trace)
        with open(trace_out, "w") as fh:
            json.dump(trace, fh)
        out.append(
            f"TRACE-EXPORT events={len(trace['traceEvents'])} "
            f"ranks={len(bundle['ranks'])} out={trace_out}"
        )
        for p in problems:
            out.append(f"INVALID: {p}")
    return "\n".join(out)


def trace_section(targets: List[str], trace_id: str,
                  spans: Optional[List[dict]] = None) -> str:
    """The assembled causal timeline of ONE trace id across every artifact
    the targets hold: telemetry spans whose attrs carry the id, scheduler
    journal records (``tid``), and flight-recorder ring records (``tid``
    on collective stamps and job events).  This is the end-to-end payoff
    of trace propagation: one command renders a single job's path —
    submit, dispatches, per-collective seq stamps, retries, terminal state
    — across ranks, processes and supervisor restarts, merged on the
    epoch-seconds axis the exports share."""
    events: List[dict] = []  # {t, rank, source, what}
    if spans is None:
        spans = []
        for t in targets:
            for p in find_rank_files(t):
                spans.extend(
                    r for r in _read_records(p) if r.get("type") == "span"
                )
    for s in spans:
        at = s.get("attrs") or {}
        if at.get("trace_id") != trace_id:
            continue
        what = f"span {s.get('name')} ({float(s.get('dur_s', 0.0)) * 1e3:.3f}ms"
        extra = ", ".join(
            f"{k}={at[k]}" for k in ("kind", "outcome", "op", "attempts")
            if k in at
        )
        what += f"; {extra})" if extra else ")"
        events.append({
            "t": float(s.get("ts", 0.0)),
            "rank": s.get("rank", "?"),
            "source": "telemetry",
            "what": what,
        })
    sched = _scheduler_mod()
    for t in targets:
        for jp in find_journals(t):
            if sched is None:
                break
            try:
                replay = sched.replay_journal(jp)
            except Exception:
                continue
            for rec in replay["records"]:
                if rec.get("tid") != trace_id:
                    continue
                bits = [str(rec.get("type"))]
                for k in ("id", "seq", "attempt", "reason", "epoch"):
                    if rec.get(k) is not None:
                        bits.append(f"{k}={rec[k]}")
                events.append({
                    "t": float(rec.get("t", 0.0)),
                    "rank": "journal",
                    "source": "journal",
                    "what": " ".join(bits),
                })
    pm = _postmortem_mod()
    if pm is not None:
        for t in targets:
            if not os.path.isdir(t):
                continue
            for rank, ring in sorted(pm.load_rings(t).items()):
                for rec in ring.get("records", []):
                    if rec.get("tid") != trace_id:
                        continue
                    kind = rec.get("k")
                    if kind == "coll":
                        what = (
                            f"collective seq={rec.get('seq')} "
                            f"op={rec.get('op')} wire={rec.get('wire')}B"
                        )
                    else:
                        bits = [str(kind)]
                        for k in ("id", "state", "attempt"):
                            if rec.get(k) is not None:
                                bits.append(f"{k}={rec[k]}")
                        what = " ".join(bits)
                    events.append({
                        "t": float(rec.get("t", 0.0)),
                        "rank": rank,
                        "source": "flightrec",
                        "what": what,
                    })
    if not events:
        return f"trace {trace_id}: no records found under {targets}"
    events.sort(key=lambda e: e["t"])
    t0 = events[0]["t"]
    out = [f"-- causal timeline for trace {trace_id} "
           f"({len(events)} records, all sources) --"]
    rows = [
        [f"+{(e['t'] - t0) * 1e3:.3f}ms", str(e["rank"]), e["source"], e["what"]]
        for e in events
    ]
    out.append(_fmt_table(rows, ["t", "rank", "source", "event"]))
    return "\n".join(out)


_scheduler = None


def _scheduler_mod():
    """``heat_tpu/parallel/scheduler.py`` loaded standalone (stdlib-only,
    like this CLI) — the ONE implementation of journal replay.  None when
    the file is missing (a stripped install): the report then has no SLO
    section from journals (spans still render)."""
    global _scheduler
    if _scheduler is None:
        import importlib.util

        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "heat_tpu", "parallel", "scheduler.py",
        )
        if not os.path.exists(path):
            return None
        spec = importlib.util.spec_from_file_location("telemetry_report_scheduler", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        _scheduler = mod
    return _scheduler


def find_journals(target: str) -> List[str]:
    """Scheduler journal files under a directory, or the file itself."""
    if os.path.isdir(target):
        return sorted(glob.glob(os.path.join(target, "sched_journal*.jsonl")))
    base = os.path.basename(target)
    if base.startswith("sched_journal") and os.path.exists(target):
        return [target]
    return []


def _pctl(values: List[float], q: float) -> float:
    """Exact upper percentile of a small sample (serving job counts are
    human-scale; no need for the histogram approximation here)."""
    if not values:
        return 0.0
    vs = sorted(values)
    idx = min(len(vs) - 1, max(0, int(math.ceil(q * len(vs))) - 1))
    return vs[idx]


def slo_section(targets: List[str], spans: Optional[List[dict]] = None) -> str:
    """The per-tenant serving SLO table, from whichever artifacts exist:

    - ``sched.job`` spans in the rank files — the high-fidelity latency
      source (queue wait rides the span attrs, execution latency is the
      span duration);
    - scheduler journals (``sched_journal*.jsonl``) — the complete outcome
      accounting (incl. shed jobs, which never execute and so never span),
      with record-timestamp latencies as the fallback when no spans were
      exported (a SIGKILLed rank flushes no telemetry; its journal
      survives).

    '' when neither is present (the common non-serving invocation prints
    nothing extra).  ``spans`` lets a caller that already parsed the rank
    files (``main`` passes ``merged['timeline']``) skip the second read —
    the rank files are otherwise re-parsed here."""
    if spans is None:
        spans = []
        for t in targets:
            for p in find_rank_files(t):
                for rec in _read_records(p):
                    if rec.get("type") == "span" and rec.get("name") == "sched.job":
                        spans.append(rec)
    else:
        spans = [s for s in spans
                 if s.get("type") == "span" and s.get("name") == "sched.job"]
    # every rank of an SPMD serve world emits an identical span per job —
    # dedup by job id or the per-rank copies would multiply the job
    # counts and skew the percentiles
    seen_jobs = set()
    deduped = []
    for rec in spans:
        jid = (rec.get("attrs") or {}).get("id")
        if jid is not None:
            if jid in seen_jobs:
                continue
            seen_jobs.add(jid)
        deduped.append(rec)
    spans = deduped
    views: Dict[str, dict] = {}
    notes: List[str] = []
    for t in targets:
        for jp in find_journals(t):
            sched = _scheduler_mod()
            if sched is None:
                break
            try:
                views.update(sched.replay_journal(jp)["jobs"])
            except Exception as e:  # a bad journal must not sink the report
                notes.append(f"journal {jp}: unreadable ({e})")
    if not spans and not views and not notes:
        return ""
    tenants: Dict[str, dict] = {}

    def row(tenant: str) -> dict:
        return tenants.setdefault(tenant, {
            "jobs": 0, "done": 0, "failed": 0, "shed": 0,
            "waits": [], "execs": [],
        })

    for v in views.values():
        r = row(str(v.get("tenant", "default")))
        r["jobs"] += 1
        state = v.get("state")
        if state == "done":
            r["done"] += 1
        elif state == "failed":
            r["failed"] += 1
        elif state == "shed":
            r["shed"] += 1
        if v.get("dispatch_t") and v.get("submit_t"):
            r["waits"].append(max(0.0, v["dispatch_t"] - v["submit_t"]))
        if v.get("exec_s") is not None:
            r["execs"].append(float(v["exec_s"]))
        elif v.get("finish_t") and v.get("dispatch_t"):
            r["execs"].append(max(0.0, v["finish_t"] - v["dispatch_t"]))
    by_tenant_spans: Dict[str, dict] = {}
    for s in spans:
        at = s.get("attrs") or {}
        d = by_tenant_spans.setdefault(str(at.get("tenant", "default")),
                                       {"waits": [], "execs": [], "outcomes": {}})
        d["waits"].append(float(at.get("queue_wait_s", 0.0)))
        d["execs"].append(float(s.get("dur_s", 0.0)))
        oc = str(at.get("outcome", "?"))
        d["outcomes"][oc] = d["outcomes"].get(oc, 0) + 1
    for tenant, d in by_tenant_spans.items():
        r = row(tenant)
        # spans are the higher-fidelity latency source when both exist
        r["waits"], r["execs"] = d["waits"], d["execs"]
        if not views:  # spans-only dir: outcome counts from the spans too
            r["jobs"] = sum(d["outcomes"].values())
            r["done"] = d["outcomes"].get("done", 0)
            r["failed"] = r["jobs"] - r["done"]
    out = ["\n-- per-tenant serving SLO (sched.job spans + scheduler journal) --"]
    out.extend(notes)
    if tenants:
        rows = []
        for tenant in sorted(tenants):
            r = tenants[tenant]
            rows.append([
                tenant, r["jobs"], r["done"], r["failed"], r["shed"],
                f"{_pctl(r['waits'], 0.5) * 1e3:.1f}",
                f"{_pctl(r['waits'], 0.99) * 1e3:.1f}",
                f"{_pctl(r['execs'], 0.5) * 1e3:.1f}",
                f"{_pctl(r['execs'], 0.99) * 1e3:.1f}",
            ])
        out.append(_fmt_table(rows, [
            "tenant", "jobs", "done", "failed", "shed",
            "wait_p50_ms", "wait_p99_ms", "exec_p50_ms", "exec_p99_ms",
        ]))
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("targets", nargs="+", help="telemetry dirs and/or rank*.jsonl files")
    ap.add_argument("--json", default=None, help="also write the merged structure here")
    ap.add_argument("--top", type=int, default=20, help="span-summary rows to print")
    ap.add_argument("--timeline", type=int, default=25,
                    help="timeline rows to print (0 disables)")
    ap.add_argument("--context", type=int, default=5,
                    help="collective-grid rows either side of the divergence")
    ap.add_argument("--trace", default=None, metavar="TRACE_ID",
                    help="render the assembled causal timeline of ONE trace "
                         "id across spans, scheduler journals and flight "
                         "rings, instead of the full report")
    ap.add_argument("--trace-out", default=None, metavar="TRACE_JSON",
                    help="also export the cross-rank Chrome trace-event "
                         "JSON (clock-aligned; scripts/traceviz.py is the "
                         "standalone form)")
    args = ap.parse_args(argv)

    paths = []
    for t in args.targets:
        paths.extend(find_rank_files(t))
    paths = sorted(dict.fromkeys(paths))  # de-dup, stable order
    if args.trace:
        merged = merge_files(paths) if paths else None
        print(trace_section(
            list(args.targets), args.trace,
            spans=merged["timeline"] if merged is not None else None,
        ))
        return 0
    section = flightrec_section(
        [t for t in args.targets if os.path.isdir(t)], context=args.context
    )
    mem = memory_section([t for t in args.targets if os.path.isdir(t)])
    merged = merge_files(paths) if paths else None
    # reuse the merge's already-parsed spans instead of re-reading every
    # rank file just to pick out the sched.job records
    slo = slo_section(
        list(args.targets),
        spans=merged["timeline"] if merged is not None else None,
    )
    if not paths:
        # a dir holding ONLY flight-recorder rings or a scheduler journal
        # is a legitimate target: the supervisor's harvested epoch dirs
        # contain rings but no telemetry jsonl, and a SIGKILLed serving
        # rank leaves a journal and nothing else — the timeline / SLO
        # table is exactly what a post-mortem reader comes for
        if section or slo or mem:
            print(f"no rank*.jsonl telemetry files under {args.targets}; "
                  "rendering the journal/ring artifacts only")
            if section:
                print(section)
            if mem:
                print(mem)
            if slo:
                print(slo)
            # rings alone still align and attribute (the harvested
            # epoch-dir case: collective stamps are the anchors)
            cp = critical_path_section(
                list(args.targets), trace_out=args.trace_out
            )
            if cp:
                print(cp)
            return 0
        print(
            f"no rank*.jsonl files (nor flight_rank*.ring / "
            f"sched_journal*.jsonl files) found under {args.targets}",
            file=sys.stderr,
        )
        return 1
    print(render(merged, top=args.top, timeline=args.timeline))
    if section:
        print(section)
    if mem:
        print(mem)
    if slo:
        print(slo)
    overlap = overlap_section(merged["timeline"])
    if overlap:
        print(overlap)
    # cross-rank clock alignment + critical-path blame (and optionally
    # the Chrome trace artifact) — which rank/op/seq gated each step,
    # not just how much time each class took
    cp = critical_path_section(list(args.targets), trace_out=args.trace_out)
    if cp:
        print(cp)
    if args.json:
        # the timeline can be huge; the JSON artifact keeps it whole (the
        # text rendering is the bounded view)
        with open(args.json, "w") as fh:
            json.dump(merged, fh, indent=1)
        print(f"\nmerged JSON written to {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
