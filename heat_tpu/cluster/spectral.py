"""Spectral clustering (reference: ``heat/cluster/spectral.py``).

RBF affinity → normalized Laplacian → Lanczos eigenvectors → KMeans in the
embedding space, all through the public array API (SURVEY §2.4).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .. import spatial
from ..core import types
from ..core.base import BaseEstimator, ClusteringMixin
from ..core.dndarray import DNDarray
from ..graph.laplacian import Laplacian
from ..linalg.solver import lanczos
from .kmeans import KMeans
from ..core.communication import Communication

__all__ = ["Spectral"]


class Spectral(ClusteringMixin, BaseEstimator):
    """Spectral clustering on the normalized graph Laplacian."""

    def __init__(
        self,
        n_clusters: Optional[int] = None,
        gamma: float = 1.0,
        metric: str = "rbf",
        laplacian: str = "fully_connected",
        threshold: float = 1.0,
        boundary: str = "upper",
        n_lanczos: int = 300,
        assign_labels: str = "kmeans",
        **params,
    ):
        self.n_clusters = n_clusters
        self.gamma = gamma
        self.metric = metric
        self.n_lanczos = n_lanczos
        self.assign_labels = assign_labels

        sigma = jnp.sqrt(1.0 / (2.0 * gamma)) if gamma > 0 else 1.0
        if metric == "rbf":
            sim = lambda x: spatial.rbf(x, sigma=float(sigma), quadratic_expansion=True)
        elif metric == "euclidean":
            sim = lambda x: spatial.cdist(x, quadratic_expansion=True)
        else:
            raise NotImplementedError(f"metric {metric!r} not supported")
        self._laplacian = Laplacian(sim, definition="norm_sym", mode=laplacian,
                                    threshold_key=boundary, threshold_value=threshold)
        self._cluster = KMeans(n_clusters=n_clusters or 8, init="kmeans++", random_state=0)
        self._labels = None

    @property
    def labels_(self):
        return self._labels

    def _spectral_embedding(self, x: DNDarray):
        L = self._laplacian.construct(x)
        m = min(self.n_lanczos, L.shape[0])
        V, T = lanczos(L, m)
        evals, evecs = jnp.linalg.eigh(T._jarray)
        # eigenvectors of L ≈ V @ evecs; take the k smallest eigenvalues
        components = V._jarray @ evecs
        return evals, components

    def fit(self, x: DNDarray):
        evals, components = self._spectral_embedding(x)
        k = self.n_clusters
        if k is None:
            # largest eigen-gap heuristic (reference behavior)
            diffs = jnp.diff(evals)
            k = int(Communication.host_fetch(jnp.argmax(diffs))) + 1
            k = max(k, 2)
            self._cluster.n_clusters = k
        emb = components[:, :k]
        embedding = DNDarray(
            x.comm.shard(emb, x.split), tuple(emb.shape),
            types.canonical_heat_type(emb.dtype), x.split, x.device, x.comm, True,
        )
        self._cluster.fit(embedding)
        self._labels = self._cluster.labels_
        self._embedding = embedding
        self._fit_shape = tuple(x.shape)
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Labels of the FITTED data (spectral embeddings do not extend to
        out-of-sample points; the reference has the same restriction)."""
        if self._labels is None:
            raise RuntimeError("fit must be called before predict")
        if tuple(x.shape) != self._fit_shape:
            raise NotImplementedError(
                "Spectral clustering cannot label out-of-sample points; "
                "re-fit on the combined data instead"
            )
        return self._labels
