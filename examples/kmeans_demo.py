"""KMeans on synthetic blobs — the reference's flagship demo (config[2] shape).

Run (CPU mesh): XLA_FLAGS=--xla_force_host_platform_device_count=8 python examples/kmeans_demo.py
"""

import heat_tpu as ht


def main() -> None:
    data = ht.utils.data.create_spherical_dataset(num_samples_cluster=10_000)
    print(f"data: {data.shape}, split={data.split} over {data.comm.size} shards")
    km = ht.cluster.KMeans(n_clusters=4, init="kmeans++", random_state=0)
    km.fit(data)
    print(f"converged in {km.n_iter_} iterations, inertia={km.inertia_:.1f}")
    print("centers (mean per cluster):")
    print(km.cluster_centers_.numpy().mean(axis=1))


if __name__ == "__main__":
    main()
