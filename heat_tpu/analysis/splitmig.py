"""splitmig — the named-axis mesh migration's codemod planner and executor.

``SPLIT_INVENTORY.json`` (the absint pass's catalog of every
single-``split``-axis assumption) is a work list with no executor.  This
module turns it into a committed, drift-gated **plan**: every site is
classified into a mechanically-rewritable class or a semantic one, ordered
into dependency tranches via the PR 8 call graph, and the lowest-risk
tranche is *executable* through the fix-engine's edit machinery against
the ``core/axisspec.py`` compatibility shim (``split ↔ named-spec``
translation, value-preserving by construction).

Classes:

- ``spec-kwarg`` — a ``split=`` keyword argument.  Mechanical when the
  value is a literal: ``split=0`` rewrites to ``split=axisspec.named(0)``,
  bit-identical at runtime (AxisSpec subclasses int) while already
  speaking the named vocabulary.
- ``axis-read`` — a ``.split`` attribute read.  Mechanical in principle
  (the shim translates), staged after the kwargs.
- ``respec`` — a ``resplit``/``resplit_``/``redistribute_`` call.
  Mechanical when the axis is literal; becomes a respec once the
  placement core speaks PartitionSpecs.
- ``signature`` — a ``split`` *parameter*.  Never mechanical: changing a
  signature changes every caller, which is exactly what the tranche
  ordering exists to sequence.

Tranches (lower = earlier, executed first):

- **0** — mechanical ``spec-kwarg`` sites in pure consumer code
  (``benchmarks/``, ``tutorials/``): nothing depends on them, the rewrite
  is value-preserving, and the linter's shim-aware ``_literal_split``
  keeps the inventory/plan byte-stable across execution.  SHIPPED
  EXECUTED in this repo.
- **1** — mechanical ``spec-kwarg`` sites in library modules few other
  inventoried modules depend on (call-graph fan-in ≤ the threshold).
- **2** — mechanical ``axis-read``/``respec`` sites, plus mechanical
  kwargs in high-fan-in modules.
- **3** — semantic sites: ``signature`` changes and anything in the
  placement core / SUMMA / IO / tiling modules, where ``split`` is not a
  label but the algorithm.

The committed ``MIGRATION_PLAN.json`` is exact-match drift-gated in CI
beside ``SPLIT_INVENTORY.json``: the plan can only change when a human
regenerates and commits it — the denominator (414 sites) cannot silently
rot.

Stdlib-only and standalone-loadable, like the rest of ``analysis/``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Sequence, Tuple

from .callgraph import dotted_name
from .fixes import Edit, _relative_core_prefix, ensure_import_edit, node_span
from .framework import LintContext


def _binds_heat_tpu(ctx: LintContext, name: str) -> bool:
    """True when ``name`` is bound to the heat_tpu package anywhere in the
    file (``import heat_tpu as ht`` — including the consumer idiom of
    importing it lazily inside a function)."""
    for node in ctx.walk(ast.Import):
        for alias in node.names:
            if alias.name == "heat_tpu" and (alias.asname or alias.name) == name:
                return True
    return False

__all__ = [
    "classify_site",
    "build_plan",
    "render_plan",
    "tranche_edits",
    "SEMANTIC_MODULES",
]

# modules where `split` IS the algorithm, not a label: the placement core,
# the tiled redistribution planner, SUMMA's 2D-over-1D routing, IO's
# chunk layout, and the tiling/stride machinery.  Sites here are semantic
# regardless of lexical shape.
SEMANTIC_MODULES = frozenset(
    {
        "heat_tpu/core/communication.py",
        "heat_tpu/core/redistribution.py",
        "heat_tpu/core/dndarray.py",
        "heat_tpu/core/_operations.py",
        "heat_tpu/core/factories.py",
        "heat_tpu/core/manipulations.py",
        "heat_tpu/core/io.py",
        "heat_tpu/core/tiling.py",
        "heat_tpu/core/stride_tricks.py",
        "heat_tpu/linalg/basics.py",
    }
)

_CONSUMER_TOPDIRS = ("benchmarks", "tutorials")
_FANIN_THRESHOLD = 3  # dependent-module count above which a module is "load-bearing"

_MIGRATED_RE = re.compile(
    r"\bsplit\s*=\s*(?:[A-Za-z_][A-Za-z0-9_]*\s*\.\s*)*named\s*\("
)


def _module_dependents(program) -> Dict[str, set]:
    """path → set of OTHER paths whose functions call into it (the PR 8
    call graph, folded to module granularity)."""
    deps: Dict[str, set] = {}
    if program is None:
        return deps
    for ck in sorted(program.effects):
        cpath = ck[0]
        for r in program.resolved[ck]:
            if r.kind == "resolved":
                tpath = r.target[0]
                if tpath != cpath:
                    deps.setdefault(tpath, set()).add(cpath)
    return deps


def _is_consumer(path: str) -> bool:
    p = "/" + path.replace("\\", "/")
    return any(f"/{d}/" in p for d in _CONSUMER_TOPDIRS)


def _is_semantic_module(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(p.endswith(m) for m in SEMANTIC_MODULES)


def classify_site(site: dict, dependents: Dict[str, set]) -> dict:
    """class / mechanical / tranche / reason for one inventory site.

    Path matching is suffix/segment-based so an absolute-path invocation
    classifies identically to a repo-relative one — the committed plan's
    drift gate must not depend on how the CLI was launched."""
    path, kind, detail = site["path"], site["kind"], site["detail"]
    consumer = _is_consumer(path)
    fan_in = len(dependents.get(path, ()))

    if kind == "split-param":
        cls, mechanical = "signature", False
        reason = "a `split` parameter is API surface: migrating it changes every caller"
    elif _is_semantic_module(path):
        cls = {
            "split-read": "axis-read",
            "split-kwarg": "spec-kwarg",
            "resplit-call": "respec",
        }[kind]
        mechanical = False
        reason = (
            "placement-core/SUMMA/IO/tiling module: `split` is the algorithm "
            "here, not a label — hand migration with the linter holding the "
            "invariants"
        )
    elif kind == "split-read":
        cls, mechanical = "axis-read", True
        reason = "positional-axis read: shim-translatable once consumers speak specs"
    elif kind == "resplit-call":
        lit = "?" not in detail
        cls, mechanical = "respec", lit
        reason = (
            "literal resplit axis: becomes a respec when the core speaks specs"
            if lit
            else "dynamic resplit axis: needs the dataflow, not a token rewrite"
        )
    else:  # split-kwarg
        lit = "?" not in detail
        cls, mechanical = "spec-kwarg", lit
        reason = (
            "literal split= kwarg: value-preserving rewrite through axisspec.named()"
            if lit
            else "dynamic split= kwarg: the value is computed, not a literal to name"
        )

    if not mechanical:
        tranche = 3
    elif cls == "spec-kwarg" and consumer:
        tranche = 0
    elif cls == "spec-kwarg":
        tranche = 1 if fan_in <= _FANIN_THRESHOLD else 2
    else:  # axis-read / respec
        tranche = 2
    return {
        "class": cls,
        "mechanical": mechanical,
        "tranche": tranche,
        "reason": reason,
        "fan_in": fan_in,
    }


def _is_migrated(site: dict, contexts: Dict[str, LintContext]) -> bool:
    ctx = contexts.get(site["path"])
    if ctx is None or site["line"] - 1 >= len(ctx.lines):
        return False
    return bool(_MIGRATED_RE.search(ctx.lines[site["line"] - 1]))


def build_plan(
    inventory: Sequence[dict],
    program,
    contexts: Dict[str, LintContext],
) -> dict:
    """The full migration plan over ``inventory`` (every site classified,
    tranched, and — for executed tranches — marked migrated)."""
    dependents = _module_dependents(program)
    sites: List[dict] = []
    for raw in sorted(
        inventory, key=lambda s: (s["path"], s["line"], s["kind"], s["detail"])
    ):
        info = classify_site(raw, dependents)
        site = {
            "path": raw["path"],
            "line": raw["line"],
            "qualname": raw.get("qualname", "<module>"),
            "kind": raw["kind"],
            "detail": raw["detail"],
            "class": info["class"],
            "mechanical": info["mechanical"],
            "tranche": info["tranche"],
            "fan_in": info["fan_in"],
            "reason": info["reason"],
            "migrated": (
                info["tranche"] == 0
                and info["class"] == "spec-kwarg"
                and _is_migrated(raw, contexts)
            ),
        }
        sites.append(site)
    classes: Dict[str, int] = {}
    tranches: Dict[str, dict] = {}
    for s in sites:
        classes[s["class"]] = classes.get(s["class"], 0) + 1
        t = tranches.setdefault(
            str(s["tranche"]), {"sites": 0, "mechanical": 0, "migrated": 0}
        )
        t["sites"] += 1
        t["mechanical"] += int(s["mechanical"])
        t["migrated"] += int(s["migrated"])
    return {
        "version": 1,
        "comment": (
            "Named-axis mesh migration plan over every SPLIT_INVENTORY.json "
            "site: class + tranche per site, dependency-ordered via the "
            "analysis call graph. Tranche 0 executes mechanically against "
            "the core/axisspec.py shim (value-preserving, round-trip "
            "tested). Regenerate with: python scripts/heatlint.py heat_tpu/ "
            "benchmarks/ tutorials/ --split-plan MIGRATION_PLAN.json "
            "(drift-gated in CI: regeneration must match this file exactly)."
        ),
        "count": len(sites),
        "classes": {k: classes[k] for k in sorted(classes)},
        "tranches": {k: tranches[k] for k in sorted(tranches)},
        "sites": sites,
    }


def tranche_edits(
    plan: dict, contexts: Dict[str, LintContext], tranche: int = 0
) -> Tuple[List[Edit], List[dict]]:
    """Concrete edits executing one tranche's mechanical ``spec-kwarg``
    rewrites (``split=<k>`` → ``split=axisspec.named(<k>)``), plus the
    skipped sites with reasons.  Idempotent by construction: an already-
    migrated site no longer matches a literal-int kwarg and is skipped."""
    edits: List[Edit] = []
    skipped: List[dict] = []
    for site in plan["sites"]:
        if site["tranche"] != tranche or not site["mechanical"]:
            continue
        if site["class"] != "spec-kwarg":
            skipped.append(
                dict(site, skip_reason="only spec-kwarg sites execute mechanically today")
            )
            continue
        if site["migrated"]:
            continue
        ctx = contexts.get(site["path"])
        if ctx is None:
            skipped.append(dict(site, skip_reason="no parsed context for this path"))
            continue
        kw_value = None
        call_node = None
        replicated = False
        for node in ctx.walk(ast.Call):
            if node.lineno != site["line"]:
                continue
            for kw in node.keywords:
                if kw.arg != "split" or not isinstance(kw.value, ast.Constant):
                    continue
                if kw.value.value is None:
                    replicated = True  # nothing to name: already axis-free
                elif isinstance(kw.value.value, int) and not isinstance(
                    kw.value.value, bool
                ):
                    kw_value = kw.value
                    call_node = node
                if kw_value is not None or replicated:
                    break
            if kw_value is not None or replicated:
                break
        if replicated:
            continue
        if kw_value is None:
            skipped.append(
                dict(site, skip_reason="no literal-int split= kwarg found at this line")
            )
            continue
        # Prefer the call site's OWN heat_tpu binding (`ht.random.randn(...)`
        # → `ht.axisspec.named(k)`): consumer entry points routinely set
        # XLA_FLAGS env vars BEFORE importing heat_tpu, so a module-top
        # `from heat_tpu.core import axisspec` would import jax early and
        # silently void the device-count flags.  Only files with no such
        # binding get the import inserted.
        prefix_name = None
        root = (dotted_name(call_node.func) or "").split(".")[0]
        if root and _binds_heat_tpu(ctx, root):
            prefix_name = f"{root}.axisspec.named"
        s, e = node_span(ctx, kw_value)
        if prefix_name is None:
            edits.append(
                Edit(
                    ctx.path, s, e,
                    f"axisspec.named({kw_value.value})",
                    note=f"splitmig tranche-{tranche}",
                )
            )
            prefix = _relative_core_prefix(ctx.path)
            imp = ensure_import_edit(
                ctx, f"from {prefix} import axisspec", "axisspec"
            )
            if imp is not None:
                edits.append(imp)
        else:
            edits.append(
                Edit(
                    ctx.path, s, e,
                    f"{prefix_name}({kw_value.value})",
                    note=f"splitmig tranche-{tranche}",
                )
            )
    # dedupe identical import insertions
    seen: set = set()
    unique: List[Edit] = []
    for e in edits:
        ident = (e.path, e.start, e.end, e.replacement)
        if ident in seen:
            continue
        seen.add(ident)
        unique.append(e)
    return unique, skipped


def render_plan(plan: dict) -> str:
    import json

    return json.dumps(plan, indent=2) + "\n"
