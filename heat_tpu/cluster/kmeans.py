"""KMeans (reference: ``heat/cluster/kmeans.py``; BASELINE workload, SURVEY §3.4).

M-step = segment-sum over the sharded sample axis; XLA emits the two small
Allreduces (sums, counts) the reference issues by hand.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp

from ._kcluster import _KCluster

__all__ = ["KMeans"]


class KMeans(_KCluster):
    """K-Means clustering with the reference's API.

    Parameters mirror ``heat.cluster.KMeans``: n_clusters, init
    ('kmeans++' | 'random' | array), max_iter, tol, random_state.
    """

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, object] = "kmeans++",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        super().__init__(
            metric=lambda x, y: None, n_clusters=n_clusters, init=init,
            max_iter=max_iter, tol=tol, random_state=random_state,
        )

    @staticmethod
    def _blocked_stats(jx, k, label_fn):
        """(k, d) cluster sums + (k,) counts over transposed fixed-size blocks.

        ``label_fn(xb, start, blk) -> (blk,) labels`` supplies the assignment
        for each ``(d, blk)`` block.  The transposed view is a FREE bitcast of
        the {0,1} at-rest layout (see ``_KCluster._assign``), so X is never
        relayout-copied (a (blk, d) slice layout lane-pads d→128: 4× HBM for
        d=32, measured OOM on v5e).  The clamped tail block overlaps the
        previous one; overlapped rows get weight 0, so every row counts once.
        """
        n, d = jx.shape
        blk = _KCluster._ASSIGN_BLOCK
        xt = jx.T
        nblocks = -(-n // blk)

        def body(i, carry):
            s, c = carry
            start = jnp.minimum(i * blk, n - blk)
            xb = jax.lax.dynamic_slice_in_dim(xt, start, blk, axis=1)  # (d, blk)
            lb = label_fn(xb, start, blk)
            w = (jnp.arange(blk) + start >= i * blk).astype(jx.dtype)
            onehot = (jnp.arange(k)[:, None] == lb[None, :]).astype(jx.dtype) * w[None, :]
            bs = jnp.einsum("kb,db->kd", onehot, xb)  # MXU GEMM, no relayout
            return s + bs, c + jnp.sum(onehot, axis=1)

        return jax.lax.fori_loop(
            0, nblocks, body,
            (jnp.zeros((k, d), jx.dtype), jnp.zeros((k,), jx.dtype)),
        )

    @staticmethod
    def _centers_from_stats(sums, counts, centers):
        safe = jnp.maximum(counts, 1.0)
        new = sums / safe[:, None]
        # empty clusters keep their previous center (reference behavior)
        return jnp.where(counts[:, None] > 0, new, centers)

    @staticmethod
    def _update(jx, labels, centers):
        k = centers.shape[0]
        n = jx.shape[0]
        if n <= _KCluster._ASSIGN_BLOCK:
            onehot = (labels[:, None] == jnp.arange(k)[None, :]).astype(jx.dtype)
            sums, counts = onehot.T @ jx, jnp.sum(onehot, axis=0)
        else:
            sums, counts = KMeans._blocked_stats(
                jx, k,
                lambda xb, start, blk: jax.lax.dynamic_slice(labels, (start,), (blk,)),
            )
        return KMeans._centers_from_stats(sums, counts, centers)

    @classmethod
    def _em_step(cls, jx, centers):
        """Fused Lloyd iteration: ONE pass over X per iteration — each block
        is read once, assigned, and immediately folded into the (k, d)/(k,)
        statistics.  Halves HBM traffic vs assign-then-update."""
        k = centers.shape[0]
        n = jx.shape[0]
        if n <= _KCluster._ASSIGN_BLOCK:
            labels, _ = cls._assign(jx, centers)
            return cls._update(jx, labels, centers)
        cc = jnp.sum(centers * centers, axis=1)[:, None]

        def assign_block(xb, start, blk):
            xx = jnp.sum(xb * xb, axis=0)[None, :]
            d2 = cc + xx - 2.0 * (centers @ xb)  # (k, blk)
            return jnp.argmin(d2, axis=0)

        sums, counts = cls._blocked_stats(jx, k, assign_block)
        return cls._centers_from_stats(sums, counts, centers)
