"""Distributed linear algebra basics (reference: ``heat/core/linalg/basics.py``).

The reference's ``matmul`` is a hand-rolled SUMMA: case analysis on
``(a.split, b.split)``, K-blocks circulated with Bcast/ring, local GEMMs
accumulated (SURVEY §3.2).  On TPU that entire machinery collapses: one
``jnp.matmul`` on sharded operands lets GSPMD emit the identical blocked
algorithm (collective-matmul fusion over ICI keeps the MXU busy during
transfers).  What remains here is the *result-split bookkeeping* — the same
case table as the reference — plus an explicit ``shard_map`` SUMMA path for
when manual control wins.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core import types
from ..core import _operations
from ..core._cache import cached_program, comm_cached
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in

__all__ = [
    "cross",
    "det",
    "dot",
    "einsum",
    "einsum_path",
    "inv",
    "kron",
    "matmul",
    "matmul_summa",
    "matrix_norm",
    "norm",
    "outer",
    "projection",
    "trace",
    "transpose",
    "tril",
    "triu",
    "vdot",
    "vecdot",
    "vector_norm",
]


def _wrap(jarr, split, proto: DNDarray) -> DNDarray:
    if split is not None and (jarr.ndim == 0 or split >= jarr.ndim):
        split = None
    jarr = proto.comm.shard(jarr, split)
    return DNDarray(
        jarr, tuple(jarr.shape), types.canonical_heat_type(jarr.dtype), split, proto.device, proto.comm, True
    )


def det(a: DNDarray) -> DNDarray:
    """Determinant of a (batch of) square matrix (beyond-reference extra).

    The factorization is inherently sequential, so the computation is
    replicated; batch dims of a batched input stay sharded.
    """
    sanitize_in(a)
    res = jnp.linalg.det(a._jarray.astype(jnp.promote_types(a._jarray.dtype, jnp.float32)))
    split = a.split if a.split is not None and a.split < res.ndim else None
    return _wrap(res, split, a)


def inv(a: DNDarray) -> DNDarray:
    """Inverse of a (batch of) square matrix (beyond-reference extra)."""
    sanitize_in(a)
    res = jnp.linalg.inv(a._jarray.astype(jnp.promote_types(a._jarray.dtype, jnp.float32)))
    return _wrap(res, a.split, a)


def _matmul_result_split(sa: Optional[int], sb: Optional[int], nd_out: int) -> Optional[int]:
    """The reference's result-split case table for 2-D matmul.

    (None,None)→None; a row-split → out row-split; b col-split → out
    col-split; both-split contraction cases reduce over K → prefer row-split
    output (the reference picks split=0 for the 0/0 and 0/1 cases).
    """
    row, col = nd_out - 2, nd_out - 1
    if sa is None and sb is None:
        return None
    if sa == 0 and sb is None:
        return row
    if sa == 1 and sb is None:
        return row  # contraction over a's split: result gathered then re-split 0? ref: split=None→we keep row for locality
    if sa is None and sb == 0:
        return col if nd_out >= 2 else None
    if sa is None and sb == 1:
        return col
    if sa == 0:
        return row
    return col


# Measured SUMMA-vs-GSPMD winners (VERDICT r4 weak #4 reopened, round 5):
# {(platform, p): N_cross} — the explicit-ring SUMMA wins for square-ish
# 2-D split0×split0 products whose smaller matrix dim is >= N_cross; below
# it (and for every other split case) GSPMD wins.  Round-5 interleaved
# cached measurements on the 8-device CPU mesh (min of 4-5 reps, both
# orders): 1024 -> GSPMD 1.32x, 2048 -> GSPMD 1.04-1.14x, 4096 -> SUMMA
# 1.14x.  r4d's recorded 0.708 at 2048 was a one-shot ordering artifact —
# the pair is at parity there.  p=4 cpu mesh (same methodology): 1.20 at
# 1024, 1.01 at 2048/4096 — GSPMD wins or ties everywhere, so no entry
# (ties go to GSPMD, the fused default).  No TPU entry: multi-chip
# hardware is not measurable in this environment, and GSPMD's
# collective-matmul fusion is the principled TPU default; bench.py
# re-measures the pair every round, and `scripts/bench_compare.py` flags
# drift.
_SUMMA_DISPATCH = {("cpu", 8): 4096}


def _summa_wins(a: DNDarray, b: DNDarray) -> bool:
    """Bench-driven dispatch test for ``matmul(method='auto')``."""
    if a.ndim != 2 or b.ndim != 2 or a.split != 0 or b.split != 0:
        return False
    comm = a.comm
    if comm is None or comm.size <= 1:
        return False
    platform = comm.mesh.devices.flat[0].platform
    cross = _SUMMA_DISPATCH.get((platform, comm.size))
    return cross is not None and min(*a.shape, *b.shape) >= cross


def matmul(a: DNDarray, b: DNDarray, allow_resplit: bool = False,
           method: str = "auto") -> DNDarray:
    """Matrix product with distributed-split bookkeeping.

    All eight split cases of the reference map onto ONE sharded
    ``jnp.matmul``; XLA's SPMD partitioner performs the K-block circulation
    (SUMMA) that ``heat/core/linalg/basics.py::matmul`` hand-implements.

    ``method``: ``'auto'`` (default) consults the measured dispatch table
    ``_SUMMA_DISPATCH`` and routes large split0×split0 2-D products to the
    explicit ring when measurements say it wins on this (platform, p);
    ``'gspmd'`` / ``'summa'`` force a path (``'summa'`` requires the 2-D
    split0×split0 case, like :func:`matmul_summa`).
    """
    sanitize_in(a)
    sanitize_in(b)
    if method not in ("auto", "gspmd", "summa"):
        raise ValueError(f"method must be 'auto', 'gspmd' or 'summa', got {method!r}")
    if method == "summa" or (method == "auto" and _summa_wins(a, b)):
        return matmul_summa(a, b)
    if a.ndim == 1 and b.ndim == 1:
        return dot(a, b)
    # result rank is a pure function of the operand ranks (vector operands
    # drop their axis; both-1-D went to dot() above, so nd >= 1), so the
    # split table resolves BEFORE dispatch and the (matmul + output
    # placement) pair compiles into one cached program
    nd = max(a.ndim, b.ndim) - (a.ndim == 1) - (b.ndim == 1)
    if a.ndim == 1:
        split = None if b.split is None else (nd - 1 if b.split == b.ndim - 1 else None)
    elif b.ndim == 1:
        split = None if a.split is None else (nd - 1 if a.split == a.ndim - 2 else None)
    else:
        sa = None if a.split is None else (0 if a.split == a.ndim - 2 else (1 if a.split == a.ndim - 1 else None))
        sb = None if b.split is None else (0 if b.split == b.ndim - 2 else (1 if b.split == b.ndim - 1 else None))
        split = _matmul_result_split(sa, sb, nd)
    ja, jb = a._jarray, b._jarray
    if not a._pad and not b._pad and _operations._cacheable(ja, jb):
        comm = a.comm
        entry = cached_program(
            comm,
            ("matmul", _operations._sig(ja), _operations._sig(jb), split),
            lambda: _operations._build_binary(comm, jnp.matmul, ja, jb, split, False, {}),
        )
        prog, rshape, rdtype, rsplit = entry
        if rsplit is None or comm.size <= 1 or rshape[rsplit] % comm.size == 0:
            return DNDarray._from_parts(
                prog(ja, jb), rshape, rdtype, rsplit, a.device, comm
            )
        return DNDarray(prog(ja, jb), rshape, rdtype, rsplit, a.device, comm, True)
    return _wrap(jnp.matmul(ja, jb), split, a)


def matmul_summa(a: DNDarray, b: DNDarray) -> DNDarray:
    """Explicit shard_map SUMMA (both operands split=0).

    Stationary A row-block; B row-blocks rotate around the ring while each
    shard accumulates its partial GEMM — the reference's K-block circulation
    made explicit.  Status history: rounds 2-4 recorded a 2.5-5.5× GSPMD
    win that turned out to be per-call retrace+recompile, not the
    algorithm; round-4d's one-shot 0.708 "SUMMA ahead at 2048" was an
    ordering artifact.  Round-5 interleaved cached measurements (min of
    4-5 reps, both orders, p=8 CPU mesh) settle it as a SHAPE CROSSOVER:
    GSPMD wins below ~4096 (1.32× at 1024, 1.04-1.14× at 2048), SUMMA wins
    ~1.14× at 4096.  ``ht.matmul`` now auto-dispatches per the measured
    table (``_SUMMA_DISPATCH``); this entry point remains for forcing the
    ring path and for the per-round bench re-measurement
    (``BENCH summa_vs_gspmd``).
    """
    sanitize_in(a)
    sanitize_in(b)
    if a.ndim != 2 or b.ndim != 2:
        raise ValueError("matmul_summa requires 2-D operands")
    comm = a.comm
    M, K = a.shape
    K2, N = b.shape
    if K != K2:
        raise ValueError(f"shapes {a.shape} and {b.shape} not aligned")
    a0 = a.resplit(0) if a.split != 0 else a
    b0 = b.resplit(0) if b.split != 0 else b

    Kp = comm.padded_extent(K)
    Mp = comm.padded_extent(M)
    ja, jb = a0._jarray, b0._jarray
    if Mp != M or Kp != K:
        # ragged shards: zero-pad to the mesh grid (pad-and-mask) — zero
        # K-rows contribute nothing to the contraction and the dead M-rows
        # are sliced off below; the ring algorithm runs unchanged
        ja = jnp.pad(ja, ((0, Mp - M), (0, Kp - K)))
        jb = jnp.pad(jb, ((0, Kp - K), (0, 0)))
    res = _summa_program(comm)(ja, jb)
    if Mp != M:
        # keep the padded physical: the constructor records pad=(Mp-M) and
        # the result stays fully sharded with no unpad round-trip
        return DNDarray(
            res, (M, N), types.canonical_heat_type(res.dtype), 0,
            a.device, comm, True,
        )
    return _wrap(res, 0, a)


@comm_cached
def _summa_program(comm):
    """Jitted + comm-cached SUMMA ring (repeat calls — and the bench's
    timed reps — reuse the compiled pipeline instead of recompiling, so
    the recorded SUMMA-vs-GSPMD comparison measures the algorithm)."""
    axis, size = comm.axis, comm.size

    def shard_fn(a_blk, b_blk):
        my = lax.axis_index(axis)
        kblk = b_blk.shape[0]

        def step(carry, i):
            acc, rot = carry
            src = (my + i) % size  # which K-rows this rotating block holds
            a_cols = lax.dynamic_slice_in_dim(a_blk, src * kblk, kblk, axis=1)
            acc = acc + a_cols @ rot
            # ring shift source j+1 -> dest j == comm.Send(shift=-1); routed
            # through the Communication wrapper so the rotation shows up in
            # telemetry's comm.Send byte accounting (staged once per trace —
            # it lives inside lax.scan)
            rot = comm.Send(rot, shift=-1)
            return (acc, rot), None

        acc0 = jnp.zeros((a_blk.shape[0], b_blk.shape[1]), dtype=jnp.promote_types(a_blk.dtype, b_blk.dtype))
        (acc, _), _ = lax.scan(step, (acc0, b_blk), jnp.arange(size))
        return acc

    return jax.jit(
        comm.shard_map(shard_fn, in_splits=((2, 0), (2, 0)), out_splits=(2, 0))
    )


def dot(a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None) -> DNDarray:
    """Dot product: 1-D·1-D → scalar (implicit Allreduce); else matmul."""
    if a.ndim == 1 and b.ndim == 1:
        ja, jb = a._jarray, b._jarray
        if not a._pad and not b._pad and _operations._cacheable(ja, jb):
            comm = a.comm
            prog, rshape, rdtype, rsplit = cached_program(
                comm,
                ("dot", _operations._sig(ja), _operations._sig(jb)),
                lambda: _operations._build_binary(comm, jnp.dot, ja, jb, None, False, {}),
            )
            r = DNDarray._from_parts(prog(ja, jb), rshape, rdtype, rsplit, a.device, comm)
        else:
            r = _wrap(jnp.dot(ja, jb), None, a)
        if out is not None:
            out._jarray = r._jarray
            return out
        return r
    r = matmul(a, b)
    if out is not None:
        out._jarray = r._jarray
        return out
    return r


def vdot(x1: DNDarray, x2: DNDarray) -> DNDarray:
    res = jnp.vdot(x1._jarray, x2._jarray)
    return _wrap(res, None, x1)


def einsum(subscripts: str, *operands, out=None) -> DNDarray:
    """Einstein summation over DNDarrays.

    The contraction is expressed on the GLOBAL arrays and partitioned by
    GSPMD: contracted split axes lower to a sharded dot + psum, batch/free
    split axes stay sharded.  The output split is the position the first
    operand's split axis maps to in the output subscript (None if it was
    contracted away) — the same bookkeeping rule the matmul split table uses.
    """
    djs = [o._jarray if isinstance(o, DNDarray) else jnp.asarray(o) for o in operands]
    res = jnp.einsum(subscripts, *djs)
    proto = next((o for o in operands if isinstance(o, DNDarray)), None)
    if proto is None:
        raise TypeError("einsum needs at least one DNDarray operand")
    if "->" in subscripts:
        in_specs, out_spec = subscripts.split("->")
        out_spec = out_spec.replace(" ", "")
    else:
        # implicit mode: free labels = those appearing exactly once across all
        # inputs, in alphabetical order (numpy semantics); an ellipsis prefixes
        # broadcast dims, which keeps the '.' guard below in force so split
        # inference safely bails to None
        in_specs = subscripts
        flat = in_specs.replace(",", "").replace(" ", "").replace(".", "")
        out_spec = "".join(sorted(c for c in set(flat) if flat.count(c) == 1))
        if "." in in_specs:
            out_spec = "..." + out_spec
    in_list = [s.replace(" ", "") for s in in_specs.split(",")]
    split = None
    if "." not in out_spec:
        for o, spec in zip(operands, in_list):
            if isinstance(o, DNDarray) and o.split is not None and "." not in spec:
                label = spec[o.split] if o.split < len(spec) else None
                if label and label in out_spec:
                    split = out_spec.index(label)
                    break
    r = _wrap(res, split, proto)
    if out is not None:
        from ..core import sanitation

        sanitation.sanitize_out(out, r.shape, split, r.device)
        out._jarray = r._jarray.astype(out.dtype.jax_dtype())
        return out
    return r


def einsum_path(subscripts: str, *operands, optimize="greedy"):
    """Contraction-order plan for :func:`einsum` (numpy ``einsum_path``).

    Pure planning metadata — shapes only, no data movement — so delegating to
    numpy on the GLOBAL shapes is exact.  Note that under XLA the plan is
    advisory: ``jnp.einsum`` hands contraction ordering to opt_einsum/XLA
    itself; this exists for numpy-API parity and for users sizing
    intermediates by hand.
    """
    hosts = [
        # zero-copy shape carriers for anything shaped (DNDarray, jax array,
        # ndarray) — np.asarray would device-to-host a large operand just to
        # read its shape; asarray only for shapeless Python sequences
        np.broadcast_to(np.empty((), np.float32), o.shape)
        if hasattr(o, "shape")
        else np.asarray(o)
        for o in operands
    ]
    return np.einsum_path(subscripts, *hosts, optimize=optimize)


def kron(a, b) -> DNDarray:
    """Kronecker product; result split follows ``a``'s split axis (each of
    ``a``'s rows/cols expands to a contiguous block, preserving the axis
    order, so the blocked axis remains shardable)."""
    from ..core import factories

    # coerce array-likes onto the DNDarray operand's comm/device so the
    # result does not silently migrate to the default communicator
    if not isinstance(a, DNDarray):
        proto = b if isinstance(b, DNDarray) else None
        a = factories.array(a, device=proto.device, comm=proto.comm) if proto is not None else factories.array(a)
    if not isinstance(b, DNDarray):
        b = factories.array(b, device=a.device, comm=a.comm)
    res = jnp.kron(a._jarray, b._jarray)
    # numpy prepends size-1 axes to the lower-rank operand, so a's split axis
    # lands at a.split + (res.ndim - a.ndim) in the result
    split = None
    if a.split is not None:
        split = a.split + (res.ndim - a.ndim)
        if split >= res.ndim:
            split = None
    return _wrap(res, split, a)




def vecdot(x1: DNDarray, x2: DNDarray, axis: int = -1, keepdims: bool = False) -> DNDarray:
    res = jnp.sum(jnp.conj(x1._jarray) * x2._jarray, axis=axis, keepdims=keepdims)
    split = None
    return _wrap(res, split, x1)


def outer(a: DNDarray, b: DNDarray, out=None, split=None) -> DNDarray:
    """Outer product (reference: ring algorithm; here sharded broadcast-mul)."""
    res = jnp.outer(a._jarray, b._jarray)
    if split is None:
        split = 0 if (a.split is not None or b.split is not None) else None
    r = _wrap(res, split, a)
    if out is not None:
        out._jarray = r._jarray
        return out
    return r


def cross(a: DNDarray, b: DNDarray, axisa: int = -1, axisb: int = -1, axisc: int = -1, axis: int = -1) -> DNDarray:
    res = jnp.cross(a._jarray, b._jarray, axisa=axisa, axisb=axisb, axisc=axisc, axis=axis)
    return _wrap(res, a.split, a)


def projection(a: DNDarray, b: DNDarray) -> DNDarray:
    """Projection of vector a onto vector b."""
    scale = dot(a, b) / dot(b, b)
    from ..core import arithmetics

    return arithmetics.mul(b, scale)


def trace(a: DNDarray, offset: int = 0, axis1: int = 0, axis2: int = 1, dtype=None, out=None) -> DNDarray:
    res = jnp.trace(a._jarray, offset=offset, axis1=axis1, axis2=axis2)
    if dtype is not None:
        res = res.astype(types.canonical_heat_type(dtype).jax_dtype())
    r = _wrap(res, None, a)
    if out is not None:
        out._jarray = r._jarray
        return out
    return r


def transpose(a: DNDarray, axes=None) -> DNDarray:
    """Permute axes; the split axis moves with its dimension (no data motion
    beyond XLA's layout change + reshard)."""
    sanitize_in(a)
    if axes is None:
        axes = tuple(reversed(range(a.ndim)))
    axes = tuple(int(ax) % a.ndim for ax in axes)
    res = jnp.transpose(a._jarray, axes)
    split = axes.index(a.split) if a.split is not None else None
    return _wrap(res, split, a)


def tril(m: DNDarray, k: int = 0) -> DNDarray:
    return _wrap(jnp.tril(m._jarray, k=k), m.split, m)


def triu(m: DNDarray, k: int = 0) -> DNDarray:
    return _wrap(jnp.triu(m._jarray, k=k), m.split, m)


def vector_norm(x: DNDarray, axis=None, keepdims: bool = False, ord=2) -> DNDarray:
    res = jnp.linalg.vector_norm(x._jarray, axis=axis, keepdims=keepdims, ord=ord)
    split = None
    if axis is not None and x.split is not None:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        axes = tuple(ax % x.ndim for ax in axes)
        if x.split not in axes:
            split = x.split - sum(1 for ax in axes if ax < x.split) if not keepdims else x.split
    return _wrap(res, split, x)


def matrix_norm(x: DNDarray, axis=None, keepdims: bool = False, ord="fro") -> DNDarray:
    if axis is None:
        if x.ndim < 2:
            raise ValueError("matrix_norm requires at least 2 dimensions")
        axis = (x.ndim - 2, x.ndim - 1)
    res = jnp.linalg.norm(x._jarray, ord=ord, axis=tuple(axis), keepdims=keepdims)
    return _wrap(res, None, x)


def norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """Vector/matrix norm dispatch (numpy semantics)."""
    res = jnp.linalg.norm(x._jarray, ord=ord, axis=axis if axis is None or isinstance(axis, int) else tuple(axis), keepdims=keepdims)
    split = None
    if axis is not None and x.split is not None:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        axes = tuple(ax % x.ndim for ax in axes)
        if x.split not in axes and not keepdims:
            split = x.split - sum(1 for ax in axes if ax < x.split)
        elif x.split not in axes:
            split = x.split
    return _wrap(res, split, x)


DNDarray.__matmul__ = lambda self, other: matmul(self, other)
DNDarray.transpose = transpose
DNDarray.tril = lambda self, k=0: tril(self, k)
DNDarray.triu = lambda self, k=0: triu(self, k)


def inner(a: DNDarray, b: DNDarray) -> DNDarray:
    """Inner product over the last axes (numpy ``inner``)."""
    from ..core import factories

    if not isinstance(a, DNDarray):
        a = factories.array(a, device=b.device, comm=b.comm)
    if not isinstance(b, DNDarray):
        b = factories.array(b, device=a.device, comm=a.comm)
    res = jnp.inner(a._jarray, b._jarray)
    split = a.split if a.split is not None and a.split < max(a.ndim - 1, 0) else None
    return _wrap(res, split, a)


def tensordot(a: DNDarray, b: DNDarray, axes=2) -> DNDarray:
    """Tensor contraction over the given axes; GSPMD partitions the
    contraction (contracted split axes lower to sharded dot + psum)."""
    if isinstance(axes, (list, tuple)):
        ax_a, ax_b = axes
        ax_a = [ax_a] if isinstance(ax_a, int) else list(ax_a)
        ax_b = [ax_b] if isinstance(ax_b, int) else list(ax_b)
        contracted_a = {x % a.ndim for x in ax_a}
    else:
        contracted_a = set(range(a.ndim - int(axes), a.ndim))
    res = jnp.tensordot(a._jarray, b._jarray, axes=axes)
    split = None
    if a.split is not None and a.split not in contracted_a:
        # a's free axes come first in the output, in order
        split = sum(1 for x in range(a.split) if x not in contracted_a)
    return _wrap(res, split, a)


__all__ += ["inner", "tensordot"]
