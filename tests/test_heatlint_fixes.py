"""heatfix + splitmig tests (ISSUE 13 tentpole).

The proof-carrying autofix engine: every fixer gets a positive fixture
(the proof holds and the rewrite lands, re-lints clean, and is idempotent)
AND a refusal fixture per proof obligation (traced context, non-0-d value,
non-literal seed, caller-armed deadline, missing comm handle) asserting
the site is left byte-identical with the refusal reason shipped in
``--json``.  Plus: the HT110 stale-suppression rule both ways, the CLI
surface (``--fix``/``--dry-run-diff``/``--fix-check``/SARIF ``fixes``/
``--list-rules`` fixable column/``--select`` refusal), the baseline
burn-down honesty gate (every fingerprint removed from the baseline
re-lints clean UN-suppressed in the live repo), and the split-migration
planner (plan coverage, tranche-0 execution round-trip, committed-plan
drift gate).
"""

import importlib.util
import json
import os
import textwrap

import pytest

from heat_tpu.analysis import LintContext, fixes, lint_paths, splitmig, summaries
from heat_tpu.analysis.framework import load_baseline_records
from heat_tpu.analysis.rules import (
    HostSyncRule,
    NakedBlockingWaitRule,
    RawEntropyRule,
    StaleSuppressionRule,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "heatlint_cli_fixes", os.path.join(REPO, "scripts", "heatlint.py")
)
heatlint_cli = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(heatlint_cli)


def _ctx(source, path="heat_tpu/cluster/somelib.py"):
    return LintContext(path, textwrap.dedent(source))


def _plan_one(rule, source, path="heat_tpu/cluster/somelib.py", with_program=False):
    ctx = _ctx(source, path)
    findings = list(rule.check(ctx))
    assert findings, "fixture must trigger the rule"
    program = (
        summaries.build_program({ctx.path: ctx}, cache_path=None)
        if with_program
        else None
    )
    attempts = fixes.plan_fixes(findings, {ctx.path: ctx}, program)
    return ctx, attempts


def _apply(ctx, attempts):
    outcome = fixes.execute_fixes(attempts, {ctx.path: ctx}, write=False)
    return outcome.new_sources.get(ctx.path, ctx.source), outcome


# ---------------------------------------------------------------------- #
# edit engine
# ---------------------------------------------------------------------- #
class TestEditEngine:
    def test_apply_edits_splices(self):
        src = "abc def ghi"
        out = fixes.apply_edits(
            src,
            [
                fixes.Edit("p", 4, 7, "XYZ"),
                fixes.Edit("p", 0, 3, "A"),
            ],
        )
        assert out == "A XYZ ghi"

    def test_overlapping_edits_raise(self):
        with pytest.raises(ValueError, match="overlapping"):
            fixes.apply_edits(
                "abcdef",
                [fixes.Edit("p", 0, 4, "x"), fixes.Edit("p", 2, 6, "y")],
            )

    def test_insertion_at_same_point(self):
        out = fixes.apply_edits("ab", [fixes.Edit("p", 1, 1, "X")])
        assert out == "aXb"

    def test_node_span_handles_unicode_lines(self):
        # ast cols are utf-8 BYTE offsets; the splice must still be correct
        src = 'x = "αβγ"\ny = float(jnp.sum(a))\n'
        ctx = LintContext("p.py", src)
        import ast

        call = next(
            n for n in ctx.walk(ast.Call)
            if getattr(n.func, "id", None) == "float"
        )
        s, e = fixes.node_span(ctx, call)
        assert src[s:e] == "float(jnp.sum(a))"

    def test_ensure_import_edit_dedupes(self):
        ctx = _ctx(
            """
            from ..core.communication import Communication
            x = 1
            """
        )
        assert (
            fixes.ensure_import_edit(
                ctx, "from ..core.communication import Communication", "Communication"
            )
            is None
        )

    def test_relative_core_prefix(self):
        assert fixes._relative_core_prefix("heat_tpu/cluster/spectral.py") == "..core"
        assert fixes._relative_core_prefix("heat_tpu/core/statistics.py") == "..core"
        assert (
            fixes._relative_core_prefix("heat_tpu/utils/data/datatools.py") == "...core"
        )
        assert fixes._relative_core_prefix("benchmarks/main.py") == "heat_tpu.core"


# ---------------------------------------------------------------------- #
# HT101 fixer — host sync -> Communication.host_fetch
# ---------------------------------------------------------------------- #
class TestHostSyncFixer:
    def test_float_cast_of_reduction_fixed(self):
        ctx, attempts = _plan_one(
            HostSyncRule(),
            """
            import jax.numpy as jnp
            def f(x):
                return float(jnp.max(x._jarray))
            """,
        )
        new_src, outcome = _apply(ctx, attempts)
        assert "float(Communication.host_fetch(jnp.max(x._jarray)))" in new_src
        assert "from ..core.communication import Communication" in new_src
        assert outcome.applied and not outcome.refused

    def test_item_inside_cast_fixed(self):
        ctx, attempts = _plan_one(
            HostSyncRule(),
            """
            import jax.numpy as jnp
            def f(s):
                return int(jnp.sum(s > 0).item())
            """,
        )
        new_src, _ = _apply(ctx, attempts)
        assert "int(Communication.host_fetch(jnp.sum(s > 0)))" in new_src
        assert ".item()" not in new_src

    def test_bare_item_fixed_and_relints_clean(self):
        ctx, attempts = _plan_one(
            HostSyncRule(),
            """
            import jax.numpy as jnp
            def f(x):
                k = jnp.argmax(x._jarray).item()
                return k
            """,
        )
        new_src, _ = _apply(ctx, attempts)
        assert "Communication.host_fetch(jnp.argmax(x._jarray)).item()" in new_src
        # the engine already asserted the fixed fingerprint is gone; double-
        # check the materializer exemption makes the rewrite lint-clean
        assert not list(HostSyncRule().check(LintContext(ctx.path, new_src)))

    def test_item_on_materialized_data_exempt_only_when_outermost(self):
        # host_fetch(x).item() (the bare-item rewrite shape) is host data —
        # exempt, including through attribute/subscript views; but a device
        # recomputation ON TOP of fetched data is a real sync again
        clean = """
        def f(x, comm):
            a = comm.host_fetch(x).item()
            b = comm.host_fetch(x).T.item()
            c = comm.host_fetch(x)[0].item()
            return a, b, c
        """
        assert list(HostSyncRule().check(_ctx(clean))) == []
        dirty = """
        import jax.numpy as jnp
        def f(x, y, comm):
            return jnp.abs(comm.host_fetch(x) - y._jarray).item()
        """
        fs = list(HostSyncRule().check(_ctx(dirty)))
        assert [f.detail for f in fs] == ["item"]

    def test_refusal_traced_decorator(self):
        ctx, attempts = _plan_one(
            HostSyncRule(),
            """
            import jax
            import jax.numpy as jnp
            @jax.jit
            def f(x):
                return float(jnp.max(x._jarray))
            """,
        )
        assert attempts[0].refusal is not None and "traced" in attempts[0].refusal
        new_src, outcome = _apply(ctx, attempts)
        assert new_src == ctx.source  # byte-identical
        assert outcome.refused[0]["reason"] == attempts[0].refusal

    def test_refusal_nested_def(self):
        _ctx_, attempts = _plan_one(
            HostSyncRule(),
            """
            import jax.numpy as jnp
            def outer(x):
                def body(c):
                    return float(jnp.max(x._jarray))
                return body
            """,
        )
        assert "nested def" in attempts[0].refusal

    def test_refusal_passed_to_tracer(self):
        _ctx_, attempts = _plan_one(
            HostSyncRule(),
            """
            import jax
            import jax.numpy as jnp
            def f(x):
                return float(jnp.max(x._jarray))
            g = jax.jit(f)
            """,
        )
        assert "passed to `jit`" in attempts[0].refusal

    def test_refusal_non_zero_d(self):
        ctx, attempts = _plan_one(
            HostSyncRule(),
            """
            import jax.numpy as jnp
            def f(x):
                return float(jnp.max(x._jarray, axis=0))
            """,
        )
        assert "not" in attempts[0].refusal and "0-d" in attempts[0].refusal
        new_src, _ = _apply(ctx, attempts)
        assert new_src == ctx.source

    def test_refusal_device_get(self):
        _ctx_, attempts = _plan_one(
            HostSyncRule(),
            """
            import jax
            def f(x):
                return jax.device_get(x)
            """,
        )
        assert "pytrees" in attempts[0].refusal

    def test_zero_d_proof_accepts_keepdims_false_axis_none(self):
        ctx, attempts = _plan_one(
            HostSyncRule(),
            """
            import jax.numpy as jnp
            def f(x):
                return float(jnp.sum(x._jarray, axis=None, keepdims=False))
            """,
        )
        assert attempts[0].refusal is None


# ---------------------------------------------------------------------- #
# HT105 fixer — literal-seeded entropy -> core/random.host_rng
# ---------------------------------------------------------------------- #
class TestEntropyFixer:
    def test_literal_seed_rewritten(self):
        ctx, attempts = _plan_one(
            RawEntropyRule(),
            """
            import numpy as np
            def perm(n):
                return np.random.default_rng(0xC0FFEE).permutation(n)
            """,
        )
        new_src, _ = _apply(ctx, attempts)
        assert "ht_random.host_rng(0xC0FFEE).permutation(n)" in new_src
        assert "from ..core import random as ht_random" in new_src
        assert not list(RawEntropyRule().check(LintContext(ctx.path, new_src)))

    def test_refusal_seedless(self):
        ctx, attempts = _plan_one(
            RawEntropyRule(),
            """
            import numpy as np
            def f():
                return np.random.default_rng().integers(10)
            """,
        )
        assert "seedless" in attempts[0].refusal
        new_src, _ = _apply(ctx, attempts)
        assert new_src == ctx.source

    def test_refusal_nonliteral_seed(self):
        _ctx_, attempts = _plan_one(
            RawEntropyRule(),
            """
            import numpy as np
            def f(seed):
                return np.random.default_rng(seed).integers(10)
            """,
        )
        assert "rank-uniform" in attempts[0].refusal

    def test_refusal_other_entropy_shapes(self):
        _ctx_, attempts = _plan_one(
            RawEntropyRule(),
            """
            import numpy as np
            def f():
                return np.random.randint(2**31)
            """,
        )
        assert "no mechanical route" in attempts[0].refusal


# ---------------------------------------------------------------------- #
# HT107 fixer — wrap naked waits in comm.deadline
# ---------------------------------------------------------------------- #
class TestDeadlineWrapFixer:
    def test_wait_wrapped_when_no_caller_arms(self):
        ctx, attempts = _plan_one(
            NakedBlockingWaitRule(),
            """
            def fence(comm):
                comm.Barrier()
            """,
            with_program=True,
        )
        new_src, _ = _apply(ctx, attempts)
        assert "with comm.deadline(60.0):" in new_src
        assert not list(
            NakedBlockingWaitRule().check(LintContext(ctx.path, new_src))
        )

    def test_multiline_statement_wrapped(self):
        ctx, attempts = _plan_one(
            NakedBlockingWaitRule(),
            """
            import jax
            def fence(comm, xs):
                jax.block_until_ready(
                    xs
                )
            """,
            with_program=True,
        )
        new_src, _ = _apply(ctx, attempts)
        ctx2 = LintContext(ctx.path, new_src)  # must re-parse cleanly
        assert "with comm.deadline(60.0):" in new_src
        assert not list(NakedBlockingWaitRule().check(ctx2))

    def test_refusal_caller_already_arms_deadline(self):
        ctx, attempts = _plan_one(
            NakedBlockingWaitRule(),
            """
            def helper(comm):
                comm.Barrier()
            def entry(comm):
                with comm.deadline(5.0):
                    helper(comm)
            """,
            with_program=True,
        )
        assert "already arms a deadline" in attempts[0].refusal
        new_src, _ = _apply(ctx, attempts)
        assert new_src == ctx.source

    def test_refusal_transitive_caller_arms_deadline(self):
        _ctx_, attempts = _plan_one(
            NakedBlockingWaitRule(),
            """
            def helper(comm):
                comm.Barrier()
            def mid(comm):
                helper(comm)
            def entry(comm):
                with comm.deadline(5.0):
                    mid(comm)
            """,
            with_program=True,
        )
        assert "already arms a deadline" in attempts[0].refusal

    def test_refusal_other_class_comm_does_not_prove_handle(self):
        # a DIFFERENT class in the same file owning self.comm proves
        # nothing about this one — writing `with self.comm.deadline(...)`
        # into a comm-less class would raise AttributeError at runtime
        _ctx_, attempts = _plan_one(
            NakedBlockingWaitRule(),
            """
            import jax
            class HasComm:
                def __init__(self, comm):
                    self.comm = comm
            class NoComm:
                def wait(self, x):
                    jax.block_until_ready(x)
            """,
            with_program=True,
        )
        assert "no Communication handle" in attempts[0].refusal

    def test_own_class_comm_attribute_proves_handle(self):
        ctx, attempts = _plan_one(
            NakedBlockingWaitRule(),
            """
            import jax
            class Owner:
                def __init__(self, comm):
                    self.comm = comm
                def wait(self, x):
                    jax.block_until_ready(x)
            """,
            with_program=True,
        )
        new_src, _ = _apply(ctx, attempts)
        assert "with self.comm.deadline(60.0):" in new_src

    def test_refusal_comm_bound_after_the_wait(self):
        # `comm = ...` AFTER the wait must not count: wrapping would emit
        # `with comm.deadline(...)` over an unbound local
        _ctx_, attempts = _plan_one(
            NakedBlockingWaitRule(),
            """
            import jax
            def f(x, make_comm):
                jax.block_until_ready(x)
                comm = make_comm()
                return comm
            """,
            with_program=True,
        )
        assert "no Communication handle" in attempts[0].refusal

    def test_comm_bound_before_the_wait_counts(self):
        ctx, attempts = _plan_one(
            NakedBlockingWaitRule(),
            """
            import jax
            def f(x, make_comm):
                comm = make_comm()
                jax.block_until_ready(x)
            """,
            with_program=True,
        )
        new_src, _ = _apply(ctx, attempts)
        assert "with comm.deadline(60.0):" in new_src

    def test_refusal_no_comm_handle(self):
        _ctx_, attempts = _plan_one(
            NakedBlockingWaitRule(),
            """
            import jax
            def f(x):
                jax.block_until_ready(x)
            """,
            with_program=True,
        )
        assert "no Communication handle" in attempts[0].refusal

    def test_idempotence_pass_keeps_cross_file_proofs(self):
        # worker.py: a fixable HT101 cast AND a naked wait whose deadline
        # is armed by caller.py.  Pass 1 fixes HT101 and refuses HT107;
        # the idempotence re-plan must see caller.py too, or the refusal
        # flips into a planned edit and the whole run dies in FixError.
        worker = _ctx(
            """
            import jax.numpy as jnp
            def work(comm, x):
                comm.Barrier()
                return float(jnp.max(x._jarray))
            """,
            path="heat_tpu/cluster/worker.py",
        )
        caller = _ctx(
            """
            from .worker import work
            def entry(comm, x):
                with comm.deadline(5.0):
                    return work(comm, x)
            """,
            path="heat_tpu/cluster/caller.py",
        )
        contexts = {worker.path: worker, caller.path: caller}
        program = summaries.build_program(contexts, cache_path=None)
        findings = []
        for rule in (HostSyncRule(), NakedBlockingWaitRule()):
            findings.extend(rule.check(worker))
        attempts = fixes.plan_fixes(findings, contexts, program)
        by_rule = {a.finding.rule: a for a in attempts}
        assert by_rule["HT101"].edits and by_rule["HT101"].refusal is None
        assert "already arms a deadline" in by_rule["HT107"].refusal
        # must NOT raise FixError (the spurious-idempotence regression)
        outcome = fixes.execute_fixes(attempts, contexts, write=False)
        assert len(outcome.applied) == 1
        assert "host_fetch" in outcome.new_sources[worker.path]

    def test_refusal_without_program_facts(self):
        _ctx_, attempts = _plan_one(
            NakedBlockingWaitRule(),
            """
            def fence(comm):
                comm.Barrier()
            """,
            with_program=False,
        )
        assert "program facts unavailable" in attempts[0].refusal


# ---------------------------------------------------------------------- #
# HT110 — stale suppressions (rule + fixer)
# ---------------------------------------------------------------------- #
class TestStaleSuppression:
    def test_stale_suppression_flagged(self):
        fs = list(
            StaleSuppressionRule().check(
                _ctx(
                    """
                    def f(x):
                        return x + 1  # heatlint: disable=HT101
                    """
                )
            )
        )
        assert [f.detail for f in fs] == ["HT101"]
        assert fs[0].rule == "HT110"

    def test_live_suppression_not_flagged(self):
        fs = list(
            StaleSuppressionRule().check(
                _ctx(
                    """
                    def f(x):
                        return x.sum().item()  # heatlint: disable=HT101
                    """
                )
            )
        )
        assert fs == []

    def test_unknown_code_flagged(self):
        fs = list(
            StaleSuppressionRule().check(
                _ctx(
                    """
                    def f(x):
                        return x.sum().item()  # heatlint: disable=HT999
                    """
                )
            )
        )
        assert [f.detail for f in fs] == ["HT999"]
        assert "no registered rule" in fs[0].message

    def test_program_level_codes_skipped(self):
        fs = list(
            StaleSuppressionRule().check(
                _ctx(
                    """
                    def f(x):
                        return x + 1  # heatlint: disable=HT202
                    """
                )
            )
        )
        assert fs == []

    def test_disable_all_stale_flagged_live_not(self):
        stale = list(
            StaleSuppressionRule().check(
                _ctx("def f(x):\n    return x + 1  # heatlint: disable=all\n")
            )
        )
        assert [f.detail for f in stale] == ["ALL"]
        live = list(
            StaleSuppressionRule().check(
                _ctx("def f(x):\n    return x.sum().item()  # heatlint: disable=all\n")
            )
        )
        assert live == []

    def test_fixer_deletes_whole_comment(self):
        ctx, attempts = _plan_one(
            StaleSuppressionRule(),
            """
            def f(x):
                return x + 1  # heatlint: disable=HT101 historic reason
            """,
        )
        new_src, _ = _apply(ctx, attempts)
        assert "heatlint" not in new_src
        assert "return x + 1\n" in new_src  # padding gone too

    def test_fixer_drops_only_stale_code_from_mixed_list(self):
        ctx, attempts = _plan_one(
            StaleSuppressionRule(),
            """
            def f(x):
                return x.sum().item()  # heatlint: disable=HT101,HT105
            """,
        )
        # HT101 is live (the .item() sync), HT105 is stale
        assert [a.finding.detail for a in attempts] == ["HT105"]
        new_src, _ = _apply(ctx, attempts)
        assert "# heatlint: disable=HT101" in new_src
        assert "HT105" not in new_src

    def test_fixer_removes_all_stale_codes_in_one_edit(self):
        # two stale codes on one comment: the sibling findings must plan
        # IDENTICAL whole-line edits (deduped), not overlapping ones that
        # would poison the idempotence assertion
        ctx, attempts = _plan_one(
            StaleSuppressionRule(),
            """
            def f(x):
                return x + 1  # heatlint: disable=HT101,HT105
            """,
        )
        assert len(attempts) == 2
        assert all(a.refusal is None for a in attempts)
        new_src, outcome = _apply(ctx, attempts)
        assert "heatlint" not in new_src
        assert outcome.applied  # engine contract held (no FixError)

    def test_fixer_mixed_live_and_two_stale_codes(self):
        ctx, attempts = _plan_one(
            StaleSuppressionRule(),
            """
            def f(x):
                return x.sum().item()  # heatlint: disable=HT101,HT105,HT106
            """,
        )
        assert sorted(a.finding.detail for a in attempts) == ["HT105", "HT106"]
        new_src, _ = _apply(ctx, attempts)
        assert "# heatlint: disable=HT101" in new_src
        assert "HT105" not in new_src and "HT106" not in new_src

    def test_fix_is_idempotent_via_engine(self):
        ctx, attempts = _plan_one(
            StaleSuppressionRule(),
            """
            def f(x):
                return x + 1  # heatlint: disable=HT106
            """,
        )
        # execute_fixes raises FixError if a second pass would still edit
        _new_src, outcome = _apply(ctx, attempts)
        assert outcome.applied


# ---------------------------------------------------------------------- #
# the CLI surface
# ---------------------------------------------------------------------- #
class TestCli:
    FIXABLE = (
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    return float(jnp.max(x._jarray))\n"
    )

    def test_fix_check_fails_on_autofixable_new_finding(self, tmp_path, capsys):
        (tmp_path / "lib.py").write_text(self.FIXABLE)
        rc = heatlint_cli.main([str(tmp_path), "--fix-check", "--no-cache"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "autofixable" in out and "--fix" in out

    def test_fix_check_ok_on_unfixable_finding(self, tmp_path, capsys):
        (tmp_path / "lib.py").write_text(
            "import jax.numpy as jnp\n"
            "def f(x):\n"
            "    return float(jnp.max(x._jarray, axis=0))\n"
        )
        rc = heatlint_cli.main([str(tmp_path), "--fix-check", "--no-cache"])
        assert rc == 0
        assert "--fix-check OK" in capsys.readouterr().out

    def test_fix_dry_run_prints_diff_and_leaves_file(self, tmp_path, capsys):
        p = tmp_path / "lib.py"
        p.write_text(self.FIXABLE)
        rc = heatlint_cli.main(
            [str(tmp_path), "--fix", "--dry-run-diff", "--no-cache"]
        )
        out = capsys.readouterr().out
        assert rc == 0  # the one new finding is fixable -> nothing remains
        assert "host_fetch" in out and "+++" in out
        assert p.read_text() == self.FIXABLE  # untouched

    def test_fix_writes_and_second_run_clean(self, tmp_path, capsys):
        p = tmp_path / "lib.py"
        p.write_text(self.FIXABLE)
        rc = heatlint_cli.main([str(tmp_path), "--fix", "--no-cache"])
        assert rc == 0
        assert "Communication.host_fetch" in p.read_text()
        capsys.readouterr()
        rc2 = heatlint_cli.main([str(tmp_path), "--fix", "--no-cache"])
        assert rc2 == 0
        assert "0 fix(es) applied" in capsys.readouterr().out

    def test_fix_exit_1_when_refused_sibling_shares_fingerprint(self, tmp_path):
        # two same-fingerprint findings (same def, same detail), one fixed
        # one refused: the refused one must still gate — identity matching,
        # not fingerprint matching
        (tmp_path / "lib.py").write_text(
            "import jax.numpy as jnp\n"
            "def f(x):\n"
            "    a = float(jnp.max(x._jarray))\n"
            "    b = float(jnp.max(x._jarray, axis=0))\n"
            "    return a, b\n"
        )
        rc = heatlint_cli.main([str(tmp_path), "--fix", "--no-cache"])
        assert rc == 1

    def test_split_apply_written_plan_survives_regeneration(self, tmp_path):
        # a tranche-0 file NEEDING an import insertion shifts line numbers;
        # the plan written by --split-apply must match a fresh --split-plan
        # of the new tree (the CI drift-gate contract)
        (tmp_path / "bench_fixture.py").write_text(
            "from heat_tpu import random\n"
            "def bench():\n"
            "    return random.randn(8, 8, split=0)\n"
        )
        # the consumer classification keys on a benchmarks/ segment
        bench_dir = tmp_path / "benchmarks"
        bench_dir.mkdir()
        (tmp_path / "bench_fixture.py").rename(bench_dir / "bench_fixture.py")
        plan1 = tmp_path / "plan1.json"
        rc = heatlint_cli.main(
            [str(bench_dir), "--split-apply", "0", "--split-plan", str(plan1),
             "--no-cache"]
        )
        assert rc == 0
        new_src = (bench_dir / "bench_fixture.py").read_text()
        assert "from heat_tpu.core import axisspec" in new_src
        assert "split=axisspec.named(0)" in new_src
        plan2 = tmp_path / "plan2.json"
        heatlint_cli.main(
            [str(bench_dir), "--split-plan", str(plan2), "--no-cache"]
        )
        assert json.loads(plan1.read_text()) == json.loads(plan2.read_text())

    def test_fix_exit_1_when_unfixable_new_remains(self, tmp_path, capsys):
        (tmp_path / "lib.py").write_text(
            self.FIXABLE
            + "def g(x):\n    return float(jnp.max(x._jarray, axis=0))\n"
        )
        rc = heatlint_cli.main([str(tmp_path), "--fix", "--no-cache"])
        assert rc == 1  # the refused site still gates

    def test_json_ships_refusal_reasons(self, tmp_path):
        (tmp_path / "lib.py").write_text(
            "import numpy as np\n"
            "def f(seed):\n"
            "    return np.random.default_rng(seed).integers(10)\n"
        )
        out = tmp_path / "out.json"
        heatlint_cli.main(
            [str(tmp_path), "--fix-check", "--json", str(out), "--no-cache"]
        )
        payload = json.loads(out.read_text())
        refused = payload["fixes"]["refused"]
        assert len(refused) == 1
        assert "rank-uniform" in refused[0]["reason"]
        assert refused[0]["rule"] == "HT105"

    def test_sarif_carries_fix_objects(self, tmp_path):
        (tmp_path / "lib.py").write_text(self.FIXABLE)
        out = tmp_path / "out.sarif"
        heatlint_cli.main(
            [str(tmp_path), "--fix-check", "--sarif", str(out), "--no-cache"]
        )
        sarif = json.loads(out.read_text())
        results = sarif["runs"][0]["results"]
        fixed = [r for r in results if "fixes" in r]
        assert fixed, "the fixable finding must carry a SARIF fix object"
        reps = fixed[0]["fixes"][0]["artifactChanges"][0]["replacements"]
        assert any(
            "host_fetch" in rep["insertedContent"]["text"] for rep in reps
        )

    def test_fix_with_select_matching_no_fixable_rule_refuses(self, tmp_path, capsys):
        (tmp_path / "lib.py").write_text(self.FIXABLE)
        rc = heatlint_cli.main(
            [str(tmp_path), "--fix", "--select", "HT102", "--no-cache"]
        )
        assert rc == 2
        assert "matches no fixable rule" in capsys.readouterr().err

    def test_fix_with_select_matching_fixable_rule_ok(self, tmp_path):
        (tmp_path / "lib.py").write_text(self.FIXABLE)
        rc = heatlint_cli.main(
            [str(tmp_path), "--fix", "--select", "HT101", "--no-cache"]
        )
        assert rc == 0

    def test_fix_and_split_apply_mutually_exclusive(self, tmp_path, capsys):
        (tmp_path / "lib.py").write_text(self.FIXABLE)
        with pytest.raises(SystemExit):
            heatlint_cli.main(
                [str(tmp_path), "--fix", "--split-apply", "0", "--no-cache"]
            )
        assert "mutually exclusive" in capsys.readouterr().err

    def test_list_rules_has_fixable_column(self, capsys):
        heatlint_cli.main(["--list-rules"])
        out = capsys.readouterr().out
        assert "[fixable]" in out
        ht101 = next(ln for ln in out.splitlines() if ln.startswith("HT101"))
        ht102 = next(ln for ln in out.splitlines() if ln.startswith("HT102"))
        assert "[fixable]" in ht101 and "[fixable]" not in ht102


# ---------------------------------------------------------------------- #
# baseline burn-down honesty gate
# ---------------------------------------------------------------------- #
class TestBaselineBurnDown:
    # every fingerprint removed from the baseline this PR, by file: the
    # burned sites must re-lint clean UN-suppressed in the live repo —
    # asserting each removal was a real code fix, never a suppression
    BURNED = {
        "heat_tpu/cluster/spectral.py": [("HT101", "Spectral.fit", "item")],
        "heat_tpu/core/statistics.py": [
            ("HT101", "bincount", "item"),
            ("HT101", "histc", "float-cast"),
        ],
        "heat_tpu/decomposition/dmd.py": [("HT101", "DMD.fit", "item")],
        "heat_tpu/decomposition/pca.py": [
            ("HT101", "PCA.fit", "int-cast"),
            ("HT101", "PCA.fit", "float-cast"),
        ],
        "heat_tpu/naive_bayes/gaussianNB.py": [
            ("HT101", "GaussianNB.fit", "float-cast"),
            ("HT101", "GaussianNB.partial_fit", "bool-cast"),
            ("HT101", "GaussianNB.partial_fit", "float-cast"),
        ],
        "heat_tpu/parallel/sample_sort.py": [
            ("HT105", "_shuffle_perm", "np.random.default_rng")
        ],
        "heat_tpu/regression/lasso.py": [("HT101", "Lasso.fit", "float-cast")],
        "heat_tpu/utils/data/datatools.py": [
            ("HT105", "Dataset.shuffle", "np.random.randint"),
            ("HT105", "Dataset.ishuffle_start", "np.random.randint"),
        ],
        "heat_tpu/utils/data/mnist.py": [
            ("HT105", "_synthetic", "np.random.default_rng")
        ],
    }

    def test_baseline_shrunk_to_at_most_five(self):
        records = load_baseline_records(os.path.join(REPO, ".heatlint-baseline.json"))
        assert len(records) <= 5
        # the survivors are profiler's deliberate measurement syncs only
        assert {r["path"] for r in records} == {"heat_tpu/utils/profiler.py"}

    def test_burned_sites_relint_clean_unsuppressed(self):
        for rel, burned in self.BURNED.items():
            path = os.path.join(REPO, rel)
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
            # honesty: the fix must not be a suppression in disguise
            assert "heatlint: disable" not in src, rel
            ctx = LintContext(rel, src)
            found = {
                (f.rule, f.qualname, f.detail)
                for rule in (HostSyncRule(), RawEntropyRule())
                for f in rule.check(ctx)
            }
            for sig in burned:
                assert sig not in found, f"{rel}: {sig} resurfaced"

    def test_repo_fix_dry_run_plans_nothing(self):
        # the repo is fully burned down: a repo-wide fix pass must be a
        # no-op (and the engine's idempotence contract holds trivially)
        contexts: dict = {}
        program_holder: list = []
        findings = lint_paths(
            [os.path.join(REPO, "heat_tpu")],
            cache_path=None,
            contexts_out=contexts,
            program_out=program_holder,
        )
        errors = [f for f in findings if f.severity == "error"]
        attempts = fixes.plan_fixes(errors, contexts, program_holder[0])
        assert [a for a in attempts if a.edits] == []


# ---------------------------------------------------------------------- #
# splitmig — the migration planner + tranche-0 executor
# ---------------------------------------------------------------------- #
class TestSplitMig:
    def test_classify_kinds(self):
        deps: dict = {}
        sig = splitmig.classify_site(
            {"path": "heat_tpu/cluster/kmeans.py", "kind": "split-param",
             "detail": "split", "line": 1}, deps)
        assert sig["class"] == "signature" and not sig["mechanical"]
        assert sig["tranche"] == 3
        core = splitmig.classify_site(
            {"path": "heat_tpu/core/communication.py", "kind": "split-read",
             "detail": "split", "line": 1}, deps)
        assert not core["mechanical"] and core["tranche"] == 3
        consumer = splitmig.classify_site(
            {"path": "benchmarks/main.py", "kind": "split-kwarg",
             "detail": "ht.random.randn(split=0)", "line": 1}, deps)
        assert consumer["class"] == "spec-kwarg" and consumer["tranche"] == 0
        dyn = splitmig.classify_site(
            {"path": "benchmarks/main.py", "kind": "split-kwarg",
             "detail": "ht.zeros(split=?)", "line": 1}, deps)
        assert not dyn["mechanical"] and dyn["tranche"] == 3

    def test_fan_in_bumps_tranche(self):
        deps = {"heat_tpu/linalg/solver.py": {f"m{i}" for i in range(5)}}
        hot = splitmig.classify_site(
            {"path": "heat_tpu/linalg/solver.py", "kind": "split-kwarg",
             "detail": "ht.zeros(split=0)", "line": 1}, deps)
        assert hot["tranche"] == 2
        cold = splitmig.classify_site(
            {"path": "heat_tpu/cluster/kmeans.py", "kind": "split-kwarg",
             "detail": "ht.zeros(split=0)", "line": 1}, {})
        assert cold["tranche"] == 1

    def test_tranche0_execution_round_trip(self, tmp_path):
        src = (
            "import heat_tpu as ht\n"
            "def bench():\n"
            "    return ht.random.randn(64, 64, split=0)\n"
        )
        path = "benchmarks/fixture_bench.py"
        ctx = LintContext(path, src)
        inventory = [
            {"path": path, "line": 3, "kind": "split-kwarg",
             "qualname": "bench", "detail": "ht.random.randn(split=0)"}
        ]
        plan = splitmig.build_plan(inventory, None, {path: ctx})
        assert plan["count"] == 1
        assert plan["sites"][0]["tranche"] == 0
        assert plan["sites"][0]["migrated"] is False
        edits, skipped = splitmig.tranche_edits(plan, {path: ctx}, tranche=0)
        assert skipped == []
        new_src = fixes.apply_edits(src, edits)
        # the call-site's own ht binding is used: NO import inserted (the
        # consumer lazy-import / XLA_FLAGS-before-jax contract)
        assert "split=ht.axisspec.named(0)" in new_src
        assert "from heat_tpu.core import axisspec" not in new_src
        # round trip: the rewritten site is migrated, detail-stable, and a
        # second execution plans zero edits (idempotence)
        ctx2 = LintContext(path, new_src)
        plan2 = splitmig.build_plan(inventory, None, {path: ctx2})
        assert plan2["sites"][0]["migrated"] is True
        edits2, _ = splitmig.tranche_edits(plan2, {path: ctx2}, tranche=0)
        assert edits2 == []

    def test_tranche0_without_ht_binding_inserts_import(self):
        src = (
            "from heat_tpu import random\n"
            "def bench():\n"
            "    return random.randn(64, 64, split=0)\n"
        )
        path = "benchmarks/fixture2.py"
        ctx = LintContext(path, src)
        inventory = [
            {"path": path, "line": 3, "kind": "split-kwarg",
             "qualname": "bench", "detail": "random.randn(split=0)"}
        ]
        plan = splitmig.build_plan(inventory, None, {path: ctx})
        edits, _ = splitmig.tranche_edits(plan, {path: ctx}, tranche=0)
        new_src = fixes.apply_edits(src, edits)
        assert "from heat_tpu.core import axisspec" in new_src
        assert "split=axisspec.named(0)" in new_src

    def test_committed_plan_matches_fresh_regeneration(self):
        committed = json.load(open(os.path.join(REPO, "MIGRATION_PLAN.json")))
        inv = json.load(open(os.path.join(REPO, "SPLIT_INVENTORY.json")))
        contexts: dict = {}
        program_holder: list = []
        split_inventory: list = []
        lint_paths(
            [os.path.join(REPO, d) for d in ("heat_tpu", "benchmarks", "tutorials")],
            cache_path=None,
            split_inventory_out=split_inventory,
            contexts_out=contexts,
            program_out=program_holder,
        )
        plan = splitmig.build_plan(split_inventory, program_holder[0], contexts)
        for s in plan["sites"]:
            s["path"] = os.path.relpath(s["path"], REPO).replace(os.sep, "/")
        assert plan["count"] == committed["count"] == inv["count"] == 414
        assert plan == committed
        # every inventory site is covered, keyed identically
        key = lambda s: (s["path"], s["line"], s["kind"], s["detail"])  # noqa: E731
        assert {key(s) for s in plan["sites"]} == {key(s) for s in inv["sites"]}

    def test_committed_plan_tranche0_fully_migrated(self):
        plan = json.load(open(os.path.join(REPO, "MIGRATION_PLAN.json")))
        t0 = plan["tranches"]["0"]
        assert t0["sites"] == t0["migrated"] == 15
        # and every site record carries class + tranche (the acceptance shape)
        for s in plan["sites"]:
            assert s["class"] in ("axis-read", "spec-kwarg", "respec", "signature")
            assert s["tranche"] in (0, 1, 2, 3)
            assert isinstance(s["mechanical"], bool)
