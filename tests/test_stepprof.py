"""Step-time breakdown + trace timeline (ISSUE 11 tentpole part 3).

``scripts/stepprof.py`` decomposes step spans into compute / comm-wait /
host-sync / idle and reports the overlap fraction; ``scripts/
telemetry_report.py --trace`` assembles one trace id's causal timeline
across spans, scheduler journals and flight-recorder rings.  Both are
stdlib-only CLIs — tested here against synthetic and real artifacts.
"""

import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name, relpath):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, relpath)
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


sp = _load("stepprof_under_test", "scripts/stepprof.py")
trep = _load("telemetry_report_under_test", "scripts/telemetry_report.py")


def _span(name, ts, dur, rank=0, depth=0, attrs=None):
    rec = {"type": "span", "rank": rank, "name": name, "ts": ts,
           "dur_s": dur, "depth": depth}
    if attrs:
        rec["attrs"] = attrs
    return rec


class TestClassification:
    def test_host_beats_comm_beats_compute(self):
        assert sp.classify("comm.host_fetch.wait") == sp._HOST
        assert sp.classify("io.save_checkpoint") == sp._HOST
        assert sp.classify("comm.resplit") == sp._COMM
        assert sp.classify("comm.Wait.wait") == sp._COMM
        assert sp.classify("sched.dispatch.matmul.wait") == sp._COMM
        assert sp.classify("dispatch.binary") == sp._COMPUTE
        assert sp.classify("daso.blend") == sp._COMPUTE


class TestBreakdown:
    SPANS = [
        _span("daso.step", 0.00, 0.10),
        _span("dispatch.binary", 0.01, 0.03, depth=1),
        _span("comm.Wait.wait", 0.05, 0.02, depth=1),
        _span("comm.host_fetch.wait", 0.08, 0.01, depth=1),
        _span("comm.resplit", 0.12, 0.04),   # between the two steps
        _span("daso.step", 0.20, 0.05),
    ]

    def test_window_sweep_and_classes(self):
        rows = sp.step_breakdown(self.SPANS, ("daso.step",))
        assert len(rows) == 2
        r = rows[0]
        # window [0, 0.2): step span is compute minus the overlapped
        # comm/host leaves; the inter-step resplit charges to this step
        assert abs(r["comm_wait_s"] - 0.06) < 1e-9
        assert abs(r["host_sync_s"] - 0.01) < 1e-9
        assert abs(r["compute_s"] - 0.07) < 1e-9
        assert abs(r["idle_s"] - 0.06) < 1e-9
        assert abs(r["total_s"] - 0.20) < 1e-9
        assert abs(r["overlap_fraction"] - 0.7) < 1e-3
        # the final step has no trailing records: window = its own span
        assert rows[1]["overlap_fraction"] == 1.0

    def test_nested_records_never_double_count(self):
        spans = [
            _span("optim.step", 0.0, 0.1),
            _span("comm.resplit", 0.02, 0.04, depth=1),
            # a wait INSIDE the resplit span: the sweep must charge the
            # overlap region once (comm), not twice
            _span("comm.resplit.tile.wait", 0.03, 0.02, depth=2),
        ]
        (r,) = sp.step_breakdown(spans, ("optim.step",))
        assert abs(r["comm_wait_s"] - 0.04) < 1e-9

    def test_ranks_decompose_independently(self):
        spans = [
            _span("sched.job", 0.0, 0.1, rank=0),
            _span("sched.job", 0.0, 0.2, rank=1),
            _span("comm.Wait.wait", 0.05, 0.1, rank=1),
        ]
        rows = sp.step_breakdown(spans, ("sched.job",))
        by_rank = {r["rank"]: r for r in rows}
        assert by_rank[0]["comm_wait_s"] == 0.0
        assert abs(by_rank[1]["comm_wait_s"] - 0.1) < 1e-9

    def test_aggregate_totals_and_marker(self):
        rows = sp.step_breakdown(self.SPANS, ("daso.step",))
        (agg,) = sp.aggregate(rows)
        assert agg["steps"] == 2
        assert abs(agg["total_s"] - 0.25) < 1e-9
        assert abs(agg["comm_wait_s"] - 0.06) < 1e-9
        text = sp.render(rows)
        assert "STEP-OVERLAP kind=daso.step steps=2 overlap=" in text
        assert "comm_wait_ms=60.0" in text

    def test_no_steps_empty_section(self):
        assert sp.overlap_section([_span("dispatch.binary", 0, 0.1)]) == ""
        assert sp.step_breakdown([], ()) == []


class TestStepDistribution:
    """ISSUE 18 satellite: per-step p50/p99 distribution lines beside the
    aggregate — whose STEP-OVERLAP format stays pinned unchanged."""

    def test_percentiles_exact_upper_rule(self):
        rows = sp.step_breakdown(TestBreakdown.SPANS, ("daso.step",))
        d = sp.distribution(rows)["daso.step"]
        # totals [0.05, 0.20]: p50 = lower, p99 = upper (exact rule,
        # same as telemetry_report's histogram quantiles)
        assert d["n"] == 2
        assert abs(d["total_s_p50"] - 0.05) < 1e-9
        assert abs(d["total_s_p99"] - 0.20) < 1e-9
        assert abs(d["comm_wait_s_p99"] - 0.06) < 1e-9
        assert d["overlap_p50"] == 0.7 and d["overlap_p99"] == 1.0

    def test_dist_line_beside_pinned_aggregate(self):
        rows = sp.step_breakdown(TestBreakdown.SPANS, ("daso.step",))
        text = sp.render(rows)
        # the pre-existing marker is untouched...
        assert "STEP-OVERLAP kind=daso.step steps=2 overlap=" in text
        # ...and the distribution rides beside it
        assert (
            "STEP-DIST kind=daso.step n=2 total_ms_p50=50.0 "
            "total_ms_p99=200.0 comm_wait_ms_p50=0.0 comm_wait_ms_p99=60.0 "
            "overlap_p50=0.700 overlap_p99=1.000" in text
        )

    def test_dist_rides_cli_json(self, tmp_path, capsys):
        d = str(tmp_path)
        with open(os.path.join(d, "rank0.jsonl"), "w") as fh:
            for rec in TestBreakdown.SPANS:
                fh.write(json.dumps(rec) + "\n")
        out_json = str(tmp_path / "steps.json")
        assert sp.main([d, "--json", out_json]) == 0
        assert "STEP-DIST kind=daso.step" in capsys.readouterr().out
        payload = json.load(open(out_json))
        assert payload["distribution"]["daso.step"]["n"] == 2

    def test_no_rows_no_dist(self):
        assert sp.distribution([]) == {}


class TestOverlapDelta:
    """ISSUE 16: a merge dir holding BOTH sync labels yields the
    STEP-OVERLAP-DELTA comparison line; the existing STEP-OVERLAP format
    (asserted by the chaos lane and CI greps) must not change."""

    # two monolithic-sync steps (20% of the window waiting on comm) and
    # two bucketed ones (5% waiting): overlap 0.8 vs 0.95, delta +0.15
    SPANS = [
        _span("daso.step", 0.00, 0.10, attrs={"sync": "monolithic"}),
        _span("comm.Wait.wait", 0.05, 0.02, depth=1),
        _span("daso.step", 0.10, 0.10, attrs={"sync": "monolithic"}),
        _span("comm.allreduce.wait", 0.15, 0.02, depth=1),
        _span("daso.step", 0.20, 0.10, attrs={"sync": "bucketed"}),
        _span("comm.allreduce.wait", 0.25, 0.005, depth=1),
        _span("daso.step", 0.30, 0.10, attrs={"sync": "bucketed"}),
        _span("comm.allreduce.wait", 0.35, 0.005, depth=1),
    ]

    def test_delta_line_when_both_labels_present(self):
        rows = sp.step_breakdown(self.SPANS, ("daso.step",))
        d = sp.overlap_delta(rows)
        assert d["daso.step"]["monolithic"] == 0.8
        assert d["daso.step"]["bucketed"] == 0.95
        text = sp.render(rows)
        # the pre-existing marker format is untouched
        assert "STEP-OVERLAP kind=daso.step steps=4 overlap=" in text
        assert (
            "STEP-OVERLAP-DELTA kind=daso.step "
            "monolithic=0.800 bucketed=0.950 delta=+0.150" in text
        )

    def test_no_delta_line_for_single_label(self):
        rows = sp.step_breakdown(self.SPANS[:4], ("daso.step",))
        assert sp.overlap_delta(rows) == {}
        assert "STEP-OVERLAP-DELTA" not in sp.render(rows)

    def test_unlabeled_steps_do_not_fabricate_a_comparison(self):
        spans = [
            _span("daso.step", 0.0, 0.1),
            _span("comm.Wait.wait", 0.05, 0.02, depth=1),
            _span("daso.step", 0.1, 0.1, attrs={"sync": "bucketed"}),
        ]
        rows = sp.step_breakdown(spans, ("daso.step",))
        assert sp.overlap_delta(rows) == {}

    def test_delta_rides_the_cli(self, tmp_path, capsys):
        d = str(tmp_path)
        with open(os.path.join(d, "rank0.jsonl"), "w") as fh:
            for rec in self.SPANS:
                fh.write(json.dumps(rec) + "\n")
        assert sp.main([d]) == 0
        out = capsys.readouterr().out
        assert "STEP-OVERLAP-DELTA kind=daso.step" in out


class TestCLI:
    def test_main_end_to_end(self, tmp_path, capsys):
        d = str(tmp_path)
        with open(os.path.join(d, "rank0.jsonl"), "w") as fh:
            for rec in TestBreakdown.SPANS:
                fh.write(json.dumps(rec) + "\n")
        out_json = str(tmp_path / "steps.json")
        assert sp.main([d, "--per-step", "5", "--json", out_json]) == 0
        out = capsys.readouterr().out
        assert "STEP-OVERLAP kind=daso.step" in out
        payload = json.load(open(out_json))
        assert len(payload["steps"]) == 2 and payload["aggregate"]

    def test_main_no_files_exits_1(self, tmp_path, capsys):
        assert sp.main([str(tmp_path / "void")]) == 1

    def test_main_no_step_spans_exits_0(self, tmp_path, capsys):
        with open(os.path.join(str(tmp_path), "rank0.jsonl"), "w") as fh:
            fh.write(json.dumps(_span("dispatch.binary", 0, 0.1)) + "\n")
        assert sp.main([str(tmp_path)]) == 0
        assert "no step spans" in capsys.readouterr().out


class TestReportIntegration:
    def test_overlap_section_rides_the_merged_report(self, tmp_path, capsys):
        d = str(tmp_path)
        with open(os.path.join(d, "rank0.jsonl"), "w") as fh:
            for rec in TestBreakdown.SPANS:
                fh.write(json.dumps(rec) + "\n")
        assert trep.main([d, "--timeline", "0"]) == 0
        out = capsys.readouterr().out
        assert "step-time breakdown" in out
        assert "STEP-OVERLAP kind=daso.step" in out

    def test_trace_timeline_across_spans_and_journal(self, tmp_path, capsys):
        """--trace assembles one id's records from BOTH the telemetry
        spans and a scheduler journal into one time-ordered table."""
        d = str(tmp_path)
        tid = "feedface00000001"
        with open(os.path.join(d, "rank0.jsonl"), "w") as fh:
            fh.write(json.dumps(_span(
                "sched.job", 100.0, 0.05,
                attrs={"trace_id": tid, "kind": "matmul", "outcome": "done"},
            )) + "\n")
            fh.write(json.dumps(_span("unrelated", 100.1, 0.01)) + "\n")
        sched = _load("sched_for_trace", "heat_tpu/parallel/scheduler.py")
        j = sched.JobJournal(os.path.join(d, "sched_journal.jsonl"))
        j.append({"type": "submitted", "id": "j1", "tid": tid, "t": 99.9})
        j.append({"type": "done", "id": "j1", "tid": tid, "t": 100.1})
        j.append({"type": "submitted", "id": "other",
                  "tid": "0000000000000000", "t": 99.95})
        assert trep.main([d, "--trace", tid]) == 0
        out = capsys.readouterr().out
        assert f"causal timeline for trace {tid}" in out
        assert "submitted id=j1" in out and "done id=j1" in out
        assert "span sched.job" in out
        assert "other" not in out and "unrelated" not in out
        # ordered: the journal submit precedes the span
        assert out.index("submitted id=j1") < out.index("span sched.job")

    def test_trace_timeline_reads_flight_rings(self, tmp_path, capsys):
        d = str(tmp_path)
        tid = "feedface00000002"
        fr = _load("flightrec_for_trace", "heat_tpu/utils/flightrec.py")
        rec = fr.FlightRecorder(os.path.join(d, "flight_rank0.ring"), rank=0)
        rec.record("coll", seq=1, op="resplit", wire=1024, tid=tid)
        rec.record("coll", seq=2, op="resplit", wire=1024)  # untraced
        rec.record("job", id="j1", state="done", tid=tid)
        rec.close()
        assert trep.main([d, "--trace", tid]) == 0
        out = capsys.readouterr().out
        assert "collective seq=1 op=resplit wire=1024B" in out
        assert "seq=2" not in out
        assert "job id=j1 state=done" in out

    def test_trace_unknown_id_says_so(self, tmp_path, capsys):
        assert trep.main([str(tmp_path), "--trace", "deadbeef00000000"]) == 0
        assert "no records found" in capsys.readouterr().out
