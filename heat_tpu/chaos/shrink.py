"""Auto-shrinking of failing fault schedules to minimal reproducers.

Greedy delta-debugging over a fixed candidate order — fully
deterministic, no randomness anywhere: (1) drop each fault in turn (a
1-fault schedule is already positionally minimal); (2) lower every
trigger to its floor (fail→1, corrupt→1, exit→2, hang→1, delay→0.02);
(3) collapse to one rank, re-pinning fault victims to rank 0; (4) shrink
the job count to the generator's floor.  A candidate is accepted iff the
run STILL fails **the same oracle** — failing differently is a different
bug, and chasing it would make the reproducer lie about what it
reproduces.  The accepted minimum is re-confirmed twice before it is
allowed to call itself a reproducer (a flaky minimum is worse than a fat
one).

The output rides a ``CHAOS-REPRO`` line (see :func:`schedule.repro_line`)
with the ready-to-run ``HEAT_TPU_FAULTS`` strings inline.
"""

from __future__ import annotations

import copy
import importlib.util
import os
import sys
from typing import Callable, List, Optional, Tuple

__all__ = ["shrink", "candidates"]


def _schedule_mod():
    if __package__:
        from . import schedule as s
        return s
    for name in ("heat_chaos_schedule",):
        if name in sys.modules:
            return sys.modules[name]
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "schedule.py")
    spec = importlib.util.spec_from_file_location("heat_chaos_schedule", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


# trigger floors per mode: the smallest value that still *means* the
# fault (0 firings would delete it, which step (1) already tries);
# exit's floor is 2 because trigger 1 kills the very first firing —
# before the workload has any state worth recovering, a strictly easier
# and therefore less faithful reproduction
_FLOORS = {"fail": 1, "corrupt": 1, "exit": 2, "hang": 1}
_DELAY_FLOOR = 0.02
_JOBS_FLOOR = 6


def candidates(schedule: dict) -> List[Tuple[str, dict]]:
    """The fixed-order shrink candidates for one step: each is a
    ``(description, schedule)`` strictly simpler than the input."""
    out: List[Tuple[str, dict]] = []
    faults = schedule.get("faults", ())
    # (1) drop each fault
    if len(faults) > 1:
        for i, f in enumerate(faults):
            c = copy.deepcopy(schedule)
            del c["faults"][i]
            out.append((f"drop {f['site']}:{f['mode']}", c))
    # (2) lower each trigger to its floor
    for i, f in enumerate(faults):
        floor = _DELAY_FLOOR if f["mode"] == "delay" else _FLOORS.get(f["mode"])
        if floor is not None and f["value"] > floor:
            c = copy.deepcopy(schedule)
            c["faults"][i]["value"] = floor
            out.append((f"floor {f['site']}:{f['mode']}={floor}", c))
    # (3) collapse to one rank (victims re-pinned to the survivor)
    if schedule.get("ranks", 1) > 1:
        c = copy.deepcopy(schedule)
        c["ranks"] = 1
        for f in c["faults"]:
            f["rank"] = 0
        out.append(("ranks->1", c))
    # (4) fewer jobs
    if schedule.get("jobs", _JOBS_FLOOR) > _JOBS_FLOOR:
        c = copy.deepcopy(schedule)
        c["jobs"] = _JOBS_FLOOR
        out.append((f"jobs->{_JOBS_FLOOR}", c))
    return out


def shrink(
    schedule: dict,
    run_fn: Callable[[dict], List[str]],
    *,
    confirm: int = 2,
    max_probes: int = 40,
    log: Callable[[str], None] = lambda s: None,
) -> Tuple[dict, str]:
    """Minimize ``schedule`` while ``run_fn`` keeps reporting the same
    first failing oracle.

    ``run_fn(schedule) -> [failing oracle names]`` (empty = run passed).
    Returns ``(minimal_schedule, failing_oracle)``; the minimum has been
    re-confirmed ``confirm`` extra times.  If the ORIGINAL schedule does
    not fail under ``run_fn`` (a flake the campaign caught but the probe
    cannot reproduce), ValueError — a reproducer that does not reproduce
    must never be printed.
    """
    sched_mod = _schedule_mod()
    probes = 0

    def probe(s: dict) -> List[str]:
        nonlocal probes
        probes += 1
        return run_fn(s)

    fails = probe(schedule)
    if not fails:
        raise ValueError(
            "schedule does not fail under the probe — refusing to emit a "
            "non-reproducing reproducer"
        )
    target = fails[0]
    current = copy.deepcopy(schedule)
    improved = True
    while improved and probes < max_probes:
        improved = False
        for desc, cand in candidates(current):
            if probes >= max_probes:
                break
            sched_mod.validate_schedule(cand)
            got = probe(cand)
            if got and got[0] == target:
                log(f"CHAOS-SHRINK accept {desc} (still fails {target})")
                current = cand
                improved = True
                break  # restart candidate enumeration from the new minimum
    for _ in range(int(confirm)):
        got = probe(current)
        if not got or got[0] != target:
            raise ValueError(
                f"shrunk schedule is flaky: expected {target}, got {got} on "
                "re-confirmation — keeping it would print a lying reproducer"
            )
    log(
        f"CHAOS-SHRINK minimal faults={len(current.get('faults', ()))} "
        f"probes={probes} fail={target}"
    )
    return current, target
