"""Ring attention / sequence-parallel tests."""

# assert_distributed exception (r4 #8): ring attention operates on raw jax
# arrays (not DNDarrays); distribution is asserted directly via
# sharding.device_set and compiled-HLO collective-permute checks below.

import numpy as np
import pytest

import heat_tpu as ht

# long-tail contract tests: nightly-style lane (CI 'test' matrix), excluded
# from the PR smoke lane (VERDICT r4 weak #7)
pytestmark = pytest.mark.heavy


def _oracle(q, k, v, causal):
    S, d = q.shape
    s = (q @ k.T) / np.sqrt(d)
    if causal:
        s = np.where(np.tril(np.ones((S, S), bool)), s, -np.inf)
    p = np.exp(s - s.max(1, keepdims=True))
    p /= p.sum(1, keepdims=True)
    return p @ v


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        S, d = 64, 16
        q = rng.normal(size=(S, d)).astype(np.float32)
        k = rng.normal(size=(S, d)).astype(np.float32)
        v = rng.normal(size=(S, d)).astype(np.float32)
        comm = ht.communication.get_comm()
        out = ht.parallel.ring_self_attention(
            comm.shard(jnp.asarray(q), 0),
            comm.shard(jnp.asarray(k), 0),
            comm.shard(jnp.asarray(v), 0),
            comm,
            causal=causal,
        )
        np.testing.assert_allclose(np.asarray(out), _oracle(q, k, v, causal), atol=2e-3)

    @pytest.mark.parametrize("causal", [False, True])
    def test_ragged_rides_the_ring(self, causal):
        """Round-3 verdict weak #2: S % p != 0 must stay sequence-parallel
        (pad-and-mask on the ring), not fall back to the global quadratic
        path.  Prime S, counter-asserted."""
        import jax.numpy as jnp

        import importlib

        ra = importlib.import_module("heat_tpu.parallel.ring_attention")

        rng = np.random.default_rng(1)
        S, d = 101, 8  # prime: not divisible by any mesh size > 1
        q = rng.normal(size=(S, d)).astype(np.float32)
        k = rng.normal(size=(S, d)).astype(np.float32)
        v = rng.normal(size=(S, d)).astype(np.float32)
        comm = ht.communication.get_comm()
        before = dict(ra.path_counts)
        out = ht.parallel.ring_self_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), comm, causal=causal
        )
        if comm.is_distributed():
            assert ra.path_counts["ring"] == before["ring"] + 1
            assert ra.path_counts["global"] == before["global"]
        np.testing.assert_allclose(np.asarray(out), _oracle(q, k, v, causal), atol=2e-3)
        assert out.shape == (S, d)

    def test_ragged_ring_emits_collective_permute(self):
        """The compiled HLO for a prime-length sequence contains the ring's
        collective-permute — proof the ragged path is on the ring, not just
        numerically right."""
        import jax
        import jax.numpy as jnp

        comm = ht.communication.get_comm()
        if not comm.is_distributed():
            pytest.skip("single device: no ring")
        S, d = 101, 8
        rng = np.random.default_rng(2)
        q = jnp.asarray(rng.normal(size=(S, d)).astype(np.float32))
        fn = jax.jit(lambda a: ht.parallel.ring_self_attention(a, a, a, comm))
        hlo = fn.lower(q).compile().as_text()
        assert "collective-permute" in hlo


class TestRingAttentionGrad:
    """Sequence-parallel TRAINING: the ring path is differentiable (autodiff
    through shard_map + ppermute + scan) and its gradients match the dense
    reference — divisible and ragged sequence lengths."""

    @pytest.mark.parametrize("S", [32, 37])
    def test_grad_matches_dense(self, S):
        import jax
        import jax.numpy as jnp

        from heat_tpu.parallel.ring_attention import (
            _global_attention, ring_attention,
        )

        comm = ht.communication.get_comm()
        rng = np.random.default_rng(S)
        B, H, d = 2, 2, 8
        q, k, v = (jnp.asarray(rng.normal(size=(B, H, S, d)), jnp.float32)
                   for _ in range(3))
        w = jnp.asarray(rng.normal(size=(B, H, S, d)), jnp.float32)
        gr = jax.grad(
            lambda q, k, v: jnp.sum(ring_attention(q, k, v, comm, causal=True) * w),
            argnums=(0, 1, 2),
        )(q, k, v)
        gd = jax.grad(
            lambda q, k, v: jnp.sum(_global_attention(q, k, v, True, d**-0.5) * w),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(gr, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


class TestFlashRing:
    """The flash-kernel ring: each ring step runs ``flash_attention_block``
    (Pallas on TPU; here the interpreter) over its visiting K/V block, and
    blocks merge across steps via their logsumexp.  ``kernel='flash'``
    forces the kernel path so CPU CI actually executes the kernel body —
    sizes stay tiny because the interpreter is slow."""

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("S", [24, 29])  # divisible-by-8 and ragged
    def test_matches_dense(self, S, causal):
        import jax.numpy as jnp

        from heat_tpu.parallel.ring_attention import ring_attention

        comm = ht.communication.get_comm()
        rng = np.random.default_rng(S)
        q, k, v = (jnp.asarray(rng.normal(size=(2, S, 8)), jnp.float32)
                   for _ in range(3))
        out = ring_attention(q, k, v, comm, causal=causal, kernel="flash")
        ref = np.stack([_oracle(*map(np.asarray, (q[i], k[i], v[i])), causal)
                        for i in range(2)])
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)

    def test_grad_matches_dense(self):
        """Training through the kernel ring: the custom-VJP block (backward
        Pallas kernels + the lse cotangent folded into the dd row term)
        composes with scan/ppermute autodiff."""
        import jax
        import jax.numpy as jnp

        from heat_tpu.parallel.ring_attention import (
            _global_attention, ring_attention,
        )

        comm = ht.communication.get_comm()
        rng = np.random.default_rng(7)
        S, d = 24, 8
        q, k, v, w = (jnp.asarray(rng.normal(size=(2, S, d)), jnp.float32)
                      for _ in range(4))
        gr = jax.grad(
            lambda q, k, v: jnp.sum(
                ring_attention(q, k, v, comm, causal=True, kernel="flash") * w),
            argnums=(0, 1, 2),
        )(q, k, v)
        gd = jax.grad(
            lambda q, k, v: jnp.sum(_global_attention(q, k, v, True, d**-0.5) * w),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(gr, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("kern", ["dense", "flash"])
    def test_very_negative_scores_survive_merge(self, kern):
        """Regression: rows whose TRUE logsumexp is below ~-62 must not be
        crushed by masked blocks' no-mass sentinel in the cross-step merge
        (softmax is shift-invariant — the output is a well-defined average
        regardless of the absolute score level)."""
        import jax.numpy as jnp

        from heat_tpu.parallel.ring_attention import ring_attention

        comm = ht.communication.get_comm()
        rng = np.random.default_rng(11)
        S, d = 24, 8
        # anticorrelated q/k: every score ≈ -a² · scale ≈ -90
        a = 30.0
        q = jnp.full((1, S, d), a / np.sqrt(d), jnp.float32)
        k = -q
        v = jnp.asarray(rng.normal(size=(1, S, d)), jnp.float32)
        out = ring_attention(q, k, v, comm, causal=True, kernel=kern)
        ref = np.stack([_oracle(*map(np.asarray, (q[0], k[0], v[0])), True)])
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)

    def test_block_merge_identity(self):
        """flash_attention_block's contract: attending two disjoint key sets
        and merging via logsumexp equals attending their union."""
        import jax.numpy as jnp

        from heat_tpu.ops.flash_attention import (
            _dense_block_pos, flash_attention_block,
        )

        rng = np.random.default_rng(3)
        S, d = 16, 8
        q, k, v = (jnp.asarray(rng.normal(size=(S, d)), jnp.float32)
                   for _ in range(3))
        pos = jnp.arange(S, dtype=jnp.int32)
        full, _ = _dense_block_pos(q, k, v, pos, pos, True, 0.5, S, True)
        o1, l1 = flash_attention_block(
            q, k[:8], v[:8], pos, pos[:8],
            causal=True, scale=0.5, s_valid=S, impl="interpret")
        o2, l2 = flash_attention_block(
            q, k[8:], v[8:], pos, pos[8:],
            causal=True, scale=0.5, s_valid=S, impl="interpret")
        lse = jnp.logaddexp(l1, l2)
        merged = (o1 * jnp.exp(l1 - lse)[..., None]
                  + o2 * jnp.exp(l2 - lse)[..., None])
        np.testing.assert_allclose(np.asarray(merged), np.asarray(full),
                                   atol=2e-6)


class TestCrossRingAttention:
    """Sequence-parallel CROSS-attention: q keeps its resident block while a
    differently-sized kv sequence rotates.  Rectangular causal keeps the
    top-left-aligned convention (query at global i attends keys <= i)."""

    def _ref(self, q, k, v, causal):
        Sq, Sk = q.shape[-2], k.shape[-2]
        s = np.einsum("...qd,...kd->...qk", q, k) / np.sqrt(q.shape[-1])
        if causal:
            mask = np.arange(Sq)[:, None] >= np.arange(Sk)[None, :]
            s = np.where(mask, s, -np.inf)
        alive = np.isfinite(s).any(-1, keepdims=True)
        s = np.where(alive, s, 0.0)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        p = np.where(alive, p, 0.0)
        return np.einsum("...qk,...kd->...qd", p, v)

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("shapes", [(40, 24), (37, 53)])  # ragged both ways
    def test_matches_dense(self, shapes, causal):
        import importlib

        import jax.numpy as jnp

        ra = importlib.import_module("heat_tpu.parallel.ring_attention")
        comm = ht.communication.get_comm()
        Sq, Sk = shapes
        rng = np.random.default_rng(Sq)
        q = jnp.asarray(rng.normal(size=(2, Sq, 8)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(2, Sk, 8)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(2, Sk, 8)), jnp.float32)
        before = dict(ra.path_counts)
        out = ra.ring_attention(q, k, v, comm, causal=causal)
        if comm.is_distributed():
            assert ra.path_counts["ring"] == before["ring"] + 1
        np.testing.assert_allclose(
            np.asarray(out),
            self._ref(*map(np.asarray, (q, k, v)), causal),
            atol=2e-5,
        )

    def test_flash_kernel_and_grads(self):
        import jax
        import jax.numpy as jnp

        from heat_tpu.parallel.ring_attention import (
            _global_attention, ring_attention,
        )

        comm = ht.communication.get_comm()
        rng = np.random.default_rng(5)
        Sq, Sk, d = 24, 16, 8
        q = jnp.asarray(rng.normal(size=(2, Sq, d)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(2, Sk, d)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(2, Sk, d)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(2, Sq, d)), jnp.float32)
        out = ring_attention(q, k, v, comm, kernel="flash")
        ref = _global_attention(q, k, v, False, d**-0.5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
        g = jax.grad(
            lambda q, k, v: jnp.sum(
                ring_attention(q, k, v, comm, kernel="flash") * w),
            (0, 1, 2))(q, k, v)
        gr = jax.grad(
            lambda q, k, v: jnp.sum(_global_attention(q, k, v, False, d**-0.5) * w),
            (0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_mha_cross_rides_the_ring(self):
        import importlib

        import jax
        import jax.numpy as jnp

        ra = importlib.import_module("heat_tpu.parallel.ring_attention")
        comm = ht.communication.get_comm()
        mha = ht.nn.MultiheadAttention(16, 2, comm=comm)
        params = mha.init(jax.random.key(0))
        rng = np.random.default_rng(9)
        x = jnp.asarray(rng.normal(size=(2, 40, 16)), jnp.float32)
        kv = jnp.asarray(rng.normal(size=(2, 24, 16)), jnp.float32)
        before = dict(ra.path_counts)
        y = mha.apply(params, x, kv=kv)
        counted = ra.path_counts["ring" if comm.is_distributed() else "global"]
        assert counted == before["ring" if comm.is_distributed() else "global"] + 1
        assert len(y.sharding.device_set) == comm.size
        y0 = ht.nn.MultiheadAttention(16, 2).apply(params, x, kv=kv)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y0), atol=2e-5)


class TestBatchedRingAttention:
    """(..., S, d) ring attention: batch/head axes broadcast through the
    flash accumulation; sequence axis stays sharded over the ring."""

    def _ref(self, q, k, v, causal):
        S = q.shape[-2]
        s = np.einsum("...qd,...kd->...qk", q, k) / np.sqrt(q.shape[-1])
        if causal:
            mask = np.tril(np.ones((S, S), bool))
            s = np.where(mask, s, -np.inf)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        return np.einsum("...qk,...kd->...qd", p, v)

    @pytest.mark.parametrize("lead", [(), (3,), (2, 4)])
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, lead, causal):
        import jax
        import jax.numpy as jnp
        from heat_tpu.parallel.ring_attention import ring_attention

        comm = ht.communication.get_comm()
        # S scales with the ACTUAL mesh so the ring path engages at any
        # device count (non-divisible S falls back to the dense path by
        # design, which would make the sharding assertion meaningless)
        shape = (*lead, 8 * comm.size, 8)
        rng = np.random.default_rng(1)
        q, k, v = (rng.standard_normal(shape).astype(np.float32) for _ in range(3))
        seq_ax = len(shape) - 2
        jq, jk, jv = (comm.shard(jnp.asarray(t), seq_ax) for t in (q, k, v))
        out = jax.jit(lambda a, b, c: ring_attention(a, b, c, comm, causal=causal))(jq, jk, jv)
        np.testing.assert_allclose(np.asarray(out), self._ref(q, k, v, causal), rtol=2e-3, atol=2e-4)
        # the output stays sequence-sharded over the full ring
        assert len(out.sharding.device_set) == comm.size
