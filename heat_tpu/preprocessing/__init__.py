"""Preprocessing scalers (reference: ``heat/preprocessing/``)."""

from .preprocessing import StandardScaler, MinMaxScaler, MaxAbsScaler, RobustScaler, Normalizer
