"""Sparse elementwise ops (reference: ``heat/sparse/arithmetics.py``)."""

from __future__ import annotations

import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core import types
from .dcsr_matrix import DCSR_matrix

__all__ = ["add", "mul"]


def _binary(t1: DCSR_matrix, t2: DCSR_matrix, densify_op=None) -> DCSR_matrix:
    """``densify_op=None`` → native sparse+sparse add; otherwise the
    elementwise op runs fused-dense then re-sparsifies (one fused TPU kernel)."""
    if not isinstance(t1, DCSR_matrix) or not isinstance(t2, DCSR_matrix):
        raise TypeError("sparse binary ops require DCSR_matrix operands")
    if t1.shape != t2.shape:
        raise ValueError(f"shapes {t1.shape} and {t2.shape} do not match")
    if densify_op is None:
        res = jsparse.bcoo_sum_duplicates((t1.larray + t2.larray))
    else:
        dense = densify_op(t1.larray.todense(), t2.larray.todense())
        res = jsparse.BCOO.fromdense(dense)
    dt = types.canonical_heat_type(res.data.dtype)
    return DCSR_matrix(res, int(res.nse), t1.shape, dt, t1.split, t1.device, t1.comm, True)


def add(t1: DCSR_matrix, t2: DCSR_matrix) -> DCSR_matrix:
    """Elementwise sparse + sparse."""
    return _binary(t1, t2)


def mul(t1: DCSR_matrix, t2: DCSR_matrix) -> DCSR_matrix:
    """Elementwise sparse * sparse (intersection of patterns)."""
    return _binary(t1, t2, jnp.multiply)


DCSR_matrix.__add__ = add
DCSR_matrix.__mul__ = mul
