"""Halo exchange primitive (reference skeleton: ``DNDarray.get_halo`` +
``heat/core/signal.py::convolve``).

Each shard receives ``halo_size`` boundary elements from both neighbors along
the split axis (``lax.ppermute`` neighbor shifts over the ICI ring) and the
caller computes on interior+halo — the stencil/context-parallel skeleton.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax import lax

from ..core._cache import comm_cached

__all__ = ["halo_exchange", "with_halos"]


def _take(arr, axis, start, stop):
    idx = [slice(None)] * arr.ndim
    idx[axis] = slice(start, stop)
    return arr[tuple(idx)]


def halo_exchange(block: jax.Array, halo_size: int, axis_name: str, size: int, split_axis: int = 0):
    """Inside shard_map: return (halo_prev, halo_next) for this shard.

    ``halo_prev`` is the last ``halo_size`` slice of the left neighbor (zeros
    on shard 0), ``halo_next`` the first slice of the right neighbor (zeros on
    the last shard) — matching the reference's boundary semantics.
    """
    tail = _take(block, split_axis, block.shape[split_axis] - halo_size, block.shape[split_axis])
    head = _take(block, split_axis, 0, halo_size)
    # send tail to right neighbor: j -> j+1 (shard 0 receives zeros)
    halo_prev = lax.ppermute(tail, axis_name, [(j, j + 1) for j in range(size - 1)])
    # send head to left neighbor: j -> j-1 (last shard receives zeros)
    halo_next = lax.ppermute(head, axis_name, [(j, j - 1) for j in range(1, size)])
    return halo_prev, halo_next


def with_halos(array: jax.Array, halo_size: int, split_axis: int, comm) -> jax.Array:
    """Global array → per-shard blocks extended with neighbor halos, returned
    as a global array of shape ``gshape + 2*halo*size`` along ``split_axis``
    (each shard's slab is ``[halo_prev | local | halo_next]``)."""
    return _with_halos_program(comm, halo_size, split_axis, array.ndim)(array)


@comm_cached
def _with_halos_program(comm, halo_size: int, split_axis: int, nd: int):
    """Jitted + comm-cached (eager repeat calls reuse the compiled program)."""
    axis = comm.axis
    size = comm.size

    def shard_fn(blk):
        prev, nxt = halo_exchange(blk, halo_size, axis, size, split_axis)
        return jnp.concatenate([prev, blk, nxt], axis=split_axis)

    return jax.jit(comm.shard_map(
        shard_fn, in_splits=((nd, split_axis),), out_splits=(nd, split_axis)
    ))
