"""FFT namespace (reference: ``heat/fft/fft.py``).

The reference's rule: transforms along non-split dims are local; a transform
hitting the split axis resplits to move it local, transforms, and resplits
back ("transpose method", SURVEY §2.2).  Round 4 makes that explicit here
too: when the transform hits the split axis and another (divisible) axis
can carry the shard, the call resplits → transforms locally → resplits back
(two all_to_alls, O(n/p) per-device memory); otherwise the global form runs
and GSPMD derives the data movement.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core import types
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in

__all__ = [
    "fft", "fft2", "fftn", "fftfreq", "fftshift",
    "hfft", "hfft2", "hfftn",
    "ifft", "ifft2", "ifftn", "ifftshift", "ihfft", "ihfft2", "ihfftn",
    "irfft", "irfft2", "irfftn",
    "rfft", "rfft2", "rfftfreq", "rfftn",
]


def _wrap(jarr, split, proto: DNDarray) -> DNDarray:
    if split is not None and split >= jarr.ndim:
        split = None
    jarr = proto.comm.shard(jarr, split)
    return DNDarray(
        jarr, tuple(jarr.shape), types.canonical_heat_type(jarr.dtype), split, proto.device, proto.comm, True
    )


def _fft_in(x: DNDarray):
    """FFT compute involves complex intermediates for every transform; on
    transports without native complex the whole transform runs on the host
    backend (real results migrate back at the next placement)."""
    from ..core import _complexsafe

    if _complexsafe.native_complex_supported():
        return x._jarray
    return _complexsafe.to_host_backend(x._jarray)


# eager routing counters (tests assert the transpose method engages)
fft_paths = {"transpose": 0, "direct": 0}


def _transpose_axis(x: DNDarray, busy_axes) -> Optional[int]:
    """A reshard target for the explicit transpose method — the shared
    ``manipulations.reshard_axis_for`` rule, plus FFT's extra gates: the
    transform must actually hit the split axis, and hosted-complex mode is
    excluded (host arrays have no mesh placement to preserve)."""
    if x.split not in busy_axes:
        return None
    from ..core import _complexsafe

    if not _complexsafe.native_complex_supported():
        return None
    from ..core.manipulations import reshard_axis_for

    return reshard_axis_for(x, busy_axes)


def _fft_op(op_name: str, x: DNDarray, n=None, axis=-1, norm=None) -> DNDarray:
    sanitize_in(x)
    op = getattr(jnp.fft, op_name)
    axis_n = axis % max(x.ndim, 1)
    t = _transpose_axis(x, {axis_n})
    if t is not None:
        # the reference's transpose method made explicit: resplit so the
        # transform axis is local, transform (other axes stay sharded),
        # resplit back — two all_to_alls, never a gather
        from ..core.manipulations import resplit

        fft_paths["transpose"] += 1
        xr = resplit(x, t)
        res = op(xr._jarray, n=n, axis=axis, norm=norm)
        return resplit(_wrap(res, t, x), x.split)
    fft_paths["direct"] += 1
    res = op(_fft_in(x), n=n, axis=axis, norm=norm)
    return _wrap(res, x.split, x)


def _fftn_op(op_name: str, x: DNDarray, s=None, axes=None, norm=None) -> DNDarray:
    sanitize_in(x)
    op = getattr(jnp.fft, op_name)
    if axes is not None:
        busy = {a % x.ndim for a in (axes if isinstance(axes, (tuple, list)) else (axes,))}
    elif s is not None:
        # numpy rule: with s given and axes omitted, only the LAST len(s)
        # axes are transformed — the earlier axes are valid reshard targets
        busy = set(range(x.ndim - len(s), x.ndim))
    else:
        busy = set(range(x.ndim))
    t = _transpose_axis(x, busy)
    if t is not None:
        from ..core.manipulations import resplit

        fft_paths["transpose"] += 1
        xr = resplit(x, t)
        res = op(xr._jarray, s=s, axes=axes, norm=norm)
        return resplit(_wrap(res, t, x), x.split)
    fft_paths["direct"] += 1
    res = op(_fft_in(x), s=s, axes=axes, norm=norm)
    return _wrap(res, x.split, x)


def fft(x, n=None, axis=-1, norm=None) -> DNDarray:
    """1-D discrete Fourier transform along ``axis``."""
    return _fft_op("fft", x, n=n, axis=axis, norm=norm)


def ifft(x, n=None, axis=-1, norm=None) -> DNDarray:
    return _fft_op("ifft", x, n=n, axis=axis, norm=norm)


def rfft(x, n=None, axis=-1, norm=None) -> DNDarray:
    return _fft_op("rfft", x, n=n, axis=axis, norm=norm)


def irfft(x, n=None, axis=-1, norm=None) -> DNDarray:
    return _fft_op("irfft", x, n=n, axis=axis, norm=norm)


def hfft(x, n=None, axis=-1, norm=None) -> DNDarray:
    return _fft_op("hfft", x, n=n, axis=axis, norm=norm)


def ihfft(x, n=None, axis=-1, norm=None) -> DNDarray:
    return _fft_op("ihfft", x, n=n, axis=axis, norm=norm)


def fft2(x, s=None, axes=(-2, -1), norm=None) -> DNDarray:
    return _fftn_op("fft2", x, s=s, axes=axes, norm=norm)


def ifft2(x, s=None, axes=(-2, -1), norm=None) -> DNDarray:
    return _fftn_op("ifft2", x, s=s, axes=axes, norm=norm)


def rfft2(x, s=None, axes=(-2, -1), norm=None) -> DNDarray:
    return _fftn_op("rfft2", x, s=s, axes=axes, norm=norm)


def irfft2(x, s=None, axes=(-2, -1), norm=None) -> DNDarray:
    return _fftn_op("irfft2", x, s=s, axes=axes, norm=norm)


def _hfftn_op(x: DNDarray, s, axes, norm, inverse: bool) -> DNDarray:
    """Hermitian n-D transforms composed per axis (reference inherits them
    whole from ``torch.fft.hfftn``/``ihfftn``; ``jnp.fft`` has only the 1-D
    forms).  The transforms are separable, so the one-sided Hermitian axis
    — the LAST of ``axes``, the torch convention — gets ``hfft``/``ihfft``
    and every other axis gets a plain ``fft``/``ifft``; each 1-D transform
    carries its own norm factor, so any ``norm`` composes exactly.  For
    ``ihfftn`` the real input must hit ``ihfft`` first; for ``hfftn`` the
    full-size axes are transformed first so the last axis stays one-sided
    until the end.  Split handling matches ``_fftn_op``: resplit off a busy
    split axis when a divisible axis can carry the shard, else direct."""
    sanitize_in(x)
    nd = max(x.ndim, 1)
    if axes is None:
        axes = tuple(range(nd)) if s is None else tuple(range(nd - len(s), nd))
    elif not isinstance(axes, (tuple, list)):
        axes = (axes,)
    axes = tuple(a % nd for a in axes)
    if len(set(axes)) != len(axes):
        # also catches hfft2 defaults (-2, -1) aliasing on a 1-D input —
        # torch raises there too; a silent double transform would be wrong
        raise ValueError(f"axes must be unique, got {axes} on a {nd}-D array")
    if s is not None and len(s) != len(axes):
        raise ValueError(f"s and axes must have the same length, got {len(s)} != {len(axes)}")
    ss = list(s) if s is not None else [None] * len(axes)

    def run(arr):
        if inverse:
            arr = jnp.fft.ihfft(arr, n=ss[-1], axis=axes[-1], norm=norm)
            for a, n in zip(axes[:-1], ss[:-1]):
                arr = jnp.fft.ifft(arr, n=n, axis=a, norm=norm)
        else:
            for a, n in zip(axes[:-1], ss[:-1]):
                arr = jnp.fft.fft(arr, n=n, axis=a, norm=norm)
            arr = jnp.fft.hfft(arr, n=ss[-1], axis=axes[-1], norm=norm)
        return arr

    t = _transpose_axis(x, set(axes))
    if t is not None:
        from ..core.manipulations import resplit

        fft_paths["transpose"] += 1
        xr = resplit(x, t)
        return resplit(_wrap(run(xr._jarray), t, x), x.split)
    fft_paths["direct"] += 1
    return _wrap(run(_fft_in(x)), x.split, x)


def hfft2(x, s=None, axes=(-2, -1), norm=None) -> DNDarray:
    return _hfftn_op(x, s, axes, norm, inverse=False)


def ihfft2(x, s=None, axes=(-2, -1), norm=None) -> DNDarray:
    return _hfftn_op(x, s, axes, norm, inverse=True)


def fftn(x, s=None, axes=None, norm=None) -> DNDarray:
    return _fftn_op("fftn", x, s=s, axes=axes, norm=norm)


def ifftn(x, s=None, axes=None, norm=None) -> DNDarray:
    return _fftn_op("ifftn", x, s=s, axes=axes, norm=norm)


def rfftn(x, s=None, axes=None, norm=None) -> DNDarray:
    return _fftn_op("rfftn", x, s=s, axes=axes, norm=norm)


def irfftn(x, s=None, axes=None, norm=None) -> DNDarray:
    return _fftn_op("irfftn", x, s=s, axes=axes, norm=norm)


def hfftn(x, s=None, axes=None, norm=None) -> DNDarray:
    """n-D FFT of a Hermitian-symmetric (one-sided last axis) signal — real
    output.  torch.fft.hfftn semantics (the reference's source for it);
    composed per axis, see :func:`_hfftn_op`."""
    return _hfftn_op(x, s, axes, norm, inverse=False)


def ihfftn(x, s=None, axes=None, norm=None) -> DNDarray:
    """Inverse of :func:`hfftn`: real input, one-sided complex output."""
    return _hfftn_op(x, s, axes, norm, inverse=True)


def fftfreq(n: int, d: float = 1.0, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    from ..core import factories

    res = jnp.fft.fftfreq(n, d=d)
    return factories.array(res, dtype=dtype, split=split, device=device, comm=comm)


def rfftfreq(n: int, d: float = 1.0, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    from ..core import factories

    res = jnp.fft.rfftfreq(n, d=d)
    return factories.array(res, dtype=dtype, split=split, device=device, comm=comm)


def fftshift(x, axes=None) -> DNDarray:
    sanitize_in(x)
    return _wrap(jnp.fft.fftshift(x._jarray, axes=axes), x.split, x)


def ifftshift(x, axes=None) -> DNDarray:
    sanitize_in(x)
    return _wrap(jnp.fft.ifftshift(x._jarray, axes=axes), x.split, x)
