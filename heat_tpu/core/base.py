"""Estimator base API (reference: ``heat/core/base.py``).

sklearn-style ``fit``/``predict``/``transform`` contracts.  Estimators are
written purely in terms of the public array API, so they run identically on
1 chip or a pod — the same property the reference gets from SPMD/MPI.
"""

from __future__ import annotations

import inspect
from typing import Dict, List

__all__ = [
    "BaseEstimator",
    "ClassificationMixin",
    "ClusteringMixin",
    "RegressionMixin",
    "TransformMixin",
    "is_classifier",
    "is_estimator",
    "is_transformer",
]


class BaseEstimator:
    @classmethod
    def _parameter_names(cls) -> List[str]:
        init = cls.__init__
        if init is object.__init__:
            return []
        sig = inspect.signature(init)
        return [p.name for p in sig.parameters.values() if p.name != "self" and p.kind != p.VAR_KEYWORD]

    def get_params(self, deep: bool = True) -> Dict[str, object]:
        """Estimator hyper-parameters as a dict (sklearn contract)."""
        params = {}
        for key in self._parameter_names():
            value = getattr(self, key, None)
            if deep and hasattr(value, "get_params"):
                for sub_key, sub_value in value.get_params().items():
                    params[f"{key}__{sub_key}"] = sub_value
            params[key] = value
        return params

    def set_params(self, **params) -> "BaseEstimator":
        if not params:
            return self
        valid = self.get_params(deep=True)
        for key, value in params.items():
            key, delim, sub_key = key.partition("__")
            if key not in valid:
                raise ValueError(f"Invalid parameter {key} for estimator {self}")
            if delim:
                getattr(self, key).set_params(**{sub_key: value})
            else:
                setattr(self, key, value)
        return self

    def __repr__(self, indent: int = 1) -> str:
        params = {
            k: (v if not hasattr(v, "_jarray") else "DNDarray(...)") for k, v in self.get_params(deep=False).items()
        }
        return f"{self.__class__.__name__}({params})"


def clone(estimator: BaseEstimator) -> BaseEstimator:
    """A new unfitted estimator with the same hyper-parameters."""
    return estimator.__class__(**estimator.get_params(deep=False))


class ClassificationMixin:
    _estimator_type = "classifier"

    def fit(self, x, y):
        raise NotImplementedError()

    def fit_predict(self, x, y):
        self.fit(x, y)
        return self.predict(x)

    def predict(self, x):
        raise NotImplementedError()


class ClusteringMixin:
    _estimator_type = "clusterer"

    def fit(self, x):
        raise NotImplementedError()

    def fit_predict(self, x):
        self.fit(x)
        return self.predict(x)


class TransformMixin:
    def fit(self, x):
        raise NotImplementedError()

    def fit_transform(self, x):
        return self.fit(x).transform(x)

    def transform(self, x):
        raise NotImplementedError()


class RegressionMixin:
    _estimator_type = "regressor"

    def fit(self, x, y):
        raise NotImplementedError()

    def fit_predict(self, x, y):
        self.fit(x, y)
        return self.predict(x)

    def predict(self, x):
        raise NotImplementedError()


def is_classifier(estimator) -> bool:
    return getattr(estimator, "_estimator_type", None) == "classifier"


def is_estimator(estimator) -> bool:
    return isinstance(estimator, BaseEstimator)


def is_transformer(estimator) -> bool:
    return hasattr(estimator, "transform")
