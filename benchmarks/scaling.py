"""Strong-scaling sweep over device counts (reference: ``benchmarks/cb`` run
at several node counts on Jülich HPC; here the mesh width is the axis).

Each workload runs at 1, 2, 4, ... devices of the host platform and prints
one JSON line per (workload, n_devices) with wall-clock seconds, so scaling
regressions are visible in CI exactly like the reference's perun dashboards.

Run: python benchmarks/scaling.py [max_devices]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

WORKER = r"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, ".")  # launched with cwd = repo root
import numpy as _np
import heat_tpu as ht

n_dev = int(sys.argv[1])
from jax.sharding import Mesh
mesh = Mesh(_np.asarray(jax.devices()[:n_dev]), ("x",))
ht.use_mesh(mesh)

timed = ht.utils.profiler.timeit_min

results = {}
X = ht.random.randn(2**17, 32, split=0)
results["kmeans_131k_k16_5it"] = timed(
    lambda: ht.cluster.KMeans(n_clusters=16, max_iter=5, tol=0.0, init="random", random_state=0).fit(X).inertia_
)
a = ht.random.randn(1024, 1024, split=0)
b = ht.random.randn(1024, 1024, split=1)
results["matmul_1024_s0xs1"] = timed(lambda: a @ b)
m = ht.random.randn(1024, 1024, split=0)
results["resplit_1024_0to1"] = timed(lambda: m.resplit(1))
# round-4b: TSQR with the CholeskyQR2 local factorization (comm-cached
# program — warm timing measures factorization, not retrace)
ta = ht.random.randn(2**18, 64, split=0)
ht.linalg.qr(ta, mode="r").R  # compile
results["tsqr_262k_64_r"] = timed(lambda: ht.linalg.qr(ta, mode="r").R)
v = ht.random.randn(2**20, split=0)
results["sort_1M"] = timed(lambda: ht.sort(v, method="global")[0])
if n_dev >= 2:
    # round-4b: sequence-parallel exact attention — S/p per device, K/V on
    # the ppermute ring (the CPU mesh shows the algorithmic scaling; the
    # Pallas flash local path is TPU-only and A-B'd in bench.py)
    from heat_tpu.parallel.ring_attention import ring_attention
    import jax.numpy as _rjnp
    _rq = _rjnp.asarray(_np.random.default_rng(5).normal(size=(2, 4, 4096, 32)), _rjnp.float32)
    comm = ht.communication.get_comm()
    _rqs = comm.shard(_rq, 2)
    _ring = jax.jit(lambda t: ring_attention(t, t, t, comm, causal=True))
    _ring(_rqs)  # compile
    results["ring_attn_2x4x4096x32"] = timed(lambda: _ring(_rqs))

    # round-4d: expert parallelism (experts sharded, tokens through two
    # all_to_alls) and pipeline parallelism (GPipe microbatch schedule on
    # the ppermute ring) — per-step wall-clock as the mesh widens
    _moe = ht.nn.MoE(64, 2 * n_dev, hidden_dim=128, top_k=2, comm=comm)
    _mp = _moe.init(jax.random.key(0))
    _xm = _rjnp.asarray(_np.random.default_rng(6).normal(size=(8 * n_dev, 16, 64)), _rjnp.float32)
    _moe.apply(_mp, _xm)  # compile
    results["moe_ep_%dtok_e%d" % (_xm.shape[0] * 16, 2 * n_dev)] = timed(
        lambda: _moe.apply(_mp, _xm)
    )
    from heat_tpu.nn.models import _TransformerBlock as _TB
    _pp = ht.nn.Pipelined(_TB(64, 4, mlp_ratio=2, causal=True), depth=n_dev,
                          comm=comm, n_microbatches=min(4, n_dev))
    _ppp = _pp.init(jax.random.key(1))
    _xp = _rjnp.asarray(_np.random.default_rng(7).normal(size=(8, 32, 64)), _rjnp.float32)
    _pp.apply(_ppp, _xp)  # compile
    results["pipeline_%dstage_tfblock" % n_dev] = timed(lambda: _pp.apply(_ppp, _xp))

    # the static-shape sample sort (SURVEY hard part #3) vs the global sort:
    # same input, distributed path keeps O(n/p) memory per shard
    results["sample_sort_1M"] = timed(lambda: ht.sort(v, method="sample")[0])
    results["sample_sort_desc_1M"] = timed(lambda: ht.sort(v, method="sample", descending=True)[0])
    results["percentile_bisect_1M"] = timed(lambda: ht.percentile(v, 99.0))
    # round-4 distributed selection surface
    vi = ht.array(_np.random.default_rng(2).integers(0, 50_000, 2**20).astype(_np.int32), split=0)
    import heat_tpu.core.manipulations as _M
    _M._DIST_UNIQUE_THRESHOLD = 2**20  # engage the distributed path at this n
    results["unique_1M_int"] = timed(lambda: ht.unique(vi))
    sv = ht.sort(v, method="sample")[0]
    q = ht.array(_np.linspace(-3, 3, 1024).astype(_np.float32))
    results["searchsorted_1M_1k"] = timed(lambda: ht.searchsorted(sv, q))
    results["topk_largek_1M"] = timed(lambda: ht.topk(v, 2**18)[0])

# DASO vs sync DataParallel (reference's flagship comparison, SURVEY §2.5):
# identical MLP + batch; DASO pays a per-step ici-subgroup allreduce + every-k
# dcn parameter average, DataParallel a full-mesh gradient allreduce
if n_dev >= 2:
    import jax as _jax
    import jax.numpy as _jnp

    def _mlp():
        return ht.nn.Sequential(ht.nn.Linear(64, 128), ht.nn.ReLU(), ht.nn.Linear(128, 8))

    def _loss(pred, y):
        return _jnp.mean((pred - y) ** 2)

    xb = _np.random.default_rng(0).normal(size=(256, 64)).astype("float32")
    yb = _np.random.default_rng(1).normal(size=(256, 8)).astype("float32")

    dp = ht.nn.DataParallel(_mlp(), optimizer=ht.optim.DataParallelOptimizer("sgd", lr=0.01))
    dp.init(key=_jax.random.key(0))
    opt_state = dp.optimizer.init_state(dp.parameters)
    # donate=False: the timed reps call the step repeatedly with the SAME
    # params/opt_state trees — donation would delete them on the first call
    dp_step = dp.make_train_step(_loss, donate=False)
    jxb = dp.comm.shard(_jnp.asarray(xb), 0)
    jyb = dp.comm.shard(_jnp.asarray(yb), 0)
    dp_step(dp.parameters, opt_state, jxb, jyb)  # compile

    def _dp_once():
        p, s, l = dp_step(dp.parameters, opt_state, jxb, jyb)
        return l

    results["dp_mlp_step_256"] = timed(_dp_once)

    from jax.sharding import Mesh as _Mesh

    ici = 2
    daso_mesh = _Mesh(_np.asarray(_jax.devices()[:n_dev]).reshape(n_dev // ici, ici), ("dcn", "ici"))
    daso = ht.optim.DASO(
        ht.optim.DataParallelOptimizer("sgd", lr=0.01), mesh=daso_mesh,
        global_skip=4, warmup_steps=0,
    )
    daso.init(_mlp(), key=_jax.random.key(0))
    jdx, jdy = _jnp.asarray(xb), _jnp.asarray(yb)  # pre-place: time the step, not ingest
    daso.step(_loss, jdx, jdy)  # compile
    results["daso_mlp_step_256"] = timed(lambda: daso.step(_loss, jdx, jdy))

for k, v_ in results.items():
    print(json.dumps({"benchmark": k, "n_devices": n_dev, "seconds": round(v_, 5)}))
"""


def main() -> None:
    max_dev = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    counts = [c for c in (1, 2, 4, 8, 16) if c <= max_dev]
    here = os.path.dirname(os.path.abspath(__file__))
    for n in counts:
        env = dict(os.environ)
        base_flags = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = f"{base_flags} --xla_force_host_platform_device_count={n}".strip()
        try:
            out = subprocess.run(
                [sys.executable, "-c", WORKER, str(n)],
                capture_output=True,
                text=True,
                env=env,
                cwd=os.path.dirname(here),
                timeout=1200,
            )
        except subprocess.TimeoutExpired:
            print(json.dumps({"n_devices": n, "error": "worker timed out after 1200s"}))
            continue
        if out.returncode != 0:
            print(json.dumps({"n_devices": n, "error": out.stderr.strip()[-400:]}))
            continue
        for line in out.stdout.strip().splitlines():
            if line.startswith("{"):
                print(line)
    # provenance note rides WITH the data so regenerated artifacts keep it
    print(json.dumps({"note": (
        "strong-scaling sweep on virtual CPU mesh (host devices simulate "
        "chips; transport = shared memory, so collective-heavy ops like "
        "sort/resplit show CPU-mesh overhead, not ICI behavior). "
        "sort_1M = global XLA sort (gathers the axis; degrades with mesh "
        "width); sample_sort_1M = static-shape distributed sample sort "
        "(radix-selected exact splitters + one padded all_to_all; O(n/p) "
        "per shard — improves with mesh width); percentile_bisect_1M = "
        "exact order statistics, no sort. dp_mlp_step_256 = sync "
        "DataParallel step; daso_mlp_step_256 = hierarchical DASO step on "
        "an (n/2)x2 mesh. Full sweep re-recorded round 4d, 2026-07-31; "
        "round-4 rows: descending sample sort, distributed "
        "unique/searchsorted/large-k topk; round-4b rows: tsqr_262k_64_r "
        "(CholeskyQR2 local factorization, comm-cached program) and "
        "ring_attn_2x4x4096x32 (sequence-parallel exact attention, S/p per "
        "device — improves with mesh width even on the shared-memory "
        "mesh); round-4d rows: moe_ep_* (expert-parallel MoE forward, "
        "experts sharded, tokens through two all_to_alls; token count "
        "grows with the mesh so per-token work is constant) and "
        "pipeline_*stage_tfblock (GPipe schedule over n_dev transformer-"
        "block stages, fixed batch 8 x 32 x 64, n_microbatches "
        "min(4, n_dev) — wall-clock grows with depth=n_dev since the "
        "MODEL grows with the mesh; divide by stages for per-block cost). "
        "TPU single-chip "
        "numbers live in BENCH_r03.json (BENCH_r04.json once the driver records this round); multi-chip ICI "
        "scaling requires a pod (unavailable: one tunneled v5e chip)."
    )}))


if __name__ == "__main__":
    main()
