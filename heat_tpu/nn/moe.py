"""Mixture-of-Experts layer with expert parallelism.

Beyond-reference model family (the reference has no MoE or expert
parallelism — SURVEY §2.8 lists EP as absent), built the TPU way: the
token→expert dispatch and combine are dense einsums over a capacity-bounded
``(experts, capacity, d)`` buffer (static shapes, so the whole layer jits
and rides the MXU), and with ``comm=`` the experts are sharded over the
mesh while tokens travel through TWO ``all_to_all`` collectives — the
canonical expert-parallel data movement on ICI.

Routing is token-choice top-k with slot-priority capacity assignment: all
first choices claim capacity before any second choice, tokens in order
within a slot.  Selected gate weights are renormalized by their sum, and
tokens that overflow an expert's capacity are dropped from that expert
(contributing zero — the standard GShard/Switch overflow semantics).
Routing is deterministic: no jitter noise, so eval == train and results
are reproducible across device counts.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp

from .modules import Module
from ..core._cache import comm_cached

__all__ = ["MoE"]


@comm_cached(key=lambda moe: moe._program_key)
def _ep_program(comm, moe):
    """Compiled expert-parallel forward, cached ON the comm, keyed on the
    layer's *config tuple* (``MoE._program_key``) rather than its identity:
    the trace of ``_ep_fn`` depends only on that config (+ the comm, which
    owns the table), so identical-config layers share one executable and
    per-instance retention shrinks to one config representative (the first
    instance's bound method inside the compiled program; ADVICE r4).  jit's
    own cache handles shape/dtype variation.

    Token sharding: over the expert axis itself by default; with
    ``moe.batch_axis`` set the tokens shard over BOTH axes jointly (dp x ep)
    — within each dp slice this reduces to the pure-ep path over that
    slice's token shard, so there is no replicated expert compute, while
    the expert weights stay sharded over ep only (replicated over dp;
    their gradients psum over dp under GSPMD exactly like any replicated
    parameter)."""
    from jax.sharding import PartitionSpec as P

    tok = P((moe.batch_axis, comm.axis)) if moe.batch_axis else P(comm.axis)
    fn = comm.shard_map(
        moe._ep_fn,
        in_splits=(
            {"router": (2, None), "w1": (3, 0), "b1": (2, 0), "w2": (3, 0), "b2": (2, 0)},
            tok,
            tok,
        ),
        out_splits=tok,
    )
    return jax.jit(fn)


def _topk_gates(gates, top_k: int):
    """Top-k expert selection with sum-renormalized gate weights — THE
    routing rule, shared by the capacity path (:func:`_routing`) and the
    drop-free decode path (:meth:`MoE.decode_apply`) so the
    decode == teacher-forced contract can never drift between them."""
    val, idx = jax.lax.top_k(gates, top_k)  # (n, k)
    return val / (val.sum(axis=-1, keepdims=True) + 1e-9), idx


def _routing(gates, top_k: int, capacity: int):
    """Dispatch/combine tensors for token-choice top-k routing.

    gates: (n, E) softmax router probabilities.
    Returns ``dispatch`` (n, E, C) in {0,1} and ``combine`` (n, E, C)
    carrying the renormalized gate weight at each token's claimed slot.

    Capacity positions are claimed slot-major — every token's first choice
    is ranked before any token's second choice — so dropping under pressure
    removes the *weakest* assignments first.
    """
    n, E = gates.shape
    val, idx = _topk_gates(gates, top_k)

    # slot-major priority: position of (token i, slot j) in its expert's
    # capacity queue counts all slot-<j claims plus earlier tokens' slot-j
    onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)  # (n, k, E)
    # claims in priority order: reshape (k, n, E) then cumulative count.
    # zero-gate selections (masked pad tokens) must not occupy queue
    # positions, or a pad's phantom slot-0 claim evicts real tokens under
    # capacity pressure — mask them out of the queue entirely
    claims = jnp.moveaxis(onehot, 1, 0)  # (k, n, E)
    claims = claims * (jnp.moveaxis(val, 1, 0) > 0)[..., None]
    flat = claims.reshape(top_k * n, E)
    pos_flat = jnp.cumsum(flat, axis=0) - flat  # claims STRICTLY before ours
    pos = (pos_flat * flat).sum(axis=1).reshape(top_k, n)  # (k, n) queue position
    keep = (pos < capacity) & (jnp.moveaxis(val, 1, 0) > 0)

    pos = jnp.where(keep, pos, capacity)  # parked on an out-of-range slot
    slot = jax.nn.one_hot(pos, capacity, dtype=gates.dtype)  # (k, n, C)
    expert = jnp.moveaxis(onehot, 1, 0).astype(gates.dtype)  # (k, n, E)
    # (k,n,E,C) products collapsed over slots
    dispatch = jnp.einsum("kne,knc->nec", expert, slot)
    combine = jnp.einsum("kn,kne,knc->nec", jnp.moveaxis(val, 1, 0), expert, slot)
    return dispatch, combine


class MoE(Module):
    """Token-choice top-k mixture of FFN experts.

    ``apply(params, x)`` with x (B, S, D) or (N, D).  Each expert is a
    two-layer GELU FFN (D → hidden → D) with its own weights; a linear
    router picks ``top_k`` experts per token.

    With ``comm=`` the expert dimension is sharded over the communicator's
    mesh axis (``num_experts % comm.size == 0``): each device routes its
    resident tokens, ships the per-expert buffers to the expert owners with
    one ``all_to_all``, applies its local experts, and ships results back
    with a second ``all_to_all`` — expert parallelism exactly as run on TPU
    pods, composing with the framework's data/sequence parallelism.  Tokens
    are sharded over the batch axis; a ragged batch is pad-and-masked (pad
    tokens carry zero gate weight, so they are never dispatched).

    ``capacity_factor`` scales each expert's token budget
    ``ceil(top_k * n_tokens / num_experts)``; overflow tokens contribute
    zero for that expert.  Under ``comm=`` the budget applies per source
    shard (the standard EP formulation — capacity is a *local* guarantee so
    the all_to_all buffers stay static-shaped).
    """

    def __init__(
        self,
        embed_dim: int,
        num_experts: int,
        hidden_dim: int | None = None,
        top_k: int = 2,
        capacity_factor: float = 1.5,
        comm=None,
        batch_axis: str | None = None,
    ):
        if top_k < 1 or top_k > num_experts:
            raise ValueError(f"top_k {top_k} must be in [1, num_experts={num_experts}]")
        if batch_axis is not None:
            if comm is None:
                raise ValueError(
                    "batch_axis requires a communicator (it names one of its mesh axes)"
                )
            if batch_axis not in comm.mesh.axis_names or batch_axis == comm.axis:
                raise ValueError(
                    f"batch_axis {batch_axis!r} must name a mesh axis other "
                    f"than the expert axis {comm.axis!r}"
                )
        self.embed_dim = embed_dim
        self.num_experts = num_experts
        self.hidden_dim = hidden_dim or 4 * embed_dim
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.comm = comm
        self.batch_axis = batch_axis  # dp axis of a 2-D mesh (see _ep_program)

    @property
    def _program_key(self):
        """Everything the ``_ep_fn`` trace depends on besides the comm and
        input shapes — the ``_ep_program`` cache key (see its docstring)."""
        return (type(self), self.embed_dim, self.num_experts, self.hidden_dim,
                self.top_k, self.capacity_factor, self.batch_axis)

    def init(self, key):
        D, H, E = self.embed_dim, self.hidden_dim, self.num_experts
        kr, k1, k2 = jax.random.split(key, 3)
        bound1 = 1.0 / jnp.sqrt(D)
        bound2 = 1.0 / jnp.sqrt(H)
        return {
            "router": jax.random.uniform(kr, (D, E), minval=-bound1, maxval=bound1),
            "w1": jax.random.uniform(k1, (E, D, H), minval=-bound1, maxval=bound1),
            "b1": jnp.zeros((E, H)),
            "w2": jax.random.uniform(k2, (E, H, D), minval=-bound2, maxval=bound2),
            "b2": jnp.zeros((E, D)),
        }

    # ------------------------------------------------------------------ #

    def _capacity(self, n_tokens: int) -> int:
        import math

        return max(1, math.ceil(self.top_k * n_tokens / self.num_experts * self.capacity_factor))

    def _experts(self, params, buf):
        """Apply the (possibly local-shard) stacked experts to (e, C, D)."""
        h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", buf, params["w1"]) + params["b1"][:, None, :])
        return jnp.einsum("ech,ehd->ecd", h, params["w2"]) + params["b2"][:, None, :]

    def _dense(self, params, x2d):
        gates = jax.nn.softmax(x2d @ params["router"])
        dispatch, combine = _routing(gates, self.top_k, self._capacity(x2d.shape[0]))
        buf = jnp.einsum("nec,nd->ecd", dispatch, x2d)
        out = self._experts(params, buf)
        return jnp.einsum("nec,ecd->nd", combine, out)

    def _ep_fn(self, params, x_loc, mask_loc):
        """Per-shard body: local routing, all_to_all to expert owners,
        local expert FFNs, all_to_all back, local combine."""
        comm = self.comm
        n_loc = x_loc.shape[0]
        gates = jax.nn.softmax(x_loc @ params["router"]) * mask_loc[:, None]
        dispatch, combine = _routing(gates, self.top_k, self._capacity(n_loc))
        buf = jnp.einsum("nec,nd->ecd", dispatch, x_loc)  # (E, C, D)
        # ship: each owner receives its experts' buffers from every shard
        buf = comm.Alltoall(buf, split_axis=0, concat_axis=1)  # (E/p, C*p, D)
        out = self._experts(params, buf)
        out = comm.Alltoall(out, split_axis=1, concat_axis=0)  # (E, C, D)
        return jnp.einsum("nec,ecd->nd", combine, out)

    def apply(self, params, x, **kw):
        orig_shape = x.shape
        x2d = x.reshape(-1, self.embed_dim)
        comm = self.comm
        # a (dp, ep=1) mesh still runs the EP program (the all_to_all is an
        # identity there) so the dp token sharding survives — only the
        # truly-unsharded case takes the dense shortcut
        if comm is None or (comm.size == 1 and self.batch_axis is None):
            return self._dense(params, x2d).reshape(orig_shape)
        if self.num_experts % comm.size:
            warnings.warn(
                f"MoE: num_experts={self.num_experts} not divisible by mesh size "
                f"{comm.size}; running the dense (replicated-expert) path. "
                "This changes ROUTING NUMERICS, not just speed: capacity is "
                "budgeted over the global token pool instead of per source "
                "shard, so drop decisions (and therefore outputs) can differ "
                "from the expert-parallel path for the same config",
                stacklevel=2,
            )
            return self._dense(params, x2d).reshape(orig_shape)

        # tokens shard over dp x ep jointly when batch_axis is given,
        # else over the expert axis alone
        p = comm.size * (comm.mesh.shape[self.batch_axis] if self.batch_axis else 1)
        n = x2d.shape[0]
        pad = (-n) % p
        mask = jnp.ones((n,), x2d.dtype)
        if pad:
            x2d = jnp.concatenate([x2d, jnp.zeros((pad, self.embed_dim), x2d.dtype)])
            mask = jnp.concatenate([mask, jnp.zeros((pad,), x2d.dtype)])

        y = _ep_program(comm, self)(params, x2d, mask)
        if pad:
            y = y[:n]
        return y.reshape(orig_shape)

    def decode_apply(self, params, x):
        """Drop-free per-token path for autoregressive decoding.

        Gathers each token's top-k experts' weights and applies them
        directly — no capacity buffer, so no token is ever dropped.  The
        capacity-bounded :meth:`apply` pools B·S training tokens while a
        decode step sees only B; under capacity pressure the two would
        disagree arbitrarily, so decoding uses this exact path instead
        (== :meth:`apply` whenever apply's capacity was not binding — the
        usual serving regime).  Cost is k gathered FFNs per token; with
        decode batches this is small and stays on the MXU.
        """
        orig_shape = x.shape
        x2d = x.reshape(-1, self.embed_dim)
        gates = jax.nn.softmax(x2d @ params["router"])
        val, idx = _topk_gates(gates, self.top_k)  # (n, k)
        w1, b1 = params["w1"][idx], params["b1"][idx]  # (n, k, D, H), (n, k, H)
        w2, b2 = params["w2"][idx], params["b2"][idx]
        h = jax.nn.gelu(jnp.einsum("nd,nkdh->nkh", x2d, w1) + b1)
        y = jnp.einsum("nkh,nkhd->nkd", h, w2) + b2
        return jnp.einsum("nk,nkd->nd", val, y).reshape(orig_shape)

    # ------------------------------------------------------------------ #

    def load_balance_loss(self, params, x):
        """Switch-transformer auxiliary loss: ``E * Σ_e f_e · P_e`` where
        ``f_e`` is the fraction of tokens whose TOP choice is expert e and
        ``P_e`` the mean router probability — minimized (=1) by a uniform
        router.  Add ``coef * load_balance_loss`` to the training loss."""
        x2d = x.reshape(-1, self.embed_dim)
        gates = jax.nn.softmax(x2d @ params["router"])
        top1 = jnp.argmax(gates, axis=-1)
        f = jnp.mean(jax.nn.one_hot(top1, self.num_experts, dtype=gates.dtype), axis=0)
        P = jnp.mean(gates, axis=0)
        return self.num_experts * jnp.sum(f * P)
