"""Pallas TPU kernel: fused KMeans assignment (E-step).

The jnp form materializes the (n, k) squared-distance matrix in HBM before
the argmin.  This kernel tiles the sample axis: each grid step loads a
(TILE, d) row block plus the full (k, d) centers into VMEM, runs the
distance GEMM on the MXU, and reduces to (TILE,) labels + min-distances in
VMEM — the n×k matrix never exists in HBM.

Measured on v5e (1M×32, k=64): XLA's own fusion of the jnp form runs at
~4.8 ms vs ~14.6 ms for this kernel — XLA already avoids the HBM
materialization here, so ``cluster.KMeans`` keeps the jnp path and this
kernel remains an opt-in (`ht.ops.fused_assign`) for the regimes XLA fuses
poorly (large k × large d where the (n,k) product spills).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except ImportError:  # pragma: no cover
    _HAS_PALLAS = False

__all__ = ["fused_assign"]

_TILE = 1024


def _assign_kernel(x_ref, c_ref, cc_ref, lab_ref, d2_ref):
    x = x_ref[:]  # (TILE, d)
    c = c_ref[:]  # (k, d)
    cc = cc_ref[:]  # (1, k) — precomputed ||c||²
    xx = jnp.sum(x * x, axis=1, keepdims=True)  # (TILE, 1)
    dots = jax.lax.dot_general(
        x, c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # (TILE, k) on the MXU
    d2 = xx + cc - 2.0 * dots
    d2 = jnp.maximum(d2, 0.0)
    lab_ref[:] = jnp.argmin(d2, axis=1, keepdims=True).astype(jnp.int32)
    d2_ref[:] = jnp.min(d2, axis=1, keepdims=True)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _fused_assign_impl(x, centers, interpret: bool):
    n, d = x.shape
    k = centers.shape[0]
    tile = min(_TILE, n)
    grid = (pl.cdiv(n, tile),)
    cc = jnp.sum(centers * centers, axis=1)[None, :]  # (1, k)
    labels, d2 = pl.pallas_call(
        _assign_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, d), lambda i: (i, 0)),
            pl.BlockSpec((k, d), lambda i: (0, 0)),
            pl.BlockSpec((1, k), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.int32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x.astype(jnp.float32), centers.astype(jnp.float32), cc.astype(jnp.float32))
    return labels[:, 0], d2[:, 0]


def _jnp_assign(x, centers):
    xx = jnp.sum(x * x, axis=1, keepdims=True)
    cc = jnp.sum(centers * centers, axis=1)[None, :]
    d2 = xx + cc - 2.0 * (x @ centers.T)
    d2 = jnp.maximum(d2, 0.0)
    return jnp.argmin(d2, axis=1), jnp.min(d2, axis=1)


def fused_assign(x, centers):
    """(labels, min_d2) of each row of ``x`` against ``centers``.

    Pallas-fused on TPU; interpreter mode on CPU shards; jnp fallback when
    Pallas is unavailable or shapes are unfriendly (the kernel requires the
    row count divisible by the tile, handled by padding).
    """
    if not _HAS_PALLAS:
        return _jnp_assign(x, centers)
    n = x.shape[0]
    platform = jax.devices()[0].platform
    if platform not in ("tpu", "cpu"):
        return _jnp_assign(x, centers)
    if platform == "cpu" and n > 16384:
        # interpreter mode is slow; only use it at test scale
        return _jnp_assign(x, centers)
    tile = min(_TILE, n)
    pad = (-n) % tile
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, x.shape[1]), x.dtype)], axis=0)
    try:
        labels, d2 = _fused_assign_impl(x, centers, interpret=(platform == "cpu"))
    except Exception:
        return _jnp_assign(x[:n], centers)
    return labels[:n], d2[:n]
