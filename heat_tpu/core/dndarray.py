"""DNDarray — the distributed N-D array, TPU-native.

Re-design of the reference's ``heat/core/dndarray.py`` (SURVEY §2.1).  The
reference's DNDarray is *locally a torch.Tensor, globally a chunked array*;
each MPI rank stores its chunk and all global bookkeeping (gshape, lshape_map,
index translation) is hand-maintained Python.  Here a DNDarray wraps ONE
globally-shaped :class:`jax.Array` whose ``NamedSharding`` over the
communicator's mesh realizes the ``split`` axis:

- ``split=None``  ⇔  fully replicated (``PartitionSpec()``)
- ``split=k``     ⇔  axis ``k`` sharded over the mesh axis
  (``PartitionSpec(..., 'x', ...)``)

All inter-chip data movement is emitted by XLA when ops require it; the
explicit ``resplit_`` maps to a resharding ``device_put`` (→ all-to-all).

DNDarray is registered as a JAX pytree (the array is the leaf; split/device/
comm are static aux data), so user functions over DNDarrays can be ``jax.jit``
-ed, differentiated, and vmapped — something the reference fundamentally
cannot offer.
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import types
from .communication import Communication
from .devices import Device
from .stride_tricks import sanitize_axis

__all__ = ["DNDarray"]

Scalar = Union[int, float, bool, complex]

# device-memory-ledger hook (``utils.memledger.enable()`` pokes the module
# in, ``disable()`` clears it): ``_from_parts`` is the zero-copy wrap every
# cached dispatch output and linalg fast path passes through, so it is a
# registration choke point of the ledger.  Disabled cost: one module-global
# load (the telemetry-hook pattern; module bottom re-arms against
# import-order races).
_MEMLEDGER = None


class LocalIndex:
    """Marker for local-index assignment, parity with reference ``x.lloc``."""

    def __init__(self, arr: "DNDarray"):
        self.arr = arr

    def __getitem__(self, key):
        return self.arr.larray[key]

    def __setitem__(self, key, value):
        # local == global view on a single controller; route through global set
        self.arr[key] = value


class DNDarray:
    """A globally-shaped, mesh-sharded N-D array with a NumPy-style API."""

    def __init__(
        self,
        array: jax.Array,
        gshape: Tuple[int, ...],
        dtype,
        split: Optional[int],
        device: Device,
        comm: Communication,
        balanced: Optional[bool] = True,
    ):
        gshape = tuple(int(s) for s in gshape)
        if split is not None and len(gshape):
            split = split % len(gshape)
        elif split is not None:
            split = None
        self.__gshape = gshape
        self.__dtype = types.canonical_heat_type(dtype)
        self.__split = split
        self.__device = device
        self.__comm = comm
        # `balanced` is accepted for reference API parity but not stored:
        # balancedness is a pure function of (gshape, split, comm) under the
        # canonical ceil-div layout — see is_balanced()
        self.__pad = 0
        self.__unpadded = None
        # --- physical normalization (pad-and-mask, SURVEY §7 hard part #1) ---
        # NamedSharding requires the sharded axis to be divisible by the mesh
        # axis size.  Ragged axes are physically stored zero-padded to
        # ceil(n/p)*p; `gshape` carries the logical (true) extent and `_pad`
        # the trailing dead region.  This constructor is the single choke
        # point: any DNDarray with a split axis is guaranteed physically
        # sharded over the full mesh, so `split` metadata never lies
        # (cf. reference `heat/core/dndarray.py` chunk-map invariant).
        if split is not None and comm.size > 1 and hasattr(array, "shape"):
            n = gshape[split]
            target = comm.padded_extent(n)
            pad = target - n
            ashape = tuple(array.shape)
            expect_logical = gshape
            expect_physical = gshape[:split] + (target,) + gshape[split + 1 :]
            if ashape == expect_physical and pad:
                # caller provides the padded physical — still enforce placement
                self.__pad = pad
                array = self._enforce_placement(array, comm, split)
            elif ashape == expect_logical:
                if pad:
                    array = comm.pad_shard(array, split)
                    self.__pad = pad
                else:
                    array = self._enforce_placement(array, comm, split)
            else:
                raise ValueError(
                    f"array shape {ashape} matches neither the logical gshape "
                    f"{expect_logical} nor the padded physical shape {expect_physical}"
                )
        self.__array = array

    @classmethod
    def _from_parts(cls, array, gshape, dtype, split, device, comm) -> "DNDarray":
        """Wrap a dispatch-cache program output WITHOUT re-validation.

        The cached executables compile the canonical output sharding in
        (``with_sharding_constraint``) and their plans pre-resolve shape,
        heat dtype and split — re-running ``__init__``'s placement
        enforcement and pad bookkeeping per call would re-derive facts the
        plan already guarantees.  Callers must guarantee: ``gshape`` is a
        tuple of ints matching ``array.shape``, ``split`` is in range (or
        None), and the split axis is mesh-divisible (pad-free).
        """
        self = object.__new__(cls)
        self._DNDarray__gshape = gshape
        self._DNDarray__dtype = dtype
        self._DNDarray__split = split
        self._DNDarray__device = device
        self._DNDarray__comm = comm
        self._DNDarray__pad = 0
        self._DNDarray__unpadded = None
        self._DNDarray__array = array
        if _MEMLEDGER is not None:
            # ledger choke point, hot-tier recorder: one lean call —
            # under-threshold buffers coalesce into a counter, buffers of
            # consequence get the full provenance entry (op name resolved
            # by frame peek: the public wrapper above the dispatch tail)
            _MEMLEDGER.register_dispatch(array)
        return self

    @staticmethod
    def _enforce_placement(array, comm, split):
        """No DNDarray may claim a split its sharding doesn't have: place
        concrete arrays on the canonical sharding unless already equivalent.
        Hosted-complex arrays (transport without native complex) stay on the
        host backend; tracers are left to the surrounding jit."""
        if isinstance(array, jax.core.Tracer):
            return array
        sh = comm.sharding(array.ndim, split)
        cur = getattr(array, "sharding", None)
        if cur == sh:
            return array
        try:
            if cur is not None and cur.is_equivalent_to(sh, array.ndim):
                return array
        except Exception:
            pass
        from ._complexsafe import guard

        hosted = guard(array)
        if hosted is not None:
            return hosted  # complex on a non-native transport: keep host-side
        return jax.device_put(array, sh)

    # ------------------------------------------------------------------ #
    # internal access
    # ------------------------------------------------------------------ #
    @property
    def _jarray(self) -> jax.Array:
        """The LOGICAL global jax.Array — true ``gshape``, pad sliced off.

        For the (common) divisible case this is the stored array itself; for
        ragged splits it is a cached slice of the padded physical array. Ops
        that consume `_jarray` are correct by construction; pad-aware fast
        paths use `_parray`/`_masked` instead.
        """
        if self.__pad == 0:
            return self.__array
        if self.__unpadded is None:
            sl = tuple(
                slice(0, self.__gshape[i]) if i == self.__split else slice(None)
                for i in range(len(self.__gshape))
            )
            self.__unpadded = self.__array[sl]
        return self.__unpadded

    @_jarray.setter
    def _jarray(self, arr) -> None:
        """Replace contents with a LOGICAL (true-shape) array; re-pads/places."""
        self._renormalize(arr)

    @property
    def _parray(self) -> jax.Array:
        """The PHYSICAL stored array (padded along split when `_pad` > 0)."""
        return self.__array

    @property
    def _pad(self) -> int:
        """Trailing zero-pad extent along the split axis (0 when divisible)."""
        return self.__pad

    def _masked(self, fill) -> jax.Array:
        """Physical array with the pad region replaced by ``fill`` — the
        reduction-identity masking of pad-and-mask (e.g. 0 for sum, -inf for
        max).  No-op when the array is not padded."""
        if self.__pad == 0:
            return self.__array
        from jax import lax as _lax

        iota = _lax.broadcasted_iota(jnp.int32, self.__array.shape, self.__split)
        fillv = jnp.asarray(fill, dtype=self.__array.dtype)
        return jnp.where(iota < self.__gshape[self.__split], self.__array, fillv)

    def _renormalize(self, logical: jax.Array) -> None:
        """Install ``logical`` (true-shape) as the new contents: recompute the
        global shape, pad and physically place as needed."""
        self.__gshape = tuple(int(s) for s in logical.shape)
        self.__unpadded = None
        self.__pad = 0
        split = self.__split
        if split is not None and split < len(self.__gshape) and self.__comm.size > 1:
            n = self.__gshape[split]
            target = self.__comm.padded_extent(n)
            if target != n:
                logical = self.__comm.pad_shard(logical, split)
                self.__pad = target - n
        self.__array = logical

    # ------------------------------------------------------------------ #
    # reference-parity attributes
    # ------------------------------------------------------------------ #
    @property
    def larray(self) -> jax.Array:
        """The process-local data.

        Single-controller JAX addresses all chips, so the 'local' view is the
        global (logical) array itself.  (Reference users index shards via
        ``lshape_map``/``chunk``.)
        """
        return self._jarray

    @larray.setter
    def larray(self, array: jax.Array) -> None:
        self._renormalize(array)

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.__gshape

    @property
    def gshape(self) -> Tuple[int, ...]:
        return self.__gshape

    @property
    def lshape(self) -> Tuple[int, ...]:
        """Shape of shard 0's chunk (reference: "this rank's chunk").

        Single-controller semantics: there is ONE process addressing all
        shards, so "local" is a convention — this reports the FIRST shard's
        valid extent from the canonical ceil-div chunk map.  Per-shard truth
        for every shard is ``lshape_map()``; for ragged shapes the shards
        differ (e.g. 100 rows on 8 devices → 13,…,13,9) and ``lshape`` alone
        cannot describe them all.
        """
        _, lshape, _ = self.__comm.chunk(self.__gshape, self.__split, rank=0)
        return lshape

    def lshape_map(self, force_check: bool = False) -> np.ndarray:
        """(size, ndim) matrix of all shard shapes — pure math, no comm needed."""
        return self.__comm.lshape_map(self.__gshape, self.__split)

    @property
    def dtype(self):
        return self.__dtype

    @property
    def split(self) -> Optional[int]:
        return self.__split

    @property
    def device(self) -> Device:
        return self.__device

    @property
    def comm(self) -> Communication:
        return self.__comm

    @property
    def balanced(self) -> bool:
        return self.is_balanced()

    @property
    def ndim(self) -> int:
        return len(self.__gshape)

    @property
    def size(self) -> int:
        return int(np.prod(self.__gshape, dtype=np.int64)) if self.__gshape else 1

    @property
    def gnumel(self) -> int:
        return self.size

    @property
    def lnumel(self) -> int:
        return int(np.prod(self.lshape, dtype=np.int64)) if self.lshape else 1

    @property
    def nbytes(self) -> int:
        return self.size * self.__dtype.np_dtype().itemsize

    @property
    def gnbytes(self) -> int:
        return self.nbytes

    @property
    def lnbytes(self) -> int:
        return self.lnumel * self.__dtype.np_dtype().itemsize

    @property
    def imag(self) -> "DNDarray":
        from . import complex_math

        return complex_math.imag(self)

    @property
    def real(self) -> "DNDarray":
        from . import complex_math

        return complex_math.real(self)

    @property
    def T(self) -> "DNDarray":
        from ..linalg import basics

        return basics.transpose(self)

    @property
    def lloc(self) -> LocalIndex:
        return LocalIndex(self)

    @property
    def stride(self) -> Tuple[int, ...]:
        """Row-major strides in elements (XLA owns the physical layout)."""
        strides = np.cumprod((1,) + self.__gshape[:0:-1])[::-1]
        return tuple(int(s) for s in strides)

    @property
    def strides(self) -> Tuple[int, ...]:
        return tuple(s * self.__dtype.np_dtype().itemsize for s in self.stride)

    @property
    def __partitioned__(self) -> dict:
        """Cross-framework partitioned-array protocol (reference parity)."""
        comm = self.__comm
        parts = {}
        for r in range(comm.size if self.__split is not None else 1):
            off, lsh, _ = comm.chunk(self.__gshape, self.__split, r)
            pos = (r,)
            start = tuple(
                off if i == self.__split else 0 for i in range(self.ndim)
            ) if self.__split is not None else (0,) * self.ndim
            parts[pos] = {
                "start": start,
                "shape": lsh,
                "data": None,
                "location": [r],
                "dtype": self.__dtype.np_dtype(),
            }
        return {
            "shape": self.__gshape,
            "partition_tiling": (comm.size,) if self.__split is not None else (1,),
            "partitions": parts,
            "locals": [(comm.rank,)],
            "get": lambda x: x,
        }

    # ------------------------------------------------------------------ #
    # basic conversions
    # ------------------------------------------------------------------ #
    def astype(self, dtype, copy: bool = True) -> "DNDarray":
        from . import _complexsafe

        dtype = types.canonical_heat_type(dtype)
        jdt = dtype.jax_dtype()
        src = self.__array
        if jnp.issubdtype(jdt, jnp.complexfloating) and not _complexsafe.native_complex_supported():
            src = _complexsafe.to_host_backend(src)
        casted = src.astype(jdt)
        # honor JAX canonicalization (64→32-bit when x64 is off) in metadata
        dtype = types.canonical_heat_type(casted.dtype)
        if copy:
            return DNDarray(
                casted, self.__gshape, dtype, self.__split, self.__device, self.__comm, True
            )
        self.__array = casted
        self.__unpadded = None
        self.__dtype = dtype
        return self

    def numpy(self) -> np.ndarray:
        """Gather the global (logical) array to host memory as a numpy array."""
        src = self.__array
        try:
            out = self.__comm.host_fetch(src)
        except jax.errors.JaxRuntimeError:
            if jnp.issubdtype(src.dtype, jnp.complexfloating):
                # some TPU transports cannot ship complex buffers to host;
                # move the real/imag planes separately and recombine
                re = np.asarray(jax.device_get(jnp.real(src)))
                im = np.asarray(jax.device_get(jnp.imag(src)))
                out = (re + 1j * im).astype(self.__dtype.np_dtype())
            else:
                raise
        if self.__pad:
            sl = tuple(
                slice(0, self.__gshape[i]) if i == self.__split else slice(None)
                for i in range(len(self.__gshape))
            )
            out = out[sl]
        return out

    def __array__(self, dtype=None) -> np.ndarray:
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def tolist(self, keepsplit: bool = False) -> List:
        return self.numpy().tolist()

    def item(self):
        if self.size != 1:
            raise ValueError("only one-element DNDarrays can be converted to scalars")
        return self._jarray.reshape(()).item()

    def __bool__(self) -> bool:
        return bool(self.item())

    def __int__(self) -> int:
        return int(self.item())

    def __float__(self) -> float:
        return float(self.item())

    def __complex__(self) -> complex:
        return complex(self.item())

    def __index__(self) -> int:
        if not types.heat_type_is_exact(self.__dtype):
            raise TypeError("only integer scalar arrays can be used as an index")
        return int(self.item())

    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.__gshape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # ------------------------------------------------------------------ #
    # device / distribution management
    # ------------------------------------------------------------------ #
    def is_distributed(self) -> bool:
        return self.__split is not None and self.__comm.is_distributed()

    def is_balanced(self, force_check: bool = False) -> bool:
        """True iff every shard's valid extent differs by at most one row —
        the reference's balancedness criterion, computed from the REAL
        ceil-division chunk map (truthful for ragged shapes: e.g. 100 rows on
        8 devices gives chunks 13×7+9, which is NOT balanced).  Closed form:
        chunks are ``c = ceil(n/p)`` except the tail, so balanced ⇔
        ``c - clamp(n - (p-1)c, 0, c) <= 1``."""
        if self.__split is None or not self.__comm.is_distributed():
            return True
        n, p = self.__gshape[self.__split], self.__comm.size
        c = -(-n // p)
        tail = max(0, min(c, n - (p - 1) * c))
        return c - tail <= 1

    def balance_(self) -> None:
        """Reference parity stub: under GSPMD the ceil-division grid is the
        ONLY physical layout — there is no unbalanced state to repair (ragged
        shapes are padded, not unevenly chunked), so this is a no-op.
        ``is_balanced()`` may legitimately stay False for ragged shapes; that
        reports the ceil-div chunk asymmetry, not a repairable state."""

    def resplit_(
        self, axis: Optional[int] = None, memory_budget: Optional[int] = None
    ) -> "DNDarray":
        """In-place redistribution to a new split axis (reference SURVEY §3.3).

        Lowered by XLA to an all-to-all (split↔split) or allgather (→None);
        ragged axes are re-padded along the new split axis.  In-place means
        in-place: the old buffer is DONATED to the reshard program (layout
        permitting, XLA aliases or early-frees it), so other DNDarrays
        sharing this array's buffer — ``astype(copy=False)`` views — must
        not be read afterwards.  Use ``resplit()`` for the copying form.

        ``memory_budget`` (bytes; ``None`` → the process default from
        ``ht.set_redistribution_budget()`` / ``HEAT_TPU_RESPLIT_BUDGET``)
        bounds the bytes moved per step: an oversized transition streams as
        K budget-sized tiled all-to-alls with the destination written in
        place and the source freed as soon as its last tile is staged (see
        ``core.redistribution``).
        """
        axis = sanitize_axis(self.__gshape, axis)
        if axis == self.__split:
            return self
        logical = self._jarray
        self.__split = axis
        self.__pad = 0
        self.__unpadded = None
        if axis is None:
            self.__array = self.__comm.resplit(
                logical, None, donate=True, memory_budget=memory_budget
            )
        else:
            self._renormalize(logical)
            if self.__pad == 0:
                self.__array = self.__comm.resplit(
                    self.__array, axis, donate=True, memory_budget=memory_budget
                )
        from . import sanitation  # lazy: sanitation imports this module

        return sanitation.check(self, "resplit_")

    def redistribute_(self, lshape_map=None, target_map=None) -> None:
        """Redistribute to a target chunk map (reference
        ``DNDarray.redistribute_``).

        Under GSPMD the per-shard placement is canonically determined by the
        ``NamedSharding`` (ceil-division chunks): the canonical map is
        enforced physically (a ``device_put``, lowered to all-to-all if data
        is elsewhere); any OTHER chunk map is not representable — JAX offers
        no per-device uneven placement — so a non-canonical ``target_map``
        raises ``NotImplementedError`` instead of silently lying about the
        layout (SURVEY §7 hard part #1).
        """
        if self.__split is None:
            return
        if target_map is not None:
            tm = np.asarray(target_map)
            canonical = self.__comm.lshape_map(self.__gshape, self.__split)
            if tm.shape != canonical.shape or not (tm == canonical).all():
                raise NotImplementedError(
                    "arbitrary chunk maps are not representable under GSPMD "
                    "even-sharding; only the canonical ceil-division map is "
                    f"supported (requested {tm.tolist()}, canonical "
                    f"{canonical.tolist()}). Use resplit_() to change the "
                    "split axis instead."
                )
        # enforce canonical physical placement
        if self.__pad == 0:
            self.__array = self.__comm.shard(self.__array, self.__split)
        else:
            self.__array = self.__comm.pad_shard(self._jarray, self.__split)
            self.__unpadded = None

    def resplit(
        self, axis: Optional[int] = None, memory_budget: Optional[int] = None
    ) -> "DNDarray":
        from . import manipulations

        return manipulations.resplit(self, axis, memory_budget=memory_budget)

    def cpu(self) -> "DNDarray":
        from . import devices as _dev

        return self.to_device(_dev.cpu)

    def to_device(self, device) -> "DNDarray":
        from . import devices as _dev
        from .communication import Communication

        device = _dev.sanitize_device(device)
        if device == self.__device:
            return self
        comm = Communication(device.mesh)
        host = jnp.asarray(self.numpy())
        split = self.__split
        if split is None or self.__gshape[split] % comm.size == 0:
            host = jax.device_put(host, comm.sharding(self.ndim, split))
        # ragged: the constructor pad-shards onto the target mesh
        return DNDarray(host, self.__gshape, self.__dtype, split, device, comm, True)

    # ------------------------------------------------------------------ #
    # halo support (reference: get_halo / array_with_halos, used by convolve)
    # ------------------------------------------------------------------ #
    def get_halo(self, halo_size: int, prev: bool = True, next: bool = True) -> None:
        """Record the requested halo width; materialization happens inside the
        shard_map of the consuming op (see ``parallel.halo.halo_exchange``)."""
        if not isinstance(halo_size, int) or halo_size < 0:
            raise (TypeError if not isinstance(halo_size, int) else ValueError)(
                f"halo_size needs to be a non-negative int, got {halo_size}"
            )
        self.__halo_size = halo_size

    @property
    def array_with_halos(self) -> jax.Array:
        from ..parallel.halo import with_halos

        hs = getattr(self, "_DNDarray__halo_size", 0)
        if self.__split is None or hs == 0:
            return self._jarray
        return with_halos(self._jarray, hs, self.__split, self.__comm)

    # ------------------------------------------------------------------ #
    # indexing
    # ------------------------------------------------------------------ #
    def _normalized_key(self, key):
        def conv(k):
            if isinstance(k, DNDarray):
                return k._jarray
            if isinstance(k, (list, np.ndarray)):
                # numpy-style list/ndarray fancy index → jnp array
                return jnp.asarray(k)
            return k

        if isinstance(key, tuple):
            return tuple(conv(k) for k in key)
        return conv(key)

    def _result_split_of_key(self, key) -> Optional[int]:
        """Compute the split axis of an indexing result (None ⇒ replicated)."""
        if self.__split is None:
            return None
        key_t = key if isinstance(key, tuple) else (key,)
        # expand Ellipsis
        if any(k is Ellipsis for k in key_t):
            n_specified = sum(1 for k in key_t if k is not None and k is not Ellipsis)
            fill = self.ndim - n_specified
            out = []
            for k in key_t:
                if k is Ellipsis:
                    out.extend([slice(None)] * fill)
                else:
                    out.append(k)
            key_t = tuple(out)
        # walk input axes vs output axes
        in_ax = 0
        out_ax = 0
        has_advanced = any(
            isinstance(k, (list, np.ndarray, jax.Array)) and not isinstance(k, (bool, np.bool_))
            for k in key_t
        )
        for k in key_t:
            if k is None:
                out_ax += 1
                continue
            if in_ax == self.__split:
                if isinstance(k, slice):
                    return out_ax
                if isinstance(k, (int, np.integer)):
                    return None
                # advanced index on the split axis
                if has_advanced and not isinstance(k, (bool, np.bool_)):
                    # 1-D fancy index keeps a distributed result axis
                    return 0 if not isinstance(k, slice) else out_ax
                return None
            if isinstance(k, (int, np.integer)):
                in_ax += 1  # consumes an axis, produces none
            elif isinstance(k, slice):
                in_ax += 1
                out_ax += 1
            else:
                # advanced index consumes (possibly several for bool) axes
                if isinstance(k, (np.ndarray, jax.Array)) and k.dtype == np.bool_:
                    in_ax += k.ndim
                else:
                    in_ax += 1
                out_ax += 1
        # remaining untouched axes
        if in_ax <= self.__split:
            return out_ax + (self.__split - in_ax)
        return None

    def __getitem__(self, key) -> "DNDarray":
        nkey = self._normalized_key(key)
        result = self._jarray[nkey]
        new_split = self._result_split_of_key(nkey)
        if new_split is not None and new_split >= result.ndim:
            new_split = None
        result = self.__comm.shard(result, new_split)
        return DNDarray(
            result,
            tuple(result.shape),
            types.canonical_heat_type(result.dtype),
            new_split,
            self.__device,
            self.__comm,
            True,
        )

    def __setitem__(self, key, value) -> None:
        nkey = self._normalized_key(key)
        if isinstance(value, DNDarray):
            value = value._jarray
        if self.__pad:
            self._renormalize(self._jarray.at[nkey].set(value))
        else:
            updated = self.__array.at[nkey].set(value)
            self.__array = self.__comm.shard(updated, self.__split)

    def fill_diagonal(self, value) -> "DNDarray":
        n = min(self.__gshape[-2], self.__gshape[-1]) if self.ndim >= 2 else 0
        idx = jnp.arange(n)
        if self.__pad:
            self._renormalize(self._jarray.at[..., idx, idx].set(value))
        else:
            updated = self.__array.at[..., idx, idx].set(value)
            self.__array = self.__comm.shard(updated, self.__split)
        return self

    # ------------------------------------------------------------------ #
    # printing
    # ------------------------------------------------------------------ #
    def __repr__(self) -> str:
        from . import printing

        return printing.__repr__(self)

    def __str__(self) -> str:
        from . import printing

        return printing.__str__(self)

    # ------------------------------------------------------------------ #
    # interop stubs
    # ------------------------------------------------------------------ #
    def __torch_proxy__(self):
        import torch

        return torch.from_numpy(np.asarray(self.numpy()))

    def counts_displs(self):
        if self.__split is None:
            raise ValueError("Non-distributed DNDarray has no counts and displacements")
        return self.__comm.counts_displs_shape(self.__gshape, self.__split)


# ---------------------------------------------------------------------- #
# pytree registration: DNDarray-valued functions are jit/grad/vmap-able
# ---------------------------------------------------------------------- #
def _dnd_flatten(x: DNDarray):
    # the LOGICAL array is the leaf: transforms must see the true gshape or
    # vmap(in_axes=0) over a ragged array maps over pad rows.  The unpad
    # slice this costs at a trace boundary is re-padded by the constructor on
    # the way out (concrete leaves), so distribution is restored at every
    # concrete boundary; pad in the aux is always 0 here, kept (with ndim)
    # so unflatten can re-anchor split when batching transforms add axes
    return (x._jarray,), (x.split, x.device, x.comm, 0, x.ndim)


def _dnd_unflatten(aux, children):
    (arr,) = children
    split, device, comm, _pad_unused, ndim0 = aux  # flatten always emits pad=0
    shape = list(arr.shape) if hasattr(arr, "shape") else []
    nd = len(shape)
    if split is not None:
        delta = nd - ndim0
        adj = split + delta if delta > 0 else split  # leading batch dims added
        split = adj if 0 <= adj < nd else None
    shape = tuple(shape)
    try:
        dtype = types.canonical_heat_type(arr.dtype)
    except (TypeError, AttributeError):
        dtype = types.float32
    try:
        return DNDarray(arr, shape, dtype, split, device, comm, True)
    except ValueError:
        # a transform (vmap batching, scan carry) reshaped the leaf so the
        # pad bookkeeping no longer lines up; treat the leaf as logical
        return DNDarray(arr, tuple(arr.shape), dtype, None, device, comm, True)


jax.tree_util.register_pytree_node(DNDarray, _dnd_flatten, _dnd_unflatten)

# the memory ledger may have been env-armed (HEAT_TPU_MEMLEDGER=1) while
# this module was still importing — re-read the flag now, the defensive
# module-bottom pattern every hot-path hook here follows
import sys as _sys  # noqa: E402

_ml = _sys.modules.get("heat_tpu.utils.memledger")
if _ml is not None and _ml.enabled():
    _MEMLEDGER = _ml
del _sys, _ml
