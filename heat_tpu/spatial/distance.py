"""Pairwise distances (reference: ``heat/spatial/distance.py``).

The reference's both-split case is a ring algorithm: the X block stays put,
Y blocks circulate via Isend/Irecv (SURVEY §2.4).  Here the default path is
one sharded computation (GSPMD chooses the data movement — typically an
all-gather of the smaller operand over ICI); the explicit ring is available
as ``cdist_ring`` built on ``parallel.ring_map`` for the memory-constrained
regime where only one rotating block may be resident at a time.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core import types
from ..core.dndarray import DNDarray
from ..core.sanitation import sanitize_in

__all__ = ["cdist", "cdist_ring", "cdist_small", "manhattan", "rbf"]


def _wrap(jarr, split, proto: DNDarray) -> DNDarray:
    if split is not None and split >= jarr.ndim:
        split = None
    jarr = proto.comm.shard(jarr, split)
    return DNDarray(
        jarr, tuple(jarr.shape), types.canonical_heat_type(jarr.dtype), split, proto.device, proto.comm, True
    )


def _sq_euclid(x, y):
    # quadratic expansion: ||x||² + ||y||² − 2 x·yᵀ — one big MXU GEMM
    xx = jnp.sum(x * x, axis=1, keepdims=True)
    yy = jnp.sum(y * y, axis=1, keepdims=True).T
    d2 = xx + yy - 2.0 * (x @ y.T)
    return jnp.maximum(d2, 0.0)


def cdist(x: DNDarray, y: Optional[DNDarray] = None, quadratic_expansion: bool = False) -> DNDarray:
    """Euclidean distance matrix between rows of ``x`` and ``y``.

    ``quadratic_expansion=True`` uses the GEMM form (MXU-friendly; the TPU
    default regardless, since the expansion maps the whole computation onto
    the systolic array).
    """
    sanitize_in(x)
    if y is None:
        y = x
    sanitize_in(y)
    jx, jy = x._jarray, y._jarray
    if quadratic_expansion:
        d = jnp.sqrt(_sq_euclid(jx, jy))
    else:
        # direct form, still batched: (n,1,d)-(1,m,d) — better precision
        d = jnp.sqrt(jnp.maximum(jnp.sum((jx[:, None, :] - jy[None, :, :]) ** 2, axis=-1), 0.0))
    split = 0 if x.split == 0 else (1 if y.split == 0 else None)
    return _wrap(d, split, x)


def cdist_small(x: DNDarray, y: Optional[DNDarray] = None, quadratic_expansion: bool = False) -> DNDarray:
    return cdist(x, y, quadratic_expansion)


def manhattan(x: DNDarray, y: Optional[DNDarray] = None, expand: bool = False) -> DNDarray:
    """City-block distance matrix."""
    sanitize_in(x)
    if y is None:
        y = x
    d = jnp.sum(jnp.abs(x._jarray[:, None, :] - y._jarray[None, :, :]), axis=-1)
    split = 0 if x.split == 0 else (1 if y.split == 0 else None)
    return _wrap(d, split, x)


def rbf(x: DNDarray, y: Optional[DNDarray] = None, sigma: float = 1.0, quadratic_expansion: bool = False) -> DNDarray:
    """Gaussian RBF kernel matrix exp(−d²/(2σ²))."""
    sanitize_in(x)
    if y is None:
        y = x
    d2 = _sq_euclid(x._jarray, y._jarray) if quadratic_expansion else jnp.sum(
        (x._jarray[:, None, :] - y._jarray[None, :, :]) ** 2, axis=-1
    )
    k = jnp.exp(-d2 / (2.0 * sigma * sigma))
    split = 0 if x.split == 0 else (1 if y.split == 0 else None)
    return _wrap(k, split, x)


def cdist_ring(x: DNDarray, y: Optional[DNDarray] = None) -> DNDarray:
    """Explicit ring cdist (reference's Isend/Irecv algorithm on ppermute).

    Both operands row-split; X blocks stationary, Y blocks rotate. Peak
    memory per chip is one X block + one Y block + one output block —
    the reason the reference uses this form at scale.
    """
    from ..parallel.ring import ring_map

    sanitize_in(x)
    if y is None:
        y = x
    comm = x.comm
    if (
        comm.size == 1
        or x.split != 0
        or y.split != 0
        or x.shape[0] % comm.size
        or y.shape[0] % comm.size
    ):
        return cdist(x, y, quadratic_expansion=True)

    d = ring_map(
        _cdist_ring_step, x._jarray, y._jarray, comm,
        combine="concat", concat_axis=1,
    )
    return _wrap(d, 0, x)


def _cdist_ring_step(x_blk, y_blk, src):
    # module-level (stable identity) so ring_map's comm-cached program is
    # reused across cdist_ring calls instead of recompiling per call
    return jnp.sqrt(_sq_euclid(x_blk, y_blk))
