"""Flight recorder: a crash-durable, bounded, per-rank event ring.

PR 5's supervisor can tell *that* a world hung and restart it; what it
could not tell was *which collective* the ranks disagreed on, *which rank*
fell behind, or what the last healthy operation was — the telemetry ring
(PR 3) dies with the process because ``atexit`` never runs under SIGKILL.
This module is the black box: every staged collective (and the coarse
local events around it) is appended to a **preallocated mmap'd ring
file**, so the last N events survive any process death without an exit
handler.  ``scripts/postmortem.py`` merges the per-rank rings into a
verdict naming the first divergent sequence or the straggler rank.

**Durability contract.**  Appends go through an ``mmap`` of a fully
preallocated file; there is NO ``msync``/``fsync`` on the hot path.  The
written pages live in the OS page cache, which outlives the process: the
ring survives SIGKILL, an uncaught exception, an OOM kill — anything that
kills the *process*.  It does NOT survive kernel panic or power loss
(that tier needs fsync, which would put a disk round-trip on the
collective staging path).  The file itself is created tmp + rename, so a
reader never sees a half-initialized header.

**Record taxonomy** (the ``k`` field):

- ``coll`` — a staged collective, stamped at the one choke point every
  collective passes through (``Communication._account_bytes``).  Carries
  the per-rank monotone **collective sequence number** ``seq`` plus the
  fingerprint ``(op, gshape, dtype, src/dst split, wire bytes, epoch ts,
  deadline remaining)``.  In lockstep SPMD every rank stages the identical
  ``seq → fingerprint`` stream; the first index where streams differ IS
  the desync, and the rank whose stream is shortest IS the straggler.
- ``d`` — a coalesced cached-dispatch summary ``{"ops": {name: count}}``:
  every local dispatch since the previous full record, flushed immediately
  before the next collective/span/checkpoint append (and on ``sync()``) —
  the "last healthy local operations" context around the collectives.
  Coalescing keeps the per-dispatch hot path to ONE dict increment (the
  same cost class as the telemetry hook); the window of local op names
  since the last full record is the only thing a SIGKILL can lose, never
  a collective stamp.
- ``span`` / ``span_end`` — telemetry span open/close (named phases).
- ``ckpt`` / ``resume`` / ``shutdown`` — checkpoint IO, restart-resume,
  and clean teardown markers (the analyzer's "clean" evidence).

Every record additionally carries the per-rank event counter ``e`` (its
ring slot is ``e % n_slots``) and an epoch timestamp ``t``.

**Arming.**  ``flightrec.enable(directory)`` (ring file
``{dir}/flight_rank{k}.ring``) or ``HEAT_TPU_FLIGHTREC_DIR`` in the
environment.  Like the telemetry module, enabling pokes module globals
*into* the hot-path modules (``core._operations._FLIGHTREC``,
``core.communication._FLIGHTREC``, ``utils.telemetry._FLIGHTREC``), so
the recorder-off cost on the dispatch path is ONE module-global load —
gated in CI via ``benchmarks/dispatch.py --flightrec-gate``.

Stdlib-only and standalone-loadable on purpose: ``scripts/postmortem.py``
and ``scripts/telemetry_report.py`` load this file via
``spec_from_file_location`` to read rings on machines that never import
jax (a login node, the supervising launcher).
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "FlightRecorder",
    "enable",
    "disable",
    "enabled",
    "recorder",
    "record_event",
    "record_collective",
    "record_dispatch",
    "last_collective",
    "sync",
    "read_ring",
    "find_ring_files",
    "counters",
    "slots_skipped_total",
    "RING_MAGIC",
    "RING_VERSION",
    "DEFAULT_SLOTS",
    "DEFAULT_SLOT_SIZE",
]

RING_MAGIC = b"HTFR"
RING_VERSION = 1
DEFAULT_SLOTS = 2048
DEFAULT_SLOT_SIZE = 256

# header: magic(4s) version(u32) slot_size(u32) n_slots(u32) rank(i32)
#         pid(u32) created(f64) ev_count(u64) — 40 bytes used, padded to 64
_HEADER_FMT = "<4sIIIiIdQ"
_HEADER_SIZE = 64
_EV_COUNT_OFF = struct.calcsize("<4sIIIiId")  # offset of the ev_count field
_LEN_FMT = "<I"
_LEN_SIZE = 4


class FlightRecorder:
    """One rank's ring: fixed-size length-prefixed JSON slots over mmap.

    Appends are O(slot) memory writes under a lock (collective staging and
    span boundaries are never the per-op hot path; the dispatch-path
    recorder only bumps an in-memory per-op counter, coalesced into one
    record at the next append).  The header's event counter is rewritten after every append
    so a reader knows the cursor, but records are self-describing (each
    carries its own ``e``), so a torn counter only costs the reader a
    sort, never a record."""

    def __init__(
        self,
        path: str,
        slots: int = DEFAULT_SLOTS,
        slot_size: int = DEFAULT_SLOT_SIZE,
        rank: int = 0,
    ):
        if slots < 1 or slot_size < _LEN_SIZE + 16:
            raise ValueError(f"ring too small: slots={slots} slot_size={slot_size}")
        self.path = path
        self.n_slots = int(slots)
        self.slot_size = int(slot_size)
        self.rank = int(rank)
        self._ev = 0  # per-rank event counter (ring cursor)
        self._closed = False  # set under the lock; appends become no-ops
        self._seq = 0  # per-rank COLLECTIVE sequence number
        self._last_coll: Optional[Tuple[int, str]] = None
        self._lock = threading.Lock()
        # dispatch fast path: per-op counts accumulated lock-free (GIL) and
        # flushed as ONE coalesced "d" record at the next full append
        self._d_pending: Dict[str, int] = {}
        size = _HEADER_SIZE + self.n_slots * self.slot_size
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        # tmp + rename: a reader (the supervisor harvesting mid-teardown)
        # never maps a half-initialized header.  Unique tmp per pid — SPMD
        # ranks share the directory.
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as fh:
            fh.truncate(size)
            fh.seek(0)
            fh.write(
                struct.pack(
                    _HEADER_FMT,
                    RING_MAGIC,
                    RING_VERSION,
                    self.slot_size,
                    self.n_slots,
                    self.rank,
                    os.getpid() & 0xFFFFFFFF,
                    time.time(),
                    0,
                )
            )
        os.replace(tmp, path)
        self._fh = open(path, "r+b")
        self._mm = mmap.mmap(self._fh.fileno(), size)

    # ------------------------------------------------------------------ #
    def _append(self, build) -> int:
        """Allocate the next slot and write ``build(ev)``'s bytes into it;
        returns the event index.  ``build`` runs under the lock so the
        ``e`` field inside the payload always matches the slot it lands in
        (collectives may be stamped from the watchdog worker thread while
        the main thread records spans)."""
        mm = self._mm
        with self._lock:
            if self._closed:
                # disable() raced an in-flight stamp (the watchdog worker
                # thread may stamp while the main thread disarms): dropping
                # the record beats a ValueError out of collective staging
                return self._ev
            ev = self._ev
            self._ev = ev + 1
            payload = build(ev)
            off = _HEADER_SIZE + (ev % self.n_slots) * self.slot_size
            n = len(payload)
            limit = self.slot_size - _LEN_SIZE
            if n > limit:  # defensive: callers pre-shrink oversize records
                payload = payload[:limit]
                n = limit
            # length LAST: a reader of a torn slot sees either the old
            # record (old length, old bytes intact) or the new one — never
            # a new length over old bytes.  Zero the tail so a shorter new
            # record can't leave parseable garbage from the evicted one.
            mm[off + _LEN_SIZE : off + _LEN_SIZE + n] = payload
            tail = self.slot_size - _LEN_SIZE - n
            if tail:
                mm[off + _LEN_SIZE + n : off + self.slot_size] = b"\x00" * tail
            struct.pack_into(_LEN_FMT, mm, off, n)
            struct.pack_into("<Q", mm, _EV_COUNT_OFF, self._ev)
        return ev

    def _flush_dispatch(self, blocking: bool = True) -> None:
        """Fold the pending per-op dispatch counts into one ``d`` record.
        Called before every full append so the summary lands immediately
        BEFORE the record that closed its window ("these local ops ran
        since the previous full record"), and from :meth:`sync`.

        The detach + snapshot happens under the lock (two concurrent full
        appends must not both serialize the same window), and the snapshot
        is a C-level ``dict()`` copy — atomic under the GIL — because a
        preempted lock-free ``record_dispatch`` may still insert into the
        detached dict: ``json.dumps`` iterating a live dict would raise
        ``RuntimeError`` straight through collective staging, whereas a
        late insert into the detached original after the copy costs one
        context count, which is the documented trade.  ``blocking=False``
        is the signal-flush path: the handler can interrupt THIS thread
        inside the locked region, and a blocking acquire there would
        self-deadlock — skipping the flush just leaves the counts pending."""
        if not self._lock.acquire(blocking):
            return
        try:
            if not self._d_pending:
                return
            pend = dict(self._d_pending)
            self._d_pending = {}
        finally:
            self._lock.release()
        t = time.time()

        def build(ev: int) -> bytes:
            rec = {"e": ev, "t": t, "k": "d", "ops": pend}
            payload = json.dumps(rec, separators=(",", ":"), default=str).encode()
            if len(payload) > self.slot_size - _LEN_SIZE:
                payload = json.dumps(
                    {"e": ev, "t": t, "k": "d", "n": sum(pend.values()), "trunc": 1},
                    separators=(",", ":"),
                ).encode()
            return payload

        self._append(build)

    def record(self, kind: str, **fields: Any) -> int:
        """Append one event of ``kind`` with JSON-able ``fields``."""
        self._flush_dispatch()
        t = time.time()

        def build(ev: int) -> bytes:
            rec: Dict[str, Any] = {"e": ev, "t": t, "k": kind}
            rec.update(fields)
            limit = self.slot_size - _LEN_SIZE
            payload = json.dumps(rec, separators=(",", ":"), default=str).encode()
            if len(payload) > limit:
                # too big for a slot: shed the bulky attributes (gshape,
                # path, span attrs...) but KEEP the small identity fields —
                # dropping a coll record's seq/op would punch a hole in the
                # very stream the post-mortem diagnoses from
                small = {
                    f: rec[f]
                    for f in ("seq", "op", "name", "wire", "dtype", "src",
                              "dst", "tid")
                    if f in rec
                }
                rec = {"e": ev, "t": t, "k": kind, **small, "trunc": 1}
                payload = json.dumps(
                    rec, separators=(",", ":"), default=str
                ).encode()
                if len(payload) > limit:  # pathological field values
                    payload = json.dumps(
                        {"e": ev, "t": t, "k": kind, "trunc": 1},
                        separators=(",", ":"),
                    ).encode()
            return payload

        return self._append(build)

    def record_collective(
        self,
        name: str,
        wire_bytes: int,
        x: Any = None,
        src_split: Optional[int] = None,
        dst_split: Optional[int] = None,
    ) -> int:
        """Stamp one staged collective: bump the per-rank sequence number
        and append the fingerprint.  ``x`` may be an array or tracer (shape
        and dtype are read defensively) or None."""
        gshape = dtype = None
        if x is not None:
            try:
                gshape = [int(s) for s in x.shape]
                dtype = str(x.dtype)
            except Exception:
                pass
        dl = _deadline_remaining()
        tid = _trace_id()
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._last_coll = (seq, name)
        fields: Dict[str, Any] = {"seq": seq, "op": name, "wire": int(wire_bytes)}
        if gshape is not None:
            fields["gshape"] = gshape
            fields["dtype"] = dtype
        if src_split is not None:
            fields["src"] = src_split
        if dst_split is not None:
            fields["dst"] = dst_split
        if dl is not None:
            fields["dl"] = round(dl, 3)
        if tid is not None:
            # the causal join key: this staged collective belongs to the
            # ambient trace (a scheduler job's dispatch, a traced train
            # step) — deliberately NOT part of the post-mortem fingerprint
            # (postmortem._FP_FIELDS), so trace identity can never convict
            # a rank of desync
            fields["tid"] = tid
        self.record("coll", **fields)
        return seq

    def record_dispatch(self, op_name: str) -> None:
        """The ONE recorder call on the per-op hot path, so it is a single
        dict increment — the same cost class as the telemetry dispatch
        hook, and what keeps the recorder-on cost inside the ±5%
        ``--flightrec-gate``.  The counts coalesce into one ``d`` summary
        record at the next full append (:meth:`_flush_dispatch`): a ring
        write per dispatch measured ~10× that, because any main-thread
        Python burns GIL time the async XLA workers are bidding for.  No
        lock: a lost increment under cross-thread interleaving costs one
        count in a context record, never a collective stamp."""
        pend = self._d_pending
        pend[op_name] = pend.get(op_name, 0) + 1

    def last_collective(self) -> Optional[Tuple[int, str]]:
        """(seq, op name) of the most recently stamped collective, or None
        — folded into the heartbeat beacon by ``health.write_heartbeat``."""
        return self._last_coll

    def sync(self) -> None:
        """Flush pending dispatch counts into the ring, then the mapped
        pages to disk (graceful-exit path only — the signal-flush handler
        and tests; never the hot path).  The dispatch flush is
        NON-blocking: this can run from a signal handler that interrupted
        the very thread holding the append lock."""
        self._flush_dispatch(blocking=False)
        try:
            self._mm.flush()
        except (OSError, ValueError):
            pass

    def close(self) -> None:
        self._flush_dispatch()
        # the flag flips under the append lock: a stamp that held the lock
        # when we got here has fully written its slot; any later one sees
        # the flag and drops the record instead of writing a closed mmap
        with self._lock:
            self._closed = True
        try:
            self._mm.flush()
            self._mm.close()
        except (OSError, ValueError):
            pass
        try:
            self._fh.close()
        except OSError:
            pass


def _deadline_remaining() -> Optional[float]:
    """Remaining budget of the armed ``comm.deadline`` — via ``sys.modules``
    so a standalone load of this file never imports the package."""
    hlth = sys.modules.get("heat_tpu.utils.health")
    if hlth is None:
        return None
    try:
        dl = hlth.active_deadline()
        return dl.remaining() if dl is not None else None
    except Exception:
        return None


def _trace_id() -> Optional[str]:
    """The ambient trace id (``telemetry.tracing``) — via ``sys.modules``
    for the same standalone-load reason.  Works with telemetry DISABLED:
    trace identity is a contextvar, not span-ring state, so the
    crash-durable ring carries a job's causal path even when nothing
    exports spans."""
    tel = sys.modules.get("heat_tpu.utils.telemetry")
    if tel is None:
        return None
    try:
        return tel.current_trace_id()
    except Exception:
        return None


# ---------------------------------------------------------------------- #
# module-global recorder + hot-path hook poking (the telemetry pattern)
# ---------------------------------------------------------------------- #
_RECORDER: Optional[FlightRecorder] = None


def _rank() -> int:
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        try:
            return int(jax_mod.process_index())
        except Exception:
            pass
    return int(
        os.environ.get(
            "HEAT_TPU_FLIGHTREC_RANK",
            os.environ.get("HEAT_TPU_TELEMETRY_RANK", "0"),
        )
        or 0
    )


def _poke_hooks(on: bool) -> None:
    """Arm/disarm the hot-path hooks: each consumer module reads its OWN
    ``_FLIGHTREC`` global (one load, no call) to decide whether to record."""
    me = sys.modules.get(__name__) if on else None
    for name in (
        "heat_tpu.core._operations",
        "heat_tpu.core.communication",
        "heat_tpu.utils.telemetry",
    ):
        mod = sys.modules.get(name)
        if mod is not None:
            mod._FLIGHTREC = me


def enabled() -> bool:
    return _RECORDER is not None


def recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def enable(
    directory: Optional[str] = None,
    rank: Optional[int] = None,
    slots: int = DEFAULT_SLOTS,
    slot_size: int = DEFAULT_SLOT_SIZE,
) -> str:
    """Arm the flight recorder: ring file ``{dir}/flight_rank{k}.ring``
    (``directory`` or ``HEAT_TPU_FLIGHTREC_DIR``).  Re-enabling replaces
    the ring — each supervisor generation starts a clean black box (the
    previous generation's ring was harvested at teardown).  Returns the
    ring path."""
    global _RECORDER
    directory = directory or os.environ.get("HEAT_TPU_FLIGHTREC_DIR")
    if not directory:
        raise ValueError(
            "flightrec.enable() needs a directory (arg or HEAT_TPU_FLIGHTREC_DIR)"
        )
    r = _rank() if rank is None else int(rank)
    old, _RECORDER = _RECORDER, None
    if old is not None:
        old.close()
    path = os.path.join(directory, f"flight_rank{r}.ring")
    _RECORDER = FlightRecorder(path, slots=slots, slot_size=slot_size, rank=r)
    _poke_hooks(True)
    # graceful kills (SIGTERM/SIGINT) flush the telemetry ring AND msync
    # this one — the satellite hardening; in-package only (a standalone
    # load is tooling that must not install process-wide handlers)
    if __package__:
        try:
            from . import telemetry

            telemetry.install_signal_flush()
        except Exception:
            pass
    return path


def disable() -> None:
    """Disarm and close the ring (the file stays on disk for the analyzer)."""
    global _RECORDER
    old, _RECORDER = _RECORDER, None
    _poke_hooks(False)
    if old is not None:
        old.close()


def record_event(kind: str, **fields: Any) -> None:
    """Append one event when armed; no-op (one global check) when not."""
    r = _RECORDER
    if r is not None:
        r.record(kind, **fields)


def record_collective(
    name: str,
    wire_bytes: int,
    x: Any = None,
    src_split: Optional[int] = None,
    dst_split: Optional[int] = None,
) -> None:
    r = _RECORDER
    if r is not None:
        r.record_collective(name, wire_bytes, x, src_split, dst_split)


def record_dispatch(op_name: str) -> None:
    r = _RECORDER
    if r is not None:
        r.record_dispatch(op_name)


def last_collective() -> Optional[Tuple[int, str]]:
    r = _RECORDER
    return r.last_collective() if r is not None else None


def sync() -> None:
    r = _RECORDER
    if r is not None:
        r.sync()


# ---------------------------------------------------------------------- #
# reader — used by scripts/postmortem.py and scripts/telemetry_report.py
# (loaded standalone); tolerant of torn slots and foreign garbage
# ---------------------------------------------------------------------- #
# torn/unparseable slots seen inside written ring regions by THIS
# process's read_ring calls — the reader-side honesty counter (the writer
# path stays untouched: zero new hot-path cost).  Rides /metrics via
# monitor._runtime_counters when nonzero.
_SLOTS_SKIPPED = 0


def slots_skipped_total() -> int:
    """Torn/unparseable written slots skipped by reads in this process."""
    return _SLOTS_SKIPPED


def counters() -> Dict[str, int]:
    """Monitor-facing counters (empty while nothing was skipped, keeping
    /metrics noise-free — like ``telemetry.ring.dropped``)."""
    if _SLOTS_SKIPPED:
        return {"flightrec.slots.skipped": _SLOTS_SKIPPED}
    return {}


def read_ring(path: str) -> Dict[str, Any]:
    """Parse one ring file: header fields + records sorted by event index.

    Unparseable slots (torn writes, zeroed tails) are skipped — the black
    box must be readable after ANY crash, so a bad slot costs one record,
    never the file.  Skips inside the *written* region are COUNTED
    (``slots_skipped`` in the result, accumulated into
    ``flightrec.slots.skipped``): a lossy ring must never read as a
    complete one.  Slots the writer never reached (``ev_count`` short of a
    full ring) are simply empty, not torn, and are not counted."""
    with open(path, "rb") as fh:
        head = fh.read(_HEADER_SIZE)
        if len(head) < _HEADER_SIZE:
            raise ValueError(f"{path}: truncated ring header")
        magic, version, slot_size, n_slots, rank, pid, created, ev_count = (
            struct.unpack_from(_HEADER_FMT, head)
        )
        if magic != RING_MAGIC:
            raise ValueError(f"{path}: not a flight-recorder ring (magic {magic!r})")
        records: List[dict] = []
        skipped = 0
        # slots the writer reached: the whole ring once it has wrapped,
        # else the first ev_count.  (A torn ev_count merely shifts this
        # boundary by the one in-flight record; it cannot hide a torn slot
        # deep inside the written region.)
        written = n_slots if ev_count >= n_slots else ev_count
        for i in range(n_slots):
            slot = fh.read(slot_size)
            if len(slot) < _LEN_SIZE:
                break
            (n,) = struct.unpack_from(_LEN_FMT, slot)
            if n == 0 or n > slot_size - _LEN_SIZE:
                if i < written:
                    skipped += 1
                continue
            try:
                rec = json.loads(slot[_LEN_SIZE : _LEN_SIZE + n])
            except ValueError:
                if i < written:
                    skipped += 1
                continue
            if isinstance(rec, dict) and "e" in rec:
                records.append(rec)
            elif i < written:
                skipped += 1
    if skipped:
        global _SLOTS_SKIPPED
        _SLOTS_SKIPPED += skipped
    records.sort(key=lambda r: r.get("e", 0))
    return {
        "path": path,
        "version": version,
        "rank": rank,
        "pid": pid,
        "created": created,
        "ev_count": ev_count,
        "n_slots": n_slots,
        "slot_size": slot_size,
        "slots_skipped": skipped,
        "records": records,
    }


def find_ring_files(directory: str) -> List[str]:
    """``flight_rank*.ring`` files under ``directory`` (non-recursive),
    sorted by rank number."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        if name.startswith("flight_rank") and name.endswith(".ring"):
            out.append(os.path.join(directory, name))

    def key(p: str) -> Tuple[int, str]:
        base = os.path.basename(p)[len("flight_rank") : -len(".ring")]
        try:
            return (int(base), p)
        except ValueError:
            return (1 << 30, p)

    return sorted(out, key=key)


# env arming: one check at import (io.py imports this module at package
# import, so HEAT_TPU_FLIGHTREC_DIR takes effect process-wide).  Gated on
# __package__ exactly like telemetry: a STANDALONE load of this file is
# tooling (the postmortem reader) and must not create ring files.
def _env_arm() -> None:
    directory = os.environ.get("HEAT_TPU_FLIGHTREC_DIR")
    if not directory:
        return
    try:
        enable()
    except OSError as e:
        # an unwritable dir must not kill the runtime import — but a
        # silently-disarmed black box is exactly the failure this module
        # exists to prevent, so say it happened
        import warnings

        warnings.warn(
            f"HEAT_TPU_FLIGHTREC_DIR={directory!r} is set but the flight "
            f"recorder could not arm ({e!r}); this process will leave NO "
            "ring file for the post-mortem",
            RuntimeWarning,
            stacklevel=2,
        )


if __package__:
    _env_arm()
