"""Telemetry layer (ISSUE 3): spans, byte accounting, histograms, export —
plus the profiler satellite fixes (provider namespacing, exception-safe
timer, weakref pruning)."""

import gc
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import _operations
from heat_tpu.utils import profiler, telemetry

# NOT mp-marked: these tests toggle global telemetry state and write rank
# files into tmp dirs — under the SPMD lane's shared tmp_path both ranks
# would race on rank{k}.jsonl sets and counter totals.  The multi-rank
# telemetry path is covered by the dryrun's per-rank export + merge check
# (scripts/multiprocess_dryrun.py, asserted in test_multiprocess.py).


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Every test starts disarmed with empty rings/counters and leaves the
    process the same way (telemetry state is global by design)."""
    telemetry.disable()
    telemetry.reset()
    profiler.reset_counters()
    yield
    telemetry.disable()
    telemetry.reset()
    profiler.reset_counters()


def _ring_names():
    return [r[0] for r in telemetry._ring]


class TestSpans:
    def test_span_records_nesting_and_self_time(self):
        telemetry.enable()
        with telemetry.span("outer", kind="test"):
            with telemetry.span("inner"):
                pass
        recs = {r[0]: r for r in telemetry._ring}
        assert set(recs) == {"outer", "inner"}
        name, ts, dur, self_s, depth, attrs = recs["outer"]
        assert depth == 0 and attrs == {"kind": "test"}
        assert recs["inner"][4] == 1  # nested depth
        # parent self-time excludes the child's wall time
        assert recs["outer"][3] <= recs["outer"][2]
        assert recs["inner"][2] <= recs["outer"][2]

    def test_span_survives_exceptions_and_tags_error(self):
        telemetry.enable()
        with pytest.raises(ValueError):
            with telemetry.span("boom"):
                raise ValueError("x")
        (rec,) = list(telemetry._ring)
        assert rec[0] == "boom" and rec[5]["error"] == "ValueError"

    def test_disabled_span_is_shared_null_object(self):
        assert telemetry.span("a") is telemetry.span("b")
        with telemetry.span("nope", anything=1) as s:
            s.set(more=2)  # null span absorbs attribute updates too
        assert len(telemetry._ring) == 0

    def test_span_attrs_set_midway(self):
        telemetry.enable()
        with telemetry.span("s", a=1) as s:
            s.set(b=2)
        (rec,) = list(telemetry._ring)
        assert rec[5] == {"a": 1, "b": 2}

    def test_disabled_noop_under_jit_tracing(self):
        """Satellite: span() inside a traced function must be a no-op when
        disabled and must not break tracing when enabled."""

        def f(a):
            with telemetry.span("traced.block"):
                return a * 2

        out = jax.jit(f)(jnp.ones(4))
        np.testing.assert_allclose(np.asarray(out), 2.0)
        assert len(telemetry._ring) == 0  # disabled: nothing recorded

        telemetry.enable()
        out = jax.jit(f)(jnp.ones(8))  # fresh shape -> fresh trace
        np.testing.assert_allclose(np.asarray(out), 2.0)
        assert "traced.block" in _ring_names()  # recorded at trace time

    def test_ring_is_bounded(self):
        telemetry.enable()
        for i in range(telemetry._ring.maxlen + 100):
            telemetry.record_event("e", 1e-6)
        assert len(telemetry._ring) == telemetry._ring.maxlen


class TestDispatchInstrumentation:
    def test_dispatch_tail_records_op_and_cache_state(self):
        x = ht.random.randn(16, 16, split=0)
        y = ht.random.randn(16, 16, split=0)
        _ = x + y  # compile outside the armed window
        telemetry.enable()
        telemetry.reset()
        _ = x + y
        _ = ht.sum(x, axis=0)
        recs = list(telemetry._ring)
        kinds = {r[0]: r[5] for r in recs}
        assert kinds["dispatch.binary"]["op"] == "add"
        assert kinds["dispatch.binary"]["cache"] == "hit"
        assert "dispatch.reduce" in kinds
        # a fresh signature through the armed window records a miss
        # (mesh-divisible leading extent: ragged shapes take the pad path)
        z = ht.random.randn(8 * ht.communication.get_comm().size, 4, split=0)
        telemetry.reset()
        _ = z + z
        (rec,) = [r for r in telemetry._ring if r[0] == "dispatch.binary"]
        assert rec[5]["cache"] == "miss"

    def test_disabled_dispatch_adds_nothing(self):
        """The telemetry-off contract: the hot-path hook is None and no
        record is ever created."""
        assert _operations._TELEMETRY is None
        x = ht.random.randn(8, 8, split=0)
        _ = x + x
        assert len(telemetry._ring) == 0
        telemetry.enable()
        assert _operations._TELEMETRY is telemetry
        telemetry.disable()
        assert _operations._TELEMETRY is None


class TestCollectiveAccounting:
    def _fresh_comm(self):
        # a fresh Communication => fresh program caches => the collectives
        # genuinely re-stage (byte accounting happens at trace time)
        return ht.core.communication.Communication(ht.communication.get_comm().mesh)

    def test_resplit_bytes_and_calls(self):
        m = ht.reshape(ht.arange(64, dtype=ht.float32, split=0), (8, 8))
        m.resplit_(1)
        c = profiler.counters()
        assert c["comm.resplit.calls"] >= 1
        p = m.comm.size
        # (p-1)/p of the global payload crosses the wire
        assert c["comm.resplit.bytes"] >= int(64 * 4 * (p - 1) / p)

    def test_noop_resplit_not_counted(self):
        """A resplit to the sharding the array already carries moves no
        bytes and must not inflate the redistribution traffic metric."""
        x = ht.zeros((16, 16), split=0)
        x._jarray  # force canonical placement
        before = profiler.counters().get("comm.resplit.calls", 0)
        _ = x.comm.resplit(x._jarray, 0)
        _ = x.comm.resplit(x._jarray, 0, donate=True)
        c = profiler.counters()
        assert c.get("comm.resplit.calls", 0) == before
        assert c.get("comm.resplit.bytes", 0) == 0

    def test_allreduce_traffic_factor(self):
        comm = self._fresh_comm()
        p = comm.size
        prog = comm.shard_map(lambda v: comm.Allreduce(v), ((1, 0),), (1, None))
        out = prog(jnp.ones(8 * p, jnp.float32))
        np.testing.assert_allclose(np.asarray(out)[:1], p)  # p ones summed
        c = profiler.counters()
        assert c["comm.Allreduce.calls"] == 1
        # per-shard payload 8*4 bytes x ring factor 2(p-1)/p
        assert c["comm.Allreduce.bytes"] == int(round(8 * 4 * 2 * (p - 1) / p))

    def test_summa_matmul_shows_send_bytes(self):
        """Acceptance: per-collective calls/bytes for a SUMMA matmul."""
        comm = self._fresh_comm()
        rng = np.random.default_rng(0)
        a = ht.array(rng.standard_normal((32, 32)).astype(np.float32), split=0, comm=comm)
        b = ht.array(rng.standard_normal((32, 32)).astype(np.float32), split=0, comm=comm)
        out = ht.linalg.matmul_summa(a, b)
        np.testing.assert_allclose(out.numpy(), a.numpy() @ b.numpy(), atol=1e-4)
        c = profiler.counters()
        assert c["comm.Send.calls"] >= 1  # the K-block ring rotation
        assert c["comm.Send.bytes"] > 0

    def test_scan_exscan_prod_attribution(self):
        """Scan/Exscan account under their own names (not the shared
        helper), and Allreduce-prod accounts ONCE with scan+psum cost."""
        comm = self._fresh_comm()
        p = comm.size
        nb = 8 * 4  # per-shard payload bytes below
        x = jnp.ones(8 * p, jnp.float32)
        _ = comm.shard_map(lambda v: comm.Exscan(v), ((1, 0),), (1, 0))(x)
        _ = comm.shard_map(lambda v: comm.Scan(v), ((1, 0),), (1, 0))(x)
        _ = comm.shard_map(lambda v: comm.Allreduce(v, "prod"), ((1, 0),), (1, None))(x)
        c = profiler.counters()
        logp = max(p - 1, 0).bit_length()
        assert c["comm.Exscan.calls"] == 1
        assert c["comm.Exscan.bytes"] == int(round(nb * (logp + 1)))
        assert c["comm.Scan.calls"] == 1  # Exscan's inner scan not re-counted
        assert c["comm.Scan.bytes"] == int(round(nb * logp))
        assert c["comm.Allreduce.calls"] == 1
        assert c["comm.Allreduce.bytes"] == int(round(nb * (2 * (p - 1) / p + logp)))

    def test_gather_fallback_counter(self):
        """Satellite: gather-based collectives count under
        comm.gather_fallback.<name> even below the warn threshold."""
        comm = self._fresh_comm()
        p = comm.size
        prog = comm.shard_map(lambda v: comm.Gather(v), ((1, 0),), (1, 0))
        _ = prog(jnp.arange(8 * p, dtype=jnp.float32))
        c = profiler.counters()
        assert c["comm.gather_fallback.Gather"] >= 1
        assert c["comm.Gather.calls"] >= 1

    def test_payload_nbytes_on_tracers(self):
        from heat_tpu.core.communication import _payload_nbytes

        assert _payload_nbytes(jnp.ones((4, 2), jnp.float32)) == 32

        seen = {}

        def f(v):
            seen["n"] = _payload_nbytes(v)  # v is a tracer here
            return v

        jax.jit(f)(jnp.ones((4, 2), jnp.float32))
        assert seen["n"] == 32


class TestHistogram:
    def test_summary_and_quantiles(self):
        h = telemetry.Histogram("lat")
        for v in [1e-5] * 50 + [1e-3] * 40 + [1e-1] * 10:
            h.observe(v)
        s = h.summary()
        assert s["count"] == 100
        assert s["min_s"] == pytest.approx(1e-5)
        assert s["max_s"] == pytest.approx(1e-1)
        assert s["p50_s"] <= s["p90_s"] <= s["p99_s"] <= s["max_s"] * 1.6
        # p50 sits in the 10us decade, p99 near the top
        assert s["p50_s"] < 1e-3
        assert s["p99_s"] > 1e-2

    def test_bounded_memory_and_degenerate_values(self):
        h = telemetry.Histogram("x")
        n_slots = len(h.counts)
        for i in range(10000):
            h.observe(i * 1e-7)
        h.observe(float("nan"))
        h.observe(-1.0)
        h.observe(1e9)  # overflow bin
        assert len(h.counts) == n_slots
        assert h.count == 10003

    def test_observe_helper_and_report_section(self):
        telemetry.observe("unit.lat_s", 0.01)
        telemetry.observe("unit.lat_s", 0.02)
        rep = telemetry.report()
        assert rep["histograms"]["unit.lat_s"]["count"] == 2


class TestReportAndExport:
    def test_report_merges_counters_hists_spans(self):
        telemetry.enable()
        profiler.counter_inc("unit.events", 3)
        telemetry.observe("unit.lat_s", 0.5)
        with telemetry.span("unit.work"):
            pass
        rep = telemetry.report()
        assert rep["enabled"] is True
        assert rep["counters"]["unit.events"] == 3
        assert "cache.hits" in rep["counters"]  # cache.* provider rides along
        assert rep["histograms"]["unit.lat_s"]["count"] == 1
        assert any(r["name"] == "unit.work" for r in rep["top_spans"])

    def test_flush_and_cli_merge(self, tmp_path):
        telemetry.enable()
        with telemetry.span("unit.flushme", tag="t"):
            pass
        telemetry.observe("unit.lat_s", 0.002)
        profiler.counter_inc("unit.flush_counter", 7)
        d = str(tmp_path / "tel")
        path = telemetry.flush(d)
        assert path is not None and os.path.exists(path)
        lines = [json.loads(line) for line in open(path)]
        types = {rec["type"] for rec in lines}
        assert {"meta", "span", "counters", "hist"} <= types
        assert len(telemetry._ring) == 0  # flush drains the ring

        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "telemetry_report",
            os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                         "scripts", "telemetry_report.py"),
        )
        trep = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(trep)
        merged = trep.merge_files(trep.find_rank_files(d))
        assert any(r["name"] == "unit.flushme" for r in merged["span_summary"])
        assert merged["counters"]["unit.flush_counter"] == 7
        assert merged["histograms"]["unit.lat_s"]["count"] == 1
        text = trep.render(merged)
        assert "unit.flushme" in text and "unit.flush_counter" in text
        # two-rank merge: fake a second rank's file, counters must SUM
        second = [dict(rec, rank=1) for rec in lines]
        with open(os.path.join(d, "rank1.jsonl"), "w") as fh:
            for rec in second:
                fh.write(json.dumps(rec) + "\n")
        merged2 = trep.merge_files(trep.find_rank_files(d))
        assert merged2["ranks"] == [0, 1]
        assert merged2["counters"]["unit.flush_counter"] == 14
        assert merged2["histograms"]["unit.lat_s"]["count"] == 2
        # CLI entry point end to end
        out_json = str(tmp_path / "merged.json")
        assert trep.main([d, "--json", out_json]) == 0
        assert json.load(open(out_json))["ranks"] == [0, 1]
        # a SECOND flush of the same rank appends a fresh cumulative
        # histogram snapshot — within one rank the last snapshot must win
        # (summing would double-count every observation)
        telemetry.observe("unit.lat_s", 0.002)
        telemetry.flush(d)
        merged3 = trep.merge_files(trep.find_rank_files(d))
        # rank0 now has 2 observations (last snapshot), fake rank1 has 1
        assert merged3["histograms"]["unit.lat_s"]["count"] == 3

    def test_flush_without_dir_is_none(self):
        telemetry.enable()
        env_dir = os.environ.pop("HEAT_TPU_TELEMETRY_DIR", None)
        saved = telemetry._flush_dir
        telemetry._flush_dir = None
        try:
            assert telemetry.flush() is None
        finally:
            telemetry._flush_dir = saved
            if env_dir is not None:
                os.environ["HEAT_TPU_TELEMETRY_DIR"] = env_dir


class TestIOInstrumentation:
    def test_checkpoint_bytes_fsync_and_span(self, tmp_path):
        telemetry.enable()
        x = ht.arange(64, dtype=ht.float32, split=0)
        ht.save_array_checkpoint(x, str(tmp_path / "ckpt"))
        c = profiler.counters()
        assert c["io.bytes_written"] > 64 * 4  # chunks + meta + LATEST tmp
        assert c["io.fsync.calls"] >= 4  # files + dir fsyncs
        names = _ring_names()
        assert "io.save_array_checkpoint" in names
        back = ht.load_array_checkpoint(str(tmp_path / "ckpt"))
        np.testing.assert_allclose(back.numpy(), x.numpy())
        assert "io.load_array_checkpoint" in _ring_names()

    def test_pytree_checkpoint_counts_bytes(self, tmp_path):
        from heat_tpu.core import io as htio

        telemetry.enable()
        tree = {"w": jnp.ones((8, 8), jnp.float32)}
        htio.save_checkpoint(tree, str(tmp_path / "t.npz"))
        c = profiler.counters()
        assert c["io.bytes_written"] > 0
        assert "io.save_checkpoint" in _ring_names()


class TestOptimInstrumentation:
    def test_eager_step_histogram_and_guard_provider(self):
        telemetry.enable()
        opt = ht.optim.DataParallelOptimizer("sgd", lr=0.1)
        params = {"w": jnp.ones(4, jnp.float32)}
        grads = {"w": jnp.full(4, 0.5, jnp.float32)}
        params = opt.step(params, grads)
        rep = telemetry.report()
        assert rep["histograms"]["optim.step_dispatch_s"]["count"] == 1
        assert any(r["name"] == "optim.step" for r in rep["top_spans"])
        # guard counters surface under the instance's unique provider key
        assert rep["counters"][f"{opt.profiler_key}.steps"] == 1
        assert rep["counters"][f"{opt.profiler_key}.skipped_steps"] == 0

    def test_daso_step_histogram(self):
        if len(jax.devices()) % 2:
            pytest.skip("DASO needs an even device count")
        telemetry.enable()
        from heat_tpu.optim.dp_optimizer import DASO, DataParallelOptimizer

        daso = DASO(DataParallelOptimizer("sgd", lr=0.1), warmup_steps=0)
        daso.init(ht.nn.Sequential(ht.nn.Linear(8, 4)), key=jax.random.key(0))
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
        y = jnp.asarray(rng.standard_normal((16, 4)), jnp.float32)
        daso.step(lambda pred, t: jnp.mean((pred - t) ** 2), x, y)
        rep = telemetry.report()
        assert rep["histograms"]["daso.step_dispatch_s"]["count"] == 1
        assert any(r["name"] == "daso.step" for r in rep["top_spans"])

    def test_train_step_wrapper_keeps_lower(self):
        opt = ht.optim.DataParallelOptimizer("sgd", lr=0.1)
        dp = ht.nn.DataParallel(ht.nn.Sequential(ht.nn.Linear(8, 4)), optimizer=opt)
        params = dp.init(jax.random.key(0))
        state = opt.init_state(params)
        step = dp.make_train_step(lambda p, y: jnp.mean((p - y) ** 2))
        x = jnp.zeros((16, 8), jnp.float32)
        y = jnp.zeros((16, 4), jnp.float32)
        assert "input_output_alias" in step.lower(params, state, x, y).compile().as_text()
        telemetry.enable()
        params, state, loss = step(params, state, x, y)
        rep = telemetry.report()
        assert rep["histograms"]["nn.train_step_dispatch_s"]["count"] == 1


class TestProfilerSatellites:
    def test_provider_prefix_collision_regression(self):
        """Satellite: a provider key that startswith the provider name must
        NOT overwrite an identically-named plain counter."""
        profiler.counter_inc("svc_total", 3)

        key = profiler.register_counter_provider("svc", lambda: {"svc_total": 7})
        try:
            c = profiler.counters()
            assert c["svc_total"] == 3  # the plain counter survives
            assert c[f"{key}.svc_total"] == 7  # the provider is namespaced
        finally:
            profiler._providers.pop(key, None)

    def test_provider_already_dotted_key_not_double_prefixed(self):
        key = profiler.register_counter_provider("dot", lambda: {"dot.x": 1, "y": 2})
        try:
            c = profiler.counters()
            assert c["dot.x"] == 1 and c["dot.y"] == 2
            assert "dot.dot.x" not in c
        finally:
            profiler._providers.pop(key, None)

    def test_timer_exception_safe(self):
        """Satellite: a raising block still records its elapsed time."""
        holder = {}
        with pytest.raises(RuntimeError):
            with profiler.timer("t", holder):
                raise RuntimeError("boom")
        assert holder["t"] >= 0.0

    def test_timer_normal_path(self):
        holder = {}
        with profiler.timer("ok", holder):
            pass
        assert holder["ok"] >= 0.0

    def test_provider_weakref_pruned_after_gc(self):
        """Satellite: a bound-method provider dies with its owner and is
        dropped at the next counters() read."""

        class Owner:
            def snapshot(self):
                return {"alive": 1}

        o = Owner()
        key = profiler.register_counter_provider("weakowner", o.snapshot)
        assert profiler.counters()[f"{key}.alive"] == 1
        assert key in profiler._providers
        del o
        gc.collect()
        c = profiler.counters()
        assert f"{key}.alive" not in c
        assert key not in profiler._providers  # pruned, not just skipped


class TestServingSLOReport:
    """ISSUE 10 satellite: scripts/telemetry_report.py renders the
    per-tenant SLO table (p50/p99 queue wait + execution latency) from
    whichever serving artifacts exist — sched.job spans, the scheduler
    journal, or both.  A journal-only dir (all a SIGKILLed serving rank
    leaves behind) is a legitimate target: exit 0, full table."""

    def _trep(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "telemetry_report_slo",
            os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                         "scripts", "telemetry_report.py"),
        )
        trep = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(trep)
        return trep

    def _write_journal(self, d):
        recs = [
            {"type": "meta", "schema": 1, "epoch": 0, "t": 100.0},
            {"type": "submitted", "id": "a", "kind": "matmul",
             "tenant": "acme", "priority": 0, "t": 100.0},
            {"type": "dispatched", "id": "a", "seq": 1, "attempt": 1,
             "t": 100.25, "epoch": 0},
            {"type": "done", "id": "a", "exec_s": 0.5, "t": 100.75,
             "epoch": 0},
            {"type": "submitted", "id": "b", "kind": "solve",
             "tenant": "acme", "priority": 0, "t": 100.0},
            {"type": "dispatched", "id": "b", "seq": 2, "attempt": 1,
             "t": 101.0, "epoch": 0},
            {"type": "failed", "id": "b", "reason": "deadline_expired",
             "t": 101.5, "epoch": 0},
            {"type": "shed", "id": "c", "kind": "matmul",
             "tenant": "globex", "reason": "queue_full", "t": 100.1,
             "epoch": 0},
        ]
        path = os.path.join(d, "sched_journal.jsonl")
        with open(path, "w") as fh:
            for r in recs:
                fh.write(json.dumps(r) + "\n")
        return path

    def test_journal_only_dir_renders_slo_exit_zero(self, tmp_path, capsys):
        trep = self._trep()
        d = str(tmp_path)
        self._write_journal(d)
        assert trep.main([d]) == 0
        out = capsys.readouterr().out
        assert "per-tenant serving SLO" in out
        # acme: 2 jobs (1 done, 1 failed); globex: 1 shed
        acme = [l for l in out.splitlines() if l.startswith("acme")][0]
        assert acme.split()[1:5] == ["2", "1", "1", "0"]
        globex = [l for l in out.splitlines() if l.startswith("globex")][0]
        assert globex.split()[1:5] == ["1", "0", "0", "1"]
        # journal-timestamp latencies: acme queue wait p50 = 250 ms
        assert "250.0" in acme

    def test_spans_only_dir_renders_slo(self, tmp_path, capsys):
        trep = self._trep()
        d = str(tmp_path)
        spans = [
            {"type": "span", "rank": 0, "name": "sched.job", "ts": 100.0,
             "dur_s": 0.2, "self_s": 0.2, "depth": 0,
             "attrs": {"id": "a", "tenant": "acme", "kind": "matmul",
                       "outcome": "done", "queue_wait_s": 0.05,
                       "attempts": 1}},
            {"type": "span", "rank": 0, "name": "sched.job", "ts": 101.0,
             "dur_s": 0.4, "self_s": 0.4, "depth": 0,
             "attrs": {"id": "b", "tenant": "acme", "kind": "matmul",
                       "outcome": "retries_exhausted", "queue_wait_s": 0.15,
                       "attempts": 3}},
        ]
        with open(os.path.join(d, "rank0.jsonl"), "w") as fh:
            for r in spans:
                fh.write(json.dumps(r) + "\n")
        assert trep.main([d, "--timeline", "0"]) == 0
        out = capsys.readouterr().out
        assert "per-tenant serving SLO" in out
        acme = [l for l in out.splitlines() if l.startswith("acme")][0]
        # 2 jobs, 1 done, 1 failed — outcome counts from the span attrs
        assert acme.split()[1:5] == ["2", "1", "1", "0"]
        # exec p50 from span durations: 200 ms
        assert "200.0" in acme

    def test_spans_and_journal_merge(self, tmp_path, capsys):
        """Both present: outcome counts come from the journal (it alone
        knows shed jobs), latencies from the spans."""
        trep = self._trep()
        d = str(tmp_path)
        self._write_journal(d)
        with open(os.path.join(d, "rank0.jsonl"), "w") as fh:
            fh.write(json.dumps(
                {"type": "span", "rank": 0, "name": "sched.job", "ts": 100.0,
                 "dur_s": 0.125, "self_s": 0.125, "depth": 0,
                 "attrs": {"id": "a", "tenant": "acme", "kind": "matmul",
                           "outcome": "done", "queue_wait_s": 0.0625,
                           "attempts": 1}}) + "\n")
        assert trep.main([d, "--timeline", "0"]) == 0
        out = capsys.readouterr().out
        acme = [l for l in out.splitlines() if l.startswith("acme")][0]
        assert acme.split()[1:5] == ["2", "1", "1", "0"]  # journal counts
        assert "125.0" in acme and "62.5" in acme  # span latencies

    def test_spans_deduped_across_ranks_by_job_id(self, tmp_path, capsys):
        """Review finding: every rank of an SPMD serve world emits an
        identical sched.job span per job — a 2-rank dir must count each
        job ONCE, not once per rank."""
        trep = self._trep()
        d = str(tmp_path)
        span = {"type": "span", "name": "sched.job", "ts": 100.0,
                "dur_s": 0.2, "self_s": 0.2, "depth": 0,
                "attrs": {"id": "a", "tenant": "acme", "kind": "matmul",
                          "outcome": "done", "queue_wait_s": 0.05,
                          "attempts": 1}}
        for rank in (0, 1):
            with open(os.path.join(d, f"rank{rank}.jsonl"), "w") as fh:
                fh.write(json.dumps(dict(span, rank=rank)) + "\n")
        assert trep.main([d, "--timeline", "0"]) == 0
        out = capsys.readouterr().out
        acme = [l for l in out.splitlines() if l.startswith("acme")][0]
        assert acme.split()[1:5] == ["1", "1", "0", "0"]  # one job, not two

    def test_no_serving_artifacts_is_silent(self, tmp_path):
        trep = self._trep()
        d = str(tmp_path)
        with open(os.path.join(d, "rank0.jsonl"), "w") as fh:
            fh.write(json.dumps({"type": "span", "rank": 0,
                                 "name": "dispatch.local", "ts": 1.0,
                                 "dur_s": 0.1, "self_s": 0.1,
                                 "depth": 0}) + "\n")
        assert trep.slo_section([d]) == ""

    def test_corrupt_journal_degrades_to_note(self, tmp_path):
        trep = self._trep()
        d = str(tmp_path)
        with open(os.path.join(d, "sched_journal.jsonl"), "w") as fh:
            fh.write(json.dumps({"type": "meta", "schema": 99}) + "\n")
        section = trep.slo_section([d])
        assert "unreadable" in section  # named, not crashed


# ---------------------------------------------------------------------- #
# trace propagation (ISSUE 11 tentpole): contextvar-carried trace identity
# ---------------------------------------------------------------------- #
class TestTracing:
    def test_spans_carry_trace_and_parent_ids(self):
        telemetry.enable()
        with telemetry.tracing(name="t") as tid:
            with telemetry.span("outer"):
                with telemetry.span("inner"):
                    pass
        recs = {r[0]: r[5] for r in telemetry._ring}
        assert recs["outer"]["trace_id"] == tid
        assert recs["inner"]["trace_id"] == tid
        assert recs["inner"]["parent_id"] == recs["outer"]["span_id"]
        assert "parent_id" not in recs["outer"]  # the trace root

    def test_untraced_spans_carry_no_trace_keys(self):
        telemetry.enable()
        with telemetry.span("plain", kind="x"):
            pass
        (rec,) = list(telemetry._ring)
        assert rec[5] == {"kind": "x"}

    def test_tracing_scopes_and_restores(self):
        assert telemetry.current_trace_id() is None
        with telemetry.tracing(trace_id="aaaa000000000000"):
            assert telemetry.current_trace_id() == "aaaa000000000000"
            with telemetry.tracing(trace_id="bbbb000000000000"):
                assert telemetry.current_trace_id() == "bbbb000000000000"
            assert telemetry.current_trace_id() == "aaaa000000000000"
        assert telemetry.current_trace_id() is None

    def test_mint_is_deterministic_per_process_sequence(self):
        """SPMD contract: ranks executing the identical mint sequence
        derive identical ids — the id depends only on (name, counter,
        restart epoch), never on pid/time/entropy."""
        seq0 = telemetry._trace_seq
        a = telemetry.mint_trace_id("x")
        telemetry._trace_seq = seq0
        b = telemetry.mint_trace_id("x")
        assert a == b and len(a) == 16
        assert telemetry.mint_trace_id("x") != a  # counter advanced

    def test_dispatch_records_inherit_the_ambient_trace(self):
        x = ht.random.randn(16, 16, split=0)
        _ = x + x  # compile outside
        telemetry.enable()
        telemetry.reset()
        with telemetry.tracing(name="d") as tid:
            _ = x + x
        (rec,) = [r for r in telemetry._ring if r[0] == "dispatch.binary"]
        assert rec[5]["trace_id"] == tid
        assert rec[5]["op"] == "add"  # the op attrs still ride along

    def test_record_event_inherits_and_parents_on_open_span(self):
        telemetry.enable()
        with telemetry.tracing(name="e") as tid:
            with telemetry.span("outer"):
                telemetry.record_event("leaf", 0.001)
        recs = {r[0]: r[5] for r in telemetry._ring}
        assert recs["leaf"]["trace_id"] == tid
        assert recs["leaf"]["parent_id"] == recs["outer"]["span_id"]

    def test_tracing_works_with_telemetry_disabled(self):
        """The contextvar is independent of the span ring: the flight
        recorder reads it even when nothing exports spans."""
        with telemetry.tracing(trace_id="cccc000000000000"):
            assert telemetry.current_trace_id() == "cccc000000000000"
        assert len(telemetry._ring) == 0

    def test_flush_exports_trace_attrs(self, tmp_path):
        telemetry.enable()
        with telemetry.tracing(name="f") as tid:
            with telemetry.span("unit.traced"):
                pass
        path = telemetry.flush(str(tmp_path))
        spans = [json.loads(line) for line in open(path)
                 if json.loads(line).get("type") == "span"]
        (rec,) = [s for s in spans if s["name"] == "unit.traced"]
        assert rec["attrs"]["trace_id"] == tid
        assert "span_id" in rec["attrs"]

    def test_guarded_wait_leaf_event_lands_in_ring(self):
        """health.guard_blocking's observed wait is BOTH a histogram
        observation and a <what>.wait leaf record — the per-step position
        the stepprof breakdown attributes from."""
        from heat_tpu.utils import health

        telemetry.enable()
        telemetry.reset()
        with telemetry.span("unit.step"):
            health.guard_blocking(lambda: time.sleep(0.002), "unit.block")
        names = _ring_names()
        assert "unit.block.wait" in names
        rep = telemetry.report()
        assert rep["histograms"]["unit.block.wait"]["count"] == 1
        # the wait counted as the step's CHILD time (not self-time)
        (step,) = [r for r in telemetry._ring if r[0] == "unit.step"]
        (wait,) = [r for r in telemetry._ring if r[0] == "unit.block.wait"]
        assert step[3] <= step[2] - wait[2] + 1e-4


class TestRingDropped:
    def test_eviction_counted_and_surfaced(self):
        telemetry.enable()
        for _ in range(telemetry._ring.maxlen + 9):
            telemetry.record_event("e", 1e-6)
        assert telemetry.ring_dropped() == 9
        rep = telemetry.report()
        assert rep["counters"]["telemetry.ring.dropped"] == 9

    def test_no_eviction_no_counter(self):
        telemetry.enable()
        telemetry.record_event("e", 1e-6)
        assert telemetry.ring_dropped() == 0
        assert "telemetry.ring.dropped" not in telemetry.report()["counters"]

    def test_reset_zeroes_the_counter(self):
        telemetry.enable()
        for _ in range(telemetry._ring.maxlen + 1):
            telemetry.record_event("e", 1e-6)
        assert telemetry.ring_dropped() == 1
        telemetry.reset()
        assert telemetry.ring_dropped() == 0

    def test_flush_exports_the_counter_and_cli_surfaces_it(self, tmp_path):
        telemetry.enable()
        for _ in range(telemetry._ring.maxlen + 3):
            telemetry.record_event("e", 1e-6)
        path = telemetry.flush(str(tmp_path))
        counters = [json.loads(line) for line in open(path)
                    if json.loads(line).get("type") == "counters"]
        assert counters[-1]["values"]["telemetry.ring.dropped"] == 3

        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "telemetry_report_drop",
            os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                         "scripts", "telemetry_report.py"),
        )
        trep = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(trep)
        merged = trep.merge_files(trep.find_rank_files(str(tmp_path)))
        assert merged["counters"]["telemetry.ring.dropped"] == 3
        assert "telemetry.ring.dropped" in trep.render(merged)


class TestHistogramP999:
    def test_p999_present_and_monotone(self):
        h = telemetry.Histogram("t")
        for _ in range(2000):
            h.observe(1e-4)
        for _ in range(3):
            h.observe(0.5)  # the deep tail
        s = h.summary()
        assert s["p999_s"] >= s["p99_s"] >= s["p90_s"]
        # 3/2003 > 0.1% of mass: p99.9 must land in the tail bin
        assert s["p999_s"] > 0.1

    def test_empty_histogram_summary_unchanged(self):
        assert telemetry.Histogram("t").summary() == {"count": 0}
