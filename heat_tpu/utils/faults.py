"""Deterministic fault injection + bounded retry (the failure-hardening core).

A production run survives torn writes, flaky storage and slow coordinators
only if every recovery path is *testable on CPU*; this module provides the
two halves of that story:

- **fault sites**: named points threaded through the runtime where a test
  (or a chaos job) can deterministically inject a failure.  The catalog
  lives in ``doc/source/design.md`` ("Failure model & recovery"):

  ========================  ====================================================
  site                      fired from
  ========================  ====================================================
  ``io.write``              every durable checkpoint file write (chunk files,
                            ``meta.json``, ``LATEST`` tmp, pytree ``.npz``)
  ``io.read``               checkpoint verification/assembly reads
  ``io.fsync``              every fsync of a checkpoint file or directory
  ``comm.host_fetch``       ``Communication.host_fetch`` (device→host fetches)
  ``comm.collective``       every ``Communication`` collective staging point
                            (``_account``) and the blocking waits
                            (``Wait``/``Barrier``) — ``delay``/``hang`` here
                            model a slow or dead peer, the case the
                            ``comm.deadline`` watchdog exists for
  ``proc.exit``             once per training step (``DASO.step``) and per
                            dryrun-worker section — ``exit=N`` SIGKILLs the
                            process on the Nth firing, the deterministic
                            "rank dies mid-training" the supervisor lane
                            recovers from
  ``dist.init``             each ``jax.distributed.initialize`` attempt in
                            ``bootstrap.init_distributed``
  ``sched.dispatch``        every scheduler dispatch attempt
                            (``parallel.scheduler.Scheduler``), fired inside
                            the armed per-job deadline — ``fail``/``delay``
                            exercise the retry path, ``hang`` proves a wedged
                            dispatch trips as THAT job's failure (not a
                            wedged queue), ``exit`` SIGKILLs a serving rank
                            mid-queue (the chaos lane's journal-replay
                            scenario)
  ``sched.journal.write``   every append to the scheduler's crash-durable
                            job journal — makes torn-record and
                            journal-loss recovery deterministically testable
  ========================  ====================================================

- **retry with backoff**: :func:`call_with_retries` — capped, jittered
  exponential backoff around transient faults, with attempt counters pushed
  into ``utils.profiler`` (``retry.<site>``) so recoveries are observable.

Faults are armed either in-process::

    with faults.inject("io.write", fail=2):
        ht.save_array_checkpoint(x, d)   # first two chunk writes fail, then heal

or across a process boundary via the environment (the chaos lane's SIGKILL
tests configure the victim subprocess this way)::

    HEAT_TPU_FAULTS="io.write:delay=0.3;io.fsync:fail=1"

Modes per site (combinable):

- ``fail=N``     raise :class:`TransientFault` on the first N firings
  (``N=-1``: every firing); ``exc=`` overrides the exception type.
- ``delay=S``    sleep S seconds on every firing — widens crash windows so a
  SIGKILL deterministically lands inside a write loop.
- ``corrupt=N``  flip one byte of the file passed as ``fire(..., path=)`` on
  the first N firings — models bit rot / torn sectors *after* the writer
  computed its checksum.
- ``hang=N``     block forever on the first N firings (``-1``: every) —
  models a dead peer's collective; only a deadline watchdog or a kill
  reclaims the caller.
- ``exit=N``     SIGKILL the *own* process on the Nth firing — models rank
  death at a deterministic point (the supervisor chaos lane arms this on
  one rank's ``proc.exit``).

Everything here is stdlib-only on purpose: the registry is imported from the
innermost I/O and bootstrap paths, where a heavy import would be a cycle.
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import os
import time
from typing import Callable, Dict, Iterator, Optional, Tuple

__all__ = [
    "InjectedFault",
    "TransientFault",
    "FaultSpec",
    "inject",
    "fire",
    "trip_count",
    "reset_trips",
    "trips",
    "parse_spec",
    "render_spec",
    "catalog",
    "catalog_sites",
    "MODES",
    "jitter_unit",
    "backoff_schedule",
    "call_with_retries",
]

# every mode ``parse_spec`` accepts, in the order ``fire`` applies them
MODES = ("delay", "hang", "corrupt", "exit", "fail")

# ---------------------------------------------------------------------- #
# the machine-readable fault catalog
# ---------------------------------------------------------------------- #
# One entry per registered site: where it fires from (the layer owning the
# ``fire(...)`` call) and which modes are *meaningful* there — every mode
# mechanically works at every site, but e.g. ``corrupt`` needs a firing
# that passes ``path=`` and ``hang`` is only survivable under a watchdog.
# This tuple is the single source of truth the chaos schedule generator
# enumerates, the coverage test greps the repo against, and heatlint HT113
# checks fire/inject literals against — a typo'd site can no longer
# silently never fire.
_CATALOG = (
    {
        "site": "io.write",
        "modes": ("fail", "delay", "corrupt", "exit"),
        "layer": "core/io.py",
        "fires": "every durable checkpoint file write (chunk files, "
                 "meta.json, LATEST tmp, pytree .npz); fired with path=",
    },
    {
        "site": "io.read",
        "modes": ("fail", "delay", "corrupt"),
        "layer": "core/io.py",
        "fires": "checkpoint verification/assembly reads; fired with path=",
    },
    {
        "site": "io.fsync",
        "modes": ("fail", "delay", "corrupt"),
        "layer": "core/io.py",
        "fires": "every fsync of a checkpoint file or directory; "
                 "fired with path=",
    },
    {
        "site": "comm.host_fetch",
        "modes": ("fail", "delay"),
        "layer": "core/communication.py",
        "fires": "Communication.host_fetch (device→host fetches)",
    },
    {
        "site": "comm.collective",
        "modes": ("fail", "delay", "hang", "exit"),
        "layer": "core/communication.py",
        "fires": "every collective staging point (_account) and the "
                 "blocking waits (Wait/Barrier) — hang models a dead peer, "
                 "the case the comm.deadline watchdog exists for",
    },
    {
        "site": "proc.exit",
        "modes": ("exit", "delay"),
        "layer": "optim/dp_optimizer.py",
        "fires": "once per training step (DASO.step) and per dryrun-worker "
                 "section — exit=N is the deterministic rank death the "
                 "supervisor lane recovers from",
    },
    {
        "site": "dist.init",
        "modes": ("fail", "delay"),
        "layer": "core/bootstrap.py",
        "fires": "each jax.distributed.initialize attempt in "
                 "bootstrap.init_distributed",
    },
    {
        "site": "sched.dispatch",
        "modes": ("fail", "delay", "hang", "exit"),
        "layer": "parallel/scheduler.py",
        "fires": "every scheduler dispatch attempt, inside the armed "
                 "per-job deadline — fail/delay exercise retries, hang "
                 "proves a wedged dispatch fails the job not the queue, "
                 "exit SIGKILLs a serving rank mid-queue",
    },
    {
        "site": "sched.journal.write",
        "modes": ("fail", "delay"),
        "layer": "parallel/scheduler.py",
        "fires": "every append to a crash-durable job journal (scheduler "
                 "and federation share the format); fired with path=",
    },
    {
        "site": "mem.alloc",
        "modes": ("fail", "delay"),
        "layer": "utils/memledger.py",
        "fires": "every ledger-registered device allocation — fail models "
                 "a deterministic OOM at the registration choke point",
    },
)


def catalog() -> Tuple[Dict[str, object], ...]:
    """The machine-readable fault-site registry: one dict per site with
    ``site`` (the string ``fire`` is called with), ``modes`` (the modes
    that are meaningful there), ``layer`` (the module owning the firing)
    and ``fires`` (prose: which operations trip it).  Returns fresh copies
    — mutating the result never poisons the registry."""
    return tuple(dict(e) for e in _CATALOG)


def catalog_sites() -> frozenset:
    """Just the registered site names (membership checks: HT113, the
    schedule generator's validation, the coverage test)."""
    return frozenset(e["site"] for e in _CATALOG)


class InjectedFault(Exception):
    """Base class of every injected failure."""


class TransientFault(InjectedFault, OSError):
    """An injected failure that models a *transient* condition (flaky disk,
    slow coordinator) — the retry layer treats it as retryable.  Subclasses
    ``OSError`` so code with real-world ``except OSError`` handling exercises
    the same path the genuine failure would take."""


class FaultSpec:
    """Armed behavior of one site.  ``fail``/``corrupt``/``hang`` are
    countdowns (mutated as the site fires; ``-1`` = unlimited); ``delay``
    applies to every firing; ``exit`` counts DOWN to the fatal firing."""

    __slots__ = ("site", "fail", "delay", "corrupt", "hang", "exit", "exc")

    def __init__(
        self,
        site: str,
        fail: int = 0,
        delay: float = 0.0,
        corrupt: int = 0,
        hang: int = 0,
        exit: int = 0,
        exc: type = TransientFault,
    ):
        self.site = site
        self.fail = int(fail)
        self.delay = float(delay)
        self.corrupt = int(corrupt)
        self.hang = int(hang)
        self.exit = int(exit)
        self.exc = exc

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"FaultSpec({self.site!r}, fail={self.fail}, delay={self.delay}, "
            f"corrupt={self.corrupt}, hang={self.hang}, exit={self.exit})"
        )


def parse_spec(text: str) -> Dict[str, FaultSpec]:
    """Parse the ``HEAT_TPU_FAULTS`` grammar:
    ``site:key=val,key=val;site2:key=val`` with keys
    fail/delay/corrupt/hang/exit."""
    specs: Dict[str, FaultSpec] = {}
    for entry in filter(None, (e.strip() for e in text.split(";"))):
        site, _, kvs = entry.partition(":")
        site = site.strip()
        if not site:
            raise ValueError(f"empty fault site in {text!r}")
        kw: Dict[str, float] = {}
        for kv in filter(None, (p.strip() for p in kvs.split(","))):
            k, _, v = kv.partition("=")
            k = k.strip()
            if k not in ("fail", "delay", "corrupt", "hang", "exit"):
                raise ValueError(f"unknown fault mode {k!r} for site {site!r}")
            kw[k] = float(v) if k == "delay" else int(v)
        specs[site] = FaultSpec(site, **kw)
    return specs


def render_spec(specs: Dict[str, FaultSpec]) -> str:
    """Inverse of :func:`parse_spec`: render armed specs back into the
    ``HEAT_TPU_FAULTS`` grammar (sorted by site for a stable string — the
    chaos engine puts the result in reproducer lines, which must compare
    equal across runs).  Round-trips: ``parse_spec(render_spec(s))``
    arms identically."""
    parts = []
    for site in sorted(specs):
        s = specs[site]
        kvs = []
        for mode in MODES:
            v = getattr(s, mode)
            if v:
                kvs.append(f"{mode}={v:g}" if mode == "delay" else f"{mode}={v}")
        parts.append(f"{site}:{','.join(kvs)}" if kvs else site)
    return ";".join(parts)


# env-armed specs (subprocess chaos tests) parsed once at import; in-process
# tests use the contextvar so parallel/nested scopes stay isolated
_ENV: Dict[str, FaultSpec] = parse_spec(os.environ.get("HEAT_TPU_FAULTS", ""))
_ctx: contextvars.ContextVar[Optional[Dict[str, FaultSpec]]] = contextvars.ContextVar(
    "heat_tpu_faults", default=None
)
_trips: Dict[str, int] = {}


@contextlib.contextmanager
def inject(
    site: str,
    *,
    fail: int = 0,
    delay: float = 0.0,
    corrupt: int = 0,
    hang: int = 0,
    exit: int = 0,
    exc: type = TransientFault,
) -> Iterator[FaultSpec]:
    """Arm ``site`` for the duration of the block (nests; yields the live
    spec so tests can inspect the remaining countdown)."""
    spec = FaultSpec(
        site, fail=fail, delay=delay, corrupt=corrupt, hang=hang, exit=exit, exc=exc
    )
    current = dict(_ctx.get() or {})
    current[site] = spec
    token = _ctx.set(current)
    try:
        yield spec
    finally:
        _ctx.reset(token)


def _flip_byte(path: str) -> None:
    size = os.path.getsize(path)
    if size == 0:
        return
    offset = size // 2
    with open(path, "r+b") as fh:
        fh.seek(offset)
        b = fh.read(1)
        fh.seek(offset)
        fh.write(bytes([b[0] ^ 0xFF]))


def fire(site: str, path: Optional[str] = None) -> None:
    """Trip ``site`` if armed: delay, then hang, then corrupt ``path``,
    then exit, then fail.  A disarmed site is a dict miss — cheap enough
    for hot paths."""
    ctx = _ctx.get()
    if ctx is None and not _ENV:
        return
    spec = (ctx or {}).get(site) or _ENV.get(site)
    if spec is None:
        return
    _trips[site] = _trips.get(site, 0) + 1
    if spec.delay:
        time.sleep(spec.delay)
    if spec.hang != 0:
        if spec.hang > 0:
            spec.hang -= 1
        while True:  # a dead peer never returns; only a watchdog/kill ends this
            time.sleep(3600.0)
    if spec.corrupt != 0 and path is not None:
        if spec.corrupt > 0:
            spec.corrupt -= 1
        _flip_byte(path)
    if spec.exit > 0:
        spec.exit -= 1
        if spec.exit == 0:
            import signal

            os.kill(os.getpid(), signal.SIGKILL)  # rank death, not an exception
    if spec.fail != 0:
        if spec.fail > 0:
            spec.fail -= 1
        raise spec.exc(f"injected fault at site {site!r}")


def trip_count(site: str) -> int:
    """How many times ``site`` fired while armed (since :func:`reset_trips`)."""
    return _trips.get(site, 0)


def reset_trips() -> None:
    _trips.clear()


def trips() -> Dict[str, int]:
    """Every site's firing count since :func:`reset_trips` — the chaos
    engine's *injection evidence*: an armed site whose count stays zero
    means the schedule never actually tested what it claims (the runtime
    twin of the HT113 static check)."""
    return dict(_trips)


# ---------------------------------------------------------------------- #
# bounded retry with jittered exponential backoff
# ---------------------------------------------------------------------- #
def jitter_unit(site: str, attempt: int) -> float:
    """A uniform draw in [0, 1) derived *deterministically* from
    ``(site, attempt)`` — the backoff jitter source.  Process entropy here
    would make lockstep SPMD ranks sleep differently after the same
    transient fault (the HT105 rationale: divergent sleeps skew the
    collective timing the flight recorder fingerprints), and would make a
    replayed chaos schedule time differently than the run it reproduces.
    sha256 is stable across processes, platforms and PYTHONHASHSEED;
    distinct sites and attempts still decorrelate (the reason jitter
    exists) because they hash apart."""
    digest = hashlib.sha256(f"backoff|{site}|{int(attempt)}".encode()).digest()
    return int.from_bytes(digest[:8], "big") / 2.0**64


def backoff_schedule(
    retries: int,
    base_delay: float = 0.05,
    factor: float = 2.0,
    max_delay: float = 2.0,
    jitter: float = 0.5,
    rand: Optional[Callable[[], float]] = None,
    site: str = "",
) -> Iterator[float]:
    """The delays slept between attempts: ``min(max_delay, base*factor**i)``
    stretched by up to ``jitter``× a uniform draw (decorrelates the retry
    storms of many writers hitting one flaky store).  The draw is seeded
    per ``(site, attempt)`` (:func:`jitter_unit`) — deterministic, so two
    replayed ranks derive identical sleep sequences; distinct *sites*
    retrying concurrently still spread out.  ``rand`` remains injectable
    so tests pin the schedule without sleeping."""
    for i in range(retries):
        u = rand() if rand is not None else jitter_unit(site, i)
        yield min(max_delay, base_delay * factor**i) * (1.0 + jitter * u)


def call_with_retries(
    fn: Callable,
    site: str,
    retries: int = 4,
    base_delay: float = 0.05,
    factor: float = 2.0,
    max_delay: float = 2.0,
    jitter: float = 0.5,
    retry_on: Tuple[type, ...] = (TransientFault, OSError),
    retry_if: Optional[Callable[[BaseException], bool]] = None,
    sleep: Callable[[float], None] = time.sleep,
    rand: Optional[Callable[[], float]] = None,
    deadline: Optional[float] = None,
    clock: Callable[[], float] = time.monotonic,
):
    """Run ``fn()`` with up to ``retries`` backoff retries on transient
    failures.  Each retry increments the ``retry.<site>`` counter in
    ``utils.profiler`` so recovered faults stay visible.  ``retry_if``
    narrows ``retry_on`` (e.g. only coordinator-unreachable RuntimeErrors);
    ``sleep``/``rand``/``clock`` are injectable for fake-clock tests.

    ``deadline`` is a TOTAL-time budget in seconds: cumulative time spent
    (attempts + backoff sleeps, measured on ``clock``) never exceeds it —
    a backoff sleep that would overrun the budget is not taken and the
    last failure re-raises instead.  This caps tail latency where the
    attempt count alone cannot (attempt durations vary; a slow NFS mount
    can eat the whole budget in one try).

    Every give-up — attempts exhausted OR deadline overrun — increments
    ``retry.<site>.exhausted`` before re-raising, so abandoned recoveries
    are visible post-hoc, not just the successful ones."""
    delays = None
    attempt = 0
    t0 = clock()
    while True:
        try:
            return fn()
        except retry_on as e:
            if retry_if is not None and not retry_if(e):
                raise
            # profiler pulls in jax; a standalone-loaded consumer (the
            # supervisor's tools, the chaos harness worker) keeps the
            # bounded retry and merely loses the retry.<site> counters
            try:
                from . import profiler
            except ImportError:
                profiler = None

            def _count(name: str) -> None:
                if profiler is not None:
                    profiler.counter_inc(name)

            if attempt >= retries:
                _count(f"retry.{site}.exhausted")
                raise
            if delays is None:
                delays = list(
                    backoff_schedule(
                        retries, base_delay, factor, max_delay, jitter, rand,
                        site=site,
                    )
                )
            if deadline is not None:
                elapsed = clock() - t0
                if elapsed + delays[attempt] >= deadline:
                    _count(f"retry.{site}.exhausted")
                    raise
            _count(f"retry.{site}")
            sleep(delays[attempt])
            attempt += 1
