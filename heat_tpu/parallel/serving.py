"""Jax-side job executors for the elastic scheduler (the serving tier).

``scheduler.py`` is deliberately stdlib-only — it knows nothing about jax,
arrays or collectives.  This module is its runtime half: executors for the
heterogeneous job kinds the ROADMAP's serving scenario names — **KMeans
fits**, **matmul / triangular-solve requests** and **NN forward batches**
— each built deterministically from the job's JSON payload, so every rank
of an SPMD world reconstructs the identical computation and stages the
identical collectives (scheduling divergence would be a desync; see
design.md "Serving & scheduling").

Micro-batching contract: :func:`batch_key` groups jobs by *program
signature* (kind + structural payload fields, data/seed fields excluded),
so same-shape requests from different tenants share one dispatch —
``nn_forward`` batches genuinely stack into a single forward pass, and the
per-job kinds reuse the PR 1 sharding-keyed program cache (the second
identical-shape matmul request compiles NOTHING).

Deadline contract: the scheduler arms ``health.deadline`` (the contextvar
``comm.deadline`` also arms) around every dispatch, so the collective
staging points and the guarded blocking waits inside these executors trip
``CollectiveTimeoutError`` at the offending job when the world wedges.

All jax/heat imports are lazy (inside :func:`make_executor`): importing
this module costs nothing, and ``heat_tpu.parallel`` stays importable in
processes that never execute a job.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List

from . import scheduler as _scheduler

__all__ = ["KINDS", "batch_key", "make_executor"]

# exception type names that mean the distributed MACHINERY failed (a dead
# peer's connection reset, a torn-down client) rather than the job itself —
# name-matched because the concrete classes live in jaxlib and vary by
# version
_WORLD_ERROR_TYPES = ("XlaRuntimeError", "JaxRuntimeError")


def _raise_world_broken(e: BaseException):
    """Convert an XLA/transport runtime error into
    :class:`scheduler.WorldBroken` so the scheduler requeues the batch
    instead of terminally failing jobs whose only crime was running while
    a peer died (the raise-fast vs hang race under the supervisor's
    teardown)."""
    for klass in type(e).__mro__:
        if klass.__name__ in _WORLD_ERROR_TYPES:
            raise _scheduler.WorldBroken(
                f"distributed runtime failed under dispatch: {e}"
            ) from e

KINDS = ("matmul", "solve", "kmeans", "nn_forward")

# payload fields that parameterize the DATA, not the compiled program —
# excluded from the batch signature so same-shape jobs share one dispatch
_DATA_FIELDS = ("seed",)


def batch_key(job) -> str:
    """Program-signature batch key: jobs whose payloads differ only in
    data fields (``seed``) are compatible — one shared SPMD dispatch."""
    sig = {k: v for k, v in job.payload.items() if k not in _DATA_FIELDS}
    return f"{job.kind}|{json.dumps(sig, sort_keys=True)}"


def make_executor(comm=None) -> Callable[[List[Any]], List[Any]]:
    """Build the ``executor(jobs) -> results`` callable for
    :class:`heat_tpu.parallel.scheduler.Scheduler`.

    Every result is ``{"digest": float, ...}`` — a host-materialized
    scalar summary, so a DONE job is attested by a value that actually
    crossed the device→host boundary (a wedged collective can therefore
    never produce a phantom DONE record)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import heat_tpu as ht

    if comm is None:
        comm = ht.communication.get_comm()

    # nn_forward model cache: one Linear stack per feature width, params
    # derived from a fixed key — identical on every rank by construction
    _models: Dict[int, tuple] = {}

    def _model(features: int):
        got = _models.get(features)
        if got is None:
            model = ht.nn.Sequential(ht.nn.Linear(features, 4), ht.nn.ReLU())
            params = model.init(jax.random.key(0))
            got = _models[features] = (model, params)
        return got

    def _fetch_sum(x) -> float:
        """Host digest of a DNDarray reduction (the one sanctioned
        device→host sync per job — collective, guarded, fault-retried)."""
        return float(np.asarray(comm.host_fetch(x.sum()._jarray)))

    # ------------------------------------------------------------------ #
    def _matmul(job) -> dict:
        n = int(job.payload.get("n", 16))
        scale = 1.0 + int(job.payload.get("seed", 0)) % 7
        a = ht.reshape(ht.arange(n * n, dtype=ht.float32, split=ht.axisspec.named(0)), (n, n))
        a = a * (scale / n)
        c = a @ ht.transpose(a)
        return {"digest": _fetch_sum(c), "n": n}

    def _solve(job) -> dict:
        n = int(job.payload.get("n", 8))
        # well-conditioned lower-triangular system, deterministic entries
        ln = ht.reshape(ht.arange(n * n, dtype=ht.float32, split=ht.axisspec.named(0)), (n, n))
        a = ht.tril(ln * (1.0 / (n * n))) + ht.eye(n, dtype=ht.float32, split=ht.axisspec.named(0)) * 2.0
        b = ht.reshape(ht.arange(n, dtype=ht.float32, split=ht.axisspec.named(0)), (n, 1))
        x = ht.linalg.solve_triangular(a, b, lower=True)
        return {"digest": _fetch_sum(x), "n": n}

    def _kmeans(job) -> dict:
        n = int(job.payload.get("n", 32))
        k = int(job.payload.get("k", 2))
        # payload-seeded, so every rank draws the IDENTICAL stream — the
        # per-rank-divergence class HT105 guards against cannot occur
        rng = np.random.default_rng(int(job.payload.get("seed", 0)))  # heatlint: disable=HT105 payload-seeded, rank-identical
        pts = rng.standard_normal((n, 2)).astype(np.float32)
        pts[: n // 2] += 8.0  # two separable blobs: the fit converges fast
        x = ht.array(pts, split=ht.axisspec.named(0))
        km = ht.cluster.KMeans(n_clusters=k, init="random", max_iter=5,
                               random_state=0)
        km.fit(x)
        return {"digest": _fetch_sum(km.cluster_centers_), "k": k}

    def _nn_forward_batch(jobs) -> List[dict]:
        """The genuinely stacked kind: all jobs' inputs concatenate into
        ONE forward pass (the shared SPMD dispatch), results split back
        per job."""
        features = int(jobs[0].payload.get("features", 8))
        model, params = _model(features)
        xs, sizes = [], []
        for job in jobs:
            b = int(job.payload.get("batch", 4))
            rng = np.random.default_rng(int(job.payload.get("seed", 0)))  # heatlint: disable=HT105 payload-seeded, rank-identical
            xs.append(rng.standard_normal((b, features)).astype(np.float32))
            sizes.append(b)
        out = model.apply(params, jnp.asarray(np.concatenate(xs, axis=0)))
        host = np.asarray(comm.host_fetch(out))
        results, off = [], 0
        for b in sizes:
            results.append({"digest": float(host[off: off + b].sum()), "batch": b})
            off += b
        return results

    _single = {"matmul": _matmul, "solve": _solve, "kmeans": _kmeans}

    # federation admission feedback (ISSUE 17): with HEAT_TPU_FED_PEAKS
    # set to a history path and the memledger armed, every executed batch
    # is bracketed in a memledger.peak_window and its incremental peak is
    # recorded per kind — the persisted history federation.
    # AdmissionPredictor sheds mem_infeasible jobs against at the edge.
    _predictor = None
    _peaks_path = os.environ.get("HEAT_TPU_FED_PEAKS")
    if _peaks_path:
        from ..utils import memledger as _memledger

        if _memledger.enabled():
            from . import federation as _federation

            _predictor = _federation.AdmissionPredictor(_peaks_path)

    def _run(jobs: List[Any]) -> List[Any]:
        kind = jobs[0].kind
        if kind == "nn_forward":
            return _nn_forward_batch(jobs)
        fn = _single.get(kind)
        if fn is None:
            raise ValueError(f"unknown job kind {kind!r} (serve {KINDS})")
        # same-signature jobs re-enter the SAME cached programs (PR 1
        # sharding-keyed cache): the batch shares compiled dispatches
        # even though each job's data digest is computed separately
        return [fn(job) for job in jobs]

    def execute(jobs: List[Any]) -> List[Any]:
        try:
            if _predictor is not None:
                from ..utils import memledger as _memledger

                with _memledger.peak_window() as w:
                    results = _run(jobs)
                # per-JOB footprint: the batch's incremental peak split
                # evenly — conservative enough for admission (the window
                # maximum already over-counts concurrent neighbors)
                delta = max(0, int(w["peak"]) - int(w["base"]))
                if delta > 0:
                    _predictor.observe(jobs[0].kind,
                                       (delta + len(jobs) - 1) // len(jobs))
                return results
            return _run(jobs)
        except Exception as e:
            _raise_world_broken(e)  # transport death -> WorldBroken
            raise

    return execute
