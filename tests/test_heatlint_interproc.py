"""Interprocedural heatlint tests (ISSUE 8 tentpole).

Covers the call-graph + effect-summary engine (analysis/callgraph.py,
analysis/summaries.py), the HT2xx rule family, the unresolved-call honesty
policy (downgrade-to-info, never a false positive), the summary cache, the
SARIF renderer, the per-directory rule config, and the single-parse
performance contract.
"""

import importlib.util
import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from heat_tpu.analysis import (
    LintContext,
    lint_paths,
    load_baseline,
    render_sarif,
)
from heat_tpu.analysis import summaries as summaries_mod
from heat_tpu.analysis.summaries import build_program

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "heatlint_cli_ip", os.path.join(REPO, "scripts", "heatlint.py")
)
heatlint_cli = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(heatlint_cli)


def write_pkg(tmp_path, files: dict) -> str:
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    init = pkg / "__init__.py"
    if not init.exists():
        init.write_text("")
    for name, src in files.items():
        p = pkg / name
        p.parent.mkdir(parents=True, exist_ok=True)
        if name.endswith("__init__.py") or "/" in name:
            parent_init = p.parent / "__init__.py"
            if not parent_init.exists():
                parent_init.write_text("")
        p.write_text(textwrap.dedent(src))
    return str(pkg)


def run_rules(tmp_path, files, select):
    return lint_paths([write_pkg(tmp_path, files)], select=list(select))


def make_program(tmp_path, files):
    pkg = write_pkg(tmp_path, files)
    contexts = {}
    for dirpath, _dirs, fns in os.walk(pkg):
        for fn in sorted(fns):
            if fn.endswith(".py"):
                p = os.path.join(dirpath, fn)
                with open(p) as fh:
                    ctx = LintContext(p, fh.read())
                contexts[ctx.path] = ctx
    return build_program(contexts)


# ---------------------------------------------------------------------- #
# HT201 — static desync
# ---------------------------------------------------------------------- #
class TestHT201:
    def test_cross_function_desync_flagged_where_ht102_is_silent(self, tmp_path):
        """THE acceptance fixture: a rank-conditional collective hidden two
        calls deep.  Lexical HT102 provably misses it (asserted silent);
        HT201 fires with a >=2-hop call-chain trace."""
        files = {
            "lib.py": """
                def _stage(comm, x):
                    return _inner(comm, x)

                def _inner(comm, x):
                    return comm.Bcast(x)

                def run(comm, x):
                    if comm.rank == 0:
                        _stage(comm, x)
                    return x
            """
        }
        silent = run_rules(tmp_path, files, ["HT102"])
        assert silent == []
        fs = run_rules(tmp_path, files, ["HT201"])
        assert len(fs) == 1
        f = fs[0]
        assert f.rule == "HT201" and f.severity == "error"
        assert f.qualname == "run" and f.detail == "Bcast@comm.rank"
        # entry -> _stage -> _inner (the Bcast site): >= 2 hops past entry
        assert len(f.trace) >= 3
        assert [h["qualname"] for h in f.trace] == ["run", "_stage", "_inner"]

    def test_cross_file_desync_flagged(self, tmp_path):
        files = {
            "helpers.py": """
                def stage_extra(comm):
                    return comm.Allreduce(1)
            """,
            "lib.py": """
                from .helpers import stage_extra

                def run(comm, x):
                    if comm.rank == 0:
                        stage_extra(comm)
                    return x
            """,
        }
        fs = run_rules(tmp_path, files, ["HT201"])
        assert [f.detail for f in fs] == ["Allreduce@comm.rank"]
        assert fs[0].severity == "error"
        assert fs[0].trace[-1]["qualname"] == "stage_extra"

    def test_mpdryrun_desync_worker_pattern_flaggable(self, tmp_path):
        """The chaos-CI MPDRYRUN_DESYNC_RANK shape: a rank-conditional EXTRA
        collective staged through a helper (scripts/multiprocess_dryrun.py
        stages it lexically, where HT102 already fires; one helper deep it
        is exactly HT201's territory)."""
        files = {
            "worker.py": """
                def _stage_extra(ht, comm):
                    return ht.arange(comm.size).resplit(None)

                def loop(ht, comm, pid, desync_rank, m):
                    if pid == desync_rank:
                        _stage_extra(ht, comm)
                    return m.resplit(1)
            """
        }
        assert run_rules(tmp_path, files, ["HT102"]) == []
        fs = run_rules(tmp_path, files, ["HT201"])
        assert [f.detail for f in fs] == ["resplit@pid"]
        assert fs[0].severity == "error"

    def test_same_footprint_via_different_helpers_clean(self, tmp_path):
        files = {
            "lib.py": """
                def _a(comm, x):
                    return comm.Bcast(x)

                def _b(comm, x):
                    y = comm.Bcast(x)
                    return y

                def run(comm, x):
                    if comm.rank == 0:
                        return _a(comm, x)
                    else:
                        return _b(comm, x)
            """
        }
        assert run_rules(tmp_path, files, ["HT201"]) == []

    def test_lexical_vs_helper_same_collective_clean(self, tmp_path):
        # one arm stages Bcast lexically, the other through a helper — the
        # expanded footprints agree, so no desync either way
        files = {
            "lib.py": """
                def _via(comm, x):
                    return comm.Bcast(x)

                def run(comm, x):
                    if comm.rank == 0:
                        comm.Bcast(x)
                    else:
                        _via(comm, x)
            """
        }
        assert run_rules(tmp_path, files, ["HT201"]) == []

    def test_lexical_only_difference_left_to_ht102(self, tmp_path):
        # depth-0 divergence is HT102's finding; HT201 must not double-report
        files = {
            "lib.py": """
                def run(comm, x):
                    if comm.rank == 0:
                        comm.Bcast(x)
            """
        }
        assert run_rules(tmp_path, files, ["HT201"]) == []
        assert len(run_rules(tmp_path, files, ["HT102"])) == 1

    def test_rank_while_with_helper_collective_flagged(self, tmp_path):
        files = {
            "lib.py": """
                def _sync(comm, x):
                    return comm.Allgather(x)

                def drain(comm, x, n):
                    while comm.rank < n:
                        x = _sync(comm, x)
                    return x
            """
        }
        fs = run_rules(tmp_path, files, ["HT201"])
        assert [f.detail for f in fs] == ["Allgather@comm.rank"]

    def test_param_callable_downgrades_to_info(self, tmp_path):
        # the honesty policy: a callable passed as a value could stage
        # anything — report info ("cannot prove"), never a gating error
        files = {
            "lib.py": """
                def run(comm, fn, x):
                    if comm.rank == 0:
                        fn(x)
                    return x
            """
        }
        fs = run_rules(tmp_path, files, ["HT201"])
        assert len(fs) == 1
        assert fs[0].severity == "info"
        assert fs[0].detail == "unproven@comm.rank"

    def test_getattr_dispatch_downgrades_to_info(self, tmp_path):
        files = {
            "lib.py": """
                def run(comm, obj, x):
                    if comm.rank == 0:
                        getattr(obj, "save")(x)
                    return x
            """
        }
        fs = run_rules(tmp_path, files, ["HT201"])
        assert [f.severity for f in fs] == ["info"]

    def test_unknown_method_receiver_is_benign_no_finding(self, tmp_path):
        # x.method() on an unknown receiver is assumed collective-free
        # (collectives are matched by NAME lexically) — no finding at all,
        # not even info: "never a false positive"
        files = {
            "lib.py": """
                import os

                def run(comm, log, path):
                    if comm.rank == 0:
                        log.write(path)
                        os.makedirs(path, exist_ok=True)
                    return path
            """
        }
        assert run_rules(tmp_path, files, ["HT201"]) == []

    def test_suppression_works(self, tmp_path):
        files = {
            "lib.py": """
                def _stage(comm, x):
                    return comm.Bcast(x)

                def run(comm, x):
                    if comm.rank == 0:  # heatlint: disable=HT201 rank-0 ingest, peers attend via load()
                        _stage(comm, x)
                    return x
            """
        }
        assert run_rules(tmp_path, files, ["HT201"]) == []

    def test_depth0_order_mismatch_flagged_ht102_blind(self, tmp_path):
        """Both arms stage the same collective SET in a different ORDER:
        set-based HT102 is blind (asserted), and the ordered-footprint
        comparison must not hand off to it — a sequence divergence
        desynchronizes ranks exactly like a missing collective."""
        files = {
            "lib.py": """
                def run(comm, x):
                    if comm.rank == 0:
                        comm.Allreduce(x)
                        comm.Bcast(x)
                    else:
                        comm.Bcast(x)
                        comm.Allreduce(x)
            """
        }
        assert run_rules(tmp_path, files, ["HT102"]) == []
        fs = run_rules(tmp_path, files, ["HT201"])
        assert len(fs) == 1
        assert fs[0].severity == "error"
        assert "ORDER" in fs[0].message

    def test_order_mismatch_through_helpers_flagged(self, tmp_path):
        files = {
            "lib.py": """
                def _ab(comm, x):
                    comm.Allreduce(x)
                    comm.Bcast(x)

                def _ba(comm, x):
                    comm.Bcast(x)
                    comm.Allreduce(x)

                def run(comm, x):
                    if comm.rank == 0:
                        _ab(comm, x)
                    else:
                        _ba(comm, x)
            """
        }
        fs = run_rules(tmp_path, files, ["HT201"])
        assert len(fs) == 1 and fs[0].severity == "error"

    def test_chained_receiver_collective_seen(self, tmp_path):
        # m.resplit(None).numpy(): the receiver call stages FIRST and must
        # not be lost inside the outer call's footprint extraction
        files = {
            "lib.py": """
                def _fetch(m):
                    return m.resplit(None).numpy()

                def run(pid, m):
                    if pid == 0:
                        _fetch(m)
                    return m
            """
        }
        fs = run_rules(tmp_path, files, ["HT201"])
        assert [f.detail for f in fs] == ["resplit@pid"]


# ---------------------------------------------------------------------- #
# HT202 — transitive host sync
# ---------------------------------------------------------------------- #
class TestHT202:
    def test_sink_in_private_helper_reported_at_public_entry(self, tmp_path):
        files = {
            "lib.py": """
                def _fetch_count(x):
                    return x.sum().item()

                def truncate(x):
                    k = _fetch_count(x)
                    return k
            """
        }
        fs = run_rules(tmp_path, files, ["HT202"])
        assert len(fs) == 1
        f = fs[0]
        assert f.qualname == "truncate" and f.severity == "error"
        assert f.detail == "item@_fetch_count"
        assert [h["qualname"] for h in f.trace] == ["truncate", "_fetch_count"]

    def test_cast_of_device_returning_helper_ht101_provably_misses(self, tmp_path):
        # float(_norm(x)): no lexical device marker in the argument, so
        # HT101's heuristic cannot see it (asserted silent); the summary
        # knows _norm returns a device value
        files = {
            "lib.py": """
                import jax.numpy as jnp

                def _norm(x):
                    return jnp.sqrt(jnp.sum(x._jarray * x._jarray))

                def scale(x):
                    s = float(_norm(x))
                    return s
            """
        }
        assert run_rules(tmp_path, files, ["HT101"]) == []
        fs = run_rules(tmp_path, files, ["HT202"])
        assert len(fs) == 1
        assert fs[0].detail == "float-cast@_norm"
        assert fs[0].severity == "error"

    def test_returns_device_propagates_through_wrappers(self, tmp_path):
        files = {
            "lib.py": """
                import jax.numpy as jnp

                def _norm(x):
                    return jnp.sum(x._jarray)

                def _wrapped(x):
                    return _norm(x)

                def scale(x):
                    return float(_wrapped(x))
            """
        }
        fs = run_rules(tmp_path, files, ["HT202"])
        assert [f.detail for f in fs] == ["float-cast@_wrapped"]

    def test_suppressed_sink_propagates_as_info(self, tmp_path):
        files = {
            "lib.py": """
                def _read(x):
                    return x.sum().item()  # heatlint: disable=HT101 debug-only path

                def api(x):
                    return _read(x)
            """
        }
        fs = run_rules(tmp_path, files, ["HT202"])
        assert [f.severity for f in fs] == ["info"]

    def test_materializer_def_is_a_barrier(self, tmp_path):
        # host_fetch_all is the sanctioned materialization API: its syncs
        # are its job, never "hidden" — nothing propagates
        files = {
            "lib.py": """
                import jax

                def host_fetch_all(arrays):
                    return [jax.device_get(a) for a in arrays]

                def api(xs):
                    return host_fetch_all(xs)
            """
        }
        assert run_rules(tmp_path, files, ["HT202"]) == []

    def test_sanctioned_module_is_a_barrier(self, tmp_path):
        files = {
            "core/io.py": """
                def save(x, path):
                    data = x.sum().item()
                    return data
            """,
            "lib.py": """
                from .core import io

                def checkpoint(x, path):
                    return io.save(x, path)
            """,
        }
        assert run_rules(tmp_path, files, ["HT202"]) == []

    def test_sink_in_public_function_consumed_there_no_cascade(self, tmp_path):
        # a public g with its own sink is HT101's finding at g; public
        # callers of g are NOT cascaded (one report per root cause)
        files = {
            "lib.py": """
                def fetch(x):
                    return x.sum().item()

                def api(x):
                    return fetch(x)
            """
        }
        assert run_rules(tmp_path, files, ["HT202"]) == []
        assert len(run_rules(tmp_path, files, ["HT101"])) == 1

    def test_nested_def_sink_propagates_to_enclosing_public(self, tmp_path):
        files = {
            "lib.py": """
                def api(x):
                    def inner():
                        return x.sum().item()
                    return inner()
            """
        }
        fs = run_rules(tmp_path, files, ["HT202"])
        assert len(fs) == 1
        assert fs[0].qualname == "api"
        assert fs[0].trace[-1]["qualname"] == "api.inner"


# ---------------------------------------------------------------------- #
# HT203 — interprocedural use-after-donate
# ---------------------------------------------------------------------- #
class TestHT203:
    def test_callee_donation_then_use_flagged_ht103_silent(self, tmp_path):
        files = {
            "lib.py": """
                import jax

                def _consume(a, sh):
                    return jax.device_put(a, sh, donate=True)

                def caller(x, sh):
                    y = _consume(x, sh)
                    return x + y
            """
        }
        assert run_rules(tmp_path, files, ["HT103"]) == []
        fs = run_rules(tmp_path, files, ["HT203"])
        assert len(fs) == 1
        f = fs[0]
        assert f.detail == "x" and f.qualname == "caller" and f.severity == "error"
        assert [h["qualname"] for h in f.trace] == ["caller", "_consume"]

    def test_transitive_donation_chain(self, tmp_path):
        files = {
            "lib.py": """
                import jax

                def _inner(a, sh):
                    return jax.device_put(a, sh, donate=True)

                def _outer(b, sh):
                    return _inner(b, sh)

                def api(x, sh):
                    r = _outer(x, sh)
                    return x
            """
        }
        fs = run_rules(tmp_path, files, ["HT203"])
        assert len(fs) == 1
        assert [h["qualname"] for h in fs[0].trace] == ["api", "_outer", "_inner"]

    def test_rebind_clears_taint(self, tmp_path):
        files = {
            "lib.py": """
                import jax

                def _consume(a, sh):
                    return jax.device_put(a, sh, donate=True)

                def caller(x, sh):
                    x = _consume(x, sh)
                    return x
            """
        }
        assert run_rules(tmp_path, files, ["HT203"]) == []

    def test_module_level_jit_alias_donation(self, tmp_path):
        # step = jax.jit(_step, donate_argnums=(0,)) at MODULE level is
        # invisible to HT103 (which only scans function-local jits)
        files = {
            "lib.py": """
                import jax

                def _step(state, batch):
                    return state

                step = jax.jit(_step, donate_argnums=(0,))

                def train(state, batch):
                    out = step(state, batch)
                    return state, out
            """
        }
        assert run_rules(tmp_path, files, ["HT103"]) == []
        fs = run_rules(tmp_path, files, ["HT203"])
        assert [f.detail for f in fs] == ["state"]

    def test_plain_rename_alias_of_donating_helper_flagged(self, tmp_path):
        """`h = _helper` carries no lexical donation, so HT103 is blind to
        the call through the rename (asserted) — HT203 must still see it
        (only jit aliases WITH donate_argnums are HT103's)."""
        files = {
            "lib.py": """
                import jax

                def _consume(a, sh):
                    return jax.device_put(a, sh, donate=True)

                def caller(x, sh):
                    h = _consume
                    y = h(x, sh)
                    return x + y
            """
        }
        assert run_rules(tmp_path, files, ["HT103"]) == []
        fs = run_rules(tmp_path, files, ["HT203"])
        assert [f.detail for f in fs] == ["x"]

    def test_local_jit_alias_with_donate_left_to_ht103(self, tmp_path):
        files = {
            "lib.py": """
                import jax

                def _step(s, b):
                    return s

                def train(state, batch):
                    prog = jax.jit(_step, donate_argnums=(0,))
                    out = prog(state, batch)
                    return state, out
            """
        }
        assert run_rules(tmp_path, files, ["HT203"]) == []
        assert len(run_rules(tmp_path, files, ["HT103"])) == 1

    def test_lexical_donate_kwarg_left_to_ht103(self, tmp_path):
        # the call site itself says donate=True: HT103's finding, not ours
        files = {
            "lib.py": """
                import jax

                def caller(x, sh):
                    y = jax.device_put(x, sh, donate=True)
                    return x + y
            """
        }
        assert run_rules(tmp_path, files, ["HT203"]) == []
        assert len(run_rules(tmp_path, files, ["HT103"])) == 1

    def test_exclusive_branch_use_not_flagged(self, tmp_path):
        files = {
            "lib.py": """
                import jax

                def _consume(a, sh):
                    return jax.device_put(a, sh, donate=True)

                def caller(x, sh, fast):
                    if fast:
                        y = _consume(x, sh)
                    else:
                        y = x + 1
                    return y
            """
        }
        assert run_rules(tmp_path, files, ["HT203"]) == []


# ---------------------------------------------------------------------- #
# HT204 — transitively undeadlined blocking
# ---------------------------------------------------------------------- #
class TestHT204:
    def test_naked_wait_in_helper_reported_at_public_entry(self, tmp_path):
        files = {
            "lib.py": """
                import jax

                def _fence(x):
                    jax.block_until_ready(x)

                def api(x):
                    _fence(x)
                    return x
            """
        }
        fs = run_rules(tmp_path, files, ["HT204"])
        assert len(fs) == 1
        f = fs[0]
        assert f.qualname == "api" and f.severity == "error"
        assert f.detail == "block_until_ready@_fence"
        assert [h["qualname"] for h in f.trace] == ["api", "_fence"]

    def test_barrier_through_helper_flagged(self, tmp_path):
        files = {
            "lib.py": """
                def _sync_world(comm):
                    comm.Barrier()

                def api(comm):
                    _sync_world(comm)
            """
        }
        fs = run_rules(tmp_path, files, ["HT204"])
        assert [f.detail for f in fs] == ["Barrier@_sync_world"]

    def test_deadline_at_call_site_satisfies(self, tmp_path):
        files = {
            "lib.py": """
                import jax

                def _fence(x):
                    jax.block_until_ready(x)

                def api(comm, x):
                    with comm.deadline(30.0):
                        _fence(x)
                    return x
            """
        }
        assert run_rules(tmp_path, files, ["HT204"]) == []

    def test_deadline_inside_callee_satisfies(self, tmp_path):
        files = {
            "lib.py": """
                def _fence(comm, x):
                    with comm.deadline(30.0):
                        comm.Wait(x)

                def api(comm, x):
                    _fence(comm, x)
            """
        }
        assert run_rules(tmp_path, files, ["HT204"]) == []

    def test_deadline_one_hop_up_covers_two_hop_chain(self, tmp_path):
        files = {
            "lib.py": """
                def _fence(comm, x):
                    comm.Wait(x)

                def _mid(comm, x):
                    with comm.deadline(10.0):
                        _fence(comm, x)

                def api(comm, x):
                    _mid(comm, x)
            """
        }
        assert run_rules(tmp_path, files, ["HT204"]) == []

    def test_wait_in_public_function_left_to_ht107(self, tmp_path):
        files = {
            "lib.py": """
                def sync(comm):
                    comm.Barrier()

                def api(comm):
                    sync(comm)
            """
        }
        assert run_rules(tmp_path, files, ["HT204"]) == []
        fs = run_rules(tmp_path, files, ["HT107"])
        assert [f.qualname for f in fs] == ["sync"]


# ---------------------------------------------------------------------- #
# the call graph: edge cases + the unresolved-bucket honesty policy
# ---------------------------------------------------------------------- #
class TestCallGraph:
    def test_functools_wraps_decorated_helper_resolves(self, tmp_path):
        files = {
            "lib.py": """
                import functools

                def _decorate(fn):
                    @functools.wraps(fn)
                    def wrapper(*a, **k):
                        return fn(*a, **k)
                    return wrapper

                @_decorate
                def _fetch(x):
                    return x.sum().item()

                def api(x):
                    return _fetch(x)
            """
        }
        fs = run_rules(tmp_path, files, ["HT202"])
        assert [f.detail for f in fs] == ["item@_fetch"]

    def test_jax_jit_decorated_helper_resolves(self, tmp_path):
        files = {
            "lib.py": """
                import jax

                def _stage(comm, x):
                    return comm.Bcast(x)

                @jax.jit
                def _jitted(comm, x):
                    return _stage(comm, x)

                def run(comm, x):
                    if comm.rank == 0:
                        _jitted(comm, x)
            """
        }
        fs = run_rules(tmp_path, files, ["HT201"])
        assert [f.detail for f in fs] == ["Bcast@comm.rank"]

    def test_lambda_lands_in_unresolved_bucket(self, tmp_path):
        program = make_program(
            tmp_path,
            {
                "lib.py": """
                    def run(comm, x):
                        f = lambda: comm.Bcast(x)
                        if comm.rank == 0:
                            f()
                        return x
                """
            },
        )
        reasons = {u["reason"] for u in program.graph.unresolved}
        assert "lambda" in reasons
        benign = {u["reason"]: u["benign"] for u in program.graph.unresolved}
        assert benign["lambda"] is False  # poisoning: downgrades, never drops

    def test_getattr_lands_in_unresolved_bucket(self, tmp_path):
        program = make_program(
            tmp_path,
            {
                "lib.py": """
                    def run(obj, x):
                        return getattr(obj, "go")(x)
                """
            },
        )
        assert any(u["reason"] == "getattr" for u in program.graph.unresolved)

    def test_receiver_unknown_is_benign_in_bucket(self, tmp_path):
        program = make_program(
            tmp_path,
            {
                "lib.py": """
                    def run(log, x):
                        return log.write(x)
                """
            },
        )
        recs = [u for u in program.graph.unresolved if u["reason"] == "receiver-unknown"]
        assert recs and all(u["benign"] for u in recs)

    def test_self_method_resolution_through_base_class(self, tmp_path):
        files = {
            "lib.py": """
                class Base:
                    def _fetch(self, x):
                        return x.sum().item()

                class Derived(Base):
                    def read(self, x):
                        return self._fetch(x)
            """
        }
        fs = run_rules(tmp_path, files, ["HT202"])
        assert [f.qualname for f in fs] == ["Derived.read"]
        assert fs[0].trace[-1]["qualname"] == "Base._fetch"

    def test_reexport_chase_through_init(self, tmp_path):
        files = {
            "impl.py": """
                def _stage(comm, x):
                    return comm.Allreduce(x)
            """,
            "__init__.py": """
                from .impl import _stage
            """,
            "lib.py": """
                from . import _stage

                def run(comm, x):
                    if comm.rank == 0:
                        _stage(comm, x)
            """,
        }
        fs = run_rules(tmp_path, files, ["HT201"])
        assert [f.detail for f in fs] == ["Allreduce@comm.rank"]


# ---------------------------------------------------------------------- #
# the summary cache
# ---------------------------------------------------------------------- #
class TestSummaryCache:
    SRC = """
        def _fetch(x):
            return x.sum().item()

        def api(x):
            return _fetch(x)
    """

    def _contexts(self, pkg):
        contexts = {}
        for fn in sorted(os.listdir(pkg)):
            if fn.endswith(".py"):
                p = os.path.join(pkg, fn)
                with open(p) as fh:
                    ctx = LintContext(p, fh.read())
                contexts[ctx.path] = ctx
        return contexts

    def test_cache_roundtrip_and_hit(self, tmp_path, monkeypatch):
        pkg = write_pkg(tmp_path, {"lib.py": self.SRC})
        cache = str(tmp_path / "summaries.json")
        prog1 = build_program(self._contexts(pkg), cache_path=cache)
        assert os.path.exists(cache)
        data = json.load(open(cache))
        assert data["version"] >= 1 and data["files"]
        assert prog1.sync_reports

        # a second build over IDENTICAL sources must come from the cache:
        # extraction would raise if it were (incorrectly) re-run
        def boom(ctx):
            raise AssertionError(f"cache miss: re-extracted {ctx.path}")

        monkeypatch.setattr(summaries_mod, "extract_effects", boom)
        monkeypatch.setattr(summaries_mod, "extract_structure", boom)
        prog2 = build_program(self._contexts(pkg), cache_path=cache)
        r1 = [(r.entry, r.detail, r.vis) for r in prog1.sync_reports]
        r2 = [(r.entry, r.detail, r.vis) for r in prog2.sync_reports]
        assert r1 == r2

    def test_cache_invalidates_on_edit(self, tmp_path, monkeypatch):
        pkg = write_pkg(tmp_path, {"lib.py": self.SRC})
        cache = str(tmp_path / "summaries.json")
        build_program(self._contexts(pkg), cache_path=cache)

        # edit the file: the content hash changes, so extraction MUST re-run
        (tmp_path / "pkg" / "lib.py").write_text(
            textwrap.dedent(self.SRC) + "\n# trailing comment\n"
        )
        calls = []
        real = summaries_mod.extract_effects
        monkeypatch.setattr(
            summaries_mod,
            "extract_effects",
            lambda ctx: (calls.append(ctx.path), real(ctx))[1],
        )
        build_program(self._contexts(pkg), cache_path=cache)
        assert any(p.endswith("lib.py") for p in calls)

    def test_corrupt_cache_is_a_miss_not_an_error(self, tmp_path):
        pkg = write_pkg(tmp_path, {"lib.py": self.SRC})
        cache = str(tmp_path / "summaries.json")
        with open(cache, "w") as fh:
            fh.write("{not json")
        prog = build_program(self._contexts(pkg), cache_path=cache)
        assert prog.sync_reports  # analysis still ran

    def test_findings_identical_with_and_without_cache(self, tmp_path):
        pkg = write_pkg(tmp_path, {"lib.py": self.SRC})
        cache = str(tmp_path / "summaries.json")
        cold = lint_paths([pkg], select=["HT202"], cache_path=cache)
        warm = lint_paths([pkg], select=["HT202"], cache_path=cache)
        assert [f.to_dict() for f in cold] == [f.to_dict() for f in warm]

    def test_narrow_run_preserves_out_of_scope_cache_entries(self, tmp_path):
        # a one-file invocation must not wipe the repo-wide cache: only
        # entries whose file is GONE from disk are evicted
        pkg = write_pkg(
            tmp_path, {"lib.py": self.SRC, "other.py": "def g():\n    return 1\n"}
        )
        cache = str(tmp_path / "summaries.json")
        lint_paths([pkg], select=["HT202"], cache_path=cache)
        assert len(json.load(open(cache))["files"]) >= 3  # lib, other, __init__
        lint_paths([os.path.join(pkg, "lib.py")], select=["HT202"], cache_path=cache)
        kept = json.load(open(cache))["files"]
        assert any(p.endswith("other.py") for p in kept)
        # a DELETED file's entry does get evicted on the next run
        os.remove(os.path.join(pkg, "other.py"))
        lint_paths([pkg], select=["HT202"], cache_path=cache)
        kept = json.load(open(cache))["files"]
        assert not any(p.endswith("other.py") for p in kept)


# ---------------------------------------------------------------------- #
# per-directory rule config (framework.DIR_RULE_CONFIG)
# ---------------------------------------------------------------------- #
class TestDirConfig:
    def test_benchmarks_relaxed_but_desync_rules_stay_on(self, tmp_path):
        bench = tmp_path / "benchmarks"
        bench.mkdir()
        (bench / "bench.py").write_text(
            textwrap.dedent(
                """
                import jax

                def _stage(comm, x):
                    return comm.Bcast(x)

                def measure(comm, x):
                    t = x.sum().item()          # host sync: legitimate here
                    jax.block_until_ready(x)    # timing wait: legitimate here
                    if comm.rank == 0:
                        _stage(comm, x)         # desync hazard: NOT legitimate
                    return t
                """
            )
        )
        fs = lint_paths([str(bench)])
        rules = sorted({f.rule for f in fs})
        assert "HT101" not in rules and "HT107" not in rules
        assert "HT201" in rules

    def test_library_paths_keep_full_select(self, tmp_path):
        lib = tmp_path / "somelib"
        lib.mkdir()
        (lib / "mod.py").write_text("def f(x):\n    return x.sum().item()\n")
        fs = lint_paths([str(lib)], select=["HT101"])
        assert len(fs) == 1


# ---------------------------------------------------------------------- #
# SARIF 2.1.0 renderer
# ---------------------------------------------------------------------- #
class TestSarif:
    def test_sarif_structure_and_codeflows(self, tmp_path):
        files = {
            "lib.py": """
                def _fetch(x):
                    return x.sum().item()

                def api(x):
                    return _fetch(x)
            """
        }
        fs = run_rules(tmp_path, files, ["HT101", "HT202"])
        errors = [f for f in fs if f.severity == "error"]
        log = json.loads(render_sarif(errors, [], []))
        assert log["version"] == "2.1.0"
        assert "sarif-schema-2.1.0" in log["$schema"]
        run = log["runs"][0]
        assert run["tool"]["driver"]["name"] == "heatlint"
        rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert {"HT201", "HT202", "HT203", "HT204"} <= rule_ids
        results = run["results"]
        assert results and all(r["level"] == "error" for r in results)
        for r in results:
            loc = r["locations"][0]["physicalLocation"]
            assert loc["artifactLocation"]["uri"]
            assert loc["region"]["startLine"] >= 1
            assert "heatlintFingerprint/v1" in r["partialFingerprints"]
        flows = [r for r in results if "codeFlows" in r]
        assert flows, "interprocedural finding must carry a codeFlow"
        tf = flows[0]["codeFlows"][0]["threadFlows"][0]["locations"]
        assert len(tf) >= 2  # entry -> sink

    def test_baselined_results_carry_suppressions(self, tmp_path):
        files = {"lib.py": "def f(x):\n    return x.sum().item()\n"}
        fs = run_rules(tmp_path, files, ["HT101"])
        log = json.loads(render_sarif([], fs, []))
        res = log["runs"][0]["results"]
        assert res[0]["suppressions"][0]["kind"] == "external"
        assert res[0]["level"] == "note"

    def test_cli_json_carries_unresolved_bucket(self, tmp_path, capsys):
        # the honesty policy's audit trail: every unresolvable call with
        # its reason lands in the machine output, never silently dropped
        src_dir = tmp_path / "pkg"
        src_dir.mkdir()
        (src_dir / "lib.py").write_text(
            "def run(comm, fn, x):\n"
            "    if comm.rank == 0:\n"
            "        fn(x)\n"
            "    return x\n"
        )
        out_json = str(tmp_path / "out.json")
        heatlint_cli.main(
            [str(src_dir), "--baseline", str(tmp_path / "bl.json"),
             "--json", out_json, "--no-cache"]
        )
        capsys.readouterr()
        data = json.load(open(out_json))
        recs = data["unresolved_calls"]
        assert any(u["reason"] == "param-callable" and u["call"] == "fn" for u in recs)

    def test_cli_sarif_flag_writes_valid_log(self, tmp_path, capsys):
        src_dir = tmp_path / "pkg"
        src_dir.mkdir()
        (src_dir / "lib.py").write_text(
            "def _fetch(x):\n    return x.sum().item()\n\n"
            "def api(x):\n    return _fetch(x)\n"
        )
        sarif_path = str(tmp_path / "out.sarif")
        rc = heatlint_cli.main(
            [str(src_dir), "--baseline", str(tmp_path / "bl.json"),
             "--sarif", sarif_path, "--no-cache"]
        )
        capsys.readouterr()
        assert rc == 1  # new findings
        log = json.load(open(sarif_path))
        assert log["version"] == "2.1.0"
        assert any(r["ruleId"] == "HT202" for r in log["runs"][0]["results"])


# ---------------------------------------------------------------------- #
# performance + stdlib-only contracts
# ---------------------------------------------------------------------- #
class TestContracts:
    def test_repo_run_under_ten_seconds(self):
        """Single-parse satellite: the full repo run — every rule including
        the interprocedural passes, cold cache — stays under 10 s."""
        t0 = time.monotonic()
        lint_paths(
            [
                os.path.join(REPO, "heat_tpu"),
                os.path.join(REPO, "benchmarks"),
                os.path.join(REPO, "tutorials"),
            ],
            cache_path=None,
        )
        assert time.monotonic() - t0 < 10.0

    def test_cli_with_new_passes_never_imports_jax_or_numpy(self, tmp_path):
        """The jax-import-blocking contract extended to the interprocedural
        passes: the CLI (callgraph + summaries + SARIF included) completes
        with jax/numpy/torch imports BLOCKED — the CI heatlint lane installs
        nothing."""
        fixture = tmp_path / "pkg"
        fixture.mkdir()
        (fixture / "lib.py").write_text(
            "def _stage(comm, x):\n    return comm.Bcast(x)\n\n"
            "def run(comm, x):\n    if comm.rank == 0:\n        _stage(comm, x)\n"
        )
        sarif = str(tmp_path / "out.sarif")
        blocker = (
            "import sys\n"
            "class _Block:\n"
            "    def find_module(self, name, path=None):\n"
            "        if name.split('.')[0] in ('jax', 'numpy', 'torch', 'jaxlib'):\n"
            "            raise ImportError('blocked: ' + name)\n"
            "sys.meta_path.insert(0, _Block())\n"
            f"sys.argv = ['heatlint', {str(fixture)!r}, '--no-cache',\n"
            f"            '--baseline', {str(tmp_path / 'bl.json')!r},\n"
            f"            '--sarif', {sarif!r}]\n"
            "import runpy\n"
            "try:\n"
            f"    runpy.run_path({os.path.join(REPO, 'scripts', 'heatlint.py')!r}, "
            "run_name='__main__')\n"
            "except SystemExit as e:\n"
            "    raise SystemExit(e.code)\n"
        )
        p = subprocess.run(
            [sys.executable, "-c", blocker],
            capture_output=True,
            text=True,
            timeout=120,
        )
        # exit 1 = the fixture's HT201 finding was detected, with zero
        # non-stdlib imports available
        assert p.returncode == 1, p.stderr[-2000:]
        assert "HT201" in p.stdout
        log = json.load(open(sarif))
        assert log["version"] == "2.1.0"


# ---------------------------------------------------------------------- #
# the repo gate, interprocedural edition
# ---------------------------------------------------------------------- #
class TestRepoGateInterproc:
    def test_repo_clean_with_ht2xx_and_extended_scope(self, capsys):
        """Acceptance: the repo-wide run with HT2xx enabled over heat_tpu/ +
        benchmarks/ + tutorials/ is clean vs the committed baseline."""
        rc = heatlint_cli.main(
            [
                os.path.join(REPO, "heat_tpu"),
                os.path.join(REPO, "benchmarks"),
                os.path.join(REPO, "tutorials"),
                "--no-cache",
            ]
        )
        capsys.readouterr()
        assert rc == 0

    def test_baseline_net_smaller_than_before_this_pr(self):
        """Acceptance: the interprocedural evidence FIXED grandfathered
        findings (ravel_multi_index host syncs -> one host_fetch; io.py
        sync_global_devices -> comm.deadline via _bounded_sync; the
        gaussianNB priors validation -> host-side) instead of suppressing
        them: the baseline shrank from 32 entries."""
        records = json.load(open(os.path.join(REPO, ".heatlint-baseline.json")))
        assert len(records["findings"]) <= 30  # was 32 before ISSUE 8
        baseline = load_baseline(os.path.join(REPO, ".heatlint-baseline.json"))
        gone = [
            "heat_tpu/core/factories.py:HT101:ravel_multi_index:int-cast",
            "heat_tpu/core/io.py:HT107:save_zarr:sync_global_devices",
            "heat_tpu/core/io.py:HT107:_token_ring_write:sync_global_devices",
        ]
        for fp in gone:
            assert fp not in baseline

    def test_fixed_sites_are_clean_not_suppressed(self):
        fs = lint_paths(
            [os.path.join(REPO, "heat_tpu", "core", "factories.py")], select=["HT101"]
        )
        assert [f for f in fs if f.qualname == "ravel_multi_index"] == []
        fs = lint_paths(
            [os.path.join(REPO, "heat_tpu", "core", "io.py")], select=["HT107"]
        )
        assert fs == []
