"""Deterministic chaos campaign engine (stdlib-only, jax-free).

Sweeps pseudo-random fault schedules drawn from ``faults.catalog()``
against real supervised multi-process workloads, judges every run with
invariant oracles, and auto-shrinks failing schedules to minimal
``CHAOS-REPRO`` reproducers.  See design.md "Chaos engineering".

Layout:

- ``schedule``   — seeded fault-schedule generation, tokens, repro lines
- ``worker``     — the fast-tier supervised harness workload
- ``oracles``    — the invariant suite judging a finished run
- ``engine``     — Supervisor-driven runner, campaign journal, verdicts
- ``shrink``     — greedy delta-debugging to a re-confirmed minimum
- ``scenarios``  — the five legacy full-tier scenarios as declarative specs

Every submodule is also standalone-loadable by path (the
``scripts/chaoscamp.py`` / supervisor-host discipline: no package import
may pull in jax).
"""

from . import engine, oracles, scenarios, schedule, shrink  # noqa: F401

__all__ = ["engine", "oracles", "scenarios", "schedule", "shrink"]
