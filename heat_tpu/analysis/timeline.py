"""Cross-rank timeline assembly, Chrome-trace export, critical-path blame.

The runtime already records everything this module needs — telemetry span
JSONL exports (``utils/telemetry.py``), crash-durable flight-ring
collective stamps (``utils/flightrec.py``), scheduler/federation journals
(``parallel/scheduler.py`` / ``parallel/federation.py``) — but each
artifact answers only "how much".  This module merges them into ONE
cross-rank timeline and answers "which rank, which op, which seq gated
this step":

- **Clock alignment.**  Each rank's span timestamps live in a private
  ``perf_counter`` domain anchored to wall clock once at import
  (``telemetry._T0_PERF``/``_T0_WALL``); ring stamps are raw
  ``time.time()``.  Neither is comparable across hosts.  The shared
  collective-stamp anchors fix that: in lockstep SPMD, equal ``seq``
  means the same logical staging instant, so a rank's offset against the
  reference rank is the **robust median** of its per-seq stamp deltas,
  with the max residual reported as the quality bound.  One offset per
  rank corrects both streams (spans and stamps share the rank's wall
  clock).  A rank with telemetry but no ring is NAMED unaligned — it is
  never silently merged on a clock nobody estimated.
- **Chrome trace-event export** (:func:`to_chrome_trace`): one pid per
  rank; lanes for compute spans, collectives, host syncs; a pseudo-pid
  for scheduler journal records; flow events joining every collective's
  participants across ranks via its ``seq`` and ``trace_id`` flows
  across ingress → scheduler → serving.  :func:`validate_chrome_trace`
  is the stdlib schema checker CI runs against the exported artifact.
- **Critical path** (:func:`critical_path`): per step-cycle (the
  stepprof window rule — a step's window runs to the next same-name step
  start on that rank), every instant is attributed to the highest-
  priority active record (host sync > comm wait > compute), naming the
  dominant contributor per step kind; across ranks, every shared seq
  charges its **gating rank** (the last stamper) with the stamp spread,
  and a rank whose stream stops short is charged the whole time the
  world kept going without it — which is how the chaos lane's injected
  straggler gets named.  Output: greppable ``CRITICAL-PATH kind=… rank=…
  op=… seq=… share=…`` lines plus per-rank / per-op blame tables.

Stdlib-only and standalone-loadable on purpose (the postmortem pattern):
``scripts/traceviz.py`` loads this file via ``spec_from_file_location``
on machines that never import jax.  Everything here is post-hoc reading
of already-written artifacts — the hot paths gain zero cost.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "DEFAULT_STEPS",
    "assemble",
    "estimate_clock_offsets",
    "load_telemetry",
    "load_rings",
    "load_journals",
    "to_chrome_trace",
    "validate_chrome_trace",
    "critical_path",
    "critical_path_report",
    "clock_report",
    "classify",
]

DEFAULT_STEPS = ("daso.step", "optim.step", "nn.train_step", "sched.job")

# trace lanes (tid per rank pid); the scheduler journal gets its own
# pseudo-pid — journals are written by one process for the whole world
LANE_COMPUTE = 0
LANE_COLL = 1
LANE_HOST = 2
SCHED_PID = 1 << 20
_LANE_NAMES = {
    LANE_COMPUTE: "compute spans",
    LANE_COLL: "collectives",
    LANE_HOST: "host syncs",
}


def classify(name: str) -> str:
    """Span class, mirroring ``scripts/stepprof.py``: host syncs outrank
    comm waits outrank compute when deciding what gates an instant."""
    if "host_fetch" in name or name.startswith("io."):
        return "host"
    if name.startswith("comm.") or name.endswith(".wait"):
        return "comm"
    return "compute"


# ---------------------------------------------------------------------- #
# artifact loading (flightrec is the ONE ring-format implementation —
# loaded standalone exactly like scripts/postmortem.py does)
# ---------------------------------------------------------------------- #
_flightrec = None


def _flightrec_mod():
    mod = sys.modules.get("heat_tpu.utils.flightrec")
    if mod is not None:
        return mod
    global _flightrec
    if _flightrec is None:
        import importlib.util

        path = os.path.normpath(
            os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                os.pardir, "utils", "flightrec.py",
            )
        )
        spec = importlib.util.spec_from_file_location("heat_timeline_flightrec", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        _flightrec = mod
    return _flightrec


def expand_dirs(dirs: List[str]) -> List[str]:
    """Each dir plus its harvested ``epoch<N>/`` ring subdirectories (the
    supervisor moves a failed generation's rings there at teardown).
    Epoch dirs come FIRST so a live ring for the same rank wins the merge
    — the final generation is the story the timeline tells."""
    out: List[str] = []
    for d in dirs:
        subs = []
        try:
            for name in sorted(os.listdir(d)):
                p = os.path.join(d, name)
                if name.startswith("epoch") and os.path.isdir(p):
                    subs.append(p)
        except OSError:
            pass
        out.extend(subs)
        out.append(d)
    return list(dict.fromkeys(out))


def load_telemetry(dirs: List[str]) -> Tuple[Dict[int, List[dict]], Dict[int, dict]]:
    """``rank → span records`` and ``rank → meta record`` from every
    ``rank<k>.jsonl`` under the target dirs.  Torn lines are skipped —
    the exporter may have died mid-flush."""
    spans: Dict[int, List[dict]] = {}
    meta: Dict[int, dict] = {}
    for d in dirs:
        try:
            names = sorted(os.listdir(d))
        except OSError:
            continue
        for name in names:
            if not (name.startswith("rank") and name.endswith(".jsonl")):
                continue
            try:
                rank = int(name[len("rank"):-len(".jsonl")])
            except ValueError:
                continue
            try:
                with open(os.path.join(d, name)) as fh:
                    for line in fh:
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if not isinstance(rec, dict):
                            continue
                        if rec.get("type") == "span":
                            spans.setdefault(rank, []).append(rec)
                        elif rec.get("type") == "meta":
                            meta[rank] = rec
            except OSError:
                continue
    for sp in spans.values():
        sp.sort(key=lambda r: r.get("ts", 0.0))
    return spans, meta


def load_rings(dirs: List[str]) -> Dict[int, dict]:
    """``rank → parsed ring`` across the target dirs; a later dir's ring
    for the same rank replaces an earlier one (see :func:`expand_dirs`).
    Unreadable files are skipped, never fatal."""
    fr = _flightrec_mod()
    rings: Dict[int, dict] = {}
    for d in dirs:
        for path in fr.find_ring_files(d):
            try:
                ring = fr.read_ring(path)
            except (OSError, ValueError):
                continue
            rings[int(ring.get("rank", 0))] = ring
    return rings


def load_journals(dirs: List[str]) -> List[dict]:
    """Scheduler/federation journal records (``*journal*.jsonl``) across
    the target dirs, each tagged with its source path."""
    out: List[dict] = []
    seen = set()
    for d in dirs:
        try:
            names = sorted(os.listdir(d))
        except OSError:
            continue
        for name in names:
            if "journal" not in name or not name.endswith(".jsonl"):
                continue
            path = os.path.join(d, name)
            if path in seen:
                continue
            seen.add(path)
            try:
                with open(path) as fh:
                    for line in fh:
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if isinstance(rec, dict) and rec.get("type"):
                            rec["_journal"] = name
                            out.append(rec)
            except OSError:
                continue
    out.sort(key=lambda r: r.get("t", 0.0))
    return out


# ---------------------------------------------------------------------- #
# clock alignment
# ---------------------------------------------------------------------- #
def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def _coll_stamps(ring: dict) -> Dict[int, dict]:
    """seq → coll record (last write wins: after a wrap the ring holds the
    latest window; duplicates cannot survive it anyway)."""
    out: Dict[int, dict] = {}
    for rec in ring.get("records", []):
        if rec.get("k") == "coll" and rec.get("seq") is not None and rec.get("t") is not None:
            try:
                out[int(rec["seq"])] = rec
            except (TypeError, ValueError):
                continue
    return out


def estimate_clock_offsets(rings: Dict[int, dict]) -> dict:
    """Per-rank clock offsets from the shared collective-stamp anchors.

    Reference = the lowest rank holding stamps (offset 0 by definition).
    For every other rank, offset = median of ``t_rank(seq) − t_ref(seq)``
    over the shared seqs — robust against the handful of seqs where one
    rank really was late (the very skew the critical path then measures)
    — and ``max_residual_s`` bounds how far any single anchor strays from
    the estimate.  Aligned time = ``t_raw − offset``.

    A rank sharing no seq with the reference is returned in
    ``unaligned`` — named, never silently aligned."""
    stamps = {r: _coll_stamps(ring) for r, ring in rings.items()}
    stamps = {r: s for r, s in stamps.items() if s}
    align: dict = {"ref": None, "offsets": {}, "per_rank": {}, "unaligned": []}
    if not stamps:
        align["unaligned"] = [
            {"rank": r, "reason": "no-collective-stamps"} for r in sorted(rings)
        ]
        return align
    ref = min(stamps)
    align["ref"] = ref
    align["offsets"][ref] = 0.0
    align["per_rank"][ref] = {
        "anchors": len(stamps[ref]), "offset_s": 0.0, "max_residual_s": 0.0,
    }
    for r in sorted(stamps):
        if r == ref:
            continue
        shared = sorted(set(stamps[r]) & set(stamps[ref]))
        if not shared:
            align["unaligned"].append({"rank": r, "reason": "no-shared-anchors"})
            continue
        deltas = [
            float(stamps[r][s]["t"]) - float(stamps[ref][s]["t"]) for s in shared
        ]
        off = _median(deltas)
        align["offsets"][r] = off
        align["per_rank"][r] = {
            "anchors": len(shared),
            "offset_s": off,
            "max_residual_s": max(abs(d - off) for d in deltas),
        }
    for r in sorted(rings):
        if r not in stamps and r != ref:
            align["unaligned"].append({"rank": r, "reason": "no-collective-stamps"})
    return align


# ---------------------------------------------------------------------- #
# assembly
# ---------------------------------------------------------------------- #
def assemble(dirs: List[str], step_names: Tuple[str, ...] = DEFAULT_STEPS) -> dict:
    """Load + align every artifact under ``dirs`` (epoch ring harvests
    included) into one bundle the exporters and the critical-path walker
    consume."""
    dirs = expand_dirs([d for d in dirs if d])
    spans, meta = load_telemetry(dirs)
    rings = load_rings(dirs)
    journals = load_journals(dirs)
    align = estimate_clock_offsets(rings)
    # telemetry without a ring: no anchors exist for this rank's clock —
    # name it; its events still export on its own (uncorrected) clock
    for r in sorted(spans):
        if r not in rings:
            align["unaligned"].append({"rank": r, "reason": "no-ring"})
    align["unaligned"].sort(key=lambda u: u["rank"])
    offsets = align["offsets"]

    # journal clock domain: the writer's pid, matched to a rank via ring /
    # telemetry meta pids, borrows that rank's offset
    jpids = {
        rec.get("pid") for rec in journals if rec.get("type") == "meta"
    } - {None}
    journal_offset = 0.0
    pid_to_rank = {ring.get("pid"): r for r, ring in rings.items()}
    pid_to_rank.update({m.get("pid"): r for r, m in meta.items()})
    for p in jpids:
        if p in pid_to_rank and pid_to_rank[p] in offsets:
            journal_offset = offsets[pid_to_rank[p]]
            break

    t0 = None
    for r, sp in spans.items():
        off = offsets.get(r, 0.0)
        for s in sp:
            t = float(s.get("ts", 0.0)) - off
            t0 = t if t0 is None else min(t0, t)
    for r, ring in rings.items():
        off = offsets.get(r, 0.0)
        for rec in ring.get("records", []):
            if rec.get("t") is not None:
                try:
                    t = float(rec["t"]) - off
                except (TypeError, ValueError):
                    continue
                t0 = t if t0 is None else min(t0, t)
    for rec in journals:
        if rec.get("t") is not None:
            t = float(rec["t"]) - journal_offset
            t0 = t if t0 is None else min(t0, t)

    return {
        "ranks": sorted(set(spans) | set(rings)),
        "spans": spans,
        "meta": meta,
        "rings": rings,
        "journals": journals,
        "journal_offset": journal_offset,
        "align": align,
        "offsets": offsets,
        "t0": t0 if t0 is not None else 0.0,
        "step_names": tuple(step_names),
        "dirs": dirs,
    }


def _aligned(bundle: dict, rank: int, t: float) -> float:
    return float(t) - bundle["offsets"].get(rank, 0.0)


# ---------------------------------------------------------------------- #
# Chrome trace-event export
# ---------------------------------------------------------------------- #
def _us(bundle: dict, rank: int, t: float) -> float:
    return round((_aligned(bundle, rank, t) - bundle["t0"]) * 1e6, 1)


def to_chrome_trace(bundle: dict) -> dict:
    """The merged bundle as Chrome trace-event JSON (Perfetto-loadable).

    Mapping (documented in design.md "Timeline export & critical path"):
    telemetry span → ``X`` on the rank's compute/collectives/host lane;
    ring ``coll`` stamp → 1 µs ``X`` on the collectives lane + ``s/t/f``
    flow chain joining every participant of that seq; ring
    ``ckpt/resume/shutdown/mem`` → ``i`` instants; ring ``span``/
    ``span_end`` pairs → reconstructed ``X`` slices ONLY for ranks with
    no telemetry export (the chaos post-mortem case); journal record →
    ``i`` on the scheduler pseudo-pid + per-job ``X`` slice; trace ids →
    ``s/t/f`` flows across every source that carries them."""
    ev: List[dict] = []
    flows: List[dict] = []
    trace_points: Dict[str, List[Tuple[float, int, int, str]]] = {}

    for rank in bundle["ranks"]:
        ev.append({"ph": "M", "pid": rank, "tid": 0, "name": "process_name",
                   "args": {"name": f"rank{rank}"}})
        ev.append({"ph": "M", "pid": rank, "tid": 0, "name": "process_sort_index",
                   "args": {"sort_index": rank}})
        for lane, lname in _LANE_NAMES.items():
            ev.append({"ph": "M", "pid": rank, "tid": lane, "name": "thread_name",
                       "args": {"name": lname}})

    # telemetry spans
    for rank, sp in bundle["spans"].items():
        for s in sp:
            name = str(s.get("name", "?"))
            lane = {"compute": LANE_COMPUTE, "comm": LANE_COLL,
                    "host": LANE_HOST}[classify(name)]
            ts = _us(bundle, rank, float(s.get("ts", 0.0)))
            dur = max(float(s.get("dur_s", 0.0)) * 1e6, 1.0)
            attrs = s.get("attrs") or {}
            ev.append({
                "ph": "X", "pid": rank, "tid": lane, "ts": ts, "dur": round(dur, 1),
                "name": name, "cat": classify(name),
                "args": {k: v for k, v in attrs.items()},
            })
            tid = attrs.get("trace_id")
            if tid:
                trace_points.setdefault(str(tid), []).append((ts, rank, lane, name))

    # flight-ring records
    coll_by_seq: Dict[int, List[Tuple[float, int, str]]] = {}
    for rank, ring in bundle["rings"].items():
        open_spans: List[Tuple[str, float]] = []
        has_telemetry = rank in bundle["spans"]
        for rec in ring.get("records", []):
            k = rec.get("k")
            try:
                t = float(rec.get("t"))
            except (TypeError, ValueError):
                continue
            ts = _us(bundle, rank, t)
            if k == "coll":
                op = str(rec.get("op", "?"))
                args = {
                    f: rec[f]
                    for f in ("seq", "wire", "gshape", "dtype", "src", "dst", "dl", "tid")
                    if rec.get(f) is not None
                }
                ev.append({"ph": "X", "pid": rank, "tid": LANE_COLL, "ts": ts,
                           "dur": 1.0, "name": op, "cat": "collective-stamp",
                           "args": args})
                if rec.get("seq") is not None:
                    try:
                        coll_by_seq.setdefault(int(rec["seq"]), []).append((ts, rank, op))
                    except (TypeError, ValueError):
                        pass
                if rec.get("tid"):
                    trace_points.setdefault(str(rec["tid"]), []).append(
                        (ts, rank, LANE_COLL, op)
                    )
            elif k in ("ckpt", "resume", "shutdown") or (k == "mem" and rec.get("oom")):
                name = "OOM" if k == "mem" else k
                ev.append({"ph": "i", "pid": rank, "tid": LANE_HOST, "ts": ts,
                           "s": "p", "name": name, "cat": "marker"})
            elif k == "span" and not has_telemetry:
                open_spans.append((str(rec.get("name", "?")), ts))
            elif k == "span_end" and not has_telemetry and open_spans:
                name, t_open = open_spans.pop()
                ev.append({"ph": "X", "pid": rank, "tid": LANE_COMPUTE,
                           "ts": t_open, "dur": round(max(ts - t_open, 1.0), 1),
                           "name": name, "cat": "ring-span", "args": {}})

    # flow events: every collective seq joins its participants across ranks
    for seq, parts in sorted(coll_by_seq.items()):
        if len(parts) < 2:
            continue
        parts.sort()
        for i, (ts, rank, op) in enumerate(parts):
            ph = "s" if i == 0 else ("f" if i == len(parts) - 1 else "t")
            flow = {"ph": ph, "pid": rank, "tid": LANE_COLL, "ts": ts,
                    "name": op, "cat": "collective", "id": seq}
            if ph == "f":
                flow["bp"] = "e"
            flows.append(flow)

    # scheduler / federation journals: one pseudo-process, job slices +
    # per-record instants
    if bundle["journals"]:
        ev.append({"ph": "M", "pid": SCHED_PID, "tid": 0, "name": "process_name",
                   "args": {"name": "scheduler (journal)"}})
        ev.append({"ph": "M", "pid": SCHED_PID, "tid": 0, "name": "thread_name",
                   "args": {"name": "scheduler jobs"}})
        joff = bundle["journal_offset"]
        jobs: Dict[str, List[Tuple[float, str]]] = {}
        for rec in bundle["journals"]:
            try:
                t = float(rec.get("t"))
            except (TypeError, ValueError):
                continue
            ts = round((t - joff - bundle["t0"]) * 1e6, 1)
            kind = str(rec.get("type", "?"))
            if kind == "meta":
                continue
            args = {
                f: rec[f] for f in ("id", "kind", "tenant", "tid", "epoch", "reason")
                if rec.get(f) is not None
            }
            ev.append({"ph": "i", "pid": SCHED_PID, "tid": 0, "ts": ts, "s": "t",
                       "name": kind, "cat": "journal", "args": args})
            if rec.get("id") is not None:
                jobs.setdefault(str(rec["id"]), []).append((ts, kind))
            if rec.get("tid"):
                trace_points.setdefault(str(rec["tid"]), []).append(
                    (ts, SCHED_PID, 0, kind)
                )
        for job_id, points in sorted(jobs.items()):
            points.sort()
            t_first, t_last = points[0][0], points[-1][0]
            ev.append({
                "ph": "X", "pid": SCHED_PID, "tid": 0, "ts": t_first,
                "dur": round(max(t_last - t_first, 1.0), 1),
                "name": f"job {job_id}", "cat": "job",
                "args": {"records": [k for _, k in points]},
            })

    # trace-id flows: ingress → scheduler → serving → collectives
    for tid, points in sorted(trace_points.items()):
        spots = sorted(set(points))
        if len(spots) < 2 or len({(p[1], p[2]) for p in spots}) < 2:
            continue
        for i, (ts, pid, lane, name) in enumerate(spots):
            ph = "s" if i == 0 else ("f" if i == len(spots) - 1 else "t")
            flow = {"ph": ph, "pid": pid, "tid": lane, "ts": ts,
                    "name": "trace", "cat": "trace", "id": f"tr-{tid}"}
            if ph == "f":
                flow["bp"] = "e"
            flows.append(flow)

    align = bundle["align"]
    return {
        "traceEvents": ev + flows,
        "displayTimeUnit": "ms",
        "otherData": {
            "t0_epoch_s": bundle["t0"],
            "clock_ref_rank": align.get("ref"),
            "clock_offsets_s": {
                str(r): round(o, 6) for r, o in sorted(bundle["offsets"].items())
            },
            "clock_unaligned": align.get("unaligned", []),
            "source_dirs": bundle.get("dirs", []),
        },
    }


# every phase the trace-event format defines (the exporter above uses
# X/i/M/s/t/f; the checker accepts the full alphabet so it can validate
# foreign traces too)
_VALID_PH = frozenset({
    "B", "E", "X",            # duration
    "i", "I",                 # instant (I is the legacy spelling)
    "C",                      # counter
    "b", "n", "e",            # async
    "s", "t", "f",            # flow
    "S", "T", "p", "F",       # legacy async
    "M",                      # metadata
    "P",                      # sample
    "N", "O", "D",            # object
    "R",                      # mark
    "c",                      # clock sync
    "a",                      # linked id
    "v", "V",                 # memory dumps
    "(", ")",                 # legacy context
})
_TS_FREE = frozenset("M")  # metadata events carry no timestamp


def validate_chrome_trace(obj: Any, max_problems: int = 25) -> List[str]:
    """Stdlib trace-event schema check: [] iff ``obj`` is a loadable
    Chrome trace.  Deliberately structural (phases, required fields,
    numeric timestamps, flow ids) — the CI gate for the exported
    artifact."""
    problems: List[str] = []

    def bad(msg: str) -> bool:
        problems.append(msg)
        return len(problems) >= max_problems

    if not isinstance(obj, dict):
        return ["top level: expected an object with 'traceEvents'"]
    events = obj.get("traceEvents")
    if not isinstance(events, list):
        return ["top level: 'traceEvents' missing or not a list"]
    for i, e in enumerate(events):
        if not isinstance(e, dict):
            if bad(f"event {i}: not an object"):
                break
            continue
        ph = e.get("ph")
        if not isinstance(ph, str) or ph not in _VALID_PH:
            if bad(f"event {i}: bad phase {ph!r}"):
                break
            continue
        if "pid" not in e:
            if bad(f"event {i} (ph={ph}): missing pid"):
                break
            continue
        if ph not in _TS_FREE:
            ts = e.get("ts")
            if not isinstance(ts, (int, float)) or isinstance(ts, bool):
                if bad(f"event {i} (ph={ph}): non-numeric ts {ts!r}"):
                    break
                continue
        if not isinstance(e.get("name", ""), str):
            if bad(f"event {i} (ph={ph}): non-string name"):
                break
            continue
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or isinstance(dur, bool) or dur < 0:
                if bad(f"event {i} (X): bad dur {dur!r}"):
                    break
                continue
        if ph in "stf" and e.get("id") is None:
            if bad(f"event {i} (flow {ph}): missing id"):
                break
            continue
    return problems


# ---------------------------------------------------------------------- #
# critical path + blame
# ---------------------------------------------------------------------- #
def _step_windows(spans: List[dict], name: str) -> List[Tuple[float, float, dict]]:
    """One rank's step cycles for ``name``: window = step start → next
    same-name step start (the stepprof rule); the last window is the
    step's own extent (post-run idle gates nothing)."""
    steps = sorted(
        (s for s in spans if s.get("name") == name),
        key=lambda s: float(s.get("ts", 0.0)),
    )
    out = []
    for i, s in enumerate(steps):
        t0 = float(s.get("ts", 0.0))
        t1 = (
            float(steps[i + 1].get("ts", 0.0))
            if i + 1 < len(steps)
            else t0 + float(s.get("dur_s", 0.0))
        )
        if t1 > t0:
            out.append((t0, t1, s))
    return out


def _window_segments(
    rank: int, spans: List[dict], t0: float, t1: float, step: dict
) -> List[Tuple[float, str, str]]:
    """Attribute every elementary segment of one step window to the record
    gating it: (seconds, class, op-name).  Host spans outrank comm spans
    outrank the step's own compute — at any instant the highest-priority
    live record is what progress is waiting on."""
    marks = {t0, t1}
    active: List[Tuple[float, float, str, str]] = []
    for s in spans:
        if s is step:
            continue
        cls = classify(str(s.get("name", "")))
        if cls == "compute":
            continue
        a = float(s.get("ts", 0.0))
        b = a + float(s.get("dur_s", 0.0))
        a, b = max(a, t0), min(b, t1)
        if b <= a:
            continue
        active.append((a, b, cls, str(s.get("name", "?"))))
        marks.add(a)
        marks.add(b)
    points = sorted(marks)
    segs: List[Tuple[float, str, str]] = []
    step_name = str(step.get("name", "?"))
    for a, b in zip(points, points[1:]):
        mid = (a + b) / 2.0
        live = [iv for iv in active if iv[0] <= mid < iv[1]]
        host = [iv for iv in live if iv[2] == "host"]
        comm = [iv for iv in live if iv[2] == "comm"]
        if host:
            segs.append((b - a, "host", host[0][3]))
        elif comm:
            segs.append((b - a, "comm", comm[0][3]))
        else:
            segs.append((b - a, "compute", step_name))
    return segs


def critical_path(
    bundle: dict, step_names: Optional[Tuple[str, ...]] = None
) -> dict:
    """The gating chain, two views over the ALIGNED timeline:

    - per step kind: dominant (class, rank, op) share of the summed
      step-cycle windows; a comm contributor carries the seq of the
      rank's latest collective stamp at the segment;
    - per collective seq across ranks: the gating rank (last stamper)
      charged with the stamp spread — and a rank whose stream stops
      short of the world's charged with the (right-censored) time the
      world kept recording without it, at its last stamped (seq, op),
      and NAMED as the gating rank regardless of charge magnitude: the
      injected straggler's conviction, matching the post-mortem's.

    Returns steps/collective detail, greppable ``lines``, and the merged
    per-rank / per-op ``blame`` shares."""
    step_names = tuple(step_names or bundle.get("step_names") or DEFAULT_STEPS)
    spans = bundle["spans"]
    offsets = bundle["offsets"]

    # per-rank aligned stamp streams
    stamps: Dict[int, Dict[int, Tuple[float, str]]] = {}
    for rank, ring in bundle["rings"].items():
        off = offsets.get(rank, 0.0)
        by = {}
        for seq, rec in _coll_stamps(ring).items():
            try:
                by[seq] = (float(rec["t"]) - off, str(rec.get("op", "?")))
            except (TypeError, ValueError):
                continue
        if by:
            stamps[rank] = by

    lines: List[str] = []
    blame: Dict[Tuple[int, str], float] = {}

    # ---- per step kind ------------------------------------------------ #
    steps_out: Dict[str, dict] = {}
    for kind in step_names:
        contrib: Dict[Tuple[str, int, str], dict] = {}
        total = 0.0
        windows = 0
        for rank, sp in spans.items():
            my_stamps = sorted(
                (t, seq, op) for seq, (t, op) in stamps.get(rank, {}).items()
            )
            for t0, t1, step in _step_windows(sp, kind):
                off = offsets.get(rank, 0.0)
                windows += 1
                total += t1 - t0
                seg_t = t0
                for secs, cls, op in _window_segments(rank, sp, t0, t1, step):
                    c = contrib.setdefault(
                        (cls, rank, op), {"s": 0.0, "seq": None, "big": 0.0}
                    )
                    c["s"] += secs
                    if secs > c["big"]:
                        c["big"] = secs
                        if cls == "comm" and my_stamps:
                            at = seg_t - off  # aligned segment start
                            before = [x for x in my_stamps if x[0] <= at]
                            c["seq"] = (before[-1] if before else my_stamps[0])[1]
                    seg_t += secs
        if not windows or total <= 0:
            continue
        ranked = sorted(contrib.items(), key=lambda kv: -kv[1]["s"])
        cls, rank, op = ranked[0][0]
        top = ranked[0][1]
        seq = top["seq"] if top["seq"] is not None else "-"
        share = top["s"] / total
        lines.append(
            f"CRITICAL-PATH kind={kind} rank={rank} op={op} seq={seq} "
            f"share={share:.3f}"
        )
        for (ccls, crank, cop), c in contrib.items():
            blame[(crank, cop)] = blame.get((crank, cop), 0.0) + c["s"]
        steps_out[kind] = {
            "windows": windows,
            "total_s": total,
            "contributors": [
                {"class": k[0], "rank": k[1], "op": k[2],
                 "s": v["s"], "seq": v["seq"], "share": v["s"] / total}
                for k, v in ranked
            ],
        }

    # ---- cross-rank collective gating --------------------------------- #
    coll_out: dict = {"charges": [], "total_s": 0.0}
    if len(stamps) >= 2:
        charges: Dict[int, dict] = {}

        def charge(rank: int, secs: float, op: str, seq: int) -> None:
            c = charges.setdefault(rank, {"s": 0.0, "op": op, "seq": seq, "big": 0.0})
            c["s"] += secs
            if secs > c["big"]:
                c.update(big=secs, op=op, seq=seq)

        all_seqs = set()
        for by in stamps.values():
            all_seqs |= set(by)
        for seq in sorted(all_seqs):
            parts = [
                (by[seq][0], r, by[seq][1]) for r, by in stamps.items() if seq in by
            ]
            if len(parts) < 2:
                continue
            parts.sort()
            gap = parts[-1][0] - parts[0][0]
            if gap > 0:
                charge(parts[-1][1], gap, parts[-1][2], seq)
        # short streams: a rank that stopped stamping while the world kept
        # going gated every later seq — charge it the span the world spent
        # without it, at its LAST stamp (the post-mortem convention).  The
        # wait is right-censored: nobody stamps while the world is wedged
        # on the straggler, so the observable lag only reaches the last
        # aligned record of ANY kind in ANY ring, not the teardown.
        global_last_seq = max(max(by) for by in stamps.values())
        global_last_t = max(max(t for t, _ in by.values()) for by in stamps.values())
        for rank, ring in bundle["rings"].items():
            off = offsets.get(rank, 0.0)
            for rec in ring.get("records", []):
                try:
                    global_last_t = max(global_last_t, float(rec["t"]) - off)
                except (KeyError, TypeError, ValueError):
                    continue
        short: Dict[int, int] = {}
        for rank, by in stamps.items():
            last_seq = max(by)
            if last_seq < global_last_seq:
                short[rank] = last_seq
                t_last, op_last = by[last_seq]
                charge(rank, max(global_last_t - t_last, 0.0), op_last, last_seq)
                # blame coordinates pin to the LAST stamp even when some
                # earlier rendezvous gap was the bigger single charge —
                # the (seq, op) the post-mortem names is where it wedged
                charges[rank].update(op=op_last, seq=last_seq)
        total = sum(c["s"] for c in charges.values())
        if total > 0:
            if short:
                # identification goes by stream lag, not charge magnitude:
                # however small the censored tail reads, the rank that
                # stopped stamping while the world kept going is
                # definitionally the rank the run ended waiting on — the
                # most-behind stream (ties: larger charge) is the verdict,
                # and it matches POSTMORTEM verdict=straggler by design
                worst_rank = min(
                    short, key=lambda r: (short[r], -charges[r]["s"])
                )
            else:
                worst_rank = max(charges, key=lambda r: charges[r]["s"])
            w = charges[worst_rank]
            lines.append(
                f"CRITICAL-PATH kind=collective rank={worst_rank} "
                f"op={w['op']} seq={w['seq']} share={w['s'] / total:.3f}"
            )
            for rank, c in charges.items():
                blame[(rank, c["op"])] = blame.get((rank, c["op"]), 0.0) + c["s"]
            coll_out = {
                "total_s": total,
                "charges": [
                    {"rank": r, "s": c["s"], "share": c["s"] / total,
                     "op": c["op"], "seq": c["seq"]}
                    for r, c in sorted(
                        charges.items(), key=lambda kv: -kv[1]["s"]
                    )
                ],
            }

    total_blame = sum(blame.values())
    by_rank: Dict[int, float] = {}
    by_op: Dict[str, float] = {}
    for (rank, op), secs in blame.items():
        by_rank[rank] = by_rank.get(rank, 0.0) + secs
        by_op[op] = by_op.get(op, 0.0) + secs
    return {
        "steps": steps_out,
        "collective": coll_out,
        "lines": lines,
        "blame": {
            "total_s": total_blame,
            "by_rank": {
                str(r): {"s": s, "share": (s / total_blame if total_blame else 0.0)}
                for r, s in sorted(by_rank.items(), key=lambda kv: -kv[1])
            },
            "by_op": {
                op: {"s": s, "share": (s / total_blame if total_blame else 0.0)}
                for op, s in sorted(by_op.items(), key=lambda kv: -kv[1])
            },
        },
    }


def _fmt_table(rows: List[List[str]], header: List[str]) -> str:
    widths = [max(len(str(r[i])) for r in [header] + rows) for i in range(len(header))]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*header), fmt.format(*("-" * w for w in widths))]
    lines += [fmt.format(*(str(c) for c in r)) for r in rows]
    return "\n".join(lines)


def clock_report(bundle: dict) -> str:
    """Greppable ``CLOCK-ALIGN`` lines: one per aligned rank (offset,
    worst anchor residual, anchor count) and one per NAMED unaligned
    rank."""
    align = bundle["align"]
    out = []
    for r in sorted(align.get("per_rank", {})):
        q = align["per_rank"][r]
        out.append(
            f"CLOCK-ALIGN rank={r} offset_ms={q['offset_s'] * 1e3:+.3f} "
            f"residual_ms={q['max_residual_s'] * 1e3:.3f} anchors={q['anchors']}"
        )
    for u in align.get("unaligned", []):
        out.append(f"CLOCK-ALIGN rank={u['rank']} UNALIGNED reason={u['reason']}")
    return "\n".join(out)


def critical_path_report(
    bundle: dict, step_names: Optional[Tuple[str, ...]] = None
) -> str:
    """CRITICAL-PATH lines + the per-rank / per-op blame tables; '' when
    the artifacts hold nothing attributable (no step spans AND fewer than
    two stamped rings)."""
    cp = critical_path(bundle, step_names)
    if not cp["lines"]:
        return ""
    out = ["-- critical path (aligned cross-rank attribution) --"]
    out.extend(cp["lines"])
    blame = cp["blame"]
    if blame["total_s"] > 0:
        out.append("-- blame: share of total critical time --")
        out.append(_fmt_table(
            [
                [r, f"{v['s'] * 1e3:.1f}", f"{v['share']:.3f}"]
                for r, v in blame["by_rank"].items()
            ],
            ["rank", "ms", "share"],
        ))
        out.append(_fmt_table(
            [
                [op, f"{v['s'] * 1e3:.1f}", f"{v['share']:.3f}"]
                for op, v in blame["by_op"].items()
            ],
            ["op", "ms", "share"],
        ))
    return "\n".join(out)
