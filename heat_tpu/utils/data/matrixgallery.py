"""Test-matrix gallery (reference: ``heat/utils/data/matrixgallery.py``)."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ...core import factories, types
from ...core.dndarray import DNDarray

__all__ = ["hermitian", "parter", "random_known_rank", "random_known_singularvalues"]


def parter(n: int, split=None, device=None, comm=None, dtype=types.float32) -> DNDarray:
    """The Parter matrix: A[i,j] = 1/(i−j+0.5) — a Cauchy matrix with
    singular values clustered at π (reference parity)."""
    i = jnp.arange(n, dtype=jnp.float32)
    a = 1.0 / (i[:, None] - i[None, :] + 0.5)
    return factories.array(a, split=split, device=device, comm=comm, dtype=dtype)


def hermitian(n: int, split=None, device=None, comm=None, dtype=types.complex64,
              positive_definite: bool = False, random_state: int = 0) -> DNDarray:
    """Random (complex) Hermitian n×n matrix; optionally positive definite."""
    key = jax.random.key(random_state)
    k1, k2 = jax.random.split(key)
    dt = types.canonical_heat_type(dtype)
    if types.heat_type_is_complexfloating(dt):
        a = jax.random.normal(k1, (n, n)) + 1j * jax.random.normal(k2, (n, n))
    else:
        a = jax.random.normal(k1, (n, n))
    if positive_definite:
        h = a @ jnp.conj(a.T) + n * jnp.eye(n, dtype=a.dtype)
    else:
        h = 0.5 * (a + jnp.conj(a.T))
    return factories.array(h.astype(dt.jax_dtype()), split=split, device=device, comm=comm)


def random_known_singularvalues(
    m: int, n: int, singular_values, split=None, device=None, comm=None,
    dtype=types.float32, random_state: int = 1
) -> Tuple[DNDarray, Tuple]:
    """Random matrix with prescribed singular values (returns (A, (U, s, V)))."""
    sv = jnp.asarray(singular_values, dtype=jnp.float32)
    k = sv.shape[0]
    key = jax.random.key(random_state)
    k1, k2 = jax.random.split(key)
    u, _ = jnp.linalg.qr(jax.random.normal(k1, (m, k)))
    v, _ = jnp.linalg.qr(jax.random.normal(k2, (n, k)))
    a = (u * sv[None, :]) @ v.T
    A = factories.array(a, split=split, device=device, comm=comm, dtype=dtype)
    return A, (factories.array(u), factories.array(sv), factories.array(v))


def random_known_rank(
    m: int, n: int, r: int, split=None, device=None, comm=None, dtype=types.float32
) -> Tuple[DNDarray, Tuple]:
    """Random matrix of known rank r (uniform-decaying singular values)."""
    sv = jnp.linspace(1.0, 0.1, r)
    return random_known_singularvalues(m, n, sv, split=split, device=device, comm=comm, dtype=dtype)
