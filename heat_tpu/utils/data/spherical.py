"""Synthetic clustered data (reference: ``heat/utils/data/spherical.py``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import factories, types
from ...core.dndarray import DNDarray
from ...core import axisspec

__all__ = ["create_spherical_dataset", "create_clusters"]


def create_spherical_dataset(
    num_samples_cluster: int,
    radius: float = 1.0,
    offset: float = 4.0,
    dtype=types.float32,
    random_state: int = 1,
) -> DNDarray:
    """Four Gaussian blobs on a diagonal (the reference's KMeans test set)."""
    key = jax.random.key(random_state)
    keys = jax.random.split(key, 4)
    blobs = []
    for i, k in enumerate(keys):
        center = (i - 1.5) * offset
        pts = jax.random.normal(k, (num_samples_cluster, 3)) * radius + center
        blobs.append(pts)
    data = jnp.concatenate(blobs, axis=0).astype(types.canonical_heat_type(dtype).jax_dtype())
    return factories.array(data, split=axisspec.named(0))


def create_clusters(
    n_samples: int,
    n_features: int,
    n_clusters: int,
    cluster_mean,
    cluster_std=1.0,
    cluster_weight=None,
    device=None,
    random_state: int = 42,
) -> DNDarray:
    """Gaussian blobs with the given per-cluster means/stds (reference API)."""
    key = jax.random.key(random_state)
    means = jnp.asarray(cluster_mean, dtype=jnp.float32)
    if means.shape[0] != n_clusters:
        raise ValueError("cluster_mean must have n_clusters rows")
    if cluster_weight is None:
        counts = [n_samples // n_clusters] * n_clusters
        counts[-1] += n_samples - sum(counts)
    else:
        counts = [int(w * n_samples) for w in cluster_weight]
        counts[-1] += n_samples - sum(counts)
    stds = jnp.broadcast_to(jnp.asarray(cluster_std, dtype=jnp.float32), (n_clusters,))
    parts = []
    for i in range(n_clusters):
        key, sub = jax.random.split(key)
        parts.append(jax.random.normal(sub, (counts[i], n_features)) * stds[i] + means[i])
    data = jnp.concatenate(parts, axis=0)
    return factories.array(data, split=axisspec.named(0), device=device)
