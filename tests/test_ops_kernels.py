"""Pallas kernel tests (interpret mode on the CPU mesh)."""

import numpy as np

import heat_tpu as ht


class TestFusedAssign:
    def test_matches_oracle(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(1000, 32)).astype(np.float32))
        c = jnp.asarray(rng.normal(size=(16, 32)).astype(np.float32))
        lab, d2 = ht.ops.fused_assign(x, c)
        D = ((np.asarray(x)[:, None, :] - np.asarray(c)[None, :, :]) ** 2).sum(-1)
        np.testing.assert_array_equal(np.asarray(lab), D.argmin(1))
        np.testing.assert_allclose(np.asarray(d2), D.min(1), atol=1e-2)

    def test_ragged_rows(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(1)
        # row count not divisible by the kernel tile → padding path
        x = jnp.asarray(rng.normal(size=(1537, 8)).astype(np.float32))
        c = jnp.asarray(rng.normal(size=(5, 8)).astype(np.float32))
        lab, d2 = ht.ops.fused_assign(x, c)
        assert lab.shape == (1537,)
        D = ((np.asarray(x)[:, None, :] - np.asarray(c)[None, :, :]) ** 2).sum(-1)
        np.testing.assert_array_equal(np.asarray(lab), D.argmin(1))
