"""Dataset/DataLoader (reference: ``heat/utils/data/datatools.py``).

The reference wraps DNDarrays for per-rank batch iteration with a per-epoch
global shuffle exchanging samples across ranks via Alltoall (SURVEY §2.5).
Here a Dataset holds sharded global arrays; the shuffle is one device-side
permutation gather (XLA emits the all-to-all), and ``ishuffle`` exploits
JAX's async dispatch to overlap the next epoch's shuffle with training.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax

from ...core import random as ht_random
from ...core.dndarray import DNDarray

__all__ = ["Dataset", "DataLoader", "dataset_shuffle", "dataset_ishuffle"]


class Dataset:
    """Holds one or more global arrays aligned on the sample axis."""

    def __init__(self, array: Union[DNDarray, Sequence[DNDarray]], labels: Optional[DNDarray] = None,
                 ishuffle: bool = False, test_set: bool = False):
        arrays = [array] if isinstance(array, DNDarray) else list(array)
        if labels is not None:
            arrays.append(labels)
        n = arrays[0].shape[0]
        for a in arrays[1:]:
            if a.shape[0] != n:
                raise ValueError("all arrays must share the sample axis length")
        self.arrays = arrays
        self.has_labels = labels is not None
        self.ishuffle = ishuffle
        self.test_set = test_set
        self._pending = None  # async-dispatched shuffled arrays

    def __len__(self) -> int:
        return self.arrays[0].shape[0]

    def __getitem__(self, idx):
        items = [a[idx] for a in self.arrays]
        return items[0] if len(items) == 1 else tuple(items)

    def shuffle(self, seed: Optional[int] = None):
        """Global permutation of the sample axis (reference: Alltoall exchange).

        The default seed comes from the broadcast RNG state
        (``ht_random.derive_seed()``), never process entropy: every SPMD
        rank must derive the IDENTICAL permutation or the shuffle silently
        desynchronizes the sample axis across ranks."""
        key = jax.random.key(seed if seed is not None else ht_random.derive_seed())
        n = len(self)
        perm = jax.random.permutation(key, n)
        new = []
        for a in self.arrays:
            g = a._jarray[perm]
            g = a.comm.shard(g, a.split)
            new.append(DNDarray(g, a.gshape, a.dtype, a.split, a.device, a.comm, True))
        self.arrays = new

    def ishuffle_start(self, seed: Optional[int] = None):
        """Dispatch next epoch's shuffle asynchronously (JAX async dispatch);
        the default seed is broadcast-derived like :meth:`shuffle`."""
        key = jax.random.key(seed if seed is not None else ht_random.derive_seed())
        perm = jax.random.permutation(key, len(self))
        self._pending = [a._jarray[perm] for a in self.arrays]

    def ishuffle_finish(self):
        if self._pending is None:
            return
        new = []
        for a, g in zip(self.arrays, self._pending):
            g = a.comm.shard(g, a.split)
            new.append(DNDarray(g, a.gshape, a.dtype, a.split, a.device, a.comm, True))
        self.arrays = new
        self._pending = None


def dataset_shuffle(dataset: Dataset, attrs=None) -> None:
    """Reference free-function API."""
    dataset.shuffle()


def dataset_ishuffle(dataset: Dataset, attrs=None) -> None:
    dataset.ishuffle_start()


class DataLoader:
    """Iterate global batches of a Dataset/DNDarray.

    Batches are slices along the (sharded) sample axis; with ``shuffle=True``
    the dataset is globally re-permuted each epoch (``ishuffle`` overlaps it
    with the tail of the previous epoch).
    """

    def __init__(self, dataset=None, batch_size: int = 1, shuffle: bool = False,
                 drop_last: bool = False, ishuffle: bool = False, lcl_dataset=None):
        if dataset is None:
            dataset = lcl_dataset
        if isinstance(dataset, DNDarray):
            dataset = Dataset(dataset)
        if dataset is None:
            raise ValueError("DataLoader requires a dataset")
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        # the dataset's own ishuffle flag turns on async shuffle too
        # (reference usage: MNISTDataset(ishuffle=True) + DataLoader(shuffle=True))
        self.ishuffle = ishuffle or getattr(dataset, "ishuffle", False)
        self.drop_last = drop_last
        self._epoch = 0

    def __len__(self) -> int:
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def __iter__(self):
        if self.shuffle:
            if self.ishuffle and self.dataset._pending is not None:
                self.dataset.ishuffle_finish()
            else:
                self.dataset.shuffle(seed=self._epoch)
        n = len(self.dataset)
        nb = len(self)
        for b in range(nb):
            lo = b * self.batch_size
            hi = min(lo + self.batch_size, n)
            if self.ishuffle and self.shuffle and b == nb - 1:
                # overlap next epoch's shuffle with the last batch
                self.dataset.ishuffle_start(seed=self._epoch + 1)
            yield self.dataset[lo:hi]
        self._epoch += 1
