"""Version information for heat_tpu."""

major: int = 0
minor: int = 1
micro: int = 0
extension: str = None

if not extension:
    __version__ = f"{major}.{minor}.{micro}"
else:
    __version__ = f"{major}.{minor}.{micro}-{extension}"
