"""KMedoids (reference: ``heat/cluster/kmedoids.py``).

The reference's variant: compute the coordinate-wise median of each cluster,
then snap to the nearest actual data point (keeps medoids ∈ X without the
O(n²) pairwise search).
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp

from ._kcluster import _KCluster
from .kmedians import _masked_median

__all__ = ["KMedoids"]


class KMedoids(_KCluster):
    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, object] = "random",
        max_iter: int = 300,
        random_state: Optional[int] = None,
    ):
        super().__init__(
            metric=lambda x, y: None, n_clusters=n_clusters, init=init,
            max_iter=max_iter, tol=0.0, random_state=random_state,
        )

    @staticmethod
    def _update(jx, labels, centers):
        k = centers.shape[0]

        def one(c):
            m = labels == c
            med = _masked_median(jx, m)
            med = jnp.where(jnp.any(m), med, centers[c])
            # snap to nearest member of the cluster (inf distance outside it)
            d2 = jnp.sum((jx - med[None, :]) ** 2, axis=1)
            d2 = jnp.where(m, d2, jnp.inf)
            idx = jnp.argmin(d2)
            return jnp.where(jnp.any(m), jx[idx], centers[c])

        return jax.vmap(one)(jnp.arange(k))

    def fit(self, x):
        # medoids move discretely; tol-based stop would trigger immediately on
        # a repeated medoid, which is exactly the convergence criterion
        self.tol = 1e-12
        return super().fit(x)
