"""Profiling shim (SURVEY §5.1).

The reference has no built-in tracer (external perun only).  On TPU we get a
first-class story: this wraps ``jax.profiler`` so benchmarks are one-liner
instrumented, plus a wall-clock timer that forces completion (the tunneled
platform's ``block_until_ready`` can be a no-op, so timers fetch a scalar).
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax
import numpy as np

from ..core._cache import cache_stats, reset_cache_stats

__all__ = [
    "trace",
    "timer",
    "sync",
    "annotate",
    "timeit_min",
    "cache_stats",
    "reset_cache_stats",
    "cache_hit_rate",
]


def cache_hit_rate() -> float:
    """Hit rate of the sharding-keyed program caches since the last
    ``reset_cache_stats()`` — 1.0 means every dispatched op reused a
    compiled executable (zero recompilation)."""
    s = cache_stats()
    total = s["hits"] + s["misses"]
    return s["hits"] / total if total else 1.0


def timeit_min(fn, reps: int = 3) -> float:
    """Best-of-``reps`` wall-clock seconds of ``fn()``, forcing completion of
    its result (the benchmark harness's shared timing methodology)."""
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return best


def sync(x=None) -> None:
    """Force device completion (fetch-based; tunnel-safe)."""
    if x is None:
        return
    arr = getattr(x, "_jarray", x)
    try:
        np.asarray(jax.device_get(arr.ravel()[:1] if hasattr(arr, "ravel") else arr))
    except Exception:
        jax.block_until_ready(arr)


@contextlib.contextmanager
def timer(label: str = "", result_holder: Optional[dict] = None, sync_on=None):
    """Wall-clock a block; forces completion of ``sync_on`` before stopping."""
    t0 = time.perf_counter()
    yield
    sync(sync_on)
    dt = time.perf_counter() - t0
    if result_holder is not None:
        result_holder[label or "elapsed"] = dt


@contextlib.contextmanager
def trace(logdir: str = "/tmp/heat_tpu_trace"):
    """XProf/TensorBoard trace of the block (``jax.profiler.trace``)."""
    with jax.profiler.trace(logdir):
        yield


annotate = jax.profiler.TraceAnnotation
