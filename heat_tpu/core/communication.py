"""Communication backend: XLA collectives over an ICI/DCN device mesh.

This is the TPU-native re-design of the reference's
``heat/core/communication.py::MPICommunication`` (SURVEY §2.1, §5.8).  The
reference wraps ``mpi4py``: every rank owns a local torch tensor and ships
bytes explicitly (derived datatypes, CUDA-aware fast paths, request objects).
Here the roles invert — arrays are globally-shaped ``jax.Array``s sharded over
a :class:`jax.sharding.Mesh`, and *implicit* collectives are emitted by XLA's
SPMD partitioner whenever a computation needs them.  What remains for an
explicit ``Communication`` object:

- **shard math** (``chunk``, ``counts_displs_shape``) for I/O boundaries and
  test oracles, matching JAX's ceil-division placement convention;
- **sharding constructors** (``sharding(ndim, split)``) translating the
  reference's ``split`` axis to a ``NamedSharding``;
- **redistribution** (``resplit`` → ``jax.device_put`` with a new sharding,
  lowered by XLA to all-to-all, cf. arXiv 2112.01075);
- **functional collectives** (``psum``/``all_gather``/``all_to_all``/
  ``ppermute``/…) for use inside ``shard_map`` — the building blocks of the
  manual-control paths (ring cdist, halo convolve, TSQR, DASO);
- process-level helpers for the multi-host control plane.

MPI-name parity table (reference → here):
``Allreduce→psum``, ``Allgather(v)→all_gather``, ``Alltoall(v)→all_to_all``,
``Bcast→select-from-source ppermute``, ``Isend/Irecv→ppermute`` (XLA
collectives are asynchronously dispatched, so every op is effectively the
nonblocking variant; ``jax.block_until_ready`` is ``Wait``), ``Exscan→
associative_scan over shards``.
"""

from __future__ import annotations

import warnings
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from . import devices

__all__ = [
    "Communication",
    "sanitize_comm",
    "get_comm",
    "use_comm",
    "world",
]

# telemetry is imported lazily (core modules load before utils) and cached;
# every call below is at collective STAGING time or inside resplit — never
# the per-op dispatch hot path
_TELEMETRY_MOD = None

# health (deadline watchdog) and faults are lazily cached the same way:
# used at collective staging and around the blocking waits, never in the
# dispatch hot path
_HEALTH_MOD = None

# runtime sanitizer hook (HEAT_TPU_CHECKS=1): ``core.sanitation.
# enable_checks()`` points this at ``sanitation.check_placement`` so every
# eager resplit verifies the produced array actually carries the canonical
# sharding of its target split (metadata-only: sharding objects, no value
# reads).  Disabled cost: one module-global load per resplit.  This module
# currently loads before sanitation (sanitation → dndarray → here), so the
# env-arming poke lands after this line runs — but that ordering is
# transitive and fragile, so the module bottom re-arms defensively like
# ``_operations`` does.
_RESPLIT_CHECK = None

# flight-recorder hook (``utils.flightrec.enable()`` pokes the module in,
# ``disable()`` clears it): every staged collective is seq-stamped at the
# ``_account_bytes`` choke point below.  Disabled cost: one module-global
# load at staging time.  Module bottom re-arms against import-order races
# exactly like the two hooks above.
_FLIGHTREC = None

# device-memory-ledger hook (``utils.memledger.enable()`` pokes the module
# in): resplit outputs are registration choke points, the ``mem.alloc``
# fault site fires ahead of each transfer's allocation, donated sources
# are consumed, and a RESOURCE_EXHAUSTED out of the transfer renders the
# ledger dump into the flight ring before re-raising.  Disabled cost: one
# module-global load per resplit.  Module bottom re-arms.
_MEMLEDGER = None


def _telemetry():
    global _TELEMETRY_MOD
    if _TELEMETRY_MOD is None:
        from ..utils import telemetry

        _TELEMETRY_MOD = telemetry
    return _TELEMETRY_MOD


def _health():
    global _HEALTH_MOD
    if _HEALTH_MOD is None:
        from ..utils import health

        _HEALTH_MOD = health
    return _HEALTH_MOD


def _payload_nbytes(x) -> int:
    """nbytes of an array OR a tracer (shape/dtype live on the aval, so the
    collective wrappers can account bytes while being traced)."""
    try:
        n = 1
        for s in x.shape:
            n *= int(s)
        return n * np.dtype(x.dtype).itemsize
    except Exception:
        return 0


def _array_from_callback(host: "np.ndarray", sh: NamedSharding) -> jax.Array:
    """Global array from host data, one slice per addressable device.

    The explicit dtype matters on sub-meshes that leave this process with
    ZERO addressable shards (inference has no data there), but the kwarg is
    newer than some supported jax versions — fall back to inference, which
    is correct whenever at least one shard is local."""
    try:
        return jax.make_array_from_callback(
            host.shape, sh, lambda idx: host[idx], dtype=host.dtype
        )
    except TypeError:
        return jax.make_array_from_callback(host.shape, sh, lambda idx: host[idx])


def _jax_shard_map(fn, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` across jax versions: the public entry point (with
    ``check_vma``) when present, else the pre-0.5 experimental one (where
    the same knob is named ``check_rep``)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma)


class Communication:
    """A communicator: a device mesh axis over which arrays are sharded.

    The analogue of the reference's ``MPICommunication``.  ``size`` is the
    number of shards along the communicator's mesh axis (the reference's
    ``comm.size``); ``rank`` is the *process* index, which on a single
    controller addressing all chips is 0 — per-shard identity only exists
    inside ``shard_map`` (use :meth:`axis_index`).
    """

    def __init__(self, mesh: Optional[Mesh] = None, axis: str = "x"):
        if mesh is None:
            mesh = devices.get_default_mesh()
        if axis not in mesh.axis_names:
            raise ValueError(f"axis {axis!r} not in mesh axes {mesh.axis_names}")
        self.__mesh = mesh
        self.__axis = axis

    # ------------------------------------------------------------------ #
    # identity / topology
    # ------------------------------------------------------------------ #
    @property
    def mesh(self) -> Mesh:
        return self.__mesh

    @property
    def axis(self) -> str:
        return self.__axis

    @property
    def size(self) -> int:
        """Number of shards along this communicator's axis (= reference nprocs)."""
        return self.__mesh.shape[self.__axis]

    @property
    def rank(self) -> int:
        """The PROCESS index — NOT a shard index.

        Single-controller JAX addresses all chips from one process, so this
        is 0 everywhere today; under multi-process JAX it is the host index
        (0..n_processes-1), NOT 0..size-1.  Code needing per-shard identity
        must use :meth:`axis_index` inside ``shard_map`` — reference code
        that branches on ``comm.rank`` for data placement should consult
        ``chunk()``/``lshape_map`` instead.
        """
        return jax.process_index()

    @property
    def n_processes(self) -> int:
        return jax.process_count()

    def is_distributed(self) -> bool:
        return self.size > 1

    def axis_index(self):
        """Shard index along this communicator's axis — ONLY inside shard_map."""
        return lax.axis_index(self.__axis)

    def __eq__(self, other) -> bool:
        if not isinstance(other, Communication):
            return NotImplemented
        return self.__mesh == other.mesh and self.__axis == other.axis

    def __hash__(self) -> int:
        return hash((self.__mesh, self.__axis))

    def __repr__(self) -> str:
        return f"Communication(size={self.size}, axis={self.__axis!r}, mesh={tuple(self.__mesh.shape.items())})"

    # ------------------------------------------------------------------ #
    # shard math — matches JAX's ceil-division placement so that
    # `chunk()` predictions agree with jax.Array.addressable_shards.
    # (Deviation from the reference, which gives the first gshape%size
    # ranks one extra row; documented in SURVEY §7 "Hard parts" #1.)
    # ------------------------------------------------------------------ #
    def chunk(
        self, shape, split: Optional[int], rank: Optional[int] = None
    ) -> Tuple[int, Tuple[int, ...], Tuple[slice, ...]]:
        """Offset, local shape and slices of shard ``rank`` of a global ``shape``.

        cf. reference ``MPICommunication.chunk`` — pure shard math, no comm.
        """
        shape = tuple(int(s) for s in shape)
        if split is None:
            return 0, shape, tuple(slice(0, s) for s in shape)
        split = split % len(shape)
        if rank is None:
            rank = 0
        n, p = shape[split], self.size
        c = -(-n // p)  # ceil division, JAX/GSPMD convention
        start = min(rank * c, n)
        end = min(start + c, n)
        lshape = shape[:split] + (end - start,) + shape[split + 1 :]
        slices = tuple(
            slice(start, end) if i == split else slice(0, s) for i, s in enumerate(shape)
        )
        return start, lshape, slices

    def padded_extent(self, n: int) -> int:
        """Smallest multiple of ``size`` ≥ ``ceil(n/size)*size`` — the physical
        extent of a ragged axis under pad-and-mask sharding (SURVEY §7 hard
        part #1)."""
        c = -(-int(n) // self.size)
        return c * self.size

    def counts_displs_shape(self, shape, split: int):
        """Per-shard counts and displacements along ``split`` (I/O hyperslabs)."""
        counts, displs = [], []
        for r in range(self.size):
            off, lsh, _ = self.chunk(shape, split, r)
            counts.append(lsh[split])
            displs.append(off)
        return tuple(counts), tuple(displs)

    def lshape_map(self, shape, split: Optional[int]) -> np.ndarray:
        """(size, ndim) array of every shard's local shape (reference: DNDarray.lshape_map)."""
        out = np.empty((self.size, len(shape)), dtype=np.int64)
        for r in range(self.size):
            _, lsh, _ = self.chunk(shape, split, r)
            out[r] = lsh
        return out

    # ------------------------------------------------------------------ #
    # shardings
    # ------------------------------------------------------------------ #
    def spec(self, ndim: int, split: Optional[int]) -> PartitionSpec:
        if split is None:
            return PartitionSpec()
        split = split % ndim if ndim else 0
        return PartitionSpec(*(self.__axis if i == split else None for i in range(ndim)))

    def sharding(self, ndim: int, split: Optional[int]) -> NamedSharding:
        """The ``NamedSharding`` realizing ``split`` over this communicator.

        Memoized per ``(ndim, split)`` on the instance: the dispatch layer
        asks for the canonical sharding on EVERY op, and returning the same
        object each time makes the placement-equality checks in
        ``DNDarray._enforce_placement``/``shard`` an identity comparison
        instead of a structural one.
        """
        cache = self.__dict__.setdefault("_sharding_cache", {})
        key = (ndim, split)
        sh = cache.get(key)
        if sh is None:
            sh = cache[key] = NamedSharding(self.__mesh, self.spec(ndim, split))
        return sh

    @staticmethod
    def host_fetch(array) -> "np.ndarray":
        """Fetch a (possibly multi-process) jax array to host memory.

        Single-controller arrays are fully addressable and ``device_get``
        suffices; under multi-process JAX a sharded array's remote shards
        are NOT addressable, so the fetch is an SPMD ``process_allgather``
        (every process must call this together — the same contract the
        reference's gather-to-all has).  Fully-replicated arrays read their
        local replica directly — no collective, so ``if rank == 0: print(x)``
        on replicated data stays legal — PROVIDED this process holds a
        replica: an array on a sub-mesh of purely remote devices is
        "replicated" yet unreadable locally, and must allgather (found by
        the -m mp lane's sub-mesh sweep).

        Fault site ``comm.host_fetch``: transient injected faults are
        retried with short backoff (every process fires the site the same
        number of times — fault countdowns are process-local and the call
        pattern is SPMD, so retries stay collective-aligned).

        Deadline-guarded: under an armed ``comm.deadline(...)`` a fetch
        whose peers never show up (the collective ``process_allgather``
        against a dead rank) raises ``CollectiveTimeoutError`` instead of
        blocking forever — this is the real-world hang point of a dead
        peer, not the staged collectives."""
        from ..utils import faults as _flt  # lazy: core imports before utils
        from ..utils import health as _hlth

        def _fetch():
            _flt.fire("comm.host_fetch")
            if getattr(array, "is_fully_addressable", True) or (
                getattr(array, "is_fully_replicated", False)
                and len(array.addressable_shards) > 0
            ):
                return np.asarray(jax.device_get(array))
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(array, tiled=True))

        return _hlth.guard_blocking(
            lambda: _flt.call_with_retries(
                _fetch, "comm.host_fetch", retries=3, base_delay=0.02, max_delay=0.5,
                retry_on=(_flt.TransientFault,),
            ),
            "comm.host_fetch",
        )

    @staticmethod
    def host_fetch_all(arrays) -> "list":
        """Batched :meth:`host_fetch` of many (possibly non-addressable)
        arrays in ONE collective: ``process_allgather`` accepts a pytree,
        so a checkpoint of a model with hundreds of cross-process leaves
        costs one round-trip, not one per leaf.  Same contract as
        ``host_fetch``: collective (every process calls together), fault
        site ``comm.host_fetch``, retried, deadline-guarded."""
        from ..utils import faults as _flt
        from ..utils import health as _hlth

        arrays = list(arrays)
        if not arrays:
            return []

        def _fetch():
            _flt.fire("comm.host_fetch")
            if all(getattr(a, "is_fully_addressable", True) for a in arrays):
                return [np.asarray(a) for a in jax.device_get(arrays)]
            from jax.experimental import multihost_utils

            out = multihost_utils.process_allgather(arrays, tiled=True)
            return [np.asarray(o) for o in out]

        return _hlth.guard_blocking(
            lambda: _flt.call_with_retries(
                _fetch, "comm.host_fetch", retries=3, base_delay=0.02, max_delay=0.5,
                retry_on=(_flt.TransientFault,),
            ),
            "comm.host_fetch",
        )

    def shard(self, array: jax.Array, split: Optional[int]) -> jax.Array:
        """Place/constrain ``array`` to the sharding of ``split``.

        Eager: ``device_put`` (no-op if already so sharded).  Traced (inside
        jit): ``with_sharding_constraint``.

        JAX requires the sharded dimension to be divisible by the mesh axis
        size; for ragged shapes the physical placement is left to XLA's
        computation-follows-data propagation and ``split`` remains *logical*
        metadata (SURVEY §7, hard part #1 — padding-free best-effort design).
        """
        from ._complexsafe import guard

        hosted = guard(array)
        if hosted is not None:
            return hosted  # complex on a transport without native complex
        if split is not None:
            split = split % array.ndim if array.ndim else None
        if split is not None and (
            array.ndim == 0 or array.shape[split] % self.size != 0
        ):
            return array  # ragged: keep XLA's placement, split stays logical
        sh = self.sharding(array.ndim, split)
        if isinstance(array, jax.core.Tracer):
            return lax.with_sharding_constraint(array, sh)
        if getattr(array, "sharding", None) == sh:
            return array
        if self.n_processes > 1 and getattr(array, "is_fully_addressable", True):
            # multi-process device_put runs multihost assert_equal, whose
            # np.equal makes NaN != NaN — identical NaN-bearing inputs would
            # spuriously fail.  Inputs are SPMD-identical by contract, so
            # build the global array from per-device slices instead (found
            # by the -m mp lane: nansum's ht.array([1, nan, 3]))
            host = np.asarray(array)
            return _array_from_callback(host, sh)
        return jax.device_put(array, sh)

    def pad_shard(self, array: jax.Array, split: int) -> jax.Array:
        """Zero-pad ``array`` along ``split`` to a mesh-divisible extent and
        physically place it on this communicator's sharding.

        This is the ragged-shape ingest path (pad-and-mask, SURVEY §7 hard
        part #1): JAX's ``NamedSharding`` requires the sharded dimension to be
        divisible by the mesh axis size, so non-divisible ("ragged") axes are
        padded to ``ceil(n/p)*p`` with zeros.  The logical extent is carried by
        ``DNDarray.gshape``; the pad region is dead data masked at reduction
        boundaries.  Returns the padded, sharded physical array.
        """
        from ._complexsafe import guard

        hosted = guard(array)
        if hosted is not None:
            # complex on a transport without native complex: stays host-side,
            # pad for shape consistency but skip device placement
            n = hosted.shape[split]
            pad = self.padded_extent(n) - n
            if pad:
                widths = [(0, pad if i == split else 0) for i in range(hosted.ndim)]
                hosted = jnp.pad(hosted, widths)
            return hosted
        split = split % array.ndim
        n = array.shape[split]
        pad = self.padded_extent(n) - n
        if pad:
            widths = [(0, pad if i == split else 0) for i in range(array.ndim)]
            array = jnp.pad(array, widths)
        sh = self.sharding(array.ndim, split)
        if isinstance(array, jax.core.Tracer):
            try:
                return lax.with_sharding_constraint(array, sh)
            except Exception:
                return array  # inside a transform where constraints don't apply
        if getattr(array, "sharding", None) == sh:
            return array
        if self.n_processes > 1 and getattr(array, "is_fully_addressable", True):
            # same NaN-vs-assert_equal hazard as shard() (see there)
            host = np.asarray(array)
            return _array_from_callback(host, sh)
        return jax.device_put(array, sh)

    def split_of(self, array: jax.Array) -> Optional[int]:
        """Infer the split axis from a concrete array's sharding (None if replicated)."""
        sh = getattr(array, "sharding", None)
        if not isinstance(sh, NamedSharding):
            return None
        for i, p in enumerate(sh.spec):
            names = p if isinstance(p, tuple) else (p,)
            if self.__axis in [n for n in names if n]:
                return i
        return None

    # ------------------------------------------------------------------ #
    # redistribution — the reference's Alltoallv-based resplit_
    # ------------------------------------------------------------------ #
    def resplit(
        self,
        array: jax.Array,
        split: Optional[int],
        donate: bool = False,
        memory_budget: Optional[int] = None,
    ) -> jax.Array:
        """Redistribute a global array to a new split axis.

        XLA lowers the sharding change to an all-to-all over ICI (the
        memory-efficient reshard of arXiv 2112.01075); the reference does the
        same thing by hand with derived datatypes + ``Alltoallv``
        (``DNDarray.resplit_``, SURVEY §3.3).

        ``memory_budget`` (bytes; ``None`` → the process default set via
        ``heat_tpu.set_redistribution_budget()`` / ``HEAT_TPU_RESPLIT_BUDGET``)
        bounds the bytes moved per step: when the transition is tileable and
        the array exceeds the budget, the transfer runs as the chunked
        pipeline of ``core.redistribution`` — K tiled all-to-alls along a
        non-split axis, each ≤ budget bytes, destination written in place,
        transient memory ≤ budget + one tile beyond source + destination.
        K=1 (or no budget) degenerates to the monolithic fast path below.

        ``donate=True`` (the in-place ``resplit_`` path) hands the source
        buffer to the transfer (``jax.device_put(..., donate=True)``): the
        runtime may alias input and output storage (layout permitting) and
        can free the source as soon as the all-to-all has consumed it, so
        peak memory stays at ~one copy instead of two.  The caller must not
        use ``array`` afterwards.  Donation falls back to the plain path
        for tracers, hosted-complex arrays, ragged extents and
        multi-process meshes (where placement goes through host assembly
        anyway) — counted under ``comm.resplit.donate_fallbacks`` when the
        running jax lacks the ``donate`` kwarg, so a peak-memory regression
        is attributable to the silently-lost donation.

        Telemetry: every resharding call counts under
        ``comm.resplit.calls``/``.bytes`` (the all-to-all moves (p-1)/p of
        the GLOBAL payload — the known hot spot of redistribution traffic;
        a chunked transfer accounts per tile, summing to the identical
        total), plus ``comm.resplit.tiles``/``.peak_tile_bytes`` for the
        plan shape, and the eager transfer runs under a ``comm.resplit``
        span when telemetry is enabled.  A no-op call (the array already
        carries the target sharding) moves nothing and is NOT counted —
        defensive resplit calls must not inflate the traffic metric.
        """
        if self._already_placed(array, split):
            return array
        from . import redistribution as _redist

        plan = _redist.make_plan(self, array, split, memory_budget)
        if plan is not None and plan.n_tiles > 1:
            return self.resplit_tiled(array, split, donate=donate, _plan=plan)
        self._account(
            "resplit",
            array,
            (self.size - 1) / self.size,
            src_split=self.split_of(array) if not isinstance(array, jax.core.Tracer) else None,
            dst_split=split,
        )
        tel = _telemetry()
        tel.counter_inc("comm.resplit.tiles", 1)
        nbytes = _payload_nbytes(array)
        with tel.span(
            "comm.resplit",
            split=split,
            donate=donate,
            nbytes=nbytes,
            tiles=1,
        ):
            ml = _MEMLEDGER
            src_cat = ml.category_of(array) if ml is not None else None
            try:
                if ml is not None:
                    # the mem.alloc fault site: chaos CI injects a
                    # deterministic allocation failure ahead of the transfer
                    ml.alloc_check(nbytes, "comm.resplit")
                if donate and self._donatable(array, split):
                    # no already-placed test here: _already_placed() at the
                    # top returned for every case a donatable array could hit
                    sh = self.sharding(array.ndim, split)
                    donated = False
                    try:
                        out = jax.device_put(array, sh, donate=True)
                        donated = True
                    except TypeError:  # jax without the donate kwarg
                        self._note_donate_fallback()
                        out = jax.device_put(array, sh)
                    if ml is not None and donated:
                        # consumed only AFTER a successful donating transfer:
                        # a RESOURCE_EXHAUSTED out of the device_put must
                        # still find the in-flight source in the OOM dump
                        # (it is typically the dominant buffer), and the
                        # donate-less ancient-jax fallback keeps the source
                        # alive for real.  Metadata-only id lookup, not a
                        # buffer read.
                        ml.consume(array)  # heatlint: disable=HT103 — ledger id-lookup decrement, no storage read
                else:
                    out = self.shard(array, split)
            except Exception as e:
                if ml is not None:
                    ml.note_oom(e, "comm.resplit", nbytes)
                raise
            if ml is not None:
                # the output inherits the source's category (a resplit moves
                # a buffer, it does not change what the buffer IS)
                ml.register(out, op="resplit", site="resplit", category=src_cat)
            if _RESPLIT_CHECK is not None:
                _RESPLIT_CHECK(out, self, split, where="comm.resplit")
            return out

    def resplit_tiled(
        self,
        array: jax.Array,
        split: Optional[int],
        memory_budget: Optional[int] = None,
        donate: bool = False,
        _plan=None,
    ) -> jax.Array:
        """Explicit tiled-redistribution entry: stream ``array`` to ``split``
        in budget-bounded tiles (``core.redistribution.execute_plan``).

        ``resplit`` routes here whenever a budget yields K>1; calling it
        directly forces the planner with ``memory_budget`` and degenerates
        to :meth:`resplit` when the transition is not tileable.  Byte
        accounting happens PER TILE at the executor's staging points (one
        ``_account_bytes`` per tile — telescoped so the ``comm.resplit.bytes``
        total is identical to the monolithic path's), which also gives every
        tile the ``comm.collective`` fault site and ``comm.deadline``
        refusal/watchdog semantics — a hung tile trips the deadline instead
        of wedging the plan."""
        from . import redistribution as _redist

        plan = _plan
        if plan is None:
            if self._already_placed(array, split):
                return array
            plan = _redist.make_plan(self, array, split, memory_budget)
        if plan is None or plan.n_tiles <= 1:
            return self.resplit(array, split, donate=donate, memory_budget=0)
        tel = _telemetry()
        nbytes = _payload_nbytes(array)
        with tel.span(
            "comm.resplit",
            split=split,
            donate=donate,
            nbytes=nbytes,
            tiles=plan.n_tiles,
            tile_axis=plan.tile_axis,
            budget=plan.budget,
        ):
            ml = _MEMLEDGER
            src_cat = ml.category_of(array) if ml is not None else None
            try:
                out = _redist.execute_plan(self, array, plan, donate=donate)
            except Exception as e:
                if ml is not None:
                    # the per-tile alloc_check inside execute_plan (or a
                    # real RESOURCE_EXHAUSTED mid-plan) lands here: dump
                    # the ledger with the failed tile's request size
                    ml.note_oom(e, "comm.resplit_tiled", plan.max_tile_bytes)
                raise
            if ml is not None:
                # the finished destination is no longer a transient: it IS
                # the moved array, carrying its source's category
                ml.reclassify(
                    out, op="resplit",
                    category=src_cat or "activation", site="resplit",
                )
            if _RESPLIT_CHECK is not None:
                _RESPLIT_CHECK(out, self, split, where="comm.resplit_tiled")
            return out

    # one-time-per-process warning flag for the lost-donation fallback
    _DONATE_FALLBACK_WARNED = False

    def _note_donate_fallback(self) -> None:
        """The running jax's ``device_put`` lacks ``donate=`` — the in-place
        resplit silently degraded to a copying transfer.  Counted under
        ``comm.resplit.donate_fallbacks`` (every occurrence) and warned once
        per process, so a peak-memory regression on an old jax is
        attributable instead of invisible."""
        from ..utils import profiler as _profiler

        _profiler.counter_inc("comm.resplit.donate_fallbacks")
        if not Communication._DONATE_FALLBACK_WARNED:
            Communication._DONATE_FALLBACK_WARNED = True
            warnings.warn(
                "jax.device_put does not support donate=: in-place resplit "
                "falls back to a copying transfer (peak memory ~2x the "
                "array). Upgrade jax to recover donation; occurrences are "
                "counted under comm.resplit.donate_fallbacks.",
                stacklevel=4,
            )

    def _already_placed(self, array, split: Optional[int]) -> bool:
        """True when ``array`` is concrete and already carries exactly the
        canonical sharding of ``split`` — a resplit of it moves no bytes
        (the same early-return condition ``shard``/the donate path apply)."""
        if isinstance(array, jax.core.Tracer) or not isinstance(array, jax.Array):
            return False
        if split is not None:
            split = split % array.ndim if array.ndim else None
        if split is not None and (
            array.ndim == 0 or array.shape[split] % self.size != 0
        ):
            return False  # ragged: placement is XLA's, not the canonical one
        return getattr(array, "sharding", None) == self.sharding(array.ndim, split)

    def _donatable(self, array, split: Optional[int]) -> bool:
        """True when the donating reshard program may be used for ``array``."""
        from ._complexsafe import guard

        if isinstance(array, jax.core.Tracer) or not isinstance(array, jax.Array):
            return False
        if guard(array) is not None:
            return False  # hosted complex: stays off the mesh
        if self.n_processes > 1:
            return False  # placement goes through host assembly (see shard())
        if split is not None and (
            array.ndim == 0 or array.shape[split % array.ndim] % self.size != 0
        ):
            return False  # ragged: split stays logical, no canonical target
        return True

    # ------------------------------------------------------------------ #
    # functional collectives — valid ONLY inside shard_map over this mesh.
    # These carry the MPI names for discoverability by reference users.
    # ------------------------------------------------------------------ #
    # mesh size above which gather-based collectives warn (module-level so
    # tests can lower it; 8 ≈ one host's worth of chips)
    GATHER_WARN_THRESHOLD = 8

    def _account(
        self,
        name: str,
        x,
        factor: float,
        src_split: Optional[int] = None,
        dst_split: Optional[int] = None,
    ) -> None:
        """Byte accounting of one staged collective: ``comm.<name>.calls``
        += 1 and ``comm.<name>.bytes`` += per-shard payload nbytes × the
        collective's algorithmic traffic factor (the wire cost per shard in
        payload units — factor table in design.md "Telemetry & metrics").

        Counted at STAGING (trace) time: a cached executable's replays never
        re-enter these Python wrappers, so ``calls`` counts distinct staged
        collectives per compilation — a collective inside ``lax.scan``
        counts once however many iterations run.  Derived collectives
        (``Reduce``, ``Scatter``) account under the primitive they are
        built from (``Allreduce``, ``Bcast``).

        Health hooks ride the same choke point: fault site
        ``comm.collective`` fires here (delay/hang model a slow or dead
        peer at staging), and an armed :meth:`deadline` both refuses to
        stage more work once blown AND catches an injected staging hang —
        under a deadline the fire runs inside ``guard_blocking``, so a
        ``hang=`` injection trips ``CollectiveTimeoutError`` exactly like
        a hang in ``Wait`` would, instead of wedging the caller's thread."""
        self._account_bytes(
            name,
            int(round(_payload_nbytes(x) * factor)),
            x=x,
            src_split=src_split,
            dst_split=dst_split,
        )

    def _account_bytes(
        self,
        name: str,
        wire_bytes: int,
        x=None,
        src_split: Optional[int] = None,
        dst_split: Optional[int] = None,
    ) -> None:
        """The staging choke point itself, taking pre-computed WIRE bytes:
        :meth:`_account` (payload × factor) and the tiled-resplit executor
        (telescoped per-tile bytes, ``core.redistribution.execute_plan``)
        both land here, so fault injection, deadline refusal, byte
        accounting AND the flight-recorder seq stamp cover every staged
        collective — monolithic or per-tile — through one code path.

        The stamp is written FIRST, before the fault site fires: a hang
        injected (or suffered) at staging leaves the collective it hung on
        as the rank's last ring record — "stuck AT seq N op X", which is
        exactly what ``scripts/postmortem.py`` names."""
        if _FLIGHTREC is not None:
            _FLIGHTREC.record_collective(name, wire_bytes, x, src_split, dst_split)
        from ..utils import faults as _flt  # lazy: core imports before utils

        hlth = _health()
        if hlth.active_deadline() is None:
            _flt.fire("comm.collective")
        else:
            # checks expiry first (raises CollectiveTimeoutError with this
            # site name), then runs the fire on the watchdog thread
            hlth.guard_blocking(
                lambda: _flt.fire("comm.collective"), f"comm.{name}"
            )
        _telemetry().account_collective(name, wire_bytes)

    def _warn_gather_based(self, name: str) -> None:
        """Perf-trap warning (reference: ``warnings.warn`` on implicit-comm
        traps, SURVEY §5.5): this collective is implemented via all_gather, so
        every shard materializes p× the buffer — fine at p≤8, a memory trap at
        pod scale.  Warned at trace time.  Every call additionally counts
        under ``comm.gather_fallback.<name>`` so slow-path collective usage
        is visible in ``telemetry.report()`` even below the warn threshold
        (where the one-shot warning stays silent)."""
        from ..utils import profiler as _profiler

        _profiler.counter_inc(f"comm.gather_fallback.{name}")
        if self.size > Communication.GATHER_WARN_THRESHOLD:
            warnings.warn(
                f"Communication.{name} is gather-based: each shard holds "
                f"size×buffer = {self.size}× the payload. At this mesh size "
                "prefer psum/reduce_scatter formulations.",
                stacklevel=3,
            )

    def Allreduce(self, x, op: str = "sum"):
        p = self.size
        # prod is realized as a log-p prefix scan + one masked psum — its
        # true wire cost, accounted here ONCE (the shared _inclusive_scan
        # helper deliberately does no accounting of its own)
        factor = 2.0 * (p - 1) / p
        if op == "prod":
            factor += float(max(p - 1, 0).bit_length())
        self._account("Allreduce", x, factor)
        ops = {
            "sum": lax.psum,
            "max": lax.pmax,
            "min": lax.pmin,
            "mean": lax.pmean,
        }
        if op in ("prod", "land", "lor"):
            if op == "prod":
                # sign/zero-safe product in O(1) memory: inclusive-scan
                # product via log-p recursive doubling, then broadcast the
                # last shard's total with a masked psum (no all_gather)
                inc = self._inclusive_scan(x, jnp.multiply, unit=1)
                last = jnp.where(
                    lax.axis_index(self.__axis) == self.size - 1,
                    inc,
                    jnp.zeros_like(inc),
                )
                # psum promotes bool/small ints — restore the caller's dtype
                return lax.psum(last, self.__axis).astype(x.dtype)
            if op == "land":
                return lax.pmin(x.astype(jnp.int32), self.__axis).astype(jnp.bool_)
            return lax.pmax(x.astype(jnp.int32), self.__axis).astype(jnp.bool_)
        return ops[op](x, self.__axis)

    def hierarchical_allreduce(self, x, op: str = "sum", domains: Optional[int] = None):
        """Two-level allreduce over this communicator's axis (valid only
        inside ``shard_map``, like ``Allreduce``): reduce-scatter within
        each of ``domains`` contiguous process subgroups (the fast tier),
        cross-domain exchange of the 1/i shard (the slow tier — the only
        traffic that crosses domains), allgather back (arXiv 2004.09362).

        ``domains=None`` derives the slow-domain count from the process
        topology (one domain per host process); when the world has one
        domain — or the hierarchy does not divide the axis — this falls
        back to the flat allreduce.  ``op`` is ``"sum"`` or ``"mean"``.

        Accounting: every stage routes through ``_account_bytes`` under
        ``comm.allreduce`` — per-stage seq stamps in the flight ring, the
        ``comm.collective`` fault site, deadline enforcement — with the
        stage factors telescoping exactly to the flat ring total:
        (i−1)/i + 2(d−1)/(d·i) + (i−1)/i = 2(p−1)/p, so
        ``comm.allreduce.bytes`` for the K staged records reconciles
        against the monolithic accounting to the byte."""
        if op not in ("sum", "mean"):
            raise ValueError(f"hierarchical_allreduce supports sum/mean, got {op!r}")
        from . import collectives as _coll

        p = self.size
        d = _coll._derive_domains(self, domains)
        factors = _coll._hier_stage_factors(p, d)
        if factors is None:
            # single domain: the hierarchy is the flat ring
            self._account_bytes(
                "allreduce",
                int(round(_payload_nbytes(x) * 2.0 * (p - 1) / p)),
                x=x,
            )
            out = lax.psum(x, self.__axis)
            return out / p if op == "mean" else out
        nbytes = _payload_nbytes(x)
        tele = _coll._Telescope()
        _coll._account_stages(self, tele, nbytes, factors, x=x)
        return _coll._hierarchical_body(x, self.__axis, p, d, mean=(op == "mean"))

    def Allgather(self, x, axis: int = 0, tiled: bool = True):
        self._account("Allgather", x, self.size - 1)
        return lax.all_gather(x, self.__axis, axis=axis, tiled=tiled)

    def Alltoall(self, x, split_axis: int, concat_axis: int):
        self._account("Alltoall", x, (self.size - 1) / self.size)
        return lax.all_to_all(
            x, self.__axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True
        )

    def Bcast(self, x, root: int = 0):
        """Every shard receives shard ``root``'s block.

        O(1)-memory: the non-root shards contribute zeros to a ``psum``, so
        the wire cost is one allreduce of the payload and no shard ever holds
        a p× buffer (the reference Bcasts a single buffer too — this is the
        SPMD-collective realization of the same cost)."""
        p = self.size
        self._account("Bcast", x, 2.0 * (p - 1) / p)
        mine = lax.axis_index(self.__axis) == root
        contrib = jnp.where(mine, x, jnp.zeros_like(x))
        # psum promotes bool to int32 — restore the caller's dtype
        return lax.psum(contrib, self.__axis).astype(x.dtype)

    def Send(self, x, shift: int = 1):
        """Ring shift by ``shift`` (reference Isend/Irecv neighbor exchange)."""
        self._account("Send", x, 1.0)
        n = self.size
        perm = [(i, (i + shift) % n) for i in range(n)]
        return lax.ppermute(x, self.__axis, perm)

    def ReduceScatter(self, x, axis: int = 0):
        self._account("ReduceScatter", x, (self.size - 1) / self.size)
        return lax.psum_scatter(x, self.__axis, scatter_dimension=axis, tiled=True)

    def _inclusive_scan(self, x, combine, unit):
        """Inclusive prefix combine across shards in O(log p) ``ppermute``
        steps (Hillis–Steele recursive doubling), O(1) memory per shard.
        ``unit`` fills the holes of the partial permutation (ranks below the
        stride receive nothing).  No telemetry accounting here: the PUBLIC
        entry points (Scan, Exscan, Allreduce-prod) each account their own
        end-to-end cost — accounting in this shared helper would double-count
        and misattribute (found in review)."""
        idx = lax.axis_index(self.__axis)
        n = self.size
        acc = x
        shift = 1
        while shift < n:
            perm = [(i, i + shift) for i in range(n - shift)]
            recvd = lax.ppermute(acc, self.__axis, perm)
            filled = jnp.where(idx >= shift, recvd, jnp.full_like(recvd, unit))
            acc = combine(acc, filled)
            shift *= 2
        return acc

    def Exscan(self, x):
        """Exclusive prefix sum across shards (reference ``comm.Exscan``).

        O(log p) ``ppermute`` rounds, O(1) memory: the inclusive scan is
        computed by recursive doubling, then shifted one rank down the ring
        (rank 0 receives the empty-sum zero) — exact, unlike
        ``inclusive - x`` which reassociates floats."""
        # ceil(log2 p) doubling rounds + the one-rank down-shift
        self._account("Exscan", x, float(max(self.size - 1, 0).bit_length()) + 1.0)
        inc = self._inclusive_scan(x, jnp.add, unit=0)
        n = self.size
        perm = [(i, i + 1) for i in range(n - 1)]
        shifted = lax.ppermute(inc, self.__axis, perm)
        idx = lax.axis_index(self.__axis)
        return jnp.where(idx > 0, shifted, jnp.zeros_like(shifted))

    def Scan(self, x):
        # ceil(log2 p) recursive-doubling rounds, one payload each
        self._account("Scan", x, float(max(self.size - 1, 0).bit_length()))
        return self._inclusive_scan(x, jnp.add, unit=0)

    def Reduce(self, x, root: int = 0, op: str = "sum"):
        """Reduce to shard ``root``; other shards receive zeros (XLA is SPMD —
        every shard computes; the root-masking preserves MPI semantics)."""
        red = self.Allreduce(x, op)
        mine = lax.axis_index(self.__axis) == root
        return jnp.where(mine, red, jnp.zeros_like(red))

    def Scatter(self, x, root: int = 0, axis: int = 0):
        """Shard ``root``'s block, split along ``axis``, one piece per shard.

        Transient memory = ONE copy of root's buffer per shard (the masked-
        psum Bcast), then the local slice — no p× gather."""
        src = self.Bcast(x, root=root)
        n = self.size
        idx = lax.axis_index(self.__axis)
        piece = src.shape[axis] // n
        return lax.dynamic_slice_in_dim(src, idx * piece, piece, axis=axis)

    def Gather(self, x, root: int = 0, axis: int = 0):
        """All blocks concatenated on shard ``root`` (others receive the same
        buffer zeroed — SPMD equivalence of the MPI rooted gather).

        O(p)-memory by definition (every shard materializes the gathered
        buffer before root-masking); see ``_warn_gather_based``."""
        self._warn_gather_based("Gather")
        self._account("Gather", x, self.size - 1)
        full = lax.all_gather(x, self.__axis, axis=axis, tiled=True)
        mine = lax.axis_index(self.__axis) == root
        return jnp.where(mine, full, jnp.zeros_like(full))

    # nonblocking names: EVERY XLA collective is asynchronously dispatched,
    # so the I* forms are the same ops; Wait == block_until_ready
    Iallreduce = Allreduce
    Iallgather = Allgather
    Ialltoall = Alltoall
    Ibcast = Bcast
    Isend = Send
    Irecv = Send

    @staticmethod
    def Wait(x):
        """Block until a dispatched result is ready (reference MPIRequest.Wait).

        Deadline-guarded: under an armed :meth:`deadline` a wait on a
        collective whose peer died raises ``CollectiveTimeoutError`` (with
        a full stack dump) instead of hanging the process forever — the
        elastic runtime's detection point for a wedged world.  Fault site
        ``comm.collective`` fires inside the guard so an injected hang is
        caught by the watchdog exactly like a real one."""
        from ..utils import faults as _flt

        def _wait():
            _flt.fire("comm.collective")
            return jax.block_until_ready(x)

        return _health().guard_blocking(_wait, "comm.Wait")

    def Barrier(self) -> None:
        """Host-level barrier: forces completion of all enqueued work.
        Deadline-guarded like :meth:`Wait` (same watchdog, same fault
        site)."""
        from ..utils import faults as _flt

        def _barrier():
            _flt.fire("comm.collective")
            tok = jax.device_put(jnp.zeros(()), self.sharding(0, None))
            jax.block_until_ready(tok)

        _health().guard_blocking(_barrier, "comm.Barrier")

    def deadline(self, seconds: float):
        """Arm a collective deadline for the block (``with comm.deadline(30):``).

        Inside it, the blocking waits (:meth:`Wait`, :meth:`Barrier`,
        :meth:`host_fetch`) run under a watchdog that raises
        :class:`heat_tpu.utils.health.CollectiveTimeoutError` — after
        dumping every thread's stack — once the budget is exhausted, and
        collective *staging* points refuse to stage more work past the
        deadline.  A hung Allreduce becomes a catchable error the caller
        (or the supervisor, via process exit) can recover from, instead of
        being indistinguishable from slow progress."""
        return _health().deadline(seconds)

    # convenience: run fn under shard_map over this communicator
    def shard_map(self, fn, in_splits, out_splits, check_vma: bool = False):
        """Wrap ``fn`` in a ``shard_map`` where each argument is split per ``in_splits``.

        ``in_splits``/``out_splits`` are pytrees of ``split`` values (ints or
        None) which are translated to PartitionSpecs over this communicator's
        axis.  The per-shard function sees local blocks and may call the
        collective methods above.
        """
        def is_leaf(s):
            return (
                isinstance(s, PartitionSpec)
                or (isinstance(s, tuple) and len(s) == 2 and isinstance(s[0], int))
            )

        def to_spec(s):
            if isinstance(s, PartitionSpec):
                return s
            return self.spec(s[0], s[1])

        in_specs = jax.tree.map(to_spec, in_splits, is_leaf=is_leaf)
        out_specs = jax.tree.map(to_spec, out_splits, is_leaf=is_leaf)
        return _jax_shard_map(
            fn, mesh=self.__mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )


# ---------------------------------------------------------------------- #
# world communicator bootstrap
# ---------------------------------------------------------------------- #
_world_cache = {}


def world() -> Communication:
    """The default communicator over the default device's mesh (= ``MPI_WORLD``)."""
    dev = devices.get_device()
    comm = _world_cache.get(dev.device_type)
    if comm is None or comm.mesh is not dev.mesh:
        mesh = dev.mesh
        axis = mesh.axis_names[-1] if "x" not in mesh.axis_names else "x"
        comm = Communication(mesh, axis)
        _world_cache[dev.device_type] = comm
    return comm


_default_comm: Optional[Communication] = None


def _invalidate_default(device=None) -> None:
    global _default_comm
    _default_comm = None
    _world_cache.clear()


def get_comm() -> Communication:
    return _default_comm if _default_comm is not None else world()


def use_comm(comm: Optional[Communication] = None) -> None:
    global _default_comm
    if comm is not None and not isinstance(comm, Communication):
        raise TypeError(f"Expected Communication, got {type(comm)}")
    _default_comm = comm


def sanitize_comm(comm: Optional[Communication]) -> Communication:
    if comm is None:
        return get_comm()
    if isinstance(comm, Communication):
        return comm
    raise TypeError(f"Expected Communication or None, got {type(comm)}")


# reference-name aliases: the class the reference calls MPICommunication is
# this mesh-backed Communication; MPI_WORLD/MPI_SELF resolve lazily so that
# importing the module does not force device initialization
MPICommunication = Communication


def __getattr__(name):
    if name == "MPI_WORLD":
        return world()
    if name == "MPI_SELF":
        import jax
        from jax.sharding import Mesh

        return Communication(Mesh(np.asarray(jax.devices()[:1]), ("x",)), "x")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


# the sanitizer may have been armed while this module was still importing
# (if any import path ever makes sanitation load first, its poke would hit
# the half-initialized module and the `_RESPLIT_CHECK = None` line above
# would clobber it) — re-read the flag now that the body is done, same
# defensive pattern as core._operations
import sys as _sys  # noqa: E402

# getattr default: in the hypothetical sanitation-loads-first ordering,
# sanitation would be MID-import here (this import triggered by its own
# top-of-module imports) and checks_enabled not yet defined — treat that as
# "not armed"; sanitation's own env-arming poke runs once it finishes
_san = _sys.modules.get("heat_tpu.core.sanitation")
if _san is not None and getattr(_san, "checks_enabled", lambda: False)():
    _RESPLIT_CHECK = _san.check_placement
# same defensive re-arm for the flight recorder: if utils.flightrec was
# env-armed before this module finished importing, its poke hit the
# half-initialized module and the `_FLIGHTREC = None` line clobbered it
_fr = _sys.modules.get("heat_tpu.utils.flightrec")
if _fr is not None and getattr(_fr, "enabled", lambda: False)():
    _FLIGHTREC = _fr
# and for the memory ledger (HEAT_TPU_MEMLEDGER=1 arms at utils.memledger
# import time, which may precede or follow this module)
_ml = _sys.modules.get("heat_tpu.utils.memledger")
if _ml is not None and getattr(_ml, "enabled", lambda: False)():
    _MEMLEDGER = _ml
del _sys, _san, _fr, _ml
