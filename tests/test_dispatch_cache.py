"""Zero-copy dispatch contract tests (sharding-keyed program cache +
buffer donation across the op layer).

Three claims are pinned here, matching the dispatch redesign:

- **cache**: repeated ops with an identical ``(op, avals, split)`` signature
  reuse ONE compiled executable — zero recompilation over 100+ calls,
  observable through the ``utils.profiler`` hit/miss counters;
- **donation**: the in-place surfaces (``__i*__`` dunders, ``resplit_``,
  the DASO/DataParallel train steps) hand their input buffers to XLA —
  ``input_output_alias`` shows up in the compiled HLO where layouts permit
  aliasing, and the donated source buffer is actually consumed;
- **correctness**: cached/donating paths produce the same values and split
  metadata as the eager path they replaced.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import _cache
from heat_tpu.utils import profiler


def _dispatch_table(comm):
    return comm.__dict__.get("_compiled_programs", {}).get(
        _cache._DISPATCH_SLOT, {}
    )


class TestProgramCacheHitRate:
    def test_repeated_ops_zero_recompilation(self):
        """≥100 repeated same-signature ops: every one a cache hit."""
        x = ht.random.randn(64, 32, split=0)
        y = ht.random.randn(64, 32, split=0)
        # warmup: one miss per distinct signature
        _ = x + y, x * 2, ht.exp(x), ht.sum(x, axis=0), ht.cumsum(x, axis=0)
        profiler.reset_cache_stats()
        n0 = len(_dispatch_table(x.comm))
        for _ in range(25):
            _ = x + y
            _ = x * 2
            _ = ht.exp(x)
            _ = ht.sum(x, axis=0)
            _ = ht.cumsum(x, axis=0)
        stats = profiler.cache_stats()
        assert stats["misses"] == 0, f"recompilations after warmup: {stats}"
        assert stats["hits"] >= 125
        assert profiler.cache_hit_rate() >= 0.99
        assert len(_dispatch_table(x.comm)) == n0  # no table growth

    def test_distinct_signatures_miss_once(self):
        x = ht.random.randn(16, 16, split=0)
        profiler.reset_cache_stats()
        _ = x + 1.5
        _ = x + 2.5  # same program: the scalar is a runtime arg, not a constant
        s = profiler.cache_stats()
        assert s["misses"] == 1 and s["hits"] == 1, s
        _ = x.resplit(1) + 1.5  # different operand split: a new signature
        assert profiler.cache_stats()["misses"] == s["misses"] + 1

    def test_cached_path_matches_eager_metadata(self):
        x = ht.random.randn(64, 32, split=0)
        y = ht.random.randn(64, 32, split=0)
        for _ in range(2):  # second pass takes the cached program
            z = x * y
            assert z.split == 0 and z.shape == (64, 32)
            s0 = ht.sum(x, axis=0)
            assert s0.split is None  # reduced over the split axis
            s1 = ht.sum(x, axis=1)
            assert s1.split == 0
            c = ht.cumsum(x, axis=1)
            assert c.split == 0
        np.testing.assert_allclose(z.numpy(), x.numpy() * y.numpy(), rtol=1e-5)
        np.testing.assert_allclose(
            s1.numpy(), x.numpy().sum(axis=1), rtol=1e-4, atol=1e-4
        )

    def test_matmul_program_cached(self):
        a = ht.random.randn(32, 16, split=0)
        b = ht.random.randn(16, 24)
        c1 = a @ b
        profiler.reset_cache_stats()
        c2 = a @ b
        s = profiler.cache_stats()
        assert s["misses"] == 0 and s["hits"] >= 1
        assert c2.split == c1.split == 0
        np.testing.assert_allclose(
            c2.numpy(), a.numpy() @ b.numpy(), rtol=1e-4, atol=1e-4
        )

    def test_weak_scalar_promotion_preserved(self):
        # scalars ride as weak-typed runtime args: int8 + 2 stays int8,
        # exactly like the eager path
        x = ht.array(np.arange(6, dtype=np.int8), split=0)
        for _ in range(2):
            y = x + 2
            assert y.dtype == ht.int8, y.dtype
        z = x + 2.5  # weak float promotes to the default float
        assert z.dtype == ht.float32

    def test_tracer_dispatch_bypasses_cache(self):
        # inside jit the surrounding trace owns compilation; the dispatch
        # cache must not capture tracers
        x = ht.random.randn(16, 8, split=0)

        @jax.jit
        def f(a):
            return a + a * 2

        r = f(x)
        np.testing.assert_allclose(r.numpy(), x.numpy() * 3, rtol=1e-5)


class TestDonation:
    def test_iadd_emits_input_output_alias(self):
        """The in-place dunder's compiled program aliases in/out buffers."""
        x = ht.random.randn(32, 16, split=0)
        x += 1.0  # builds + caches the donating program
        table = _dispatch_table(x.comm)
        progs = [
            v for k, v in table.items()
            if k[0] == "binary" and k[4] is True  # the donate key component
        ]
        assert progs, f"no donating binary program cached: {list(table)}"
        prog = progs[-1][0]
        hlo = prog.lower(x._jarray, 1.0).compile().as_text()
        assert "input_output_alias" in hlo, "donation did not alias in/out"

    def test_iadd_consumes_old_buffer(self):
        x = ht.random.randn(32, 16, split=0)
        ref = x.numpy()
        old = x._parray
        x += 2.0
        np.testing.assert_allclose(x.numpy(), ref + 2.0, rtol=1e-6)
        assert old.is_deleted(), "in-place add kept a second live copy"

    def test_out_of_place_never_donates(self):
        x = ht.random.randn(32, 16, split=0)
        y = x + 1.0
        _ = x + 1.0  # cached path again
        np.testing.assert_allclose(
            (x + y).numpy(), 2 * x.numpy() + 1.0, rtol=1e-5, atol=1e-6
        )  # x still alive and correct (atol: near-zero elements may differ
        # by one float32 ulp between the cached program's (x+y) association
        # and the numpy oracle's 2x+1)

    def test_self_referencing_iadd_safe(self):
        # x += x may not donate (one buffer, two args) — falls back cleanly
        x = ht.random.randn(16, 8, split=0)
        ref = x.numpy()
        x += x
        np.testing.assert_allclose(x.numpy(), 2 * ref, rtol=1e-6)

    def test_resplit_donates_source_buffer(self, monkeypatch):
        """resplit_ hands its source buffer to the transfer
        (device_put(donate=True)): the runtime aliases or early-frees it
        wherever source/target layouts permit."""
        comm = ht.communication.get_comm()
        if not comm.is_distributed():
            pytest.skip("resplit needs a multi-device mesh")
        seen = {}
        orig = jax.device_put

        def spy(v, *a, **kw):
            seen.update(kw)
            return orig(v, *a, **kw)

        monkeypatch.setattr(jax, "device_put", spy)
        x = ht.random.randn(32, 16, split=0)
        ref = x.numpy()
        seen.clear()
        x.resplit_(1)
        assert seen.get("donate") is True, "resplit_ did not donate its source"
        assert x.split == 1
        np.testing.assert_allclose(x.numpy(), ref, rtol=1e-6)
        # the copying form must NOT donate (source stays live)
        seen.clear()
        y = x.resplit(0)
        assert seen.get("donate") is not True
        np.testing.assert_allclose(x.numpy(), ref, rtol=1e-6)

    def test_resplit_roundtrip_values(self):
        x = ht.random.randn(48, 16, split=0)
        ref = x.numpy()
        x.resplit_(1)
        np.testing.assert_allclose(x.numpy(), ref, rtol=1e-6)
        x.resplit_(None)
        np.testing.assert_allclose(x.numpy(), ref, rtol=1e-6)
        x.resplit_(0)
        np.testing.assert_allclose(x.numpy(), ref, rtol=1e-6)
        assert x.split == 0


class TestTrainStepDonation:
    def _mesh_4x2(self):
        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs 8 devices")
        from jax.sharding import Mesh

        return Mesh(np.asarray(devs[:8]).reshape(4, 2), ("dcn", "ici"))

    def test_daso_step_emits_input_output_alias(self):
        """The DASO per-step program aliases params/opt_state in→out: the
        hierarchical train loop holds ONE copy of the model state."""
        mesh = self._mesh_4x2()
        opt = ht.optim.DataParallelOptimizer("sgd", lr=0.1)
        daso = ht.optim.DASO(opt, mesh=mesh, global_skip=2, warmup_steps=0)
        model = ht.nn.Sequential(ht.nn.Linear(8, 16), ht.nn.ReLU(), ht.nn.Linear(16, 4))
        daso.init(model, key=jax.random.key(0))

        def loss_fn(pred, y):
            return jnp.mean((pred - y) ** 2)

        daso._build_steps(loss_fn)
        g, ici = daso.n_groups, daso.ici_size
        xs = jnp.zeros((g, 4 * ici, 8), jnp.float32)
        ys = jnp.zeros((g, 4 * ici, 4), jnp.float32)
        hlo = (
            daso._train_step.lower(daso._params, daso._opt_state, xs, ys)
            .compile()
            .as_text()
        )
        assert "input_output_alias" in hlo, "DASO step does not donate state"

    def test_daso_losses_stay_on_device(self):
        # host-sync audit: step() returns an async 0-d device array, not a
        # blocking float — materialization is the caller's choice
        mesh = self._mesh_4x2()
        daso = ht.optim.DASO(
            ht.optim.DataParallelOptimizer("sgd", lr=0.05), mesh=mesh, warmup_steps=1
        )
        model = ht.nn.Sequential(ht.nn.Linear(8, 4))
        daso.init(model, key=jax.random.key(1))

        def loss_fn(pred, y):
            return jnp.mean((pred - y) ** 2)

        rng = np.random.default_rng(0)
        xb = rng.normal(size=(16, 8)).astype(np.float32)
        loss = daso.step(loss_fn, jnp.asarray(xb), jnp.asarray(xb @ np.ones((8, 4), np.float32)))
        assert isinstance(loss, jax.Array)
        assert float(loss) >= 0.0  # materializes on demand

    def test_data_parallel_step_donates_and_trains(self):
        opt = ht.optim.DataParallelOptimizer("sgd", lr=0.1)
        dp = ht.nn.DataParallel(
            ht.nn.Sequential(ht.nn.Flatten(), ht.nn.Linear(8, 4)), optimizer=opt
        )
        params = dp.init(jax.random.key(0))
        state = opt.init_state(params)
        step = dp.make_train_step(lambda p, y: jnp.mean((p - y) ** 2))
        hlo = None
        x = jnp.zeros((16, 8), jnp.float32)
        y = jnp.zeros((16, 4), jnp.float32)
        hlo = step.lower(params, state, x, y).compile().as_text()
        assert "input_output_alias" in hlo
        old_leaves = jax.tree_util.tree_leaves(params)
        params, state, loss = step(params, state, x, y)
        # the pre-step replicas were consumed (no second live copy)
        assert any(leaf.is_deleted() for leaf in old_leaves)
        params, state, loss = step(params, state, x, y)  # rebind loop works
        assert np.isfinite(float(loss))

    def test_data_parallel_step_donation_opt_out(self):
        opt = ht.optim.DataParallelOptimizer("sgd", lr=0.1)
        dp = ht.nn.DataParallel(
            ht.nn.Sequential(ht.nn.Flatten(), ht.nn.Linear(8, 4)), optimizer=opt
        )
        params = dp.init(jax.random.key(0))
        state = opt.init_state(params)
        step = dp.make_train_step(lambda p, y: jnp.mean((p - y) ** 2), donate=False)
        x = jnp.zeros((16, 8), jnp.float32)
        y = jnp.zeros((16, 4), jnp.float32)
        new_params, _, _ = step(params, state, x, y)
        # opt-out keeps the old tree alive (e.g. for trust-region rollbacks)
        assert all(not leaf.is_deleted() for leaf in jax.tree_util.tree_leaves(params))
