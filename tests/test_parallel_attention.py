"""Ring attention / sequence-parallel tests."""

import numpy as np
import pytest

import heat_tpu as ht


def _oracle(q, k, v, causal):
    S, d = q.shape
    s = (q @ k.T) / np.sqrt(d)
    if causal:
        s = np.where(np.tril(np.ones((S, S), bool)), s, -np.inf)
    p = np.exp(s - s.max(1, keepdims=True))
    p /= p.sum(1, keepdims=True)
    return p @ v


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        S, d = 64, 16
        q = rng.normal(size=(S, d)).astype(np.float32)
        k = rng.normal(size=(S, d)).astype(np.float32)
        v = rng.normal(size=(S, d)).astype(np.float32)
        comm = ht.communication.get_comm()
        out = ht.parallel.ring_self_attention(
            comm.shard(jnp.asarray(q), 0),
            comm.shard(jnp.asarray(k), 0),
            comm.shard(jnp.asarray(v), 0),
            comm,
            causal=causal,
        )
        np.testing.assert_allclose(np.asarray(out), _oracle(q, k, v, causal), atol=2e-3)

    def test_ragged_fallback(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(1)
        S, d = 30, 8  # not divisible by the mesh → dense fallback
        q = rng.normal(size=(S, d)).astype(np.float32)
        comm = ht.communication.get_comm()
        out = ht.parallel.ring_self_attention(
            jnp.asarray(q), jnp.asarray(q), jnp.asarray(q), comm
        )
        np.testing.assert_allclose(np.asarray(out), _oracle(q, q, q, False), atol=2e-3)


class TestBatchedRingAttention:
    """(..., S, d) ring attention: batch/head axes broadcast through the
    flash accumulation; sequence axis stays sharded over the ring."""

    def _ref(self, q, k, v, causal):
        S = q.shape[-2]
        s = np.einsum("...qd,...kd->...qk", q, k) / np.sqrt(q.shape[-1])
        if causal:
            mask = np.tril(np.ones((S, S), bool))
            s = np.where(mask, s, -np.inf)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        return np.einsum("...qk,...kd->...qd", p, v)

    @pytest.mark.parametrize("lead", [(), (3,), (2, 4)])
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, lead, causal):
        import jax
        import jax.numpy as jnp
        from heat_tpu.parallel.ring_attention import ring_attention

        comm = ht.communication.get_comm()
        # S scales with the ACTUAL mesh so the ring path engages at any
        # device count (non-divisible S falls back to the dense path by
        # design, which would make the sharding assertion meaningless)
        shape = (*lead, 8 * comm.size, 8)
        rng = np.random.default_rng(1)
        q, k, v = (rng.standard_normal(shape).astype(np.float32) for _ in range(3))
        seq_ax = len(shape) - 2
        jq, jk, jv = (comm.shard(jnp.asarray(t), seq_ax) for t in (q, k, v))
        out = jax.jit(lambda a, b, c: ring_attention(a, b, c, comm, causal=causal))(jq, jk, jv)
        np.testing.assert_allclose(np.asarray(out), self._ref(q, k, v, causal), rtol=2e-3, atol=2e-4)
        # the output stays sequence-sharded over the full ring
        assert len(out.sharding.device_set) == comm.size
