"""heat_tpu — a TPU-native distributed array and data-analytics framework.

A from-scratch re-design of HeAT's capabilities (NumPy-style global arrays
sharded along a ``split`` axis, MPI-style collectives, distributed linear
algebra, sklearn-style estimators, data-parallel NN training) on
JAX/XLA/shard_map/Pallas.  ``import heat_tpu as ht`` exposes the reference's
flat namespace.
"""

from .core import *
from . import core
from .core import axisspec
from .core import random
from .core.redistribution import set_redistribution_budget, get_redistribution_budget
from .core.collectives import set_grad_bucket_budget, get_grad_bucket_budget
from . import linalg
from .linalg import matmul, dot, transpose, norm  # hoist reference's flat exports
from .linalg.basics import outer, trace, tril, triu, vdot, cross, projection, vector_norm, matrix_norm, einsum, einsum_path, kron, inner, tensordot, vecdot
from .linalg.qr import qr
from .linalg.svdtools import svd
from . import spatial
from . import cluster
from . import decomposition
from . import regression
from . import naive_bayes
from . import classification
from . import preprocessing
from . import graph
from . import nn
from . import optim
from . import utils
from . import fft
from . import sparse
from . import parallel
from . import ops

__version__ = core.version.__version__


def __getattr__(name):
    # MPI_WORLD / MPI_SELF are lazy in core.communication (the mesh may not be
    # initialized at import time); forward them here for `ht.MPI_WORLD` parity.
    if name in ("MPI_WORLD", "MPI_SELF"):
        return getattr(core.communication, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
