"""Pipeline parallelism: GPipe microbatch schedule over a mesh axis.

Beyond-reference strategy (SURVEY §2.8 lists PP as absent from the
reference), built the TPU way: the stage dimension is sharded over the
mesh — every device holds ONE stage's parameters — and activations flow
stage-to-stage through ``lax.ppermute`` ring shifts inside a single
``shard_map``-ed ``lax.scan`` over microbatch ticks.  The whole schedule
is ONE compiled XLA program (no host round-trips between ticks), and the
*backward* pipeline falls out of autodiff: the transpose of ``ppermute``
is the reverse shift, so ``jax.grad`` of :func:`pipeline_apply` runs the
textbook reverse schedule without any hand-written machinery.

Schedule shape: with ``p`` stages and ``M`` microbatches the program runs
``M + p - 1`` ticks; the bubble fraction ``(p-1)/(M+p-1)`` shrinks as
``M`` grows — pick ``n_microbatches`` a few multiples of ``p``.

Constraint (inherent to SPMD pipelining, not a shortcut): every stage must
map microbatches to outputs of the SAME shape/dtype, since all devices run
one traced program and the carried activation buffer has one shape.
Homogeneous-block models (transformer stacks, MLP towers) fit naturally —
see :class:`heat_tpu.nn.Pipelined`.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from ..core._cache import comm_cached

__all__ = ["pipeline_apply"]


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x: jax.Array,
    comm,
    n_microbatches: int | None = None,
    batch_axis: str | None = None,
):
    """Apply ``p`` pipelined stages to ``x``, microbatched GPipe-style.

    ``stage_fn(params_i, x_mb) -> y_mb`` is the per-stage computation;
    ``stage_params`` is a pytree whose leaves are stacked on a leading axis
    of size ``comm.size`` (stage ``i`` consumes slice ``i`` — the leading
    axis is sharded so each device holds only its own stage's weights).
    ``x`` has shape (N, ...); it is split into ``n_microbatches`` equal
    microbatches along axis 0 (default ``comm.size``, which must divide N).
    Returns the final stage's output, replicated (the usual input to a
    loss), shaped like ``x``.

    Keyed on ``stage_fn``'s identity via the per-comm program cache — pass
    a stable (module-level or instance-held) callable so repeat calls reuse
    one compiled schedule.

    ``batch_axis`` composes the pipeline with data parallelism: name a
    SECOND axis of ``comm``'s mesh (e.g. ``'dp'`` of a ``('dp','pp')``
    mesh with ``comm = Communication(mesh, axis='pp')``) and ``x`` is
    batch-sharded over it — each dp slice runs the same pipeline schedule
    over its batch shard while the stage weights stay sharded over the pp
    axis, so one compiled program is dp×pp-parallel.  ``n_microbatches``
    must divide the per-dp-shard batch.
    """
    p = comm.size
    M = int(n_microbatches) if n_microbatches else p
    n = x.shape[0]
    if batch_axis is not None:
        if batch_axis not in comm.mesh.axis_names or batch_axis == comm.axis:
            raise ValueError(
                f"batch_axis {batch_axis!r} must name a mesh axis other than "
                f"the pipeline axis {comm.axis!r}"
            )
        dp = comm.mesh.shape[batch_axis]
        if n % dp:
            raise ValueError(f"leading dim {n} not divisible by {batch_axis} size {dp}")
        n = n // dp
    if n % M:
        raise ValueError(f"leading dim {n} not divisible by n_microbatches={M}")
    if p == 1 and batch_axis is None:
        # a (dp, pp=1) mesh still runs the program so the batch sharding
        # and axis validation hold; only the truly-unsharded case shortcuts
        one = jax.tree.map(lambda a: a[0], stage_params)
        return stage_fn(one, x)
    return _pipeline_program(comm, stage_fn, M, x.ndim, batch_axis)(stage_params, x)


@comm_cached
def _pipeline_program(comm, stage_fn, M: int, x_ndim: int, batch_axis=None):
    p, axis = comm.size, comm.axis

    def body(params_st, x):
        idx = lax.axis_index(axis)
        params_loc = jax.tree.map(lambda a: a[0], params_st)  # this stage's slice
        xm = x.reshape(M, x.shape[0] // M, *x.shape[1:])
        perm = [(i, i + 1) for i in range(p - 1)]

        def tick(carry, t):
            state, out = carry
            mb = jnp.clip(t, 0, M - 1)
            inp = jnp.where(idx == 0, xm[mb], state)
            y = stage_fn(params_loc, inp)
            # the last stage commits microbatch t-(p-1) as it drains
            ot = jnp.clip(t - (p - 1), 0, M - 1)
            write = (idx == p - 1) & (t >= p - 1)
            out = out.at[ot].set(jnp.where(write, y, out[ot]))
            # everyone else hands its activation to the next stage
            state = lax.ppermute(y, axis, perm)
            return (state, out), None

        init = (jnp.zeros_like(xm[0]), jnp.zeros_like(xm))
        (_, out), _ = lax.scan(tick, init, jnp.arange(M + p - 1))
        # replicate the last stage's buffer (masked psum — one payload on the wire)
        out = lax.psum(jnp.where(idx == p - 1, out, jnp.zeros_like(out)), axis)
        return out.reshape(x.shape)

    from jax.sharding import PartitionSpec as P

    # a single PartitionSpec is a valid tree-prefix for the whole params
    # pytree: every leaf is stage-stacked on its leading axis; with a
    # batch_axis the activations are additionally batch-sharded over it
    # (each dp slice runs the schedule on its shard — same traced body)
    x_spec = P(batch_axis) if batch_axis else P()
    return jax.jit(
        comm.shard_map(
            body,
            in_splits=(P(axis), x_spec),
            out_splits=x_spec,
        )
    )
