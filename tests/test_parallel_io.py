"""Shard-parallel writes (VERDICT r2 item 6; reference per-rank hyperslab
writes in ``heat/core/io.py::save_hdf5``, SURVEY §5.4).

Every save path must stream one shard at a time — proven via the
``io._CHUNK_WRITES`` counters: a full-gather write would show one chunk of
the whole array's size; the shard-parallel path shows p chunks each a
fraction of it.
"""

import os

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import io as htio
from test_suites.basic_test import TestCase


def reset_counters():
    htio._CHUNK_WRITES["count"] = 0
    htio._CHUNK_WRITES["max_bytes"] = 0


def make_split(shape=(64, 8)):
    rng = np.random.default_rng(0)
    d = rng.uniform(-5, 5, size=shape).astype(np.float32)
    return d, ht.array(d, split=0)


class TestShardParallelWrites(TestCase):
    def test_hdf5_roundtrip_chunked(self, tmp_path):
        if not htio.supports_hdf5():
            pytest.skip("h5py missing")
        d, x = make_split()
        p = x.comm.size
        reset_counters()
        path = str(tmp_path / "a.h5")
        ht.save_hdf5(x, path, "data")
        assert htio._CHUNK_WRITES["count"] == p, "expected one write per shard"
        ceil_chunk = -(-d.shape[0] // p) * d[0].nbytes  # ceil-div shard bytes
        assert htio._CHUNK_WRITES["max_bytes"] <= ceil_chunk, (
            f"peak chunk {htio._CHUNK_WRITES['max_bytes']}B — looks like a full gather "
            f"({d.nbytes}B array)"
        )
        back = ht.load_hdf5(path, "data", split=0)
        self.assert_array_equal(back, d)

    def test_hdf5_ragged_roundtrip(self, tmp_path):
        if not htio.supports_hdf5():
            pytest.skip("h5py missing")
        rng = np.random.default_rng(1)
        d = rng.uniform(size=(13, 3)).astype(np.float32)
        x = ht.array(d, split=0)
        path = str(tmp_path / "r.h5")
        reset_counters()
        ht.save_hdf5(x, path, "data")
        # pad rows must never be written
        back = ht.load_hdf5(path, "data", split=0)
        self.assert_array_equal(back, d)

    def test_netcdf_roundtrip_chunked(self, tmp_path):
        if not htio.supports_netcdf():
            pytest.skip("no netcdf backend")
        d, x = make_split((40, 5))
        p = x.comm.size
        reset_counters()
        path = str(tmp_path / "a.nc")
        ht.save_netcdf(x, path, "var")
        assert htio._CHUNK_WRITES["count"] == p
        if p > 1:  # at p=1 the single chunk IS the whole array
            assert htio._CHUNK_WRITES["max_bytes"] < d.nbytes
        back = ht.load_netcdf(path, "var", split=0)
        self.assert_array_equal(back, d)

    def test_csv_streamed(self, tmp_path):
        d, x = make_split((24, 4))
        p = x.comm.size
        reset_counters()
        path = str(tmp_path / "a.csv")
        ht.save_csv(x, path)
        if p > 1:  # p=1 takes the (also correct) non-streaming fallback
            assert htio._CHUNK_WRITES["count"] == p
        back = ht.load_csv(path, split=0)
        self.assert_array_equal(back, d, rtol=1e-5, atol=1e-5)

    def test_csv_streamed_with_header(self, tmp_path):
        d, x = make_split((16, 3))
        path = str(tmp_path / "h.csv")
        ht.save_csv(x, path, header_lines=["colA,colB,colC"])
        back = ht.load_csv(path, header_lines=1, split=0)
        self.assert_array_equal(back, d, rtol=1e-5, atol=1e-5)

    def test_npy_memmap_streamed(self, tmp_path):
        d, x = make_split((32, 6))
        p = x.comm.size
        reset_counters()
        path = str(tmp_path / "a.npy")
        ht.save(x, path)
        assert htio._CHUNK_WRITES["count"] == p
        assert htio._CHUNK_WRITES["max_bytes"] <= -(-d.shape[0] // p) * d[0].nbytes
        back = np.load(path)
        np.testing.assert_allclose(back, d)

    def test_replicated_save_single_write(self, tmp_path):
        if not htio.supports_hdf5():
            pytest.skip("h5py missing")
        d = np.arange(12, dtype=np.float32).reshape(3, 4)
        x = ht.array(d, split=None)
        reset_counters()
        ht.save_hdf5(x, str(tmp_path / "rep.h5"), "data")
        assert htio._CHUNK_WRITES["count"] == 1  # replicated: one gather write


class TestArrayCheckpoint(TestCase):
    def test_roundtrip_split0(self, tmp_path):
        d, x = make_split((56, 7))
        p = x.comm.size
        ckpt = str(tmp_path / "ckpt")
        reset_counters()
        ht.save_array_checkpoint(x, ckpt)
        assert htio._CHUNK_WRITES["count"] == p
        assert htio._CHUNK_WRITES["max_bytes"] <= -(-d.shape[0] // p) * d[0].nbytes
        vdir = os.path.join(ckpt, open(os.path.join(ckpt, "LATEST")).read().strip())
        files = [f for f in os.listdir(vdir) if f.startswith("chunk_")]
        assert len(files) == p
        back = ht.load_array_checkpoint(ckpt)
        assert back.split == 0
        self.assert_array_equal(back, d)

    def test_resave_crash_safety(self, tmp_path):
        # a completed re-save prunes old versions; an INTERRUPTED one (dead
        # v-dir, LATEST still on the old version) must leave loads intact
        d1 = np.arange(16, dtype=np.float32)
        d2 = np.arange(16, 32, dtype=np.float32)
        ckpt = str(tmp_path / "safe")
        ht.save_array_checkpoint(ht.array(d1, split=0), ckpt)
        ht.save_array_checkpoint(ht.array(d2, split=0), ckpt)
        versions = [f for f in os.listdir(ckpt) if f.startswith("v")]
        assert len(versions) == 1, f"old versions not pruned: {versions}"
        self.assert_array_equal(ht.load_array_checkpoint(ckpt), d2)
        # simulate a crashed save: half-written v-dir without LATEST flip
        os.makedirs(os.path.join(ckpt, "v99"))
        np.save(os.path.join(ckpt, "v99", "chunk_0.npy"), d1[:2])
        self.assert_array_equal(ht.load_array_checkpoint(ckpt), d2)

    def test_pad_garbage_does_not_leak_into_convolve(self):
        # a ragged array whose pad region holds nonzero garbage (elementwise
        # fast paths leave f(0) there) must still convolve correctly — the
        # halo path masks pads to the conv zero-padding on entry
        n, m = 37, 4
        rng = np.random.default_rng(12)
        an = rng.uniform(1.0, 2.0, n).astype(np.float32)
        x = ht.array(an, split=0)
        y = ht.exp(x)  # pad region now exp(0)=1, not 0
        r = ht.convolve(y, ht.array(np.ones(m, np.float32)), mode="same")
        self.assert_array_equal(r, np.convolve(np.exp(an), np.ones(m), mode="same"), rtol=1e-4)
        r2 = ht.convolve(r, ht.array(np.ones(m, np.float32)), mode="same")
        want2 = np.convolve(np.convolve(np.exp(an), np.ones(m), "same"), np.ones(m), "same")
        self.assert_array_equal(r2, want2, rtol=1e-4)

    def test_roundtrip_ragged(self, tmp_path):
        rng = np.random.default_rng(3)
        d = rng.uniform(size=(19, 4)).astype(np.float32)
        x = ht.array(d, split=0)
        ckpt = str(tmp_path / "rag")
        ht.save_array_checkpoint(x, ckpt)
        back = ht.load_array_checkpoint(ckpt)
        self.assert_array_equal(back, d)

    def test_roundtrip_replicated(self, tmp_path):
        d = np.arange(20, dtype=np.float32).reshape(4, 5)
        x = ht.array(d, split=None)
        ckpt = str(tmp_path / "rep")
        ht.save_array_checkpoint(x, ckpt)
        back = ht.load_array_checkpoint(ckpt)
        assert back.split is None
        self.assert_array_equal(back, d)

    def test_roundtrip_different_mesh_size(self, tmp_path):
        # the loader re-cuts chunk boundaries to ITS mesh: save on 8, load on 3
        import jax
        from jax.sharding import Mesh

        rng = np.random.default_rng(5)
        d = rng.uniform(size=(22, 3)).astype(np.float32)
        x = ht.array(d, split=0)  # world comm (8 devices)
        if len(jax.devices()) < 3:
            pytest.skip("remesh target needs >= 3 devices")
        ckpt = str(tmp_path / "remesh")
        ht.save_array_checkpoint(x, ckpt)
        comm3 = ht.communication.Communication(
            Mesh(np.asarray(jax.devices()[:3]), ("x",)), "x"
        )
        back = ht.load_array_checkpoint(ckpt, comm=comm3)
        assert back.split == 0
        assert back.comm.size == 3
        self.assert_array_equal(back, d)

    def test_roundtrip_split1(self, tmp_path):
        rng = np.random.default_rng(4)
        d = rng.uniform(size=(6, 32)).astype(np.float32)
        x = ht.array(d, split=1)
        ckpt = str(tmp_path / "s1")
        ht.save_array_checkpoint(x, ckpt)
        back = ht.load_array_checkpoint(ckpt)
        assert back.split == 1
        self.assert_array_equal(back, d)
