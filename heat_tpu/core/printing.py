"""Global-array printing (reference: ``heat/core/printing.py``).

``print(x)`` must show the GLOBAL array.  The reference gathers boundary
chunks to rank 0; here the array already has a global view, but for huge
arrays we fetch only the edge tiles to the host (never the full buffer),
mirroring SURVEY §5.5's guidance.
"""

from __future__ import annotations

import jax
import numpy as np

from .communication import Communication

__all__ = ["get_printoptions", "set_printoptions", "local_printing", "global_printing", "print0"]

# numpy-style print options (threshold/edgeitems/precision/sci_mode)
__PRINT_OPTIONS = dict(precision=4, threshold=1000, edgeitems=3, linewidth=120, sci_mode=None)
_LOCAL_PRINTING = False


def set_printoptions(precision=None, threshold=None, edgeitems=None, linewidth=None, profile=None, sci_mode=None):
    """Configure printing (mirrors torch/numpy set_printoptions)."""
    if profile == "default":
        __PRINT_OPTIONS.update(precision=4, threshold=1000, edgeitems=3, linewidth=120)
    elif profile == "short":
        __PRINT_OPTIONS.update(precision=2, threshold=1000, edgeitems=2, linewidth=120)
    elif profile == "full":
        __PRINT_OPTIONS.update(precision=4, threshold=np.inf, edgeitems=3, linewidth=120)
    for k, v in dict(
        precision=precision, threshold=threshold, edgeitems=edgeitems, linewidth=linewidth, sci_mode=sci_mode
    ).items():
        if v is not None:
            __PRINT_OPTIONS[k] = v


def get_printoptions() -> dict:
    return dict(__PRINT_OPTIONS)


def local_printing() -> None:
    global _LOCAL_PRINTING
    _LOCAL_PRINTING = True


def global_printing() -> None:
    global _LOCAL_PRINTING
    _LOCAL_PRINTING = False


def print0(*args, **kwargs) -> None:
    """Print only on process 0 (reference ``ht.print0``)."""
    if jax.process_index() == 0:
        print(*args, **kwargs)


def _edge_fetch(x) -> np.ndarray:
    """Host-fetch only the edge tiles of a large array for summarized printing."""
    e = __PRINT_OPTIONS["edgeitems"]
    jarr = x._jarray
    # slice e+1 items from each end of every axis; numpy's own summarization
    # then prints ellipses correctly for any axis longer than 2e
    slices = []
    for s in x.shape:
        if s > 2 * e + 1:
            slices.append(None)  # needs stitching
        else:
            slices.append(slice(None))
    if all(sl == slice(None) for sl in slices):
        return Communication.host_fetch(jarr)
    # fetch per-axis edges by advanced indexing with index vectors
    idxs = []
    for s in x.shape:
        if s > 2 * e + 1:
            idxs.append(np.r_[0 : e + 1, s - e : s])
        else:
            idxs.append(np.arange(s))
    mesh_idx = np.ix_(*idxs)
    return Communication.host_fetch(jarr[mesh_idx])


def __str__(x) -> str:
    # host-sync audit: printing is an EXPLICIT materialization point, but a
    # repr reached during tracing (a print inside a jitted user function, a
    # debugger hitting a traced DNDarray) must not try to fetch values — it
    # would raise a TracerArrayConversionError mid-trace.  Show the aval.
    if isinstance(x._parray, jax.core.Tracer):
        return f"Traced<shape={x.shape}, dtype={x.dtype.__name__}>"
    opt = get_printoptions()
    threshold = opt["threshold"]
    with np.printoptions(
        precision=opt["precision"],
        threshold=int(threshold) if np.isfinite(threshold) else 10**18,
        edgeitems=opt["edgeitems"],
        linewidth=opt["linewidth"],
    ):
        if x.size <= threshold or not np.isfinite(threshold):
            data = Communication.host_fetch(x._jarray)
            return np.array2string(data, separator=", ")
        data = _edge_fetch(x)
        # force summarization formatting of the stitched edges
        with np.printoptions(threshold=0, edgeitems=opt["edgeitems"]):
            return np.array2string(data, separator=", ")


def __repr__(x) -> str:
    body = __str__(x)
    return f"DNDarray({body}, dtype=ht.{x.dtype.__name__}, device={x.device}, split={x.split})"
