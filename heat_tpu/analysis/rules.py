"""Built-in heatlint rules HT101–HT106: the runtime's distributed invariants.

Each rule encodes one contract established by earlier rounds of perf,
robustness, and telemetry work (see doc/source/design.md "Static
contracts" for the full table):

- HT101 — no host syncs in library code (the sanitation.py contract)
- HT102 — no collective lexically inside a rank-conditional branch
- HT103 — no use of a name after its buffer was donated
- HT104 — every public collective in communication.py byte-accounts
- HT105 — no raw process entropy; seeding goes through ht.random
- HT106 — no DNDarray metadata mutation outside sanctioned modules
- HT107 — no naked blocking collective waits bypassing comm.deadline
- HT108 — no collective staging bypassing the seq-stamp choke point

All analyses are intentionally *lexical and intra-procedural*: false
negatives across call boundaries are accepted; false positives are kept
low enough that the committed baseline stays short and new code rarely
needs a suppression.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from .framework import Finding, LintContext, Rule, register

# -------------------------------------------------------------------- #
# shared AST helpers
# -------------------------------------------------------------------- #


def dotted_name(node: ast.AST) -> Optional[str]:
    """'np.random.seed' for Attribute/Name chains, None for anything else."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def last_attr(call: ast.Call) -> Optional[str]:
    """Final attribute of a call target: 'item' for ``x.y.item()``."""
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


# calls that END a device-value expression: their result is host data, so a
# float()/int()/np.asarray around them is not an additional sync
_MATERIALIZERS = {"host_fetch", "numpy", "tolist", "item"}


def subtree_mentions_device_value(node: ast.AST) -> bool:
    """Heuristic for 'this expression is a device value': it touches the raw
    jax array plumbing (``._jarray``/``._parray``/``.larray``) or directly
    calls into jnp/lax/jax.numpy — UNLESS the expression already routes
    through a sanctioned materialization call (``host_fetch``/``numpy()``),
    in which case the value is host-side by the time it is consumed."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and last_attr(sub) in _MATERIALIZERS:
            return False
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in (
            "_jarray",
            "_parray",
            "larray",
        ):
            return True
        if isinstance(sub, ast.Call):
            dn = call_name(sub)
            if dn and (
                dn.startswith("jnp.") or dn.startswith("lax.") or dn.startswith("jax.numpy.")
            ):
                return True
    return False


def module_matches(path: str, suffixes: Tuple[str, ...]) -> bool:
    return any(path.endswith(s) for s in suffixes)


def branch_exclusive(ctx: LintContext, a: ast.AST, b: ast.AST) -> bool:
    """True when ``a`` and ``b`` sit in mutually exclusive branches of the
    same ``if``/``try`` — sequential-order reasoning between them is invalid
    (used by HT103 to avoid flagging the untaken arm)."""
    chain_a = [a] + ctx.ancestors(a)
    chain_b = [b] + ctx.ancestors(b)
    set_b = set(map(id, chain_b))
    lca = next((n for n in chain_a if id(n) in set_b), None)
    if lca is None or not isinstance(lca, (ast.If, ast.Try)):
        return False

    def arm_of(node: ast.AST) -> Optional[str]:
        # which field of the lca contains this node's ancestor chain
        chain = [node] + ctx.ancestors(node)
        idx = [id(n) for n in chain].index(id(lca))
        if idx == 0:
            return None  # node IS the lca (e.g. the if test)
        child = chain[idx - 1]
        for fieldname in ("body", "orelse", "handlers", "finalbody"):
            if child in getattr(lca, fieldname, []):
                return fieldname
        return None

    fa, fb = arm_of(a), arm_of(b)
    if fa is None or fb is None:
        return False
    if isinstance(lca, ast.Try):
        # body vs handlers is exclusive-ish; finalbody always runs
        return fa != fb and "finalbody" not in (fa, fb)
    return fa != fb


# -------------------------------------------------------------------- #
# HT101 — host sync in library code
# -------------------------------------------------------------------- #


@register
class HostSyncRule(Rule):
    """Blocking device→host reads outside sanctioned materialization points.

    Library code runs in the middle of async dispatch pipelines: a
    ``.item()``, ``jax.device_get``, or ``np.asarray``/``float()``/``int()``
    of a device value stalls the host on the device stream (the
    ``sanitation.py`` no-value-reads contract).  Value materialization
    belongs behind the explicit points: ``numpy()``, ``item()``,
    ``Communication.host_fetch``, printing, and I/O.
    """

    code = "HT101"
    name = "host-sync-in-library"
    description = "blocking device→host read outside sanctioned materialization points"

    # modules whose JOB is materialization (printing, I/O)
    SANCTIONED_MODULES = (
        "core/printing.py",
        "core/io.py",
    )
    # the materialization API itself + host-boundary helpers
    SANCTIONED_DEFS = {
        "numpy",
        "item",
        "tolist",
        "host_fetch",
        "host_fetch_all",
        "__array__",
        "__bool__",
        "__int__",
        "__float__",
        "__complex__",
        "__index__",
        "__torch_proxy__",
        "__repr__",
        "__str__",
    }

    def _sanctioned(self, ctx: LintContext, node: ast.AST) -> bool:
        fn = ctx.enclosing_function(node)
        while fn is not None:
            if fn.name in self.SANCTIONED_DEFS:
                return True
            fn = ctx.enclosing_function(ctx.parent(fn)) if ctx.parent(fn) else None
        return False

    def check(self, ctx: LintContext) -> Iterable[Finding]:
        if module_matches(ctx.path, self.SANCTIONED_MODULES):
            return []
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if self._sanctioned(ctx, node):
                continue
            la = last_attr(node)
            dn = call_name(node)
            if la == "item" and isinstance(node.func, ast.Attribute) and not node.args:
                out.append(
                    ctx.finding(
                        self, node,
                        "`.item()` is a blocking device→host sync; route through a "
                        "sanctioned materialization point (numpy()/host_fetch) or keep "
                        "the value on device",
                        detail="item",
                    )
                )
            elif dn in ("jax.device_get",):
                out.append(
                    ctx.finding(
                        self, node,
                        "`jax.device_get` in library code is a blocking host sync; use "
                        "Communication.host_fetch at an explicit materialization point",
                        detail="device_get",
                    )
                )
            elif dn in ("np.asarray", "numpy.asarray", "np.array", "numpy.array") and node.args:
                if subtree_mentions_device_value(node.args[0]):
                    out.append(
                        ctx.finding(
                            self, node,
                            f"`{dn}` of a device value blocks on device→host transfer; "
                            "materialize via numpy()/host_fetch instead",
                            detail="np.asarray",
                        )
                    )
            elif dn in ("float", "int", "bool") and len(node.args) == 1:
                if subtree_mentions_device_value(node.args[0]):
                    out.append(
                        ctx.finding(
                            self, node,
                            f"`{dn}()` of a device value is an implicit `.item()` host "
                            "sync; keep the value on device or materialize explicitly",
                            detail=f"{dn}-cast",
                        )
                    )
        return [f for f in out if f is not None]


# -------------------------------------------------------------------- #
# HT102 — collective inside a rank-conditional branch
# -------------------------------------------------------------------- #


@register
class RankConditionalCollectiveRule(Rule):
    """A collective call lexically inside an ``if``/``while`` that branches on
    process/shard identity diverges the SPMD program: ranks that skip the
    branch never post the collective and the others deadlock (the round-5
    rank-conditional hazard class).  Rank-conditional *local* work (logging,
    file writes) is fine — only collective entry points are flagged."""

    code = "HT102"
    name = "rank-conditional-collective"
    description = "collective call inside a rank-conditional branch (SPMD divergence)"

    COLLECTIVES: Set[str] = {
        # Communication public API (MPI names)
        "Allreduce", "Allgather", "Alltoall", "Bcast", "Send", "Reduce",
        "Scatter", "Gather", "ReduceScatter", "Scan", "Exscan",
        "Iallreduce", "Iallgather", "Ialltoall", "Ibcast", "Isend", "Irecv",
        "Barrier", "resplit", "resplit_", "redistribute_",
        # collective-by-contract host boundary (every process must call)
        "host_fetch", "numpy", "process_allgather", "sync_global_devices",
        # raw lax collectives
        "psum", "pmax", "pmin", "pmean", "all_gather", "all_to_all",
        "ppermute", "psum_scatter", "pbroadcast",
    }
    # rank-identity markers, by syntactic shape (each tuple drives
    # _rank_conditional — extend HERE to widen detection)
    RANK_ATTRS = ("rank",)  # comm.rank, self.rank, ...
    RANK_CALLS = ("process_index", "axis_index")  # jax.process_index(), ...
    RANK_NAMES = ("rank", "process_id", "pid")  # bare local variables

    def _rank_conditional(self, test: ast.AST) -> Optional[str]:
        for sub in ast.walk(test):
            if isinstance(sub, ast.Attribute) and sub.attr in self.RANK_ATTRS:
                return dotted_name(sub) or sub.attr
            if isinstance(sub, ast.Call):
                la = last_attr(sub)
                if la in self.RANK_CALLS:
                    return la
            if isinstance(sub, ast.Name) and sub.id in self.RANK_NAMES:
                return sub.id
        return None

    def _arm_collectives(self, arm) -> dict:
        """collective name → [call nodes] for one branch arm."""
        found: dict = {}
        for stmt in arm:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    la = last_attr(sub)
                    if la in self.COLLECTIVES:
                        found.setdefault(la, []).append(sub)
        return found

    def check(self, ctx: LintContext) -> Iterable[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            marker = self._rank_conditional(node.test)
            if marker is None:
                continue
            body = self._arm_collectives(node.body)
            orelse = self._arm_collectives(node.orelse if isinstance(node, ast.If) else [])
            for arm, other in ((body, orelse), (orelse, body)):
                for la, calls in arm.items():
                    if la in other:
                        # posted in BOTH arms: every rank attends whichever
                        # branch it takes — the sanctioned "collective fetch,
                        # rank-conditional use" idiom (e.g. save_zarr)
                        continue
                    for sub in calls:
                        out.append(
                            ctx.finding(
                                self, sub,
                                f"collective `{la}` inside a branch conditioned "
                                f"on `{marker}`: ranks that skip the branch never "
                                "post it (SPMD divergence/deadlock hazard)",
                                detail=la,
                            )
                        )
        return [f for f in out if f is not None]


# -------------------------------------------------------------------- #
# HT103 — use after donate
# -------------------------------------------------------------------- #


@register
class UseAfterDonateRule(Rule):
    """A name whose buffer was donated (``donate=True`` kwarg, or passed in a
    ``donate_argnums`` position of a locally-jitted function) must not be
    read afterwards: XLA may have aliased or freed the storage, and the read
    returns garbage or raises only under certain layouts.  Rebinding the
    name clears the taint; uses in a mutually exclusive branch don't count."""

    code = "HT103"
    name = "use-after-donate"
    description = "name referenced after its buffer was donated"

    def check(self, ctx: LintContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(self._check_function(ctx, node))
        return out

    def _jit_donated_positions(self, call: ast.Call) -> Optional[Tuple[int, ...]]:
        """(positions) when ``call`` is jax.jit/jit with literal donate_argnums."""
        dn = call_name(call)
        if dn not in ("jax.jit", "jit"):
            return None
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                v = kw.value
                if isinstance(v, ast.Tuple):
                    pos = tuple(
                        e.value for e in v.elts if isinstance(e, ast.Constant) and isinstance(e.value, int)
                    )
                    return pos
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    return (v.value,)
                return ()  # dynamic donate_argnums: positions unknown, skip
        return None

    def _check_function(self, ctx: LintContext, fn: ast.AST) -> Iterable[Finding]:
        # jitted-callable names -> donated positions, discovered on the fly
        jitted: dict = {}
        # donation events: (sort key, donated name, donation call node)
        events: List[Tuple[Tuple[int, int], str, ast.Call]] = []

        own = [
            n
            for n in ast.walk(fn)
            if ctx.enclosing_function(n) is fn or n is fn
        ]
        for node in own:
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                pos = self._jit_donated_positions(node.value)
                if pos:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            jitted[tgt.id] = pos
        for node in own:
            if not isinstance(node, ast.Call):
                continue
            donated_names: List[str] = []
            for kw in node.keywords:
                if kw.arg == "donate" and isinstance(kw.value, ast.Constant) and kw.value.value is True:
                    if node.args and isinstance(node.args[0], ast.Name):
                        donated_names.append(node.args[0].id)
            fname = call_name(node)
            if fname in jitted:
                for p in jitted[fname]:
                    if p < len(node.args) and isinstance(node.args[p], ast.Name):
                        donated_names.append(node.args[p].id)
            for name in donated_names:
                key = (node.end_lineno or node.lineno, node.end_col_offset or 0)
                events.append((key, name, node))

        if not events:
            return []

        findings: List[Finding] = []
        for key, name, call in events:
            rebound_at: Optional[Tuple[int, int]] = None
            # the donating statement may itself rebind the name
            # (x = f(x, donate=True)) — taint never takes effect
            stmt = call
            for anc in [call] + ctx.ancestors(call):
                if isinstance(anc, ast.stmt):
                    stmt = anc
                    break
            if isinstance(stmt, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name for t in stmt.targets
            ):
                continue
            if isinstance(stmt, (ast.Return, ast.Raise)):
                # `return f(x, donate=True)` — control leaves the function at
                # the donation itself; no later read in this frame can see
                # the donated buffer
                continue
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Name)
                    and node.id == name
                    and isinstance(node.ctx, ast.Store)
                ):
                    at = (node.lineno, node.col_offset)
                    if at > key and (rebound_at is None or at < rebound_at):
                        rebound_at = at
            for node in ast.walk(fn):
                if not (
                    isinstance(node, ast.Name)
                    and node.id == name
                    and isinstance(node.ctx, ast.Load)
                ):
                    continue
                at = (node.lineno, node.col_offset)
                if at <= key:
                    continue
                if rebound_at is not None and at > rebound_at:
                    continue
                if branch_exclusive(ctx, call, node):
                    continue
                f = ctx.finding(
                    self, node,
                    f"`{name}` is read after its buffer was donated at line "
                    f"{call.lineno}; the storage may be aliased or freed",
                    detail=name,
                )
                if f is not None:
                    findings.append(f)
        return findings


# -------------------------------------------------------------------- #
# HT104 — unaccounted public collective in communication.py
# -------------------------------------------------------------------- #


@register
class CollectiveAccountingRule(Rule):
    """Every public collective in ``communication.py`` must byte-account at
    its entry (``self._account(...)`` / ``self._account_bytes(...)``) or
    delegate to another public collective that does — the telemetry round's
    invariant that no staged collective traffic is invisible to
    ``comm.<name>.calls/.bytes``.  The tiled-redistribution entry points
    (``resplit*``) may instead delegate to the chunked executor
    (``core.redistribution.execute_plan``), which byte-accounts every tile
    at its own staging point through ``_account_bytes`` — per-tile staging
    behind that entry is accounted, not invisible."""

    code = "HT104"
    name = "unaccounted-collective"
    description = "public collective without comm.<name> byte accounting"

    TARGET_SUFFIX = ("communication.py",)
    # public-but-not-traffic: Wait is a completion fence, Barrier moves one
    # scalar token (accounting it would pollute the traffic metric)
    EXEMPT = {"Wait", "Barrier"}
    # direct accounting calls at a collective's staging entry
    ACCOUNT_CALLS = {"self._account", "self._account_bytes"}
    # the tiled executor: accounts each tile exactly once via _account_bytes
    # (core/redistribution.py), so delegating to it IS accounting
    TILED_EXECUTORS = {"execute_plan"}

    def check(self, ctx: LintContext) -> Iterable[Finding]:
        if not module_matches(ctx.path, self.TARGET_SUFFIX):
            return []
        out = []
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for fn in cls.body:
                if not isinstance(fn, ast.FunctionDef):
                    continue
                is_mpi_name = fn.name[:1].isupper()
                if not (is_mpi_name or fn.name.startswith("resplit")):
                    continue
                if fn.name in self.EXEMPT:
                    continue
                accounted = False
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call):
                        dn = call_name(node)
                        if dn in self.ACCOUNT_CALLS:
                            accounted = True
                            break
                        la = last_attr(node)
                        if la in self.TILED_EXECUTORS and fn.name.startswith("resplit"):
                            # scoped to the resplit* entries: a future public
                            # collective calling something named execute_plan
                            # must still account its own traffic
                            accounted = True  # per-tile accounting in the executor
                            break
                        if (
                            dn
                            and dn.startswith("self.")
                            and la
                            and (la[:1].isupper() or la.startswith("resplit"))
                            and la != fn.name
                            and la not in self.EXEMPT
                        ):
                            accounted = True  # derived: accounts under its primitive
                            break
                if not accounted:
                    f = ctx.finding(
                        self, fn,
                        f"public collective `{fn.name}` never calls self._account(...) "
                        "nor delegates to an accounted collective — its traffic is "
                        "invisible to comm.<name>.calls/.bytes",
                        detail=fn.name,
                    )
                    if f is not None:
                        out.append(f)
        return out


# -------------------------------------------------------------------- #
# HT105 — raw process entropy
# -------------------------------------------------------------------- #


@register
class RawEntropyRule(Rule):
    """Randomness in library code must flow through the broadcast
    ``ht.random`` state (Threefry key from the global seed/counter): raw
    ``np.random``/stdlib ``random``/``os.urandom`` draws are per-process
    entropy, so under multi-process SPMD each rank generates DIFFERENT
    values from nominally identical code — the round-5 per-rank-seed
    divergence class."""

    code = "HT105"
    name = "raw-process-entropy"
    description = "raw np.random/process-entropy use instead of broadcast ht.random state"

    # the module that IMPLEMENTS the broadcast state is the one sanctioned
    # consumer of raw entropy
    SANCTIONED_MODULES = ("core/random.py",)

    def check(self, ctx: LintContext) -> Iterable[Finding]:
        if module_matches(ctx.path, self.SANCTIONED_MODULES):
            return []
        imports_stdlib_random = False
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                if any(a.name == "random" for a in node.names):
                    imports_stdlib_random = True
            elif isinstance(node, ast.ImportFrom):
                if node.module == "random":
                    imports_stdlib_random = True
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = call_name(node)
            if dn is None:
                continue
            bad = None
            if dn.startswith("np.random.") or dn.startswith("numpy.random."):
                bad = dn
            elif imports_stdlib_random and dn.startswith("random."):
                bad = dn
            elif dn in ("os.urandom", "uuid.uuid4", "secrets.token_bytes"):
                bad = dn
            if bad is not None:
                f = ctx.finding(
                    self, node,
                    f"`{bad}` draws per-process entropy — under multi-process SPMD "
                    "each rank diverges; use the broadcast ht.random state "
                    "(ht.random.seed/rand/randn) instead",
                    detail=bad,
                )
                if f is not None:
                    out.append(f)
        return out


# -------------------------------------------------------------------- #
# HT106 — DNDarray metadata mutation outside sanctioned modules
# -------------------------------------------------------------------- #


@register
class MetadataMutationRule(Rule):
    """``DNDarray``'s split/gshape/pad/array metadata is maintained by the
    class itself (constructor, ``_from_parts``, ``_renormalize``): writing
    the name-mangled privates from outside desynchronizes the logical
    metadata from the physical sharding — `split` starts lying.  Mutation
    goes through the public surface (``resplit_``, ``larray``/``_jarray``
    setters) instead."""

    code = "HT106"
    name = "metadata-mutation"
    description = "direct mutation of DNDarray metadata outside sanctioned modules"

    SANCTIONED_MODULES = ("core/dndarray.py",)
    # explicitly-mangled writes reach DNDarray's privates from anywhere
    MANGLED_ATTRS = {
        "_DNDarray__split", "_DNDarray__gshape", "_DNDarray__lshape",
        "_DNDarray__pad", "_DNDarray__array", "_DNDarray__dtype",
        "_DNDarray__unpadded",
    }
    # unmangled double-underscore writes only hit (or shadow) DNDarray
    # metadata OUTSIDE a class body — inside one, Python mangles them to the
    # ENCLOSING class's private (e.g. DCSR_matrix's own __gshape), which is
    # that class's business, not ours
    UNMANGLED_ATTRS = {
        "__split", "__gshape", "__lshape", "__pad", "__array", "__dtype", "__unpadded",
    }

    def _in_class_body(self, ctx: LintContext, node: ast.AST) -> bool:
        return any(isinstance(a, ast.ClassDef) for a in ctx.ancestors(node))

    def check(self, ctx: LintContext) -> Iterable[Finding]:
        if module_matches(ctx.path, self.SANCTIONED_MODULES):
            return []
        out = []
        for node in ast.walk(ctx.tree):
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            elif isinstance(node, ast.Delete):
                targets = list(node.targets)
            for tgt in targets:
                for sub in ast.walk(tgt):
                    if not isinstance(sub, ast.Attribute):
                        continue
                    hits = sub.attr in self.MANGLED_ATTRS or (
                        sub.attr in self.UNMANGLED_ATTRS
                        and not self._in_class_body(ctx, sub)
                    )
                    if not hits:
                        continue
                    f = ctx.finding(
                        self, node,
                        f"direct write to DNDarray metadata `{sub.attr}` outside "
                        "core/dndarray.py desynchronizes split/gshape from the "
                        "physical sharding; use resplit_/the _jarray setter",
                        detail=sub.attr,
                    )
                    if f is not None:
                        out.append(f)
        return out


# -------------------------------------------------------------------- #
# HT107 — naked blocking collective wait bypassing the deadline watchdog
# -------------------------------------------------------------------- #


@register
class NakedBlockingWaitRule(Rule):
    """A blocking collective wait — ``Barrier()``, ``Wait(...)``,
    ``jax.block_until_ready``, ``multihost_utils.sync_global_devices`` —
    in library code, lexically outside any ``with comm.deadline(...)``
    scope, hangs forever when one peer is dead: the exact failure mode the
    elastic runtime's watchdog exists to convert into
    ``CollectiveTimeoutError``.  Call sites that are legitimately
    unbounded (process teardown, the materialization layer) are exempted
    via the suppression/baseline machinery, like every other rule.

    Lexical and intra-procedural on purpose: a deadline armed by a CALLER
    is invisible here and such sites belong in the baseline — the point of
    the rule is that NEW naked waits need a conscious decision."""

    code = "HT107"
    name = "naked-blocking-wait"
    description = "blocking collective wait outside a comm.deadline scope"

    # the wrapper itself and the guard implementation are the two places a
    # raw blocking wait is the point
    SANCTIONED_MODULES = (
        "core/communication.py",
        "utils/health.py",
    )
    BLOCKING_ATTRS = {"Barrier", "Wait", "block_until_ready", "sync_global_devices"}

    def _under_deadline(self, ctx: LintContext, node: ast.AST) -> bool:
        """True when an ancestor ``with`` arms a deadline (``comm.deadline``
        / ``health.deadline`` / ``deadline(...)``) around this call."""
        for anc in ctx.ancestors(node):
            if not isinstance(anc, (ast.With, ast.AsyncWith)):
                continue
            for item in anc.items:
                expr = item.context_expr
                if isinstance(expr, ast.Call) and last_attr(expr) == "deadline":
                    return True
        return False

    def check(self, ctx: LintContext) -> Iterable[Finding]:
        if module_matches(ctx.path, self.SANCTIONED_MODULES):
            return []
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            la = last_attr(node)
            if la not in self.BLOCKING_ATTRS:
                continue
            if la == "Barrier" and (node.args or node.keywords):
                continue  # a foreign Barrier(...) API, not the collective fence
            if self._under_deadline(ctx, node):
                continue
            f = ctx.finding(
                self, node,
                f"blocking collective wait `{la}` outside any `comm.deadline(...)` "
                "scope hangs forever on a dead peer; arm a deadline (or baseline "
                "the site if it is legitimately unbounded)",
                detail=la,
            )
            if f is not None:
                out.append(f)
        return out


# -------------------------------------------------------------------- #
# HT108 — collective staging bypassing the seq-stamp choke point
# -------------------------------------------------------------------- #


@register
class SeqStampBypassRule(Rule):
    """Every staged collective must pass through
    ``Communication._account_bytes`` — the ONE choke point where fault
    injection, deadline refusal, byte accounting AND the flight recorder's
    sequence stamp live.  A collective staged around it is invisible to
    ``scripts/postmortem.py``: the ranks' seq streams stay aligned while
    the wire traffic diverges, which is exactly the blind spot the flight
    recorder exists to close.  Two bypass shapes are flagged in library
    code (outside ``core/communication.py`` / ``core/redistribution.py``,
    the accounting layer itself):

    - a direct call to the tiled executor ``execute_plan`` — its sanctioned
      caller is ``Communication.resplit_tiled``, which wraps it in the
      sanitizer boundary and deadline scope; anything else staging a plan
      skips that wrapping;
    - a resharding ``jax.device_put`` of an already-device-resident array
      (the raw ``._jarray``/``._parray`` plumbing) onto comm sharding
      machinery (``comm.sharding(...)``/``NamedSharding``) — the lowered
      all-to-all never reaches the choke point.  Host→device uploads
      (``device_put`` of host data) are placement, not collective traffic,
      and are not flagged."""

    code = "HT108"
    name = "seq-stamp-bypass"
    description = "collective staged around the _account_bytes seq-stamp choke point"

    # the accounting layer itself: _account_bytes lives in communication.py;
    # execute_plan (redistribution.py) byte-accounts + stamps every tile
    # through it at the executor's own staging point
    SANCTIONED_MODULES = (
        "core/communication.py",
        "core/redistribution.py",
    )
    SHARDING_MARKERS = {"sharding", "NamedSharding", "PositionalSharding"}

    def _mentions_sharding(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and sub.attr in self.SHARDING_MARKERS:
                return True
            if isinstance(sub, ast.Name) and sub.id in self.SHARDING_MARKERS:
                return True
        return False

    def _device_resident(self, node: ast.AST) -> bool:
        """Stricter than HT101's heuristic on purpose: only the raw device
        plumbing counts.  ``jnp.asarray(host_data)`` ahead of a sharded
        ``device_put`` is an upload idiom, not a resharding."""
        return any(
            isinstance(sub, ast.Attribute)
            and sub.attr in ("_jarray", "_parray", "larray")
            for sub in ast.walk(node)
        )

    def check(self, ctx: LintContext) -> Iterable[Finding]:
        if module_matches(ctx.path, self.SANCTIONED_MODULES):
            return []
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            la = last_attr(node)
            if la == "execute_plan":
                f = ctx.finding(
                    self, node,
                    "direct `execute_plan` call bypasses Communication.resplit_tiled "
                    "— the staged tiles skip the sanitizer boundary and deadline "
                    "scope of the sanctioned entry; route through comm.resplit",
                    detail="execute_plan",
                )
                if f is not None:
                    out.append(f)
            elif la == "device_put" and len(node.args) >= 2:
                if self._device_resident(node.args[0]) and self._mentions_sharding(
                    node.args[1]
                ):
                    f = ctx.finding(
                        self, node,
                        "resharding `device_put` of a device-resident array stages "
                        "an all-to-all around the `_account_bytes` choke point — "
                        "invisible to the flight recorder's seq stream and the "
                        "comm.<name> byte accounting; use Communication.resplit",
                        detail="device_put",
                    )
                    if f is not None:
                        out.append(f)
        return out
