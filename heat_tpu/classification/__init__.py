"""Placeholder — populated in this round."""
