"""Matrix decompositions (reference: ``heat/decomposition/``)."""

from .pca import PCA, IncrementalPCA
from .dmd import DMD
