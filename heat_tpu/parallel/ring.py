"""Ring pipeline primitive (reference skeleton: ``heat/spatial/distance.py::cdist``).

Each shard holds a stationary block; a rotating block circulates around the
mesh ring via ``lax.ppermute`` while a per-step function consumes
(stationary, rotating, source_index).  This is the same data movement as
ring attention's KV rotation — on TPU the permute rides the ICI torus links
and overlaps with the per-step compute (XLA async collectives).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ring_map"]


def ring_map(
    fn: Callable,
    stationary: jax.Array,
    rotating: jax.Array,
    comm,
    combine: str = "concat",
    concat_axis: int = -1,
):
    """Run ``fn(stationary_block, rotating_block, src_index)`` for every ring step.

    Must be called with GLOBAL arrays sharded along axis 0 over ``comm``'s
    mesh axis; returns the global result with per-step outputs combined
    along ``concat_axis`` (``combine='concat'``) or summed (``'sum'``).
    """
    axis = comm.axis
    size = comm.size

    def shard_fn(stat, rot):
        my = lax.axis_index(axis)

        def step(carry, i):
            rot_blk = carry
            src = (my + i) % size
            out = fn(stat, rot_blk, src)
            # rotate: receive from right neighbor (rank+1), send to left
            nxt = lax.ppermute(rot_blk, axis, [((j + 1) % size, j) for j in range(size)])
            return nxt, out

        _, outs = lax.scan(step, rot, jnp.arange(size))
        if combine == "sum":
            return jnp.sum(outs, axis=0)
        # outs: (size, *block_out) — reorder ring order back to rank order
        my_order = (my + jnp.arange(size)) % size
        inv = jnp.argsort(my_order)
        outs = outs[inv]
        return jnp.concatenate([outs[i] for i in range(size)], axis=concat_axis)

    mapped = comm.shard_map(
        shard_fn,
        in_splits=((stationary.ndim, 0), (rotating.ndim, 0)),
        out_splits=(stationary.ndim, 0),
    )
    return mapped(stationary, rotating)
