"""MNIST dataset (reference: ``heat/utils/data/mnist.py``).

The reference wraps torchvision's MNIST with rank-sliced loading.  Here:
reads the standard idx files from ``root`` when present (no network in this
environment), else generates a deterministic synthetic stand-in with the
same shapes/dtypes so the DataParallel/DASO pipelines run end-to-end.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional

import numpy as np

from ...core import factories
from ...core import random as ht_random
from .datatools import Dataset

__all__ = ["MNISTDataset"]


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(ndim)]
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(dims)


def _find(root: str, names) -> Optional[str]:
    for n in names:
        for cand in (os.path.join(root, n), os.path.join(root, "MNIST", "raw", n)):
            for suffix in ("", ".gz"):
                if os.path.exists(cand + suffix):
                    return cand + suffix
    return None


def _synthetic(n: int, seed: int):
    """Deterministic digit-like blobs: class k = gaussian bump at position k.

    The generator comes from the sanctioned ``ht_random.host_rng`` route:
    callers pass an explicit seed, and the contract (documented there) is
    that it must be rank-uniform so every SPMD process synthesizes the
    identical dataset."""
    rng = ht_random.host_rng(seed)
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    yy, xx = np.mgrid[0:28, 0:28].astype(np.float32)
    cx = 4 + 2.2 * (labels % 5)
    cy = 7 + 11 * (labels // 5)
    imgs = np.exp(
        -((xx[None] - cx[:, None, None]) ** 2 + (yy[None] - cy[:, None, None]) ** 2) / 14.0
    ).astype(np.float32)
    imgs += rng.normal(0, 0.05, imgs.shape).astype(np.float32)
    return (imgs * 255).clip(0, 255).astype(np.uint8), labels


class MNISTDataset(Dataset):
    """MNIST as a sharded Dataset (images float32 in [0,1], int32 labels)."""

    def __init__(self, root: str = "./data", train: bool = True, transform=None,
                 target_transform=None, ishuffle: bool = False, test_set: bool = False,
                 split: int = 0, synthetic_n: int = 4096):
        train = train and not test_set
        img_names = (
            ["train-images-idx3-ubyte", "train-images.idx3-ubyte"]
            if train
            else ["t10k-images-idx3-ubyte", "t10k-images.idx3-ubyte"]
        )
        lbl_names = (
            ["train-labels-idx1-ubyte", "train-labels.idx1-ubyte"]
            if train
            else ["t10k-labels-idx1-ubyte", "t10k-labels.idx1-ubyte"]
        )
        img_path = _find(root, img_names)
        lbl_path = _find(root, lbl_names)
        if img_path and lbl_path:
            imgs = _read_idx(img_path)
            labels = _read_idx(lbl_path).astype(np.int32)
            self.synthetic = False
        else:
            imgs, labels = _synthetic(synthetic_n if train else synthetic_n // 4, seed=0 if train else 1)
            self.synthetic = True
        x = imgs.astype(np.float32) / 255.0
        if transform is not None:
            x = np.asarray([transform(i) for i in x])
        images = factories.array(x, split=split)
        targets = factories.array(labels, split=split)
        super().__init__(images, labels=targets, ishuffle=ishuffle, test_set=test_set)
        self.images = images
        self.targets = targets
