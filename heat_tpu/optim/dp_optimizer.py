"""Data-parallel optimizers + DASO (reference: ``heat/optim/dp_optimizer.py``).

``DataParallelOptimizer`` wraps any optax optimizer (or a named torch-style
optimizer) and coordinates with ``nn.DataParallel``'s fused train step.

``DASO`` — Distributed Asynchronous and Selective Optimization — is the
reference's hierarchical data-parallel SGD (SURVEY §2.5/§3.5): NCCL allreduce
across each node's GPUs every step, asynchronous MPI allreduce of PARAMETERS
across nodes every ``global_skip`` steps, blended with a staleness weight.
The TPU translation per SURVEY §2.8: a 2-axis mesh ``('dcn', 'ici')`` —
every step syncs gradients over the fast ``ici`` axis only (each dcn-group
keeps its own parameter replica, sharded over 'dcn'); every ``global_skip``
steps the parameter psum over ``dcn`` is dispatched, and — because JAX
dispatch is asynchronous — consumed ``stale_steps`` later with the staleness
blend, giving the reference's fire-and-forget overlap without request objects.
"""

from __future__ import annotations

import os
import time
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


__all__ = [
    "DataParallelOptimizer",
    "DASO",
    "SGD",
    "Adam",
    "AdamW",
    "nonfinite_guard",
    "NonFiniteGuardState",
]


class NonFiniteGuardState(NamedTuple):
    """State of :func:`nonfinite_guard`: the wrapped optimizer's state plus
    DEVICE-RESIDENT step/skip counters (0-d int32 — reading them is the only
    host sync, and it happens at reporting time, never on the step path)."""

    inner_state: Any
    steps: Any
    skipped: Any


def nonfinite_guard(inner: "optax.GradientTransformation") -> "optax.GradientTransformation":
    """Wrap ``inner`` so a non-finite gradient skips the whole update ON
    DEVICE (SURVEY §5.4 guarded training): one all-reduced finite flag —
    under data parallelism the gradients arriving here are already the
    cross-replica mean, so any replica's NaN/Inf has propagated into every
    replica's copy and the flag agrees SPMD-wide — selects between the
    updated and the previous params/optimizer state with ``jnp.where``.  No
    host sync, no ``float()``: a NaN blow-up costs one skipped step, not a
    poisoned model.  Skip/step counters ride in the state and surface via
    ``DataParallelOptimizer.guard_stats`` / ``DASO.skip_stats`` /
    ``utils.profiler.counters()``."""

    def init_fn(params):
        return NonFiniteGuardState(
            inner.init(params), jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32)
        )

    def update_fn(updates, state, params=None):
        leaves = jax.tree_util.tree_leaves(updates)
        if leaves:
            finite = jnp.all(
                jnp.stack([jnp.all(jnp.isfinite(u)) for u in leaves])
            )
        else:
            finite = jnp.asarray(True)
        new_updates, new_inner = inner.update(updates, state.inner_state, params)

        def sel(new, old):
            try:
                return jnp.where(finite, new, old)
            except TypeError:
                return new  # non-numeric state leaf: keep the update

        guarded = jax.tree.map(lambda u: sel(u, jnp.zeros_like(u)), new_updates)
        inner_sel = jax.tree.map(sel, new_inner, state.inner_state)
        return guarded, NonFiniteGuardState(
            inner_sel,
            state.steps + 1,
            state.skipped + jnp.where(finite, 0, 1).astype(jnp.int32),
        )

    return optax.GradientTransformation(init_fn, update_fn)


def _guard_counters(opt_state) -> dict:
    """{'steps': int, 'skipped': int} summed over any leading replica axes
    (DASO broadcasts the counters per dcn group).  Syncs the two 0-d/1-d
    counter arrays — call at reporting boundaries only.

    Under multi-process SPMD the per-group counters are sharded over
    processes and a plain ``device_get`` would raise; this reads the
    LOCALLY addressable shards only — a per-rank view, deliberately not a
    collective (reporting must never be able to deadlock a rank whose
    peers aren't reporting), and the multi-rank telemetry merge sums the
    per-rank counter snapshots anyway."""
    if not isinstance(opt_state, NonFiniteGuardState):
        return {}

    def _local(x):
        if getattr(x, "is_fully_addressable", True):
            return jax.device_get(x)  # heatlint: disable=HT101 local-shard read, never collective
        import numpy as _np

        # one value per DISTINCT shard index: each group's counter is
        # replicated over 'ici', so raw addressable_shards holds duplicates
        uniq = {}
        for s in x.addressable_shards:
            uniq.setdefault(str(s.index), _np.asarray(s.data))
        return _np.concatenate([v.reshape(-1) for _, v in sorted(uniq.items())])

    try:
        steps, skipped = _local(opt_state.steps), _local(opt_state.skipped)
    except RuntimeError as e:
        if "deleted" not in str(e).lower():
            raise
        # the tracked tree was DONATED to a jitted step (make_train_step's
        # default) — the live state is whatever the train loop rebound
        raise RuntimeError(
            "optimizer state buffers were donated to the train step; pass "
            "the current state explicitly: guard_stats(opt_state)"
        ) from e
    import numpy as _np

    return {"steps": int(_np.max(steps)), "skipped": int(_np.sum(skipped))}


def _nontrainable_mask(params):
    """True for trainable leaves, False for buffers (``running_*`` stats of
    BatchNorm live in the params pytree but must receive no updates and no
    weight decay)."""
    import jax

    def is_trainable(path):
        return not any(
            getattr(k, "key", None) is not None and str(getattr(k, "key", "")).startswith("running_")
            for k in path
        )

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    return jax.tree_util.tree_unflatten(treedef, [is_trainable(p) for p, _ in flat])


def _mask_buffers(opt: "optax.GradientTransformation") -> "optax.GradientTransformation":
    """Mask any ``running_*`` buffer leaves out of an optax transformation."""
    return optax.masked(opt, _nontrainable_mask)


def _named_optimizer(name: str, **kw):
    table = {
        "sgd": lambda lr=0.01, momentum=0.0, weight_decay=0.0, nesterov=False: optax.chain(
            optax.add_decayed_weights(weight_decay) if weight_decay else optax.identity(),
            optax.sgd(lr, momentum=momentum if momentum else None, nesterov=nesterov),
        ),
        "adam": lambda lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0: optax.adam(
            lr, b1=betas[0], b2=betas[1], eps=eps
        ),
        "adamw": lambda lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=1e-2: optax.adamw(
            lr, b1=betas[0], b2=betas[1], eps=eps, weight_decay=weight_decay
        ),
    }
    if name.lower() not in table:
        raise ValueError(f"Unknown optimizer {name!r}")
    return table[name.lower()](**kw)


def SGD(params=None, lr: float = 0.01, momentum: float = 0.0, weight_decay: float = 0.0, nesterov: bool = False):
    """torch-style constructor returning an optax optimizer."""
    return _named_optimizer("sgd", lr=lr, momentum=momentum, weight_decay=weight_decay, nesterov=nesterov)


def Adam(params=None, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8):
    return _named_optimizer("adam", lr=lr, betas=betas, eps=eps)


def AdamW(params=None, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8, weight_decay: float = 1e-2):
    return _named_optimizer("adamw", lr=lr, betas=betas, eps=eps, weight_decay=weight_decay)


class DataParallelOptimizer:
    """Wrap an optax optimizer for use with ``nn.DataParallel``.

    Accepts an optax GradientTransformation, or a name ('sgd' | 'adam' |
    'adamw') + kwargs, mirroring ``ht.optim.DataParallelOptimizer(torch_opt)``.

    ``guard_nonfinite`` (default True) compiles a non-finite guard into every
    update — a NaN/Inf gradient skips the step on device (params and inner
    optimizer state unchanged, skip counter incremented) instead of poisoning
    the model; see :func:`nonfinite_guard`.  Counters: :meth:`guard_stats`.
    """

    def __init__(
        self,
        optimizer,
        blocking: bool = False,
        guard_nonfinite: bool = True,
        overlap_sync: bool = False,
        grad_bucket_bytes=None,
        **kwargs,
    ):
        if isinstance(optimizer, str):
            optimizer = _named_optimizer(optimizer, **kwargs)
        # buffers (BatchNorm running stats) get neither updates nor decay
        base = _mask_buffers(optimizer)
        self.guarded = bool(guard_nonfinite)
        self.optax_optimizer = nonfinite_guard(base) if self.guarded else base
        self.blocking = blocking
        # opt-in bucketed hierarchical gradient sync (core.collectives):
        # picked up by DataParallel.make_train_step / allreduce_grads; the
        # default train step is bit-exact unchanged when False
        self.overlap_sync = bool(overlap_sync)
        self.grad_bucket_bytes = grad_bucket_bytes
        self._dp = None
        self._opt_state = None
        from ..utils import profiler as _profiler

        # guard step/skip counters surface in profiler.counters() /
        # telemetry.report() like DASO's; the provider name is unique per
        # instance and the bound method is held weakly (dies with self)
        self.profiler_key = _profiler.register_counter_provider(
            "optim", self._counter_snapshot
        )

    def _counter_snapshot(self) -> dict:
        """Profiler counter provider.  Returns {} (not None — None would
        deregister) when the eagerly-tracked state is absent or was donated
        to a jitted step (the live state lives in the caller's loop)."""
        try:
            s = _guard_counters(self._opt_state)
        except RuntimeError:
            return {}
        if not s:
            return {}
        return {"steps": s["steps"], "skipped_steps": s["skipped"]}

    def _attach(self, dp) -> None:
        self._dp = dp

    def init_state(self, params):
        self._opt_state = self.optax_optimizer.init(params)
        return self._opt_state

    @property
    def state(self):
        return self._opt_state

    @state.setter
    def state(self, s):
        self._opt_state = s

    def _update(self, params, grads, opt_state):
        updates, new_state = self.optax_optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_state

    def step(self, params, grads):
        """Eager parameter update (gradients already globally averaged by XLA)."""
        from ..utils import telemetry as _tel

        if self._opt_state is None:
            self.init_state(params)
        if not _tel._ENABLED:
            new_params, self._opt_state = self._update(params, grads, self._opt_state)
            return new_params
        t0 = time.perf_counter()
        with _tel.span("optim.step"):
            new_params, self._opt_state = self._update(params, grads, self._opt_state)
        # dispatch-side latency (JAX is async — no host sync is added here)
        _tel.observe("optim.step_dispatch_s", time.perf_counter() - t0)
        return new_params

    def allreduce_grads(self, comm, stacked_grads, domains=None):
        """Bucketed hierarchical mean-allreduce of per-shard gradients
        stacked on a leading axis sharded over ``comm``'s mesh axis
        (``core.collectives.bucketed_grad_allreduce``): byte-budgeted
        buckets (``grad_bucket_bytes`` / ``ht.set_grad_bucket_budget`` /
        ``HEAT_TPU_GRAD_BUCKET_BYTES``), bucket k+1's transfer in flight
        while bucket k is consumed, two-level reduce-scatter → cross-domain
        exchange → allgather when the topology has more than one domain
        (flat allreduce otherwise).  Returns the replicated mean tree."""
        from ..core import collectives as _coll

        return _coll.bucketed_grad_allreduce(
            comm, stacked_grads, budget=self.grad_bucket_bytes, domains=domains
        )

    def zero_grad(self) -> None:
        """No-op: JAX gradients are functional (kept for API parity)."""

    def guard_stats(self, opt_state=None) -> dict:
        """{'steps', 'skipped'} of the non-finite guard.  Pass the state your
        train loop threads through a jitted step; defaults to the eagerly
        tracked one.  Syncs two scalars — call at reporting boundaries."""
        s = opt_state if opt_state is not None else self._opt_state
        return _guard_counters(s) or {"steps": 0, "skipped": 0}


class DASO:
    """Hierarchical async data parallelism on a ('dcn', 'ici') mesh.

    Parameters (reference names): ``local_optimizer``, ``total_local_comm_size``
    (size of the fast axis; default = all devices on one host ring),
    ``global_skip`` (steps between inter-group syncs), ``stale_steps``
    (dispatch-to-consume delay of the global average), ``staleness_weight``
    (blend factor for the stale global params), ``warmup_steps`` (full sync
    every step at the start), ``cooldown_epochs`` + ``total_epochs`` (fully
    synchronous final phase), ``plateau_tol`` (relative improvement below
    which :meth:`epoch_loss_logic` halves ``global_skip``).
    """

    def __init__(
        self,
        local_optimizer: DataParallelOptimizer,
        total_local_comm_size: Optional[int] = None,
        global_skip: int = 4,
        stale_steps: int = 1,
        staleness_weight: float = 0.5,
        warmup_steps: int = 4,
        cooldown_epochs: int = 0,
        total_epochs: Optional[int] = None,
        plateau_tol: float = 0.05,
        mesh=None,
        checkpoint_every: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        overlap_sync: bool = False,
        grad_bucket_bytes=None,
    ):
        if isinstance(local_optimizer, DataParallelOptimizer):
            self.local_optimizer = local_optimizer
        else:
            self.local_optimizer = DataParallelOptimizer(local_optimizer)
        self.global_skip = max(int(global_skip), 1)
        self.stale_steps = max(int(stale_steps), 0)
        self.staleness_weight = float(staleness_weight)
        self.warmup_steps = int(warmup_steps)
        self.cooldown_epochs = int(cooldown_epochs)
        self.total_epochs = total_epochs
        self.plateau_tol = float(plateau_tol)
        if self.cooldown_epochs > 0 and total_epochs is None:
            raise ValueError(
                "cooldown_epochs requires total_epochs so DASO knows when the "
                "final synchronous phase begins (reference: DASO's cooldown "
                "switches to full sync for the LAST cooldown_epochs epochs)"
            )
        self._epoch = 0
        self._best_epoch_loss = None
        self.in_cooldown = False

        if mesh is None:
            all_devs = jax.devices()
            n = len(all_devs)
            ici = total_local_comm_size or self._default_ici(n)
            if n % ici != 0:
                raise ValueError(f"total_local_comm_size {ici} must divide device count {n}")
            import numpy as np
            from jax.sharding import Mesh

            mesh = Mesh(np.asarray(all_devs).reshape(n // ici, ici), ("dcn", "ici"))
        self.mesh = mesh
        self.n_groups = mesh.shape["dcn"]
        self.ici_size = mesh.shape["ici"]
        self._step_count = 0
        self._pending = None  # (dispatched global average, due_step)
        self._train_step = None
        self._sync_step = None
        # opt-in bucketed hierarchical dcn-tier sync (core.collectives):
        # the default schedule below is bit-exact unchanged when False
        self.overlap_sync = bool(overlap_sync)
        self.grad_bucket_bytes = grad_bucket_bytes
        self._sync_comm = None  # lazy Communication(mesh, 'dcn') + bucket plan
        self._bucket_plan = None
        # opt-in durable auto-checkpoint: every K steps the full training
        # state (per-group params + opt state + step count) is written
        # atomically; resume() restores it after a preemption/crash
        if checkpoint_every is not None and checkpoint_dir is None:
            raise ValueError("checkpoint_every requires checkpoint_dir")
        self.checkpoint_every = int(checkpoint_every) if checkpoint_every else None
        self.checkpoint_dir = checkpoint_dir
        from ..utils import profiler as _profiler

        # unique per instance ("daso", "daso2", ...): concurrent optimizers
        # never shadow each other's counters in profiler.counters()
        self.profiler_key = _profiler.register_counter_provider(
            "daso", self._counter_snapshot
        )

    def _overlap_state(self):
        """Lazy (Communication('dcn'), GradBucketPlan) for the opt-in
        overlapped sync — the comm instance carries the per-bucket program
        cache and the accounting/flight-ring/deadline choke point; the plan
        is computed ONCE (leaf sizes are static for a model's lifetime), so
        steady state re-plans and recompiles nothing."""
        if self._sync_comm is None:
            from ..core import collectives as _coll
            from ..core.communication import Communication

            self._sync_comm = Communication(self.mesh, "dcn")
            leaves = jax.tree_util.tree_leaves(self._params)
            self._bucket_plan = _coll.plan_grad_buckets(
                [a.nbytes for a in leaves], self.grad_bucket_bytes
            )
        return self._sync_comm, self._bucket_plan

    def _sync_label(self) -> str:
        """``sync=`` attribute of the ``daso.step`` span: 'bucketed' when
        the opt-in overlapped path splits the sync, 'monolithic' otherwise
        (stepprof groups on it and prints STEP-OVERLAP-DELTA when a merge
        dir holds both)."""
        if not self.overlap_sync or getattr(self, "_params", None) is None:
            return "monolithic"
        return "bucketed" if self._overlap_state()[1].n_buckets > 1 else "monolithic"

    @staticmethod
    def _default_ici(n: int) -> int:
        ici = 1
        while ici * 2 <= n and n % (ici * 2) == 0 and ici * 2 <= 8:
            ici *= 2
        return ici

    # ------------------------------------------------------------------ #
    def init(self, module, key=None, sample_input=None):
        """Per-group parameter replicas: leading axis n_groups, sharded over dcn."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        if key is None:
            key = jax.random.key(0)
        params = module.init(key)
        # stack one replica per dcn group
        stacked = jax.tree.map(lambda p: jnp.broadcast_to(p[None], (self.n_groups,) + p.shape), params)
        sh = lambda p: jax.device_put(p, NamedSharding(self.mesh, P("dcn", *([None] * (p.ndim - 1)))))
        self._params = jax.tree.map(sh, stacked)
        # per-group optimizer states
        self._opt_state = jax.tree.map(
            lambda s: jnp.broadcast_to(s[None], (self.n_groups,) + s.shape) if hasattr(s, "ndim") else s,
            self.local_optimizer.optax_optimizer.init(jax.tree.map(lambda p: p[0], self._params)),
        )
        # memory-ledger registration (HT111 registrar): params and the
        # per-group optimizer moments are the long-lived buffers the
        # ROADMAP's ZeRO-1 item promises to shrink — categorized here so
        # mem.live_bytes.opt-state IS the before-number that PR must beat
        from ..utils import memledger

        if memledger.enabled():
            jax.tree.map(
                lambda p: memledger.register(
                    p, op="daso.init", site="factory", category="param"
                ),
                self._params,
            )
            jax.tree.map(
                lambda s: memledger.register(
                    s, op="daso.init", site="factory", category="opt-state"
                )
                if hasattr(s, "ndim")
                else None,
                self._opt_state,
            )
        self.module = module
        return self._params

    @property
    def parameters(self):
        return self._params

    def _build_steps(self, loss_fn):
        from ..nn.modules import _module_accepts_train

        apply = self.module.apply
        opt = self.local_optimizer.optax_optimizer
        mesh = self.mesh

        # training-mode forward for heat modules and duck-typed modules with
        # an explicit train parameter (BatchNorm batch statistics, keyed
        # Dropout); flax-style **kwargs applies are called plain
        accepts_train = _module_accepts_train(self.module)

        def fwd(p, x, key):
            if not accepts_train:
                return apply(p, x)
            if key is not None:
                return apply(p, x, train=True, key=key)
            return apply(p, x, train=True)

        from jax.sharding import PartitionSpec as P

        def shard_step(params, opt_state, x, y, key):
            """Per-(dcn, ici) mesh cell: params/opt_state are ONE group's
            replica (leading axis 1, replicated over 'ici'); x/y are this
            cell's slice of the group's batch (sharded over 'ici').

            The reference's two tiers map exactly (SURVEY §2.8):
            - per-step node-local NCCL allreduce  →  the EXPLICIT
              ``lax.pmean(grads, 'ici')`` below, a per-step collective over
              the fast axis only;
            - every-k async MPI parameter averaging  →  the dcn-tier
              ``_global_average``/``_blend`` schedule in :meth:`step`.
            """
            p0 = jax.tree.map(lambda q: q[0], params)
            s0 = jax.tree.map(lambda q: q[0], opt_state)
            x, y = x[0], y[0]  # drop the per-cell group axis (size 1)

            def loss(p):
                return loss_fn(fwd(p, x, key), y)

            lval, grads = jax.value_and_grad(loss)(p0)
            grads = jax.lax.pmean(grads, "ici")  # in-group gradient allreduce
            lval = jax.lax.pmean(lval, "ici")
            updates, new_state = opt.update(grads, s0, p0)
            new_p = optax.apply_updates(p0, updates)
            lift = lambda t: jax.tree.map(lambda q: jnp.asarray(q)[None], t)
            return lift(new_p), lift(new_state), lval[None]

        def _smap(fn, with_keys: bool):
            in_specs = [P("dcn"), P("dcn"), P("dcn", "ici"), P("dcn", "ici")]
            if with_keys:
                in_specs.append(P("dcn", "ici"))
            from ..core.communication import _jax_shard_map

            return _jax_shard_map(
                fn,
                mesh=mesh,
                in_specs=tuple(in_specs),
                out_specs=(P("dcn"), P("dcn"), P("dcn")),
                check_vma=False,
            )

        import functools

        # params/opt_state are DONATED: each step's replicas alias (or free
        # early into) the previous step's buffers, so training never holds
        # two full copies of the model state — the donate_argnums discipline
        # of a production train loop.  self._params/_opt_state are rebound
        # immediately on return, so nothing reads the consumed buffers.
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def train_step(params, opt_state, xs, ys):
            return _smap(
                lambda p, s, x, y: shard_step(p, s, x, y, None), with_keys=False
            )(params, opt_state, xs, ys)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def train_step_rng(params, opt_state, xs, ys, keys):
            # keys: (n_groups, ici) key array; each mesh cell gets its (1,1) block
            def fn(p, s, x, y, k):
                return shard_step(p, s, x, y, k[0, 0])

            return _smap(fn, with_keys=True)(params, opt_state, xs, ys, keys)

        # NOT donated: step() reads params again after dispatching the average
        @jax.jit
        def global_average(params):
            return jax.tree.map(lambda p: jnp.mean(p, axis=0, keepdims=True), params)

        # the blend CONSUMES the pre-blend replicas (donated); avg is kept —
        # a pending stale average must survive if the same tree is reused
        @functools.partial(jax.jit, donate_argnums=(0,))
        def blend(params, avg, w):
            return jax.tree.map(
                lambda p, a: (1.0 - w) * p + w * jnp.broadcast_to(a, p.shape), params, avg
            )

        self._train_step = train_step
        self._train_step_rng = train_step_rng
        self._global_average = global_average
        self._blend = blend

    def step(self, loss_fn, x, y, key=None):
        """One DASO step on a global batch (leading axis divisible by n_groups).

        Every step: per-group sync training (the 'ici' tier).  Every
        ``global_skip`` steps: dispatch the cross-group parameter average (the
        'dcn' tier); consume it ``stale_steps`` later with the staleness blend.
        During warmup, sync fully every step.  Pass ``key`` when the model
        contains stochastic layers (Dropout): each group receives a split.

        Telemetry (when enabled): each step runs under a ``daso.step`` span
        and its DISPATCH-side wall time feeds the ``daso.step_dispatch_s``
        latency histogram — the step stays asynchronous (no host sync is
        added; the returned loss is still a 0-d device array).
        """
        from ..utils import telemetry as _tel

        if not _tel._ENABLED:
            return self._step_impl(loss_fn, x, y, key)
        t0 = time.perf_counter()
        with _tel.span(
            "daso.step", step=self._step_count + 1, sync=self._sync_label()
        ):
            out = self._step_impl(loss_fn, x, y, key)
        _tel.observe("daso.step_dispatch_s", time.perf_counter() - t0)
        return out

    def _step_impl(self, loss_fn, x, y, key=None):
        if self._train_step is None:
            self._build_steps(loss_fn)
        jx = x._jarray if hasattr(x, "_jarray") else jnp.asarray(x)
        jy = y._jarray if hasattr(y, "_jarray") else jnp.asarray(y)
        g = self.n_groups
        if jx.shape[0] % (g * self.ici_size):
            raise ValueError(
                f"global batch {jx.shape[0]} must be divisible by n_groups*ici "
                f"= {g}*{self.ici_size} (each ici shard computes a batch slice)"
            )
        xs = jx.reshape((g, jx.shape[0] // g) + jx.shape[1:])
        ys = jy.reshape((g, jy.shape[0] // g) + jy.shape[1:])

        if key is not None:
            keys = jax.random.split(key, g * self.ici_size).reshape(g, self.ici_size)
            self._params, self._opt_state, losses = self._train_step_rng(
                self._params, self._opt_state, xs, ys, keys
            )
        else:
            self._params, self._opt_state, losses = self._train_step(self._params, self._opt_state, xs, ys)
        self._step_count += 1
        t = self._step_count

        if self.overlap_sync:
            from ..core import collectives as _coll

        if t <= self.warmup_steps:
            if self.overlap_sync:
                comm, plan = self._overlap_state()
                self._params = _coll.bucketed_param_sync(
                    comm, self._params, 1.0, plan=plan
                )
            else:
                avg = self._global_average(self._params)
                self._params = self._blend(self._params, avg, 1.0)  # full sync
        else:
            if self._pending is not None and t >= self._pending[1]:
                avg, _ = self._pending
                if self.overlap_sync:
                    self._params = _coll.consume_bucket_averages_all(
                        self._sync_comm, self._params, avg, self.staleness_weight
                    )
                else:
                    self._params = self._blend(self._params, avg, self.staleness_weight)
                self._pending = None
            # dispatch a new global average only when none is in flight —
            # otherwise stale_steps > global_skip would overwrite the pending
            # average forever and the dcn tier would never sync
            if t % self.global_skip == 0 and self._pending is None:
                if self.overlap_sync:
                    comm, plan = self._overlap_state()
                    if self.stale_steps == 0:
                        self._params = _coll.bucketed_param_sync(
                            comm, self._params, self.staleness_weight, plan=plan
                        )
                    else:
                        # pending payload = every bucket's average in flight at
                        # once (the stale window IS the overlap); consumed
                        # stale_steps later by consume_bucket_averages_all
                        self._pending = (
                            _coll.dispatch_all_bucket_averages(
                                comm, self._params, plan=plan
                            ),
                            t + self.stale_steps,
                        )
                else:
                    # dispatched now (async under JAX), consumed stale_steps later
                    avg = self._global_average(self._params)
                    if self.stale_steps == 0:
                        self._params = self._blend(self._params, avg, self.staleness_weight)
                    else:
                        self._pending = (avg, t + self.stale_steps)
        if self.checkpoint_every and t % self.checkpoint_every == 0:
            self.checkpoint()
        # fault site ``proc.exit`` (elastic-runtime chaos lane): arming
        # ``proc.exit:exit=N`` on one rank SIGKILLs it after its Nth step —
        # the deterministic "rank dies mid-training" the supervisor must
        # detect and recover from.  Disarmed cost: one dict miss.
        from ..utils import faults as _flt

        _flt.fire("proc.exit")
        # asynchronous loss: a 0-d device array (duck-types float) — the old
        # float(...) here was a blocking host sync on EVERY step, serializing
        # the train loop on the slowest collective.  Callers that need the
        # number call float() at their own materialization point.
        return jnp.mean(losses)

    def epoch_loss_logic(self, epoch_loss) -> int:
        """Adaptive skip schedule — call once per epoch with the epoch's mean
        loss (reference: ``heat/optim/dp_optimizer.py`` ``DASO.epoch_loss_logic``,
        SURVEY §2.5 "auto-tuned skips shrinking as loss plateaus").

        Two mechanisms, applied in priority order:

        - **cooldown**: the call ends epoch ``e``; when every remaining
          epoch lies in the final ``cooldown_epochs`` of ``total_epochs``,
          switch to fully synchronous training (``global_skip=1``, no
          staleness, full-weight blend) so the final model is exactly
          averaged — the reference's cooldown phase.
        - **plateau**: if the epoch loss failed to improve on the best loss
          so far by more than ``plateau_tol`` (relative), halve
          ``global_skip`` (floor 1): stale wide-interval averaging is cheap
          while loss falls fast, but once progress stalls the groups must
          sync tighter to keep converging.

        Returns the ``global_skip`` now in force.
        """
        self._epoch += 1
        epoch_loss = float(epoch_loss)
        if (
            self.total_epochs is not None
            and self.cooldown_epochs > 0
            and self._epoch >= self.total_epochs - self.cooldown_epochs
        ):
            self.in_cooldown = True
            self.global_skip = 1
            self.stale_steps = 0
            self.staleness_weight = 1.0
            # drop any in-flight pre-cooldown average: consuming it at the
            # cooldown's full blend weight would overwrite every replica
            # with stale parameters and discard the updates since dispatch
            self._pending = None
        elif self._best_epoch_loss is not None:
            ref = abs(self._best_epoch_loss)
            improved = (self._best_epoch_loss - epoch_loss) > self.plateau_tol * (
                ref if ref > 0 else 1.0
            )
            if not improved and self.global_skip > 1:
                self.global_skip = max(self.global_skip // 2, 1)
        if self._best_epoch_loss is None or epoch_loss < self._best_epoch_loss:
            self._best_epoch_loss = epoch_loss
        return self.global_skip

    def consolidated_params(self):
        """The cross-group averaged parameters (for eval/checkpoint)."""
        avg = self._global_average(self._params)
        return jax.tree.map(lambda a: a[0], avg)

    def zero_grad(self) -> None:
        """No-op (API parity)."""

    # ------------------------------------------------------------------ #
    # failure hardening: skip counters + durable checkpoint/resume
    # ------------------------------------------------------------------ #
    def skip_stats(self) -> dict:
        """{'steps': train steps taken, 'skipped': group-updates suppressed
        by the non-finite guard}.  The skip counter lives ON DEVICE inside
        the optimizer state (no host sync on the step path); reading here
        syncs it."""
        counters = _guard_counters(getattr(self, "_opt_state", None))
        return {"steps": self._step_count, "skipped": counters.get("skipped", 0)}

    def _counter_snapshot(self) -> dict:
        """utils.profiler counter provider (polled at reporting time)."""
        s = self.skip_stats()
        return {"steps": s["steps"], "skipped_steps": s["skipped"]}

    _CKPT_NAME = "daso_state.npz"
    _PREV_NAME = "daso_state.prev.npz"
    _META_NAME = "daso_state.meta.json"

    def _world_meta(self) -> dict:
        return {
            "n_groups": int(self.n_groups),
            "ici": int(self.ici_size),
            "devices": int(len(self.mesh.devices.ravel())),
        }

    def checkpoint(self, directory: Optional[str] = None) -> str:
        """Atomically checkpoint the full training state (per-group params,
        optimizer state incl. guard counters, step count) to
        ``<dir>/daso_state.npz`` via the durable pytree writer; returns the
        path.  Called automatically every ``checkpoint_every`` steps.

        Two durability extras for the elastic runtime:

        - the previously durable state is preserved as
          ``daso_state.prev.npz`` before the new save, so :meth:`resume`
          has a verified-fallback target when the newest file is corrupt
          (bit rot between crash and restart);
        - a ``daso_state.meta.json`` sidecar records the step count and the
          world shape (n_groups, ici, device count) so a restarted world
          can refuse a mismatched topology with a clear error instead of a
          shape crash deep inside the loader.
        """
        import json as _json
        import shutil as _shutil

        from ..core import io as _io

        d = directory or self.checkpoint_dir
        if d is None:
            raise ValueError("no checkpoint directory configured")
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, self._CKPT_NAME)
        if os.path.exists(path):
            # copy (not rename): `path` stays durable through the whole new
            # save; `prev` only ever holds a complete older state
            try:
                _shutil.copy2(path, os.path.join(d, self._PREV_NAME))
            except OSError:
                pass  # a missing fallback degrades recovery, never the save
        tree = {
            "params": self._params,
            "opt_state": self._opt_state,
            "step": jnp.asarray(self._step_count, jnp.int32),
        }
        _io.save_checkpoint(tree, path)
        meta = dict(self._world_meta(), step=int(self._step_count), time=time.time())
        mpath = os.path.join(d, self._META_NAME)
        tmp = f"{mpath}.tmp.{os.getpid()}"  # per-pid: SPMD ranks share the dir
        with open(tmp, "w") as fh:
            _json.dump(meta, fh)
        os.replace(tmp, mpath)
        return path

    def resume(self, directory: Optional[str] = None) -> bool:
        """Restore the newest auto-checkpoint (False when none exists yet).
        Call after :meth:`init` — the live params/opt-state tree provides the
        structure, dtypes and shardings the loaded leaves are validated
        against and placed back onto.  Any in-flight global average is
        dropped (it refers to pre-crash state).

        Validation and fallback (the restart-with-resume contract):

        - the sidecar's world shape must match this optimizer's mesh — a
          restarted world with a different n_groups/ici/device count gets a
          clear ``ValueError`` naming both topologies, not a shape crash;
        - a corrupt/torn ``daso_state.npz`` falls back (with a warning and
          a ``health.resume.fallbacks`` counter) to the preserved
          ``daso_state.prev.npz``; only when nothing verifies does the
          corruption error surface;
        - a sidecar step disagreeing with the restored tree's step (the
          crash window between the two writes) is warned about — the tree,
          which is what actually restores, wins.
        """
        import json as _json
        import warnings as _warnings

        from ..core import io as _io
        from ..utils import health as _health

        d = directory or self.checkpoint_dir
        if d is None:
            raise ValueError("no checkpoint directory configured")
        path = os.path.join(d, self._CKPT_NAME)
        prev = os.path.join(d, self._PREV_NAME)
        if not os.path.exists(path) and not os.path.exists(prev):
            return False
        if not hasattr(self, "_params"):
            raise RuntimeError("call init() before resume(): the live tree "
                               "provides the structure to restore into")
        meta = None
        try:
            with open(os.path.join(d, self._META_NAME)) as fh:
                meta = _json.load(fh)
        except (OSError, ValueError):
            meta = None  # pre-sidecar checkpoint or torn write: skip checks
        if meta is not None:
            want = self._world_meta()
            got = {k: int(meta.get(k, want[k])) for k in want}
            if got != want:
                raise ValueError(
                    f"checkpoint under {d!r} was written by a different world: "
                    f"checkpoint {got} vs this optimizer {want} — a restarted "
                    "world must be rebuilt with the same n_groups/ici/device "
                    "count to resume this state"
                )
        tree_like = {
            "params": self._params,
            "opt_state": self._opt_state,
            "step": jnp.asarray(0, jnp.int32),
        }
        used_fallback = False
        try:
            loaded = _io.load_checkpoint(tree_like, path)
        except (_io.CheckpointCorruptionError, FileNotFoundError) as e:
            if not os.path.exists(prev):
                raise
            _warnings.warn(
                f"newest DASO checkpoint is unusable ({e}); falling back to "
                f"the preserved previous state {prev!r}"
            )
            _health.counter_inc("health.resume.fallbacks")
            loaded = _io.load_checkpoint(tree_like, prev)
            used_fallback = True
        from jax.sharding import NamedSharding

        multiprocess = jax.process_count() > 1

        def place(new, old):
            # restore mesh shardings (params live sharded over 'dcn');
            # everything else stays UNcommitted like init() leaves it, so
            # jit remains free to co-locate it with the params
            sh = getattr(old, "sharding", None)
            if isinstance(sh, NamedSharding):
                if multiprocess:
                    # device_put of host data onto a multi-process mesh runs
                    # the NaN-hostile multihost assert_equal; build the
                    # global array from per-device slices instead (same
                    # hazard Communication.shard handles)
                    import numpy as _np

                    from ..core.communication import _array_from_callback

                    return _array_from_callback(_np.asarray(new), sh)
                return jax.device_put(jnp.asarray(new), sh)
            return jnp.asarray(new)

        self._params = jax.tree.map(place, loaded["params"], self._params)
        self._opt_state = jax.tree.map(place, loaded["opt_state"], self._opt_state)
        # re-register the REPLACEMENT buffers with the memory ledger, like
        # init() does: the leaves io.load_checkpoint registered were the
        # host-side intermediates place() discarded (their weakref deaths
        # decrement), and without this a resumed job's mem.live_bytes.param/
        # .opt-state would collapse to ~0 — losing the very before-numbers
        # the ZeRO-1 ROADMAP item measures
        from ..utils import memledger as _memledger

        if _memledger.enabled():

            def _reg(leaf, cat):
                # register covers the freshly-placed buffers; reclassify
                # corrects leaves place() passed through UNCHANGED — those
                # are the very objects load_checkpoint already registered
                # (site=ckpt defaults to `param`), and first-registration-
                # wins would otherwise leave moments misfiled as params
                _memledger.register(leaf, op="daso.resume", site="ckpt",
                                    category=cat)
                _memledger.reclassify(leaf, op="daso.resume", category=cat)

            jax.tree.map(lambda p: _reg(p, "param"), self._params)
            jax.tree.map(
                lambda s: _reg(s, "opt-state") if hasattr(s, "ndim") else None,
                self._opt_state,
            )
        self._step_count = int(loaded["step"])
        if meta is not None and not used_fallback and int(meta.get("step", -1)) not in (
            -1, self._step_count
        ):
            _warnings.warn(
                f"checkpoint sidecar records step {meta.get('step')} but the "
                f"restored tree holds step {self._step_count} (crash window "
                "between the two writes); trusting the restored tree"
            )
        self._pending = None
        # restart-with-resume marker in the flight recorder: the analyzer
        # reads `resume` events to tell a relaunched generation's ring from
        # a first boot (no-op when the recorder is disarmed)
        from ..utils import flightrec as _flightrec

        _flightrec.record_event(
            "resume", step=int(self._step_count),
            epoch=_health.restart_epoch(), fallback=bool(used_fallback),
        )
        return True
