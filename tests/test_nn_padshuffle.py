"""Padding / shuffle / adaptive-max modules vs the torch.nn oracle
(round-5 mirror completion; see heat_tpu/nn/padshuffle.py)."""

import numpy as np
import pytest
import torch

import heat_tpu as ht

RNG = np.random.default_rng(11)


def _x(spatial):
    shape = {1: (2, 3, 9), 2: (2, 3, 6, 7), 3: (2, 3, 4, 5, 6)}[spatial]
    return RNG.normal(size=shape).astype(np.float32)


PADS = [
    ("ZeroPad1d", 1, 2), ("ZeroPad1d", 1, (1, 3)),
    ("ZeroPad2d", 2, 1), ("ZeroPad2d", 2, (1, 2, 0, 3)),
    ("ZeroPad3d", 3, (1, 0, 2, 1, 0, 2)),
    ("ReflectionPad1d", 1, 2), ("ReflectionPad2d", 2, (1, 2, 0, 3)),
    ("ReflectionPad3d", 3, 1),
    ("ReplicationPad1d", 1, 3), ("ReplicationPad2d", 2, (2, 0, 1, 1)),
    ("ReplicationPad3d", 3, 1),
    ("CircularPad1d", 1, 2), ("CircularPad2d", 2, (1, 2, 3, 0)),
    ("CircularPad3d", 3, 1),
]


@pytest.mark.parametrize("name,spatial,pad", PADS,
                         ids=[f"{n}-{p}" for n, _, p in PADS])
def test_pad_matches_torch(name, spatial, pad):
    x = _x(spatial)
    got = np.asarray(getattr(ht.nn, name)(pad).apply((), x))
    want = getattr(torch.nn, name)(pad)(torch.from_numpy(x)).numpy()
    np.testing.assert_array_equal(got, want)


def test_constant_pad_value():
    x = _x(2)
    got = np.asarray(ht.nn.ConstantPad2d((1, 2, 0, 1), 7.5).apply((), x))
    want = torch.nn.ConstantPad2d((1, 2, 0, 1), 7.5)(torch.from_numpy(x)).numpy()
    np.testing.assert_array_equal(got, want)
    with pytest.raises(ValueError, match="per-side"):
        ht.nn.ConstantPad2d((1, 2, 3))


def test_pixel_shuffle_roundtrip_matches_torch():
    x = RNG.normal(size=(2, 12, 3, 4)).astype(np.float32)
    got = np.asarray(ht.nn.PixelShuffle(2).apply((), x))
    want = torch.nn.PixelShuffle(2)(torch.from_numpy(x)).numpy()
    np.testing.assert_array_equal(got, want)
    back = np.asarray(ht.nn.PixelUnshuffle(2).apply((), got))
    np.testing.assert_array_equal(back, x)
    wantu = torch.nn.PixelUnshuffle(2)(torch.from_numpy(got)).numpy()
    np.testing.assert_array_equal(back, wantu)
    with pytest.raises(ValueError, match="divisible"):
        ht.nn.PixelShuffle(5).apply((), x)


def test_channel_shuffle_matches_torch():
    x = RNG.normal(size=(2, 8, 3, 3)).astype(np.float32)
    got = np.asarray(ht.nn.ChannelShuffle(4).apply((), x))
    want = torch.nn.ChannelShuffle(4)(torch.from_numpy(x)).numpy()
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("name,spatial,out", [
    ("AdaptiveMaxPool1d", 1, 3), ("AdaptiveMaxPool2d", 2, (3, 7)),
    ("AdaptiveMaxPool3d", 3, (2, 5, 3)), ("AdaptiveAvgPool3d", 3, (2, 1, 2)),
])
def test_adaptive_pools_match_torch(name, spatial, out):
    x = _x(spatial)
    got = np.asarray(getattr(ht.nn, name)(out).apply((), x))
    want = getattr(torch.nn, name)(out)(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_adaptive_divisibility_raises():
    with pytest.raises(ValueError, match="divisible"):
        ht.nn.AdaptiveMaxPool1d(4).apply((), _x(1))  # 9 rows / 4


def test_negative_padding_crops_like_torch():
    x = _x(2)
    for pad in ((-1, 1, 0, 0), (-1, -2, 1, -1)):
        got = np.asarray(ht.nn.ZeroPad2d(pad).apply((), x))
        want = torch.nn.ZeroPad2d(pad)(torch.from_numpy(x)).numpy()
        np.testing.assert_array_equal(got, want)


def test_pixel_shuffle_unbatched_and_5d():
    x3 = RNG.normal(size=(12, 3, 4)).astype(np.float32)
    got = np.asarray(ht.nn.PixelShuffle(2).apply((), x3))
    want = torch.nn.PixelShuffle(2)(torch.from_numpy(x3)).numpy()
    np.testing.assert_array_equal(got, want)
    x5 = RNG.normal(size=(2, 2, 8, 3, 4)).astype(np.float32)
    got = np.asarray(ht.nn.PixelShuffle(2).apply((), x5))
    want = torch.nn.PixelShuffle(2)(torch.from_numpy(x5)).numpy()
    np.testing.assert_array_equal(got, want)
    back = np.asarray(ht.nn.PixelUnshuffle(2).apply((), got))
    np.testing.assert_array_equal(back, x5)


def test_adaptive_output_size_forms():
    x = _x(2)  # (2, 3, 6, 7)
    # list form and torch's None (= keep that dim)
    got = np.asarray(ht.nn.AdaptiveMaxPool2d([3, 7]).apply((), x))
    want = torch.nn.AdaptiveMaxPool2d([3, 7])(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(got, want, atol=1e-6)
    got = np.asarray(ht.nn.AdaptiveMaxPool2d((3, None)).apply((), x))
    want = torch.nn.AdaptiveMaxPool2d((3, None))(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(got, want, atol=1e-6)
    with pytest.raises(ValueError, match="entries"):
        ht.nn.AdaptiveMaxPool2d((3, 4, 5))
