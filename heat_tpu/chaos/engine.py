"""The chaos campaign engine: run schedules under the real Supervisor,
judge them with the invariant oracles, journal the verdicts.

One *run* = one schedule armed (via ``HEAT_TPU_FAULTS``, computed per
``(rank, generation)`` by :func:`schedule.env_for`) against the fast-tier
harness workload (``chaos/worker.py``) supervised by the REAL
``parallel.supervisor.Supervisor`` — real process death, real heartbeat
staleness detection, real restart-with-resume, real journal recovery.
After the supervisor returns, the oracle suite audits the run directory
and the verdict (which oracles failed, if any) is appended to a
crash-durable campaign journal.

One *campaign* = ``count`` schedules drawn from ``(seed, 0..count-1)``.
The journal header pins the seed; records are keyed by index, so a
killed campaign resumes by replaying the journal and skipping finished
indices — re-running any index reproduces the identical schedule and,
modulo wall-clock noise in timing fields the verdict deliberately
excludes, the identical verdict row.

Stdlib-only, standalone-loadable, never imports jax.
"""

from __future__ import annotations

import importlib.util
import json
import os
import shutil
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional

__all__ = [
    "run_schedule",
    "CampaignJournal",
    "run_campaign",
    "verdict_table",
    "VERDICT_FIELDS",
]

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.normpath(os.path.join(_HERE, "..", ".."))
_WORKER = os.path.join(_HERE, "worker.py")


def _load(name: str, relpath: str):
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, relpath)
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


if __package__:
    from . import oracles as _oracles
    from . import schedule as _schedule
    from ..parallel import supervisor as _sup_mod
else:  # spec-loaded standalone (scripts/chaoscamp.py)
    _schedule = _load("heat_chaos_schedule", "heat_tpu/chaos/schedule.py")
    _oracles = _load("heat_chaos_oracles", "heat_tpu/chaos/oracles.py")
    _sup_mod = _load("heat_chaos_supervisor", "heat_tpu/parallel/supervisor.py")


# ---------------------------------------------------------------------- #
# one schedule -> one supervised run -> one oracle verdict
# ---------------------------------------------------------------------- #
# the verdict row is DETERMINISTIC: same (seed, index) -> byte-identical
# row on any two runs.  Timing, pids, paths and other wall-clock noise
# are deliberately excluded — two same-seed campaigns must produce
# identical verdict tables (the acceptance bar for the whole engine).
VERDICT_FIELDS = (
    "index", "seed", "digest", "workload", "ranks", "jobs",
    "faults", "ok", "fails",
)

# fast-tier supervision envelope: the harness beats after every job, so
# 2.5 s of silence IS a wedge (a hang fault parks the rank forever); the
# generation deadline is a backstop against pathologies the heartbeat
# cannot see, sized generously above the worst legal schedule (delays
# are capped at ~0.1 s/firing by the generator's envelope).
_HB_TIMEOUT = 2.5
_GEN_DEADLINE = 90.0


def _fault_tokens(schedule: dict) -> List[str]:
    return [
        f"{f['site']}:{f['mode']}={f['value']}@r{f['rank']}g{f['generation']}"
        for f in schedule.get("faults", ())
    ]


def run_schedule(
    schedule: dict,
    run_dir: str,
    *,
    keep: bool = False,
    python: Optional[str] = None,
) -> dict:
    """Execute one schedule under the Supervisor and judge it.

    Returns the verdict row: ``ok`` is True iff every oracle passed;
    ``fails`` lists the failing oracle names; ``oracles`` carries each
    oracle's detail string (True, or the failure explanation).  The run
    directory (journals, per-rank logs, flight rings, reports) survives
    for failing runs — it IS the evidence — and is deleted for passing
    runs unless ``keep``.
    """
    _schedule.validate_schedule(schedule)
    # the run dir is this run's scratch: stale evidence from a previous
    # run of the same schedule (a kept replay dir, a re-run index) would
    # feed the recovery path and the oracles someone ELSE's journals —
    # every run starts from nothing, or replays aren't independent
    shutil.rmtree(run_dir, ignore_errors=True)
    os.makedirs(run_dir, exist_ok=True)
    hb_dir = os.path.join(run_dir, "hb")
    fr_dir = os.path.join(run_dir, "fr")
    exe = python or sys.executable

    def spawn(rank: int, epoch: int, port: int) -> subprocess.Popen:
        env = {
            k: v for k, v in os.environ.items()
            if k != "HEAT_TPU_FAULTS" and not k.startswith("CHAOS_")
        }
        env["CHAOS_DIR"] = run_dir
        env["CHAOS_WORKLOAD"] = schedule["workload"]
        env["CHAOS_JOBS"] = str(schedule["jobs"])
        env["HEAT_TPU_RESTART_EPOCH"] = str(epoch)
        env["PYTHONUNBUFFERED"] = "1"
        armed = _schedule.env_for(schedule, rank, epoch)
        if armed:
            env["HEAT_TPU_FAULTS"] = armed
        log = open(
            os.path.join(run_dir, f"log_rank{rank}_epoch{epoch}.txt"), "ab"
        )
        try:
            return subprocess.Popen(
                [exe, _WORKER, str(rank)],
                env=env, cwd=run_dir,
                stdout=log, stderr=subprocess.STDOUT,
            )
        finally:
            log.close()  # the child holds its own copy of the fd

    if schedule["workload"] == "fed":
        job_journal = os.path.join(run_dir, "fed.jsonl")
    else:
        job_journal = os.path.join(run_dir, "journal_rank0.jsonl")

    sup = _sup_mod.Supervisor(
        spawn,
        schedule["ranks"],
        heartbeat_dir=hb_dir,
        heartbeat_timeout=_HB_TIMEOUT,
        restart_budget=_schedule.lethal_count(schedule),
        generation_deadline=_GEN_DEADLINE,
        poll_interval=0.05,
        grace=0.5,
        flightrec_dir=fr_dir,
        job_journal=job_journal,
    )
    result = sup.run()
    report = result.report()
    oracle_results = _oracles.run_oracles(run_dir, schedule, report)
    fails = _oracles.failing(oracle_results)
    verdict = {
        "index": schedule["index"],
        "seed": schedule["seed"],
        "digest": _schedule.schedule_digest(schedule),
        "workload": schedule["workload"],
        "ranks": schedule["ranks"],
        "jobs": schedule["jobs"],
        "faults": _fault_tokens(schedule),
        "ok": not fails,
        "fails": fails,
        "oracles": {
            r["oracle"]: (True if r["ok"] else r["detail"])
            for r in oracle_results
        },
        "sup": {
            "ok": report.get("ok"),
            "restarts": report.get("restarts"),
            "generations": report.get("generations"),
            "failures": report.get("failures"),
        },
        "run_dir": run_dir,
    }
    if not fails and not keep:
        shutil.rmtree(run_dir, ignore_errors=True)
        verdict["run_dir"] = None
    return verdict


# ---------------------------------------------------------------------- #
# the campaign journal: crash-durable, resumable by index
# ---------------------------------------------------------------------- #
class CampaignJournal:
    """Append-only JSONL verdict log with a tmp+rename header.

    The header pins the campaign identity ``(seed, count, tier)``; every
    verdict and reproducer is one flushed line.  ``resume()`` replays an
    existing journal — refusing a seed mismatch, because appending
    verdicts of a DIFFERENT campaign to this journal would poison the
    determinism audit — and returns the set of finished indices.
    """

    SCHEMA = 1

    def __init__(self, path: str, *, seed: int, count: int, tier: str):
        self.path = path
        self.meta = {
            "type": "meta", "schema": self.SCHEMA,
            "seed": int(seed), "count": int(count), "tier": str(tier),
        }
        if not os.path.exists(path):
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                fh.write(json.dumps(self.meta, sort_keys=True) + "\n")
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        self._fh = open(path, "a")

    def append(self, record: dict) -> None:
        self._fh.write(json.dumps(record, sort_keys=True) + "\n")
        self._fh.flush()

    def close(self) -> None:
        self._fh.close()

    @staticmethod
    def replay(path: str) -> dict:
        """``{"meta": header, "verdicts": {index: row}, "repros": [...]}``
        — last verdict per index wins; a torn trailing line (the crash
        the tmp+rename header and line-granular appends are armor
        against) is skipped, not fatal."""
        meta, verdicts, repros = None, {}, []
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail
                if rec.get("type") == "meta":
                    meta = rec
                elif rec.get("type") == "verdict":
                    verdicts[int(rec["index"])] = rec
                elif rec.get("type") == "repro":
                    repros.append(rec)
        return {"meta": meta, "verdicts": verdicts, "repros": repros}

    def resume(self) -> Dict[int, dict]:
        state = self.replay(self.path)
        meta = state["meta"]
        if meta is None:
            raise ValueError(f"{self.path}: no journal header")
        for key in ("seed", "tier"):
            if meta.get(key) != self.meta[key]:
                raise ValueError(
                    f"{self.path}: journal is campaign "
                    f"{key}={meta.get(key)!r}, not {self.meta[key]!r} — "
                    "refusing to mix campaigns in one journal"
                )
        return state["verdicts"]


# ---------------------------------------------------------------------- #
# the campaign runner
# ---------------------------------------------------------------------- #
def run_campaign(
    seed: int,
    count: int,
    out_dir: str,
    *,
    shrink_failures: bool = True,
    keep: bool = False,
    resume: bool = False,
    sites: Optional[tuple] = None,
    modes: tuple = ("train", "serve", "fed"),
    log: Callable[[str], None] = lambda s: print(s, flush=True),
) -> dict:
    """Sweep schedules ``(seed, 0..count-1)`` through :func:`run_schedule`.

    Verdicts land in ``<out_dir>/campaign.jsonl`` as they finish; with
    ``resume`` an existing journal's finished indices are skipped (the
    generator re-derives identical schedules for the rest).  Every
    failing schedule is auto-shrunk to its minimal reproducer and the
    greppable ``CHAOS-REPRO`` line is both printed and journaled.

    Returns ``{"rows": [verdict...], "failures": [...], "repro_lines":
    [...], "table": str}``.
    """
    os.makedirs(out_dir, exist_ok=True)
    journal = CampaignJournal(
        os.path.join(out_dir, "campaign.jsonl"),
        seed=seed, count=count, tier="fast",
    )
    done = journal.resume() if resume else {}
    rows: List[dict] = []
    repro_lines: List[str] = []
    t0 = time.monotonic()
    try:
        for i in range(int(count)):
            if i in done:
                rows.append(done[i])
                continue
            sched = _schedule.generate_schedule(
                seed, i, modes=modes, sites=sites
            )
            run_dir = os.path.join(out_dir, f"run{i:04d}")
            verdict = run_schedule(sched, run_dir, keep=keep)
            verdict["type"] = "verdict"
            journal.append(verdict)
            rows.append(verdict)
            status = "ok" if verdict["ok"] else f"FAIL({','.join(verdict['fails'])})"
            log(
                f"CHAOS-RUN idx={i} workload={sched['workload']} "
                f"faults=[{' '.join(_fault_tokens(sched))}] {status}"
            )
            if not verdict["ok"] and shrink_failures:
                shrink = _shrink_mod()
                minimal, fail = shrink.shrink(
                    sched,
                    lambda s, _dir=out_dir, _i=i: _shrink_probe(s, _dir, _i),
                    log=log,
                )
                line = _schedule.repro_line(minimal, fail)
                log(line)
                repro_lines.append(line)
                journal.append({
                    "type": "repro", "index": i, "fail": fail, "line": line,
                    "schedule": minimal,
                })
    finally:
        journal.close()
    failures = [r for r in rows if not r.get("ok")]
    return {
        "rows": rows,
        "failures": failures,
        "repro_lines": repro_lines,
        "table": verdict_table(rows),
        "elapsed_s": round(time.monotonic() - t0, 1),
    }


def _shrink_mod():
    if __package__:
        from . import shrink as s
        return s
    return _load("heat_chaos_shrink", "heat_tpu/chaos/shrink.py")


_probe_n = [0]


def _shrink_probe(sched: dict, out_dir: str, index: int) -> List[str]:
    """The shrinker's run function: execute a candidate schedule in a
    scratch dir, return the failing oracle names, clean up regardless —
    shrink probes are evidence-gathering, not evidence."""
    _probe_n[0] += 1
    d = os.path.join(out_dir, f"shrink{index:04d}_{_probe_n[0]:03d}")
    try:
        v = run_schedule(sched, d, keep=False)
        return list(v["fails"])
    finally:
        shutil.rmtree(d, ignore_errors=True)


# ---------------------------------------------------------------------- #
# the verdict table
# ---------------------------------------------------------------------- #
def verdict_table(rows: List[dict]) -> str:
    """Deterministic fixed-order text table — two same-seed campaigns
    must render byte-identical tables (no timing, no paths)."""
    header = ("idx", "workload", "r", "jobs", "faults", "verdict")
    body = []
    for r in sorted(rows, key=lambda r: int(r["index"])):
        body.append((
            str(r["index"]),
            str(r["workload"]),
            str(r["ranks"]),
            str(r["jobs"]),
            " ".join(r.get("faults", ())) or "-",
            "ok" if r.get("ok") else "FAIL:" + ",".join(r.get("fails", ())),
        ))
    widths = [
        max(len(header[c]), *(len(row[c]) for row in body)) if body
        else len(header[c])
        for c in range(len(header))
    ]
    lines = [
        "  ".join(h.ljust(w) for h, w in zip(header, widths)).rstrip(),
        "  ".join("-" * w for w in widths),
    ]
    for row in body:
        lines.append(
            "  ".join(v.ljust(w) for v, w in zip(row, widths)).rstrip()
        )
    n_ok = sum(1 for r in rows if r.get("ok"))
    lines.append(f"CHAOS-CAMPAIGN schedules={len(rows)} ok={n_ok} "
                 f"fail={len(rows) - n_ok}")
    return "\n".join(lines)
