"""I/O, FFT, sparse, signal, tiling tests (reference: test_io.py,
heat/fft/tests, heat/sparse/tests, test_signal.py, test_tiling.py)."""

import numpy as np
import pytest

import heat_tpu as ht

from test_suites.basic_test import TestCase


class TestSignal(TestCase):
    def test_convolve_modes(self):
        rng = np.random.default_rng(0)
        a = rng.normal(size=37).astype(np.float32)
        v = np.array([1.0, 2.0, 3.0], dtype=np.float32)
        for split in [None, 0]:
            for mode in ("full", "same", "valid"):
                got = ht.convolve(ht.array(a, split=split), ht.array(v), mode=mode)
                np.testing.assert_allclose(got.numpy(), np.convolve(a, v, mode=mode), atol=1e-4)

    def test_convolve_int_and_swap(self):
        a = np.array([1, 2, 3], dtype=np.int32)
        v = np.array([0, 1, 0, 0, 0], dtype=np.int32)
        got = ht.convolve(ht.array(a), ht.array(v), mode="full")
        np.testing.assert_array_equal(got.numpy(), np.convolve(a, v))
        assert got.dtype == ht.int32

    def test_convolve_errors(self):
        with pytest.raises(ValueError):
            ht.convolve(ht.ones((2, 2)), ht.ones(3))
        with pytest.raises(ValueError):
            ht.convolve(ht.ones(5), ht.ones(3), mode="bogus")

    def test_convolve2d(self):
        from scipy.signal import convolve2d as sconv

        rng = np.random.default_rng(1)
        a = rng.normal(size=(9, 9)).astype(np.float32)
        v = rng.normal(size=(3, 3)).astype(np.float32)
        for mode in ("full", "same", "valid"):
            got = ht.core.signal.convolve2d(ht.array(a, split=0), ht.array(v), mode=mode)
            np.testing.assert_allclose(got.numpy(), sconv(a, v, mode=mode), atol=1e-3)


class TestFFT(TestCase):
    def setup_method(self, method):
        self.x = np.random.default_rng(2).normal(size=(8, 16)).astype(np.float32)

    def test_fft_family(self):
        for split in [None, 0, 1]:
            a = ht.array(self.x, split=split)
            np.testing.assert_allclose(ht.fft.fft(a).numpy(), np.fft.fft(self.x), atol=1e-3)
            np.testing.assert_allclose(ht.fft.rfft(a).numpy(), np.fft.rfft(self.x), atol=1e-3)
            np.testing.assert_allclose(
                ht.fft.fft(a, axis=0).numpy(), np.fft.fft(self.x, axis=0), atol=1e-3
            )

    def test_roundtrips(self):
        a = ht.array(self.x, split=0)
        np.testing.assert_allclose(ht.fft.ifft(ht.fft.fft(a)).numpy().real, self.x, atol=1e-4)
        np.testing.assert_allclose(ht.fft.irfft(ht.fft.rfft(a), n=16).numpy(), self.x, atol=1e-4)
        np.testing.assert_allclose(
            ht.fft.ifftn(ht.fft.fftn(a)).numpy().real, self.x, atol=1e-4
        )

    def test_freq_shift(self):
        np.testing.assert_allclose(ht.fft.fftfreq(16).numpy(), np.fft.fftfreq(16), atol=1e-6)
        np.testing.assert_allclose(ht.fft.rfftfreq(16).numpy(), np.fft.rfftfreq(16), atol=1e-6)
        a = ht.array(self.x, split=0)
        np.testing.assert_allclose(ht.fft.fftshift(a).numpy(), np.fft.fftshift(self.x))

    def test_split_preserved(self):
        a = ht.array(self.x, split=1)
        assert ht.fft.fft(a).split == 1


class TestHermitianN(TestCase):
    """hfftn/ihfftn (+ hfft2/ihfft2 with explicit shape) against the
    torch.fft oracle — the reference inherits these whole from torch
    (SURVEY §2.2 fft row); ours composes them per axis (VERDICT r4
    missing #2)."""

    def setup_method(self, method):
        rng = np.random.default_rng(7)
        self.real = rng.normal(size=(6, 10)).astype(np.float32)
        self.cplx = (rng.normal(size=(6, 9)) + 1j * rng.normal(size=(6, 9))).astype(np.complex64)

    @pytest.mark.parametrize("norm", [None, "ortho", "forward"])
    def test_hfftn_matches_torch(self, norm):
        import torch

        want = torch.fft.hfftn(torch.from_numpy(self.cplx), norm=norm).numpy()
        for split in [None, 0, 1]:
            got = ht.fft.hfftn(ht.array(self.cplx, split=split), norm=norm)
            np.testing.assert_allclose(got.numpy(), want, atol=1e-3)
            assert got.split == split

    @pytest.mark.parametrize("norm", [None, "ortho", "forward"])
    def test_ihfftn_matches_torch(self, norm):
        import torch

        want = torch.fft.ihfftn(torch.from_numpy(self.real), norm=norm).numpy()
        got = ht.fft.ihfftn(ht.array(self.real, split=0), norm=norm)
        np.testing.assert_allclose(got.numpy(), want, atol=1e-4)

    def test_hfftn_with_shape_and_axes(self):
        import torch

        want = torch.fft.hfftn(torch.from_numpy(self.cplx), s=(8, 12), dim=(0, 1)).numpy()
        got = ht.fft.hfftn(ht.array(self.cplx), s=(8, 12), axes=(0, 1))
        np.testing.assert_allclose(got.numpy(), want, atol=1e-3)
        # s given, axes omitted: the last len(s) axes are transformed
        want = torch.fft.hfftn(torch.from_numpy(self.cplx), s=(12,)).numpy()
        got = ht.fft.hfftn(ht.array(self.cplx), s=(12,))
        np.testing.assert_allclose(got.numpy(), want, atol=1e-3)

    def test_hfft2_shape_no_longer_raises(self):
        import torch

        want = torch.fft.hfft2(torch.from_numpy(self.cplx), s=(6, 12)).numpy()
        got = ht.fft.hfft2(ht.array(self.cplx), s=(6, 12))
        np.testing.assert_allclose(got.numpy(), want, atol=1e-3)
        want = torch.fft.ihfft2(torch.from_numpy(self.real), s=(8, 10)).numpy()
        got = ht.fft.ihfft2(ht.array(self.real), s=(8, 10))
        np.testing.assert_allclose(got.numpy(), want, atol=1e-4)

    def test_roundtrip(self):
        """ihfftn(hfftn-sized real signal) recovers the one-sided spectrum."""
        spec = ht.fft.ihfftn(ht.array(self.real, split=0))
        back = ht.fft.hfftn(spec, s=self.real.shape)
        np.testing.assert_allclose(back.numpy(), self.real, atol=1e-4)

    def test_validation(self):
        with pytest.raises(ValueError, match="same length"):
            ht.fft.hfftn(ht.array(self.cplx), s=(4,), axes=(0, 1))
        # default (-2, -1) axes alias on a 1-D input — torch raises too;
        # a silent double transform on axis 0 would be wrong
        with pytest.raises(ValueError, match="unique"):
            ht.fft.hfft2(ht.array(self.cplx[0]))
        with pytest.raises(ValueError, match="unique"):
            ht.fft.hfftn(ht.array(self.cplx), axes=(0, 0))


@pytest.mark.mp  # IO round-trips cross the process seam via token-ring /
# per-chunk writers (conftest redirects tmp_path to a rank-shared directory)
class TestIO(TestCase):
    def test_hdf5_roundtrip(self, tmp_path):
        pytest.importorskip("h5py")
        p = str(tmp_path / "x.h5")
        a = ht.random.randn(16, 4, split=0)
        ht.save(a, p, "data")
        for split in [None, 0, 1]:
            b = ht.load(p, "data", split=split)
            np.testing.assert_allclose(b.numpy(), a.numpy(), atol=1e-6)
            assert b.split == split

    def test_csv_roundtrip(self, tmp_path):
        p = str(tmp_path / "x.csv")
        a = ht.random.randn(10, 3, split=0)
        ht.save(a, p)
        b = ht.load(p, split=0)
        np.testing.assert_allclose(b.numpy(), a.numpy(), atol=1e-5)

    @pytest.mark.mp_unsafe  # raw open() write: every rank would write the
    # same path unsynchronized (the token-ring writers exist for this)
    def test_csv_header(self, tmp_path):
        p = str(tmp_path / "h.csv")
        with open(p, "w") as f:
            f.write("col1,col2\n1.0,2.0\n3.0,4.0\n")
        b = ht.load_csv(p, header_lines=1)
        np.testing.assert_allclose(b.numpy(), [[1, 2], [3, 4]])

    @pytest.mark.mp_unsafe  # raw np.save + mkdir from every rank
    def test_npy(self, tmp_path):
        p = str(tmp_path / "x.npy")
        data = np.arange(20.0, dtype=np.float32).reshape(5, 4)
        np.save(p, data)
        b = ht.load(p, split=0)
        np.testing.assert_array_equal(b.numpy(), data)
        # directory of npy files
        d = tmp_path / "dir"
        d.mkdir()
        np.save(str(d / "a.npy"), data)
        np.save(str(d / "b.npy"), data + 20)
        c = ht.core.io.load_npy_from_path(str(d), split=0)
        assert c.shape == (10, 4)

    def test_netcdf_roundtrip(self, tmp_path):
        p = str(tmp_path / "t.nc")
        x = ht.random.randn(12, 5, split=0)
        ht.save_netcdf(x, p, "temp")
        y = ht.load_netcdf(p, "temp", split=0)
        np.testing.assert_allclose(y.numpy(), x.numpy(), rtol=1e-6)
        # extension dispatch and resplit-on-load
        z = ht.load(p, "temp", split=1)
        assert z.split == 1
        np.testing.assert_allclose(z.numpy(), x.numpy(), rtol=1e-6)
        assert ht.supports_netcdf()
        # the h5py-backed writer must attach netCDF-style dimension scales
        import h5py

        with h5py.File(p, "r") as f:
            assert "temp_dim0" in f and "temp_dim1" in f

    def test_unsupported_ext(self, tmp_path):
        with pytest.raises(ValueError):
            ht.load(str(tmp_path / "x.xyz"))

    def test_checkpoint_pytree(self, tmp_path):
        p = str(tmp_path / "ck.npz")
        tree = {"layer": {"w": np.ones((3, 2), np.float32)}, "step": np.asarray(7)}
        ht.core.io.save_checkpoint(tree, p)
        back = ht.core.io.load_checkpoint(tree, p)
        np.testing.assert_array_equal(np.asarray(back["layer"]["w"]), tree["layer"]["w"])
        assert int(back["step"]) == 7

    def test_zarr_roundtrip(self, tmp_path):
        """zarr v2 directory format (VERDICT r4 missing #3): per-device
        chunk files, ragged extents stored as fill-padded edge chunks."""
        import json
        import os

        d = str(tmp_path / "x.zarr")
        a = ht.reshape(ht.arange(101 * 3, dtype=ht.float32, split=0), (101, 3))
        ht.save(a, d)
        meta = json.load(open(os.path.join(d, ".zarray")))
        assert meta["zarr_format"] == 2 and meta["compressor"] is None
        assert meta["shape"] == [101, 3]
        p = a.comm.size
        chunk = -(-101 // p)
        assert meta["chunks"] == [chunk, 3]
        # every chunk file is the full nominal size (zarr edge convention)
        for f in os.listdir(d):
            if f != ".zarray":
                assert os.path.getsize(os.path.join(d, f)) == chunk * 3 * 4
        for split in [0, 1, None]:
            b = ht.load(d, split=split)
            assert b.split == split and b.shape == (101, 3)
            np.testing.assert_array_equal(b.numpy(), a.numpy())

    def test_zarr_replicated_int_and_dispatch(self, tmp_path):
        d = str(tmp_path / "i.zarr")
        x = ht.array(np.arange(24, dtype=np.int32).reshape(4, 6))
        ht.save(x, d)
        b = ht.load(d, split=0)
        assert b.dtype == ht.int32
        np.testing.assert_array_equal(b.numpy(), x.numpy())

    @pytest.mark.mp_unsafe  # hand-rolled .zarray writes from every rank
    def test_zarr_validation(self, tmp_path):
        import json
        import os

        with pytest.raises(ValueError, match="zarr v2 representation"):
            ht.save(ht.ones(8, dtype=ht.bfloat16, split=0), str(tmp_path / "b.zarr"))
        d = str(tmp_path / "c.zarr")
        os.makedirs(d)
        meta = {"zarr_format": 2, "shape": [4], "chunks": [4], "dtype": "<f4",
                "compressor": {"id": "blosc"}, "fill_value": 0, "order": "C",
                "filters": None}
        json.dump(meta, open(os.path.join(d, ".zarray"), "w"))
        with pytest.raises(ValueError, match="compressed"):
            ht.load(d)
        # absent chunk files read as fill_value (zarr convention)
        meta["compressor"] = None
        json.dump(meta, open(os.path.join(d, ".zarray"), "w"))
        np.testing.assert_array_equal(ht.load(d).numpy(), np.zeros(4, np.float32))
        # "fill_value": null is legal v2 metadata — read as 0, even for ints
        meta["fill_value"] = None
        meta["dtype"] = "<i4"
        json.dump(meta, open(os.path.join(d, ".zarray"), "w"))
        np.testing.assert_array_equal(ht.load(d).numpy(), np.zeros(4, np.int32))


class TestSparse(TestCase):
    def setup_method(self, method):
        import scipy.sparse as sp

        self.scipy_mat = sp.random(16, 8, density=0.25, format="csr", random_state=0, dtype=np.float32)

    def test_factory_and_todense(self):
        s = ht.sparse.sparse_csr_matrix(self.scipy_mat, split=0)
        assert s.shape == (16, 8)
        assert s.nnz == self.scipy_mat.nnz
        assert s.split == 0
        np.testing.assert_allclose(s.todense().numpy(), self.scipy_mat.toarray())

    def test_from_dense(self):
        dense = np.array([[1.0, 0.0], [0.0, 2.0]], dtype=np.float32)
        s = ht.sparse.sparse_csr_matrix(dense)
        assert s.nnz == 2
        np.testing.assert_allclose(s.todense().numpy(), dense)

    def test_csr_attributes(self):
        s = ht.sparse.sparse_csr_matrix(self.scipy_mat)
        np.testing.assert_array_equal(np.asarray(s.indptr), self.scipy_mat.indptr)

    def test_arithmetic(self):
        s1 = ht.sparse.sparse_csr_matrix(self.scipy_mat)
        s2 = ht.sparse.sparse_csr_matrix(self.scipy_mat * 2)
        np.testing.assert_allclose((s1 + s2).todense().numpy(), 3 * self.scipy_mat.toarray(), atol=1e-5)
        np.testing.assert_allclose(
            (s1 * s2).todense().numpy(), 2 * self.scipy_mat.toarray() ** 2, atol=1e-5
        )

    def test_spmm(self):
        s = ht.sparse.sparse_csr_matrix(self.scipy_mat, split=0)
        v = ht.random.randn(8, 3)
        np.testing.assert_allclose(
            (s @ v).numpy(), self.scipy_mat.toarray() @ v.numpy(), atol=1e-4
        )

    def test_matmul_distributed_dense(self):
        """DCSR(split=0) @ dense → split=0 dense, physically row-parallel
        (each shard computes from its own nonzeros only), scipy oracle."""
        import scipy.sparse as sp

        A = sp.random(37, 23, density=0.15, format="csr", random_state=1, dtype=np.float32)
        B = np.random.default_rng(0).standard_normal((23, 5)).astype(np.float32)
        s = ht.sparse.sparse_csr_matrix(A, split=0)
        r = ht.sparse.matmul(s, ht.array(B))
        assert r.split == 0
        self.assert_array_equal(r, A @ B, rtol=1e-4, atol=1e-4)
        # the per-shard nnz buffers are mesh-sharded, not replicated
        data, rows, cols, m, rps = s._row_sharded_parts()
        comm = s.comm
        if comm.is_distributed():
            assert len(data.sharding.device_set) >= comm.size
            for shard in data.addressable_shards:
                assert shard.data.shape[1] == m and shard.data.shape[0] * comm.size == data.shape[0]

    def test_matmul_vector_and_split_dense(self):
        import scipy.sparse as sp

        A = sp.random(37, 23, density=0.15, format="csr", random_state=1, dtype=np.float32)
        s = ht.sparse.sparse_csr_matrix(A, split=0)
        v = np.random.default_rng(1).standard_normal(23).astype(np.float32)
        rv = s @ ht.array(v)
        assert rv.shape == (37,) and rv.split == 0
        self.assert_array_equal(rv, A @ v, rtol=1e-4, atol=1e-4)
        # split dense RHS is resplit to None first (needs full columns)
        B = np.random.default_rng(2).standard_normal((23, 4)).astype(np.float32)
        r = s @ ht.array(B, split=0)
        self.assert_array_equal(r, A @ B, rtol=1e-4, atol=1e-4)

    def test_matmul_nonfinite_dense_matches_scipy(self):
        """Regression: nnz-pad entries use out-of-range indices (dropped by
        BCOO), not explicit zeros at (0,0) — explicit zeros would turn an
        inf/NaN in dense row 0 into NaN on every under-full shard's first
        row (0·inf = NaN)."""
        import scipy.sparse as sp

        A = sp.random(37, 23, density=0.15, format="csr", random_state=1, dtype=np.float32)
        B = np.random.default_rng(0).standard_normal((23, 5)).astype(np.float32)
        B[0, 0] = np.inf
        B[1, 2] = np.nan
        s = ht.sparse.sparse_csr_matrix(A, split=0)
        ours = (s @ ht.array(B)).numpy()
        want = A @ B
        mask = np.isfinite(want)
        np.testing.assert_allclose(ours[mask], want[mask], rtol=1e-4, atol=1e-4)
        np.testing.assert_array_equal(np.isfinite(ours), mask)

    def test_matmul_sparse_sparse(self):
        """DCSR @ DCSR: pure sparse BCOO product (no dense intermediate),
        result keeps the left operand's row split."""
        import scipy.sparse as sp

        A = sp.random(24, 16, density=0.2, format="csr", random_state=3, dtype=np.float32)
        C = sp.random(16, 9, density=0.2, format="csr", random_state=4, dtype=np.float32)
        s1 = ht.sparse.sparse_csr_matrix(A, split=0)
        s2 = ht.sparse.sparse_csr_matrix(C)
        rs = s1 @ s2
        assert isinstance(rs, ht.sparse.DCSR_matrix)
        assert rs.split == 0 and rs.shape == (24, 9)
        np.testing.assert_allclose(rs.todense().numpy(), (A @ C).toarray(), rtol=1e-4, atol=1e-4)

    def test_matmul_edge_shapes_and_errors(self):
        import pytest as _pytest
        import scipy.sparse as sp

        # fewer rows than devices: pad shards carry zero nnz
        A3 = sp.random(3, 23, density=0.3, format="csr", random_state=5, dtype=np.float32)
        B = np.random.default_rng(3).standard_normal((23, 2)).astype(np.float32)
        s3 = ht.sparse.sparse_csr_matrix(A3, split=0)
        r3 = s3 @ ht.array(B)
        self.assert_array_equal(r3, A3 @ B, rtol=1e-4, atol=1e-4)
        s = ht.sparse.sparse_csr_matrix(A3, split=0)
        with _pytest.raises(ValueError):
            ht.sparse.matmul(s, ht.array(B[:5]))  # shape mismatch
        with _pytest.raises(TypeError):
            ht.sparse.matmul(s, B)  # raw numpy is not a DNDarray

    def test_sub_neg_scalar_ops(self):
        d = self.scipy_mat.toarray()
        s1 = ht.sparse.sparse_csr_matrix(self.scipy_mat)
        s2 = ht.sparse.sparse_csr_matrix(self.scipy_mat * 0.5)
        np.testing.assert_allclose((s1 - s2).todense().numpy(), 0.5 * d, atol=1e-5)
        np.testing.assert_allclose((-s1).todense().numpy(), -d, atol=1e-6)
        np.testing.assert_allclose((s1 * 3.0).todense().numpy(), 3 * d, atol=1e-5)
        np.testing.assert_allclose((2.0 * s1).todense().numpy(), 2 * d, atol=1e-5)
        np.testing.assert_allclose((s1 / 2.0).todense().numpy(), d / 2, atol=1e-5)

    def test_to_sparse_roundtrip(self):
        d = self.scipy_mat.toarray()
        x = ht.array(d, split=0)
        s = ht.sparse.to_sparse(x)
        assert s.split == 0
        assert s.nnz == self.scipy_mat.nnz
        back = s.todense()
        assert back.split == 0
        self.assert_array_equal(back, d)
        # factory accepts a dense DNDarray and inherits its split
        s2 = ht.sparse.sparse_csr_matrix(x)
        assert s2.split == 0
        np.testing.assert_allclose(s2.todense().numpy(), d)

    def test_invalid_operands_raise(self):
        import pytest as _pytest

        s = ht.sparse.sparse_csr_matrix(self.scipy_mat)
        with _pytest.raises(TypeError):
            s * np.full(2, 3.0)  # array is not a scalar
        with _pytest.raises(TypeError):
            s - 2.0  # sparse - scalar is not defined
        with _pytest.raises(ValueError):
            ht.sparse.to_sparse(ht.array(self.scipy_mat.toarray(), split=1))
        with _pytest.raises(ValueError):
            ht.sparse.sparse_csr_matrix(
                ht.array(self.scipy_mat.toarray(), split=0), split=1
            )

    def test_transpose(self):
        d = self.scipy_mat.toarray()
        s = ht.sparse.sparse_csr_matrix(self.scipy_mat, split=0)
        st = ht.sparse.transpose(s)
        assert st.shape == (8, 16)
        assert st.split is None  # CSR-rows-only: transposed split unrepresentable
        np.testing.assert_allclose(st.todense().numpy(), d.T, atol=1e-6)


class TestTiling(TestCase):
    def test_split_tiles(self):
        a = ht.array(np.arange(64.0, dtype=np.float32).reshape(16, 4), split=0)
        t = ht.core.tiling.SplitTiles(a)
        assert sum(t.tile_dimensions[0]) == 16
        # tile 0 spans the first shard's rows (ceil-div chunk convention)
        rows = -(-16 // a.comm.size)
        first = np.asarray(t[0])
        np.testing.assert_array_equal(first, a.numpy()[:rows])
        t[0] = np.zeros_like(first)
        assert float(a.numpy()[:rows].sum()) == 0.0

    def test_square_diag_tiles(self):
        a = ht.array(np.arange(64.0, dtype=np.float32).reshape(8, 8), split=0)
        t = ht.core.tiling.SquareDiagTiles(a, tiles_per_proc=1)
        assert t.tile_rows >= 1 and t.tile_columns >= 1
        blk = np.asarray(t[0, 0])
        assert blk.shape[0] == blk.shape[1]  # square diagonal tile
        t[0, 0] = np.zeros_like(blk)
        assert float(a.numpy()[: blk.shape[0], : blk.shape[1]].sum()) == 0.0


class TestProfiler(TestCase):
    def test_timer(self):
        holder = {}
        x = ht.random.randn(64, 64)
        with ht.utils.profiler.timer("mm", holder, sync_on=None):
            y = x @ x
        ht.utils.profiler.sync(y)
        assert "mm" in holder and holder["mm"] >= 0.0


class TestFFTTransposeMethod(TestCase):
    """Transforms hitting the split axis use the explicit transpose method
    (resplit → local FFT → resplit back), the reference's own scheme —
    never a gather (r4)."""

    def _mod(self):
        import importlib

        return importlib.import_module("heat_tpu.fft.fft")

    def test_split_axis_fft_rides_transpose(self):
        F = self._mod()
        comm = ht.communication.get_comm()
        if not comm.is_distributed():
            pytest.skip("needs a multi-device mesh")
        from heat_tpu.core import _complexsafe

        if not _complexsafe.native_complex_supported():
            pytest.skip("hosted-complex mode: no mesh placement to preserve")
        x = np.random.default_rng(0).standard_normal((1000, 2 * comm.size)).astype(np.float32)
        hx = ht.array(x, split=0)
        before = dict(F.fft_paths)
        y = ht.fft.fft(hx, axis=0)
        assert F.fft_paths["transpose"] == before["transpose"] + 1
        np.testing.assert_allclose(y.numpy(), np.fft.fft(x, axis=0), rtol=1e-4, atol=1e-3)
        assert y.split == 0
        # rfft halves the split-axis extent: bookkeeping survives resplit-back
        yr = ht.fft.rfft(hx, axis=0)
        assert yr.shape == (501, 2 * comm.size) and yr.split == 0
        np.testing.assert_allclose(yr.numpy(), np.fft.rfft(x, axis=0), rtol=1e-4, atol=1e-3)
        # 2-D fft2 transforms EVERY axis — no free reshard target, so it
        # takes the direct path (still exact)
        before = dict(F.fft_paths)
        y2 = ht.fft.fft2(hx)
        assert F.fft_paths["transpose"] == before["transpose"]
        np.testing.assert_allclose(y2.numpy(), np.fft.fft2(x), rtol=1e-4, atol=1e-2)

    def test_fftn_partial_axes_reshards(self):
        """3-D fftn over axes (0, 2) with split=0: axis 1 is free and
        divisible → the _fftn_op transpose branch engages."""
        F = self._mod()
        comm = ht.communication.get_comm()
        if not comm.is_distributed():
            pytest.skip("needs a multi-device mesh")
        from heat_tpu.core import _complexsafe

        if not _complexsafe.native_complex_supported():
            pytest.skip("hosted-complex mode")
        p = comm.size
        x = np.random.default_rng(2).standard_normal((8 * p, 2 * p, 6)).astype(np.float32)
        hx = ht.array(x, split=0)
        before = dict(F.fft_paths)
        y = ht.fft.fftn(hx, axes=(0, 2))
        assert F.fft_paths["transpose"] == before["transpose"] + 1
        np.testing.assert_allclose(y.numpy(), np.fft.fftn(x, axes=(0, 2)), rtol=1e-4, atol=1e-2)
        assert y.split == 0
        # numpy rule: s given + axes omitted transforms only the LAST
        # len(s) axes — axis 0 (the split) is then untouched: direct path
        before = dict(F.fft_paths)
        y2 = ht.fft.fftn(hx, s=(2 * p, 6))
        assert F.fft_paths["transpose"] == before["transpose"]
        np.testing.assert_allclose(y2.numpy(), np.fft.fftn(x, s=(2 * p, 6)), rtol=1e-4, atol=1e-2)

    def test_local_axis_stays_direct(self):
        F = self._mod()
        x = np.random.default_rng(1).standard_normal((64, 8)).astype(np.float32)
        hx = ht.array(x, split=0)
        before = dict(F.fft_paths)
        y = ht.fft.fft(hx, axis=1)
        assert F.fft_paths["transpose"] == before["transpose"]
        np.testing.assert_allclose(y.numpy(), np.fft.fft(x, axis=1), rtol=1e-4, atol=1e-3)
