"""Dynamic Mode Decomposition (reference: ``heat/decomposition/dmd.py``).

Exact DMD via the distributed SVD of the snapshot matrix.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core import types
from ..core.base import BaseEstimator
from ..core.dndarray import DNDarray
from ..linalg import svdtools
from ..core.communication import Communication

__all__ = ["DMD"]


class DMD(BaseEstimator):
    """Exact DMD of a snapshot matrix X (features × time).

    ``svd_solver``: 'full' | 'hierarchical' | 'randomized';
    ``svd_rank``/``svd_tol`` select the truncation, mirroring the reference.
    """

    def __init__(
        self,
        svd_solver: str = "full",
        svd_rank: Optional[int] = None,
        svd_tol: Optional[float] = None,
    ):
        if svd_solver not in ("full", "hierarchical", "randomized"):
            raise ValueError(f"Unknown svd_solver {svd_solver!r}")
        self.svd_solver = svd_solver
        self.svd_rank = svd_rank
        self.svd_tol = svd_tol
        self.rom_basis_ = None
        self.rom_eigenvalues_ = None
        self.rom_eigenmodes_ = None
        self.dmdmodes_ = None
        self.n_modes_ = None

    def fit(self, x: DNDarray) -> "DMD":
        if x.ndim != 2 or x.shape[1] < 2:
            raise ValueError("DMD requires a 2-D snapshot matrix with ≥ 2 time steps")
        X0d, X1d = x[:, :-1], x[:, 1:]
        X0, X1 = X0d._jarray, X1d._jarray

        # dispatch to the distributed SVD layer, like PCA.fit
        if self.svd_solver == "hierarchical":
            rank = self.svd_rank or min(X0.shape)
            U, S, V, _ = svdtools.hsvd_rank(X0d, maxrank=rank, compute_sv=True)
            u, s, vt = U._jarray, S._jarray, V._jarray.T
            r = min(rank, s.shape[0])
        elif self.svd_solver == "randomized":
            rank = self.svd_rank or min(X0.shape)
            U, S, V = svdtools.rsvd(X0d, rank=rank)
            u, s, vt = U._jarray, S._jarray, V._jarray.T
            r = min(rank, s.shape[0])
        else:
            U, S, V = svdtools.svd(X0d)
            u, s, vt = U._jarray, S._jarray, V._jarray.T
            if self.svd_rank is not None:
                r = min(self.svd_rank, s.shape[0])
            elif self.svd_tol is not None:
                r = int(Communication.host_fetch(jnp.sum(s > self.svd_tol * s[0])))
            else:
                r = int(Communication.host_fetch(jnp.sum(s > 1e-10 * s[0])))
        r = max(r, 1)
        u_r, s_r, v_r = u[:, :r], s[:r], vt[:r].T
        # reduced operator Ã = Uᵀ X1 V Σ⁻¹
        atilde = u_r.T @ X1 @ v_r / s_r[None, :]
        evals, evecs = jnp.linalg.eig(atilde.astype(jnp.complex64))
        modes = (X1 @ v_r / s_r[None, :]).astype(jnp.complex64) @ evecs

        comm, device = x.comm, x.device

        def wrap(j, split=None):
            j = comm.shard(j, split)
            return DNDarray(j, tuple(j.shape), types.canonical_heat_type(j.dtype), split, device, comm, True)

        self.rom_basis_ = wrap(u_r, 0 if x.split == 0 else None)
        self.rom_transfer_matrix_ = wrap(atilde)
        self.rom_eigenvalues_ = wrap(evals)
        self.rom_eigenmodes_ = wrap(evecs)
        self.dmdmodes_ = wrap(modes)
        self.n_modes_ = r
        return self

    def predict(self, x: DNDarray, n_steps) -> DNDarray:
        """Forecast a trajectory with the fitted ROM (reference
        ``heat/decomposition/dmd.py::DMD.predict``).

        ``n_steps``: int — predict states 1..n_steps; or a sequence of
        (possibly non-contiguous) step indices.  Uses the eigendecomposition
        of the reduced operator, so step ``t`` costs one diagonal power
        ``Λ^t`` instead of ``t`` matmuls; the real part is returned (states
        of a real system driven by a real operator).

        Returns shape ``(len(steps),) + x.shape``, replicated (forecasts are
        small: rank-r dynamics lifted back through the basis).
        """
        if self.rom_basis_ is None:
            raise RuntimeError("fit must be called before predict")
        import numbers

        if isinstance(n_steps, numbers.Integral):
            steps = list(range(1, int(n_steps) + 1))
        else:
            steps = [int(t) for t in np.atleast_1d(np.asarray(n_steps))]
        if not steps:
            raise ValueError("predict needs at least one step")
        u = self.rom_basis_._jarray
        lam = self.rom_eigenvalues_._jarray
        w = self.rom_eigenmodes_._jarray
        jx = x._jarray
        red0 = jnp.linalg.solve(w, (u.T @ jx).astype(w.dtype))  # (r, ...)
        flat0 = red0.reshape(red0.shape[0], -1)  # (r, m)
        powers = lam[None, :] ** jnp.asarray(steps, dtype=lam.real.dtype)[:, None]  # (t, r)
        red_t = jnp.einsum("ir,tr,rm->tim", w, powers, flat0)  # one batched contraction
        res = jnp.einsum("ni,tim->tnm", u, red_t.real.astype(u.dtype))
        res = res.reshape((len(steps),) + jx.shape)
        res = x.comm.shard(res, None)
        return DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), None, x.device, x.comm, True)

    def predict_next(self, x: DNDarray, n_steps: int = 1) -> DNDarray:
        """Advance state(s) n_steps with the fitted reduced operator."""
        if self.rom_basis_ is None:
            raise RuntimeError("fit must be called before predict_next")
        u = self.rom_basis_._jarray
        a = self.rom_transfer_matrix_._jarray
        jx = x._jarray
        red = u.T @ jx
        for _ in range(n_steps):
            red = a @ red
        res = u @ red
        res = x.comm.shard(res, x.split)
        return DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), x.split, x.device, x.comm, True)
