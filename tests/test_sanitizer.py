"""Runtime metadata sanitizer (HEAT_TPU_CHECKS=1) + the sanitation
metadata-only contract (ISSUE 4).

Three tiers:

1. the sanitizer itself: arming pokes the dispatch/resplit hooks, armed
   dispatch passes on healthy arrays (all split shapes incl. ragged),
   corrupted metadata is caught with a precise error;
2. the no-value-reads contract: every ``sanitize_*`` function (and the new
   validators) runs with ALL device→host entry points monkeypatched to
   raise — none may trip;
3. env arming: ``HEAT_TPU_CHECKS=1`` in a fresh interpreter arms the hooks
   and survives a round of real ops.
"""

import os
import subprocess
import sys

import jax
import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import _operations, communication, sanitation
from heat_tpu.core.communication import Communication
from heat_tpu.core.dndarray import DNDarray

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def armed():
    was_on = sanitation.checks_enabled()
    sanitation.enable_checks()
    try:
        yield
    finally:
        # restore rather than disarm: under the HEAT_TPU_CHECKS=1 CI lane
        # the rest of the session must stay armed
        if not was_on:
            sanitation.disable_checks()


# ---------------------------------------------------------------------- #
# arming / hooks
# ---------------------------------------------------------------------- #
class TestArming:
    def test_state_matches_environment(self):
        # default off in a plain session; ON when the suite itself runs
        # under the HEAT_TPU_CHECKS=1 CI lane
        want = os.environ.get("HEAT_TPU_CHECKS", "").strip().lower() in (
            "1", "true", "on", "yes",
        )
        assert sanitation.checks_enabled() == want
        assert (_operations._CHECKS is not None) == want
        assert (communication._RESPLIT_CHECK is not None) == want

    def test_poke_roundtrip(self):
        was_on = sanitation.checks_enabled()
        try:
            sanitation.enable_checks()
            assert sanitation.checks_enabled()
            assert _operations._CHECKS is sanitation.validate_dispatch
            assert communication._RESPLIT_CHECK is sanitation.check_placement
            sanitation.disable_checks()
            assert not sanitation.checks_enabled()
            assert _operations._CHECKS is None
            assert communication._RESPLIT_CHECK is None
        finally:
            (sanitation.enable_checks if was_on else sanitation.disable_checks)()

    def test_check_is_identity_when_disabled(self):
        if sanitation.checks_enabled():
            pytest.skip("suite is running with HEAT_TPU_CHECKS=1")
        x = ht.ones(4)
        assert sanitation.check(x, "test") is x

    @pytest.mark.slow  # fresh-interpreter jax import ~40s; the quick lane's
    # budget can't carry it, and the checks-tier1 CI lane proves env arming
    # end-to-end anyway (whole suite under HEAT_TPU_CHECKS=1)
    def test_env_arming_fresh_interpreter(self):
        out = subprocess.run(
            [sys.executable, "-c", (
                "import heat_tpu as ht\n"
                "from heat_tpu.core import _operations, sanitation, communication\n"
                "assert sanitation.checks_enabled()\n"
                "assert _operations._CHECKS is sanitation.validate_dispatch\n"
                "assert communication._RESPLIT_CHECK is sanitation.check_placement\n"
                "x = ht.arange(16, dtype=ht.float32, split=0)\n"
                "y = ((x + 1.0) * 2.0).sum()\n"
                "r = ht.arange(101, dtype=ht.float32, split=0) * 3.0\n"
                "print('ARMED-OK', float(y.numpy()), float(r.sum().numpy()))\n"
            )],
            env={**os.environ, "HEAT_TPU_CHECKS": "1", "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=240, cwd=REPO,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        assert "ARMED-OK" in out.stdout


# ---------------------------------------------------------------------- #
# armed dispatch on healthy arrays
# ---------------------------------------------------------------------- #
class TestArmedDispatch:
    def test_ops_pass_all_split_shapes(self, armed):
        for split in (None, 0):
            x = ht.arange(16, dtype=ht.float32, split=split)
            np.testing.assert_allclose(
                ((x + 1.0) * 2.0).sum().numpy(), np.sum((np.arange(16.0) + 1) * 2)
            )
        m = ht.reshape(ht.arange(64, dtype=ht.float32, split=0), (8, 8))
        assert m.cumsum(0).shape == (8, 8)
        assert float(m.max().numpy()) == 63.0

    def test_ragged_ops_pass(self, armed):
        x = ht.arange(101, dtype=ht.float32, split=0)
        np.testing.assert_allclose((x * 2.0).sum().numpy(), np.arange(101.0).sum() * 2)

    def test_factory_and_resplit_boundaries_pass(self, armed):
        m = ht.array(np.arange(24.0, dtype=np.float32).reshape(6, 4), split=0)
        m2 = m.resplit(1)
        assert m2.split == 1
        m.resplit_(1)
        assert m.split == 1

    def test_out_path_validated(self, armed):
        x = ht.ones((4, 4), split=0)
        out = ht.zeros((4, 4), split=0)
        ht.add(x, x, out=out)
        np.testing.assert_allclose(out.numpy(), 2.0)


# ---------------------------------------------------------------------- #
# corruption detection
# ---------------------------------------------------------------------- #
class TestValidator:
    def test_non_dndarray_rejected(self):
        with pytest.raises(sanitation.MetadataError, match="expected DNDarray"):
            sanitation.validate_metadata(np.ones(3))

    def test_wrong_gshape_caught(self):
        x = ht.arange(16, dtype=ht.float32)
        bad = DNDarray._from_parts(x._jarray, (17,), x.dtype, None, x.device, x.comm)
        with pytest.raises(sanitation.MetadataError, match="physical shape"):
            sanitation.validate_metadata(bad, "unit")

    def test_wrong_dtype_caught(self):
        x = ht.arange(16, dtype=ht.float32)
        bad = DNDarray._from_parts(x._jarray, (16,), ht.int32, None, x.device, x.comm)
        with pytest.raises(sanitation.MetadataError, match="dtype metadata"):
            sanitation.validate_metadata(bad)

    def test_split_out_of_range_caught(self):
        x = ht.arange(16, dtype=ht.float32)
        bad = DNDarray._from_parts(x._jarray, (16,), x.dtype, 3, x.device, x.comm)
        with pytest.raises(sanitation.MetadataError, match="split 3 out of range"):
            sanitation.validate_metadata(bad)

    def test_wrong_sharding_caught(self):
        comm = ht.communication.get_comm()
        if comm.size < 2:
            pytest.skip("needs a multi-device mesh")
        n = comm.size
        m = ht.array(np.arange(float(n * n), dtype=np.float32).reshape(n, n), split=0)
        # claim split=1 on an array physically sharded along axis 0
        lying = DNDarray._from_parts(m._parray, (n, n), m.dtype, 1, m.device, m.comm)
        with pytest.raises(sanitation.MetadataError, match="canonical sharding"):
            sanitation.validate_metadata(lying, "unit")

    def test_bad_pad_caught(self):
        comm = ht.communication.get_comm()
        if comm.size < 2:
            pytest.skip("needs a multi-device mesh")
        x = ht.arange(101, dtype=ht.float32, split=0)
        assert x._pad > 0  # ragged on any multi-device mesh
        # corrupt the logical extent: pad no longer matches padded_extent
        bad = DNDarray._from_parts(x._parray, x.gshape, x.dtype, 0, x.device, x.comm)
        bad._DNDarray__pad = x._pad + 1  # heatlint: disable=HT106 (test corrupts on purpose)
        with pytest.raises(sanitation.MetadataError, match="pad"):
            sanitation.validate_metadata(bad)

    def test_validator_returns_input(self):
        x = ht.ones((4,))
        assert sanitation.validate_metadata(x) is x

    def test_cross_rank_single_process_passes(self):
        x = ht.arange(8, dtype=ht.float32, split=0)
        assert sanitation.assert_cross_rank_consistent(x, tag="unit") is x


# ---------------------------------------------------------------------- #
# the metadata-only contract: NO sanitize_*/validator may read values
# ---------------------------------------------------------------------- #
class TestNoValueReads:
    @pytest.fixture
    def no_value_reads(self, monkeypatch):
        """Every device→host value-read entry point raises; metadata-only
        code must never trip one."""

        def _boom(*a, **k):
            raise AssertionError("device→host value read inside sanitation!")

        real_asarray = np.asarray

        def guarded_asarray(obj, *a, **k):
            if isinstance(obj, jax.Array):
                _boom()
            return real_asarray(obj, *a, **k)

        monkeypatch.setattr(jax, "device_get", _boom)
        monkeypatch.setattr(np, "asarray", guarded_asarray)
        monkeypatch.setattr(Communication, "host_fetch", staticmethod(_boom))
        monkeypatch.setattr(DNDarray, "numpy", _boom)
        monkeypatch.setattr(DNDarray, "item", _boom)
        return None

    def test_every_sanitize_function_is_metadata_only(self, no_value_reads):
        x = ht.array(np.arange(24.0, dtype=np.float32).reshape(6, 4), split=0)
        y = ht.ones((6, 4), dtype=ht.float32, split=0)
        rep = ht.ones((6, 4), dtype=ht.float32)  # replicated

        sanitation.sanitize_in(x)
        assert sanitation.sanitize_infinity(x) > 0
        assert sanitation.sanitize_in_tensor(x) is x._jarray
        sanitation.sanitize_in_tensor([1.0, 2.0])
        sanitation.sanitize_lshape(x, x._jarray)
        sanitation.sanitize_out(y, (6, 4), 0, x.device)
        sanitation.sanitize_distribution(y, target=x)
        # distribution repair (replicated -> split) is a device_put, NOT a
        # value read — it must survive the patched entry points too
        sanitation.sanitize_distribution(rep, target=x)
        sanitation.sanitize_sequence([1, 2, 3])
        sanitation.sanitize_sequence((1, 2, 3))
        sanitation.sanitize_sequence(ht.ones(3))
        sanitation.scalar_to_1d(ht.array(np.float32(2.0)))

    def test_out_resplit_repair_is_metadata_only(self, no_value_reads, recwarn):
        if ht.communication.get_comm().n_processes > 1:
            pytest.skip("multi-process placement goes through host assembly")
        x = ht.array(np.arange(24.0, dtype=np.float32).reshape(6, 4), split=0)
        out = ht.ones((6, 4), dtype=ht.float32)  # wrong split: triggers resplit_
        sanitation.sanitize_out(out, (6, 4), 0, x.device)
        assert out.split == 0

    def test_runtime_validators_are_metadata_only(self, no_value_reads):
        x = ht.array(np.arange(24.0, dtype=np.float32).reshape(6, 4), split=0)
        sanitation.validate_metadata(x, "contract")
        sanitation.check_placement(x._parray, x.comm, x.split, "contract")
        sanitation.assert_cross_rank_consistent(x, "contract")
        rag = ht.arange(101, dtype=ht.float32, split=0)
        sanitation.validate_metadata(rag, "contract-ragged")

    def test_armed_dispatch_is_metadata_only(self, no_value_reads, armed):
        # a full armed dispatch round (fast path + general path + factory)
        # must not read a single value either
        x = ht.arange(16, dtype=ht.float32, split=0)
        _ = (x + 1.0) * 2.0
        _ = x.sum()
        _ = x.cumsum(0)
