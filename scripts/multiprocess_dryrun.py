"""N-process SPMD dryrun (round-4 verdict item 1; shapes r5 item 4).

The reference's defining property is N-process SPMD (``mpirun -n N``,
SURVEY §4); single-controller JAX hides that tier.  This script stands it
up for real: **n_proc processes × devs_per_proc CPU devices** under
``jax.distributed`` (gloo collectives) — default 2×4, round-5 adds 4×2 —
exercising the paths that implicitly assumed all shards addressable:

- factories + binary ops + reductions on a global mesh spanning processes
- ``resplit_`` across the process boundary
- per-process hyperslab ``save_hdf5``/``load_hdf5`` (token-ring writes)
- ``numpy()`` / ``__repr__`` of a sharded array from ALL processes
- one ``DataParallel`` train step with cross-process gradient psum
- ring attention / MoE all_to_all / pipeline ppermute over the seam
- ``Communication.rank`` / ``n_processes`` semantics

Run:  python scripts/multiprocess_dryrun.py                    (launcher, 2×4)
      MPDRYRUN_NPROC=4 MPDRYRUN_DEVS=2 python scripts/multiprocess_dryrun.py
      python scripts/multiprocess_dryrun.py WORKER_ID          (internal)

The launcher exits 0 iff every worker completes every check.

``launch_pytest`` is the second tier (VERDICT r4 weak #6): it runs the
REAL test suite's ``-m mp`` subset inside the same n-process context —
every process executes the identical pytest selection SPMD-style, with a
shared tmp dir so file round-trips cross the process seam.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_PROC = 2
DEVS_PER_PROC = 4
MARKER = "MPDRYRUN-OK"


PASS_MARKER = "MULTIPROCESS DRYRUN: PASS"


def launch(timeout: float = 540.0, n_proc: int = 2, devs_per_proc: int = 4):
    """Run the launcher as a subprocess with the scrub every caller needs
    (XLA_FLAGS stripped so workers pick their own device count) — THE ONE
    place the launch contract lives; the dryrun tier and the pytest lane
    both call this.  Success iff ``returncode == 0`` and ``PASS_MARKER`` in
    stdout."""
    import subprocess as sp

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["MPDRYRUN_NPROC"] = str(n_proc)
    env["MPDRYRUN_DEVS"] = str(devs_per_proc)
    return sp.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
    )


def launch_pytest(timeout: float = 1500.0, n_proc: int = 2,
                  devs_per_proc: int = 4, marker: str = "mp and not mp_unsafe",
                  extra_args: tuple = ()):
    """Run the real suite's ``-m {marker}`` subset in an n-process SPMD
    context: every process runs the IDENTICAL pytest selection (pytest's
    collection order is deterministic), so the collectives inside the
    tests line up across processes; ``tmp_path`` is redirected to a shared
    per-test directory (see tests/conftest.py) so IO round-trips exercise
    the token-ring writers across the seam.  Returns the list of completed
    processes (one per rank); success = every returncode 0."""
    import tempfile
    import time

    port = _free_port()
    tmpdir = tempfile.mkdtemp(prefix="mppytest_")
    procs, logs = [], []
    for pid in range(n_proc):
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS", "PYTHONPATH")}
        env["HEAT_MP_COORD"] = f"{n_proc}:{pid}:{port}:{devs_per_proc}"
        env["HEAT_MP_TMP"] = tmpdir
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONUNBUFFERED"] = "1"
        # rank self-watchdog (see tests/conftest.py): dump stacks + exit
        # shortly BEFORE this launcher's own deadline, so a wedged
        # collective yields tracebacks in the rank log, not a silent kill
        env.setdefault("HEAT_MP_WATCHDOG", str(max(60, int(timeout) - 60)))
        # stream to files (not PIPE): a wedged rank's progress stays
        # inspectable mid-run, and full buffers can't deadlock the child
        log = open(os.path.join(tmpdir, f"rank{pid}.log"), "w+b")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "pytest", "-m", marker, "-q",
             "-p", "no:cacheprovider", *extra_args, "tests/"],
            env=env, cwd=REPO, stdout=log, stderr=subprocess.STDOUT,
        ))
    print(f"launch_pytest: logs under {tmpdir}", flush=True)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        codes = [p.poll() for p in procs]
        if all(c is not None for c in codes):
            break
        if any(c is not None and c != 0 for c in codes):
            break  # one rank failed: peers will wedge on its collectives
        time.sleep(0.5)
    _dump_stacks_then_kill(procs)
    results = []
    for p, log in zip(procs, logs):
        if p.poll() is None:
            p.wait()
        log.seek(0)
        results.append((p.returncode, log.read().decode(errors="replace")))
        log.close()
    return results


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _dump_stacks_then_kill(procs, grace: float = 3.0) -> bool:
    """Watchdog teardown for wedged workers: SIGUSR1 each live process (the
    workers registered a faulthandler stack dump on it, so every thread's
    traceback lands in that rank's output), give them ``grace`` seconds to
    finish dumping, then kill.  Returns True iff any process had to be
    reaped — per-process stacks instead of a silent suite hang."""
    import signal
    import time

    hung = [p for p in procs if p.poll() is None]
    if not hung:
        return False
    print(
        f"watchdog: {len(hung)} process(es) still alive at the deadline; "
        "requesting stack dumps (SIGUSR1) before kill",
        flush=True,
    )
    for p in hung:
        try:
            p.send_signal(signal.SIGUSR1)
        except OSError:
            pass
    t0 = time.monotonic()
    while time.monotonic() - t0 < grace and any(p.poll() is None for p in hung):
        time.sleep(0.1)
    for p in hung:
        if p.poll() is None:
            p.kill()
    return True


# ---------------------------------------------------------------------- #
# worker
# ---------------------------------------------------------------------- #
def worker(pid: int, port: int, tmpdir: str) -> None:
    # watchdog (robustness tier): a wedged collective must dump stacks and
    # die, not hang the suite.  SIGUSR1 lets the launcher demand a stack
    # dump from a live-but-stuck worker; dump_traceback_later(exit=True) is
    # the self-watchdog — when a collective never completes, every thread's
    # stack goes to stderr and the process exits, unwedging the peers' poll
    # loop instead of riding out the full outer timeout.
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1)
    faulthandler.dump_traceback_later(
        float(os.environ.get("MPDRYRUN_WATCHDOG", "450")), exit=True
    )
    n_proc = int(os.environ.get("MPDRYRUN_NPROC", N_PROC))
    devs = int(os.environ.get("MPDRYRUN_DEVS", DEVS_PER_PROC))
    os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devs}"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    # jax.distributed must initialize before ANY backend touch — importing
    # heat_tpu resolves the default device, so initialize first
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=n_proc, process_id=pid
    )
    sys.path.insert(0, REPO)

    import numpy as np

    import heat_tpu as ht

    ht.core.bootstrap.init_distributed(num_processes=n_proc, process_id=pid)
    comm = ht.communication.get_comm()
    # ---- rank/n_processes semantics --------------------------------- #
    assert comm.n_processes == n_proc, comm.n_processes
    assert comm.rank == pid, (comm.rank, pid)
    assert comm.size == n_proc * devs, comm.size
    print(f"[{pid}] comm: size={comm.size} rank={comm.rank}/{comm.n_processes}", flush=True)

    # ---- factories + binary ops + reduce ---------------------------- #
    n = 101  # ragged on 8 shards
    x = ht.arange(n, dtype=ht.float32, split=0)
    y = ht.ones(n, dtype=ht.float32, split=0)
    z = x * 2.0 + y
    total = float(z.sum().numpy())
    want = float(np.sum(np.arange(n, dtype=np.float32) * 2.0 + 1.0))
    assert total == want, (total, want)
    assert not z._jarray.is_fully_addressable  # genuinely cross-process
    print(f"[{pid}] factories/binary/reduce: OK ({total})", flush=True)

    # ---- numpy() / __repr__ from both processes --------------------- #
    full = z.numpy()
    np.testing.assert_allclose(full, np.arange(n, dtype=np.float32) * 2.0 + 1.0)
    r = repr(ht.reshape(ht.arange(64, dtype=ht.float32, split=0), (8, 8)))
    assert "DNDarray" in r and "split=0" in r, r[:80]
    print(f"[{pid}] numpy()/repr: OK", flush=True)

    # ---- resplit_ across the process boundary ----------------------- #
    m = ht.reshape(ht.arange(64, dtype=ht.float32, split=0), (8, 8))
    m2 = ht.resplit(m, 1)
    assert m2.split == 1
    np.testing.assert_allclose(m2.numpy(), np.arange(64, dtype=np.float32).reshape(8, 8))
    print(f"[{pid}] resplit_: OK", flush=True)

    # ---- per-process hyperslab HDF5 write + read -------------------- #
    try:
        import h5py  # noqa: F401

        has_h5 = True
    except ImportError:
        has_h5 = False
    if has_h5:
        path = os.path.join(tmpdir, "mp.h5")
        data = ht.reshape(ht.arange(96, dtype=ht.float32, split=0), (24, 4))
        ht.save_hdf5(data, path, "d")
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("mpdryrun:h5-written")
        back = ht.load_hdf5(path, "d", dtype=ht.float32, split=0)
        assert not back._jarray.is_fully_addressable
        np.testing.assert_allclose(back.numpy(), data.numpy())
        # replicated (split=None) save: regression for the rank-0-only write
        # deadlocking on the collective host fetch
        rep = ht.resplit(data, None)
        ht.save_hdf5(rep, os.path.join(tmpdir, "mp_rep.h5"), "d")
        multihost_utils.sync_global_devices("mpdryrun:h5-rep-written")
        back2 = ht.load_hdf5(os.path.join(tmpdir, "mp_rep.h5"), "d", dtype=ht.float32)
        np.testing.assert_allclose(back2.numpy(), data.numpy())
        # RAGGED extent (101 rows on 8 devices): the per-process slab must
        # follow the per-DEVICE padded grid, not ceil-over-processes
        ragged = ht.arange(101, dtype=ht.float32, split=0)
        ht.save_hdf5(ht.reshape(ragged, (101, 1)), os.path.join(tmpdir, "mp_rag.h5"), "d")
        multihost_utils.sync_global_devices("mpdryrun:h5-rag-written")
        back3 = ht.load_hdf5(os.path.join(tmpdir, "mp_rag.h5"), "d", dtype=ht.float32, split=0)
        assert back3.shape == (101, 1) and back3._pad == 3
        np.testing.assert_allclose(back3.numpy().ravel(), np.arange(101, dtype=np.float32))
        print(f"[{pid}] hdf5 hyperslab save/load: OK", flush=True)
    else:  # pragma: no cover
        print(f"[{pid}] hdf5 hyperslab save/load: SKIP (no h5py)", flush=True)

    # ---- one DataParallel step -------------------------------------- #
    model = ht.nn.Sequential(ht.nn.Linear(16, 8), ht.nn.ReLU(), ht.nn.Linear(8, 2))
    opt = ht.optim.DataParallelOptimizer("sgd", lr=0.1)
    dp = ht.nn.DataParallel(model, optimizer=opt)
    params = dp.init(jax.random.key(0))
    state = opt.init_state(params)
    step = dp.make_train_step(ht.nn.functional.cross_entropy)
    rng = np.random.default_rng(0)  # same data on every process (SPMD)
    xb = ht.array(rng.standard_normal((32, 16)).astype(np.float32), split=0)
    yb = ht.array(rng.integers(0, 2, 32).astype(np.int32), split=0)
    params, state, loss = step(params, state, xb._jarray, yb._jarray)
    # post-step params identical on every process and every device
    w = params[0]["weight"]
    wl = comm.host_fetch(w)
    digest = float(np.sum(wl * wl))
    from jax.experimental import multihost_utils

    digests = np.asarray(multihost_utils.process_allgather(np.asarray([digest])))
    assert np.all(digests == digests[0]), digests
    print(f"[{pid}] DataParallel step: OK (loss={float(loss):.4f})", flush=True)

    # ---- ring attention across the process boundary ------------------ #
    # the ring's ppermute crosses the 2-process seam every rotation — this
    # is the long-context path running over real inter-process transport
    # (gloo standing in for DCN), not just intra-process device lanes
    import jax.numpy as jnp

    from heat_tpu.parallel.ring_attention import _global_attention, ring_attention

    rng2 = np.random.default_rng(7)  # same operands on every process (SPMD)
    S, d = 37, 8  # ragged on 8 shards
    q = jnp.asarray(rng2.standard_normal((2, S, d)), jnp.float32)
    k = jnp.asarray(rng2.standard_normal((2, S, d)), jnp.float32)
    v = jnp.asarray(rng2.standard_normal((2, S, d)), jnp.float32)
    out = ring_attention(
        comm.shard(q, 1), comm.shard(k, 1), comm.shard(v, 1), comm, causal=True
    )
    assert not out.is_fully_addressable  # spans both processes
    got = comm.host_fetch(out)
    ref = np.asarray(_global_attention(q, k, v, True, d**-0.5))
    np.testing.assert_allclose(got, ref, atol=2e-5)
    print(f"[{pid}] ring attention (cross-process ppermute): OK", flush=True)

    # ---- expert parallelism across the process boundary --------------- #
    # the MoE's two all_to_alls move tokens between experts owned by
    # DIFFERENT processes (round-4d) — EP data movement over the seam
    moe = ht.nn.MoE(8, 2 * comm.size, hidden_dim=16, top_k=2,
                    capacity_factor=8.0, comm=comm)
    dense = ht.nn.MoE(8, 2 * comm.size, hidden_dim=16, top_k=2,
                      capacity_factor=8.0)
    mp_ = moe.init(jax.random.key(11))
    xm = jnp.asarray(np.random.default_rng(8).standard_normal((comm.size, 3, 8)),
                     jnp.float32)
    ym = moe.apply(mp_, xm)
    assert not ym.is_fully_addressable  # EP really crossed the seam (no dense fallback)
    np.testing.assert_allclose(
        comm.host_fetch(ym), np.asarray(dense.apply(mp_, xm)), atol=2e-5
    )
    print(f"[{pid}] MoE expert parallelism (cross-process all_to_all): OK", flush=True)

    # ---- pipeline parallelism across the process boundary ------------- #
    # stage weights sharded over devices of BOTH processes; activations
    # cross the seam on ppermute every tick
    blk = ht.nn.Linear(8, 8)
    pipe = ht.nn.Pipelined(blk, depth=comm.size, comm=comm, n_microbatches=2)
    seq = ht.nn.Pipelined(blk, depth=comm.size, comm=None)
    pp_ = pipe.init(jax.random.key(12))
    xp = jnp.asarray(np.random.default_rng(9).standard_normal((4, 8)), jnp.float32)
    yp = pipe.apply(pp_, xp)
    np.testing.assert_allclose(
        comm.host_fetch(yp), np.asarray(seq.apply(pp_, xp)), atol=2e-5
    )
    print(f"[{pid}] pipeline stages (cross-process ppermute): OK", flush=True)

    # ---- runtime metadata sanitizer across the process seam ----------- #
    # HEAT_TPU_CHECKS tier: arm the metadata-only validator (dispatch tails
    # + factory/resplit boundaries) on a REAL multi-process mesh, then
    # assert cross-rank metadata agreement — a rank whose (gshape, split,
    # dtype, pad) diverged would stage different collectives and deadlock
    # its peers, so the digest comparison itself is the canary
    from heat_tpu.core import sanitation

    checks_were_on = sanitation.checks_enabled()  # e.g. env-armed HEAT_TPU_CHECKS=1
    sanitation.enable_checks()
    try:
        chk = ht.arange(48, dtype=ht.float32, split=0) * 2.0  # validated at the tail
        sanitation.assert_cross_rank_consistent(chk, tag="mpdryrun.dispatch")
        chk2 = ht.resplit(ht.reshape(chk, (8, 6)), 1)  # validated at the boundary
        sanitation.assert_cross_rank_consistent(chk2, tag="mpdryrun.resplit")
        rag = ht.arange(101, dtype=ht.float32, split=0) + 1.0  # pad metadata agrees too
        sanitation.assert_cross_rank_consistent(rag, tag="mpdryrun.ragged")
    finally:
        # restore rather than disarm: an env-armed worker keeps validating
        # the rest of its checks
        if not checks_were_on:
            sanitation.disable_checks()
    print(f"[{pid}] SANITIZER-OK (cross-rank metadata agreement)", flush=True)

    # ---- telemetry per-rank export ----------------------------------- #
    # every rank flushes its span/counter/histogram state to a shared dir;
    # the launcher merges rank0+rank1+... with scripts/telemetry_report.py
    # — the multi-rank observability story running over a REAL process seam
    from heat_tpu.utils import telemetry

    telemetry.enable()
    with telemetry.span("mpdryrun.telemetry_check", rank=pid):
        _ = (x * 3.0).sum().numpy()
    rep = telemetry.report()
    assert rep["counters"].get("comm.resplit.calls", 0) >= 1, rep["counters"]
    assert rep["rank"] == pid, (rep["rank"], pid)
    tpath = telemetry.flush(os.path.join(tmpdir, "telemetry"))
    assert tpath and tpath.endswith(f"rank{pid}.jsonl"), tpath
    print(f"[{pid}] telemetry: rank file exported", flush=True)

    print(f"[{pid}] {MARKER}", flush=True)
    faulthandler.cancel_dump_traceback_later()
    ht.core.bootstrap.finalize_distributed()


# ---------------------------------------------------------------------- #
# launcher
# ---------------------------------------------------------------------- #
def main() -> int:
    import tempfile

    n_proc = int(os.environ.get("MPDRYRUN_NPROC", N_PROC))
    port = _free_port()
    tmpdir = tempfile.mkdtemp(prefix="mpdryrun_")
    env = dict(os.environ)
    env["MPDRYRUN_PORT"] = str(port)
    env["MPDRYRUN_TMP"] = tmpdir
    # scrub accelerator plumbing HERE (popping inside the worker is too
    # late: PYTHONPATH site hooks run at interpreter startup) — the workers
    # must come up as plain-CPU jax processes
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), str(pid)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for pid in range(n_proc)
    ]
    ok = True
    # ONE shared deadline below the callers' 540 s outer timeout (a
    # per-worker budget would stack sequentially past it), so any hang is
    # reaped by this launcher — which can kill its children — rather than
    # by the caller killing the launcher and orphaning the workers.  The
    # poll loop watches ALL workers at once: one failing fast kills its
    # peers immediately (a dead peer wedges every surviving worker's next
    # collective — waiting out the deadline for that is pure lost time).
    import time

    deadline = time.monotonic() + 480
    while time.monotonic() < deadline:
        codes = [p.poll() for p in procs]
        if any(c is not None and c != 0 for c in codes) or all(
            c is not None for c in codes
        ):
            break
        time.sleep(0.5)
    if _dump_stacks_then_kill(procs):
        ok = False
    for pid, p in enumerate(procs):
        out, _ = p.communicate()
        text = out.decode(errors="replace")
        sys.stdout.write(text)
        if p.returncode != 0 or MARKER not in text:
            ok = False
    # merge every rank's telemetry export into one report (the tool the
    # acceptance criterion names: multi-rank jsonl -> one summary table)
    tdir = os.path.join(tmpdir, "telemetry")
    if ok and os.path.isdir(tdir):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "telemetry_report",
            os.path.join(os.path.dirname(os.path.abspath(__file__)), "telemetry_report.py"),
        )
        trep = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(trep)
        merged = trep.merge_files(trep.find_rank_files(tdir))
        print(trep.render(merged, top=10, timeline=0), flush=True)
        if len(merged["ranks"]) != n_proc:
            print(f"telemetry merge: expected {n_proc} ranks, got {merged['ranks']}")
            ok = False
        else:
            print(f"TELEMETRY-MERGED ranks={len(merged['ranks'])}", flush=True)
    print("MULTIPROCESS DRYRUN:", "PASS" if ok else "FAIL", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    if len(sys.argv) > 1:
        worker(
            int(sys.argv[1]),
            int(os.environ["MPDRYRUN_PORT"]),
            os.environ["MPDRYRUN_TMP"],
        )
    else:
        sys.exit(main())
