"""Per-communicator compiled-program caches.

Compiled collective pipelines (shard_map + jit) close over a
``Communication``'s mesh and pin XLA executables.  Caching them with
``functools.lru_cache`` keyed on the comm strongly pins comm + mesh +
executables until LRU eviction — the leak ADVICE.md flagged in round 3.

``comm_cached`` stores each function's programs in a dict ON the comm
instance (``comm._compiled_programs``), so:

- lifetime is tied to the comm by construction — programs die exactly when
  the comm is garbage collected, with no global registry pinning either;
- keying is by *instance identity*, not ``Communication.__eq__`` (which
  compares (mesh, axis)) — two value-equal comms never alias or steal each
  other's cache entries, which a ``WeakKeyDictionary`` would get wrong;
- each (comm, function) table is LRU-bounded: some static keys derive from
  user data (global length ``n``, ``k``), so an unbounded table on the
  process-lifetime world comm would accumulate executables forever.
"""

from __future__ import annotations

import functools
from collections import OrderedDict

__all__ = ["comm_cached"]


def comm_cached(fn=None, *, maxsize: int = 32, key=None):
    """Memoize ``fn(comm, *args)`` on the comm instance, LRU-bounded.

    ``args`` must be hashable (static ints/strings/tuples — the same
    contract ``lru_cache`` imposed).  ``key``, if given, maps ``*args`` to
    the cache key instead of using the args themselves — layer-program
    caches key on a *config tuple* (e.g. ``MoE._program_key``) so
    identical-config layers share one executable and the table *key* never
    pins a layer.  Note the cached *value* may still close over the first
    instance of each config (a bound method inside the compiled program) —
    retention drops from every-instance to one representative per config,
    LRU-bounded.  Without ``key``, object-valued args are retained until
    eviction, acceptable only for long-lived objects (see
    ``parallel.pipeline._pipeline_program``).
    """
    if fn is None:
        return lambda f: comm_cached(f, maxsize=maxsize, key=key)

    slot = f"{fn.__module__}.{fn.__qualname__}"

    @functools.wraps(fn)
    def wrapper(comm, *args):
        tables = comm.__dict__.setdefault("_compiled_programs", {})
        table = tables.get(slot)
        if table is None:
            table = tables[slot] = OrderedDict()
        k = key(*args) if key is not None else args
        prog = table.get(k)
        if prog is None:
            prog = table[k] = fn(comm, *args)
            if len(table) > maxsize:
                table.popitem(last=False)
        else:
            table.move_to_end(k)
        return prog

    wrapper._cache_slot = slot  # introspection hook for tests
    return wrapper
