"""Ring attention / sequence-parallel tests."""

import numpy as np
import pytest

import heat_tpu as ht


def _oracle(q, k, v, causal):
    S, d = q.shape
    s = (q @ k.T) / np.sqrt(d)
    if causal:
        s = np.where(np.tril(np.ones((S, S), bool)), s, -np.inf)
    p = np.exp(s - s.max(1, keepdims=True))
    p /= p.sum(1, keepdims=True)
    return p @ v


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        S, d = 64, 16
        q = rng.normal(size=(S, d)).astype(np.float32)
        k = rng.normal(size=(S, d)).astype(np.float32)
        v = rng.normal(size=(S, d)).astype(np.float32)
        comm = ht.communication.get_comm()
        out = ht.parallel.ring_self_attention(
            comm.shard(jnp.asarray(q), 0),
            comm.shard(jnp.asarray(k), 0),
            comm.shard(jnp.asarray(v), 0),
            comm,
            causal=causal,
        )
        np.testing.assert_allclose(np.asarray(out), _oracle(q, k, v, causal), atol=2e-3)

    def test_ragged_fallback(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(1)
        S, d = 30, 8  # not divisible by the mesh → dense fallback
        q = rng.normal(size=(S, d)).astype(np.float32)
        comm = ht.communication.get_comm()
        out = ht.parallel.ring_self_attention(
            jnp.asarray(q), jnp.asarray(q), jnp.asarray(q), comm
        )
        np.testing.assert_allclose(np.asarray(out), _oracle(q, q, q, False), atol=2e-3)
