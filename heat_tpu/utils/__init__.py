"""Utilities."""
from . import data
