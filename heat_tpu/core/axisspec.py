"""split ↔ named-axis-spec compatibility shim (mesh-refactor tranche 0).

The named-axis mesh refactor (ROADMAP: t5x-style ``LogicalAxisRules`` over
a named ``Mesh``) migrates 414 cataloged single-``split``-axis sites.  The
first executable step is this shim: call sites keep passing ``split=`` —
the entire runtime keeps consuming a plain axis index — but migrated sites
pass :func:`named`, whose :class:`AxisSpec` return value **is** the int
(a subclass), while also carrying the logical-axis-name view the future
partitioner will consume.

Guarantees (round-trip tested in ``tests/test_axisspec.py``):

- ``named(k) == k``, ``hash(named(k)) == hash(k)``, arithmetic, formatting,
  JSON serialization and dict/cache keying are bit-identical to the raw
  int — a migrated call site cannot change ANY runtime behavior, including
  the sharding-keyed program cache (same key → same cached executable).
- ``spec_to_split(split_to_spec(s, ndim)) == s`` for every valid axis and
  for ``None`` (replicated), so the translation layer itself cannot drift.

Today's mesh has ONE axis; its logical name is :data:`DATA_AXIS`.  When the
hybrid ICI×DCN mesh lands, :func:`split_to_spec` grows the rules table and
the migrated call sites need no further edits — that is the point of
executing tranche 0 now.
"""

from __future__ import annotations

from typing import Optional, Tuple

__all__ = [
    "DATA_AXIS",
    "AxisSpec",
    "named",
    "split_to_spec",
    "spec_to_split",
    "is_named",
]

# the single mesh axis every split indexes into today (matches the
# one-dimensional device mesh the Communication layer builds)
DATA_AXIS = "data"


class AxisSpec(int):
    """A split axis index that also speaks the named-spec vocabulary.

    Subclasses :class:`int` so equality, hashing, arithmetic, slicing,
    formatting and serialization are EXACTLY the raw index's — migrated
    ``split=`` call sites are behavior-identical by construction, not by
    testing alone (the tests prove the construction holds).
    """

    __slots__ = ()

    @property
    def axis_name(self) -> str:
        """Logical name of the mesh axis this split maps onto."""
        return DATA_AXIS

    def spec(self, ndim: int) -> Tuple[Optional[str], ...]:
        """PartitionSpec-style view for an ``ndim``-rank array."""
        return split_to_spec(int(self), ndim)

    # deliberately NO __repr__ override: on an int subclass, object.__str__
    # delegates to __repr__, so a custom repr would change str()/f-string/
    # format() output — exactly the kind of silent behavior drift the shim
    # promises cannot happen.  Debug identity comes from is_named()/axis_name.


def named(split: Optional[int]) -> Optional[AxisSpec]:
    """The named view of a split axis; ``None`` (replicated) stays ``None``.

    This is the tranche-0 rewrite target: ``split=0`` → ``split=named(0)``.
    The linter's split inventory reads through it (``absint._literal_split``),
    so migrating a site changes neither the runtime nor the catalogs.
    """
    if split is None:
        return None
    if isinstance(split, bool) or not isinstance(split, int):
        raise TypeError(f"split must be an int axis or None, got {split!r}")
    return AxisSpec(split)


def split_to_spec(split: Optional[int], ndim: int) -> Tuple[Optional[str], ...]:
    """``split=1, ndim=3`` → ``(None, 'data', None)``; replicated → all-None."""
    if ndim < 0:
        raise ValueError(f"ndim must be non-negative, got {ndim}")
    if split is None:
        return (None,) * ndim
    ax = int(split)
    if ax < 0:
        ax += ndim
    if not 0 <= ax < ndim:
        raise ValueError(f"split {split} out of range for ndim {ndim}")
    return tuple(DATA_AXIS if i == ax else None for i in range(ndim))


def spec_to_split(spec: Tuple[Optional[str], ...]) -> Optional[int]:
    """Inverse of :func:`split_to_spec`; raises on specs the single-axis
    world cannot express (more than one named axis) instead of guessing."""
    hits = [i for i, name in enumerate(spec) if name is not None]
    if not hits:
        return None
    if len(hits) > 1:
        raise ValueError(
            f"spec {spec!r} names {len(hits)} axes — not expressible as a "
            "single split (that is the refactor's destination, not the shim's)"
        )
    if spec[hits[0]] != DATA_AXIS:
        raise ValueError(f"unknown mesh axis {spec[hits[0]]!r} (have {DATA_AXIS!r})")
    return hits[0]


def is_named(split) -> bool:
    """True when a split value already carries the named view."""
    return isinstance(split, AxisSpec)
