"""PCA / IncrementalPCA (reference: ``heat/decomposition/pca.py``).

PCA routes through the distributed SVD layer: tall row-split data uses the
hierarchical SVD (``hsvd_rank``/``hsvd_rtol``) or exact TS-SVD, exactly the
reference's dispatch (SURVEY §2.4).
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ..core import types
from ..core.base import BaseEstimator, TransformMixin
from ..core.dndarray import DNDarray
from ..linalg import svdtools
from ..core.communication import Communication

__all__ = ["PCA", "IncrementalPCA"]


def _wrap(jarr, split, proto: DNDarray) -> DNDarray:
    if split is not None and split >= jarr.ndim:
        split = None
    jarr = proto.comm.shard(jarr, split)
    return DNDarray(
        jarr, tuple(jarr.shape), types.canonical_heat_type(jarr.dtype), split, proto.device, proto.comm, True
    )


class PCA(TransformMixin, BaseEstimator):
    """Principal component analysis via distributed SVD.

    ``svd_solver``: 'full' (TS-SVD), 'hierarchical' (hsvd), 'randomized'
    (rsvd) — the reference's three solvers.
    """

    def __init__(
        self,
        n_components: Optional[Union[int, float]] = None,
        copy: bool = True,
        whiten: bool = False,
        svd_solver: str = "hierarchical",
        tol: Optional[float] = None,
        iterated_power: int = 0,
        n_oversamples: int = 10,
        power_iteration_normalizer: str = "qr",
        random_state: Optional[int] = None,
    ):
        if whiten:
            raise NotImplementedError("whiten=True not supported (reference parity)")
        self.n_components = n_components
        self.copy = copy
        self.whiten = whiten
        self.svd_solver = svd_solver
        self.tol = tol
        self.iterated_power = iterated_power
        self.n_oversamples = n_oversamples
        self.power_iteration_normalizer = power_iteration_normalizer
        self.random_state = random_state

        self.components_ = None
        self.explained_variance_ = None
        self.explained_variance_ratio_ = None
        self.singular_values_ = None
        self.mean_ = None
        self.n_components_ = None
        self.total_explained_variance_ratio_ = None

    def fit(self, x: DNDarray, y=None) -> "PCA":
        if x.ndim != 2:
            raise ValueError("PCA requires 2-D data (n_samples, n_features)")
        n, d = x.shape
        mean = x.mean(axis=0)
        xc = x - mean
        self.mean_ = mean

        k = self.n_components
        if k is None:
            k = min(n, d)
        if isinstance(k, float):
            k_int = min(n, d)
        else:
            k_int = int(k)

        if self.svd_solver == "full":
            U, S, V = svdtools.svd(xc)
            s = S._jarray
            comps = V._jarray.T  # (d_eff, d) row components
        elif self.svd_solver == "hierarchical":
            U, S, V, err = svdtools.hsvd_rank(xc, maxrank=k_int, compute_sv=True)
            s = S._jarray
            comps = V._jarray.T
        elif self.svd_solver == "randomized":
            U, S, V = svdtools.rsvd(xc, rank=k_int, n_oversamples=self.n_oversamples,
                                    power_iter=self.iterated_power)
            s = S._jarray
            comps = V._jarray.T
        else:
            raise ValueError(f"Unknown svd_solver {self.svd_solver!r}")

        var = (s**2) / max(n - 1, 1)
        total_var = jnp.sum(jnp.var(xc._jarray, axis=0, ddof=1)) if n > 1 else jnp.sum(var)
        ratio = var / jnp.maximum(total_var, 1e-30)

        if isinstance(self.n_components, float):
            # keep enough components to reach the requested variance fraction.
            # searchsorted of a scalar probe is 0-d but not a reduction, so
            # the autofixer refuses it — the sanctioned host_fetch route is
            # applied by hand (collective-correct, retried, deadline-guarded)
            csum = jnp.cumsum(ratio)
            k_int = int(Communication.host_fetch(jnp.searchsorted(csum, self.n_components))) + 1
        k_int = min(k_int, s.shape[0])

        self.components_ = _wrap(comps[:k_int], None, x)
        self.singular_values_ = _wrap(s[:k_int], None, x)
        self.explained_variance_ = _wrap(var[:k_int], None, x)
        self.explained_variance_ratio_ = _wrap(ratio[:k_int], None, x)
        self.total_explained_variance_ratio_ = float(Communication.host_fetch(jnp.sum(ratio[:k_int])))
        self.n_components_ = k_int
        return self

    def transform(self, x: DNDarray) -> DNDarray:
        if self.components_ is None:
            raise RuntimeError("fit must be called before transform")
        xc = x - self.mean_
        res = xc._jarray @ self.components_._jarray.T
        return _wrap(res, x.split, x)

    def inverse_transform(self, x: DNDarray) -> DNDarray:
        res = x._jarray @ self.components_._jarray + self.mean_._jarray[None, :]
        return _wrap(res, x.split, x)


class IncrementalPCA(TransformMixin, BaseEstimator):
    """Streaming PCA: SVD factors merged batch-by-batch (reference API)."""

    def __init__(self, n_components: Optional[int] = None, copy: bool = True,
                 whiten: bool = False, batch_size: Optional[int] = None):
        if whiten:
            raise NotImplementedError("whiten=True not supported")
        self.n_components = n_components
        self.copy = copy
        self.whiten = whiten
        self.batch_size = batch_size
        self.components_ = None
        self.singular_values_ = None
        self.mean_ = None
        self.n_samples_seen_ = 0
        self._us = None  # running U·S sketch

    def partial_fit(self, x: DNDarray, y=None) -> "IncrementalPCA":
        n_new, d = x.shape
        jx = x._jarray
        n_old = self.n_samples_seen_
        n_tot = n_old + n_new
        mean_new = jnp.mean(jx, axis=0)
        if n_old == 0:
            mean = mean_new
            stack = jx - mean
        else:
            mean_old = self.mean_._jarray
            mean = (n_old * mean_old + n_new * mean_new) / n_tot
            # mean-correction row (Ross et al. incremental SVD)
            corr = jnp.sqrt(n_old * n_new / n_tot) * (mean_old - mean_new)
            stack = jnp.concatenate([self._us, jx - mean_new[None, :], corr[None, :]], axis=0)
        u, s, vt = jnp.linalg.svd(stack, full_matrices=False)
        k = self.n_components or min(stack.shape)
        k = min(k, s.shape[0])
        self._us = s[:k, None] * vt[:k]  # keep the (k, d) sketch Σ·Vᵀ
        comm, device = x.comm, x.device
        self.mean_ = DNDarray(comm.shard(mean, None), (d,), x.dtype, None, device, comm, True)
        self._vt = vt[:k]
        self._s = s[:k]
        self.n_samples_seen_ = n_tot
        self.components_ = DNDarray(comm.shard(vt[:k], None), tuple(vt[:k].shape), x.dtype, None, device, comm, True)
        self.singular_values_ = DNDarray(comm.shard(s[:k], None), (int(s[:k].shape[0]),), x.dtype, None, device, comm, True)
        return self

    def fit(self, x: DNDarray, y=None) -> "IncrementalPCA":
        n = x.shape[0]
        bs = self.batch_size or max(1, 5 * (self.n_components or 10))
        self.n_samples_seen_ = 0
        self._us = None
        for lo in range(0, n, bs):
            self.partial_fit(x[lo : min(lo + bs, n)])
        return self

    def transform(self, x: DNDarray) -> DNDarray:
        xc = x._jarray - self.mean_._jarray[None, :]
        res = xc @ self.components_._jarray.T
        res = x.comm.shard(res, x.split)
        return DNDarray(res, tuple(res.shape), x.dtype, x.split, x.device, x.comm, True)
