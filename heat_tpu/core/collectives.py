"""Bucketed hierarchical gradient sync: two-level collectives + overlap.

DASO and ``DataParallelOptimizer`` historically synchronized in one
monolithic, serialized shot — the exact stall ``scripts/stepprof.py``'s
``STEP-OVERLAP kind=daso.step`` line made measurable (PR 11's committed
before-number).  Following "Generalized hierarchical all-reduce"
(arXiv 2004.09362), an allreduce over ``p = d·i`` participants decomposes
into *reduce-scatter in the fast domain (i members) → cross-domain exchange
of the 1/i shard (d domains) → allgather back in the fast domain*, and
following the dominant-term analysis of "The Big Send-off" (arXiv
2504.18658), the sync payload splits into byte-budgeted **buckets** whose
transfers pipeline against the consuming compute — bucket k's blend/update
runs while bucket k+1's collective is in flight.  This module is both
halves:

- **Bucket planner** (:func:`plan_grad_buckets`): PURE — packs the
  flattened grad/param pytree's leaves into contiguous buckets of at most
  ``budget`` bytes (an oversized leaf gets its own bucket; K=1 degenerates
  to the monolithic path, reason recorded).  Budget resolution order:
  explicit ``grad_bucket_bytes=`` kwarg → process default
  (:func:`set_grad_bucket_budget`) → ``HEAT_TPU_GRAD_BUCKET_BYTES`` env
  (read once at import; K/M/G suffixes via the same
  :func:`~heat_tpu.core.redistribution.parse_budget` the resplit budget
  uses).

- **Stage math** (:func:`_hier_stage_factors` / :func:`_daso_stage_factors`):
  per-stage wire-traffic factors.  The two-level decomposition telescopes
  EXACTLY — (i−1)/i + 2(d−1)/(d·i) + (i−1)/i = 2(p−1)/p, the flat ring
  factor — so ``comm.allreduce.bytes`` accounted stage-by-stage reconciles
  against the monolithic accounting to the byte (cumulative-rounding
  telescoping across stages AND buckets, the ``execute_plan`` discipline:
  the sum over any K-bucket split equals the K=1 total exactly).

- **Executors** (:func:`bucketed_param_sync`, :func:`bucketed_grad_allreduce`
  and their dispatch/consume halves): double-buffered lookahead-1 pipelines.
  Bucket k+1's collective is dispatched before bucket k is awaited, so at
  most TWO buckets are ever in flight (transient peak ≤ budget + one
  bucket, the resplit bound, observed by the memledger from inside); every
  bucket's staging routes through ``Communication._account_bytes`` — the
  existing choke point — so flight-ring seq stamps, the ``comm.collective``
  fault site, armed deadlines, and telemetry counters see the new path for
  free, and each bucket is awaited through ``health.guard_blocking`` so one
  hung bucket trips ``CollectiveTimeoutError`` at the offending bucket
  (with its seq/op in the flight ring for the post-mortem) instead of
  wedging the step.  Per-bucket programs live in the PR 1 sharding-keyed
  program cache: steady state recompiles nothing.

Opt-in only: ``DASO(overlap_sync=True)``, ``DataParallelOptimizer(
overlap_sync=True)`` and ``DataParallel.make_train_step(overlap_sync=True)``
route here; the default paths are bit-exact untouched.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from .redistribution import parse_budget

# device-memory-ledger hook (``utils.memledger.enable()`` pokes the module
# in): the executors register every in-flight bucket average (category
# ``transient``), fire the ``mem.alloc`` fault site ahead of each bucket's
# staging, and consume each bucket the moment its blend/update dispatched —
# so ``mem.live_bytes`` observes the budget + one-bucket pipeline contract
# FROM INSIDE.  Disabled cost: one module-global load per sync.  Module
# bottom re-arms.
_MEMLEDGER = None

__all__ = [
    "GradBucketPlan",
    "plan_grad_buckets",
    "set_grad_bucket_budget",
    "get_grad_bucket_budget",
    "bucketed_param_sync",
    "dispatch_bucket_averages",
    "consume_bucket_averages",
    "bucketed_grad_allreduce",
    "dispatch_bucket_allreduce",
]


# ---------------------------------------------------------------------- #
# process-wide default bucket budget (same resolution order as resplit)
# ---------------------------------------------------------------------- #
_DEFAULT_BUDGET: Optional[int] = parse_budget(
    os.environ.get("HEAT_TPU_GRAD_BUCKET_BYTES")
)


def set_grad_bucket_budget(budget) -> Optional[int]:
    """Set the process-wide default gradient-bucket budget (bytes; K/M/G
    string suffixes accepted; ``None``/``0`` restores unbounded =
    monolithic single-bucket sync).  Returns the previous value so callers
    can scope-and-restore."""
    global _DEFAULT_BUDGET
    prev = _DEFAULT_BUDGET
    _DEFAULT_BUDGET = parse_budget(budget)
    return prev


def get_grad_bucket_budget() -> Optional[int]:
    """The process-wide default grad-bucket budget in bytes (None =
    unbounded: the whole tree syncs as one bucket)."""
    return _DEFAULT_BUDGET


# ---------------------------------------------------------------------- #
# planner (pure — no jax; unit-testable standalone)
# ---------------------------------------------------------------------- #
@dataclass(frozen=True)
class GradBucketPlan:
    """A flattened pytree's leaves packed into K contiguous byte-budgeted
    buckets.  ``buckets[k]`` holds the leaf indices of bucket k, in tree
    order — contiguity keeps the per-bucket programs' signatures stable
    across steps, which is what keeps the program cache at 100% hits."""

    leaf_nbytes: Tuple[int, ...]
    budget: Optional[int]
    buckets: Tuple[Tuple[int, ...], ...]
    total_bytes: int
    reason: str

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)

    def bucket_nbytes(self, k: int) -> int:
        return sum(self.leaf_nbytes[j] for j in self.buckets[k])

    @property
    def max_bucket_bytes(self) -> int:
        return max(
            (self.bucket_nbytes(k) for k in range(self.n_buckets)), default=0
        )


def plan_grad_buckets(leaf_nbytes: Sequence[int], budget=None) -> GradBucketPlan:
    """Pack leaves (given by their byte sizes, tree order) into buckets of
    at most ``budget`` bytes each.  ``budget=None`` resolves to the process
    default (:func:`set_grad_bucket_budget` / ``HEAT_TPU_GRAD_BUCKET_BYTES``);
    pass ``0`` to force the monolithic single bucket regardless of the
    default.  A leaf larger than the budget gets its own bucket (best
    effort — the budget floors at one leaf, like resplit's floor-at-one-
    slice)."""
    sizes = tuple(int(n) for n in leaf_nbytes)
    total = sum(sizes)
    if budget is None:
        budget = get_grad_bucket_budget()
    else:
        budget = parse_budget(budget)
    if not sizes:
        return GradBucketPlan(sizes, budget, (), 0, "no-leaves")
    if budget is None:
        return GradBucketPlan(
            sizes, None, (tuple(range(len(sizes))),), total, "no-budget"
        )
    if total <= budget:
        return GradBucketPlan(
            sizes, budget, (tuple(range(len(sizes))),), total, "fits-in-budget"
        )
    buckets: List[Tuple[int, ...]] = []
    cur: List[int] = []
    cur_bytes = 0
    for j, nb in enumerate(sizes):
        if cur and cur_bytes + nb > budget:
            buckets.append(tuple(cur))
            cur, cur_bytes = [], 0
        cur.append(j)
        cur_bytes += nb
    if cur:
        buckets.append(tuple(cur))
    return GradBucketPlan(sizes, budget, tuple(buckets), total, "bucketed")


# ---------------------------------------------------------------------- #
# stage math: per-stage wire-traffic factors of the two-level path
# ---------------------------------------------------------------------- #
def _hier_stage_factors(p: int, d: int) -> Optional[Tuple[float, float, float]]:
    """Wire factors (reduce-scatter, cross-domain exchange, allgather) of a
    two-level allreduce over ``p = d·i`` participants, in units of one
    participant's payload.  ``None`` means the hierarchy degenerates (one
    domain, or one member per domain) and the caller takes the flat path.
    The three stages telescope exactly to the flat ring factor:
    (i−1)/i + 2(d−1)/(d·i) + (i−1)/i = 2(p−1)/p."""
    if d <= 1 or p % d or p // d <= 1:
        return None
    i = p // d
    return ((i - 1) / i, 2.0 * (d - 1) / (d * i), (i - 1) / i)


def _daso_stage_factors(d: int, i: int) -> Tuple[float, float]:
    """Wire factors (cross-domain exchange, allgather) of the DASO bucket
    sync on the ('dcn', 'ici') mesh, in units of one GROUP's payload.  The
    reduce-scatter stage is a local slice (params are replicated over
    'ici'), so it moves zero wire bytes; the exchange psums the 1/i chunk
    across the d groups; the allgather rebuilds the full payload in the
    fast domain."""
    return (2.0 * (d - 1) / (d * i), (i - 1) / i)


class _Telescope:
    """Cumulative-rounding byte accountant (the ``execute_plan``
    discipline): ``wire(x)`` returns ``round(moved+x) − accounted`` so the
    SUM over any split into stages/buckets equals the monolithic
    ``round(total)`` to the byte — K-invariance of ``comm.allreduce.bytes``."""

    __slots__ = ("moved", "accounted")

    def __init__(self):
        self.moved = 0.0
        self.accounted = 0

    def wire(self, nbytes: float) -> int:
        self.moved += nbytes
        w = int(round(self.moved)) - self.accounted
        self.accounted += w
        return w


def _account_stages(comm, tele: _Telescope, payload: float, factors, x=None) -> None:
    """Stage each hierarchical stage's wire bytes through the existing
    ``Communication._account_bytes`` choke point under ``comm.allreduce``:
    one flight-ring seq stamp + ``comm.collective`` fault firing +
    telemetry counter per stage, telescoped so the K-bucket total
    reconciles against the monolithic accounting exactly."""
    for f in factors:
        if f <= 0.0:
            continue
        comm._account_bytes("allreduce", tele.wire(payload * f), x=x)


def _await_bucket(arrs, what: str = "comm.allreduce") -> None:
    """Await one in-flight bucket through the watchdog: under an armed
    ``comm.deadline`` a hung bucket trips ``CollectiveTimeoutError`` at the
    offending bucket; with telemetry armed the blocked time lands as a
    ``comm.allreduce.wait`` leaf record (stepprof's comm-wait input);
    disarmed it is a bare await."""
    import jax

    from ..utils import health as _hlth

    _hlth.guard_blocking(
        lambda: jax.block_until_ready(arrs),  # heatlint: disable=HT107 — routed through guard_blocking: watchdogged under an armed deadline, timed leaf record otherwise
        what,
    )


def _ledger_dispatch(bucket_bytes: int, avg_leaves) -> None:
    ml = _MEMLEDGER
    if ml is None:
        return
    # the mem.alloc fault site, per bucket: chaos CI injects deterministic
    # mid-sync allocation failures HERE
    ml.alloc_check(bucket_bytes, "comm.allreduce.bucket")
    for a in avg_leaves:
        # explicit category: these are in-flight sync transients even when
        # dispatched inside a daso.step span (which would infer opt-state)
        ml.register(
            a, op="allreduce.bucket", site="allreduce.bucket", category="transient"
        )


def _ledger_consume(avg_leaves) -> None:
    ml = _MEMLEDGER
    if ml is None:
        return
    for a in avg_leaves:
        ml.consume(a)


def _bucket_counters(bucket_bytes: int) -> None:
    from ..utils import profiler as _prof
    from ..utils import telemetry as _tel

    _tel.counter_inc("comm.allreduce.buckets", 1)
    _prof.counter_max("comm.allreduce.peak_bucket_bytes", bucket_bytes)


# ---------------------------------------------------------------------- #
# shard-level two-level allreduce body (single mesh axis, subgroup-based)
# ---------------------------------------------------------------------- #
def _hier_groups(p: int, d: int):
    """(intra, inter) ``axis_index_groups`` for a two-level decomposition
    of ``p`` participants into ``d`` contiguous domains of ``i = p // d``
    members: intra-domain groups are the contiguous blocks (the fast tier),
    inter-domain groups are the strided transversals (member k of every
    domain — the slow tier exchanging chunk k)."""
    i = p // d
    intra = [list(range(g * i, (g + 1) * i)) for g in range(d)]
    inter = [[g * i + k for g in range(d)] for k in range(i)]
    return intra, inter


def _hierarchical_body(x, axis: str, p: int, d: int, mean: bool = False):
    """Shard-level two-level allreduce of ``x`` over mesh axis ``axis``
    (valid only inside ``shard_map``): reduce-scatter within each domain,
    cross-domain exchange of the 1/i shard, allgather back.  Raw ``lax``
    collectives — byte accounting belongs to the STAGING caller (the
    ``_account_stages`` choke-point delegation), never to the traced body,
    so cached program replays can never under-account."""
    import jax.numpy as jnp
    from jax import lax

    factors = _hier_stage_factors(p, d)
    if factors is None:
        out = lax.psum(x, axis)
        return out / p if mean else out
    i = p // d
    intra, inter = _hier_groups(p, d)
    flat = x.reshape(-1)
    n = flat.shape[0]
    pad = (-n) % i
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    # stage 1: reduce-scatter in the fast domain — member k of each domain
    # ends up owning chunk k of the domain-local sum
    chunk = lax.psum_scatter(
        flat, axis, scatter_dimension=0, axis_index_groups=intra, tiled=True
    )
    # stage 2: cross-domain exchange — the 1/i shard allreduces across the
    # d domains (the only traffic that crosses the slow tier)
    chunk = lax.psum(chunk, axis, axis_index_groups=inter)
    if mean:
        chunk = chunk / p
    # stage 3: allgather in the fast domain rebuilds the full payload
    full = lax.all_gather(chunk, axis, axis=0, axis_index_groups=intra, tiled=True)
    if pad:
        full = full[:n]
    return full.reshape(x.shape)


def _derive_domains(comm, domains=None) -> int:
    """Topology-derived slow-domain count: one domain per host process when
    that divides the axis size (the DCN/ICI boundary a multi-host mesh
    exposes), else 1 (single domain → flat fallback).  An explicit
    ``domains`` overrides — tests and single-host benches use it to model a
    multi-host topology."""
    p = comm.size
    d = comm.n_processes if domains is None else int(domains)
    if d <= 1 or p % d or p // d <= 1:
        return 1
    return d


# ---------------------------------------------------------------------- #
# DASO bucket engine: ('dcn', 'ici') mesh, params stacked over groups
# ---------------------------------------------------------------------- #
def _daso_sig(leaves, idxs) -> tuple:
    import jax.numpy as jnp

    return tuple((tuple(leaves[j].shape), str(jnp.dtype(leaves[j].dtype))) for j in idxs)


def _daso_avg_program(comm, mesh, sig, n_leaves: int, d: int, i: int):
    from ._cache import cached_program

    def build():
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from .communication import _jax_shard_map

        def body(*leaves):
            outs = []
            for g in leaves:
                flat = g.reshape(-1)
                n = flat.shape[0]
                pad = (-n) % i
                if pad:
                    flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
                chunk = (n + pad) // i
                k = lax.axis_index("ici")
                # reduce-scatter degenerates to a local slice: params are
                # replicated over 'ici', so chunk k needs no wire traffic
                mine = lax.dynamic_slice_in_dim(flat, k * chunk, chunk)
                # cross-domain exchange: the 1/i chunk allreduces over the
                # d groups (the slow tier) and becomes the group mean
                mine = lax.psum(mine, "dcn") / d
                # allgather in the fast domain rebuilds the full payload
                full = lax.all_gather(mine, "ici", axis=0, tiled=True)
                if pad:
                    full = full[:n]
                outs.append(full.reshape(g.shape))
            return tuple(outs)

        fn = _jax_shard_map(
            body,
            mesh=mesh,
            in_specs=(P("dcn"),) * n_leaves,
            out_specs=(P("dcn"),) * n_leaves,
            check_vma=False,
        )
        return jax.jit(fn)

    return cached_program(comm, ("daso.bucket_avg", sig, d, i), build)


def _blend_program(comm, sig, n_leaves: int):
    from ._cache import cached_program

    def build():
        import jax

        def f(ps, avgs, w):
            return tuple((1.0 - w) * p + w * a for p, a in zip(ps, avgs))

        # pre-blend replicas donated (freed into the blend); the averages
        # are kept — the ledger consume below is their logical death
        return jax.jit(f, donate_argnums=(0,))

    return cached_program(comm, ("daso.bucket_blend", sig, n_leaves), build)


def dispatch_bucket_averages(comm, leaves, plan: GradBucketPlan, k: int, tele: _Telescope):
    """Stage bucket ``k``'s cross-group average: byte-account every
    hierarchical stage through ``comm._account_bytes`` (seq stamps, fault
    site, deadline, counters), fire the ledger's ``mem.alloc`` site, then
    dispatch the cached per-bucket program.  Returns the in-flight average
    leaves (async)."""
    mesh = comm.mesh
    d = int(mesh.shape["dcn"])
    i = int(mesh.shape["ici"])
    idxs = plan.buckets[k]
    bucket_bytes = plan.bucket_nbytes(k)
    # accounting basis: one GROUP's payload (the per-shard convention of
    # the flat collectives — stacked bytes / d)
    _account_stages(
        comm, tele, bucket_bytes / d, _daso_stage_factors(d, i), x=leaves[idxs[0]]
    )
    _bucket_counters(bucket_bytes)
    prog = _daso_avg_program(comm, mesh, _daso_sig(leaves, idxs), len(idxs), d, i)
    avgs = list(prog(*(leaves[j] for j in idxs)))
    _ledger_dispatch(bucket_bytes, avgs)
    return avgs


def consume_bucket_averages(comm, leaves, avgs, plan: GradBucketPlan, k: int, w):
    """Consume bucket ``k``: await its in-flight average under the
    watchdog, blend it into the bucket's parameter leaves (donating the
    pre-blend replicas), and retire the transient from the ledger.
    Mutates ``leaves`` in place."""
    idxs = plan.buckets[k]
    _await_bucket(avgs)
    blend = _blend_program(comm, _daso_sig(leaves, idxs), len(idxs))
    out = blend(tuple(leaves[j] for j in idxs), tuple(avgs), w)
    for j, b in zip(idxs, out):
        leaves[j] = b
    _ledger_consume(avgs)


def bucketed_param_sync(comm, params, w, plan: Optional[GradBucketPlan] = None, budget=None):
    """DASO's overlapped cross-group parameter sync: bucket the stacked
    parameter tree, pipeline bucket k+1's collective against bucket k's
    blend (lookahead-1: at most two buckets in flight, transient peak ≤
    budget + one bucket), and return the blended tree.  ``w`` is the blend
    weight (1.0 = full sync).  Semantically identical to
    ``blend(params, global_average(params), w)`` for every bucket count —
    bucketing splits work, never math."""
    import jax

    mesh = comm.mesh
    if int(mesh.shape["dcn"]) <= 1:
        return params  # one group: the cross-group mean is the identity
    leaves, treedef = jax.tree_util.tree_flatten(params)
    if plan is None:
        plan = plan_grad_buckets([a.nbytes for a in leaves], budget)
    if not plan.n_buckets:
        return params
    leaves = list(leaves)
    tele = _Telescope()
    avgs = dispatch_bucket_averages(comm, leaves, plan, 0, tele)
    for k in range(plan.n_buckets):
        nxt = (
            dispatch_bucket_averages(comm, leaves, plan, k + 1, tele)
            if k + 1 < plan.n_buckets
            else None
        )
        consume_bucket_averages(comm, leaves, avgs, plan, k, w)
        avgs = nxt
    return jax.tree_util.tree_unflatten(treedef, leaves)


def dispatch_all_bucket_averages(comm, params, plan: Optional[GradBucketPlan] = None, budget=None):
    """Dispatch EVERY bucket's average without consuming (DASO's stale
    pending path: averages dispatched at step t, blended ``stale_steps``
    later).  All K transients ride in flight — the lookahead-1 bound
    applies to the immediate path, not this one (documented in design.md).
    Returns ``(plan, [bucket averages])`` or None when the mesh has one
    group."""
    import jax

    mesh = comm.mesh
    if int(mesh.shape["dcn"]) <= 1:
        return None
    leaves = jax.tree_util.tree_flatten(params)[0]
    if plan is None:
        plan = plan_grad_buckets([a.nbytes for a in leaves], budget)
    tele = _Telescope()
    return plan, [
        dispatch_bucket_averages(comm, list(leaves), plan, k, tele)
        for k in range(plan.n_buckets)
    ]


def consume_bucket_averages_all(comm, params, pending, w):
    """Blend a :func:`dispatch_all_bucket_averages` result into ``params``
    bucket by bucket (each awaited under the watchdog)."""
    import jax

    if pending is None:
        return params
    plan, all_avgs = pending
    leaves, treedef = jax.tree_util.tree_flatten(params)
    leaves = list(leaves)
    for k in range(plan.n_buckets):
        consume_bucket_averages(comm, leaves, all_avgs[k], plan, k, w)
    return jax.tree_util.tree_unflatten(treedef, leaves)


# ---------------------------------------------------------------------- #
# DataParallel bucket engine: stacked per-shard grads → replicated mean
# ---------------------------------------------------------------------- #
def _grad_mean_program(comm, sig, n_leaves: int, p: int, d: int):
    from ._cache import cached_program

    axis = comm.axis
    mesh = comm.mesh

    def build():
        import jax
        from jax.sharding import PartitionSpec as P

        from .communication import _jax_shard_map

        def body(*leaves):
            outs = []
            for g in leaves:
                # g: (1, ...) — this shard's gradient block
                outs.append(_hierarchical_body(g[0], axis, p, d, mean=True))
            return tuple(outs)

        fn = _jax_shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis),) * n_leaves,
            # the two-level path ends in an allgather: every shard holds the
            # full mean, so the outputs are replicated
            out_specs=(P(),) * n_leaves,
            check_vma=False,
        )
        return jax.jit(fn)

    return cached_program(comm, ("grad.bucket_mean", sig, p, d), build)


def dispatch_bucket_allreduce(comm, leaves, plan: GradBucketPlan, k: int, tele: _Telescope, d: int):
    """Stage bucket ``k`` of a stacked-gradient mean-allreduce: account the
    two-level stages (or the flat factor when ``d == 1``) through
    ``comm._account_bytes``, then dispatch the cached program.  Returns the
    in-flight replicated mean leaves."""
    p = comm.size
    idxs = plan.buckets[k]
    bucket_bytes = plan.bucket_nbytes(k)
    factors = _hier_stage_factors(p, d)
    if factors is None:
        factors = (2.0 * (p - 1) / p,)  # flat fallback: one ring stage
    # accounting basis: one shard's payload (stacked bytes / p)
    _account_stages(comm, tele, bucket_bytes / p, factors, x=leaves[idxs[0]])
    _bucket_counters(bucket_bytes)
    prog = _grad_mean_program(comm, _daso_sig(leaves, idxs), len(idxs), p, d)
    means = list(prog(*(leaves[j] for j in idxs)))
    _ledger_dispatch(bucket_bytes, means)
    return means


def bucketed_grad_allreduce(
    comm,
    stacked_grads,
    budget=None,
    domains=None,
    plan: Optional[GradBucketPlan] = None,
):
    """Mean-allreduce a pytree of per-shard gradients stacked on a leading
    axis sharded over ``comm``'s mesh axis, bucketed and hierarchical:
    reduce-scatter in the fast domain → cross-domain exchange → allgather,
    with bucket k+1 in flight while bucket k is awaited.  ``domains=None``
    derives the slow-domain count from the process topology (flat allreduce
    when the world has one domain).  Returns the replicated mean tree (leaf
    shapes without the stacking axis)."""
    import jax

    d = _derive_domains(comm, domains)
    leaves, treedef = jax.tree_util.tree_flatten(stacked_grads)
    if plan is None:
        plan = plan_grad_buckets([a.nbytes for a in leaves], budget)
    if not plan.n_buckets:
        return stacked_grads
    tele = _Telescope()
    out: List = [None] * len(leaves)
    means = dispatch_bucket_allreduce(comm, leaves, plan, 0, tele, d)
    for k in range(plan.n_buckets):
        nxt = (
            dispatch_bucket_allreduce(comm, leaves, plan, k + 1, tele, d)
            if k + 1 < plan.n_buckets
            else None
        )
        _await_bucket(means)
        for j, m in zip(plan.buckets[k], means):
            out[j] = m
        _ledger_consume(means)
        means = nxt
    return jax.tree_util.tree_unflatten(treedef, out)


# the memory ledger may have been env-armed (HEAT_TPU_MEMLEDGER=1) while
# this module was still importing — re-read the flag now (defensive
# module-bottom re-arm, the established hot-path-hook pattern)
import sys as _sys  # noqa: E402

_ml = _sys.modules.get("heat_tpu.utils.memledger")
if _ml is not None and getattr(_ml, "enabled", lambda: False)():
    _MEMLEDGER = _ml
del _sys, _ml
