#!/usr/bin/env python
"""heatlint CLI — static analysis of heat_tpu's distributed invariants.

Usage:
    python scripts/heatlint.py heat_tpu/ benchmarks/ tutorials/
    python scripts/heatlint.py heat_tpu/ --json out.json    # machine output
    python scripts/heatlint.py heat_tpu/ --sarif out.sarif  # PR annotations
    python scripts/heatlint.py heat_tpu/ --write-baseline   # regenerate
    python scripts/heatlint.py heat_tpu/ --select HT3*      # prefix wildcard
    python scripts/heatlint.py heat_tpu/ --split-inventory SPLIT_INVENTORY.json
    python scripts/heatlint.py --list-rules                 # severity + level

Exit codes: 0 = clean (no ERROR findings beyond the committed baseline),
1 = new error findings, 2 = usage error.  ``info``-severity findings (the
interprocedural rules' unresolved-call downgrades) never gate — they are
counted in the summary, listed with ``--show-info``, and carried in the
JSON/SARIF output at note level.

Suppressions: ``# heatlint: disable=HT101`` on the offending line,
``# heatlint: disable-file=HT101`` anywhere for the whole file.
The baseline (default: .heatlint-baseline.json next to the repo root)
grandfathers pre-existing findings by fingerprint — line drift does not
invalidate it, and ``--write-baseline`` regenerates it after intentional
changes.  The interprocedural passes cache per-file effect summaries in
``.heatlint-summaries.json`` (keyed by content hash; ``--no-cache``
disables, ``--summaries-cache`` relocates).
"""

from __future__ import annotations

import argparse
import importlib
import importlib.util
import json
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_analysis():
    """Import ``heat_tpu.analysis`` WITHOUT importing ``heat_tpu`` itself:
    the linter is pure stdlib, and the CI lint lane (like any pre-commit
    hook) must not need jax/numpy installed just to parse source files.
    A synthetic parent package keeps the relative imports working."""
    name = "_heatlint_analysis"
    if name in sys.modules:
        # a second loader in the same process (two test modules both
        # importing the CLI) must get the FRAMEWORK back, not the synthetic
        # parent package
        return sys.modules[name + ".framework"]
    pkg_dir = os.path.join(REPO, "heat_tpu", "analysis")
    pkg = types.ModuleType(name)
    pkg.__path__ = [pkg_dir]
    sys.modules[name] = pkg
    spec = importlib.util.spec_from_file_location(
        name + ".framework", os.path.join(pkg_dir, "framework.py")
    )
    framework = importlib.util.module_from_spec(spec)
    sys.modules[name + ".framework"] = framework
    spec.loader.exec_module(framework)
    pkg.framework = framework
    rules = importlib.import_module(name + ".rules")
    pkg.rules = rules
    return framework


_fw = _load_analysis()
all_rules = _fw.all_rules
lint_paths = _fw.lint_paths
load_baseline = _fw.load_baseline
render_json = _fw.render_json
render_sarif = _fw.render_sarif
render_text = _fw.render_text
split_by_baseline = _fw.split_by_baseline
write_baseline = _fw.write_baseline

DEFAULT_BASELINE = os.path.join(REPO, ".heatlint-baseline.json")
DEFAULT_SUMMARIES_CACHE = os.path.join(REPO, ".heatlint-summaries.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="heatlint", description=__doc__)
    ap.add_argument("paths", nargs="*", help="files or directories to lint")
    ap.add_argument("--select", help="comma-separated rule codes (default: all)")
    ap.add_argument(
        "--baseline",
        default=DEFAULT_BASELINE,
        help="baseline file of grandfathered findings (default: %(default)s)",
    )
    ap.add_argument(
        "--no-baseline", action="store_true", help="ignore the baseline (report everything as new)"
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="write ALL current findings to the baseline file and exit 0",
    )
    ap.add_argument("--json", metavar="FILE", help="write JSON findings to FILE ('-' = stdout)")
    ap.add_argument(
        "--sarif",
        metavar="FILE",
        help="write SARIF 2.1.0 findings to FILE (for codeql-action/upload-sarif)",
    )
    ap.add_argument(
        "--show-baselined", action="store_true", help="also print grandfathered findings"
    )
    ap.add_argument(
        "--show-info",
        action="store_true",
        help="also print info-severity (non-gating, unresolved-call-downgraded) findings",
    )
    ap.add_argument(
        "--summaries-cache",
        default=DEFAULT_SUMMARIES_CACHE,
        help="interprocedural summary cache file (default: %(default)s)",
    )
    ap.add_argument(
        "--no-cache",
        action="store_true",
        help="disable the interprocedural summary cache",
    )
    ap.add_argument("--list-rules", action="store_true", help="list registered rules and exit")
    ap.add_argument(
        "--split-inventory",
        metavar="FILE",
        help="write the split-semantics site catalog (the mesh-refactor "
        "work list: every .split read, split= kwarg, resplit* call, split "
        "parameter) as JSON to FILE ('-' = stdout)",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        # severity + program-level flag: a program-level rule consumes the
        # package-wide Program (call graph + summaries + absint); a file
        # rule sees one parsed module at a time
        for rule in all_rules():
            level = "program" if rule.program_level else "file"
            print(
                f"{rule.code}  {rule.name:32s} [{level:7s}] [{rule.severity}]  "
                f"{rule.description}"
            )
        return 0

    if not args.paths:
        ap.error("no paths given (try: heat_tpu/)")

    select = [c for c in (args.select or "").split(",") if c.strip()] or None
    cache_path = None if args.no_cache else args.summaries_cache
    unresolved: list = []
    split_inventory: list = []
    try:
        findings = lint_paths(
            args.paths,
            select=select,
            cache_path=cache_path,
            unresolved_out=unresolved,
            split_inventory_out=(
                split_inventory if args.split_inventory else None
            ),
        )
    except ValueError as exc:
        print(f"heatlint: {exc}", file=sys.stderr)
        return 2

    # normalize paths relative to the baseline file's directory so the
    # committed baseline matches regardless of how the CLI was invoked
    # (absolute path, relative path, different cwd)
    base_dir = os.path.dirname(os.path.abspath(args.baseline)) or "."

    def _norm(p: str) -> str:
        abs_p = os.path.abspath(p)
        if abs_p.startswith(base_dir + os.sep):
            return os.path.relpath(abs_p, base_dir).replace(os.sep, "/")
        return p.replace(os.sep, "/")

    for f in findings:
        f.path = _norm(f.path)
        for hop in f.trace:
            hop["path"] = _norm(hop["path"])
    for u in unresolved:
        u["caller_path"] = _norm(u["caller_path"])
    for s in split_inventory:
        s["path"] = _norm(s["path"])

    if args.split_inventory:
        by_kind: dict = {}
        for s in split_inventory:
            by_kind[s["kind"]] = by_kind.get(s["kind"], 0) + 1
        catalog = json.dumps(
            {
                "version": 1,
                "comment": (
                    "Every site whose behavior depends on single-split-axis "
                    "semantics — the named-axis mesh refactor's work list. "
                    "The committed snapshot covers the full lint scope; "
                    "regenerate with: python scripts/heatlint.py heat_tpu/ "
                    "benchmarks/ tutorials/ --split-inventory SPLIT_INVENTORY.json"
                ),
                "count": len(split_inventory),
                "by_kind": {k: by_kind[k] for k in sorted(by_kind)},
                "sites": split_inventory,
            },
            indent=2,
        )
        if args.split_inventory == "-":
            print(catalog)
        else:
            with open(args.split_inventory, "w", encoding="utf-8") as fh:
                fh.write(catalog + "\n")

    # info findings (unresolved-call downgrades) are reported, never gated,
    # never baselined: a baseline entry would imply a human signed off on a
    # conclusion the analysis itself says it cannot prove
    errors = [f for f in findings if f.severity == "error"]
    info = [f for f in findings if f.severity != "error"]

    if args.write_baseline:
        if select:
            print(
                "heatlint: --write-baseline cannot be combined with --select "
                "(a rule-scoped run would silently drop every other rule's "
                "grandfathered findings from the baseline)",
                file=sys.stderr,
            )
            return 2
        # a baseline write only speaks for the files THIS run linted:
        # grandfathered findings in files outside the given paths are
        # preserved, so a narrow run can't silently shrink the baseline
        linted = {_norm(p) for p in _fw.iter_python_files(args.paths)}
        preserved = [
            _fw.Finding(
                rule=r["rule"], path=r["path"], line=r.get("line", 1), col=0,
                message=r.get("message", ""), qualname=r.get("qualname", "<module>"),
                detail=r.get("detail", ""),
            )
            for r in _fw.load_baseline_records(args.baseline)
            if r.get("path") not in linted
        ]
        write_baseline(args.baseline, list(errors) + preserved)
        print(
            f"heatlint: wrote {len(errors)} finding(s) to {args.baseline}"
            + (f" (+{len(preserved)} preserved outside the linted paths)" if preserved else "")
            + (f" ({len(info)} info finding(s) not baselined)" if info else "")
        )
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new, grandfathered = split_by_baseline(errors, baseline)

    if args.json:
        # the unresolved bucket rides along in the machine output: the
        # honesty policy's audit trail of every call the engine could not
        # place, with its reason — never silently dropped
        payload = render_json(new, grandfathered, info=info, unresolved=unresolved)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")

    if args.sarif:
        sarif = render_sarif(new, grandfathered, info=info, rules=all_rules(select))
        with open(args.sarif, "w", encoding="utf-8") as fh:
            fh.write(sarif + "\n")

    print(
        render_text(
            new,
            grandfathered,
            verbose_baselined=args.show_baselined,
            info=info,
            show_info=args.show_info,
        )
    )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
