"""Ring attention: sequence-parallel exact attention over the mesh ring.

SURVEY §5.7: the reference has no attention, but its ring skeleton
(``spatial.cdist``) is exactly ring attention's KV rotation.  This module is
that composition made concrete — blockwise (flash-style) softmax
accumulation while K/V blocks rotate via ``lax.ppermute`` over the ICI ring,
so sequence length scales with the mesh: each chip holds S/p of the sequence
and peak memory is one block pair.

Shapes: ``q, k, v`` are ``(..., S, d)`` — any leading batch/head axes —
sharded along the sequence axis over ``comm``.  Do NOT wrap the call in
``jax.vmap`` for batching (that would trace the collectives per batch
entry); the leading axes broadcast through the accumulator natively.

Ragged sequences (``S % p != 0``) ride the ring too: the sequence axis is
zero-padded to ``ceil(S/p)·p``, pad *keys* are masked out of every score
block (the same pad-and-mask scheme ``DNDarray`` uses for ragged splits),
pad *queries* compute garbage that is sliced off — so a prime-length
sequence on 8 chips stays fully sequence-parallel instead of falling back
to the O(S²)-memory global path (round-3 verdict weak #2).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..core._cache import comm_cached

__all__ = ["ring_attention", "ring_self_attention"]

# Eager engagement counters — tests assert the ring path (K/V rotation over
# the mesh) handles a given shape.  "global" counts the single-chip local
# path: no collective, whole sequence on one chip — executed by the Pallas
# flash kernel on TPU or the dense form elsewhere (ops.flash_attention
# decides and keeps its own pallas/dense counters).  Incremented per *call*
# (at trace time when called under an outer jit).
path_counts = {"ring": 0, "global": 0}


def _global_attention(q, k, v, causal, scale):
    """Dense attention: materializes the (Sq, Sk) score block.  Rectangular
    shapes supported (cross-attention callers); the causal mask is top-left
    aligned (torch ``is_causal``).  Delegates to the shared dense reference
    in ``ops.flash_attention`` so there is exactly ONE dense softmax path
    (same fully-masked-row and pad-key semantics everywhere)."""
    from ..ops.flash_attention import _dense_attention

    return _dense_attention(q, k, v, causal, scale, k.shape[-2])


def _block_impl(comm, kernel: str) -> str:
    """Resolve the per-ring-step attention implementation (static — baked
    into the cached ring program).  ``kernel='auto'`` uses the Pallas flash
    kernel when the comm's devices are TPUs and falls back to the dense jnp
    block elsewhere; ``'flash'`` forces the kernel (interpreter off-TPU —
    test scale only); ``'dense'`` forces the jnp block."""
    from ..ops.flash_attention import _HAS_PALLAS

    platform = next(iter(comm.mesh.devices.flat)).platform
    if kernel == "auto":
        return "pallas" if (_HAS_PALLAS and platform == "tpu") else "dense"
    if kernel == "flash":
        if not _HAS_PALLAS:
            raise RuntimeError("kernel='flash' requires pallas")
        return "pallas" if platform == "tpu" else "interpret"
    if kernel == "dense":
        return "dense"
    raise ValueError(f"kernel must be 'auto'|'flash'|'dense', got {kernel!r}")


def ring_attention(q, k, v, comm, causal: bool = False, scale: Optional[float] = None,
                   kernel: str = "auto"):
    """Exact softmax attention, sequence-parallel over the mesh ring.

    ``q, k, v`` have shape ``(..., S, d)`` — any leading batch/head axes —
    with the sequence axis sharded over ``comm``.  Each chip holds
    ``ceil(S/p)`` of the sequence; K/V blocks rotate via ``lax.ppermute``
    while a blockwise (flash-style) online softmax accumulates, so the
    (S, S) score matrix never materializes and peak memory is one block
    pair per chip.  Any S is sequence-parallel — non-divisible lengths are
    zero-padded and the pad keys masked (see module docstring).

    On TPU each ring step runs the Pallas flash kernel over its local
    (S/p, S/p) block (``ops.flash_attention_block``), so per-chip score
    memory is one kernel tile — O(blk·512) — rather than the whole
    (S/p)² block; blocks merge exactly across steps via their logsumexp.
    ``kernel`` picks the per-step implementation (see :func:`_block_impl`).

    CROSS-attention is sequence-parallel too: ``k``/``v`` may carry a
    different sequence length than ``q`` (leading axes and ``d`` must
    match) — each chip keeps its resident S_q/p query block while the
    S_kv/p key/value blocks rotate, so encoder-decoder attention scales
    with the mesh exactly like self-attention.  ``causal`` with
    rectangular shapes keeps the top-left-aligned convention (query at
    global position i attends key positions <= i).
    """
    S, d = q.shape[-2:]
    S_kv = k.shape[-2]
    if scale is None:
        scale = 1.0 / (d**0.5)
    try:
        # scale is baked into the compiled program (and into the comm cache
        # key), so it must be a static scalar; concrete jnp scalars coerce
        scale = float(scale)
    except Exception as e:
        raise TypeError(
            "ring_attention's scale must be a static Python/NumPy scalar — "
            "it is compiled into the cached ring program; a traced value "
            "(e.g. a jit argument) is not supported"
        ) from e
    if k.shape != v.shape or k.shape[:-2] != q.shape[:-2] or k.shape[-1] != d:
        # the sharded ring path has no broadcast semantics (each operand is
        # split with its own seq axis; only the kv sequence length may
        # differ from q's) — demand congruent shapes up front
        raise ValueError(
            f"ring_attention requires k.shape == v.shape and q/k agreeing "
            f"in every axis but the sequence, got {q.shape}, {k.shape}, "
            f"{v.shape} — broadcast/repeat shared K/V (e.g. MQA) to q's "
            f"leading shape before the call"
        )
    axis, size = comm.axis, comm.size
    if size == 1:
        # degenerate ring: one chip holds the whole sequence — run the
        # flash-fused local kernel (Pallas on TPU, dense fallback elsewhere)
        path_counts["global"] += 1
        if k.shape == q.shape:
            from ..ops.flash_attention import flash_attention

            return flash_attention(q, k, v, causal=causal, scale=scale)
        return _global_attention(q, k, v, causal, scale)
    path_counts["ring"] += 1

    seq_axis = q.ndim - 2
    blk_q = -(-S // size)  # ceil-div blocks; last block(s) carry pad rows
    blk_k = -(-S_kv // size)
    pad_q = blk_q * size - S
    pad_k = blk_k * size - S_kv

    def _pad_seq(t, pad):
        widths = [(0, 0)] * t.ndim
        widths[seq_axis] = (0, pad)
        return jnp.pad(t, widths)

    if pad_q:
        q = _pad_seq(q, pad_q)
    if pad_k:
        k = _pad_seq(k, pad_k)
        v = _pad_seq(v, pad_k)

    out = _ring_program(comm, causal, scale, S, S_kv, q.ndim,
                        _block_impl(comm, kernel))(q, k, v)
    if pad_q:
        out = lax.slice_in_dim(out, 0, S, axis=seq_axis)
    return out


@comm_cached
def _ring_program(comm, causal: bool, scale: float, S: int, S_kv: int,
                  nd: int, impl: str):
    """Jitted + comm-cached ring pipeline (same recompile lesson as TSQR:
    a fresh shard_map closure per eager call would retrace AND recompile
    every invocation — MultiheadAttention's ring path calls this eagerly).
    Keyed on (causal, scale, S, S_kv, ndim, impl); dtype/leading-shape
    changes retrace under the cached jit wrapper.

    Each ring step attends the resident Q block against the visiting K/V
    block with ``ops.flash_attention_block`` — the Pallas flash kernel on
    TPU (``impl='pallas'``), its interpreter (tests), or the shared dense
    jnp block — which returns the normalized block output plus the row
    logsumexp.  Blocks over disjoint key sets merge EXACTLY:
    ``lse' = logaddexp(lse, lse_b)``; ``o' = o·e^{lse−lse'} + o_b·e^{lse_b−lse'}``.
    Key positions rotate with their K/V block (int32 vector through the
    same ppermute), so causal/pad masking follows the data, not the step
    index — the kernel's per-tile live predicate skips fully-future and
    pad-only tiles (the causal FLOP saving), replacing the old outer cond."""
    from ..ops.flash_attention import flash_attention_block

    axis, size = comm.axis, comm.size
    seq_axis = nd - 2
    blk = -(-S // size)
    blk_k = -(-S_kv // size)

    def shard_fn(q_blk, k_blk, v_blk):
        # q_blk: (..., blk, d); k/v: (..., blk_k, d) — cross-attention may
        # carry a different kv length; all math broadcasts over the leading
        # axes
        my = lax.axis_index(axis)
        q_pos = (my * blk + jnp.arange(blk)).astype(jnp.int32)
        kv_pos0 = (my * blk_k + jnp.arange(blk_k)).astype(jnp.int32)

        # an evenly-divisible non-causal ring has no pad keys and no causal
        # constraint: pass the no-pad sentinel so the block skips mask
        # construction entirely (the pre-kernel code's masked fast path)
        s_valid = S_kv if (causal or blk_k * size != S_kv) else 2**31 - 1

        def block(k_rot, v_rot, kpos_rot):
            return flash_attention_block(
                q_blk, k_rot, v_rot, q_pos, kpos_rot,
                causal=causal, scale=scale, s_valid=s_valid, impl=impl,
            )

        def step(carry, _):
            k_rot, v_rot, kpos_rot, o, lse = carry
            if causal and impl == "dense":
                # skip the two GEMMs entirely when the whole K/V block is in
                # the future of every query here (~2x causal FLOP saving);
                # the pallas kernel does this per-tile via its live predicate
                fully_future = jnp.min(kpos_rot) > jnp.max(q_pos)
                ob, lb = lax.cond(
                    fully_future,
                    lambda k_, v_, p_: (
                        jnp.zeros(q_blk.shape, q_blk.dtype),
                        jnp.full(q_blk.shape[:-1], -1e30, jnp.float32),
                    ),
                    block,
                    k_rot, v_rot, kpos_rot,
                )
            else:
                ob, lb = block(k_rot, v_rot, kpos_rot)
            lse_new = jnp.logaddexp(lse, lb)
            w_old = jnp.exp(lse - lse_new)
            w_new = jnp.exp(lb - lse_new)
            o = o * w_old[..., None] + ob.astype(o.dtype) * w_new[..., None]
            perm = [((j + 1) % size, j) for j in range(size)]
            k_next = lax.ppermute(k_rot, axis, perm)
            v_next = lax.ppermute(v_rot, axis, perm)
            kpos_next = lax.ppermute(kpos_rot, axis, perm)
            return (k_next, v_next, kpos_next, o, lse_new), None

        o0 = jnp.zeros(q_blk.shape, jnp.float32)
        # −1e30, not −inf: the first merge computes exp(lse0 − lse'), and
        # −inf − finite is fine but −inf − (−inf) (all-masked first block
        # sentinel) would NaN; 1e30 underflows identically
        lse0 = jnp.full(q_blk.shape[:-1], -1e30, jnp.float32)
        (k_f, v_f, p_f, o, lse), _ = lax.scan(
            step, (k_blk, v_blk, kv_pos0, o0, lse0), None, length=size
        )
        return o.astype(q_blk.dtype)

    return jax.jit(comm.shard_map(
        shard_fn,
        in_splits=((nd, seq_axis),) * 3,
        out_splits=(nd, seq_axis),
    ))


def ring_self_attention(q, k, v, comm, causal: bool = False, scale: Optional[float] = None):
    """2-D ``(S, d)`` alias of :func:`ring_attention` (original API)."""
    return ring_attention(q, k, v, comm, causal=causal, scale=scale)
