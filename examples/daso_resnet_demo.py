"""DASO hierarchical training demo (BASELINE config[4] shape).

The reference's DASO baseline trains ResNet-50/ImageNet with node-local NCCL
sync every step + async global MPI parameter averaging every k steps
(``heat/optim/dp_optimizer.py::DASO``).  The TPU-native equivalent runs the
same schedule over a ('dcn', 'ici') mesh.  This demo uses a small ResNet on
synthetic image data so it runs anywhere (8 virtual CPU devices by default).

Run: python examples/daso_resnet_demo.py
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

# default to the virtual CPU mesh; set HEAT_TPU_DEMO_DEVICE=tpu to run on TPU
if os.environ.get("HEAT_TPU_DEMO_DEVICE", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import heat_tpu as ht


def main():
    model = ht.nn.models.resnet(stage_sizes=(1, 1), width=16, num_classes=4, in_channels=3)

    opt = ht.optim.DataParallelOptimizer("sgd", lr=0.05, momentum=0.9)
    daso = ht.optim.DASO(opt, global_skip=4, stale_steps=1, warmup_steps=2)
    daso.init(model, key=jax.random.key(0))

    rng = np.random.default_rng(0)
    n, side = 256, 16
    labels = rng.integers(0, 4, n)
    # one bright quadrant per class — linearly separable by a tiny CNN
    x = rng.normal(size=(n, 3, side, side)).astype(np.float32) * 0.1
    h = side // 2
    for i, y in enumerate(labels):
        r, c = divmod(int(y), 2)
        x[i, :, r * h : (r + 1) * h, c * h : (c + 1) * h] += 1.0

    loss_fn = ht.nn.functional.cross_entropy
    for epoch in range(6):
        perm = rng.permutation(n)
        losses = []
        for lo in range(0, n, 64):
            sel = perm[lo : lo + 64]
            losses.append(daso.step(loss_fn, x[sel], labels[sel]))
        print(f"epoch {epoch}: loss {np.mean(losses):.4f}")

    params = daso.consolidated_params()
    # train=True: evaluate with batch statistics (running stats are tracked
    # explicitly via BatchNorm.update_stats in this functional design)
    logits = model.apply(params, x, train=True)
    acc = float(np.mean(np.argmax(np.asarray(logits), axis=1) == labels))
    print(f"train accuracy {acc:.3f}")
    assert acc > 0.8, "DASO demo failed to learn"


if __name__ == "__main__":
    main()
