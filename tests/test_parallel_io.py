"""Shard-parallel writes (VERDICT r2 item 6; reference per-rank hyperslab
writes in ``heat/core/io.py::save_hdf5``, SURVEY §5.4).

Every save path must stream one shard at a time — proven via the
``io._CHUNK_WRITES`` counters: a full-gather write would show one chunk of
the whole array's size; the shard-parallel path shows p chunks each a
fraction of it.
"""

import os

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import io as htio
from test_suites.basic_test import TestCase


def reset_counters():
    htio._CHUNK_WRITES["count"] = 0
    htio._CHUNK_WRITES["max_bytes"] = 0


def make_split(shape=(64, 8)):
    rng = np.random.default_rng(0)
    d = rng.uniform(-5, 5, size=shape).astype(np.float32)
    return d, ht.array(d, split=0)


class TestShardParallelWrites(TestCase):
    def test_hdf5_roundtrip_chunked(self, tmp_path):
        if not htio.supports_hdf5():
            pytest.skip("h5py missing")
        d, x = make_split()
        p = x.comm.size
        reset_counters()
        path = str(tmp_path / "a.h5")
        ht.save_hdf5(x, path, "data")
        assert htio._CHUNK_WRITES["count"] == p, "expected one write per shard"
        assert htio._CHUNK_WRITES["max_bytes"] <= d.nbytes // p, (
            f"peak chunk {htio._CHUNK_WRITES['max_bytes']}B — looks like a full gather "
            f"({d.nbytes}B array)"
        )
        back = ht.load_hdf5(path, "data", split=0)
        self.assert_array_equal(back, d)

    def test_hdf5_ragged_roundtrip(self, tmp_path):
        if not htio.supports_hdf5():
            pytest.skip("h5py missing")
        rng = np.random.default_rng(1)
        d = rng.uniform(size=(13, 3)).astype(np.float32)
        x = ht.array(d, split=0)
        path = str(tmp_path / "r.h5")
        reset_counters()
        ht.save_hdf5(x, path, "data")
        # pad rows must never be written
        back = ht.load_hdf5(path, "data", split=0)
        self.assert_array_equal(back, d)

    def test_netcdf_roundtrip_chunked(self, tmp_path):
        if not htio.supports_netcdf():
            pytest.skip("no netcdf backend")
        d, x = make_split((40, 5))
        p = x.comm.size
        reset_counters()
        path = str(tmp_path / "a.nc")
        ht.save_netcdf(x, path, "var")
        assert htio._CHUNK_WRITES["count"] == p
        assert htio._CHUNK_WRITES["max_bytes"] < d.nbytes
        back = ht.load_netcdf(path, "var", split=0)
        self.assert_array_equal(back, d)

    def test_csv_streamed(self, tmp_path):
        d, x = make_split((24, 4))
        p = x.comm.size
        reset_counters()
        path = str(tmp_path / "a.csv")
        ht.save_csv(x, path)
        assert htio._CHUNK_WRITES["count"] == p
        back = ht.load_csv(path, split=0)
        self.assert_array_equal(back, d, rtol=1e-5, atol=1e-5)

    def test_csv_streamed_with_header(self, tmp_path):
        d, x = make_split((16, 3))
        path = str(tmp_path / "h.csv")
        ht.save_csv(x, path, header_lines=["colA,colB,colC"])
        back = ht.load_csv(path, header_lines=1, split=0)
        self.assert_array_equal(back, d, rtol=1e-5, atol=1e-5)

    def test_npy_memmap_streamed(self, tmp_path):
        d, x = make_split((32, 6))
        p = x.comm.size
        reset_counters()
        path = str(tmp_path / "a.npy")
        ht.save(x, path)
        assert htio._CHUNK_WRITES["count"] == p
        assert htio._CHUNK_WRITES["max_bytes"] <= d.nbytes // p
        back = np.load(path)
        np.testing.assert_allclose(back, d)

    def test_replicated_save_single_write(self, tmp_path):
        if not htio.supports_hdf5():
            pytest.skip("h5py missing")
        d = np.arange(12, dtype=np.float32).reshape(3, 4)
        x = ht.array(d, split=None)
        reset_counters()
        ht.save_hdf5(x, str(tmp_path / "rep.h5"), "data")
        assert htio._CHUNK_WRITES["count"] == 1  # replicated: one gather write


class TestArrayCheckpoint(TestCase):
    def test_roundtrip_split0(self, tmp_path):
        d, x = make_split((56, 7))
        p = x.comm.size
        ckpt = str(tmp_path / "ckpt")
        reset_counters()
        ht.save_array_checkpoint(x, ckpt)
        assert htio._CHUNK_WRITES["count"] == p
        assert htio._CHUNK_WRITES["max_bytes"] <= d.nbytes // p
        files = [f for f in os.listdir(ckpt) if f.startswith("chunk_")]
        assert len(files) == p
        back = ht.load_array_checkpoint(ckpt)
        assert back.split == 0
        self.assert_array_equal(back, d)

    def test_roundtrip_ragged(self, tmp_path):
        rng = np.random.default_rng(3)
        d = rng.uniform(size=(19, 4)).astype(np.float32)
        x = ht.array(d, split=0)
        ckpt = str(tmp_path / "rag")
        ht.save_array_checkpoint(x, ckpt)
        back = ht.load_array_checkpoint(ckpt)
        self.assert_array_equal(back, d)

    def test_roundtrip_replicated(self, tmp_path):
        d = np.arange(20, dtype=np.float32).reshape(4, 5)
        x = ht.array(d, split=None)
        ckpt = str(tmp_path / "rep")
        ht.save_array_checkpoint(x, ckpt)
        back = ht.load_array_checkpoint(ckpt)
        assert back.split is None
        self.assert_array_equal(back, d)

    def test_roundtrip_different_mesh_size(self, tmp_path):
        # the loader re-cuts chunk boundaries to ITS mesh: save on 8, load on 3
        import jax
        from jax.sharding import Mesh

        rng = np.random.default_rng(5)
        d = rng.uniform(size=(22, 3)).astype(np.float32)
        x = ht.array(d, split=0)  # world comm (8 devices)
        ckpt = str(tmp_path / "remesh")
        ht.save_array_checkpoint(x, ckpt)
        comm3 = ht.communication.Communication(
            Mesh(np.asarray(jax.devices()[:3]), ("x",)), "x"
        )
        back = ht.load_array_checkpoint(ckpt, comm=comm3)
        assert back.split == 0
        assert back.comm.size == 3
        self.assert_array_equal(back, d)

    def test_roundtrip_split1(self, tmp_path):
        rng = np.random.default_rng(4)
        d = rng.uniform(size=(6, 32)).astype(np.float32)
        x = ht.array(d, split=1)
        ckpt = str(tmp_path / "s1")
        ht.save_array_checkpoint(x, ckpt)
        back = ht.load_array_checkpoint(ckpt)
        assert back.split == 1
        self.assert_array_equal(back, d)
