"""Two-process SPMD tier (round-4 verdict #1; reference contract: the same
suite passes under ``mpirun -n N``, SURVEY §4).

The heavy lifting lives in ``scripts/multiprocess_dryrun.py``: 2 OS
processes × 4 CPU devices under ``jax.distributed`` (gloo), exercising
factories/reductions, ``resplit_``, token-ring hyperslab HDF5, cross-process
``numpy()``/``__repr__``, a DataParallel step, and ``Communication.rank``
semantics at ``n_processes == 2``.  This test launches it as a subprocess
tree (the suite's own jax runtime is single-process and cannot be
re-initialized) and asserts both workers hit every checkpoint.
"""

# assert_distributed exception (r4 #8): the checks run inside the worker
# subprocesses (is_fully_addressable assertions there are the multi-process
# equivalent of assert_distributed).

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "multiprocess_dryrun.py")

_spec = importlib.util.spec_from_file_location("multiprocess_dryrun", SCRIPT)
mpd = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(mpd)


def test_two_process_spmd_tier():
    proc = mpd.launch(timeout=540)  # the one launch contract (see script)
    out = proc.stdout
    assert proc.returncode == 0, (proc.stderr or out)[-2000:]
    assert mpd.PASS_MARKER in out
    for pid in (0, 1):
        assert f"[{pid}] {mpd.MARKER}" in out, out[-2000:]
        assert f"[{pid}] comm: size=8 rank={pid}/2" in out
