"""Cross-rank timeline export: Perfetto traces, clock alignment, blame.

    python scripts/traceviz.py DIR [DIR...] [--out trace.json]
                               [--steps daso.step,sched.job] [--json OUT]
    python scripts/traceviz.py --validate-only trace.json

Merges every artifact the runtime already wrote under the target dirs —
telemetry ``rank<k>.jsonl`` exports, flight-recorder rings (including the
supervisor's harvested ``epoch<N>/`` subdirs), scheduler/federation
journals — into ONE clock-aligned cross-rank timeline
(``heat_tpu/analysis/timeline.py``, loaded standalone: this runs on a
login node that never imports jax).  Prints:

- ``CLOCK-ALIGN rank=… offset_ms=… residual_ms=… anchors=…`` per rank
  (offsets estimated from the shared collective-stamp anchors; a rank
  with no anchors is NAMED unaligned, never silently merged);
- ``CRITICAL-PATH kind=… rank=… op=… seq=… share=…`` per step kind and
  for the cross-rank collective gating chain, plus the per-rank /
  per-op blame tables;
- ``TRACE-EXPORT events=… ranks=… out=…`` after writing the Chrome
  trace-event JSON (``--out``), which is self-validated against the
  stdlib schema checker before this exits 0.

Empty target dirs are not an error (exit 0): a run that recorded nothing
has an empty timeline, not a broken one.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))

_timeline = None


def _timeline_mod():
    """``heat_tpu/analysis/timeline.py`` — via the package when loaded,
    else standalone (the postmortem pattern)."""
    mod = sys.modules.get("heat_tpu.analysis.timeline")
    if mod is not None:
        return mod
    global _timeline
    if _timeline is None:
        import importlib.util

        path = os.path.normpath(
            os.path.join(_HERE, os.pardir, "heat_tpu", "analysis", "timeline.py")
        )
        spec = importlib.util.spec_from_file_location("traceviz_timeline", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        _timeline = mod
    return _timeline


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("targets", nargs="*",
                    help="dirs holding telemetry jsonl / flight rings / journals")
    ap.add_argument("--out", default=None, metavar="TRACE_JSON",
                    help="write the Chrome trace-event JSON here")
    ap.add_argument("--steps", default=None,
                    help="comma-separated step span names (default: stepprof's)")
    ap.add_argument("--json", default=None, metavar="OUT",
                    help="also write the critical-path/alignment structure here")
    ap.add_argument("--validate-only", default=None, metavar="TRACE_JSON",
                    help="schema-check an existing trace file and exit")
    args = ap.parse_args(argv)
    tl = _timeline_mod()

    if args.validate_only:
        try:
            with open(args.validate_only) as fh:
                obj = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"unreadable trace {args.validate_only}: {e}", file=sys.stderr)
            return 1
        problems = tl.validate_chrome_trace(obj)
        if problems:
            for p in problems:
                print(f"INVALID: {p}", file=sys.stderr)
            return 1
        n = len(obj.get("traceEvents", []))
        print(f"TRACE-VALID events={n} file={args.validate_only}")
        return 0

    if not args.targets:
        print("nothing to do: no target dirs (and no --validate-only)",
              file=sys.stderr)
        return 1
    step_names = (
        tuple(s.strip() for s in args.steps.split(",") if s.strip())
        if args.steps else tl.DEFAULT_STEPS
    )
    bundle = tl.assemble(list(args.targets), step_names=step_names)
    if not bundle["ranks"] and not bundle["journals"]:
        # an empty (or artifact-less) dir is an empty timeline, not an error
        print(f"no telemetry/ring/journal artifacts under {args.targets}")
        return 0

    clock = tl.clock_report(bundle)
    if clock:
        print(clock)
    report = tl.critical_path_report(bundle)
    if report:
        print(report)

    trace = tl.to_chrome_trace(bundle)
    problems = tl.validate_chrome_trace(trace)
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(trace, fh)
        print(
            f"TRACE-EXPORT events={len(trace['traceEvents'])} "
            f"ranks={len(bundle['ranks'])} out={args.out}"
        )
    if problems:
        # exporting a trace our own checker rejects is a bug, not a warning
        for p in problems:
            print(f"INVALID: {p}", file=sys.stderr)
        return 1
    if args.json:
        cp = tl.critical_path(bundle, step_names)
        with open(args.json, "w") as fh:
            json.dump({"align": bundle["align"], "critical_path": cp}, fh, indent=1)
        print(f"critical-path JSON written to {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
