"""TransformerLM: teacher-forced forward, KV-cache decode, generation.

assert_distributed exception (r4 #8): the LM operates on raw jax arrays
(token ids / logits) like the other nn modules; the decode path is
single-mesh by design (documented) and its correctness oracle is exact
agreement with the teacher-forced forward below.
"""

import numpy as np
import pytest

import heat_tpu as ht

# long-tail contract tests: nightly-style lane (CI 'test' matrix), excluded
# from the PR smoke lane (VERDICT r4 weak #7)
pytestmark = pytest.mark.heavy
from heat_tpu.nn.models import TransformerLM


def _lm():
    import jax

    lm = TransformerLM(vocab_size=31, embed_dim=16, num_heads=2, depth=2, max_len=32)
    return lm, lm.init(jax.random.key(0))


class TestTransformerLM:
    def test_apply_shapes(self):
        import jax
        import jax.numpy as jnp

        lm, params = _lm()
        toks = jax.random.randint(jax.random.key(1), (3, 9), 0, 31)
        logits = lm.apply(params, toks)
        assert logits.shape == (3, 9, 31)
        assert bool(jnp.isfinite(logits).all())

    def test_too_long_raises(self):
        import jax

        lm, params = _lm()
        toks = jax.random.randint(jax.random.key(1), (1, 33), 0, 31)
        with pytest.raises(ValueError, match="max_len"):
            lm.apply(params, toks)
        with pytest.raises(ValueError, match="max_len"):
            lm.generate(params, toks[:, :16], 17)

    def test_decode_matches_teacher_forced(self):
        """The KV-cache step must reproduce the full causal forward exactly
        (this is the correctness contract of the cache)."""
        import jax
        import jax.numpy as jnp

        lm, params = _lm()
        toks = jax.random.randint(jax.random.key(1), (2, 11), 0, 31)
        full = lm.apply(params, toks)
        caches = [b.init_cache(2, 11) for b in lm.blocks]
        for t in range(11):
            lg, caches = lm.decode_step(params, toks[:, t], t, caches)
            np.testing.assert_allclose(
                np.asarray(lg), np.asarray(full[:, t, :]), rtol=1e-4, atol=1e-5
            )

    def test_greedy_generate_matches_naive(self):
        """generate() == recompute-the-whole-prefix-every-step decoding."""
        import jax
        import jax.numpy as jnp

        lm, params = _lm()
        prompt = jax.random.randint(jax.random.key(1), (2, 4), 0, 31)
        out = lm.generate(params, prompt, 7)
        assert out.shape == (2, 11)
        assert bool((out[:, :4] == prompt).all())
        cur = prompt
        for _ in range(7):
            nxt = jnp.argmax(lm.apply(params, cur)[:, -1, :], axis=-1)
            cur = jnp.concatenate([cur, nxt[:, None].astype(jnp.int32)], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))

    def test_generate_program_cached(self):
        import jax

        lm, params = _lm()
        prompt = jax.random.randint(jax.random.key(1), (2, 4), 0, 31)
        lm.generate(params, prompt, 3)
        n1 = len(lm._gen_programs)
        lm.generate(params, prompt, 3)  # same shapes: reuse
        assert len(lm._gen_programs) == n1
        # prompt length is DYNAMIC: different S0 with the same total
        # reuses the executable (serving loops vary prompt lengths)
        out = lm.generate(params, prompt[:, :2], 5)
        assert out.shape == (2, 7) and len(lm._gen_programs) == n1
        lm.generate(params, prompt, 3, temperature=0.7, key=jax.random.key(2))
        assert len(lm._gen_programs) == n1 + 1  # sampled variant is a new program

    def test_decode_past_capacity_raises(self):
        import jax

        lm, params = _lm()
        mha = lm.blocks[0].mha
        cache = mha.init_cache(1, 2)
        x = jax.random.normal(jax.random.key(0), (1, 1, lm.embed_dim))
        _, cache = mha.decode_step(params["blocks"][0]["mha"], x, cache)
        _, cache = mha.decode_step(params["blocks"][0]["mha"], x, cache)
        with pytest.raises(ValueError, match="past cache capacity"):
            mha.decode_step(params["blocks"][0]["mha"], x, cache)

    def test_sampling(self):
        import jax

        lm, params = _lm()
        prompt = jax.random.randint(jax.random.key(1), (2, 4), 0, 31)
        with pytest.raises(ValueError, match="requires key"):
            lm.generate(params, prompt, 3, temperature=1.0)
        a = lm.generate(params, prompt, 8, temperature=1.5, key=jax.random.key(2))
        b = lm.generate(params, prompt, 8, temperature=1.5, key=jax.random.key(3))
        assert a.shape == b.shape == (2, 12)
        assert bool((a[:, :4] == prompt).all()) and bool((b[:, :4] == prompt).all())
        assert (np.asarray(a) != np.asarray(b)).any()  # different keys, different draws
        # deterministic under the same key
        a2 = lm.generate(params, prompt, 8, temperature=1.5, key=jax.random.key(2))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(a2))

    def test_moe_ffn_variant(self):
        """num_experts= swaps every block's FFN for the expert-parallel MoE
        (Switch-transformer block) — train and generate must both work."""
        import jax
        import jax.numpy as jnp

        comm = ht.communication.get_comm()
        lm = TransformerLM(vocab_size=17, embed_dim=16, num_heads=2, depth=2,
                           max_len=16, comm=comm if comm.size > 1 else None,
                           num_experts=2 * comm.size,
                           moe_capacity_factor=64.0)  # non-binding: decode == apply
        params = lm.init(jax.random.key(0))
        assert "w1" in params["blocks"][0]["ff"]  # MoE params, not dense FFN
        toks = jax.random.randint(jax.random.key(1), (2, 8), 0, 17)
        logits = lm.apply(params, toks)
        assert logits.shape == (2, 8, 17) and bool(jnp.isfinite(logits).all())
        g = jax.grad(
            lambda p: jnp.sum(lm.apply(p, toks) ** 2)
        )(params)
        assert bool(jnp.isfinite(g["blocks"][0]["ff"]["w1"]).all())
        out = lm.generate(params, toks[:, :3], 5)
        assert out.shape == (2, 8) and bool((out[:, :3] == toks[:, :3]).all())
        # decode == teacher-forced forward also for MoE blocks (drop-free
        # decode path; training capacity is not binding at these sizes)
        full = lm.apply(params, toks)
        caches = [b.init_cache(2, 8) for b in lm.blocks]
        for t in range(8):
            lg, caches = lm.decode_step(params, toks[:, t], t, caches)
            np.testing.assert_allclose(
                np.asarray(lg), np.asarray(full[:, t, :]), rtol=1e-4, atol=1e-5
            )

    def test_training_reduces_loss(self):
        """The full family loop: teacher-forced next-token loss + optimizer."""
        import jax
        import jax.numpy as jnp

        lm, params = _lm()
        toks = jax.random.randint(jax.random.key(1), (4, 12), 0, 31)

        def loss_fn(p):
            logits = lm.apply(p, toks[:, :-1])
            tgt = toks[:, 1:]
            return ht.nn.functional.cross_entropy(
                logits.reshape(-1, 31), tgt.reshape(-1)
            )

        opt = ht.optim.DataParallelOptimizer("adam", lr=1e-2)
        opt.init_state(params)
        vg = jax.jit(jax.value_and_grad(loss_fn))
        losses = []
        for _ in range(10):
            l, g = vg(params)
            params = opt.step(params, g)
            losses.append(float(l))
        assert losses[-1] < losses[0]


class TestSeq2Seq:
    def _model(self):
        import jax

        from heat_tpu.nn.models import Seq2SeqTransformer

        m = Seq2SeqTransformer(src_vocab=19, tgt_vocab=23, embed_dim=16,
                               num_heads=2, enc_depth=2, dec_depth=2, max_len=32)
        return m, m.init(jax.random.key(0))

    def test_apply_shapes(self):
        import jax
        import jax.numpy as jnp

        m, params = self._model()
        src = jax.random.randint(jax.random.key(1), (2, 7), 0, 19)
        tgt = jax.random.randint(jax.random.key(2), (2, 9), 0, 23)
        logits = m.apply(params, src, tgt)
        assert logits.shape == (2, 9, 23) and bool(jnp.isfinite(logits).all())

    def test_decode_matches_teacher_forced(self):
        """Self-attention cache + once-projected cross K/V must reproduce
        the full decoder forward exactly."""
        import jax

        m, params = self._model()
        src = jax.random.randint(jax.random.key(1), (2, 7), 0, 19)
        tgt = jax.random.randint(jax.random.key(2), (2, 9), 0, 23)
        full = m.apply(params, src, tgt)
        memory = m.encode(params, src)
        states = [b.decode_state(p, memory, 2, 9)
                  for b, p in zip(m.decoder, params["decoder"])]
        for t in range(9):
            lg, states = m.decode_step(params, tgt[:, t], t, states)
            np.testing.assert_allclose(
                np.asarray(lg), np.asarray(full[:, t, :]), rtol=1e-4, atol=1e-5
            )

    def test_greedy_generate_matches_naive(self):
        import jax
        import jax.numpy as jnp

        m, params = self._model()
        src = jax.random.randint(jax.random.key(1), (2, 7), 0, 19)
        out = m.generate(params, src, 6, bos_id=1)
        assert out.shape == (2, 7) and bool((out[:, 0] == 1).all())
        cur = jnp.ones((2, 1), jnp.int32)
        for _ in range(6):
            nxt = jnp.argmax(m.apply(params, src, cur)[:, -1, :], axis=-1)
            cur = jnp.concatenate([cur, nxt[:, None].astype(jnp.int32)], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))

    def test_program_cached_and_sampling(self):
        import jax

        m, params = self._model()
        src = jax.random.randint(jax.random.key(1), (2, 7), 0, 19)
        m.generate(params, src, 4)
        n1 = len(m._gen_programs)
        m.generate(params, src, 4)
        assert len(m._gen_programs) == n1
        a = m.generate(params, src, 4, temperature=1.0, key=jax.random.key(2))
        b = m.generate(params, src, 4, temperature=1.0, key=jax.random.key(2))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        with pytest.raises(ValueError, match="requires key"):
            m.generate(params, src, 4, temperature=1.0)

    def test_moe_ffn_variant(self):
        """num_experts= swaps FFNs for MoE in BOTH the encoder and decoder
        stacks; teacher forcing, decode and beam search all work and the
        decode==apply contract holds (drop-free decode, loose capacity)."""
        import jax
        import jax.numpy as jnp

        from heat_tpu.nn.models import Seq2SeqTransformer

        m = Seq2SeqTransformer(src_vocab=11, tgt_vocab=9, embed_dim=16,
                               num_heads=2, enc_depth=1, dec_depth=1,
                               max_len=16, num_experts=4,
                               moe_capacity_factor=64.0)
        params = m.init(jax.random.key(0))
        assert "w1" in params["encoder"][0]["ff"] and "w1" in params["decoder"][0]["ff"]
        src = jax.random.randint(jax.random.key(1), (2, 5), 0, 11)
        tgt = jax.random.randint(jax.random.key(2), (2, 6), 0, 9)
        full = m.apply(params, src, tgt)
        assert bool(jnp.isfinite(full).all())
        states = [b.decode_state(p, m.encode(params, src), 2, 6)
                  for b, p in zip(m.decoder, params["decoder"])]
        for t in range(6):
            lg, states = m.decode_step(params, tgt[:, t], t, states)
            np.testing.assert_allclose(
                np.asarray(lg), np.asarray(full[:, t, :]), rtol=1e-4, atol=1e-5
            )
        out = m.beam_search(params, src, 4, beam_width=3, bos_id=1)
        assert out.shape == (2, 5) and bool(jnp.isfinite(out.astype(jnp.float32)).all())

    def test_copy_task_trains(self):
        """Seq2seq lifecycle: learn the identity mapping src -> src."""
        import jax
        import jax.numpy as jnp

        from heat_tpu.nn.models import Seq2SeqTransformer

        m = Seq2SeqTransformer(src_vocab=8, tgt_vocab=8, embed_dim=32,
                               num_heads=4, enc_depth=1, dec_depth=1, max_len=16)
        params = m.init(jax.random.key(0))
        src = jax.random.randint(jax.random.key(1), (8, 6), 2, 8)
        # teacher forcing: tgt input = [BOS, src[:-1]], label = src
        bos = jnp.ones((8, 1), jnp.int32)
        tgt_in = jnp.concatenate([bos, src[:, :-1]], axis=1)

        def loss_fn(p):
            logits = m.apply(p, src, tgt_in)
            return ht.nn.functional.cross_entropy(
                logits.reshape(-1, 8), src.reshape(-1)
            )

        opt = ht.optim.DataParallelOptimizer("adam", lr=1e-2)
        opt.init_state(params)
        vg = jax.jit(jax.value_and_grad(loss_fn))
        losses = []
        for _ in range(30):
            l, g = vg(params)
            params = opt.step(params, g)
            losses.append(float(l))
        assert losses[-1] < losses[0]


class TestSamplingTruncation:
    def test_top_k_restricts_support(self):
        """With top_k=2 only the two highest-probability tokens are ever
        drawn; with top_k=1 sampling degenerates to greedy."""
        import jax
        import jax.numpy as jnp

        from heat_tpu.nn.models import _next_token

        logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0]])
        draws = set()
        k = jax.random.key(0)
        for _ in range(60):
            nxt, k = _next_token(logits, True, jnp.float32(1.0), k, 2, None)
            draws.add(int(nxt[0]))
        assert draws <= {2, 3} and len(draws) == 2
        nxt, _ = _next_token(logits, True, jnp.float32(1.0), jax.random.key(1), 1, None)
        assert int(nxt[0]) == 3

    def test_top_p_nucleus(self):
        """A dominant token forms the whole nucleus at small p; at p close
        to 1 the full support returns."""
        import jax
        import jax.numpy as jnp

        from heat_tpu.nn.models import _next_token

        logits = jnp.asarray([[8.0, 0.0, 0.0, 0.0]])  # p(top) ~ 0.999
        k = jax.random.key(0)
        for _ in range(30):
            nxt, k = _next_token(logits, True, jnp.float32(1.0), k, None, 0.5)
            assert int(nxt[0]) == 0
        # flat-ish logits, p=0.999: every token can appear
        logits = jnp.asarray([[0.0, 0.1, 0.2, 0.3]])
        draws = set()
        for _ in range(200):
            nxt, k = _next_token(logits, True, jnp.float32(1.0), k, None, 0.999)
            draws.add(int(nxt[0]))
        assert draws == {0, 1, 2, 3}

    def test_nucleus_never_masks_everything(self):
        """Ties straddling the nucleus boundary (or tiny p) must keep the
        top token(s), never degenerate to index 0 (round-4d review)."""
        import jax
        import jax.numpy as jnp

        from heat_tpu.nn.models import _next_token

        logits = jnp.asarray([[0.0, 5.0, 5.0]])  # tied top pair, index 0 is junk
        k = jax.random.key(0)
        for _ in range(40):
            nxt, k = _next_token(logits, True, jnp.float32(1.0), k, None, 0.4)
            assert int(nxt[0]) in (1, 2)

    def test_truncation_normalization(self):
        """transformers conventions: top_k=0 disables; no-op knobs do not
        fork duplicate compiled programs; invalid values raise."""
        import jax

        from heat_tpu.nn.models import _normalize_truncation

        assert _normalize_truncation(0, None, 31, True) == (None, None)
        assert _normalize_truncation(99, 1.0, 31, True) == (None, None)
        assert _normalize_truncation(50, 0.9, 31, False) == (None, None)
        with pytest.raises(ValueError, match="top_k"):
            _normalize_truncation(-1, None, 31, True)
        with pytest.raises(ValueError, match="top_p"):
            _normalize_truncation(None, 0.0, 31, True)

        lm, params = _lm()
        prompt = jax.random.randint(jax.random.key(1), (2, 4), 0, 31)
        lm.generate(params, prompt, 3)
        n0 = len(lm._gen_programs)
        # greedy ignores truncation -> same program as plain greedy
        lm.generate(params, prompt, 3, top_k=5)
        assert len(lm._gen_programs) == n0
        # sampled with no-op knobs -> same program as plain sampled
        lm.generate(params, prompt, 3, temperature=1.0, key=jax.random.key(2))
        n1 = len(lm._gen_programs)
        lm.generate(params, prompt, 3, temperature=1.0, top_k=0, top_p=1.0,
                    key=jax.random.key(2))
        assert len(lm._gen_programs) == n1

    def test_generate_with_truncation(self):
        import jax

        lm, params = _lm()
        prompt = jax.random.randint(jax.random.key(1), (2, 4), 0, 31)
        n0 = len(getattr(lm, "_gen_programs", {}))
        a = lm.generate(params, prompt, 6, temperature=1.0, top_k=5,
                        key=jax.random.key(2))
        assert a.shape == (2, 10) and bool((a[:, :4] == prompt).all())
        b = lm.generate(params, prompt, 6, temperature=1.0, top_p=0.9,
                        key=jax.random.key(2))
        assert b.shape == (2, 10)
        # distinct truncation settings are distinct compiled programs
        assert len(lm._gen_programs) == n0 + 2


class TestBeamSearch:
    def _model(self):
        import jax

        from heat_tpu.nn.models import Seq2SeqTransformer

        m = Seq2SeqTransformer(src_vocab=7, tgt_vocab=5, embed_dim=16,
                               num_heads=2, enc_depth=1, dec_depth=1, max_len=16)
        return m, m.init(jax.random.key(0))

    def test_width_one_is_greedy(self):
        import jax

        m, params = self._model()
        src = jax.random.randint(jax.random.key(1), (2, 5), 0, 7)
        b1 = m.beam_search(params, src, 4, beam_width=1, bos_id=1)
        g = m.generate(params, src, 4, bos_id=1)
        np.testing.assert_array_equal(np.asarray(b1), np.asarray(g))

    def test_exhaustive_width_finds_global_optimum(self):
        """With beam_width >= V^n the search is exhaustive and must return
        the argmax-probability sequence (brute-force oracle)."""
        import itertools

        import jax
        import jax.numpy as jnp

        m, params = self._model()
        src = jax.random.randint(jax.random.key(1), (2, 5), 0, 7)
        n = 3

        def seq_logprob(tgt_seq):
            bos = jnp.ones((2, 1), jnp.int32)
            inp = jnp.concatenate([bos, tgt_seq[:, :-1]], axis=1)
            lp = jax.nn.log_softmax(m.apply(params, src, inp), axis=-1)
            return jnp.take_along_axis(lp, tgt_seq[:, :, None], axis=2)[:, :, 0].sum(axis=1)

        lp_fn = jax.jit(seq_logprob)
        best_lp = np.full(2, -np.inf)
        best_seq = np.zeros((2, n), np.int32)
        for cand in itertools.product(range(5), repeat=n):
            c = jnp.tile(jnp.asarray(cand, jnp.int32)[None, :], (2, 1))
            lp = np.asarray(lp_fn(c))
            for b in range(2):
                if lp[b] > best_lp[b]:
                    best_lp[b] = lp[b]
                    best_seq[b] = cand
        out = np.asarray(m.beam_search(params, src, n, beam_width=125, bos_id=1))[:, 1:]
        np.testing.assert_array_equal(out, best_seq)

        # a practical width must score at least as well as greedy
        b4 = np.asarray(m.beam_search(params, src, n, beam_width=4, bos_id=1))[:, 1:]
        g = np.asarray(m.generate(params, src, n, bos_id=1))[:, 1:]
        lp4 = np.asarray(lp_fn(jnp.asarray(b4)))
        lpg = np.asarray(lp_fn(jnp.asarray(g)))
        assert (lp4 >= lpg - 1e-5).all()

    def test_validation_and_cache(self):
        import jax

        m, params = self._model()
        src = jax.random.randint(jax.random.key(1), (2, 5), 0, 7)
        with pytest.raises(ValueError, match="beam_width"):
            m.beam_search(params, src, 3, beam_width=0)
        with pytest.raises(ValueError, match="length_penalty"):
            m.beam_search(params, src, 3, beam_width=2, length_penalty=0.6)
        with pytest.raises(ValueError, match="outside vocab"):
            m.beam_search(params, src, 3, beam_width=2, eos_id=5)
        m.beam_search(params, src, 3, beam_width=2)
        n1 = len(m._gen_programs)
        m.beam_search(params, src, 3, beam_width=2)
        assert len(m._gen_programs) == n1  # program reused
        # has_eos is static (new program); the eos VALUE is dynamic
        m.beam_search(params, src, 3, beam_width=2, eos_id=2)
        assert len(m._gen_programs) == n1 + 1
        m.beam_search(params, src, 3, beam_width=2, eos_id=3)
        assert len(m._gen_programs) == n1 + 1  # value sweep reuses program
        # the GNMT alpha sweep is dynamic too — one program for all alphas
        m.beam_search(params, src, 3, beam_width=2, eos_id=2, length_penalty=0.4)
        m.beam_search(params, src, 3, beam_width=2, eos_id=2, length_penalty=0.8)
        assert len(m._gen_programs) == n1 + 1


class TestBeamSearchEos:
    """EOS-aware beam search: finished beams freeze at EOS with
    length-normalized final ranking (VERDICT r4 weak #5) — tested the same
    three ways the fixed-length contract is: width-1 == greedy, exhaustive
    width == brute-force oracle (enumerating EOS transitions), and the
    padding/program-cache contracts."""

    def _model(self):
        import jax

        from heat_tpu.nn.models import Seq2SeqTransformer

        m = Seq2SeqTransformer(src_vocab=7, tgt_vocab=5, embed_dim=16,
                               num_heads=2, enc_depth=1, dec_depth=1, max_len=16)
        return m, m.init(jax.random.key(0))

    def test_width_one_is_greedy_with_eos(self):
        import jax

        m, params = self._model()
        src = jax.random.randint(jax.random.key(1), (4, 5), 0, 7)
        b1 = m.beam_search(params, src, 6, beam_width=1, bos_id=1, eos_id=2)
        g = m.generate(params, src, 6, bos_id=1, eos_id=2)
        np.testing.assert_array_equal(np.asarray(b1), np.asarray(g))

    def test_eos_pads_tail(self):
        """After the first EOS every subsequent token is EOS — the same
        padding contract as generate(eos_id=)."""
        import jax

        m, params = self._model()
        src = jax.random.randint(jax.random.key(3), (4, 5), 0, 7)
        out = np.asarray(m.beam_search(params, src, 8, beam_width=3,
                                       bos_id=1, eos_id=2))
        for b in range(4):
            hits = np.where(out[b, 1:] == 2)[0]
            if len(hits):
                assert (out[b, 1 + hits[0]:] == 2).all()

    @staticmethod
    def _oracle(m, params, src, n, eos, alpha):
        """Brute-force best sequence under EOS beam semantics: enumerate
        every EOS-padded candidate (once EOS appears the tail is EOS),
        score = teacher-forced log-prob up to and including the first EOS,
        rank by score / len**alpha."""
        import itertools

        import jax
        import jax.numpy as jnp

        B = src.shape[0]

        def seq_logprob(tgt_seq):
            bos = jnp.ones((B, 1), jnp.int32)
            inp = jnp.concatenate([bos, tgt_seq[:, :-1]], axis=1)
            lp = jax.nn.log_softmax(m.apply(params, src, inp), axis=-1)
            return jnp.take_along_axis(lp, tgt_seq[:, :, None], axis=2)[:, :, 0]

        lp_fn = jax.jit(seq_logprob)
        best = np.full(B, -np.inf)
        best_seq = np.zeros((B, n), np.int32)
        for cand in itertools.product(range(5), repeat=n):
            cand = np.asarray(cand, np.int32)
            hits = np.where(cand == eos)[0]
            if len(hits):
                if not (cand[hits[0]:] == eos).all():
                    continue  # not beam-reachable: tail must be EOS-padded
                length = hits[0] + 1
            else:
                length = n
            lp = np.asarray(lp_fn(jnp.tile(jnp.asarray(cand)[None], (B, 1))))
            score = lp[:, :length].sum(axis=1) / float(length) ** alpha
            for b in range(B):
                if score[b] > best[b]:
                    best[b] = score[b]
                    best_seq[b] = cand
        return best_seq

    @pytest.mark.parametrize("alpha", [0.0, 0.8])
    def test_exhaustive_width_matches_oracle(self, alpha):
        import jax

        m, params = self._model()
        src = jax.random.randint(jax.random.key(1), (3, 5), 0, 7)
        n, eos = 3, 2
        want = self._oracle(m, params, src, n, eos, alpha)
        out = np.asarray(m.beam_search(params, src, n, beam_width=125, bos_id=1,
                                       eos_id=eos, length_penalty=alpha))[:, 1:]
        np.testing.assert_array_equal(out, want)

    def test_practical_width_at_least_greedy(self):
        """A practical width must normalized-score at least as well as the
        width-1 (greedy) beam under the same ranking rule."""
        import itertools

        import jax
        import jax.numpy as jnp

        m, params = self._model()
        src = jax.random.randint(jax.random.key(5), (3, 5), 0, 7)
        n, eos, alpha = 4, 2, 0.6

        def ranked_score(seqs):
            B = src.shape[0]
            bos = jnp.ones((B, 1), jnp.int32)
            inp = jnp.concatenate([bos, jnp.asarray(seqs[:, :-1])], axis=1)
            lp = jax.nn.log_softmax(m.apply(params, src, inp), axis=-1)
            lp = np.asarray(
                jnp.take_along_axis(lp, jnp.asarray(seqs)[:, :, None], axis=2)
            )[:, :, 0]
            out = np.zeros(B)
            for b in range(B):
                hits = np.where(seqs[b] == eos)[0]
                length = hits[0] + 1 if len(hits) else n
                out[b] = lp[b, :length].sum() / float(length) ** alpha
            return out

        b4 = np.asarray(m.beam_search(params, src, n, beam_width=4, bos_id=1,
                                      eos_id=eos, length_penalty=alpha))[:, 1:]
        b1 = np.asarray(m.beam_search(params, src, n, beam_width=1, bos_id=1,
                                      eos_id=eos, length_penalty=alpha))[:, 1:]
        assert (ranked_score(b4) >= ranked_score(b1) - 1e-5).all()


class TestRoPE:
    def test_relative_shift_invariance(self):
        """The RoPE property: q·k depends only on the relative position."""
        import jax
        import jax.numpy as jnp

        from heat_tpu.nn.attention import apply_rope

        q = jax.random.normal(jax.random.key(0), (1, 2, 1, 8))
        k = jax.random.normal(jax.random.key(1), (1, 2, 1, 8))

        def score(i, j):
            qi = apply_rope(q, jnp.asarray([i]))
            kj = apply_rope(k, jnp.asarray([j]))
            return float(jnp.einsum("bhqd,bhkd->bhqk", qi, kj)[0, 0, 0, 0])

        assert abs(score(3, 1) - score(10, 8)) < 1e-4
        assert abs(score(5, 5) - score(0, 0)) < 1e-4
        # position zero is the identity rotation
        np.testing.assert_allclose(
            np.asarray(apply_rope(q, jnp.asarray([0]))), np.asarray(q), atol=1e-6
        )
        with pytest.raises(ValueError, match="even head dim"):
            apply_rope(jnp.zeros((1, 1, 1, 7)), jnp.asarray([0]))

    def test_rope_lm_decode_contract(self):
        """positions='rope': no learned table, cached decode == teacher-
        forced forward, greedy generate == naive prefix recompute."""
        import jax
        import jax.numpy as jnp

        lm = TransformerLM(vocab_size=31, embed_dim=16, num_heads=2, depth=2,
                           max_len=32, positions="rope")
        params = lm.init(jax.random.key(0))
        assert "pos" not in params
        toks = jax.random.randint(jax.random.key(1), (2, 9), 0, 31)
        full = lm.apply(params, toks)
        caches = [b.init_cache(2, 9) for b in lm.blocks]
        for t in range(9):
            lg, caches = lm.decode_step(params, toks[:, t], t, caches)
            np.testing.assert_allclose(
                np.asarray(lg), np.asarray(full[:, t, :]), rtol=1e-4, atol=1e-5
            )
        out = lm.generate(params, toks[:, :3], 5)
        cur = toks[:, :3]
        for _ in range(5):
            nxt = jnp.argmax(lm.apply(params, cur)[:, -1, :], axis=-1)
            cur = jnp.concatenate([cur, nxt[:, None].astype(jnp.int32)], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))

    def test_rope_rides_the_ring(self):
        """Sequence-parallel self-attention with rope == the local path
        (rope is pointwise along S, so it shards with the sequence)."""
        import jax
        import jax.numpy as jnp

        comm = ht.communication.get_comm()
        if comm.size == 1:
            pytest.skip("needs a multi-device mesh")
        lm_loc = TransformerLM(vocab_size=31, embed_dim=16, num_heads=2,
                               depth=2, max_len=32, positions="rope")
        params = lm_loc.init(jax.random.key(0))
        toks = jax.random.randint(jax.random.key(1), (2, 11), 0, 31)
        ring = TransformerLM(vocab_size=31, embed_dim=16, num_heads=2,
                             depth=2, max_len=32, positions="rope", comm=comm)
        np.testing.assert_allclose(
            np.asarray(ring.apply(params, toks)),
            np.asarray(lm_loc.apply(params, toks)),
            rtol=1e-4, atol=1e-5,
        )

    def test_rope_validation(self):
        with pytest.raises(ValueError, match="positions"):
            TransformerLM(vocab_size=8, positions="alibi")
        from heat_tpu.nn.attention import MultiheadAttention

        with pytest.raises(ValueError, match="even head dim"):
            MultiheadAttention(embed_dim=9, num_heads=3, rope=True)

    def test_rope_training(self):
        import jax
        import jax.numpy as jnp

        lm = TransformerLM(vocab_size=31, embed_dim=16, num_heads=2, depth=2,
                           max_len=32, positions="rope")
        params = lm.init(jax.random.key(0))
        toks = jax.random.randint(jax.random.key(1), (4, 12), 0, 31)

        def loss_fn(p):
            logits = lm.apply(p, toks[:, :-1])
            return ht.nn.functional.cross_entropy(
                logits.reshape(-1, 31), toks[:, 1:].reshape(-1)
            )

        opt = ht.optim.DataParallelOptimizer("adam", lr=1e-2)
        opt.init_state(params)
        vg = jax.jit(jax.value_and_grad(loss_fn))
        losses = []
        for _ in range(10):
            l, g = vg(params)
            params = opt.step(params, g)
            losses.append(float(l))
        assert losses[-1] < losses[0]


class TestTiedEmbeddings:
    def test_tied_lm(self):
        """tie_embeddings: one (V, E) matrix serves embedding AND head —
        no head params, logits == h @ embed.T, grads accumulate from both
        uses, and the decode contract still holds."""
        import jax
        import jax.numpy as jnp

        lm = TransformerLM(vocab_size=23, embed_dim=16, num_heads=2, depth=2,
                           max_len=32, tie_embeddings=True)
        params = lm.init(jax.random.key(0))
        assert "head" not in params
        toks = jax.random.randint(jax.random.key(1), (2, 9), 0, 23)
        full = lm.apply(params, toks)
        assert full.shape == (2, 9, 23)
        caches = [b.init_cache(2, 9) for b in lm.blocks]
        for t in range(9):
            lg, caches = lm.decode_step(params, toks[:, t], t, caches)
            np.testing.assert_allclose(
                np.asarray(lg), np.asarray(full[:, t, :]), rtol=1e-4, atol=1e-5
            )
        out = lm.generate(params, toks[:, :3], 4)
        assert out.shape == (2, 7) and bool((out[:, :3] == toks[:, :3]).all())

        # the tied matrix receives gradient from BOTH ends: it must differ
        # from the embed-only gradient of an untied model with equal weights
        untied = TransformerLM(vocab_size=23, embed_dim=16, num_heads=2,
                               depth=2, max_len=32)
        up = untied.init(jax.random.key(0))
        up = {**up, "embed": params["embed"],
              "head": {"weight": params["embed"]["weight"]},
              "blocks": params["blocks"], "ln_f": params["ln_f"],
              "pos": params["pos"]}
        # identical weights (head := embed) -> identical logits
        np.testing.assert_allclose(
            np.asarray(untied.apply(up, toks)), np.asarray(full),
            rtol=1e-5, atol=1e-6,
        )

        def loss(p, mod):
            logits = mod.apply(p, toks[:, :-1])
            return ht.nn.functional.cross_entropy(
                logits.reshape(-1, 23), toks[:, 1:].reshape(-1))

        g_tied = jax.grad(lambda p: loss(p, lm))(params)["embed"]["weight"]
        gu = jax.grad(lambda p: loss(p, untied))(up)
        g_sum = gu["embed"]["weight"] + gu["head"]["weight"]
        np.testing.assert_allclose(
            np.asarray(g_tied), np.asarray(g_sum), rtol=1e-4, atol=1e-5
        )


class TestEosStopping:
    def test_lm_eos_pins_sequence(self):
        """Once a sequence emits eos_id its remaining positions are pinned
        to EOS; prompt-phase EOS tokens never mark a sequence finished."""
        import jax
        import jax.numpy as jnp

        lm, params = _lm()
        # prompt CONTAINS the eos token: must not stop generation
        prompt = jnp.asarray([[5, 7, 5, 9], [1, 2, 3, 4]], jnp.int32)
        out = lm.generate(params, prompt, 8, eos_id=5)
        out = np.asarray(out)
        assert (out[:, :4] == np.asarray(prompt)).all()
        # naive oracle: greedy with manual stop
        cur = prompt
        for _ in range(8):
            nxt = jnp.argmax(lm.apply(params, cur)[:, -1, :], axis=-1)
            cur = jnp.concatenate([cur, nxt[:, None].astype(jnp.int32)], axis=1)
        naive = np.asarray(cur)
        for b in range(2):
            row, want = out[b], naive[b]
            hits = np.where(want[4:] == 5)[0]
            stop = 4 + (hits[0] if len(hits) else 99)
            np.testing.assert_array_equal(row[: min(stop + 1, 12)],
                                          want[: min(stop + 1, 12)])
            if stop + 1 < 12:
                assert (row[stop + 1:] == 5).all()

    def test_eos_program_key_and_dynamism(self):
        """has_eos is static (separate program); the eos VALUE is dynamic
        (sweeping it reuses the executable)."""
        import jax

        lm, params = _lm()
        prompt = jax.random.randint(jax.random.key(1), (2, 4), 0, 31)
        lm.generate(params, prompt, 3)
        n0 = len(lm._gen_programs)
        lm.generate(params, prompt, 3, eos_id=7)
        assert len(lm._gen_programs) == n0 + 1
        lm.generate(params, prompt, 3, eos_id=9)  # different value, same program
        assert len(lm._gen_programs) == n0 + 1

    def test_seq2seq_eos(self):
        import jax
        import jax.numpy as jnp

        from heat_tpu.nn.models import Seq2SeqTransformer

        m = Seq2SeqTransformer(src_vocab=11, tgt_vocab=7, embed_dim=16,
                               num_heads=2, enc_depth=1, dec_depth=1, max_len=16)
        params = m.init(jax.random.key(0))
        src = jax.random.randint(jax.random.key(1), (3, 5), 0, 11)
        out = np.asarray(m.generate(params, src, 8, bos_id=1, eos_id=2))
        naive = np.asarray(m.generate(params, src, 8, bos_id=1))
        for b in range(3):
            hits = np.where(naive[b, 1:] == 2)[0]
            stop = 1 + (hits[0] if len(hits) else 99)
            np.testing.assert_array_equal(out[b, : min(stop + 1, 9)],
                                          naive[b, : min(stop + 1, 9)])
            if stop + 1 < 9:
                assert (out[b, stop + 1:] == 2).all()


class TestGQA:
    def test_module_matches_repeated_heads(self):
        """GQA module == a full-head module whose K/V weights repeat each
        group's slice — the exact-equivalence oracle."""
        import jax
        import jax.numpy as jnp

        from heat_tpu.nn.attention import MultiheadAttention

        B, S, E, H, Hkv = 2, 10, 16, 4, 2
        d = E // H
        gqa = MultiheadAttention(E, H, num_kv_heads=Hkv)
        params = gqa.init(jax.random.key(0))
        assert params["in_proj_weight"].shape == (E + 2 * Hkv * d, E)
        x = jax.random.normal(jax.random.key(1), (B, S, E))
        y = gqa.apply(params, x, causal=True)

        full = MultiheadAttention(E, H)
        w, b = params["in_proj_weight"], params["in_proj_bias"]

        def rep(block):
            return jnp.repeat(block.reshape(Hkv, d, E), H // Hkv, axis=0).reshape(H * d, E)

        def repb(block):
            return jnp.repeat(block.reshape(Hkv, d), H // Hkv, axis=0).reshape(H * d)

        pfull = {
            "in_proj_weight": jnp.concatenate(
                [w[:E], rep(w[E : E + Hkv * d]), rep(w[E + Hkv * d :])], axis=0),
            "in_proj_bias": jnp.concatenate(
                [b[:E], repb(b[E : E + Hkv * d]), repb(b[E + Hkv * d :])]),
            "out_proj": params["out_proj"],
        }
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(full.apply(pfull, x, causal=True)),
            rtol=1e-5, atol=1e-6,
        )

    def test_decode_cache_is_grouped(self):
        """The decode cache holds num_kv_heads heads (the GQA memory win)
        and the cached decode still equals the full forward."""
        import jax
        import jax.numpy as jnp

        from heat_tpu.nn.attention import MultiheadAttention

        B, S, E, H, Hkv = 2, 9, 16, 4, 1  # MQA extreme
        mha = MultiheadAttention(E, H, num_kv_heads=Hkv)
        params = mha.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (B, S, E))
        y = mha.apply(params, x, causal=True)
        cache = mha.init_cache(B, S)
        assert cache["k"].shape[1] == Hkv
        for t in range(S):
            yt, cache = mha.decode_step(params, x[:, t : t + 1, :], cache)
            np.testing.assert_allclose(
                np.asarray(yt[:, 0]), np.asarray(y[:, t]), rtol=1e-4, atol=1e-5
            )

    def test_lm_with_gqa(self):
        """num_kv_heads threads through the LM: halved caches, contracts
        hold (decode == apply, greedy == naive), rope composes."""
        import jax
        import jax.numpy as jnp

        lm = TransformerLM(vocab_size=19, embed_dim=16, num_heads=4, depth=2,
                           max_len=32, num_kv_heads=2, positions="rope")
        params = lm.init(jax.random.key(0))
        toks = jax.random.randint(jax.random.key(1), (2, 8), 0, 19)
        full = lm.apply(params, toks)
        caches = [b.init_cache(2, 8) for b in lm.blocks]
        assert caches[0]["k"].shape[1] == 2
        for t in range(8):
            lg, caches = lm.decode_step(params, toks[:, t], t, caches)
            np.testing.assert_allclose(
                np.asarray(lg), np.asarray(full[:, t, :]), rtol=1e-4, atol=1e-5
            )
        out = lm.generate(params, toks[:, :3], 4)
        cur = toks[:, :3]
        for _ in range(4):
            nxt = jnp.argmax(lm.apply(params, cur)[:, -1, :], axis=-1)
            cur = jnp.concatenate([cur, nxt[:, None].astype(jnp.int32)], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))

    def test_validation(self):
        from heat_tpu.nn.attention import MultiheadAttention

        with pytest.raises(ValueError, match="num_kv_heads"):
            MultiheadAttention(16, 4, num_kv_heads=3)


class TestBlockDropout:
    def test_dropout_semantics(self):
        """dropout= in the blocks: eval (or no key) is deterministic and
        equals the dropout-0 model; train with a key is stochastic."""
        import jax

        lm0 = TransformerLM(vocab_size=19, embed_dim=16, num_heads=2, depth=2,
                            max_len=16)
        lmd = TransformerLM(vocab_size=19, embed_dim=16, num_heads=2, depth=2,
                            max_len=16, dropout=0.5)
        params = lm0.init(jax.random.key(0))
        toks = jax.random.randint(jax.random.key(1), (2, 8), 0, 19)
        # eval: dropout is identity -> same logits as the dropout-0 model
        np.testing.assert_allclose(
            np.asarray(lmd.apply(params, toks)), np.asarray(lm0.apply(params, toks)),
            rtol=1e-6, atol=1e-7,
        )
        # train with a key: stochastic, and different keys differ
        a = lmd.apply(params, toks, train=True, key=jax.random.key(2))
        b = lmd.apply(params, toks, train=True, key=jax.random.key(3))
        base = lm0.apply(params, toks)
        assert (np.asarray(a) != np.asarray(base)).any()
        assert (np.asarray(a) != np.asarray(b)).any()
        # same key: deterministic
        a2 = lmd.apply(params, toks, train=True, key=jax.random.key(2))
        np.testing.assert_allclose(np.asarray(a), np.asarray(a2), rtol=1e-6)
        # decode path is eval-mode: contracts unaffected by the dropout knob
        full = lmd.apply(params, toks)
        caches = [b_.init_cache(2, 8) for b_ in lmd.blocks]
        for t in range(8):
            lg, caches = lmd.decode_step(params, toks[:, t], t, caches)
            np.testing.assert_allclose(
                np.asarray(lg), np.asarray(full[:, t, :]), rtol=1e-4, atol=1e-5
            )

    def test_training_with_dropout_reduces_loss(self):
        import jax

        lm = TransformerLM(vocab_size=19, embed_dim=16, num_heads=2, depth=2,
                           max_len=16, dropout=0.1)
        params = lm.init(jax.random.key(0))
        toks = jax.random.randint(jax.random.key(1), (4, 10), 0, 19)

        def loss_fn(p, k):
            logits = lm.apply(p, toks[:, :-1], train=True, key=k)
            return ht.nn.functional.cross_entropy(
                logits.reshape(-1, 19), toks[:, 1:].reshape(-1))

        opt = ht.optim.DataParallelOptimizer("adam", lr=1e-2)
        opt.init_state(params)
        vg = jax.jit(jax.value_and_grad(loss_fn))
        key = jax.random.key(2)
        losses = []
        for _ in range(12):
            key, sub = jax.random.split(key)
            l, g = vg(params, sub)
            params = opt.step(params, g)
            losses.append(float(l))
        assert losses[-1] < losses[0]

    def test_seq2seq_dropout(self):
        """The decoder family gets the same knob: eval == dropout-0 model,
        train+key stochastic."""
        import jax

        from heat_tpu.nn.models import Seq2SeqTransformer

        m0 = Seq2SeqTransformer(src_vocab=11, tgt_vocab=9, embed_dim=16,
                                num_heads=2, enc_depth=1, dec_depth=1, max_len=16)
        md = Seq2SeqTransformer(src_vocab=11, tgt_vocab=9, embed_dim=16,
                                num_heads=2, enc_depth=1, dec_depth=1, max_len=16,
                                dropout=0.5)
        params = m0.init(jax.random.key(0))
        src = jax.random.randint(jax.random.key(1), (2, 5), 0, 11)
        tgt = jax.random.randint(jax.random.key(2), (2, 6), 0, 9)
        np.testing.assert_allclose(
            np.asarray(md.apply(params, src, tgt)),
            np.asarray(m0.apply(params, src, tgt)),
            rtol=1e-6, atol=1e-7,
        )
        a = md.apply(params, src, tgt, train=True, key=jax.random.key(3))
        assert (np.asarray(a) != np.asarray(m0.apply(params, src, tgt))).any()


class TestSinusoidalPositions:
    def test_table_matches_reference_formula(self):
        import jax.numpy as jnp

        from heat_tpu.nn.models import _sinusoidal_positions

        E, S = 8, 5
        got = np.asarray(_sinusoidal_positions(jnp.arange(S), E))
        want = np.zeros((S, E), np.float32)
        for pos in range(S):
            for i in range(E // 2):
                a = pos / (10000 ** (i / (E // 2)))
                want[pos, 2 * i] = np.sin(a)
                want[pos, 2 * i + 1] = np.cos(a)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_lm_sinusoidal_contracts(self):
        """No params table; decode == apply; greedy == naive."""
        import jax
        import jax.numpy as jnp

        lm = TransformerLM(vocab_size=19, embed_dim=16, num_heads=2, depth=2,
                           max_len=32, positions="sinusoidal")
        params = lm.init(jax.random.key(0))
        assert "pos" not in params
        toks = jax.random.randint(jax.random.key(1), (2, 8), 0, 19)
        full = lm.apply(params, toks)
        caches = [b.init_cache(2, 8) for b in lm.blocks]
        for t in range(8):
            lg, caches = lm.decode_step(params, toks[:, t], t, caches)
            np.testing.assert_allclose(
                np.asarray(lg), np.asarray(full[:, t, :]), rtol=1e-4, atol=1e-5
            )
        out = lm.generate(params, toks[:, :3], 4)
        cur = toks[:, :3]
        for _ in range(4):
            nxt = jnp.argmax(lm.apply(params, cur)[:, -1, :], axis=-1)
            cur = jnp.concatenate([cur, nxt[:, None].astype(jnp.int32)], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))
