"""Statistical operations (reference: ``heat/core/statistics.py``).

The reference merges distributed moments by hand (Chan et al. pairwise update
of ``(n, μ, M2)`` via custom MPI ops).  Under XLA a global-mean/var over a
sharded axis IS that merge — the partitioner emits the tree-reduction — so
these collapse to jnp reductions plus split bookkeeping.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from . import types
from ._operations import _binary_op, _local_op, _reduce_op
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis
from ..core.communication import Communication

__all__ = [
    "argmax",
    "argmin",
    "average",
    "bincount",
    "bucketize",
    "corrcoef",
    "cov",
    "digitize",
    "histc",
    "histogram",
    "kurtosis",
    "max",
    "maximum",
    "mean",
    "median",
    "min",
    "minimum",
    "percentile",
    "ptp",
    "quantile",
    "nanargmax",
    "nanargmin",
    "nanmax",
    "nanmin",
    "nanmean",
    "nanstd",
    "nanvar",
    "skew",
    "std",
    "var",
]


def argmax(x, axis=None, out=None, keepdims=False) -> DNDarray:
    """Index of the maximum (global indices, reference MINLOC-style semantics)."""
    return _reduce_op(jnp.argmax, x, axis=axis, keepdims=keepdims, out=out)


def argmin(x, axis=None, out=None, keepdims=False) -> DNDarray:
    return _reduce_op(jnp.argmin, x, axis=axis, keepdims=keepdims, out=out)


def max(x, axis=None, out=None, keepdims=False) -> DNDarray:
    """Maximum along axis (implicit Allreduce-MAX over the split axis)."""
    return _reduce_op(jnp.max, x, axis=axis, keepdims=keepdims, out=out)


def min(x, axis=None, out=None, keepdims=False) -> DNDarray:
    return _reduce_op(jnp.min, x, axis=axis, keepdims=keepdims, out=out)


def maximum(x1, x2, out=None) -> DNDarray:
    """Elementwise maximum of two arrays."""
    return _binary_op(jnp.maximum, x1, x2, out=out)


def minimum(x1, x2, out=None) -> DNDarray:
    return _binary_op(jnp.minimum, x1, x2, out=out)


def mean(x, axis=None) -> DNDarray:
    """Arithmetic mean (distributed moment merge is XLA's tree-reduce)."""
    return _reduce_op(jnp.mean, x, axis=axis)


def var(x, axis=None, ddof: int = 0, **kwargs) -> DNDarray:
    """Variance with ``ddof`` correction (reference default ddof=0)."""
    return _reduce_op(jnp.var, x, axis=axis, ddof=ddof)


def std(x, axis=None, ddof: int = 0, **kwargs) -> DNDarray:
    return _reduce_op(jnp.std, x, axis=axis, ddof=ddof)


def average(x, axis=None, weights=None, returned: bool = False):
    """Weighted average along axis."""
    if weights is None:
        result = mean(x, axis=axis)
        if returned:
            from . import factories

            n = x.size if axis is None else np.prod([x.shape[a] for a in np.atleast_1d(axis)])
            return result, factories.full_like(result, float(n))
        return result
    w = weights._jarray if isinstance(weights, DNDarray) else jnp.asarray(weights)
    ax = sanitize_axis(x.shape, axis) if axis is not None else None
    res, wsum = jnp.average(x._jarray, axis=ax, weights=w, returned=True)
    # split bookkeeping identical to _reduce_op (axis removed shifts the split)
    if x.split is None or ax is None or ax == x.split:
        split = None
    else:
        split = x.split - (1 if ax < x.split else 0)
    if split is not None and split >= res.ndim:
        split = None
    res = x.comm.shard(res, split)
    out = DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), split, x.device, x.comm, True)
    if returned:
        wb = x.comm.shard(jnp.broadcast_to(wsum, res.shape), split)
        ws = DNDarray(wb, tuple(res.shape), types.canonical_heat_type(wsum.dtype), split, x.device, x.comm, True)
        return out, ws
    return out


def bincount(x, weights=None, minlength: int = 0) -> DNDarray:
    """Count occurrences of each value in a non-negative int array."""
    if weights is not None:
        w = weights._jarray if isinstance(weights, DNDarray) else jnp.asarray(weights)
        w = w.reshape(-1)
    else:
        w = None
    length = int(Communication.host_fetch(jnp.max(x._jarray))) + 1 if x.size else 0
    length = length if length > minlength else minlength
    res = jnp.bincount(x._jarray.reshape(-1), weights=w, length=length)
    return DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), None, x.device, x.comm, True)


def bucketize(x, boundaries, right: bool = False, out=None) -> DNDarray:
    """Index of the bucket each element falls into (torch semantics:
    ``right=False`` ⇒ boundaries[i-1] < v <= boundaries[i] ⇒ searchsorted 'left')."""
    b = boundaries._jarray if isinstance(boundaries, DNDarray) else jnp.asarray(boundaries)
    side = "right" if right else "left"
    return _local_op(lambda a: jnp.searchsorted(b, a, side=side).astype(jnp.int32), x, out=out)


def digitize(x, bins, right: bool = False) -> DNDarray:
    b = bins._jarray if isinstance(bins, DNDarray) else jnp.asarray(bins)
    return _local_op(lambda a: jnp.digitize(a, b, right=right).astype(jnp.int32), x)


def cov(m, y=None, rowvar: bool = True, bias: bool = False, ddof: Optional[int] = None) -> DNDarray:
    """Covariance matrix estimate (distributed via implicit matmul collectives)."""
    x = m
    if x.ndim > 2:
        raise ValueError("m has more than 2 dimensions")
    jm = x._jarray
    if y is not None:
        jy = y._jarray if isinstance(y, DNDarray) else jnp.asarray(y)
    else:
        jy = None
    res = jnp.cov(jm, y=jy, rowvar=rowvar, bias=bias, ddof=ddof)
    res = jnp.atleast_2d(res)
    return DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), None, x.device, x.comm, True)


def histc(x, bins: int = 100, min: float = 0.0, max: float = 0.0, out=None) -> DNDarray:
    lo, hi = float(min), float(max)
    if lo == 0.0 and hi == 0.0:
        lo = float(Communication.host_fetch(jnp.min(x._jarray)))
        hi = float(Communication.host_fetch(jnp.max(x._jarray)))
    hist, _ = jnp.histogram(x._jarray.reshape(-1), bins=bins, range=(lo, hi))
    hist = hist.astype(x.dtype.jax_dtype())
    res = DNDarray(hist, tuple(hist.shape), x.dtype, None, x.device, x.comm, True)
    if out is not None:
        out._jarray = hist
        return out
    return res


def histogram(x, bins=10, range=None, weights=None, density=None):
    """(hist, bin_edges) over the global array."""
    w = weights._jarray if isinstance(weights, DNDarray) else weights
    hist, edges = jnp.histogram(x._jarray.reshape(-1), bins=bins, range=range, weights=w, density=density)
    h = DNDarray(hist, tuple(hist.shape), types.canonical_heat_type(hist.dtype), None, x.device, x.comm, True)
    e = DNDarray(edges, tuple(edges.shape), types.canonical_heat_type(edges.dtype), None, x.device, x.comm, True)
    return h, e


def kurtosis(x, axis=None, unbiased: bool = True, Fischer: bool = True) -> DNDarray:
    """Kurtosis (Fisher: excess kurtosis). Distributed via global moments."""
    ax = sanitize_axis(x.shape, axis)
    j = x._jarray
    mu = jnp.mean(j, axis=ax, keepdims=True)
    d = j - mu
    m2 = jnp.mean(d**2, axis=ax)
    m4 = jnp.mean(d**4, axis=ax)
    n = x.size if ax is None else x.shape[ax]
    g2 = m4 / jnp.where(m2 == 0, 1.0, m2**2)
    if unbiased and n > 3:
        g2 = (n - 1) / ((n - 2) * (n - 3)) * ((n + 1) * g2 - 3 * (n - 1)) + 3
    res = g2 - 3.0 if Fischer else g2
    split = None if ax is None or ax == x.split else (x.split - (1 if ax < (x.split or 0) else 0) if x.split is not None else None)
    if split is not None and split >= res.ndim:
        split = None
    res = x.comm.shard(res, split)
    return DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), split, x.device, x.comm, True)


def skew(x, axis=None, unbiased: bool = True) -> DNDarray:
    """Skewness of the distribution along axis."""
    ax = sanitize_axis(x.shape, axis)
    j = x._jarray
    mu = jnp.mean(j, axis=ax, keepdims=True)
    d = j - mu
    m2 = jnp.mean(d**2, axis=ax)
    m3 = jnp.mean(d**3, axis=ax)
    g1 = m3 / jnp.where(m2 == 0, 1.0, m2**1.5)
    n = x.size if ax is None else x.shape[ax]
    if unbiased and n > 2:
        g1 = g1 * jnp.sqrt(n * (n - 1)) / (n - 2)
    split = None if ax is None or ax == x.split else (x.split - (1 if ax < (x.split or 0) else 0) if x.split is not None else None)
    if split is not None and split >= g1.ndim:
        split = None
    g1 = x.comm.shard(g1, split)
    return DNDarray(g1, tuple(g1.shape), types.canonical_heat_type(g1.dtype), split, x.device, x.comm, True)


def median(x, axis=None, keepdims: bool = False) -> DNDarray:
    """Median — the reference's distributed selection maps to the bisected
    exact order statistics for large 1-D split arrays (via
    :func:`percentile`); smaller/ND inputs use the global XLA sort."""
    return percentile(x, 50.0, axis=axis, keepdims=keepdims)


def quantile(x, q, axis=None, out=None, interpolation: str = "linear", keepdims: bool = False) -> DNDarray:
    """q-th quantile(s) (q in [0, 1]) — percentile/100. Accepts scalar or array-like q."""
    if isinstance(q, DNDarray):
        qs = q * 100.0
    elif np.isscalar(q):
        qs = float(q) * 100.0
    else:
        qs = np.asarray(q, dtype=np.float32) * 100.0
    return percentile(x, qs, axis=axis, out=out, interpolation=interpolation, keepdims=keepdims)


def nanmax(x, axis=None, out=None, keepdims=False) -> DNDarray:
    return _reduce_op(jnp.nanmax, x, axis=axis, keepdims=keepdims, out=out)


def nanmin(x, axis=None, out=None, keepdims=False) -> DNDarray:
    return _reduce_op(jnp.nanmin, x, axis=axis, keepdims=keepdims, out=out)


def nanmean(x, axis=None) -> DNDarray:
    return _reduce_op(jnp.nanmean, x, axis=axis)


def nanstd(x, axis=None, ddof: int = 0) -> DNDarray:
    return _reduce_op(jnp.nanstd, x, axis=axis, ddof=ddof)


def nanvar(x, axis=None, ddof: int = 0) -> DNDarray:
    return _reduce_op(jnp.nanvar, x, axis=axis, ddof=ddof)


def nanargmax(x, axis=None, out=None, keepdims=False) -> DNDarray:
    """Index of the maximum, ignoring NaNs (global indices)."""
    return _reduce_op(jnp.nanargmax, x, axis=axis, keepdims=keepdims, out=out)


def nanargmin(x, axis=None, out=None, keepdims=False) -> DNDarray:
    """Index of the minimum, ignoring NaNs (global indices)."""
    return _reduce_op(jnp.nanargmin, x, axis=axis, keepdims=keepdims, out=out)


def ptp(x, axis=None, out=None, keepdims=False) -> DNDarray:
    """Peak-to-peak range ``max - min`` — composed from the distributed
    reductions so split axes ride the standard collective path."""
    res = max(x, axis=axis, keepdims=keepdims) - min(x, axis=axis, keepdims=keepdims)
    if out is not None:
        from . import sanitation

        sanitation.sanitize_out(out, res.shape, res.split, res.device)
        out._jarray = res._jarray.astype(out.dtype.jax_dtype())
        return out
    return res


def corrcoef(m, y=None, rowvar: bool = True) -> DNDarray:
    """Pearson correlation coefficient matrix, normalized from :func:`cov`."""
    if isinstance(m, DNDarray) and m.ndim == 1 and y is None:
        # numpy returns a 0-d 1.0 for a single variable; keep the input's
        # float-promoted dtype rather than hardcoding f32
        fdt = jnp.promote_types(m._jarray.dtype, jnp.float32)
        one = jnp.asarray(1.0, dtype=fdt)
        return DNDarray(one, (), types.canonical_heat_type(one.dtype), None, m.device, m.comm, True)
    c = cov(m, y=y, rowvar=rowvar)
    d = jnp.sqrt(jnp.diag(c._jarray))
    res = c._jarray / jnp.outer(d, d)
    if jnp.issubdtype(res.dtype, jnp.complexfloating):
        # numpy clips real/imag parts independently for complex input
        res = jnp.clip(res.real, -1.0, 1.0) + 1j * jnp.clip(res.imag, -1.0, 1.0)
    else:
        res = jnp.clip(res, -1.0, 1.0)
    res = c.comm.shard(res, c.split)
    return DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), c.split, c.device, c.comm, True)


# elements above which the 1-D split percentile routes through the exact
# bisected order statistics (no gather/sort); lowered by tests
PERCENTILE_BISECT_THRESHOLD = 1_000_000


def percentile(x, q, axis=None, out=None, interpolation: str = "linear", keepdims: bool = False) -> DNDarray:
    """q-th percentile(s) along axis.

    Large 1-D split-0 float32 arrays with linear interpolation use the exact
    distributed order statistics (``parallel.order_statistics_1d``:
    radix-256 digit selection, 4 psum'd-histogram rounds, O(n/p) memory)
    instead of the global gather-and-sort — the scalable path for the
    reference's distributed median/percentile story.
    """
    ax = sanitize_axis(x.shape, axis)
    q_is_scalar = np.ndim(q) == 0 and not isinstance(q, DNDarray)
    bisect_ok = (
        x.ndim == 1
        and ax in (None, 0)
        and x.split == 0
        and interpolation == "linear"
        and not keepdims
        and x.comm.is_distributed()
        and x._jarray.dtype == jnp.float32
        and not isinstance(q, DNDarray)
        and PERCENTILE_BISECT_THRESHOLD <= x.shape[0] < 2**31
    )
    if bisect_ok:
        from ..parallel.sample_sort import order_statistics_1d

        n = x.shape[0]
        qs = np.atleast_1d(np.asarray(q, np.float64))
        if np.any(qs < 0.0) or np.any(qs > 100.0):
            # numpy contract (the global jnp path clamps; be stricter here
            # than silently selecting a pad sentinel)
            raise ValueError("Percentiles must be in the range [0, 100]")
        pos = qs / 100.0 * (n - 1)
        lo = np.floor(pos).astype(np.int64)
        hi = np.ceil(pos).astype(np.int64)
        ranks = sorted(set(lo.tolist()) | set(hi.tolist()))
        rank_pos = {rk: i for i, rk in enumerate(ranks)}
        vals = order_statistics_1d(x.comm, x._parray, n, ranks)
        vlo = vals[np.asarray([rank_pos[r] for r in lo])]
        vhi = vals[np.asarray([rank_pos[r] for r in hi])]
        frac = jnp.asarray(pos - lo, jnp.float32)
        res = vlo + frac * (vhi - vlo)
        if q_is_scalar:
            res = res[0]
        res = x.comm.shard(res, None)
        r = DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), None, x.device, x.comm, True)
        if out is not None:
            out._jarray = res.astype(out.dtype.jax_dtype())
            return out
        return r
    qj = q._jarray if isinstance(q, DNDarray) else jnp.asarray(q, dtype=jnp.float32)
    res = jnp.percentile(x._jarray.astype(jnp.float32), qj, axis=ax, method=interpolation, keepdims=keepdims)
    res = x.comm.shard(res, None)
    r = DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), None, x.device, x.comm, True)
    if out is not None:
        out._jarray = res.astype(out.dtype.jax_dtype())
        return out
    return r


DNDarray.argmax = argmax
DNDarray.argmin = argmin
DNDarray.max = max
DNDarray.min = min
DNDarray.mean = mean
DNDarray.var = var
DNDarray.std = std
DNDarray.average = average
DNDarray.median = median
DNDarray.percentile = percentile
DNDarray.kurtosis = kurtosis
DNDarray.skew = skew


amax = max
amin = min


def fmax(t1, t2, out=None) -> DNDarray:
    """Elementwise max ignoring NaNs (numpy ``fmax``)."""
    from ._operations import _binary_op

    return _binary_op(jnp.fmax, t1, t2, out=out)


def fmin(t1, t2, out=None) -> DNDarray:
    """Elementwise min ignoring NaNs (numpy ``fmin``)."""
    from ._operations import _binary_op

    return _binary_op(jnp.fmin, t1, t2, out=out)


def nanmedian(x, axis=None, keepdims: bool = False) -> DNDarray:
    res = jnp.nanmedian(x._jarray.astype(jnp.float32), axis=sanitize_axis(x.shape, axis), keepdims=keepdims)
    res = x.comm.shard(res, None)
    return DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), None, x.device, x.comm, True)


def nanpercentile(x, q, axis=None, keepdims: bool = False, interpolation: str = "linear") -> DNDarray:
    qj = q._jarray if isinstance(q, DNDarray) else jnp.asarray(q, dtype=jnp.float32)
    res = jnp.nanpercentile(x._jarray.astype(jnp.float32), qj, axis=sanitize_axis(x.shape, axis), method=interpolation, keepdims=keepdims)
    res = x.comm.shard(res, None)
    return DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), None, x.device, x.comm, True)


def nanquantile(x, q, axis=None, keepdims: bool = False, interpolation: str = "linear") -> DNDarray:
    qj = q._jarray if isinstance(q, DNDarray) else jnp.asarray(q, dtype=jnp.float32)
    res = jnp.nanquantile(x._jarray.astype(jnp.float32), qj, axis=sanitize_axis(x.shape, axis), method=interpolation, keepdims=keepdims)
    res = x.comm.shard(res, None)
    return DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), None, x.device, x.comm, True)


def histogram_bin_edges(x, bins=10, range=None, weights=None) -> DNDarray:
    res = jnp.histogram_bin_edges(x._jarray, bins=bins, range=range)
    res = x.comm.shard(res, None)
    return DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), None, x.device, x.comm, True)


def histogram2d(x, y, bins=10, range=None, weights=None, density=None):
    jw = weights._jarray if isinstance(weights, DNDarray) else weights
    h, ex, ey = jnp.histogram2d(x._jarray, y._jarray, bins=bins, range=range, weights=jw, density=density)

    def wrap(j):
        j = x.comm.shard(j, None)
        return DNDarray(j, tuple(j.shape), types.canonical_heat_type(j.dtype), None, x.device, x.comm, True)

    return wrap(h), wrap(ex), wrap(ey)


def histogramdd(sample, bins=10, range=None, weights=None, density=None):
    js = sample._jarray if isinstance(sample, DNDarray) else jnp.asarray(np.asarray(sample))
    jw = weights._jarray if isinstance(weights, DNDarray) else weights
    h, edges = jnp.histogramdd(js, bins=bins, range=range, weights=jw, density=density)
    proto = sample

    def wrap(j):
        j = proto.comm.shard(j, None)
        return DNDarray(j, tuple(j.shape), types.canonical_heat_type(j.dtype), None, proto.device, proto.comm, True)

    return wrap(h), [wrap(e) for e in edges]


__all__ += ["amax", "amin", "fmax", "fmin", "histogram2d", "histogram_bin_edges", "histogramdd", "nanmedian", "nanpercentile", "nanquantile"]
